"""Fluid-equivalent stack tests (SURVEY §2.3): ProgramDesc construction,
Executor (jit AND eager — the eager interpreter is the oracle, mirroring the
reference's CPU-oracle idiom), append_backward autodiff region, optimizer
ops, batch-norm running stats, dropout train/test."""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid import layers as L


@pytest.fixture(autouse=True)
def _fresh_program():
    fluid.reset_default_program()
    yield


def _toy_classification(n=32, d=16, c=4, seed=0):
    rs = np.random.RandomState(seed)
    lbl = rs.randint(0, c, (n, 1))
    feat = rs.randn(n, d).astype(np.float32) * 0.1
    for i, l in enumerate(lbl[:, 0]):
        feat[i, l] += 2.0
    return feat, lbl


def _build_mlp(c=4):
    x = L.data("x", shape=[16])
    y = L.data("y", shape=[1], dtype=np.int32)
    h = L.fc(x, 32, act="tanh")
    out = L.fc(h, c, act="softmax")
    loss = L.mean(L.cross_entropy(out, y))
    acc = L.accuracy(out, y)
    return x, y, out, loss, acc


def test_program_desc_structure():
    _build_mlp()
    prog = fluid.default_main_program()
    s = prog.to_string()
    assert "op mul" in s and "op cross_entropy" in s
    types = [op.type for op in prog.global_block().desc.ops]
    assert types.count("mul") == 2 and "softmax" in types
    params = {p.name for p in prog.parameters()}
    assert any(n.endswith(".w") for n in params)


def test_mlp_trains_with_each_optimizer():
    feat, lbl = _toy_classification()
    for opt_cls, kw in [
        (fluid.optimizer.SGDOptimizer, {"learning_rate": 0.5}),
        (fluid.optimizer.MomentumOptimizer, {"learning_rate": 0.2, "momentum": 0.9}),
        (fluid.optimizer.AdamOptimizer, {"learning_rate": 0.05}),
        (fluid.optimizer.AdagradOptimizer, {"learning_rate": 0.3}),
    ]:
        fluid.reset_default_program()
        _, _, _, loss, acc = _build_mlp()
        prog = fluid.default_main_program()
        opt_cls(**kw).minimize(loss)
        exe = fluid.Executor()
        losses = []
        for _ in range(30):
            (lv,) = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < losses[0] / 2, (opt_cls.__name__, losses[0], losses[-1])


def test_jit_matches_eager():
    feat, lbl = _toy_classification(seed=3)
    _, _, out, loss, _ = _build_mlp()
    prog = fluid.default_main_program()
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

    exe_jit = fluid.Executor(seed=7)
    exe_eager = fluid.Executor(seed=7)
    for step in range(3):
        (l_jit,) = exe_jit.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss])
        (l_eager,) = exe_eager.run(
            prog, feed={"x": feat, "y": lbl}, fetch_list=[loss], use_jit=False
        )
        np.testing.assert_allclose(l_jit, l_eager, rtol=1e-4, atol=1e-5)


def test_conv_pool_batchnorm_pipeline():
    rs = np.random.RandomState(0)
    img = L.data("img", shape=[3, 8, 8])
    y = L.data("y", shape=[1], dtype=np.int32)
    c = L.conv2d(img, 8, 3, padding=1, act="relu")
    bn = L.batch_norm(c)
    p = L.pool2d(bn, 2)
    flat = L.reshape(p, [-1, 8 * 4 * 4])
    out = L.fc(flat, 2, act="softmax")
    loss = L.mean(L.cross_entropy(out, y))
    prog = fluid.default_main_program()
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    feed = {
        "img": rs.randn(4, 3, 8, 8).astype(np.float32),
        "y": rs.randint(0, 2, (4, 1)),
    }
    scope = fluid.Scope()
    (l0,) = exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
    bn_mean_name = next(n for n in scope.values if n.endswith("_mean"))
    m_before = np.asarray(scope.find(bn_mean_name))
    (l1,) = exe.run(prog, feed=feed, fetch_list=[loss], scope=scope)
    m_after = np.asarray(scope.find(bn_mean_name))
    assert np.isfinite(l0) and np.isfinite(l1)
    assert not np.allclose(m_before, m_after)  # running stats moved


def test_backward_grads_match_manual():
    """sgd step on y = mean((x@w)^2): grad = 2/N * x^T (x w) — closed form."""
    rs = np.random.RandomState(1)
    xv = rs.randn(8, 4).astype(np.float32)
    wv = rs.randn(4, 1).astype(np.float32)

    x = L.data("x", shape=[4])
    block = fluid.default_main_program().global_block()
    w = block.create_parameter("w", shape=[4, 1], initializer=wv)
    out = block.create_var("out")
    block.append_op("mul", {"X": x, "Y": w}, {"Out": out}, {})
    sq = block.create_var("sq")
    block.append_op("square", {"X": out}, {"Y": sq}, {})
    loss = L.mean(sq)
    prog = fluid.default_main_program()
    fluid.append_backward(loss, [w])
    exe = fluid.Executor()
    scope = fluid.Scope()
    exe.run(prog, feed={"x": xv}, fetch_list=[loss], scope=scope, use_jit=False)
    g = np.asarray(scope.find("w@GRAD")) if scope.has("w@GRAD") else None
    # eager path stores grads in the transient values only; re-run via jit path
    # fetches instead:
    (gfetch,) = exe.run(prog, feed={"x": xv}, fetch_list=["w@GRAD"], scope=scope)
    manual = 2.0 / 8.0 * xv.T @ (xv @ wv)
    np.testing.assert_allclose(gfetch, manual, rtol=1e-4, atol=1e-5)


def test_dropout_train_vs_test():
    x = L.data("x", shape=[64])
    d = L.dropout(x, 0.5)
    prog = fluid.default_main_program()
    exe = fluid.Executor()
    xv = np.ones((4, 64), np.float32)
    (train_out,) = exe.run(prog, feed={"x": xv}, fetch_list=[d], train=True)
    (test_out,) = exe.run(prog, feed={"x": xv}, fetch_list=[d], train=False)
    assert (train_out == 0).any()  # some units dropped
    np.testing.assert_allclose(test_out, xv)  # identity at inference


def test_scope_persistence_across_runs():
    feat, lbl = _toy_classification(seed=5)
    _, _, _, loss, _ = _build_mlp()
    prog = fluid.default_main_program()
    fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    (l0,) = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss], scope=scope)
    (l1,) = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss], scope=scope)
    assert float(l1) < float(l0)  # params persisted and updated in the scope


def test_elementwise_axis_broadcast():
    """The reference's mid-axis broadcast (elementwise_op.h)."""
    import jax.numpy as jnp
    from paddle_tpu.fluid.ops import OPS, OpContext

    x = jnp.ones((2, 3, 4))
    y = jnp.asarray(np.arange(3.0, dtype=np.float32))
    fn = OPS.get("elementwise_add")
    out = fn(OpContext(), {"X": [x], "Y": [y]}, {"axis": 1})["Out"]
    assert out.shape == (2, 3, 4)
    np.testing.assert_allclose(np.asarray(out[0, :, 0]), [1.0, 2.0, 3.0])


def test_slot_like_param_names_still_train():
    """Gradient filtering uses the explicit trainable registry, not name
    substrings: a user parameter named like an optimizer slot (e.g. 'x_beta',
    'emb_lr') must still receive gradients and train, while real slots and BN
    moving stats stay excluded."""
    feat, lbl = _toy_classification()
    x = L.data("x", shape=[16])
    y = L.data("y", shape=[1], dtype=np.int32)
    # fc layers whose parameter names contain classic slot substrings
    h = L.fc(x, 32, act="tanh", name="word_lr_emb")
    out = L.fc(h, 4, act="softmax", name="x_beta")
    loss = L.mean(L.cross_entropy(out, y))

    prog = fluid.default_main_program()
    pg = fluid.optimizer.MomentumOptimizer(learning_rate=0.5).minimize(loss)
    trained = {p.name for p, _ in pg}
    assert "word_lr_emb.w" in trained and "x_beta.w" in trained
    # slots created by the optimizer must NOT be in the gradient list
    assert not any(n.endswith("_velocity") or n == "momentum_lr" for n in trained)

    exe = fluid.Executor()
    scope = fluid.Scope()
    (l0,) = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss], scope=scope)
    for _ in range(20):
        (l1,) = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[loss], scope=scope)
    assert float(l1) < float(l0) / 2, (float(l0), float(l1))


def test_program_prune_extracts_inference_subgraph():
    """framework/prune.cc parity: prune to a fetch target drops the loss/
    metric branch and the pruned program still computes the same values."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    x, y, out, loss, acc = _build_mlp()
    prog = fluid.default_main_program()
    full_types = [op.type for op in prog.global_block().desc.ops]
    assert "cross_entropy" in full_types and "accuracy" in full_types

    pruned = prog.prune([out])
    pruned_types = [op.type for op in pruned.global_block().desc.ops]
    assert "cross_entropy" not in pruned_types
    assert "accuracy" not in pruned_types
    assert pruned_types.count("mul") == 2  # both fc matmuls survive
    # the source program is untouched
    assert [op.type for op in prog.global_block().desc.ops] == full_types

    feat, lbl = _toy_classification(n=8)
    exe = fluid.Executor()
    scope = fluid.Scope()
    want = exe.run(prog, feed={"x": feat, "y": lbl}, fetch_list=[out],
                   scope=scope)[0]
    got = exe.run(pruned, feed={"x": feat}, fetch_list=[out], scope=scope)[0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
