"""Prompt-lookup speculative decoding (ISSUE 16).

The load-bearing claims, each tested directly:

  * result transparency — tokens are IDENTICAL with speculation on vs off
    vs the naive full-context greedy reference, on repetitive prompts (where
    drafts land), random prompts (where they mostly don't), and a mixed
    batch of both; `speculate_k=0` bitwise-recovers the non-speculative
    engine;
  * replay-stable sampling — at temperature > 0 a drafted-and-accepted
    token is sampled through the same fold_in(key, emitted_token_index) as
    the token the plain decode loop would have emitted, so seeded sampling
    is ALSO identical with speculation on vs off;
  * one verify program — every speculative round, whatever the draft
    length or request mix, records exactly ONE [1, K+1] verify_chunk shape
    signature, and the decode loop stays at its one signature;
  * paging — the +K reservation headroom is trimmed back to the pool when
    speculation can no longer reach it, and retirement returns everything;
  * the drafter — pure function of the committed tokens: indexes n-grams
    incrementally, drafts the continuation after the PREVIOUS occurrence
    (never self-matching the live suffix), slides its window so cyclic
    tails draft whole cycles, and returns [] rather than guessing."""

import numpy as np
import pytest

pytestmark = pytest.mark.serving

VOCAB = 96


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 24)
    return ServingSession(model, params, **kw)


def greedy_reference(model, params, prompt, max_new):
    import jax.numpy as jnp

    toks, out = list(prompt), []
    for _ in range(max_new):
        logits = model.forward_logits(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == model.cfg.eos_id:
            break
    return out


# repetitive prompts (drafts land), random-ish prompts (drafts mostly miss),
# and a short prompt below the n-gram threshold (never drafts at round 1)
REPETITIVE = [
    [1] + [5, 9, 11] * 5,
    [1] + [7, 8] * 7,
    [1] + [40, 41, 42, 43] * 4,
]
RANDOM = [
    [1, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18],
    [1, 90, 2, 90],
    [1, 7],
]


def _run_all(session, prompts, max_new, **submit_kw):
    handles = [session.submit(p, max_new, **submit_kw) for p in prompts]
    session.run_until_idle()
    return [h.tokens for h in handles]


def test_speculative_greedy_equals_nonspec_and_reference(model_and_params):
    """The acceptance bit: speculation changes STEP COUNT, never tokens —
    on prompts where drafting works, where it doesn't, and mixed."""
    model, params = model_and_params
    prompts = REPETITIVE + RANDOM

    spec = make_session(model_and_params, speculate_k=4)
    got_spec = _run_all(spec, prompts, 12)
    assert spec.spec_rounds >= 1, "workload never exercised speculation"

    base = make_session(model_and_params, speculate_k=0)
    got_base = _run_all(base, prompts, 12)
    assert got_spec == got_base
    assert base.spec_rounds == 0 and base.verify_shape_signatures() == 0

    ref = [greedy_reference(model, params, p, 12) for p in prompts]
    assert got_spec == ref


def test_speculative_sampling_replay_stable(model_and_params):
    """Seeded sampling at temperature > 0: an accepted draft position uses
    the SAME fold_in(seed-key, emitted_token_index) sample the plain decode
    loop would draw, so tokens are identical spec vs non-spec — the replay
    contract that keeps crash recovery and router failover bitwise."""
    kw = dict(temperature=0.8, top_k=20, seed=1234)
    spec = make_session(model_and_params, speculate_k=4)
    got_spec = _run_all(spec, REPETITIVE, 12, **kw)
    base = make_session(model_and_params, speculate_k=0)
    got_base = _run_all(base, REPETITIVE, 12, **kw)
    assert got_spec == got_base
    # sampled continuations of repetitive prompts still draft (the sampled
    # tail re-walks its own n-grams often enough) — otherwise this test
    # silently proves nothing
    assert spec.spec_rounds >= 1


def test_one_verify_signature_and_decode_stays_compiled(model_and_params):
    """Every verify round shares ONE compiled [1, K+1] program regardless
    of draft length or batch mix, and speculation adds NOTHING to the
    decode program's signature count."""
    s = make_session(model_and_params, speculate_k=4)
    _run_all(s, REPETITIVE + RANDOM, 12)
    assert s.spec_rounds >= 2
    assert s.verify_shape_signatures() == 1
    sigs = s.decode_shape_signatures()
    _run_all(s, REPETITIVE, 10)
    assert s.decode_shape_signatures() == sigs
    assert s.verify_shape_signatures() == 1


def test_speculate_k0_is_todays_engine(model_and_params):
    """`speculate_k=0` must recover the pre-ISSUE-16 engine exactly: no
    drafter state, no verify executable, no +K page reservation."""
    s = make_session(model_and_params, speculate_k=0)
    got = _run_all(s, RANDOM, 8)
    assert all(len(t) > 0 for t in got)
    st = s.stats()
    assert st["speculate_k"] == 0
    assert st["spec_rounds"] == 0 and st["spec_tokens_drafted"] == 0
    assert st["verify_shape_signatures"] == 0
    assert st["spec_pages_trimmed"] == 0


def test_spec_pages_reserved_trimmed_and_recycled(model_and_params):
    """The +K page headroom reserved at admission is trimmed back to the
    pool once unreachable and fully returned at retirement — later
    requests reuse the same pool with nothing leaked."""
    s = make_session(model_and_params, speculate_k=8, page_size=8)
    free0 = s.cache.free_pages
    _run_all(s, REPETITIVE, 16)
    assert s.cache.free_pages == free0, "pages leaked across retirement"
    # the trim counter moves when the reservation crossed a page boundary
    # the base length alone wouldn't have: prompt 16 + new 16 fills exactly
    # 4 pages, so +8 headroom adds a 5th that must come back mid-flight
    assert s.spec_pages_trimmed >= 1
    # pool still serves follow-up work after trim/release churn
    h = s.submit(REPETITIVE[0], 8)
    s.run_until_idle()
    assert len(h.tokens) == 8
    assert s.cache.free_pages == free0


def test_drafter_drafts_previous_occurrence_not_self():
    """The live suffix's own (latest) index entry is the suffix itself; a
    draft must come from the occurrence BEFORE it — the period-1 case that
    breaks a naive latest-only index."""
    from paddle_tpu.serving.speculation import PromptLookupDrafter

    d = PromptLookupDrafter(ngram=2)
    d.feed([7, 7, 7, 7])
    # suffix (7,7) latest occurrence IS the tail; previous predicts 7s
    assert d.draft(3) == [7, 7, 7]


def test_drafter_cycles_and_misses():
    from paddle_tpu.serving.speculation import PromptLookupDrafter

    d = PromptLookupDrafter(ngram=2)
    d.feed([1, 5, 9, 11, 5, 9, 11, 5, 9])
    # sliding window drafts the WHOLE cycle forward, past the match end
    assert d.draft(6) == [11, 5, 9, 11, 5, 9]
    # an unseen suffix refuses to guess
    miss = PromptLookupDrafter(ngram=2)
    miss.feed([1, 2, 3, 4, 5])
    assert miss.draft(4) == []
    # below the n-gram threshold there is nothing to look up
    tiny = PromptLookupDrafter(ngram=3)
    tiny.feed([1, 2])
    assert tiny.draft(4) == []


def test_drafter_sync_is_incremental_and_deterministic():
    """sync() feeds only the unseen tail, and the draft is a pure function
    of the committed sequence — two drafters shown the same history in
    different increments agree exactly (the replay contract)."""
    from paddle_tpu.serving.speculation import PromptLookupDrafter

    prompt = [1, 5, 9, 11, 5, 9, 11]
    gen = [5, 9, 11, 5]
    a = PromptLookupDrafter(ngram=2)
    for i in range(len(gen) + 1):
        a.sync(prompt, gen[:i])
    b = PromptLookupDrafter(ngram=2)
    b.sync(prompt, gen)
    assert len(a) == len(b) == len(prompt) + len(gen)
    assert a.draft(5) == b.draft(5)


def test_eos_truncates_committed_draft(model_and_params):
    """A drafted continuation that crosses EOS commits only up to the stop
    token — spec and non-spec agree on the finish reason and length."""
    spec = make_session(model_and_params, speculate_k=6)
    base = make_session(model_and_params, speculate_k=0)
    # long budgets so any EOS the model emits lands mid-budget
    for p in REPETITIVE + RANDOM:
        hs = spec.submit(p, 20)
        spec.run_until_idle()
        hb = base.submit(p, 20)
        base.run_until_idle()
        assert hs.tokens == hb.tokens
        assert hs.finish_reason == hb.finish_reason
        eos = spec.cfg.eos_id
        if eos in hs.tokens:
            assert hs.tokens.index(eos) == len(hs.tokens) - 1
