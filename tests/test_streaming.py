"""Push token streaming + delta polling (ISSUE 16).

The load-bearing claims, each tested directly:

  * delta poll — `poll(from=N)` returns tokens[N:] with the cursor echoed
    and `tokens_so_far` still counting everything; assembling the deltas
    reproduces the full sequence EXACTLY (the prefix-consistency
    regression test); garbage/out-of-range cursors clamp instead of
    throwing; a DONE reply always carries the full token list (the
    authoritative record router dedup relies on), and a poll without
    `from` is bit-for-bit the legacy reply;
  * poll_many — per-item cursors, same contract, completions full;
  * push streaming — `stream=True` on submit delivers frames on the
    submit connection as the engine emits tokens (speculative rounds push
    multi-token deltas); frames are prefix-consistent and the final frame
    carries done/finish_reason; tokens match the non-streamed oracle;
  * mid-flight attach — the `stream` RPC attaches to an in-flight request
    at a cursor, so a dropped subscriber resumes without replaying
    delivered tokens;
  * the router — the same client streams through RouterServer (frames cut
    at mirror-advance granularity), with delta polling on the same handle
    and identical tokens to the routed non-streamed path."""

import time

import pytest

pytestmark = pytest.mark.serving

VOCAB = 96


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 24)
    return ServingSession(model, params, **kw)


PROMPT = [1] + [5, 9, 11] * 4  # repetitive: speculative rounds land
PLAIN = [1, 3, 4, 5, 6, 7, 8]


def _drain_poll(client, rid, deadline_s=30.0):
    """Assemble a request's tokens from delta polls only. Returns the
    deltas collected before the done reply plus the done reply itself; the
    assembly must be a PREFIX of the done reply's full list (tokens emitted
    between the last delta and completion arrive only in the final)."""
    assembled = []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        resp = client.poll(rid, from_=len(assembled))
        assert "err" not in resp, resp
        if resp.get("done"):
            assert resp["tokens"][:len(assembled)] == assembled, (
                "delta assembly is not a prefix of the done reply"
            )
            return assembled, resp
        base = resp["from"]
        assert base == len(assembled), "server re-cut the cursor"
        assert resp["tokens_so_far"] == base + len(resp["tokens"])
        assembled.extend(resp["tokens"])
        time.sleep(0.002)
    raise AssertionError(f"request {rid} never finished")


def test_delta_poll_prefix_consistent(model_and_params):
    """The satellite-1 regression: a client that only ever reads suffixes
    reconstructs the full sequence bit-for-bit. Deterministic mid-flight
    coverage: the test holds the engine and steps it by hand between
    polls, so every delta reply is observed against a known token count."""
    from paddle_tpu.serving.server import ServingClient, ServingServer

    s = make_session(model_and_params, speculate_k=4)
    s._thread = True  # hold the engine: ServingServer.start() must not spawn it
    srv = ServingServer(session=s).start()
    try:
        c = ServingClient(srv.address)
        rid = c.submit(PROMPT, 16)
        assembled, delta_polls = [], 0
        for _ in range(200):
            if not s.scheduler.has_work():
                break
            s.step()
            resp = c.poll(rid, from_=len(assembled))
            if resp.get("done"):
                # done replies carry the FULL list; fold in the unseen tail
                assert resp["tokens"][:len(assembled)] == assembled
                assembled = list(resp["tokens"])
                break
            assert resp["from"] == len(assembled)
            assert resp["tokens_so_far"] == len(assembled) + len(resp["tokens"])
            assembled.extend(resp["tokens"])
            delta_polls += 1
        final = c.poll(rid, from_=len(assembled))
        assert final["done"] and final["finish_reason"] in ("length", "eos")
        # the done reply stays FULL whatever the cursor (the router's
        # exactly-once dedup record), and the assembly is exactly it
        assert final["tokens"] == assembled
        assert delta_polls >= 2 and len(assembled) == 16

        # legacy poll (no `from`) is byte-for-byte the full reply
        legacy = c.poll(rid)
        assert legacy["done"] and legacy["tokens"] == assembled

        # cursor clamping: garbage and past-the-end clamp instead of throw
        r = srv.dispatch("poll", {"request_id": rid, "from": 999}, "default")
        assert r["tokens"] == assembled  # done replies stay full regardless
        r = srv.dispatch("poll", {"request_id": rid, "from": "junk"}, "default")
        assert r["tokens"] == assembled
        c.close()
    finally:
        s._thread = None
        srv.stop()


def test_poll_many_delta_cursors(model_and_params):
    from paddle_tpu.serving.server import ServingClient, ServingServer

    s = make_session(model_and_params)
    srv = ServingServer(session=s).start()
    try:
        c = ServingClient(srv.address)
        rids = [c.submit(p, 12) for p in (PROMPT, PLAIN)]
        cursors = {rid: 0 for rid in rids}
        assembled = {rid: [] for rid in rids}
        finals = {}
        deadline = time.monotonic() + 30
        while len(finals) < len(rids) and time.monotonic() < deadline:
            items = [
                {"request_id": rid, "from": cursors[rid]}
                for rid in rids if rid not in finals
            ]
            resp = srv.dispatch("poll_many", {"items": items}, "default")
            for entry in resp["results"]:
                rid = entry["request_id"]
                if entry.get("done"):
                    finals[rid] = entry
                    continue
                assert entry["from"] == cursors[rid]
                assembled[rid].extend(entry["tokens"])
                cursors[rid] = entry["tokens_so_far"]
            time.sleep(0.005)
        assert len(finals) == len(rids), "poll_many requests never finished"
        for rid in rids:
            # completions carry the FULL list — the exactly-once dedup record
            assert finals[rid]["tokens"][:len(assembled[rid])] == assembled[rid]
        c.close()
    finally:
        srv.stop()


def _assemble_frames(frames_iter):
    """Fold push frames into (tokens, final_frame, n_frames), asserting
    prefix consistency: each frame's delta lands at its `from` cursor."""
    assembled, final, n = [], None, 0
    for frame in frames_iter:
        n += 1
        if "tokens" in frame:
            assert frame["from"] == len(assembled), "stream frame re-cut"
            assembled.extend(frame["tokens"])
            assert frame["tokens_so_far"] == len(assembled)
        if frame.get("done"):
            final = frame
            break
    return assembled, final, n


def test_push_stream_roundtrip(model_and_params):
    """stream=True submit: frames on the submit connection, multi-token
    deltas from speculative rounds, oracle-identical tokens, clean final
    frame — and the connection's framing survives for a SECOND stream."""
    from paddle_tpu.serving.server import ServingClient, ServingServer

    s = make_session(model_and_params, speculate_k=4)
    srv = ServingServer(session=s).start()
    try:
        c = ServingClient(srv.address)
        toks, final, n_frames = _assemble_frames(c.stream(PROMPT, 16))
        assert final is not None and final["finish_reason"] in ("length", "eos")
        assert n_frames >= 1
        # oracle: the same request non-streamed on a fresh identical engine
        oracle = make_session(model_and_params, speculate_k=4)
        h = oracle.submit(PROMPT, 16)
        oracle.run_until_idle()
        oracle.stop()
        assert toks == h.tokens
        # stats surface counts the pushed frames
        assert c.stats()["stream_frames_pushed"] >= n_frames
        # the generator-based client reuses nothing: a second stream works
        toks2, final2, _ = _assemble_frames(c.stream(PROMPT, 16))
        assert toks2 == toks and final2["finish_reason"] == final["finish_reason"]
        c.close()
    finally:
        srv.stop()


def test_stream_attach_midflight(model_and_params):
    """The `stream` RPC attaches to an in-flight request AT A CURSOR: a
    subscriber that already holds a prefix receives only the rest (never a
    replay of delivered tokens), and prefix + frames equals the full
    sequence. This is the reattach path a dropped push-stream resumes on.
    The engine is held and stepped by a pump thread so the attach lands
    mid-flight deterministically."""
    import threading

    from paddle_tpu.runtime.master import MasterClient
    from paddle_tpu.serving.server import ServingClient, ServingServer

    s = make_session(model_and_params)
    s._thread = True  # hold the engine; the pump below steps it
    srv = ServingServer(session=s).start()
    try:
        c = ServingClient(srv.address)
        rid = c.submit(PROMPT, 20)
        # step by hand until a prefix exists, BEFORE any pusher runs
        prefix = []
        for _ in range(50):
            s.step()
            resp = c.poll(rid, from_=0)
            if len(resp.get("tokens") or []) >= 3:
                prefix = list(resp["tokens"])
                break
        assert prefix and not resp.get("done")
        # pump the rest of the generation while the stream is attached
        pump = threading.Thread(
            target=lambda: [
                (s.step(), time.sleep(0.002))
                for _ in iter(lambda: s.scheduler.has_work(), False)
            ],
            daemon=True,
        )
        pump.start()
        conn = MasterClient([srv.address], timeout=10.0)
        frames = conn.call_stream(
            "stream", **{"from": len(prefix)}, request_id=rid,
        )
        ack = next(frames)
        assert "err" not in ack and ack["from"] == len(prefix)
        got = list(prefix)
        final = None
        for frame in frames:
            assert frame["from"] == len(got), "attach replayed or skipped"
            got.extend(frame["tokens"])
            assert frame["tokens_so_far"] == len(got)
            if frame.get("done"):
                final = frame
                break
        pump.join(timeout=30)
        full = c.poll(rid)
        assert full["done"] and got == full["tokens"] and len(got) == 20
        assert final is not None and final["finish_reason"] in ("length", "eos")
        conn.close()
        c.close()
    finally:
        s._thread = None
        srv.stop()


def test_router_stream_and_delta_poll(model_and_params):
    """Streaming THROUGH the router: client frames cut as the router's
    mirror advances, tokens identical to the routed non-streamed path, and
    delta polling works against the router's mirror too."""
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingClient, ServingServer

    router = RouterServer(lease_s=5.0, poll_interval_s=0.005).start()
    sessions = [
        make_session(model_and_params, speculate_k=4) for _ in range(2)
    ]
    servers = [
        ServingServer(session=s, router_endpoints=router.address).start()
        for s in sessions
    ]
    try:
        deadline = time.time() + 30
        while time.time() < deadline and len(router.fleet.live()) < 2:
            time.sleep(0.02)
        c = ServingClient(router.address)
        toks, final, n_frames = _assemble_frames(c.stream(PROMPT, 16))
        assert final is not None and n_frames >= 1
        oracle = c.generate(PROMPT, 16)
        assert toks == oracle["tokens"], (
            "streamed tokens must equal the routed non-streamed path "
            "(replica choice cannot change results)"
        )
        # delta poll against the router mirror
        rid = c.submit(PLAIN, 8)
        assembled, final2 = _drain_poll(c, rid)
        assert final2["tokens"][:len(assembled)] == assembled
        assert len(final2["tokens"]) == 8
        assert router.stream_frames >= n_frames
        c.close()
    finally:
        for srv in servers:
            srv.stop()
        router.stop()
