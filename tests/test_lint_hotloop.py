"""Grep-lint for the trainer hot loop: per-step host syncs must not regress.

ISSUE 4 removed every per-step device→host fetch from the train loop (the
old divergence guard called float(cost) on EVERY step — "the guard's price").
The remaining fetches are few, deliberate, and each carries a `sync-ok` tag
naming its justification:

  * the guard poll (_poll_guard, every guard_check_every steps),
  * the single pass-end fetch of the on-device cost sum,
  * the deferred log line (value copied to host asynchronously a dispatch
    earlier),
  * the opt-in PADDLE_TPU_TIMER block_until_ready.

This test fails the build if a sync-forcing call — float(...),
np.isfinite(...), .item(...), jax.device_get(...), block_until_ready(...) —
appears inside the train-loop body (SGDTrainer.train / _train_one_pass)
without a `sync-ok` tag on the line or within the few lines above it, so a
per-step sync cannot sneak back in as an innocent-looking one-liner."""

import ast
import os
import re

TRAINER_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "paddle_tpu", "trainer", "trainer.py",
)

# the train-loop body: everything these methods (and their closures) contain
HOT_METHODS = ("train", "_train_one_pass")

# calls that force a device sync when applied to a device array; jnp.* ops
# (async, traced) are deliberately NOT matched — hence the lookbehinds
SYNC_CALL = re.compile(
    r"(?<![\w.])float\(|(?<![\w.])np\.isfinite\(|\.item\(|"
    r"jax\.device_get\(|block_until_ready\("
)
# a tag on the offending line or in the contiguous comment block above it
TAG = "sync-ok"
TAG_LOOKBACK = 6  # lines


def _hot_spans(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SGDTrainer":
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name in HOT_METHODS
                ):
                    yield item.name, item.lineno, item.end_lineno


def test_no_untagged_device_sync_in_train_loop():
    with open(TRAINER_PY) as f:
        source = f.read()
    lines = source.splitlines()
    tree = ast.parse(source)
    spans = list(_hot_spans(tree))
    assert {name for name, _, _ in spans} == set(HOT_METHODS), (
        f"hot-loop methods moved/renamed — update {__file__}"
    )

    violations = []
    for name, lo, hi in spans:
        for ln in range(lo, hi + 1):
            text = lines[ln - 1]
            code = text.split("#", 1)[0]
            if not SYNC_CALL.search(code):
                continue
            window = lines[max(0, ln - TAG_LOOKBACK):ln]
            if any(TAG in w for w in window):
                continue
            violations.append(f"{name}:{ln}: {text.strip()}")
    assert not violations, (
        "device-sync call(s) in the train-loop body without a `sync-ok` "
        "tag — per-step host syncs serialize the XLA async dispatch "
        "pipeline (see ISSUE 4 / README 'Async execution'). Either move "
        "the fetch out of the hot loop or, if it is genuinely one of the "
        "sanctioned sites, tag the line with `# sync-ok: <why>`:\n  "
        + "\n  ".join(violations)
    )


def test_sanctioned_sync_sites_stay_rare():
    """The tag is a justification, not a loophole: the number of sync-ok
    sites in the hot loop is pinned so adding one forces a review here."""
    with open(TRAINER_PY) as f:
        source = f.read()
    lines = source.splitlines()
    spans = list(_hot_spans(ast.parse(source)))
    tagged = [
        ln
        for _, lo, hi in spans
        for ln in range(lo, hi + 1)
        if TAG in lines[ln - 1]
    ]
    assert len(tagged) <= 4, (
        f"{len(tagged)} sync-ok tags in the hot loop (expected <= 4): a new "
        "sanctioned sync site was added — confirm it is not per-step and "
        "bump this bound deliberately"
    )
