"""Grep-lint for the hot loops: per-step host syncs must not regress.

ISSUE 4 removed every per-step device→host fetch from the train loop (the
old divergence guard called float(cost) on EVERY step — "the guard's price").
ISSUE 6 added a second hot loop with the same discipline: the serving decode
loop, whose per-step budget is exactly ONE fetch (the sampled token ids,
which the autoregressive loop inherently needs on host).

The remaining fetches are few, deliberate, and each carries a `sync-ok` tag
naming its justification:

  trainer (SGDTrainer.train / _train_one_pass):
  * the guard poll (_poll_guard, every guard_check_every steps),
  * the single pass-end fetch of the on-device cost sum,
  * the deferred log line (value copied to host asynchronously a dispatch
    earlier),
  * the opt-in PADDLE_TPU_TIMER block_until_ready.

  serving (ServingSession._decode_once / step):
  * the sampled-token fetch after the decode dispatch.

This test fails the build if a sync-forcing call — float(...),
np.isfinite(...), .item(...), jax.device_get(...), block_until_ready(...),
and for the serving loop also np.asarray(...) — appears inside a hot-loop
body without a `sync-ok` tag on the line or within the few lines above it,
so a per-step sync cannot sneak back in as an innocent-looking one-liner.

ISSUE 10 added a sibling discipline for the serving request path: deadline
enforcement batches off ONE wall-clock read per engine step, so untagged
time.monotonic()/time.time() in the engine/scheduler/supervisor bodies trip
the `clock-ok` lint below."""

import ast
import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAINER_PY = os.path.join(_REPO, "paddle_tpu", "trainer", "trainer.py")
SERVING_PY = os.path.join(_REPO, "paddle_tpu", "serving", "session.py")
SCHEDULER_PY = os.path.join(_REPO, "paddle_tpu", "serving", "scheduler.py")
ROUTER_PY = os.path.join(_REPO, "paddle_tpu", "serving", "router.py")
SERVER_PY = os.path.join(_REPO, "paddle_tpu", "serving", "server.py")

# calls that force a device sync when applied to a device array; jnp.* ops
# (async, traced) are deliberately NOT matched — hence the lookbehinds
SYNC_CALL = re.compile(
    r"(?<![\w.])float\(|(?<![\w.])np\.isfinite\(|\.item\(|"
    r"jax\.device_get\(|block_until_ready\("
)
# the serving decode loop additionally bans untagged np.asarray — its one
# sanctioned fetch uses exactly that idiom, so an unreviewed second one
# must trip the lint
SERVING_SYNC_CALL = re.compile(
    SYNC_CALL.pattern + r"|(?<![\w.])np\.asarray\("
)

# (file, class, hot methods, pattern, max sync-ok tags)
#
# ISSUE 11 extended the serving hot surface: _prefill_chunks runs once per
# engine step while a long prompt commits (its ONE sanctioned fetch is the
# final chunk's sampled first token — per REQUEST, not per chunk), so it
# obeys the same np.asarray/float( ban as the decode loop.
# ISSUE 16 added _speculate: its ONE sanctioned fetch is the verify round's
# K+1 sampled tokens (per ROUND per slot — acceptance runs on host), so the
# verify loop obeys the same budget discipline as the decode loop.
HOT_LOOPS = [
    (TRAINER_PY, "SGDTrainer", ("train", "_train_one_pass"), SYNC_CALL, 4),
    (SERVING_PY, "ServingSession",
     ("_decode_once", "step", "_prefill_chunks", "_speculate"),
     SERVING_SYNC_CALL, 3),
]

# a tag on the offending line or in the contiguous comment block above it
TAG = "sync-ok"
TAG_LOOKBACK = 6  # lines

# -- span-recording sites (ISSUE 7 observability) ----------------------------
#
# Spans in the hot loops must go through the obs ring buffer (trace.span /
# trace.record_span / trace.span_from_monotonic — a no-op truth test when
# PADDLE_TPU_TRACE is off) and carry a `span-ok` tag naming the site; the
# count is pinned so a new per-step span forces a review here. Two hard bans
# ride along: no file I/O in a hot-loop body at all, and no string formatting
# inside a span call's arguments (f-strings/%/.format evaluate at the call
# site even when tracing is disabled — exactly the cost the gate exists to
# avoid).
SPAN_CALL = re.compile(
    r"(?<![\w.])trace\.(?:span|record_span|span_from_monotonic)\("
)
SPAN_TAG = "span-ok"
# (file, class, hot methods, max span-ok tags)
#
# ISSUE 15 added the router's dispatch/pump/reap surface: spans there are
# per-ASSIGNMENT / per-FAILOVER / per-HEDGE (never per pump cycle — note
# _pump_once is in the list precisely to keep it span-free), and the file-IO
# + span-formatting bans below apply to those bodies too.
SPAN_HOT_LOOPS = [
    (TRAINER_PY, "SGDTrainer", ("train", "_train_one_pass"), 2),
    (SERVING_PY, "ServingSession",
     ("_decode_once", "step", "_prefill_chunks", "_speculate",
      "_notify_streams"), 3),
    (ROUTER_PY, "Router",
     ("_forward", "_failover_requests", "_reap_once", "_pump_once"), 3),
]
HOT_IO_CALL = re.compile(r"(?<![\w.])open\(|\.write\(|json\.dump")
SPAN_FMT = re.compile(
    r"trace\.(?:span|record_span|span_from_monotonic)\("
    r"[^\n]*(?:f\"|f'|\.format\(|% ?\()"
)


def _hot_spans(tree: ast.Module, class_name: str, methods):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for item in node.body:
                if (
                    isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and item.name in methods
                ):
                    yield item.name, item.lineno, item.end_lineno


def _scan(path, class_name, methods, pattern, tag=TAG):
    with open(path) as f:
        source = f.read()
    lines = source.splitlines()
    spans = list(_hot_spans(ast.parse(source), class_name, methods))
    assert {name for name, _, _ in spans} == set(methods), (
        f"hot-loop methods of {class_name} moved/renamed — update {__file__}"
    )
    violations, tagged = [], []
    for name, lo, hi in spans:
        for ln in range(lo, hi + 1):
            text = lines[ln - 1]
            if tag is not None and tag in text:
                tagged.append(ln)
            code = text.split("#", 1)[0]
            if not pattern.search(code):
                continue
            if tag is not None:
                window = lines[max(0, ln - TAG_LOOKBACK):ln]
                if tag in text or any(tag in w for w in window):
                    continue
            violations.append(f"{os.path.basename(path)}:{name}:{ln}: {text.strip()}")
    return violations, tagged


def test_no_untagged_device_sync_in_hot_loops():
    violations = []
    for path, cls, methods, pattern, _ in HOT_LOOPS:
        v, _ = _scan(path, cls, methods, pattern)
        violations += v
    assert not violations, (
        "device-sync call(s) in a hot-loop body without a `sync-ok` tag — "
        "per-step host syncs serialize the XLA async dispatch pipeline (see "
        "ISSUE 4 / README 'Async execution' and the serving decode-loop "
        "contract, README 'Serving'). Either move the fetch out of the hot "
        "loop or, if it is genuinely one of the sanctioned sites, tag the "
        "line with `# sync-ok: <why>`:\n  " + "\n  ".join(violations)
    )


def test_sanctioned_sync_sites_stay_rare():
    """The tag is a justification, not a loophole: the number of sync-ok
    sites in each hot loop is pinned so adding one forces a review here."""
    for path, cls, methods, pattern, budget in HOT_LOOPS:
        _, tagged = _scan(path, cls, methods, pattern)
        assert len(tagged) <= budget, (
            f"{len(tagged)} sync-ok tags in the {cls} hot loop (expected <= "
            f"{budget}): a new sanctioned sync site was added — confirm it "
            "is not per-step and bump this bound deliberately"
        )


def test_span_sites_in_hot_loops_tagged_and_pinned():
    """Span recording inside the train / serving-decode hot loops must go
    through the obs ring-buffer API and carry a `span-ok` tag; the tag count
    is pinned so a new per-step span site forces a review here."""
    for path, cls, methods, budget in SPAN_HOT_LOOPS:
        violations, tagged = _scan(path, cls, methods, SPAN_CALL, tag=SPAN_TAG)
        assert not violations, (
            "span-recording call(s) in a hot-loop body without a `span-ok` "
            "tag — every hot-loop span must be a gated ring-buffer write "
            "(obs/trace.py) and name its justification:\n  "
            + "\n  ".join(violations)
        )
        assert len(tagged) <= budget, (
            f"{len(tagged)} span-ok tags in the {cls} hot loop (expected <= "
            f"{budget}): a new sanctioned span site was added — confirm it "
            "records per-dispatch (not per-step work beyond a ring write) "
            "and bump this bound deliberately"
        )


# -- precision-cast sites (ISSUE 9 mixed precision) --------------------------
#
# Inside the COMPILED train-step body (SGDTrainer._build_step), every dtype
# cast must go through the Policy.cast boundary (core/dtypes.py) so the
# precision policy stays auditable — a raw `.astype(` there is either a
# policy cast that bypassed the seam or an unreviewed numeric change. The
# sanctioned exceptions (int counter casts, the f32 pin of the cost
# reduction) carry a `cast-ok` tag with the count pinned below.

CAST_CALL = re.compile(r"\.astype\(")
CAST_TAG = "cast-ok"
# (file, class, compiled-step methods, max cast-ok tags)
CAST_HOT_LOOPS = [(TRAINER_PY, "SGDTrainer", ("_build_step",), 4)]


def test_no_untagged_astype_in_compiled_step():
    """Raw `.astype(` in the compiled train-step body must be tagged: dtype
    boundaries go through Policy.cast (ops/linalg.py, ops/conv.py call it at
    the dot/conv inputs), and the few sanctioned non-policy casts — int
    counters, the f32 cost pin — name their justification."""
    violations = []
    for path, cls, methods, _budget in CAST_HOT_LOOPS:
        v, _ = _scan(path, cls, methods, CAST_CALL, tag=CAST_TAG)
        violations += v
    assert not violations, (
        "untagged `.astype(` in the compiled train-step body — route "
        "precision casts through Policy.cast (core/dtypes.py) or, for a "
        "genuinely policy-free cast (int counters, f32 reduction pins), tag "
        "the line with `# cast-ok: <why>`:\n  " + "\n  ".join(violations)
    )


def test_sanctioned_cast_sites_stay_rare():
    """cast-ok is a justification, not a loophole: the count is pinned so a
    new cast site in the compiled step forces a review here."""
    for path, cls, methods, budget in CAST_HOT_LOOPS:
        _, tagged = _scan(path, cls, methods, CAST_CALL, tag=CAST_TAG)
        assert len(tagged) <= budget, (
            f"{len(tagged)} cast-ok tags in {cls}._build_step (expected <= "
            f"{budget}): a new sanctioned cast was added to the compiled "
            "step — confirm it is not a policy cast bypassing Policy.cast "
            "and bump this bound deliberately"
        )


# -- wall-clock sites (ISSUE 10 serving resilience) ---------------------------
#
# Deadline enforcement batches off ONE wall-clock read per engine step: the
# session's step() takes the timestamp and hands it to reap / pop_admissions
# / the admission stamps, so expiry cost never scales with occupancy or
# queue depth. A per-request time.monotonic() in these bodies is exactly the
# regression this lint exists to catch. The sanctioned reads — the step
# stamp, the supervisor's watchdog poll (4-16 Hz, off the engine thread),
# the once-per-restart recovery stamp, the once-per-request TTFT stamp, and
# the test-only `now is None` fallbacks — carry `clock-ok` tags with the
# counts pinned below.

CLOCK_CALL = re.compile(
    r"(?<![\w.])time\.monotonic\(|(?<![\w.])time\.time\("
)
CLOCK_TAG = "clock-ok"
# (file, class, methods on the request path, max clock-ok tags)
CLOCK_HOT_LOOPS = [
    (SERVING_PY, "ServingSession",
     ("step", "_admit", "_prefill_chunks", "_observe_ttft", "_decode_once",
      "_speculate", "_notify_streams", "_engine_loop", "_supervise",
      "_recover"), 4),
    (SCHEDULER_PY, "Scheduler",
     ("reap", "pop_admissions", "requeue_active", "retire"), 3),
    (SCHEDULER_PY, "ActiveSeq", ("append", "finished"), 1),
    # router dispatch path (ISSUE 15): one read per submit (the admission
    # stamp deadlines/hedge/park all derive from), one per pump cycle, one
    # per reaper tick, and the per-EVENT stamps (eviction, failover batch,
    # cancel, drain order, the evicted pump's grace check) — never one per
    # request per cycle. ISSUE 18 adds the takeover sweep (register_replica
    # / _sweep_replica): one stamp per REGISTRATION EVENT covering the
    # whole adopted batch.
    (ROUTER_PY, "Router",
     ("submit", "cancel", "drain", "_evict", "_failover_requests",
      "_try_assign", "_choose_replica", "_forward", "_on_result",
      "_pump_loop", "_pump_once", "_reap_once", "register_replica",
      "_sweep_replica"), 9),
]


def test_no_untagged_wallclock_in_serving_loops():
    """Wall-clock syscalls in the serving engine/scheduler request path must
    be tagged: deadline checks batch off the single per-step timestamp, so
    an untagged read is either a per-request syscall (the cost regression)
    or a second clock that lets expiry decisions disagree within one step."""
    violations = []
    for path, cls, methods, _budget in CLOCK_HOT_LOOPS:
        v, _ = _scan(path, cls, methods, CLOCK_CALL, tag=CLOCK_TAG)
        violations += v
    assert not violations, (
        "untagged wall-clock read in the serving request path — thread the "
        "step() timestamp through instead (one read per engine step feeds "
        "every deadline/cancellation check), or tag a genuinely "
        "non-per-request site with `# clock-ok: <why>`:\n  "
        + "\n  ".join(violations)
    )


def test_sanctioned_clock_sites_stay_rare():
    """clock-ok is a justification, not a loophole: the count is pinned so a
    new clock read in the serving request path forces a review here."""
    for path, cls, methods, budget in CLOCK_HOT_LOOPS:
        _, tagged = _scan(path, cls, methods, CLOCK_CALL, tag=CLOCK_TAG)
        assert len(tagged) <= budget, (
            f"{len(tagged)} clock-ok tags in the {cls} request path "
            f"(expected <= {budget}): a new sanctioned wall-clock site was "
            "added — confirm it is not per-request/per-step-per-slot and "
            "bump this bound deliberately"
        )


# -- TP dispatch seam (ISSUE 12 tensor-parallel serving) ----------------------
#
# Under TP the decode step's inputs split two ways: params + KV pool live
# SHARDED on the mesh (placed once at session init / crash re-init), block
# tables + per-slot lanes stay REPLICATED host state that the jit dispatch
# transfers as step data. A host-side jax.device_put / jnp.asarray of the
# block table inside the engine loop would re-place (and under TP, reshard)
# it EVERY step — exactly the per-step transfer discipline the sync-ok lint
# exists for, now applied to placements. The sanctioned sites (per-ADMISSION
# placement of one request's commit operands, never per-step) carry `tp-ok`
# tags with the count pinned below.

PUT_CALL = re.compile(
    r"(?<![\w.])jax\.device_put\(|(?<![\w.])device_put\(|"
    r"(?<![\w.])jnp\.asarray\(|(?<![\w.])jnp\.array\(|"
    r"make_array_from_process_local_data\("
)
PUT_TAG = "tp-ok"
# (file, class, engine-loop methods, max tp-ok tags)
PUT_HOT_LOOPS = [
    (SERVING_PY, "ServingSession",
     ("step", "_admit", "_prefill_chunks", "_decode_once", "_speculate"), 1),
]


def test_no_untagged_host_placement_in_serving_loops():
    """Host→device placements in the serving engine loop must be tagged:
    the block table and per-slot lanes ride the jit dispatch as replicated
    step data (one transfer, no explicit put), so an untagged device_put /
    jnp.asarray here is a per-step placement — under TP, a per-step
    RESHARD of host state."""
    violations = []
    for path, cls, methods, _budget in PUT_HOT_LOOPS:
        v, _ = _scan(path, cls, methods, PUT_CALL, tag=PUT_TAG)
        violations += v
    assert not violations, (
        "host->device placement in a serving engine-loop body without a "
        "`tp-ok` tag — pass host arrays straight to the jitted call (the "
        "dispatch owns the one transfer) or tag a genuinely per-admission "
        "site with `# tp-ok: <why>`:\n  " + "\n  ".join(violations)
    )


def test_sanctioned_placement_sites_stay_rare():
    """tp-ok is a justification, not a loophole: the count is pinned so a
    new placement site in the engine loop forces a review here."""
    for path, cls, methods, budget in PUT_HOT_LOOPS:
        _, tagged = _scan(path, cls, methods, PUT_CALL, tag=PUT_TAG)
        assert len(tagged) <= budget, (
            f"{len(tagged)} tp-ok tags in the {cls} engine loop (expected "
            f"<= {budget}): a new sanctioned placement site was added — "
            "confirm it is per-admission (not per-step) and bump this "
            "bound deliberately"
        )


# -- ZeRO resharding boundaries (ISSUE 14 sharded update) ---------------------
#
# Every with_sharding_constraint inside the updaters' compiled-step bodies is
# a potential COLLECTIVE (the scatter/gather boundaries the HLO pins in
# test_hlo_collectives.py count) or a placement pin. Each site carries a
# `reshard-ok` tag naming which it is, and the counts are pinned per body so
# a new resharding boundary — a second scatter, a stray gather-back under
# zero3, a per-parameter constraint replacing the concat — forces a review
# here before it silently multiplies wire traffic.

UPDATERS_PY = os.path.join(_REPO, "paddle_tpu", "parallel", "updaters.py")
WSC_CALL = re.compile(r"(?<![\w.])wsc\(|with_sharding_constraint\(")
WSC_TAG = "reshard-ok"
# (class or None for module functions, bodies, exact reshard-ok site count)
WSC_STEP_BODIES = [
    ("ShardedUpdater", ("apply",), 3),   # scatter, local-view pin, gather
    ("Zero3Updater", ("apply",), 3),     # scatter, resident pin, stay-pin
    (None, ("_z3_gather",), 2),          # owned-rows pin, THE param gather
]


def _updater_spans(tree: ast.Module, class_name, methods):
    if class_name is None:
        for node in tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in methods
            ):
                yield node.name, node.lineno, node.end_lineno
        return
    yield from _hot_spans(tree, class_name, methods)


def _scan_updaters(class_name, methods, pattern, tag):
    with open(UPDATERS_PY) as f:
        source = f.read()
    lines = source.splitlines()
    spans = list(_updater_spans(ast.parse(source), class_name, methods))
    assert {name for name, _, _ in spans} == set(methods), (
        f"updater step bodies {methods} moved/renamed — update {__file__}"
    )
    violations, tagged = [], 0
    for name, lo, hi in spans:
        for ln in range(lo, hi + 1):
            code = lines[ln - 1].split("#", 1)[0]
            if not pattern.search(code):
                continue
            window = lines[max(0, ln - TAG_LOOKBACK):ln]
            if tag in lines[ln - 1] or any(tag in w for w in window):
                tagged += 1
                continue
            violations.append(
                f"updaters.py:{name}:{ln}: {lines[ln - 1].strip()}"
            )
    return violations, tagged


def test_updater_reshard_sites_tagged_and_pinned():
    """Sanctioned gather/scatter sites in the sharded-update step bodies:
    every wsc() is tagged `reshard-ok` and the per-body counts are exact —
    the alias `wsc = jax.lax.with_sharding_constraint` line itself does not
    count (no call parens)."""
    for cls, methods, count in WSC_STEP_BODIES:
        violations, tagged = _scan_updaters(cls, methods, WSC_CALL, WSC_TAG)
        where = cls or "module"
        assert not violations, (
            f"untagged resharding constraint in {where} step body — a new "
            "collective boundary needs a `# reshard-ok: <why>` tag and a "
            "deliberate count bump here:\n  " + "\n  ".join(violations)
        )
        assert tagged == count, (
            f"{tagged} reshard-ok sites in {where}.{methods} (pinned "
            f"{count}): the sharded update's resharding structure changed — "
            "re-check the HLO collective pins and re-pin both"
        )


# -- router replica RPCs (ISSUE 15 multi-replica serving) ---------------------
#
# The router's whole reason to exist over "a proxy that asks each replica"
# is that its DISPATCH decisions run on piggybacked state: load/health ride
# replica heartbeats, results ride ONE batch poll per replica per pump
# cycle, and the only blocking replica RPCs on the request path are the
# submit forward itself, the pump's poll_many, and the cancel order (hedge
# losers / client cancels). A per-request `.call(` anywhere else in the
# assignment/pump/reap path is the "RPC Considered Harmful" regression this
# lint pins — a fleet-size cap smuggled in as an innocent health probe.

# ISSUE 20: `.call_many(` (the pipelined batch) and `.call_stream(` are
# round trips too — a batched RPC smuggled into a dispatch loop is still
# a blocking replica RPC and needs the same tag
RPC_CALL = re.compile(r"\.call(?:_many|_stream)?\(")
RPC_TAG = "rpc-ok"
# (file, class, dispatch-path methods, max rpc-ok tags)
#
# ISSUE 18 adds the takeover sweep to the pinned surface: register_replica /
# _sweep_replica make exactly ONE `outstanding` call per replica
# REGISTRATION EVENT (rebuilding the in-flight books after a router
# takeover) — pinned here so the sweep can never creep into the pump or
# dispatch cycles.
ROUTER_RPC_LOOPS = [
    (ROUTER_PY, "Router",
     ("submit", "_try_assign", "_choose_replica", "_forward", "_pump_once",
      "_on_result", "_reap_once", "_failover_requests", "_send_cancels",
      "register_replica", "_sweep_replica"), 4),
]


def test_no_untagged_replica_rpc_in_router_dispatch():
    """Blocking replica RPCs in the router's assignment/pump/reap path must
    be tagged: dispatch decisions read piggybacked state only, and the three
    sanctioned calls (submit forward, batch poll, cancel order) name
    themselves with `rpc-ok`."""
    violations = []
    for path, cls, methods, _budget in ROUTER_RPC_LOOPS:
        v, _ = _scan(path, cls, methods, RPC_CALL, tag=RPC_TAG)
        violations += v
    assert not violations, (
        "blocking replica RPC in the router dispatch path without an "
        "`rpc-ok` tag — route the signal over replica heartbeats / the "
        "pump's poll_many batch instead, or tag a genuinely per-event "
        "(never per-request-per-cycle) site with `# rpc-ok: <why>`:\n  "
        + "\n  ".join(violations)
    )


def test_sanctioned_router_rpc_sites_stay_rare():
    """rpc-ok is a justification, not a loophole: the count is pinned so a
    new blocking replica call in the dispatch path forces a review here."""
    for path, cls, methods, budget in ROUTER_RPC_LOOPS:
        _, tagged = _scan(path, cls, methods, RPC_CALL, tag=RPC_TAG)
        assert len(tagged) <= budget, (
            f"{len(tagged)} rpc-ok tags in the {cls} dispatch path "
            f"(expected <= {budget}): a new sanctioned replica RPC was "
            "added — confirm it is per-event (submit forward / batch poll "
            "/ cancel), not per-request-per-cycle, and bump this bound "
            "deliberately"
        )


def test_no_file_io_in_hot_loops():
    """No open()/.write()/json.dump in any hot-loop body, tagged or not —
    span export and metric scraping happen OUTSIDE the loops (export_chrome,
    the metrics/trace_export RPCs)."""
    violations = []
    for path, cls, methods, _budget in SPAN_HOT_LOOPS:
        v, _ = _scan(path, cls, methods, HOT_IO_CALL, tag=None)
        violations += v
    assert not violations, (
        "file I/O in a hot-loop body — move it behind the ring buffer / "
        "pass boundary:\n  " + "\n  ".join(violations)
    )


def test_span_args_not_formatted_in_hot_loops():
    """Span call arguments in hot loops must be cheap literals: an f-string
    or %/.format inside the call evaluates at the call site even when
    tracing is DISABLED, defeating the near-zero-cost gate."""
    violations = []
    for path, cls, methods, _budget in SPAN_HOT_LOOPS:
        v, _ = _scan(path, cls, methods, SPAN_FMT, tag=None)
        violations += v
    assert not violations, (
        "string formatting inside a hot-loop span call (evaluates even with "
        "tracing off) — pass raw ints/strings instead:\n  "
        + "\n  ".join(violations)
    )


# -- push-stream emit path (ISSUE 16 token streaming) -------------------------
#
# Push streaming splits in two on purpose: the ENGINE's entire contribution
# is a sequence-number bump under a condition variable (_notify_streams /
# stream_wait — same pair on the router's mirror), while every socket write
# happens on a server handler thread (server._Handler._push_frames; the
# router server reuses the same handler). That is what makes a slow or dead
# subscriber unable to block a decode step. Two pins keep the separation
# honest: the engine-side seam stays free of socket/frame emission, and
# encode_frame() — the framing seam call_stream() parses against — is called
# from the handler push loop only.

STREAM_EMIT = re.compile(
    r"\.sendall\(|(?<![\w.])encode_frame\(|\.makefile\(|\bwfile\b"
)
# (file, class, engine-side stream-seam methods)
STREAM_SEAM = [
    (SERVING_PY, "ServingSession",
     ("_notify_streams", "stream_wait", "step", "_decode_once",
      "_speculate")),
    (ROUTER_PY, "Router",
     ("_notify_streams", "stream_wait", "_on_result", "_pump_once")),
]


def test_engine_stream_seam_is_socket_free():
    """No socket/frame emission in the engine-side stream seam: the engine
    and the router's pump announce progress with a seq bump + notify_all and
    NOTHING else — pusher threads (which own the sockets) do the writing, so
    backpressure from one subscriber never reaches the decode loop."""
    violations = []
    for path, cls, methods in STREAM_SEAM:
        v, _ = _scan(path, cls, methods, STREAM_EMIT, tag=None)
        violations += v
    assert not violations, (
        "socket/frame emission in the engine-side stream seam — frames are "
        "written by server handler threads (_Handler._push_frames) only; "
        "the engine/router signal progress via stream_wait's condition "
        "variable:\n  " + "\n  ".join(violations)
    )


# -- autoscaler controller loop (ISSUE 17) ------------------------------------
#
# The controller's contract is "zero new RPCs on anyone's hot path": its
# entire network footprint is one cold-path `stats` poll per endpoint per
# tick (_observe) plus one lever call per ADMITTED decision (_actuate's
# drain order / resize announce — cooldown-rate-limited, so never per-tick).
# The decision engine itself (ScaleDecider.decide/_admit) is PURE: no RPCs,
# no clock reads — every cooldown/flap/backoff comparison uses the single
# `now` stamp the tick takes once. These pins keep a "quick health probe"
# or a second clock from sneaking into the reconcile loop.

AUTOSCALER_PY = os.path.join(_REPO, "paddle_tpu", "runtime", "autoscaler.py")
# (file, class, methods, max rpc-ok tags)
AUTOSCALER_RPC_LOOPS = [
    (AUTOSCALER_PY, "AutoscalerController",
     ("_observe", "_actuate", "_watch_resize", "tick", "_drain_victim"), 4),
]
# (file, class, methods, max clock-ok tags)
AUTOSCALER_CLOCK_LOOPS = [
    (AUTOSCALER_PY, "AutoscalerController",
     ("_observe", "_actuate", "_watch_resize", "tick", "_drain_victim"), 1),
]
# the pure decision engine: no tags allowed at all — a single RPC or clock
# read in decide()/_admit() breaks both determinism and the test story
DECIDER_PURE = [
    (AUTOSCALER_PY, "ScaleDecider",
     ("decide", "_admit", "_suppress", "note_resize_rejected",
      "note_resize_ok")),
]


def test_no_untagged_rpc_in_controller_loop():
    """Blocking RPCs in the controller's reconcile loop must be tagged: the
    sanctioned four are the two once-per-tick stats polls (_observe) and the
    two per-admitted-decision lever calls (_actuate)."""
    violations = []
    for path, cls, methods, _budget in AUTOSCALER_RPC_LOOPS:
        v, _ = _scan(path, cls, methods, RPC_CALL, tag=RPC_TAG)
        violations += v
    assert not violations, (
        "blocking RPC in the autoscaler reconcile loop without an `rpc-ok` "
        "tag — observation rides the existing stats endpoints once per tick "
        "and actuation is one lever call per admitted decision; anything "
        "else is a new RPC on the control loop:\n  " + "\n  ".join(violations)
    )


def test_sanctioned_controller_rpc_sites_stay_rare():
    for path, cls, methods, budget in AUTOSCALER_RPC_LOOPS:
        _, tagged = _scan(path, cls, methods, RPC_CALL, tag=RPC_TAG)
        assert len(tagged) <= budget, (
            f"{len(tagged)} rpc-ok tags in the {cls} reconcile loop "
            f"(expected <= {budget}): a new sanctioned RPC site was added — "
            "confirm it is once-per-tick (observe) or per-admitted-decision "
            "(actuate) and bump this bound deliberately"
        )


def test_controller_tick_reads_the_clock_exactly_once():
    """One wall-clock read per tick, tagged: every cooldown / flap-window /
    backoff comparison inside the decision engine uses that single stamp, so
    rate-limit decisions cannot disagree within a tick."""
    for path, cls, methods, budget in AUTOSCALER_CLOCK_LOOPS:
        violations, tagged = _scan(path, cls, methods, CLOCK_CALL,
                                   tag=CLOCK_TAG)
        assert not violations, (
            "untagged wall-clock read in the controller loop — thread "
            "tick()'s single stamp through instead:\n  "
            + "\n  ".join(violations)
        )
        assert len(tagged) <= budget, (
            f"{len(tagged)} clock-ok tags in the {cls} loop (expected <= "
            f"{budget}): the controller should take ONE stamp per tick"
        )


def test_scale_decider_is_pure():
    """The decision engine makes no RPCs and reads no clocks, tagged or
    otherwise — `now` is an argument. That purity is what lets
    tests/test_autoscaler.py pin hysteresis/cooldown/flap/backoff behavior
    with a fake clock and zero sockets."""
    for path, cls, methods in DECIDER_PURE:
        for pattern, what in ((RPC_CALL, "RPC"), (CLOCK_CALL, "clock read")):
            v, _ = _scan(path, cls, methods, pattern, tag=None)
            assert not v, (
                f"{what} inside the pure decision engine ({cls}) — decide() "
                "takes signals and a caller-supplied `now`; move the side "
                "effect to the controller's observe/actuate phases:\n  "
                + "\n  ".join(v)
            )


# -- election loop + takeover sweep (ISSUE 18 control-plane HA) ---------------
#
# The standby watcher (runtime/election.py) is deliberately dumb: raw TCP
# connect probes, NO RPC protocol — so a standby can watch anything that
# listens and a wedged primary's RPC layer can't wedge its own watcher. Its
# entire clock footprint is the max_wait_s deadline (one stamp per watch,
# one expiry check per poll_s-paced cycle). An untagged `.call(` appearing
# in the watcher would mean election grew a protocol dependency; a new
# clock read would mean a second pacing source.

ELECTION_PY = os.path.join(_REPO, "paddle_tpu", "runtime", "election.py")
ELECTION_RPC_LOOPS = [
    (ELECTION_PY, "StandbyWatcher", ("wait_for_takeover", "_probe_once"), 0),
]
ELECTION_CLOCK_LOOPS = [
    (ELECTION_PY, "StandbyWatcher", ("wait_for_takeover", "_probe_once"), 2),
]


def test_election_watcher_probes_without_rpc():
    """The election loop holds zero rpc-ok tags: probes are raw socket
    connects (protocol-free on purpose), never MasterClient calls."""
    for path, cls, methods, budget in ELECTION_RPC_LOOPS:
        violations, tagged = _scan(path, cls, methods, RPC_CALL, tag=RPC_TAG)
        assert not violations and len(tagged) <= budget, (
            "RPC call inside the election watcher — the probe loop must "
            "stay protocol-free (a raw TCP connect) so it can watch any "
            "listener and can't be wedged by a wedged RPC layer:\n  "
            + "\n  ".join(violations)
        )


def test_election_watcher_clock_sites_pinned():
    """Two tagged clock sites in the watcher (the max_wait_s stamp and its
    per-cycle expiry check); pacing itself rides time.sleep(poll_s)."""
    for path, cls, methods, budget in ELECTION_CLOCK_LOOPS:
        violations, tagged = _scan(path, cls, methods, CLOCK_CALL,
                                   tag=CLOCK_TAG)
        assert not violations, (
            "untagged wall-clock read in the election watcher:\n  "
            + "\n  ".join(violations)
        )
        assert len(tagged) <= budget, (
            f"{len(tagged)} clock-ok tags in the {cls} loop (expected <= "
            f"{budget}): the watcher needs only the deadline stamp + check"
        )


def test_takeover_sweep_stays_out_of_pump_and_dispatch():
    """The takeover sweep runs once per replica REGISTRATION EVENT — never
    inside the pump/reap/assignment cycles. Pin the separation textually:
    the hot cycle bodies must not mention the sweep or its RPC method, so
    'just re-sweep every cycle' can't land without tripping this."""
    with open(ROUTER_PY) as f:
        source = f.read()
    spans = _hot_spans(
        ast.parse(source), "Router",
        ("_pump_once", "_reap_once", "_try_assign", "_forward",
         "_on_result"),
    )
    lines = source.splitlines()
    offenders = []
    for name, lo, hi in spans:
        body = "\n".join(lines[lo - 1:hi])
        for needle in ("_sweep_replica", '"outstanding"', "'outstanding'"):
            if needle in body:
                offenders.append(f"Router.{name}: contains {needle}")
    assert not offenders, (
        "takeover sweep reached a hot cycle body — reconciliation is a "
        "once-per-registration cold path (register_replica), not per-cycle "
        "work:\n  " + "\n  ".join(offenders)
    )


def test_frame_encoding_only_in_handler_push_loop():
    """encode_frame() has exactly one call site: _Handler._push_frames. Any
    second caller is a second framing implementation waiting to drift from
    what MasterClient.call_stream parses."""
    with open(SERVER_PY) as f:
        source = f.read()
    spans = list(_hot_spans(ast.parse(source), "_Handler", ("_push_frames",)))
    assert spans, f"_Handler._push_frames moved/renamed — update {__file__}"
    _, lo, hi = spans[0]
    call = re.compile(r"(?<![\w.])encode_frame\(")
    offenders = []
    for ln, text in enumerate(source.splitlines(), 1):
        code = text.split("#", 1)[0]
        if not call.search(code) or code.lstrip().startswith("def "):
            continue
        if not (lo <= ln <= hi):
            offenders.append(f"server.py:{ln}: {text.strip()}")
    assert not offenders, (
        "encode_frame() called outside _Handler._push_frames — keep one "
        "framing seam so pushed frames and call_stream's parser cannot "
        "drift apart:\n  " + "\n  ".join(offenders)
    )


# -- shared-prefix cache index (ISSUE 19 prefix caching) ----------------------
#
# The prefix index is pure host bookkeeping: a radix-over-pages dict keyed by
# (parent node, page token chunk) with a LOGICAL LRU tick. It runs under the
# scheduler's admission locks — including the submit-thread peek — so it must
# never read a clock (the logical tick exists precisely so eviction order is
# deterministic and lock hold times stay bounded), never make an RPC, and
# never place or touch a device array (aliasing is a block-table edit; the KV
# pools are neither read nor written). Zero tolerance, no tags.
#
# The admission path gets exactly ONE sanctioned per-submit hash computation
# (Scheduler.submit's peek_hit_tokens call — prices the wait estimate and the
# chunk count by the UNCACHED suffix) and exactly TWO registration sites
# (ServingSession._admit for whole-prompt commits, _prefill_chunks for
# per-chunk commits). The counts are pinned so a second hash walk cannot
# creep into a per-step body as an innocent-looking freshness check.

PREFIX_PY = os.path.join(_REPO, "paddle_tpu", "serving", "prefix_cache.py")
PREFIX_INDEX_METHODS = (
    "__init__", "__len__", "pages", "holds", "_root_for", "max_match_pages",
    "match", "_root_children", "peek_hit_tokens", "extend", "evictable",
    "evict_lru", "drop_all", "stats",
)


def test_prefix_index_is_pure():
    """The cache index never touches a clock, a socket, or a device array,
    tagged or otherwise — its LRU is a logical counter, its lookups are dict
    walks, and the one structure it influences (the block table) is edited
    by PagedKVCache, not by the index."""
    for pattern, what in (
        (CLOCK_CALL, "wall-clock read"),
        (RPC_CALL, "RPC"),
        (PUT_CALL, "device placement"),
    ):
        v, _ = _scan(PREFIX_PY, "PrefixIndex", PREFIX_INDEX_METHODS,
                     pattern, tag=None)
        assert not v, (
            f"{what} inside the prefix cache index — the index is pure host "
            "bookkeeping that runs under admission locks; move the side "
            "effect to the session/scheduler cold path:\n  " + "\n  ".join(v)
        )


def _call_sites(path, call: "re.Pattern"):
    with open(path) as f:
        source = f.read()
    sites = []
    for ln, text in enumerate(source.splitlines(), 1):
        code = text.split("#", 1)[0]
        if call.search(code) and not code.lstrip().startswith("def "):
            sites.append(ln)
    return source, sites


def test_prefix_admission_hash_sites_pinned():
    """Exactly one `.peek_hit_tokens(` site in the scheduler — inside
    submit(), the sanctioned per-admission hash computation — and exactly
    two `.commit_prefix(` sites in the session (whole-prompt commit in
    _admit, per-chunk commit in _prefill_chunks). Each computation walks the
    prompt once, so a second site is a second O(prompt) walk on the request
    path and needs a deliberate re-pin here."""
    peek = re.compile(r"\.peek_hit_tokens\(")
    source, sites = _call_sites(SCHEDULER_PY, peek)
    spans = list(_hot_spans(ast.parse(source), "Scheduler", ("submit",)))
    assert spans, f"Scheduler.submit moved/renamed — update {__file__}"
    _, lo, hi = spans[0]
    assert len(sites) == 1 and lo <= sites[0] <= hi, (
        f".peek_hit_tokens( call sites in scheduler.py at lines {sites} "
        "(pinned: exactly 1, inside Scheduler.submit) — the admission-path "
        "hash computation happens ONCE per submit; route any new consumer "
        "through handle.prefix_hint instead of re-hashing"
    )

    commit = re.compile(r"\.commit_prefix\(")
    source, sites = _call_sites(SERVING_PY, commit)
    spans = list(_hot_spans(
        ast.parse(source), "ServingSession", ("_admit", "_prefill_chunks")))
    assert len(spans) == 2, (
        f"ServingSession._admit/_prefill_chunks moved/renamed — "
        f"update {__file__}"
    )
    in_span = [ln for ln in sites
               if any(lo <= ln <= hi for _, lo, hi in spans)]
    assert len(sites) == 2 and in_span == sites, (
        f".commit_prefix( call sites in session.py at lines {sites} "
        "(pinned: exactly 2 — _admit's whole-prompt commit and "
        "_prefill_chunks' per-chunk commit) — registration covers COMMITTED "
        "pages only; a third site is either a duplicate registration or an "
        "uncommitted-page leak into the shared index"
    )


def test_decode_hot_bodies_stay_prefix_free():
    """The per-step decode/verify bodies never touch the prefix cache: all
    index work happens at admission (reserve/peek) and at prefill commit.
    Pin the separation textually so 'just refresh the LRU every step' or a
    per-step re-hash can't land without tripping this."""
    with open(SERVING_PY) as f:
        source = f.read()
    spans = _hot_spans(
        ast.parse(source), "ServingSession",
        ("step", "_decode_once", "_speculate"),
    )
    lines = source.splitlines()
    offenders = []
    for name, lo, hi in spans:
        body = "\n".join(lines[lo - 1:hi])
        for needle in ("commit_prefix", "peek_hit_tokens", ".prefix"):
            if needle in body:
                offenders.append(f"ServingSession.{name}: contains {needle}")
    assert not offenders, (
        "prefix-cache work reached a per-step body — the index is an "
        "admission/commit-time structure (reserve aliases, commit_prefix "
        "registers); decode and verify only ever write pages past the "
        "prompt:\n  " + "\n  ".join(offenders)
    )


# -- binary control plane (ISSUE 20 framed wire) ------------------------------
#
# The framed transport exists to get per-token/per-task JSON encode cost OFF
# the hot paths: stream pushes ride frames.encode_stream (compact binary
# deltas), control replies ride frames.write_frame, and heartbeats piggyback
# on data frames. Two disciplines keep that true:
#
#   * the hot emission/dispatch bodies — router pump + dispatch, the
#     handler's frame loop and push loop, both heartbeat loops — never call
#     json.dumps/json.loads DIRECTLY (zero tolerance, no tag): every codec
#     decision lives behind the frames/encode_frame seams, so switching a
#     connection's wire can never leave a stray JSON encode on the hot path;
#   * the header struct is packed in exactly THREE places, all inside
#     frames.write_frame / frames.encode_stream, and server.py reaches
#     frames.encode_stream through exactly ONE call site (encode_frame, the
#     seam call_stream parses against) — one framing implementation, nothing
#     to drift.

FRAMES_PY = os.path.join(_REPO, "paddle_tpu", "runtime", "frames.py")
MASTER_PY = os.path.join(_REPO, "paddle_tpu", "runtime", "master.py")
FLEET_PY = os.path.join(_REPO, "paddle_tpu", "serving", "fleet.py")

JSON_CODEC_CALL = re.compile(r"(?<![\w.])json\.dumps\(|(?<![\w.])json\.loads\(")
# (file, class, wire-hot methods) — zero tolerance, no tags
WIRE_JSON_FREE = [
    (ROUTER_PY, "Router",
     ("_pump_once", "_on_result", "_try_assign", "_choose_replica",
      "_forward", "_send_cancels")),
    (SERVER_PY, "_Handler",
     ("_push_frames", "_serve_frames", "_reply_frame", "_dispatch")),
    (MASTER_PY, "_Heartbeater", ("_loop",)),
    (FLEET_PY, "ReplicaAgent", ("_loop",)),
]


def test_wire_hot_paths_free_of_direct_json_codec():
    """No direct json.dumps/json.loads in the wire-hot bodies, tagged or
    not — encoding decisions belong to the frames module / encode_frame
    seam, where the per-connection wire negotiation picks the codec."""
    violations = []
    for path, cls, methods in WIRE_JSON_FREE:
        v, _ = _scan(path, cls, methods, JSON_CODEC_CALL, tag=None)
        violations += v
    assert not violations, (
        "direct JSON codec call on a wire-hot path — route it through "
        "frames.write_frame / encode_frame so the negotiated wire (not the "
        "call site) owns the encoding:\n  " + "\n  ".join(violations)
    )


def _module_spans(tree: ast.Module, methods):
    """Module-level function spans (the _hot_spans sibling for functions
    that live outside any class)."""
    for node in tree.body:
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name in methods
        ):
            yield node.name, node.lineno, node.end_lineno


def test_frame_header_packed_only_in_the_two_encoders():
    """`_HEADER.pack(` appears exactly 3 times in frames.py — once in
    write_frame (control/reply frames) and twice in encode_stream (the
    compact delta and the JSON-carrying stream frame). A fourth site is a
    second framing implementation."""
    source, sites = _call_sites(FRAMES_PY, re.compile(r"_HEADER\.pack\("))
    spans = {name: (lo, hi) for name, lo, hi in _module_spans(
        ast.parse(source), ("write_frame", "encode_stream"))}
    assert set(spans) == {"write_frame", "encode_stream"}, (
        f"frames.write_frame/encode_stream moved/renamed — update {__file__}"
    )
    in_wf = [ln for ln in sites
             if spans["write_frame"][0] <= ln <= spans["write_frame"][1]]
    in_es = [ln for ln in sites
             if spans["encode_stream"][0] <= ln <= spans["encode_stream"][1]]
    assert len(sites) == 3 and len(in_wf) == 1 and len(in_es) == 2, (
        f"_HEADER.pack( sites in frames.py at lines {sites} (pinned: 1 in "
        "write_frame + 2 in encode_stream) — every frame on the wire must "
        "come from one of the two encoders call sites parse against"
    )


def test_stream_binary_encoder_reached_through_one_seam():
    """server.py calls frames.encode_stream from exactly one place — inside
    encode_frame, the wire-switch seam — so the framed and line stream
    encodings can never diverge per call site."""
    source, sites = _call_sites(SERVER_PY, re.compile(r"encode_stream\("))
    spans = list(_module_spans(ast.parse(source), ("encode_frame",)))
    assert spans, f"server.encode_frame moved/renamed — update {__file__}"
    _, lo, hi = spans[0]
    assert len(sites) == 1 and lo <= sites[0] <= hi, (
        f"encode_stream( call sites in server.py at lines {sites} (pinned: "
        "exactly 1, inside encode_frame) — push frames pick their codec at "
        "the encode_frame seam only"
    )
