"""ISSUE 9: mixed-precision training — bf16 compute with f32 masters — and
the remat/scan policies.

The contract under SGDTrainer(precision="bf16"):
  * dot/conv inputs cross to bfloat16 through Policy.cast (>= 1 bf16 dot in
    the compiled step's HLO), so the MXU runs its native path on TPU;
  * parameters are f32 MASTERS end to end — created f32, updated f32 by the
    optimizer, stored f32 by checkpoints — and NEVER round-trip through
    bf16 (pinned bitwise below with an off-bf16-grid master value);
  * numerically-sensitive reductions (xent, batch-norm statistics, the
    pass-cost average, the divergence guard's isfinite) stay f32;
  * a bf16-trained checkpoint resumes bitwise into an f32 trainer and vice
    versa (same f32 masters on disk), composing with shard_update /
    grad_compression / K-step dispatch / elastic resize;
  * remat ("dots" | "conv_only" | "full") changes step time and residual
    memory, never the applied updates.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core import dtypes, preempt
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD, Adam
from paddle_tpu.parallel import DataParallel, make_mesh
from paddle_tpu.trainer import SGDTrainer
from paddle_tpu.trainer.events import EndIteration, EndPass

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_cache():
    """Detach the suite's persistent compile cache for this module.

    This file interleaves collective-donated mesh programs with REPEATED
    identical single-device donated step programs (same tiny FC model across
    many tests). That is exactly the jax-0.4.37 CPU pattern where executing
    a persistent-cache-DESERIALIZED donated program corrupts memory/segfaults
    once collective donated programs have run in the process — the PR-5
    `_cache_salt` / PR-8 `detach_compilation_cache` gotcha, which salts MESH
    step programs but deliberately leaves single-device programs cacheable.
    Reproducer: `pytest tests/test_parallel.py tests/test_precision.py`
    segfaults inside test_cross_precision_checkpoint_masters_bitwise's step
    dispatch without this fixture. Compiling fresh here costs ~10 s and
    removes the deserialized-execution hazard; the cache is restored for the
    rest of the suite."""
    import jax
    from jax.experimental.compilation_cache import compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    compilation_cache.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    compilation_cache.reset_cache()


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()
    preempt.reset()


DIM, CLASSES = 16, 4


def _build_cost():
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 24, act="relu", name="h")
    logits = L.Fc(h, CLASSES, act=None, name="out")
    return C.ClassificationCost(logits, lbl, name="cost")


def _data(n=96, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, DIM).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int32) + 2 * (x[:, 0] > 0).astype(np.int32)
    return x, y


def _reader(x, y, bs=16):
    def reader():
        for i in range(0, len(x), bs):
            yield {"x": x[i:i + bs], "label": y[i:i + bs]}

    return reader


def _trainer(precision=None, remat=None, parallel=None, **kw):
    reset_name_scope()
    return SGDTrainer(
        _build_cost(),
        kw.pop("optimizer", SGD(learning_rate=0.125, momentum=0.5)),
        parallel=parallel, seed=5, precision=precision, remat=remat, **kw,
    )


def _batch(bs=16, seed=0):
    x, y = _data(bs, seed)
    return {"x": x, "label": y}


def _params(tr):
    return {k: np.asarray(v) for k, v in tr.state["params"].items()}


def _assert_bitwise(a, b, what=""):
    for k in a:
        assert np.array_equal(
            a[k].view(np.uint32), b[k].view(np.uint32)
        ), f"{what}: param {k} differs (max abs {np.abs(a[k] - b[k]).max()})"


# -- Policy / cast unit tests (tier-1 fast) -----------------------------------


def test_policy_get_spellings():
    assert dtypes.get("bf16") is dtypes.get("bfloat16")
    assert dtypes.get("f32") is dtypes.get("float32") is dtypes.get(None)
    with pytest.raises(ValueError, match="f32.*bf16"):
        dtypes.get("fp16")


def test_policy_names():
    assert dtypes.f32_policy().name == "f32"
    assert dtypes.bf16_policy().name == "bf16"


def test_policy_cast_floats_only():
    p = dtypes.bf16_policy()
    assert p.cast(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16
    assert p.cast(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.bfloat16
    assert p.cast(jnp.ones((2,), jnp.int32)).dtype == jnp.int32
    assert p.cast(jnp.ones((2,), jnp.bool_)).dtype == jnp.bool_
    f = dtypes.f32_policy()
    assert f.cast(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32
    # old spelling stays callable (out-of-tree users)
    assert p.cast_compute(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16


def test_trainer_precision_override_beats_ambient():
    tr = _trainer(precision="bf16")
    assert tr.precision == "bf16"
    with dtypes.policy_scope(dtypes.bf16_policy()):
        assert _trainer().precision == "bf16"  # ambient default
        assert _trainer(precision="f32").precision == "f32"  # explicit wins
    assert _trainer().precision == "f32"


def test_invalid_precision_and_remat_rejected():
    with pytest.raises(ValueError, match="f32"):
        _trainer(precision="fp8")
    with pytest.raises(ValueError, match="remat"):
        _trainer(remat="checkpoint_everything")
    tr = _trainer()
    with pytest.raises(ValueError, match="remat"):
        tr.train(_reader(*_data(16)), remat="bogus")


# -- HLO shape of the bf16 step ----------------------------------------------


def _step_hlo(tr, bs=16):
    batch = _batch(bs)
    tr.init_state(batch)
    return tr._make_step().lower(tr.state, batch).as_text()


def _bf16_dots(hlo):
    return [
        ln for ln in hlo.splitlines() if "dot_general" in ln and "bf16" in ln
    ]


def test_bf16_step_contains_bf16_dots():
    """The acceptance HLO assert: the bf16 step's dots run on bf16 inputs
    (forward AND the backward's grad dots), and the f32 step has none."""
    hlo = _step_hlo(_trainer(precision="bf16"))
    assert len(_bf16_dots(hlo)) >= 1, "no bf16 dot in the bf16 step"
    # every dot crossed the cast boundary: none left computing in f32
    f32_dots = [
        ln for ln in hlo.splitlines()
        if "dot_general" in ln and "bf16" not in ln
    ]
    assert not f32_dots, f32_dots
    assert not _bf16_dots(_step_hlo(_trainer(precision="f32")))


def test_policy_scope_reaches_rnn_attention_dots():
    """The seq2seq decoder's GRU/additive-attention matmuls take no policy
    parameter — they consult the AMBIENT dtypes.current() global.
    Network.init/apply pin the ambient to the trace's policy, so an explicit
    SGDTrainer(precision=...) wins over a contaminated process global in
    BOTH directions: the bench's f32 baseline leg stays all-f32 even though
    run_bench sets the ambient to bf16, and a bf16 trainer under an f32
    ambient gets bf16 dots in the recurrent core (the model the MFU push
    actually targets), not just in the Fc layers."""
    from paddle_tpu.models import Seq2SeqModel

    vocab, dim, bs, t = 50, 16, 4, 4
    rs = np.random.RandomState(0)
    s = rs.randint(2, vocab, (bs, t)).astype(np.int32)
    lens = np.full(bs, t, np.int32)
    batch = {
        "source_ids": s, "source_ids.lengths": lens,
        "target_ids": s, "target_ids.lengths": lens,
        "label_ids": s, "label_ids.lengths": lens,
    }

    def dots(precision, ambient):
        reset_name_scope()
        with dtypes.policy_scope(dtypes.get(ambient)):
            model = Seq2SeqModel(vocab, vocab, embed_dim=dim, hidden_dim=dim)
            tr = SGDTrainer(
                model.cost, SGD(learning_rate=0.1), seed=0,
                precision=precision,
            )
            tr.init_state(batch)
            hlo = tr._make_step().lower(tr.state, batch).as_text()
        lines = [ln for ln in hlo.splitlines() if "dot_general" in ln]
        return lines, [ln for ln in lines if "bf16" in ln]

    all_f32, bf16_in_f32 = dots("f32", ambient="bf16")
    assert all_f32 and not bf16_in_f32, bf16_in_f32[:3]
    all_bf16, bf16_in_bf16 = dots("bf16", ambient="f32")
    # every dot in the step — encoder/decoder GRU scans, attention scores
    # and context, projections, fwd AND bwd — crossed the cast boundary
    assert bf16_in_bf16 and len(bf16_in_bf16) == len(all_bf16), [
        ln for ln in all_bf16 if "bf16" not in ln
    ][:3]


def test_bf16_masters_stay_f32_in_state():
    tr = _trainer(precision="bf16")
    batch = _batch()
    tr.init_state(batch)
    step = tr._make_step()
    st, cost, _ = step(tr.state, batch)
    assert cost.dtype == jnp.float32  # pinned reduction
    for k, v in st["params"].items():
        assert v.dtype == jnp.float32, f"master {k} left f32"
    for k, slots in tr.updater.to_canonical(st["opt"])["slots"].items():
        for s in slots:
            assert s.dtype == jnp.float32, f"opt slot of {k} left f32"


def test_master_never_roundtrips_bf16():
    """The zero-round-trip half of the acceptance HLO assert, pinned
    behaviorally: an f32 master holding a value OFF the bf16 grid
    (1 + 2^-20) must survive a whole compiled step bitwise when the update
    is zero (lr_scale=0) — any f32→bf16→f32 round-trip of the master on the
    update path would flush the low mantissa bits."""
    off_grid = np.float32(1.0 + 2.0 ** -20)
    assert np.float32(jnp.asarray(off_grid, jnp.bfloat16)) != off_grid
    tr = _trainer(precision="bf16")
    batch = _batch()
    tr.init_state(batch)
    tr.state["params"] = {
        k: jnp.full_like(v, off_grid) for k, v in tr.state["params"].items()
    }
    tr.state["lr_scale"] = jnp.zeros((), jnp.float32)
    st, _, _ = tr._make_step()(tr.state, batch)
    for k, v in st["params"].items():
        got = np.asarray(v)
        assert (got == off_grid).all(), (
            f"master {k} lost low mantissa bits: {got.ravel()[0]!r} — a "
            "bf16 round-trip is on the master update path"
        )


def test_master_never_roundtrips_bf16_sharded_compressed():
    """Same pin through the ZeRO-1 sharded update with bf16-compressed
    collectives: the gather leg carries the parameter DELTA, so the f32
    master must survive even though both collective legs cross in bf16."""
    off_grid = np.float32(1.0 + 2.0 ** -20)
    dp = DataParallel(make_mesh({"data": 2}))
    tr = _trainer(
        precision="bf16", parallel=dp, shard_update=True,
        grad_compression="bf16",
    )
    x, y = _data(16)
    batch = {"x": x, "label": y}
    sharded = dp.shard_batch(batch)
    tr.init_state(sharded)
    state = dict(tr.state)
    state["params"] = {
        k: jnp.full_like(v, off_grid) for k, v in state["params"].items()
    }
    state["lr_scale"] = jnp.zeros((), jnp.float32)
    tr.state = dp.shard_state(state, opt_sharding=tr.updater.opt_leaf_sharding)
    st, _, _ = tr._make_step()(tr.state, sharded)
    for k, v in st["params"].items():
        assert (np.asarray(v) == off_grid).all(), k


# -- convergence smokes -------------------------------------------------------


def _run_passes(tr, passes=4, n=96, bs=16):
    x, y = _data(n)
    costs = []

    def handler(e):
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])

    tr.train(_reader(x, y, bs), num_passes=passes, event_handler=handler,
             log_period=10_000)
    return costs


def test_bf16_fc_convergence_tracks_f32():
    c32 = _run_passes(_trainer(precision="f32"))
    cbf = _run_passes(_trainer(precision="bf16"))
    assert cbf[-1] < cbf[0] * 0.9, cbf
    # same seed, same data: the bf16 loss curve tracks f32 to rounding
    np.testing.assert_allclose(cbf, c32, rtol=0.05, atol=5e-3)


@pytest.mark.slow
def test_bf16_lenet_convergence_smoke():
    """bf16 LeNet (conv path: Policy.cast inside ops/conv.py + batch-norm
    statistics pinned f32): cost drops like the f32 run at the same seed."""
    from paddle_tpu.models import lenet

    def run(precision):
        reset_name_scope()
        _img, _lbl, _logits, cost = lenet(num_classes=4)
        tr = SGDTrainer(
            cost, SGD(learning_rate=0.03125, momentum=0.5), seed=0,
            precision=precision,
        )
        rs = np.random.RandomState(1)
        n = 64
        x = rs.rand(n, 28, 28, 1).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) * 4).astype(np.int32).clip(0, 3)
        costs = []

        def handler(e):
            if isinstance(e, EndPass):
                costs.append(e.metrics["avg_cost"])

        def reader():
            for i in range(0, n, 16):
                yield {"pixel": x[i:i + 16], "label": y[i:i + 16]}

        tr.train(reader, num_passes=6, event_handler=handler)
        return costs

    cbf = run("bf16")
    c32 = run("f32")
    assert cbf[-1] < cbf[0] * 0.9, cbf
    assert abs(cbf[-1] - c32[-1]) < 0.1 * max(c32[0] - c32[-1], 1e-3), (
        cbf, c32,
    )


@pytest.mark.slow
def test_bf16_seq2seq_convergence_smoke():
    """The NMT config of the MFU push: tiny seq2seq trains under bf16 with
    loss within tolerance of the f32 run at the same seed (attention-GRU
    decoder scan + fused xent, all through the policy seam)."""
    from paddle_tpu.models import Seq2SeqModel

    vocab, dim, bs, t = 50, 16, 8, 6
    rs = np.random.RandomState(0)
    src = rs.randint(2, vocab, (32, t)).astype(np.int32)
    # learnable rule: target mirrors source (copy task)
    batches = []
    for i in range(0, 32, bs):
        s = src[i:i + bs]
        batches.append({
            "source_ids": s,
            "source_ids.lengths": np.full(bs, t, np.int32),
            "target_ids": s,
            "target_ids.lengths": np.full(bs, t, np.int32),
            "label_ids": s,
            "label_ids.lengths": np.full(bs, t, np.int32),
        })

    def run(precision):
        reset_name_scope()
        model = Seq2SeqModel(vocab, vocab, embed_dim=dim, hidden_dim=dim)
        tr = SGDTrainer(
            model.cost, Adam(learning_rate=0.01), seed=0, precision=precision
        )
        costs = []

        def handler(e):
            if isinstance(e, EndPass):
                costs.append(e.metrics["avg_cost"])

        tr.train(lambda: iter(batches), num_passes=5, event_handler=handler,
                 log_period=10_000)
        return costs

    cbf = run("bf16")
    c32 = run("f32")
    assert cbf[-1] < cbf[0] * 0.8, cbf
    drop32 = c32[0] - c32[-1]
    assert abs(cbf[-1] - c32[-1]) < 0.15 * drop32, (cbf, c32)


# -- cross-precision checkpoints ----------------------------------------------


@pytest.mark.parametrize("save_prec,load_prec", [("bf16", "f32"), ("f32", "bf16")])
def test_cross_precision_checkpoint_masters_bitwise(
    tmp_path, save_prec, load_prec
):
    """Checkpoints store the f32 masters (and canonical f32 opt slots), so a
    bf16-trained checkpoint resumes BITWISE into an f32 trainer and vice
    versa — precision is a property of the step program, not the state."""
    tr1 = _trainer(precision=save_prec)
    x, y = _data(64)
    tr1.train(_reader(x, y), num_passes=2, save_dir=str(tmp_path))
    tr1.checkpoint_wait()

    tr2 = _trainer(precision=load_prec)
    tr2.init_state(_batch())
    tr2.load(str(tmp_path))
    _assert_bitwise(_params(tr1), _params(tr2),
                    f"{save_prec}->{load_prec} masters")
    c1 = tr1.updater.to_canonical(tr1.state["opt"])["slots"]
    c2 = tr2.updater.to_canonical(tr2.state["opt"])["slots"]
    for k, slots in c1.items():
        for a, b in zip(slots, c2[k]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), k
    # and the cross-precision resume actually trains on
    costs = _run_passes(tr2, passes=1)
    assert np.isfinite(costs).all()


def test_cross_precision_resume_continues_pass_count(tmp_path):
    """auto_resume across a precision switch: the f32 restart of a bf16 run
    skips the completed passes and continues from the stored masters."""
    tr1 = _trainer(precision="bf16")
    x, y = _data(64)
    tr1.train(_reader(x, y), num_passes=1, save_dir=str(tmp_path))
    tr1.checkpoint_wait()
    p_saved = _params(tr1)

    tr2 = _trainer(precision="f32")
    seen = []
    tr2.train(
        _reader(x, y), num_passes=2, save_dir=str(tmp_path), auto_resume=True,
        event_handler=lambda e: seen.append(e.pass_id)
        if isinstance(e, EndPass) else None,
    )
    assert seen == [1], seen  # pass 0 came from the bf16 checkpoint
    assert not np.array_equal(
        _params(tr2)["h.w"], p_saved["h.w"]
    ), "resumed pass applied no updates"


# -- composition: the acceptance-criteria flag stack --------------------------


def test_bf16_composes_shard_update_compression_kdispatch_resize(tmp_path):
    """--precision bf16 --shard_update --grad_compression bf16
    --steps_per_dispatch 16 --elastic (ISSUE 9 acceptance): convergence
    smoke through a live 2→4 resize, and the mid-flight checkpoint loads
    bitwise into an f32 trainer of the same stack."""
    dp = DataParallel(make_mesh({"data": 2}))
    tr = _trainer(
        precision="bf16", parallel=dp, shard_update=True,
        grad_compression="bf16",
    )
    x, y = _data(192, seed=3)
    costs = []
    resized = []

    def handler(e):
        if isinstance(e, EndIteration) and (e.pass_id, e.batch_id) == (0, 15):
            preempt.get().request_resize(4, reason="test resize")
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])
            resized.append(e.metrics.get("resize_epochs", 0))

    tr.train(
        _reader(x, y, bs=4), num_passes=3, event_handler=handler,
        steps_per_dispatch=16, save_dir=str(tmp_path), log_period=10_000,
    )
    tr.checkpoint_wait()
    assert sum(resized) == 1, resized  # the 2→4 epoch completed mid-pass
    assert tr.parallel.data_axis_size == 4
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0], costs  # still converging through it all

    # cross-precision load of the composed run's checkpoint: masters bitwise
    dp2 = DataParallel(make_mesh({"data": 4}))
    tr2 = _trainer(
        precision="f32", parallel=dp2, shard_update=True,
        grad_compression="bf16",
    )
    tr2.init_state(dp2.shard_batch({"x": x[:16], "label": y[:16]}))
    tr2.load(str(tmp_path))
    _assert_bitwise(_params(tr), _params(tr2), "bf16 composed -> f32")


# -- remat --------------------------------------------------------------------


@pytest.mark.parametrize("remat", ["dots", "conv_only", "full"])
def test_remat_never_changes_updates(remat):
    """Rematerialization replays the exact same ops in the backward pass:
    the trained parameters match the no-remat run (power-of-two lr keeps
    the comparison FMA-proof)."""
    base = _trainer()
    _run_passes(base, passes=2)
    rem = _trainer(remat=remat)
    _run_passes(rem, passes=2)
    p0, p1 = _params(base), _params(rem)
    for k in p0:
        np.testing.assert_allclose(
            p0[k], p1[k], rtol=1e-6, atol=1e-7, err_msg=f"{remat}: {k}"
        )


def test_train_remat_override_rebuilds_step():
    tr = _trainer()
    x, y = _data(32)
    tr.train(_reader(x, y), num_passes=1)
    fn_before = tr._step_fn
    tr.train(_reader(x, y), num_passes=1, remat="dots")
    assert tr.remat == "dots"
    assert tr._step_fn is not fn_before, "remat change must drop the program"
    tr.train(_reader(x, y), num_passes=1, remat="none")
    assert tr.remat is None


# -- nightly: the heavy precision-grid bench drill ----------------------------


@pytest.mark.nightly
@pytest.mark.timeout(420)
def test_nightly_precision_grid_drill():
    """Real-subprocess run of benchmarks/dispatch_bench.py: the precision ×
    remat grid leg parses, every entry carries a platform tag, and the
    before/after HLO cost buckets are present (ISSUE 9 satellite)."""
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO, "benchmarks", "dispatch_bench.py"),
            "--batches", "48", "--passes", "1", "--batch_size", "16",
            "--dim", "16", "--hidden", "16",
        ],
        capture_output=True, text=True, timeout=390,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    data = json.loads(out.stdout.strip().splitlines()[-1])
    leg = data["precision_remat"]
    assert {(e["precision"], e["remat"]) for e in leg["grid"]} == {
        ("f32", "none"), ("f32", "dots"), ("bf16", "none"), ("bf16", "dots"),
    }
    for e in leg["grid"]:
        assert e["platform"], e
        assert e["steps_per_sec"] > 0, e
    for key in ("before_f32_none", "after_bf16_dots"):
        assert "top_buckets" in leg["hlo_cost"][key] or \
            "error" in leg["hlo_cost"][key], leg["hlo_cost"]
