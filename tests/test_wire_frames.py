"""Binary batched control plane (ISSUE 20): the framed wire under the RPC
surface.

The load-bearing claims:

  * codec — frames round-trip exactly (header methods, trace block, packed
    token runs, the compact stream delta), and the decoder REJECTS
    truncated/garbage/oversized input with named `FrameError` subclasses
    instead of wedging a handler thread (fuzzed);
  * downgrade negotiation — a legacy line-JSON peer against a frame-enabled
    server is served bit-for-bit by the unchanged line path (legacy default
    `json.dumps` encoding and all), and a frame-capable client against a
    legacy server falls back to line JSON (memoized per endpoint) unless
    pinned to `wire="frames"`, which surfaces ConnectionError;
  * pipelining — `call_many` ships N requests in ONE round trip on a framed
    connection, reuses the one socket, and a mid-pipeline conn_reset retries
    the WHOLE batch through the normal failover path (idempotency keys make
    the re-send safe);
  * socket hygiene — close() closes the buffered reader/writer WITH the
    socket (no leaked makefile objects across reconnects);
  * bulk leases + piggybacked acks — `get_tasks` leases task ranges and
    folds the previous batch's done/failed acks into the same round trip,
    cutting round trips per task >= 3x vs the get_task/task_finished pair,
    with exactly-once delivery intact;
  * serving equivalence — tokens from generate AND push streams are
    bitwise-identical across `wire="json"` and `wire="frames"`, and the
    binary stream frames cost fewer bytes than the JSON ones."""

import io
import json
import random
import socket
import struct
import threading

import pytest

from paddle_tpu.core import faults
from paddle_tpu.runtime import available, frames
from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

pytestmark = [pytest.mark.chaos, pytest.mark.timeout(120)]

# the codec + fake-legacy-server tests are pure Python; everything touching
# MasterServer leases real tasks through the native master
needs_native = pytest.mark.skipif(
    not available(), reason="native runtime unavailable"
)

PROMPT = [1, 5, 9, 11]


# -- codec --------------------------------------------------------------------


def _roundtrip(obj, **kw):
    buf = io.BytesIO()
    frames.write_frame(buf, dict(obj), **kw)
    buf.seek(0)
    got = frames.read_frame(buf)
    assert got is not None
    out, rid, flags, blob = got
    return frames.decode_payload(out, rid, flags, blob), rid, flags


def test_control_frame_roundtrip_exact():
    req = {"method": "get_tasks", "n": 4, "done_ids": [7, 9],
           "trainer_id": "t-1"}
    out, rid, _flags = _roundtrip(req, req_id=42)
    assert out == req and rid == 42
    # unknown method names stay in the JSON payload (method_id 0)
    out, _, _ = _roundtrip({"method": "made_up", "x": 1})
    assert out == {"method": "made_up", "x": 1}


def test_trace_context_moves_into_the_header():
    ctx = {"t": "00" * 8, "s": "abc.7"}
    req = {"method": "heartbeat", "_trace": dict(ctx)}
    buf = io.BytesIO()
    frames.write_frame(buf, req)
    raw = buf.getvalue()
    # the trace block is binary header state, not JSON payload bytes
    assert b"_trace" not in raw
    buf.seek(0)
    out, rid, flags, blob = frames.read_frame(buf)
    assert flags & frames.FLAG_TRACE
    assert frames.decode_payload(out, rid, flags, blob)["_trace"] == ctx
    # an id that does not fit the fixed block falls back to JSON, lossless
    fat = {"method": "heartbeat", "_trace": {"t": "00" * 8, "s": "x" * 40}}
    out, _, _ = _roundtrip(dict(fat))
    assert out == fat


def test_token_packing_roundtrip_and_fallbacks():
    resp = {"done": True, "tokens": [3, -1, 2**31 - 1, 0],
            "results": [{"request_id": 1, "tokens": [5, 6]},
                        {"request_id": 2, "err": "unknown"}]}
    packed, blob = frames.pack_tokens(dict(resp))
    assert "_ntok" in packed and blob
    assert frames.unpack_tokens(packed, blob) == resp
    # ints past int32 (and non-int elements) stay JSON instead of raising
    for toks in ([2**31], [1.5], [True]):
        packed, blob = frames.pack_tokens({"tokens": toks})
        assert blob == b"" and packed["tokens"] == toks


def test_compact_stream_delta_roundtrip():
    frame = {"request_id": 9, "from": 4, "tokens": [11, 12, 13],
             "tokens_so_far": 7}
    raw = frames.encode_stream(dict(frame))
    # header + u32 from + 3 int32 tokens: 32 bytes, no JSON at all
    assert len(raw) == frames.HEADER_SIZE + 4 + 4 * 3
    obj, rid, flags, blob = frames.read_frame(io.BytesIO(raw))
    assert flags & frames.FLAG_STREAM and obj == {}
    assert frames.decode_payload(obj, rid, flags, blob) == frame
    # the COMMON ending (length-capped, not cancelled) is compact too:
    # FLAG_EOS stands in for the whole `done` tail, still zero JSON
    capped = dict(frame, done=True, finish_reason="length", cancelled=False)
    raw = frames.encode_stream(dict(capped))
    assert len(raw) == frames.HEADER_SIZE + 4 + 4 * 3
    obj, rid, flags, blob = frames.read_frame(io.BytesIO(raw))
    assert flags & frames.FLAG_EOS and obj == {}
    assert frames.decode_payload(obj, rid, flags, blob) == capped
    # any OTHER ending keeps its JSON (completion metadata) + packed tokens
    for final in (dict(frame, done=True, finish_reason="eos",
                       cancelled=False),
                  dict(frame, done=True, finish_reason="length",
                       cancelled=True)):
        raw = frames.encode_stream(dict(final))
        obj, rid, flags, blob = frames.read_frame(io.BytesIO(raw))
        assert not flags & frames.FLAG_EOS
        assert frames.decode_payload(obj, rid, flags, blob) == final


def test_decoder_rejects_garbage_with_named_errors():
    good = io.BytesIO()
    frames.write_frame(good, {"method": "stats"}, req_id=1)
    raw = good.getvalue()
    with pytest.raises(frames.BadMagic):
        frames.read_frame(io.BytesIO(b"{" + raw[1:]))
    with pytest.raises(frames.BadVersion):
        frames.read_frame(io.BytesIO(raw[:1] + b"\x63" + raw[2:]))
    # corrupt/hostile length field: named error, no giant allocation
    huge = struct.pack("<BBBBIII", frames.MAGIC, frames.VERSION, 0, 0, 1,
                       frames.MAX_JSON + 1, 0)
    with pytest.raises(frames.FrameTooLarge):
        frames.read_frame(io.BytesIO(huge))
    with pytest.raises(frames.TruncatedFrame):
        frames.read_frame(io.BytesIO(raw[:-3]))  # EOF mid-payload
    with pytest.raises(frames.TruncatedFrame):
        frames.read_frame(io.BytesIO(raw[:7]))  # EOF mid-header
    # unparseable JSON payload severs with FrameError, not JSONDecodeError
    bad = bytearray(raw)
    bad[-2] = ord("!")
    with pytest.raises(frames.FrameError):
        frames.read_frame(io.BytesIO(bytes(bad)))
    # clean EOF at a frame boundary is None, not an error
    assert frames.read_frame(io.BytesIO(b"")) is None


def test_fuzzed_frames_never_hang_or_escape_frameerror():
    """Random mutations of a valid frame either parse or raise a named
    FrameError — never an unrelated exception, never a blocking read
    (BytesIO EOFs instead of blocking, so TruncatedFrame is the proof the
    decoder bounded its reads)."""
    base = io.BytesIO()
    frames.write_frame(
        base, {"method": "poll_many", "results": [{"tokens": [1, 2]}]},
        req_id=3, flags=frames.FLAG_BIN_TOKENS, bin_payload=b"\x01\0\0\0",
    )
    raw = bytearray(base.getvalue())
    rng = random.Random(20)
    for _ in range(400):
        mut = bytearray(raw)
        for _ in range(rng.randint(1, 4)):
            mut[rng.randrange(len(mut))] = rng.randrange(256)
        mut = bytes(mut)[: rng.randint(1, len(mut))]
        try:
            got = frames.read_frame(io.BytesIO(mut))
            if got is not None:
                frames.decode_payload(*got)
        except frames.FrameError:
            pass  # named rejection is the contract


# -- negotiation + the legacy line path ---------------------------------------


@needs_native
def test_legacy_line_client_served_bit_for_bit():
    """A peer that never sends the `_hello` probe gets the unchanged line
    protocol: one human-readable JSON line per reply, in the legacy default
    `json.dumps` encoding (spaced separators) — byte-identical to what the
    pre-frames server wrote."""
    server = MasterServer(TaskMaster()).start()
    try:
        with socket.create_connection(server.address, timeout=10.0) as s:
            f = s.makefile("rwb")
            f.write(json.dumps({"method": "stats"}).encode() + b"\n")
            f.flush()
            line = f.readline()
        obj = json.loads(line)
        assert "todo" in obj and "live_trainers" in obj
        # bit-for-bit: the line re-encodes to itself under the LEGACY
        # default separators — a compact-separator (framed-style) encoding
        # of the same dict would fail this equality
        assert line == json.dumps(obj).encode() + b"\n"
        assert line != json.dumps(obj, separators=(",", ":")).encode() + b"\n"
    finally:
        server.stop()


class _LegacyLineServer:
    """A minimal pre-frames peer: line JSON only, unknown-method for
    anything it does not speak — including `_hello`."""

    def __init__(self):
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.address = self._srv.getsockname()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with conn:
                f = conn.makefile("rwb")
                for line in f:
                    req = json.loads(line)
                    if req.get("method") == "ping":
                        resp = {"pong": True}
                    else:
                        resp = {"err": f"unknown method {req.get('method')!r}"}
                    f.write(json.dumps(resp).encode() + b"\n")
                    f.flush()

    def close(self):
        self._srv.close()


def test_downgrade_negotiation_against_legacy_server():
    legacy = _LegacyLineServer()
    try:
        c = MasterClient(legacy.address, wire="auto", retries=2)
        assert c.call("ping")["pong"] is True
        assert not c.wire_framed  # probed, refused, stayed line JSON
        # the refusal is memoized per endpoint: a reconnect must not pay
        # (or log) the probe round trip again
        c.close()
        assert c.call("ping")["pong"] is True
        c.close()
        # pinned to frames, a legacy peer is an ERROR, not a silent downgrade
        pinned = MasterClient(legacy.address, wire="frames", retries=1)
        with pytest.raises(ConnectionError):
            pinned.call("ping")
        pinned.close()
    finally:
        legacy.close()


@needs_native
def test_framed_negotiation_upgrades_and_json_pin_refrains():
    server = MasterServer(TaskMaster()).start()
    try:
        cf = MasterClient(server.address, wire="frames")
        assert "todo" in cf.call("stats") and cf.wire_framed
        cj = MasterClient(server.address, wire="json")
        assert "todo" in cj.call("stats") and not cj.wire_framed
        # both wires see the SAME dicts
        assert set(cf.call("stats")) == set(cj.call("stats"))
        cf.close()
        cj.close()
    finally:
        server.stop()


# -- pipelining + socket hygiene ----------------------------------------------


@needs_native
def test_call_many_pipelines_one_round_trip_one_socket():
    server = MasterServer(TaskMaster()).start()
    try:
        c = MasterClient(server.address, wire="frames")
        c.call("stats")
        sock = c._sock
        before = c.round_trips
        out = c.call_many([("heartbeat", {})] * 8 + [("stats", {})])
        assert len(out) == 9 and "todo" in out[-1]
        assert c.round_trips == before + 1  # 9 requests, ONE round trip
        assert c._sock is sock  # pipelining reused the one socket
        c.close()
    finally:
        server.stop()


@needs_native
def test_mid_pipeline_conn_reset_retries_whole_batch():
    server = MasterServer(TaskMaster()).start()
    try:
        c = MasterClient(server.address, wire="frames", retries=4)
        c.call("stats")  # connect + negotiate before the chaos window
        with faults.inject("conn_reset:step=0", seed=3) as inj:
            out = c.call_many([("heartbeat", {})] * 6)
            assert inj.fired.get("conn_reset", 0) == 1  # chaos actually bit
        assert len(out) == 6 and all("err" not in r for r in out)
        assert c.wire_framed  # the reconnect re-negotiated frames
        c.close()
    finally:
        server.stop()


@needs_native
def test_close_closes_buffered_reader_and_writer():
    server = MasterServer(TaskMaster()).start()
    try:
        c = MasterClient(server.address, wire="frames")
        c.call("stats")
        rfile, wfile, sock = c._rfile, c._wfile, c._sock
        assert rfile is not None and wfile is not None
        c.close()
        # the reader leak this pins: makefile objects must close WITH the
        # socket, not linger until GC on every reconnect
        assert wfile.closed and sock.fileno() == -1
        assert rfile.close() is None and c._rfile is None
        # the client reconnects (and re-negotiates) cleanly after close
        assert "todo" in c.call("stats") and c.wire_framed
        c.close()
    finally:
        server.stop()


# -- bulk leases + piggybacked acks -------------------------------------------


def _drain_tasks(client, lease_batch):
    """Drive a full pass with get_tasks range leases + deferred acks;
    returns the task ids delivered, in order."""
    tid = client.call("register")["trainer_id"]
    got, pending = [], []
    while True:
        resp = client.call("get_tasks", n=lease_batch, done_ids=pending,
                           trainer_id=tid)
        pending = []
        if resp.get("pass_finished"):
            return got
        for t in resp.get("tasks", []):
            got.append(int(t["task_id"]))
            pending.append(int(t["task_id"]))
        assert not resp.get("retry"), "nothing pending in this test"


@needs_native
def test_bulk_lease_cuts_round_trips_3x_exactly_once():
    """24 tasks: the legacy get_task/task_finished pair costs 2 RPCs per
    task; get_tasks with lease_batch=8 folds the acks into the next lease —
    >= 3x fewer round trips, same exactly-once ledger."""
    shards = [f"s{i}" for i in range(24)]
    server = MasterServer(TaskMaster()).start()
    try:
        boot = MasterClient(server.address)
        boot.call("set_dataset", shards=shards, chunks_per_task=1)

        legacy = MasterClient(server.address, wire="json")
        tid = legacy.call("register")["trainer_id"]
        seen = []
        while True:
            resp = legacy.call("get_task", trainer_id=tid)
            if resp.get("pass_finished"):
                break
            seen.append(int(resp["task_id"]))
            legacy.call("task_finished", task_id=resp["task_id"],
                        trainer_id=tid)
        legacy_rt = legacy.round_trips
        assert sorted(seen) == sorted(range(len(shards)))
        legacy.close()

        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        bulk = MasterClient(server.address, wire="frames")
        got = _drain_tasks(bulk, lease_batch=8)
        bulk_rt = bulk.round_trips
        # exactly once: every task delivered, none twice (ids are globally
        # monotonic, so count + uniqueness is the ledger)
        assert len(got) == len(shards) and len(set(got)) == len(got)
        assert legacy_rt >= 3 * bulk_rt, (legacy_rt, bulk_rt)

        st = boot.call("stats")
        assert st["done"] == len(shards) and st["discarded"] == 0
        boot.close()
        bulk.close()
    finally:
        server.stop()


@needs_native
def test_get_tasks_acks_ride_the_pass_finishing_request():
    """The final done-ack must ride the SAME request that discovers the
    pass end (acks are processed before leasing), so a bulk reader never
    needs a trailing ack round trip to complete the ledger."""
    server = MasterServer(TaskMaster()).start()
    try:
        boot = MasterClient(server.address)
        boot.call("set_dataset", shards=["a", "b"], chunks_per_task=1)
        c = MasterClient(server.address, wire="frames")
        tid = c.call("register")["trainer_id"]
        resp = c.call("get_tasks", n=2, trainer_id=tid)
        ids = [t["task_id"] for t in resp["tasks"]]
        assert len(ids) == 2
        final = c.call("get_tasks", n=2, done_ids=ids, trainer_id=tid)
        assert final.get("pass_finished") and final["acked"] == 2
        st = boot.call("stats")
        assert st["done"] == 2 and st["pending"] == 0
        boot.close()
        c.close()
    finally:
        server.stop()


@needs_native
def test_snapshot_fetch_binary_matches_line_path():
    """The framed wire ships the snapshot blob RAW (FLAG_BIN_BLOB); the
    line path base64s the same bytes — identical content either way."""
    import base64
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "master.snap")
        server = MasterServer(TaskMaster(), snapshot_path=path,
                              snapshot_every=1).start()
        try:
            boot = MasterClient(server.address)
            boot.call("set_dataset", shards=["a", "b"],
                      chunks_per_task=1)
            t = boot.call("get_task", trainer_id="t0")
            boot.call("task_finished", task_id=t["task_id"], trainer_id="t0")

            cf = MasterClient(server.address, wire="frames")
            cj = MasterClient(server.address, wire="json")
            fb = cf.call("snapshot_fetch")
            jb = cj.call("snapshot_fetch")
            assert isinstance(fb["_bin"], bytes) and fb["bytes"] > 0
            assert base64.b64decode(jb["bin_b64"]) == fb["_bin"]
            for c in (boot, cf, cj):
                c.close()
        finally:
            server.stop()


# -- serving equivalence across wires -----------------------------------------


@pytest.fixture(scope="module")
def serving_server():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM
    from paddle_tpu.serving.server import ServingServer
    from paddle_tpu.serving.session import ServingSession

    model = ServableLM(
        LMConfig(vocab=96, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    params = model.init_params(jax.random.PRNGKey(0))
    sess = ServingSession(model, params, max_slots=4, page_size=8,
                          prefill_buckets=(8, 16, 32), max_new_limit=16)
    srv = ServingServer(session=sess).start()
    yield srv
    srv.stop()


def test_serving_tokens_bitwise_identical_across_wires(serving_server):
    from paddle_tpu.serving.server import ServingClient

    cj = ServingClient(serving_server.address, wire="json")
    cf = ServingClient(serving_server.address, wire="frames")
    try:
        greedy_j = cj.generate(PROMPT, 8)["tokens"]
        greedy_f = cf.generate(PROMPT, 8)["tokens"]
        assert greedy_j == greedy_f
        # negotiation happened on first contact, per the pinned wire
        assert cf.wire_framed and not cj.wire_framed
        kw = dict(seed=77, temperature=0.8, top_k=8)
        assert (cj.generate(PROMPT, 8, **kw)["tokens"]
                == cf.generate(PROMPT, 8, **kw)["tokens"])
    finally:
        cj.close()
        cf.close()


def test_push_stream_bitwise_identical_and_smaller_binary(serving_server):
    from paddle_tpu.serving.server import ServingClient

    cj = ServingClient(serving_server.address, wire="json")
    cf = ServingClient(serving_server.address, wire="frames")
    try:
        tj = [t for fr in cj.stream(PROMPT, 8, seed=5) for t in fr["tokens"]]
        tf = [t for fr in cf.stream(PROMPT, 8, seed=5) for t in fr["tokens"]]
        assert tj == tf and len(tf) == 8
        # the binary stream connection moved fewer bytes for the same tokens
        assert 0 < cf.stream_bytes_in < cj.stream_bytes_in
        st = serving_server.stream_frames
        assert st > 0 and serving_server.stream_bytes > 0
        assert serving_server.stream_tokens >= 16  # both streams' tokens
    finally:
        cj.close()
        cf.close()
