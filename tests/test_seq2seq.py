"""Sequence layers + seq2seq: copy-task convergence and beam-search decode —
the analog of test_recurrent_machine_generation.cpp (golden generation) done
as a learnable toy task."""

import jax
import numpy as np
import pytest

from paddle_tpu.data import DataFeeder, InputSpec, integer_value_sequence
from paddle_tpu.data import reader as rd
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import Argument, Network, reset_name_scope
from paddle_tpu.nn.seq_layers import Expand, FirstSeq, LastSeq, SeqPool, SeqReshape, SeqSlice
from paddle_tpu.optim import Adam
from paddle_tpu.trainer import SGDTrainer
from paddle_tpu.models import Seq2SeqModel, text_lstm


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def test_seq_layers_shapes(np_rng):
    ids = L.Data("x", shape=(7,), is_seq=True)
    emb = L.Embedding(ids, 6, vocab_size=7)
    pool = SeqPool(emb, "average")
    last = LastSeq(emb)
    first = FirstSeq(emb)
    exp = Expand(last, emb)
    net = Network([pool, last, first, exp])
    batch = {
        "x": np_rng.randint(0, 7, (3, 5)),
        "x.lengths": np.array([2, 5, 3], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    assert outs[pool.name].value.shape == (3, 6)
    assert outs[exp.name].value.shape == (3, 5, 6)
    # expand broadcasts the last state across time
    np.testing.assert_allclose(
        np.asarray(outs[exp.name].value[:, 0]), np.asarray(outs[last.name].value)
    )


def test_seq_slice_last(np_rng):
    x = np_rng.randn(2, 6, 3).astype(np.float32)
    lengths = np.array([4, 6], np.int32)
    ids = L.Data("x", shape=(3,), is_seq=True)
    sl = SeqSlice(ids, 2, from_start=False)
    net = Network(sl)
    params, states = net.init(jax.random.PRNGKey(0), {"x": x, "x.lengths": lengths})
    outs, _ = net.apply(params, states, {"x": x, "x.lengths": lengths})
    got = np.asarray(outs[sl.name].value)
    np.testing.assert_allclose(got[0], x[0, 2:4])
    np.testing.assert_allclose(got[1], x[1, 4:6])


def test_text_lstm_trains():
    vocab, classes = 50, 2
    rs = np.random.RandomState(0)
    samples = []
    for i in range(96):
        y = i % 2
        # class determined by presence of token 7 vs 13
        length = rs.randint(3, 10)
        seq = rs.randint(20, vocab, size=length).tolist()
        seq[rs.randint(length)] = 7 if y else 13
        samples.append({"word_ids": seq, "label": y})

    def reader():
        yield from samples

    ids, label, logits, cost = text_lstm(
        vocab_size=vocab, embed_dim=16, hidden_dim=24, num_layers=1, num_classes=classes
    )
    trainer = SGDTrainer(cost, Adam(learning_rate=0.01))
    feeder = DataFeeder(
        {
            "word_ids": InputSpec("index_seq", vocab, seq_bucket=[10]),
            "label": InputSpec("index", classes, np.int32),
        }
    )
    trainer.train(rd.batch(reader, 32, drop_last=True), num_passes=10, feeder=feeder)
    res = trainer.test(rd.batch(reader, 32, drop_last=True), feeder)
    assert res["cost"] < 0.3, res


def test_seq2seq_copy_task_and_beam_search():
    # learn to copy a short token sequence; beam search must reproduce it
    vocab = 12  # 0=BOS 1=EOS 2..11 payload
    rs = np.random.RandomState(1)
    samples = []
    for _ in range(160):
        n = rs.randint(2, 5)
        toks = rs.randint(2, vocab, size=n).tolist()
        samples.append(
            {
                "source_ids": toks,
                "target_ids": [0] + toks,  # BOS + shifted
                "label_ids": toks + [1],  # tokens + EOS
            }
        )

    def reader():
        yield from samples

    model = Seq2SeqModel(vocab, vocab, embed_dim=24, hidden_dim=32)
    trainer = SGDTrainer(model.cost, Adam(learning_rate=0.01), seed=0)
    feeder = DataFeeder(
        {
            "source_ids": InputSpec("index_seq", vocab, seq_bucket=[8]),
            "target_ids": InputSpec("index_seq", vocab, seq_bucket=[8]),
            "label_ids": InputSpec("index_seq", vocab, seq_bucket=[8]),
        }
    )
    trainer.train(rd.batch(reader, 32, drop_last=True), num_passes=30, feeder=feeder)
    res = trainer.test(rd.batch(reader, 32, drop_last=True), feeder)
    assert res["cost"] < 0.35, res

    gen = model.build_generator(beam_size=3, max_len=8)
    src = np.zeros((4, 8), np.int32)
    want = []
    for i, s in enumerate(samples[:4]):
        toks = s["source_ids"]
        src[i, : len(toks)] = toks
        want.append(toks + [1])
    lengths = np.array([len(s["source_ids"]) for s in samples[:4]], np.int32)
    seqs, scores = gen(
        trainer.state["params"], trainer.state["states"], src, lengths
    )
    seqs = np.asarray(seqs)
    ok = 0
    for i in range(4):
        top = seqs[i, 0].tolist()
        if 1 in top:
            top = top[: top.index(1) + 1]
        if top == want[i]:
            ok += 1
    assert ok >= 3, f"beam search reproduced {ok}/4: {seqs[:, 0]} vs {want}"
    # beams are sorted best-first
    assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-6)
