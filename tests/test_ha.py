"""Control-plane HA (ISSUE 18): lease-elected standbys + reconciling takeover.

The load-bearing claims:

  * election — `runtime/election.py` is ONE watch/strike/confirm loop for
    every control plane: a dead primary is confirmed (weighted strikes +
    patient final probe) before takeover, a live one is never usurped, and
    stop()/max_wait_s end a watch cleanly with no takeover;
  * takeover sweep — a freshly-elected router rebuilds its in-flight/dedup
    books from the data plane: each re-registering replica's `outstanding`
    reply re-creates handles under their original (tenant, client_req_id)
    keys with the original pinned seeds, so polls resolve by key, results
    deliver exactly once, and a second failure (the adopted request's
    replica also dying) re-executes token-identically or fails NAMED
    (`replica_lost`) — never silently;
  * agent fencing — a replica agent honors control hints only from the
    router incarnation it registered with, unless that incarnation is
    provably gone (endpoint re-bound or unreachable past the rotation
    threshold): a healed old primary's stale replies are counted and
    dropped, closing the double-takeover window;
  * client self-healing — ServingClient carries an endpoint LIST end to
    end; generate() re-submits under the same key + client-pinned seed when
    the (new) router forgot its request id, and stream() reattaches at the
    delivered-token cursor so the consumer sees every token exactly once
    across a router death;
  * autoscaler — the standby rides the same election primitive with ZERO
    extra state (the controller is already stateless-reconciling); its
    liveness port drops exactly when the reconcile loop dies, including
    the controller_kill chaos site.

Timing-sensitive tests use short leases + the deterministic wedge (parking
the engine on the session's generation lock) rather than sleeps-and-hope;
every socket test carries the SIGALRM timeout marker."""

import socket
import threading
import time

import pytest

from paddle_tpu.core import faults
from paddle_tpu.core.stats import FT_EVENTS

pytestmark = [pytest.mark.serving, pytest.mark.chaos, pytest.mark.ha]

VOCAB = 96
PROMPT = [1, 5, 9, 11]


def _wait(cond, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


def _free_port() -> int:
    """Reserve a port for a standby that will bind it only at takeover."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    return ServingSession(model, params, **kw)


@pytest.fixture(scope="module")
def reference(model_and_params):
    """Oracle tokens from a direct single session: greedy and sampled."""
    s = make_session(model_and_params)
    greedy = s.submit(PROMPT, 8)
    sampled = s.submit(PROMPT, 8, seed=77, temperature=0.8, top_k=8)
    s.run_until_idle()
    return {"greedy": greedy.tokens, "sampled": sampled.tokens}


def warm_session(sess):
    """Compile before holding a lease (see test_router.warm_session)."""
    sess.submit(PROMPT, 4)
    sess.run_until_idle()
    sess.scheduler.reset_load_estimate()
    return sess


# -- election primitive -------------------------------------------------------


@pytest.mark.timeout(60)
def test_watcher_takes_over_only_when_primary_dies():
    from paddle_tpu.runtime.election import StandbyWatcher

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    ep = lst.getsockname()
    w = StandbyWatcher(ep, plane="router", poll_s=0.05)
    before = FT_EVENTS.get("router_takeover")
    box = {}

    def run():
        box["token"] = w.wait_for_takeover()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    time.sleep(0.5)
    assert t.is_alive(), "a live primary must never be usurped"
    assert w.misses == 0.0 and w.probes >= 3
    lst.close()
    t.join(timeout=20.0)
    assert not t.is_alive()
    token = box["token"]
    assert isinstance(token, str) and len(token) == 8
    assert FT_EVENTS.get("router_takeover") == before + 1


@pytest.mark.timeout(30)
def test_watcher_stop_and_max_wait_end_without_takeover():
    from paddle_tpu.runtime.election import StandbyWatcher, watch_primary

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    ep = lst.getsockname()
    try:
        # max_wait_s expiry: healthy primary, bounded watch -> None
        assert watch_primary(ep, plane="router", poll_s=0.05,
                             max_wait_s=0.3) is None
        # stop(): even with a DEAD primary a stopped watcher yields nothing
        w = StandbyWatcher(("127.0.0.1", _free_port()), plane="router",
                           poll_s=0.05)
        w.stop()
        assert w.wait_for_takeover() is None
    finally:
        lst.close()


def test_instance_tokens_are_per_incarnation():
    from paddle_tpu.runtime.election import mint_instance_token

    a, b = mint_instance_token(), mint_instance_token()
    assert a != b and len(a) == len(b) == 8


# -- replica agent: rotation + instance-token fencing (no sockets) ------------


def _bare_agent(n_eps=2):
    from paddle_tpu.serving.fleet import ReplicaAgent

    agent = ReplicaAgent(
        [("127.0.0.1", 1), ("127.0.0.1", 2)][:n_eps], session=None,
        advertise=("127.0.0.1", 9),
    )
    calls = []
    agent._register = lambda: calls.append("register") or True
    agent.replica_id = "r-0"
    agent.router_instance = "aaaa0000"
    agent._reg_ep = 0
    return agent, calls


def test_agent_honors_hint_from_own_incarnation():
    agent, calls = _bare_agent()
    out = agent._handle_reply(
        {"ok": False, "reregister": True, "instance": "aaaa0000"}
    )
    assert out is None and calls == ["register"]
    assert agent.replica_id is None and agent.stale_replies == 0


def test_agent_fences_stale_foreign_reply_and_goes_home():
    # a DIFFERENT incarnation answered from a non-home endpoint while our
    # own router was last known reachable: stale old primary — ignore the
    # hint, count it, rotate back home
    agent, calls = _bare_agent()
    agent._cur = 1
    agent._conn_failures = 0
    before = FT_EVENTS.get("replica_stale_router_reply")
    out = agent._handle_reply(
        {"ok": False, "reregister": True, "instance": "bbbb1111"}
    )
    assert out is None and calls == []
    assert agent.replica_id == "r-0", "stale hint must not drop the lease"
    assert agent.stale_replies == 1
    assert FT_EVENTS.get("replica_stale_router_reply") == before + 1
    assert agent._cur == agent._reg_ep, "fenced agent rotates back home"


def test_agent_honors_foreign_reply_when_home_rebound():
    # home endpoint answered with a NEW incarnation: the old one is provably
    # gone (its port re-bound) — re-register with the answerer
    agent, calls = _bare_agent()
    agent._cur = agent._reg_ep = 0
    agent._handle_reply({"ok": False, "reregister": True,
                         "instance": "cccc2222"})
    assert calls == ["register"] and agent.replica_id is None


def test_agent_honors_foreign_reply_when_home_unreachable():
    agent, calls = _bare_agent()
    agent._cur = 1
    agent._conn_failures = agent.ROTATE_AFTER
    agent._handle_reply({"ok": False, "reregister": True,
                         "instance": "dddd3333"})
    assert calls == ["register"] and agent.replica_id is None


def test_agent_rotates_after_threshold_only_when_registered():
    agent, _ = _bare_agent()
    # registered: one failure stays pinned to the home endpoint...
    agent._note_conn_failure()
    assert agent.rotations == 0 and agent._cur == 0
    # ...the ROTATE_AFTER'th concludes the router is gone and rotates
    agent._note_conn_failure()
    assert agent.rotations == 1 and agent._cur == 1
    # unregistered: any live router will do — first failure rotates
    fresh, _ = _bare_agent()
    fresh.replica_id = None
    fresh._note_conn_failure()
    assert fresh.rotations == 1


def test_agent_single_endpoint_rotation_is_a_noop():
    agent, _ = _bare_agent(n_eps=1)
    agent._note_conn_failure()
    agent._note_conn_failure()
    assert agent.rotations == 0 and agent._cur == 0


# -- the sweep source: the replica's `outstanding` reply ----------------------


@pytest.mark.timeout(120)
def test_outstanding_reports_resubmission_identity(model_and_params):
    from paddle_tpu.runtime.master import MasterClient
    from paddle_tpu.serving.server import ServingClient, ServingServer

    sess = warm_session(make_session(model_and_params))
    srv = ServingServer(session=sess).start()
    client = ServingClient(srv.address)
    probe = MasterClient(srv.address)
    try:
        with sess._gen_lock:  # wedge: the request stays in flight
            rid = client.submit(PROMPT, 6, client_req_id="k-ha-1", seed=77,
                                temperature=0.8, top_k=8)
            items = probe.call("outstanding")["requests"]
            mine = [i for i in items if i["client_req_id"] == "k-ha-1"]
            assert len(mine) == 1
            (item,) = mine
            assert item["request_id"] == rid
            assert item["prompt"] == PROMPT, "sweep needs the prompt back"
            assert item["seed"] == 77 and item["max_new_tokens"] == 6
            assert item["temperature"] == 0.8 and item["top_k"] == 8
            assert not item["done"]
        assert _wait(lambda: client.poll(rid).get("done"), 30.0)
        # finished-but-unpolled results are still reported (server-held):
        # the new router must learn about them to deliver, not re-run
        done = [i for i in probe.call("outstanding")["requests"]
                if i["client_req_id"] == "k-ha-1"]
        assert done and done[0]["done"] and done[0]["tokens_so_far"] == 6
    finally:
        probe.close()
        client.close()
        srv.stop()


# -- takeover sweep: a fresh router adopts replica state ----------------------


@pytest.mark.timeout(120)
def test_fresh_router_sweep_adopts_and_delivers(model_and_params, reference):
    """The reconciling-takeover core, isolated: a router that has NEVER
    seen a submit registers a replica already holding keyed requests, and
    the sweep rebuilds handles (key map, pinned seed, RUNNING status) that
    then finish with oracle tokens — pollable BY KEY by a client whose
    request ids died with the old incarnation."""
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingClient, ServingServer

    sess = warm_session(make_session(model_and_params))
    srv = ServingServer(session=sess).start()
    direct = ServingClient(srv.address)
    router = RouterServer(lease_s=3.0).start()
    try:
        with sess._gen_lock:
            direct.submit(PROMPT, 8, client_req_id="k-greedy")
            direct.submit(PROMPT, 8, client_req_id="k-sampled", seed=77,
                          temperature=0.8, top_k=8)
            router.router.register_replica(list(srv.address))
            assert router.router.adopted == 2
            hg = router.router.get_by_key("default", "k-greedy")
            hs = router.router.get_by_key("default", "k-sampled")
            assert hg is not None and hs is not None
            assert hs.seed == 77 and hs.temperature == 0.8 and hs.top_k == 8
            assert not hg.done
        assert hg.result(timeout=30.0) == reference["greedy"]
        assert hs.result(timeout=30.0) == reference["sampled"]
        # a client holding a dead incarnation's request id reattaches by key
        via = ServingClient(router.address)
        resp = via.poll(999_999, client_req_id="k-sampled")
        assert resp["done"] and resp["tokens"] == reference["sampled"]
        via.close()
        assert router.router.stats()["adopted_requests"] == 2
    finally:
        direct.close()
        srv.stop()
        router.stop()


@pytest.mark.timeout(120)
def test_adopted_request_fails_named_when_its_replica_dies(model_and_params):
    """Second-failure edge: the ONLY replica holding an adopted request dies
    before ever finishing and nobody else can take it — the request must
    fail with the NAMED reason `replica_lost` after park_give_up_s, never
    hang or vanish."""
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.scheduler import FinishReason
    from paddle_tpu.serving.server import ServingClient, ServingServer

    sess = warm_session(make_session(model_and_params))
    srv = ServingServer(session=sess).start()
    direct = ServingClient(srv.address)
    router = RouterServer(
        lease_s=1.0, park_give_up_s=1.0, poll_interval_s=0.02,
        replica_client_kw={"timeout": 2.0, "retries": 1},
    ).start()
    gate = sess._gen_lock
    gate.acquire()
    try:
        direct.submit(PROMPT, 8, client_req_id="k-doomed")
        router.router.register_replica(list(srv.address))
        h = router.router.get_by_key("default", "k-doomed")
        assert h is not None and not h.done
        srv.kill()  # the only holder dies, still wedged: nothing to adopt
        assert _wait(lambda: h.done, 30.0), "parked request must expire"
        assert h.finish_reason == FinishReason.REPLICA_LOST
        with pytest.raises(RuntimeError, match="replica_lost"):
            h.result(timeout=1.0)
    finally:
        gate.release()
        direct.close()
        router.stop()


# -- end-to-end: router killed mid-flight, standby takes over -----------------


def _ha_fleet(model_and_params, n, lease_s=2.0, standby_kw=None, **router_kw):
    """Primary RouterServer + armed RouterStandby (watching it from a
    reserved port) + n replicas carrying BOTH endpoints."""
    from paddle_tpu.serving.router import RouterServer, RouterStandby
    from paddle_tpu.serving.server import ServingServer

    router_kw.setdefault("poll_interval_s", 0.02)
    primary = RouterServer(lease_s=lease_s, **router_kw).start()
    sb_port = _free_port()
    box = {}
    standby = RouterStandby(
        primary.address, port=sb_port, poll_s=0.1, lease_s=lease_s,
        **(standby_kw or {}), **router_kw,
    )

    def run():
        box["srv"] = standby.run()

    threading.Thread(target=run, daemon=True).start()
    endpoints = [list(primary.address), ["127.0.0.1", sb_port]]
    servers = []
    for _ in range(n):
        sess = warm_session(make_session(model_and_params))
        srv = ServingServer(
            session=sess, router_endpoints=endpoints, stall_fence_s=30.0,
        ).start()
        servers.append((srv, sess))
    assert _wait(lambda: len(primary.fleet.live()) == n), "replicas must join"
    return primary, standby, box, endpoints, servers


@pytest.mark.timeout(240)
def test_router_takeover_reconciles_inflight(model_and_params, reference):
    """Kill the primary router with wedged in-flight requests (greedy AND
    seeded-sampled): the standby takes over, replicas rotate + re-register,
    the sweep adopts, clients' key-based reattach delivers oracle tokens
    exactly once."""
    from paddle_tpu.serving.server import ServingClient

    primary, standby, box, endpoints, servers = _ha_fleet(model_and_params, 2)
    gates = [sess._gen_lock for _, sess in servers]
    for g in gates:
        g.acquire()
    released = False
    results = {}

    def gen(name, **kw):
        # one client per thread: a MasterClient connection is a strict
        # request/reply stream, so concurrent callers would desync replies
        c = ServingClient(endpoints, timeout=3.0)
        try:
            results[name] = c.generate(PROMPT, 8, timeout_s=120.0, **kw)
        finally:
            c.close()

    threads = [
        threading.Thread(target=gen, args=("greedy",), daemon=True),
        threading.Thread(
            target=gen, args=("sampled",),
            kwargs=dict(seed=77, temperature=0.8, top_k=8), daemon=True,
        ),
    ]
    try:
        before = FT_EVENTS.get("router_takeover")
        for t in threads:
            t.start()
        # both requests registered on replicas (wedged: none can finish)
        assert _wait(lambda: sum(
            len(srv.dispatch("outstanding", {}, None)["requests"])
            for srv, _ in servers) >= 2, 30.0)
        primary.kill()
        assert _wait(lambda: box.get("srv") is not None, 30.0), \
            "standby must take over"
        new = box["srv"]
        assert FT_EVENTS.get("router_takeover") == before + 1
        # replicas rotate to the standby and the sweep adopts their books
        assert _wait(lambda: len(new.fleet.live()) == 2, 60.0)
        assert _wait(lambda: new.router.adopted >= 1, 30.0)
        for g in gates:
            g.release()
        released = True
        for t in threads:
            t.join(timeout=120.0)
            assert not t.is_alive()
        assert results["greedy"]["tokens"] == reference["greedy"]
        assert results["sampled"]["tokens"] == reference["sampled"]
        assert new.router.completed >= 1
    finally:
        if not released:
            for g in gates:
                g.release()
        for srv, _ in servers:
            srv.stop()
        if box.get("srv") is not None:
            box["srv"].stop()


@pytest.mark.timeout(240)
def test_exactly_once_across_router_and_replica_death(model_and_params,
                                                      reference):
    """Both control failures in one window: the router dies, the standby
    adopts, and THEN a replica holding adopted work dies too — its requests
    fail over to the survivor under the same key + pinned seed, so tokens
    stay oracle-identical and each request is delivered exactly once."""
    from paddle_tpu.serving.server import ServingClient

    primary, standby, box, endpoints, servers = _ha_fleet(model_and_params, 2)
    gates = {id(sess): sess._gen_lock for _, sess in servers}
    for g in gates.values():
        g.acquire()
    released = set()
    results = {}

    def gen(name, **kw):
        # per-thread client: MasterClient connections are not thread-safe
        c = ServingClient(endpoints, timeout=3.0)
        try:
            results[name] = c.generate(PROMPT, 8, timeout_s=150.0, **kw)
        finally:
            c.close()

    threads = [
        threading.Thread(target=gen, args=("greedy",), daemon=True),
        threading.Thread(
            target=gen, args=("sampled",),
            kwargs=dict(seed=77, temperature=0.8, top_k=8), daemon=True,
        ),
    ]
    try:
        for t in threads:
            t.start()
        assert _wait(lambda: sum(
            len(srv.dispatch("outstanding", {}, None)["requests"])
            for srv, _ in servers) >= 2, 30.0)
        primary.kill()
        assert _wait(lambda: box.get("srv") is not None, 30.0)
        new = box["srv"]
        assert _wait(lambda: len(new.fleet.live()) == 2, 60.0)
        assert _wait(lambda: new.router.adopted >= 1, 30.0)
        # kill whichever replica holds adopted work, still wedged — the new
        # incarnation must fail it over to the survivor
        with new.router._lock:
            held = {
                rep_id
                for h in new.router._handles.values() if not h.done
                for rep_id in h.assignments
            }
        victim_idx = next(
            i for i, (srv, _) in enumerate(servers)
            for r in new.fleet.replicas()
            if r.replica_id in held
            and tuple(r.endpoint) == tuple(srv.address)
        )
        victim_srv, victim_sess = servers[victim_idx]
        victim_srv.kill()
        # release only the SURVIVOR's wedge; the victim dies wedged
        for i, (_, sess) in enumerate(servers):
            if i != victim_idx:
                gates[id(sess)].release()
                released.add(id(sess))
        for t in threads:
            t.join(timeout=150.0)
            assert not t.is_alive()
        assert results["greedy"]["tokens"] == reference["greedy"]
        assert results["sampled"]["tokens"] == reference["sampled"]
    finally:
        for _, sess in servers:
            if id(sess) not in released:
                gates[id(sess)].release()
        for srv, _ in servers:
            srv.stop()
        if box.get("srv") is not None:
            box["srv"].stop()


@pytest.mark.timeout(240)
def test_stream_reattaches_by_cursor_across_takeover(model_and_params,
                                                     reference):
    """A live push-stream survives its router's death: the client reattaches
    through the standby at its delivered-token cursor (falling back to a
    same-key re-submit if the new incarnation hasn't swept yet), and the
    consumer sees the oracle token sequence exactly once."""
    from paddle_tpu.serving.server import ServingClient

    primary, standby, box, endpoints, servers = _ha_fleet(model_and_params, 1)
    client = ServingClient(endpoints, timeout=3.0)
    srv0, sess0 = servers[0]
    gate = sess0._gen_lock
    gate.acquire()  # wedge: the stream must still be mid-flight at the kill
    released = False
    got = []
    err = []
    done_evt = threading.Event()

    def consume():
        try:
            for frame in client.stream(PROMPT, 8, reattach_retries=30):
                got.extend(frame["tokens"])
                if frame.get("done"):
                    break
        except Exception as e:  # surfaced by the main thread's assert
            err.append(e)
        finally:
            done_evt.set()

    t = threading.Thread(target=consume, daemon=True)
    try:
        t.start()
        assert _wait(lambda: len(
            srv0.dispatch("outstanding", {}, None)["requests"]) >= 1, 30.0)
        primary.kill()
        assert _wait(lambda: box.get("srv") is not None, 30.0)
        new = box["srv"]
        assert _wait(lambda: len(new.fleet.live()) == 1, 60.0)
        gate.release()
        released = True
        assert done_evt.wait(120.0), "stream consumer must finish"
        assert not err, f"stream consumer raised: {err!r}"
        assert got == reference["greedy"], \
            "reattached stream must deliver every token exactly once"
        assert client.stream_reattaches >= 1
    finally:
        if not released:
            gate.release()
        client.close()
        for srv, _ in servers:
            srv.stop()
        if box.get("srv") is not None:
            box["srv"].stop()


# -- client endpoint lists ----------------------------------------------------


@pytest.mark.timeout(120)
def test_client_endpoint_list_fails_over(model_and_params, reference):
    from paddle_tpu.serving.server import ServingClient, ServingServer

    sess = warm_session(make_session(model_and_params))
    srv = ServingServer(session=sess).start()
    client = ServingClient(
        [("127.0.0.1", _free_port()), tuple(srv.address)], timeout=2.0,
    )
    try:
        out = client.generate(PROMPT, 8)
        assert out["tokens"] == reference["greedy"]
    finally:
        client.close()
        srv.stop()


# -- autoscaler standby -------------------------------------------------------


class _StubStats:
    """Minimal .call/.close endpoint stand-in for controller observation."""

    def call(self, method, **kw):
        return {"replicas": [], "estimated_queue_wait_s": 0.0, "shed": 0}

    def close(self):
        pass


@pytest.mark.timeout(60)
def test_autoscaler_standby_takes_over_on_controller_kill():
    from paddle_tpu.runtime.autoscaler import (
        AutoscalerController, AutoscalerStandby,
    )

    before = FT_EVENTS.get("autoscaler_takeover")
    with faults.inject("controller_kill:step=3"):
        ctl = AutoscalerController(
            router_client=_StubStats(), tick_s=0.05, liveness_port=0,
        ).start()
        assert ctl.liveness_address is not None
        box = {}
        standby = AutoscalerStandby(
            ctl.liveness_address,
            lambda: AutoscalerController(router_client=_StubStats(),
                                         tick_s=0.05),
            poll_s=0.05,
        )

        def run():
            box["ctl"] = standby.run()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(0.2)
        assert t.is_alive(), "standby must not usurp a live controller"
        # the seeded chaos site kills the reconcile loop; the liveness port
        # drops with it and the standby takes over with zero extra state
        assert _wait(lambda: ctl.dead, 15.0)
        t.join(timeout=20.0)
        assert not t.is_alive() and box.get("ctl") is not None
    new = box["ctl"]
    try:
        assert new.alive and len(new.instance) == 8
        assert new.instance != ctl.instance, "per-incarnation identity"
        assert FT_EVENTS.get("autoscaler_takeover") == before + 1
        assert _wait(lambda: new.ticks >= 2, 15.0), "new controller ticks"
    finally:
        new.stop()
        ctl.stop()


@pytest.mark.timeout(60)
def test_autoscaler_stop_drops_liveness_port():
    from paddle_tpu.runtime.autoscaler import AutoscalerController

    ctl = AutoscalerController(
        router_client=_StubStats(), tick_s=0.05, liveness_port=0,
    ).start()
    addr = ctl.liveness_address
    socket.create_connection(addr, timeout=2.0).close()  # probe-able while up
    ctl.stop()
    with pytest.raises(OSError):
        socket.create_connection(addr, timeout=2.0).close()


# -- CLI standby roles (subprocess; nightly tier) -----------------------------


@pytest.mark.nightly
@pytest.mark.timeout(180)
def test_cli_standby_roles_exit_3_without_takeover():
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(4)
    primary = "127.0.0.1:%d" % lst.getsockname()[1]
    try:
        for mod, extra in (
            ("paddle_tpu.serving.router", []),
            ("paddle_tpu.runtime.autoscaler", ["--router", primary]),
        ):
            proc = subprocess.run(
                [sys.executable, "-m", mod, "standby", "--primary", primary,
                 "--max_wait_s", "1.0", "--poll_s", "0.2", *extra],
                capture_output=True, text=True, timeout=120, env=env,
            )
            assert proc.returncode == 3, proc.stderr
            out = json.loads(proc.stdout.strip().splitlines()[-1])
            assert out["takeover"] is False
    finally:
        lst.close()
