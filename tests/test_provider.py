"""Tests for the PyDataProvider2-equivalent provider pipeline."""

import numpy as np
import pytest

from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
from paddle_tpu.data.provider import (
    CacheType,
    DataProviderConverter,
    DoubleBuffer,
    MultiDataProvider,
    provider,
)
from paddle_tpu.data.reader import batch


def test_provider_decorator_and_types():
    @provider(
        input_types={"x": dense_vector(4), "y": integer_value(3)},
        should_shuffle=False,
        check=True,
    )
    def process(settings, filename):
        assert settings.input_types is not None
        for i in range(5):
            yield {"x": np.full(4, i, np.float32), "y": i % 3}

    samples = list(process(file_list=["f0", "f1"]))
    assert len(samples) == 10  # 5 per "file"
    feeder = DataFeeder(process.input_types)
    b = feeder(samples[:4])
    assert b["x"].shape == (4, 4) and b["y"].dtype == np.int32


def test_provider_check_rejects_bad_sample():
    @provider(input_types=[dense_vector(4)], should_shuffle=False, check=True)
    def bad(settings, filename):
        yield (np.zeros(3, np.float32),)  # wrong dim

    with pytest.raises(ValueError):
        list(bad(file_list=["f"]))


def test_provider_init_hook_and_cache():
    calls = []

    def init_hook(settings, obj, file_list, **kw):
        calls.append(file_list)
        settings.scale = 2.0

    @provider(input_types=[dense_vector(1)], init_hook=init_hook,
              should_shuffle=False, cache=CacheType.CACHE_PASS_IN_MEM)
    def process(settings, filename):
        for i in range(3):
            yield (np.array([i * settings.scale], np.float32),)

    first = list(process(file_list=["a"]))
    second = list(process(file_list=["a"]))  # served from pass cache
    assert [s[0][0] for s in first] == [0.0, 2.0, 4.0]
    assert [s[0][0] for s in second] == [0.0, 2.0, 4.0]
    assert len(calls) >= 1


def test_multi_data_provider_ratio():
    a = lambda: iter([("a",)] * 300)
    b = lambda: iter([("b",)] * 100)
    mixed = list(MultiDataProvider([(a, 3.0), (b, 1.0)])())
    assert len(mixed) == 400
    head = mixed[:100]
    n_a = sum(1 for s in head if s[0] == "a")
    assert 55 <= n_a <= 95  # ~75 expected at ratio 3:1


def test_double_buffer_matches_sync():
    def reader():
        for i in range(20):
            yield [(np.full(2, i, np.float32), i % 2)] * 3

    feeder = DataFeeder({"x": dense_vector(2), "y": integer_value(2)})
    sync = [feeder(r) for r in reader()]
    buffered = list(DoubleBuffer(reader, feeder, capacity=2))
    assert len(buffered) == len(sync)
    for s, bch in zip(sync, buffered):
        np.testing.assert_array_equal(s["x"], bch["x"])


def test_double_buffer_propagates_errors():
    def reader():
        yield [(np.zeros(2, np.float32), 0)]
        raise RuntimeError("boom")

    feeder = DataFeeder({"x": dense_vector(2), "y": integer_value(2)})
    with pytest.raises(RuntimeError, match="boom"):
        list(DoubleBuffer(reader, feeder))


def test_converter_list_types():
    conv = DataProviderConverter([dense_vector(2), integer_value(5)], names=["img", "lbl"])
    out = conv([(np.ones(2, np.float32), 4)])
    assert out["img"].shape == (1, 2) and out["lbl"][0] == 4
