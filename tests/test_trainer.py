"""End-to-end trainer tests — analog of trainer/tests/test_TrainerOnePass.cpp
(train a real config for a pass and assert cost sanity) plus checkpoint
roundtrip (ParamUtil save/load)."""

import os

import numpy as np
import pytest

from paddle_tpu.data import DataFeeder, dense_vector, integer_value, reader as rd
from paddle_tpu.nn import layers as L
from paddle_tpu.nn import costs as C
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import Adam, SGD
from paddle_tpu.trainer import EndIteration, EndPass, SGDTrainer


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def _toy_classification_reader(n=256, dim=8, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, dim) * 3
    xs = []
    ys = []
    for i in range(n):
        y = i % classes
        xs.append((centers[y] + rs.randn(dim) * 0.3).astype(np.float32))
        ys.append(y)

    def reader():
        for x, y in zip(xs, ys):
            yield {"x": x, "label": y}

    return reader


def _build(dim=8, classes=4):
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 32, act="relu")
    logits = L.Fc(h, classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return x, lbl, logits, cost


def test_train_reduces_cost_and_events():
    _, _, logits, cost = _build()
    trainer = SGDTrainer(cost, Adam(learning_rate=0.01), extra_outputs=[logits])
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    batches = rd.batch(_toy_classification_reader(), 32, drop_last=True)
    events = {"iters": [], "passes": []}

    def handler(e):
        if isinstance(e, EndIteration):
            events["iters"].append(e.cost)
            assert logits.name in e.metrics
        elif isinstance(e, EndPass):
            events["passes"].append(e.metrics["avg_cost"])

    trainer.train(batches, num_passes=4, event_handler=handler, feeder=feeder)
    assert len(events["passes"]) == 4
    assert events["passes"][-1] < events["passes"][0] * 0.3
    # test() runs and is finite
    res = trainer.test(batches, feeder)
    assert res["cost"] < events["passes"][0]


def test_checkpoint_roundtrip(tmp_path):
    _, _, logits, cost = _build()
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    batches = rd.batch(_toy_classification_reader(), 32, drop_last=True)
    t1 = SGDTrainer(cost, SGD(learning_rate=0.1), seed=7)
    t1.train(batches, num_passes=1, feeder=feeder, save_dir=str(tmp_path))
    ref = t1.test(batches, feeder)["cost"]

    reset_name_scope()
    _, _, logits2, cost2 = _build()
    t2 = SGDTrainer(cost2, SGD(learning_rate=0.1), seed=999)
    first = next(iter(batches()))
    t2.init_state(feeder(first))
    t2.load(str(tmp_path))
    got = t2.test(batches, feeder)["cost"]
    assert got == pytest.approx(ref, rel=1e-5)


def test_lr_schedule_drives_updates():
    # caffe_poly hitting zero lr → params stop moving
    from paddle_tpu.optim import schedules

    _, _, _, cost = _build()
    sched = schedules.build(0.5, "caffe_poly", decay_a=64.0, decay_b=1.0)
    trainer = SGDTrainer(cost, SGD(learning_rate=0.5), schedule=sched)
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    batches = rd.batch(_toy_classification_reader(64), 32, drop_last=True)
    trainer.train(batches, num_passes=1, feeder=feeder)
    p_after_1 = {k: np.asarray(v) for k, v in trainer.state["params"].items()}
    trainer.train(batches, num_passes=1, feeder=feeder)  # lr is now 0
    for k, v in trainer.state["params"].items():
        np.testing.assert_array_equal(np.asarray(v), p_after_1[k])


def test_reader_combinators():
    base = lambda: iter(range(10))
    assert list(rd.firstn(base, 3)()) == [0, 1, 2]
    assert sorted(rd.shuffle(base, 5)()) == list(range(10))
    assert list(rd.chain(base, base)()) == list(range(10)) * 2
    assert list(rd.buffered(base, 2)()) == list(range(10))
    assert list(rd.map_readers(lambda a, b: a + b, base, base)()) == [2 * i for i in range(10)]
    assert list(rd.compose(base, base)()) == [(i, i) for i in range(10)]
    got = list(rd.batch(base, 4)())
    assert got == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    got = list(rd.batch(base, 4, drop_last=True)())
    assert got == [[0, 1, 2, 3], [4, 5, 6, 7]]
    c = rd.cache(base)
    assert list(c()) == list(range(10)) and list(c()) == list(range(10))
    x = rd.xmap_readers(lambda v: v * 2, base, 3, 4, order=True)
    assert list(x()) == [2 * i for i in range(10)]


def test_feeder_sequences():
    from paddle_tpu.data import integer_value_sequence

    feeder = DataFeeder({"ids": integer_value_sequence(100)})
    batch = feeder([{"ids": [1, 2, 3]}, {"ids": [4]}])
    assert batch["ids"].shape == (2, 8)  # bucketed to 8
    np.testing.assert_array_equal(batch["ids.lengths"], [3, 1])
    np.testing.assert_array_equal(batch["ids"][0, :3], [1, 2, 3])
    assert batch["ids"][1, 1:].sum() == 0


def test_resume_restores_optimizer_state(tmp_path):
    # Adam slots + samples counter must survive save/load (true resume,
    # unlike the v1 reference which saves values only)
    _, _, _, cost = _build()
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    batches = rd.batch(_toy_classification_reader(64), 32, drop_last=True)
    t1 = SGDTrainer(cost, Adam(learning_rate=0.01), seed=3)
    t1.train(batches, num_passes=2, feeder=feeder, save_dir=str(tmp_path))

    reset_name_scope()
    _, _, _, cost2 = _build()
    t2 = SGDTrainer(cost2, Adam(learning_rate=0.01), seed=3)
    t2.init_state(feeder(next(iter(batches()))))
    t2.load(str(tmp_path))
    assert int(t2.state["samples"]) == int(t1.state["samples"])
    import jax
    m1 = jax.tree.leaves(t1.state["opt"])
    m2 = jax.tree.leaves(t2.state["opt"])
    assert any(np.abs(np.asarray(a)).sum() > 0 for a in m2[:-1])
    for a, b in zip(m1, m2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_reader_error_propagation():
    def bad_reader():
        yield 1
        raise IOError("disk died")

    with pytest.raises(IOError, match="disk died"):
        list(rd.buffered(bad_reader, 2)())

    def bad_mapper(x):
        raise ValueError("corrupt sample")

    with pytest.raises(ValueError, match="corrupt sample"):
        list(rd.xmap_readers(bad_mapper, lambda: iter(range(5)), 2, 2)())


def test_cache_partial_pass_not_poisoned():
    base = lambda: iter(range(10))
    c = rd.cache(base)
    it = c()
    for _ in range(5):
        next(it)
    it.close()  # partial pass
    assert list(c()) == list(range(10))
    assert list(c()) == list(range(10))


def test_feeder_truncates_over_bucket():
    from paddle_tpu.data import InputSpec

    feeder = DataFeeder({"ids": InputSpec("index_seq", 100, seq_bucket=[4])})
    batch = feeder([{"ids": list(range(9))}])
    assert batch["ids"].shape == (1, 4)
    np.testing.assert_array_equal(batch["ids.lengths"], [4])


def test_multi_step_scan_matches_sequential():
    """make_multi_step: K scanned steps in one compiled program must produce
    the same state as K sequential compiled steps."""
    import jax

    data = {
        "x": np.random.RandomState(0).randn(16, 8).astype(np.float32),
        "label": np.random.RandomState(1).randint(0, 4, 16),
    }

    def build():
        reset_name_scope()
        _, _, _, cost = _build()
        return SGDTrainer(cost, SGD(learning_rate=0.5))

    K = 3
    t_seq = build()
    t_seq.init_state(data)
    step = t_seq._make_step()
    s = t_seq.state
    for _ in range(K):
        s, cost_seq, _ = step(s, data)

    t_scan = build()
    t_scan.init_state(data)
    multi = t_scan.make_multi_step()
    batches = {k: np.stack([v] * K) for k, v in data.items()}
    s2, costs = multi(t_scan.state, batches)
    assert costs.shape == (K,)
    np.testing.assert_allclose(float(costs[-1]), float(cost_seq), rtol=1e-5)
    for k in s["params"]:
        np.testing.assert_allclose(
            np.asarray(s["params"][k]), np.asarray(s2["params"][k]),
            rtol=1e-5, atol=1e-6,
        )


def test_end_iteration_event_is_lazy():
    """Handlers that don't read .cost must not force a device sync; reading
    .cost/.metrics fetches and caches."""
    _, _, _, cost = _build()
    tr = SGDTrainer(cost, SGD(learning_rate=0.1))
    reader = rd.batch(_toy_classification_reader(n=32), 16)
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})

    events = []
    tr.train(reader, num_passes=1, event_handler=events.append, feeder=feeder)
    iters = [e for e in events if isinstance(e, EndIteration)]
    assert iters, "no EndIteration events delivered"
    ev = iters[-1]
    assert "lazy" in repr(ev)          # repr must not sync
    c1 = ev.cost                        # first access fetches
    assert isinstance(c1, float) and np.isfinite(c1)
    assert ev.cost == c1                # cached
    passes = [e for e in events if isinstance(e, EndPass)]
    assert np.isfinite(passes[-1].metrics["avg_cost"])


def test_updater_protocol_is_wired():
    """The ParameterUpdater seam (ParameterUpdater.h:38): a custom updater's
    apply runs inside the compiled step and pass hooks fire on the host."""
    from paddle_tpu.parallel import SgdLocalUpdater

    calls = []

    class CountingUpdater(SgdLocalUpdater):
        def start_pass(self):
            calls.append("start_pass")

        def finish_pass(self):
            calls.append("finish_pass")

        def apply(self, grads, opt_state, params, lr):
            # scale LR by 0 => params must not move; proves apply() is the
            # one being traced into the step, not optimizer.update directly
            return super().apply(grads, opt_state, params, lr * 0.0)

    _, _, _, cost = _build()
    opt = SGD(learning_rate=0.5)
    tr = SGDTrainer(cost, opt, updater=CountingUpdater(opt))
    reader = rd.batch(_toy_classification_reader(n=32), 16)
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    tr.train(reader, num_passes=2, feeder=feeder)
    assert calls == ["start_pass", "finish_pass"] * 2
    # zero-LR updater: parameters unchanged after training
    p0, _ = tr.network.init(
        __import__("jax").random.PRNGKey(tr.seed),
        feeder(next(iter(rd.batch(_toy_classification_reader(n=16), 16)()))),
        train=True,
    )
    for k, v in tr.state["params"].items():
        np.testing.assert_allclose(np.asarray(v), np.asarray(p0[k]), atol=1e-6)


def test_v1_binary_parameter_format():
    """Byte-level interchange with Parameter::save (Parameter.h:263): header
    {int32 format=0, uint32 valueSize=4, uint64 size} + raw little-endian
    float32 payload, verified against hand-packed golden bytes; conv filters
    round-trip through the reference's (c, kh, kw) x out memory layout."""
    import io
    import struct

    from paddle_tpu.trainer import v1_format as V

    rs = np.random.RandomState(0)
    fc_w = rs.randn(3, 4).astype(np.float32)

    buf = io.BytesIO()
    V.write_param(buf, "fc.w", fc_w)
    got = buf.getvalue()
    golden = struct.pack("<iIQ", 0, 4, 12) + fc_w.astype("<f4").tobytes()
    assert got == golden  # exact byte layout

    buf.seek(0)
    back = V.read_param(buf, "fc.w", (3, 4))
    np.testing.assert_array_equal(back, fc_w)

    # conv HWIO <-> reference channel-major rows
    conv_w = rs.randn(2, 2, 3, 5).astype(np.float32)  # kh,kw,ci,co
    buf = io.BytesIO()
    V.write_param(buf, "conv.w", conv_w)
    raw = buf.getvalue()[16:]
    ref_rows = np.frombuffer(raw, "<f4").reshape(3, 2, 2, 5)  # ci,kh,kw,co
    np.testing.assert_array_equal(ref_rows, np.transpose(conv_w, (2, 0, 1, 3)))
    buf.seek(0)
    back = V.read_param(buf, "conv.w", conv_w.shape)
    np.testing.assert_array_equal(back, conv_w)

    # model-dir + merged-stream round trips
    import tempfile

    params = {"fc.w": fc_w, "conv.w": conv_w, "b": rs.randn(5).astype(np.float32)}
    with tempfile.TemporaryDirectory() as d:
        V.save_model_dir(d, params)
        loaded = V.load_model_dir(d, params)
    for k in params:
        np.testing.assert_array_equal(loaded[k], params[k])

    buf = io.BytesIO()
    V.write_merged(buf, b"CONFIG", params, order=sorted(params))
    buf.seek(0)
    cfg, loaded = V.read_merged(buf, params, order=sorted(params))
    assert cfg == b"CONFIG"
    for k in params:
        np.testing.assert_array_equal(loaded[k], params[k])

    # size-mismatch hard-fails (the reference CHECKs)
    buf = io.BytesIO()
    V.write_param(buf, "fc.w", fc_w)
    buf.seek(0)
    with pytest.raises(ValueError, match="size mismatch"):
        V.read_param(buf, "fc.w", (3, 5))


def test_save_pass_v1_binary_files():
    from paddle_tpu.trainer import checkpoint as ckpt
    from paddle_tpu.trainer import v1_format as V
    import tempfile

    rs = np.random.RandomState(1)
    params = {"fc.w": rs.randn(4, 2).astype(np.float32)}
    with tempfile.TemporaryDirectory() as d:
        pdir = ckpt.save_pass(d, 0, params, v1_binary=True)
        assert os.path.exists(os.path.join(pdir, "fc.w"))
        with open(os.path.join(pdir, "fc.w"), "rb") as f:
            back = V.read_param(f, "fc.w", (4, 2))
        np.testing.assert_array_equal(back, params["fc.w"])


def test_load_reference_v1_model_dir(tmp_path):
    """The actual interchange scenario (ParamUtil.cpp:50 loadParameters):
    a directory of raw Parameter::save files — byte-generated here straight
    from the Parameter.h:263 header spec, no manifest/npz — loads
    transparently through Trainer.load / load_pass header sniffing, with conv
    filters transposed from the reference's channel-major rows to HWIC."""
    import struct

    from paddle_tpu.trainer import checkpoint as ckpt

    _, _, logits, cost = _build()
    feeder = DataFeeder({"x": dense_vector(8), "label": integer_value(4)})
    batches = rd.batch(_toy_classification_reader(), 32, drop_last=True)
    t1 = SGDTrainer(cost, SGD(learning_rate=0.1), seed=7)
    t1.train(batches, num_passes=1, feeder=feeder)
    ref = t1.test(batches, feeder)["cost"]

    # emit the model dir with hand-packed bytes only (header spec, not
    # v1_format.write_param) — this is the fixture a reference build would
    # have written
    mdir = tmp_path / "ref_model"
    mdir.mkdir()
    for name, arr in t1.state["params"].items():
        a = np.asarray(arr, dtype="<f4")
        with open(mdir / name, "wb") as f:
            f.write(struct.pack("<iIQ", 0, 4, a.size))
            f.write(a.tobytes())

    assert ckpt.is_v1_model_dir(str(mdir))

    reset_name_scope()
    _, _, _, cost2 = _build()
    t2 = SGDTrainer(cost2, SGD(learning_rate=0.1), seed=999)
    t2.init_state(feeder(next(iter(batches()))))
    t2.load(str(mdir))
    got = t2.test(batches, feeder)["cost"]
    assert got == pytest.approx(ref, rel=1e-5)

    # conv layout: a reference channel-major file must land as HWIO
    rs = np.random.RandomState(3)
    hwio = rs.randn(3, 3, 2, 4).astype(np.float32)
    ref_rows = np.ascontiguousarray(np.transpose(hwio, (2, 0, 1, 3)))  # ci,kh,kw,co
    cdir = tmp_path / "conv_model"
    cdir.mkdir()
    with open(cdir / "conv.w", "wb") as f:
        f.write(struct.pack("<iIQ", 0, 4, ref_rows.size) + ref_rows.astype("<f4").tobytes())
    params, states, opt, manifest = ckpt.load_pass(
        str(cdir), params_template={"conv.w": np.zeros((3, 3, 2, 4), np.float32)}
    )
    assert manifest["v1_binary"] and not states and not opt
    np.testing.assert_array_equal(params["conv.w"], hwio)

    # without a template the sniff fails loudly, not confusingly
    with pytest.raises(ValueError, match="v1 binary"):
        ckpt.load_pass(str(mdir))


def test_save_pass_default_writes_v1_binary(tmp_path):
    """v1_binary now defaults on: every pass dir doubles as a reference
    model dir and reloads through the sniffing path byte-identically."""
    from paddle_tpu.trainer import checkpoint as ckpt
    from paddle_tpu.trainer import v1_format as V

    rs = np.random.RandomState(1)
    params = {"fc.w": rs.randn(4, 2).astype(np.float32)}
    pdir = ckpt.save_pass(str(tmp_path), 3, params)
    with open(os.path.join(pdir, "fc.w"), "rb") as f:
        back = V.read_param(f, "fc.w", (4, 2))
    np.testing.assert_array_equal(back, params["fc.w"])
    # npz manifest still wins when both are present
    p2, _, _, manifest = ckpt.load_pass(str(tmp_path), 3)
    assert "v1_binary" not in manifest
    np.testing.assert_array_equal(p2["fc.w"], params["fc.w"])
