"""Core graph-system tests: init/apply, param sharing, topo order, state updates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import Argument, Network, ParamAttr, reset_name_scope


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def test_fc_forward_shapes(rng):
    data = L.Data("x", shape=(16,))
    fc1 = L.Fc(data, size=32, act="relu")
    fc2 = L.Fc(fc1, size=4, act=None)
    net = Network(fc2)
    batch = {"x": np.random.RandomState(0).randn(8, 16).astype(np.float32)}
    params, states = net.init(rng, batch)
    outs, _ = net.apply(params, states, batch)
    assert outs[fc2.name].value.shape == (8, 4)
    # two weight matrices + two biases
    assert len(params) == 4


def test_param_sharing(rng):
    data = L.Data("x", shape=(8,))
    shared = ParamAttr(name="shared_w")
    a = L.Fc(data, size=8, act=None, bias=False, param_attr=shared)
    b = L.Fc(a, size=8, act=None, bias=False, param_attr=shared)
    net = Network(b)
    batch = {"x": np.zeros((2, 8), np.float32)}
    params, _ = net.init(rng, batch)
    assert list(params) == ["shared_w"]


def test_shared_param_shape_mismatch(rng):
    data = L.Data("x", shape=(8,))
    shared = ParamAttr(name="w")
    a = L.Fc(data, size=8, act=None, bias=False, param_attr=shared)
    b = L.Fc(a, size=4, act=None, bias=False, param_attr=shared)
    net = Network(b)
    # wrapped in LayerError carrying the failing layer's name
    # (CustomStackTrace parity)
    from paddle_tpu.core.stack_trace import LayerError

    with pytest.raises(LayerError, match="mismatch"):
        net.init(jax.random.PRNGKey(0), {"x": np.zeros((2, 8), np.float32)})


def test_batchnorm_state_updates(rng):
    data = L.Data("x", shape=(4,))
    bn = L.BatchNorm(data)
    net = Network(bn)
    x = np.random.RandomState(1).randn(32, 4).astype(np.float32) * 3 + 1
    params, states = net.init(rng, {"x": x}, train=True)
    outs, new_states = net.apply(params, states, {"x": x}, train=True)
    # train-mode output is normalized
    v = np.asarray(outs[bn.name].value)
    np.testing.assert_allclose(v.mean(0), 0.0, atol=1e-4)
    np.testing.assert_allclose(v.std(0), 1.0, atol=1e-2)
    # moving stats moved toward batch stats
    mm = np.asarray(new_states[f"{bn.name}.moving_mean"])
    assert np.all(np.abs(mm) > 0)
    # eval mode uses moving stats and does not update state
    outs2, states2 = net.apply(params, new_states, {"x": x}, train=False)
    np.testing.assert_allclose(
        np.asarray(states2[f"{bn.name}.moving_mean"]), mm, rtol=1e-6
    )


def test_dropout_train_vs_eval(rng):
    data = L.Data("x", shape=(100,))
    drop = L.Dropout(data, rate=0.5)
    net = Network(drop)
    x = np.ones((4, 100), np.float32)
    params, states = net.init(rng, {"x": x})
    out_eval, _ = net.apply(params, states, {"x": x}, train=False)
    np.testing.assert_array_equal(np.asarray(out_eval[drop.name].value), x)
    out_train, _ = net.apply(
        params, states, {"x": x}, train=True, rng=jax.random.PRNGKey(3)
    )
    v = np.asarray(out_train[drop.name].value)
    assert ((v == 0) | (v == 2.0)).all()
    assert 0.3 < (v == 0).mean() < 0.7


def test_apply_is_jittable(rng):
    data = L.Data("x", shape=(16,))
    out = L.Fc(data, size=8, act="sigmoid")
    net = Network(out)
    batch = {"x": np.zeros((4, 16), np.float32)}
    params, states = net.init(rng, batch)

    @jax.jit
    def f(params, states, x):
        outs, _ = net.apply(params, states, {"x": x})
        return outs[out.name].value

    y = f(params, states, batch["x"])
    assert y.shape == (4, 8)


def test_topo_diamond(rng):
    data = L.Data("x", shape=(8,))
    a = L.Fc(data, size=8, act=None)
    b = L.Fc(data, size=8, act=None)
    c = L.Addto([a, b], act="relu")
    net = Network(c)
    names = [l.name for l in net.layer_order]
    assert names.index(data.name) < names.index(a.name)
    assert names.index(a.name) < names.index(c.name)
    assert len(names) == len(set(names))


def test_argument_seq_mask():
    v = jnp.zeros((2, 5, 3))
    arg = Argument(v, lengths=jnp.array([2, 5]))
    m = np.asarray(arg.mask())
    assert m.tolist() == [[1, 1, 0, 0, 0], [1, 1, 1, 1, 1]]
