"""Decode fast path (ISSUE 11): ragged paged-attention kernel, chunked
prefill, on-device sampling.

The load-bearing claims, each tested directly:

  * kernel oracle — the Pallas ragged paged-attention decode kernel
    (interpret mode on CPU) matches the jnp dense-gather path to float
    tolerance across mixed lengths, ages and block-table layouts, and a
    serving session running through the kernel produces IDENTICAL tokens to
    the oracle session end to end;
  * chunked prefill — committing a prompt C tokens per engine step
    reproduces the whole-prompt prefill exactly (tokens equal), serves
    prompts beyond the largest bucket, and never skips a decode step: an
    already-decoding stream gains one token at EVERY engine step while a
    long prompt's chunks commit;
  * sampling — per-request seeded keys: same seed ⇒ same tokens, explicit
    temperature 0 ⇒ bitwise the greedy path, top_k=1 ⇒ greedy; an engine
    crash replay regenerates bitwise-identical SAMPLED tokens (the PR 10
    result-transparency contract extended beyond greedy);
  * admission guards — prompt+budget past LMConfig.max_len is rejected at
    the front door with a named error (silent XLA index-clamp regression);
  * shape discipline — chunked prefill + mixed greedy/sampled requests
    still record exactly ONE decode signature (zero recompiles)."""

import numpy as np
import pytest

from paddle_tpu.core import faults

pytestmark = pytest.mark.serving

VOCAB = 96


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    return ServingSession(model, params, **kw)


PROMPTS = [
    [1, 5, 9, 11],
    [1, 7],
    [1, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18],
    [1, 40, 41, 42, 43, 44, 45, 46],
]


# -- ragged paged-attention kernel vs the jnp gather oracle -------------------


def _oracle_paged_attention(q, k_pages, v_pages, block_table, positions,
                            scale, n_heads):
    """The jnp dense-gather path, verbatim from ServableLM._paged_attention's
    CPU branch — duplicated here so the test fails if either side drifts."""
    import jax
    import jax.numpy as jnp

    s, kd = q.shape
    ps = k_pages.shape[1]
    hd = kd // n_heads
    qh = q.reshape(s, n_heads, hd)
    k_seq = k_pages[block_table].reshape(s, -1, n_heads, hd)
    v_seq = v_pages[block_table].reshape(s, -1, n_heads, hd)
    ctx_idx = jnp.arange(block_table.shape[1] * ps)
    mask = ctx_idx[None, :] <= positions[:, None]
    sc = jnp.einsum("shd,sthd->sht", qh, k_seq) * scale
    sc = jnp.where(mask[:, None, :], sc, -1e9)
    w = jax.nn.softmax(sc.astype(jnp.float32), -1)
    return jnp.einsum("sht,sthd->shd", w, v_seq).reshape(s, -1)


@pytest.mark.parametrize("seed,ps,pmax", [(0, 8, 4), (1, 4, 7), (2, 16, 3)])
def test_kernel_matches_oracle_mixed_lengths(seed, ps, pmax):
    """Interpret-mode equality across mixed lengths, pages and block-table
    layouts — including empty slots (position 0, all-dump tables), partially
    filled pages, and out-of-order physical page assignments."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.paged_attention import paged_attention_decode

    rng = np.random.RandomState(seed)
    S, H, HD = 5, 2, 8
    NP = 1 + pmax * S
    KD = H * HD
    q = jnp.asarray(rng.randn(S, KD), jnp.float32)
    kp = jnp.asarray(rng.randn(NP, ps, KD), jnp.float32)
    vp = jnp.asarray(rng.randn(NP, ps, KD), jnp.float32)
    # ragged: each slot owns a random number of shuffled physical pages
    bt = np.zeros((S, pmax), np.int32)
    free = list(rng.permutation(np.arange(1, NP)))
    positions = np.zeros(S, np.int32)
    for s_ in range(S - 1):  # last slot stays empty (dump table, position 0)
        n = rng.randint(1, pmax + 1)
        pages = [free.pop() for _ in range(n)]
        bt[s_, :n] = pages
        positions[s_] = rng.randint(0, n * ps)
    got = paged_attention_decode(
        q, kp, vp, jnp.asarray(bt), jnp.asarray(positions),
        scale=1.0 / np.sqrt(HD), n_heads=H,
    )
    want = _oracle_paged_attention(
        q, kp, vp, jnp.asarray(bt), jnp.asarray(positions),
        1.0 / np.sqrt(HD), H,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_kernel_session_tokens_equal_oracle_session(
    model_and_params, monkeypatch
):
    """End to end: a serving session dispatching the Pallas kernel (interpret
    mode) generates IDENTICAL tokens to the jnp-oracle session over a mixed
    stream with joins and retires — greedy-decode argmax equality, the
    acceptance bar for the TPU fast path being CPU-verifiable."""
    oracle = make_session(model_and_params)
    ref = [oracle.submit(p, 8) for p in PROMPTS]
    oracle.run_until_idle()

    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
    kernel = make_session(model_and_params)
    got = [kernel.submit(p, 8) for p in PROMPTS]
    kernel.run_until_idle()
    assert [h.tokens for h in got] == [h.tokens for h in ref]
    assert kernel.decode_shape_signatures() == 1


# -- chunked prefill ----------------------------------------------------------


def test_chunked_prefill_tokens_equal_whole_prompt(model_and_params):
    """chunk-by-chunk KV commit reproduces the whole-prompt prefill exactly:
    same tokens for every prompt, chunk size not dividing the prompt included."""
    ref = make_session(model_and_params)
    want = [ref.submit(p, 8) for p in PROMPTS]
    ref.run_until_idle()

    for chunk in (3, 8):
        s = make_session(model_and_params, prefill_chunk=chunk)
        got = [s.submit(p, 8) for p in PROMPTS]
        s.run_until_idle()
        assert [h.tokens for h in got] == [h.tokens for h in want], (
            f"chunked prefill (C={chunk}) must be result-transparent"
        )
        assert s.prefill_chunks_committed > 0


def test_chunked_prefill_serves_prompts_beyond_buckets(model_and_params):
    """Chunking lifts the bucket cap: a prompt longer than the largest
    bucket decodes correctly (vs the full-context greedy reference) where
    the unchunked session rejects it."""
    import jax.numpy as jnp

    model, params = model_and_params
    long_prompt = [1] + list(range(3, 60))  # 58 tokens > largest bucket 32

    plain = make_session(model_and_params)
    with pytest.raises(ValueError, match="bucket"):
        plain.submit(long_prompt, 4)

    s = make_session(model_and_params, prefill_chunk=8)
    h = s.submit(long_prompt, 8)
    s.run_until_idle()

    toks, out = list(long_prompt), []
    for _ in range(8):
        logits = model.forward_logits(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == model.cfg.eos_id:
            break
    assert h.tokens == out


def test_bucket_gap_prompt_served_via_chunks(model_and_params):
    """A prompt in the gap between the largest bucket and a LARGER chunk
    size must be admitted (chunked), not rejected — with chunking on, no
    prompt up to max_len is unservable, and a longer prompt must never
    succeed where a shorter one fails."""
    import jax.numpy as jnp

    model, params = model_and_params
    s = make_session(
        model_and_params, prefill_buckets=(8, 16), prefill_chunk=64,
    )
    gap_prompt = [1] + list(range(3, 40))  # 38 tokens: > bucket 16, < chunk 64
    h = s.submit(gap_prompt, 6)
    s.run_until_idle()
    toks, out = list(gap_prompt), []
    for _ in range(6):
        logits = model.forward_logits(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == model.cfg.eos_id:
            break
    assert h.tokens == out


def test_load_estimator_prices_in_flight_prefill(model_and_params):
    """The wait estimate also prices chunks STILL TO COMMIT for prompts
    already mid-prefill in slots — a tight-deadline request arriving behind
    a half-committed long prompt must see those engine steps in its
    estimate (the PR 10 overload-shed contract)."""
    s = make_session(model_and_params, prefill_chunk=8)
    long_prompt = [1] + list(range(3, 60))  # 58 tokens -> 8 chunks
    s.submit(long_prompt, 4)
    s.step()  # admit + first chunk: 7 chunks remain in flight
    sch = s.scheduler
    with sch.lock:
        sch._ewma_service_s = 1.0
        sch._ewma_step_s = 0.1
    base = 1.0  # empty queue, fits now: one service wave
    est = sch.estimate_wait_s(8, prompt_len=4)
    assert est == pytest.approx(base + 7 * 0.1), (
        "remaining in-flight chunks must be priced into the estimate"
    )


def test_no_decode_step_skipped_during_chunked_prefill(model_and_params):
    """The no-stall contract: while a long prompt's chunks commit, an
    already-decoding stream gains exactly one token at EVERY engine step —
    the decode stream never waits for the prefill."""
    s = make_session(model_and_params, prefill_chunk=8)
    short = s.submit(PROMPTS[0], 16)
    s.step()  # admit + prefill (first token) + decode (second token)
    assert len(short.tokens) == 2
    long_prompt = [1] + list(range(3, 60))
    long = s.submit(long_prompt, 4)
    while long.tokens == [] and not short.done:
        n_before = len(short.tokens)
        s.step()
        assert len(short.tokens) == n_before + 1, (
            "a decode step was skipped while a chunk committed"
        )
    assert s.prefill_chunks_committed >= 7  # 58 tokens / C=8

    # the long prompt itself finishes correctly alongside
    s.run_until_idle()
    alone = make_session(model_and_params, prefill_chunk=8)
    h = alone.submit(long_prompt, 4)
    alone.run_until_idle()
    assert long.tokens == h.tokens


def test_load_estimator_prices_chunks(model_and_params):
    """The PR 10 wait estimate accounts for chunk count: with a long prompt
    queued, the estimated wait grows by its extra chunks' engine steps."""
    s = make_session(model_and_params, prefill_chunk=8)
    sch = s.scheduler
    assert sch._chunk_steps(4) == 0   # fits a bucket and one chunk
    assert sch._chunk_steps(8) == 0
    assert sch._chunk_steps(9) == 2   # chunked: ceil(9/8) chunk steps
    assert sch._chunk_steps(58) == 8
    # a prompt beyond every bucket chunks even when it fits ONE chunk
    gap = make_session(
        model_and_params, prefill_buckets=(8, 16), prefill_chunk=64,
    ).scheduler
    assert gap._chunk_steps(40) == 1
    with sch.lock:
        sch._ewma_service_s = 1.0
        sch._ewma_step_s = 0.1
    flat = sch.estimate_wait_s(16, prompt_len=8)
    chunky = sch.estimate_wait_s(66, prompt_len=58)
    assert chunky == pytest.approx(flat + 8 * 0.1)
    # TTFT estimate includes the request's own chunks too
    with sch.lock:
        t_flat = sch._estimate_ttft_wait_s(16, 8)
        t_chunky = sch._estimate_ttft_wait_s(66, 58)
    assert t_chunky == pytest.approx(t_flat + 8 * 0.1)


# -- on-device sampling -------------------------------------------------------


def test_sampling_deterministic_same_seed(model_and_params):
    """Same (seed, temperature, top_k) ⇒ same tokens, across sessions; a
    different seed diverges; explicit temperature 0 and top_k=1 are bitwise
    the greedy path."""
    def run(**kw):
        s = make_session(model_and_params)
        h = s.submit(PROMPTS[0], 12, **kw)
        s.run_until_idle()
        return h.tokens

    a = run(temperature=0.8, top_k=10, seed=42)
    b = run(temperature=0.8, top_k=10, seed=42)
    c = run(temperature=0.8, top_k=10, seed=7)
    greedy = run()
    assert a == b, "same seed must reproduce bitwise"
    assert a != c, "different seeds must diverge (fixed seeds chosen so)"
    assert run(temperature=0.0, seed=3) == greedy
    assert run(temperature=0.9, top_k=1, seed=3) == greedy, (
        "top_k=1 keeps only the argmax token"
    )


def test_sampling_batched_equals_alone(model_and_params):
    """Batching transparency extends to sampling: a sampled request's tokens
    are identical whether it runs alone or in a full mixed batch (explicit
    seeds — slot assignment must not leak into the draw)."""
    alone_tokens = []
    for i, p in enumerate(PROMPTS):
        s = make_session(model_and_params)
        h = s.submit(p, 8, temperature=0.7, top_k=8, seed=100 + i)
        s.run_until_idle()
        alone_tokens.append(h.tokens)

    batched = make_session(model_and_params)
    hs = [
        batched.submit(p, 8, temperature=0.7, top_k=8, seed=100 + i)
        for i, p in enumerate(PROMPTS)
    ]
    batched.run_until_idle()
    assert [h.tokens for h in hs] == alone_tokens


@pytest.mark.chaos
@pytest.mark.timeout(120)
def test_sampled_replay_bitwise_across_engine_restart(model_and_params):
    """The PR 10 crash-replay contract extended beyond greedy: a decode_raise
    mid-run restarts the engine, and the replayed SAMPLED requests reuse
    their seeds + token step indices — tokens bitwise-equal to unfaulted."""
    import time

    kw = dict(temperature=0.8, top_k=16)
    clean = make_session(model_and_params)
    ref = [clean.submit(p, 8, seed=50 + i, **kw) for i, p in enumerate(PROMPTS)]
    clean.run_until_idle()

    s = make_session(
        model_and_params, engine_stall_timeout_s=0.3, engine_restart_max=5
    )
    with faults.inject("decode_raise:step=3", seed=0) as inj:
        s.serve_forever()
        handles = [
            s.submit(p, 8, seed=50 + i, deadline_s=60.0, **kw)
            for i, p in enumerate(PROMPTS)
        ]
        deadline = time.monotonic() + 90
        for h in handles:
            assert h._event.wait(max(0.1, deadline - time.monotonic()))
        fired = dict(inj.fired)
    s.stop()
    assert fired.get("decode_raise", 0) >= 1
    assert s.engine_restarts >= 1
    assert [h.tokens for h in handles] == [h.tokens for h in ref], (
        "sampled replay must be bitwise result-transparent"
    )


# -- admission guards (ISSUE 11 satellite) ------------------------------------


def test_max_len_overflow_rejected_at_admission(model_and_params):
    """prompt + budget past LMConfig.max_len would index params['pos'] out
    of range inside jit — XLA clamps silently, producing wrong tokens. The
    session must reject at admission with a named error instead."""
    # chunking admits prompts beyond the buckets, so max_len is the only
    # guard left on that path — 90 + 16 > max_len 96
    s = make_session(model_and_params, prefill_chunk=8)
    with pytest.raises(ValueError, match="max_len"):
        s.submit([1] + [3] * 89, 16)
    # the boundary itself (80 + 16 == max_len) is fine
    h = s.submit([1] + [3] * 79, 16)
    assert h is not None
    h.cancel()
    # the bucketed path is covered by the constructor invariant: a session
    # whose buckets + budget could overflow max_len refuses to build at all
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    with pytest.raises(ValueError, match="max_len"):
        ServingSession(
            model, params, max_slots=4, page_size=8,
            prefill_buckets=(8, 16, 64), max_new_limit=64,
        )


# -- shape discipline ---------------------------------------------------------


def test_one_decode_signature_with_chunks_and_sampling(model_and_params):
    """The zero-recompile gate survives the fast path: chunked prefill,
    greedy and sampled requests mixed — ONE decode signature."""
    s = make_session(model_and_params, prefill_chunk=8)
    for ln in s.buckets:
        s.submit([1] + [3] * (ln - 1), 4)
    s.run_until_idle()
    assert s.decode_shape_signatures() == 1

    hs = [
        s.submit(PROMPTS[0], 8),
        s.submit([1] + list(range(3, 60)), 8),  # chunked long prompt
        s.submit(PROMPTS[1], 8, temperature=0.9, top_k=4, seed=1),
        s.submit(PROMPTS[3], 8, temperature=0.5),
    ]
    s.run_until_idle()
    assert all(h.done for h in hs)
    assert s.decode_shape_signatures() == 1
