"""Autoscaler tests (ISSUE 17): the goodput-driven controller that lets
training borrow chips from an idle serving fleet and hands them back under
load.

The decision engine (ScaleDecider) is PURE — signals in, at most one action
out, `now` passed by the caller — so everything that matters about its
robustness (hysteresis thresholds, per-lever cooldowns, square-wave flap
suppression, exponential backoff after a rejected resize) is pinned here
with a fake clock and zero sockets, subprocesses, or sleeps.  The
controller tests drive `tick(now=...)` against in-process client stand-ins
(anything with .call/.close), including the stateless-reconcile story: a
fresh controller re-derives desired state from observed stats alone.

The full fleet drill (real router + replicas + master, controller killed
and restarted mid-resize-epoch) lives in `chaos_bench --mode autoscale`;
the nightly test at the bottom runs it end-to-end.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from paddle_tpu.core import faults
from paddle_tpu.runtime.autoscaler import (
    Action,
    AutoscalerController,
    ScaleConfig,
    ScaleDecider,
    Signals,
)

pytestmark = [pytest.mark.autoscale]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def cfg(**kw):
    base = dict(
        chips_total=4, chips_per_replica=1,
        min_replicas=1, max_replicas=3,
        train_min_world=1, train_max_world=2,
        high_wait_s=1.0, low_wait_s=0.1,
        high_ticks=2, low_ticks=3,
        serving_cooldown_s=10.0, train_cooldown_s=10.0,
        flap_window_s=30.0, startup_quiet_s=0.0,
        backoff_base_s=5.0, backoff_max_s=40.0,
        resize_timeout_s=60.0, drain_deadline_s=30.0,
    )
    base.update(kw)
    return ScaleConfig(**base)


def sig(**kw):
    base = dict(queue_wait_s=0.5, live_replicas=1, train_world=1)
    base.update(kw)
    return Signals(**base)


HIGH = dict(queue_wait_s=5.0)
LOW = dict(queue_wait_s=0.01)


# ---------------------------------------------------------------------------
# hysteresis
# ---------------------------------------------------------------------------

def test_high_pressure_needs_a_streak_not_a_spike():
    d = ScaleDecider(cfg(high_ticks=3))
    assert d.decide(sig(**HIGH), 1.0) == []
    assert d.decide(sig(**HIGH), 2.0) == []
    acts = d.decide(sig(**HIGH), 3.0)
    assert len(acts) == 1 and acts[0].lever == "serving"
    assert acts[0].direction == "grow"


def test_low_pressure_needs_a_streak_not_a_dip():
    d = ScaleDecider(cfg(low_ticks=3))
    assert d.decide(sig(live_replicas=2, **LOW), 1.0) == []
    assert d.decide(sig(live_replicas=2, **LOW), 2.0) == []
    acts = d.decide(sig(live_replicas=2, **LOW), 3.0)
    assert len(acts) == 1 and acts[0].lever == "serving"
    assert acts[0].direction == "shrink"


def test_band_between_thresholds_resets_both_streaks():
    d = ScaleDecider(cfg(high_ticks=2, low_ticks=2))
    d.decide(sig(**HIGH), 1.0)
    # mid-band tick: neither high nor low — the streak must restart
    d.decide(sig(queue_wait_s=0.5), 2.0)
    assert d.decide(sig(**HIGH), 3.0) == []
    assert d.decide(sig(**HIGH), 4.0) != []


def test_shed_and_miss_deltas_count_as_pressure():
    for kw in ({"shed_delta": 1}, {"miss_delta": 1}):
        d = ScaleDecider(cfg(high_ticks=2))
        assert d.decide(sig(queue_wait_s=0.0, **kw), 1.0) == []
        acts = d.decide(sig(queue_wait_s=0.0, **kw), 2.0)
        assert acts and acts[0].direction == "grow"
        # ...and a shed tick also disqualifies "low" even at zero wait
        d2 = ScaleDecider(cfg(low_ticks=1))
        assert d2.decide(sig(live_replicas=2, queue_wait_s=0.0, **kw),
                         1.0) == []


# ---------------------------------------------------------------------------
# the chip ledger
# ---------------------------------------------------------------------------

def test_no_free_chips_reclaims_from_training_first():
    # 4 chips: 2 serving + 2 training -> a grow must shrink the world first
    d = ScaleDecider(cfg())
    s = sig(live_replicas=2, train_world=2, **HIGH)
    d.decide(s, 1.0)
    acts = d.decide(s, 2.0)
    assert len(acts) == 1 and acts[0].lever == "train"
    assert acts[0].direction == "shrink"
    assert acts[0].payload["world"] == 1


def test_training_at_floor_cannot_be_reclaimed():
    # serving at max AND training at min: pressure has nowhere to go
    d = ScaleDecider(cfg())
    s = sig(live_replicas=3, train_world=1, **HIGH)
    d.decide(s, 1.0)
    assert d.decide(s, 2.0) == []


def test_draining_replica_still_holds_its_chip():
    # 2 live + 1 draining + world 1 = 4 chips: no room to spawn, so the
    # decider reclaims from training instead of over-committing
    d = ScaleDecider(cfg(train_max_world=3))
    s = sig(live_replicas=2, draining_replicas=1, train_world=1, **HIGH)
    d.decide(s, 1.0)
    assert d.decide(s, 2.0) == []  # world already at train_min_world


def test_idle_drains_before_lending_and_one_drain_at_a_time():
    d = ScaleDecider(cfg(low_ticks=1))
    acts = d.decide(sig(live_replicas=3, **LOW), 1.0)
    assert acts and acts[0].lever == "serving" and acts[0].direction == "shrink"
    # with the drain still in flight, no second drain is stacked on top
    assert d.decide(sig(live_replicas=2, draining_replicas=1, **LOW),
                    100.0) == []


def test_idle_at_min_fleet_lends_free_chips_to_training():
    d = ScaleDecider(cfg(low_ticks=1))
    acts = d.decide(sig(live_replicas=1, train_world=1, **LOW), 1.0)
    assert len(acts) == 1 and acts[0].lever == "train"
    assert acts[0].direction == "grow" and acts[0].payload["world"] == 2


def test_resize_busy_blocks_the_train_lever_both_ways():
    d = ScaleDecider(cfg(low_ticks=1))
    assert d.decide(sig(live_replicas=1, train_world=1, resize_busy=True,
                        **LOW), 1.0) == []
    d2 = ScaleDecider(cfg())
    s = sig(live_replicas=2, train_world=2, resize_busy=True, **HIGH)
    d2.decide(s, 1.0)
    assert d2.decide(s, 2.0) == []


# ---------------------------------------------------------------------------
# cooldowns, flap suppression, startup quiet
# ---------------------------------------------------------------------------

def test_cooldown_spaces_actions_on_the_same_lever():
    d = ScaleDecider(cfg(high_ticks=1, serving_cooldown_s=10.0))
    assert d.decide(sig(**HIGH), 1.0) != []
    # pressure persists, but the lever is cooling down
    assert d.decide(sig(**HIGH), 5.0) == []
    assert d.suppressed.get("cooldown", 0) >= 1
    # ...until the cooldown elapses
    assert d.decide(sig(**HIGH), 12.0) != []


def test_startup_quiet_period_suppresses_first_action():
    d = ScaleDecider(cfg(high_ticks=1, startup_quiet_s=5.0))
    assert d.decide(sig(**HIGH), 1.0) == []
    assert d.suppressed.get("startup", 0) == 1
    assert d.decide(sig(**HIGH), 7.0) != []


def test_square_wave_load_cannot_thrash_the_train_lever():
    """A square wave faster than the cooldown yields AT MOST one train
    action per cooldown window — the flap suppressor plus cooldown turn an
    oscillating signal into a slow, damped response."""
    c = cfg(high_ticks=1, low_ticks=1, train_cooldown_s=10.0,
            flap_window_s=10.0, serving_cooldown_s=10.0,
            max_replicas=1)  # serving pinned: every action is train-lever
    d = ScaleDecider(c)
    stamps = []
    world = 1
    t = 0.0
    for cycle in range(40):  # 2s period square wave for 80s
        for s in (sig(live_replicas=1, train_world=world, **HIGH),
                  sig(live_replicas=1, train_world=world, **LOW)):
            t += 1.0
            for a in d.decide(s, t):
                assert a.lever == "train"
                stamps.append(t)
                world = a.payload["world"]
    assert stamps, "square wave never produced a single action?"
    for a, b in zip(stamps, stamps[1:]):
        assert b - a >= c.train_cooldown_s, (
            f"two train actions {b - a:.1f}s apart beats the "
            f"{c.train_cooldown_s}s cooldown: {stamps}"
        )
    assert d.suppressed.get("cooldown", 0) + d.suppressed.get("flap", 0) > 0


def test_flap_window_blocks_direction_reversal_after_cooldown():
    # cooldown shorter than the flap window: a same-direction action is
    # admitted after the cooldown, but a REVERSAL still waits the window out
    c = cfg(high_ticks=1, low_ticks=1, serving_cooldown_s=2.0,
            flap_window_s=20.0)
    d = ScaleDecider(c)
    assert d.decide(sig(live_replicas=1, **HIGH), 1.0) != []   # grow
    acts = d.decide(sig(live_replicas=2, **LOW), 5.0)          # reversal
    assert acts == [] and d.suppressed.get("flap", 0) == 1
    assert d.decide(sig(live_replicas=2, **LOW), 22.0) != []   # window over


# ---------------------------------------------------------------------------
# resize backoff
# ---------------------------------------------------------------------------

def test_backoff_after_rejected_resize_is_exponential_and_resets():
    d = ScaleDecider(cfg(low_ticks=1, backoff_base_s=5.0, backoff_max_s=40.0))
    grow = sig(live_replicas=1, train_world=1, **LOW)
    assert d.decide(grow, 1.0) != []
    h1 = d.note_resize_rejected(1.0)
    assert h1 == pytest.approx(6.0)  # 1.0 + base
    # inside the horizon the train lever is suppressed outright
    assert d.decide(grow, 4.0) == []
    assert d.suppressed.get("backoff", 0) == 1
    # second rejection doubles the delay...
    h2 = d.note_resize_rejected(10.0)
    assert h2 == pytest.approx(20.0)
    # ...and the cap holds no matter how many failures pile up
    for i in range(10):
        d.note_resize_rejected(100.0)
    assert d.resize_failures == 12
    assert d.note_resize_rejected(100.0) <= 100.0 + 40.0
    # a completed epoch clears everything
    d.note_resize_ok()
    assert d.resize_failures == 0
    assert d.decide(grow, 200.0) != []


def test_backoff_does_not_gate_the_serving_lever():
    d = ScaleDecider(cfg(high_ticks=1))
    d.note_resize_rejected(0.0)
    assert d.decide(sig(**HIGH), 1.0) != []  # spawn is still allowed


# ---------------------------------------------------------------------------
# controller: observe -> decide -> actuate against fake clients
# ---------------------------------------------------------------------------

class FakeClient:
    """In-process stand-in for the line-JSON RPC client: canned per-method
    responses, a call journal, optional injected ConnectionError."""

    def __init__(self, responses):
        self.responses = responses
        self.calls = []
        self.fail = False

    def call(self, method, **kw):
        if self.fail:
            raise ConnectionError("injected")
        self.calls.append((method, kw))
        resp = self.responses[method]
        return resp(kw) if callable(resp) else resp

    def close(self):
        pass


class FakeSpawner:
    def __init__(self):
        self.spawned = 0

    def spawn(self):
        self.spawned += 1

    def reap(self):
        return 0

    def stop_all(self):
        pass


def replica(rid, state="live", **load):
    ld = {"queue_depth": 0, "shed": 0, "deadline_misses": 0}
    ld.update(load)
    return {"replica_id": rid, "state": state, "outstanding": 0, "load": ld}


def router_stats(wait, replicas, shed=0):
    return {"estimated_queue_wait_s": wait, "shed": shed,
            "replicas": replicas}


def master_stats(world, state="idle", instance="m0", epoch=0):
    return {"resize": {"world": world, "state": state,
                       "instance": instance, "epoch": epoch}}


def make_controller(router_resp, master_resp, c=None, spawner=None):
    return AutoscalerController(
        config=c or cfg(),
        spawner=spawner,
        router_client=FakeClient(router_resp),
        master_client=FakeClient(master_resp),
    )


def test_controller_spawns_under_pressure_and_reaps():
    sp = FakeSpawner()
    ctl = make_controller(
        {"stats": router_stats(5.0, [replica("r0")])},
        {"stats": master_stats(1)},
        c=cfg(high_ticks=2), spawner=sp,
    )
    assert ctl.tick(now=1.0) == []
    acts = ctl.tick(now=2.0)
    assert [a.direction for a in acts] == ["grow"]
    assert sp.spawned == 1 and ctl.actions == ["spawn"]


def test_controller_drains_least_loaded_replica_when_idle():
    router = FakeClient({
        "stats": router_stats(0.0, [
            replica("r-busy", queue_depth=7),
            replica("r-idle", queue_depth=0),
        ]),
        "drain": {"ok": True},
    })
    ctl = AutoscalerController(
        config=cfg(low_ticks=2), router_client=router,
        master_client=FakeClient({"stats": master_stats(1)}),
    )
    ctl.tick(now=1.0)
    ctl.tick(now=2.0)
    drains = [kw for m, kw in router.calls if m == "drain"]
    assert len(drains) == 1 and drains[0]["replica_id"] == "r-idle"
    assert ctl.actions == ["drain:r-idle"]


def test_controller_announces_resize_and_settles_it():
    state = {"world": 1, "state": "idle", "instance": "m0", "epoch": 0}

    def on_resize(kw):
        state.update(world=kw["world"], state="draining", epoch=1)
        return {"instance": "m0", "epoch": 1, "world": kw["world"]}

    master = FakeClient({"stats": lambda kw: {"resize": dict(state)},
                         "resize": on_resize})
    ctl = AutoscalerController(
        config=cfg(low_ticks=2),
        router_client=FakeClient(
            {"stats": router_stats(0.0, [replica("r0")])}),
        master_client=master,
    )
    ctl.tick(now=1.0)
    acts = ctl.tick(now=2.0)  # low streak -> train grow 1 -> 2
    assert [a.lever for a in acts] == ["train"]
    assert ctl.actions == ["resize:2"]
    assert ctl._resize_inflight is not None
    # while the epoch is in flight, resize_busy blocks further train pulls
    assert ctl.tick(now=30.0) == []
    # the epoch completes: the next tick's watch settles it
    state.update(state="idle")
    ctl.tick(now=31.0)
    assert ctl._resize_inflight is None
    assert ctl.decider.resize_failures == 0


def test_controller_rejected_resize_backs_off():
    master = FakeClient({"stats": master_stats(1),
                         "resize": {"err": "epoch 3 still draining"}})
    ctl = AutoscalerController(
        config=cfg(low_ticks=1, backoff_base_s=50.0),
        router_client=FakeClient(
            {"stats": router_stats(0.0, [replica("r0")])}),
        master_client=master,
    )
    ctl.tick(now=1.0)
    assert ctl.actions == ["resize_rejected"]
    assert ctl.decider.resize_failures == 1
    # pressure persists but the train lever is in backoff
    ctl.tick(now=20.0)
    assert ctl.actions == ["resize_rejected"]  # no second announce
    assert ctl.decider.suppressed.get("backoff", 0) >= 1


def test_controller_resize_timeout_counts_as_rejection():
    state = {"world": 1, "state": "idle", "instance": "m0", "epoch": 0}

    def on_resize(kw):
        state.update(state="draining", epoch=1)  # wedges there forever
        return {"instance": "m0", "epoch": 1, "world": kw["world"]}

    ctl = AutoscalerController(
        config=cfg(low_ticks=1, resize_timeout_s=10.0),
        router_client=FakeClient(
            {"stats": router_stats(0.0, [replica("r0")])}),
        master_client=FakeClient(
            {"stats": lambda kw: {"resize": dict(state)},
             "resize": on_resize}),
    )
    ctl.tick(now=1.0)
    assert ctl.actions == ["resize:2"]
    ctl.tick(now=20.0)  # past the 10s resize timeout
    assert ctl._resize_inflight is None
    assert ctl.decider.resize_failures == 1


def test_controller_stale_observation_degrades_to_static():
    sp = FakeSpawner()
    router = FakeClient({"stats": router_stats(5.0, [replica("r0")])})
    ctl = AutoscalerController(
        config=cfg(high_ticks=1), spawner=sp, router_client=router,
        master_client=FakeClient({"stats": master_stats(1)}),
    )
    router.fail = True
    for t in (1.0, 2.0, 3.0):
        assert ctl.tick(now=t) == []
    assert sp.spawned == 0 and ctl.observe_failures == 3
    # the endpoint heals: the very next tick observes and acts again
    router.fail = False
    assert ctl.tick(now=4.0) != []
    assert sp.spawned == 1


def test_restarted_controller_reconciles_from_observed_state():
    """Crash -> restart re-derives desired state: a FRESH controller given
    only the fleet's observable stats adopts the in-flight world/fleet and
    continues — no journal, no handoff from its predecessor."""
    responses = (
        {"stats": router_stats(5.0, [replica("r0"), replica("r1")])},
        {"stats": master_stats(2),
         "resize": {"instance": "m0", "epoch": 1, "world": 1}},
    )
    c = cfg(high_ticks=2, startup_quiet_s=0.0)
    ctl1 = make_controller(*responses, c=c, spawner=FakeSpawner())
    ctl1.tick(now=1.0)
    # ctl1 dies here.  ctl2 starts cold, sees 2 live + world 2 = 4 chips
    # (no free chips), and correctly reclaims from training rather than
    # spawning a 5th chip that the budget does not have.
    ctl2 = make_controller(*responses, c=cfg(high_ticks=2),
                           spawner=FakeSpawner())
    ctl2.tick(now=10.0)
    acts = ctl2.tick(now=11.0)
    assert [(a.lever, a.direction) for a in acts] == [("train", "shrink")]
    assert acts[0].payload["world"] == 1


def test_controller_kill_site_fires_and_loop_degrades():
    ctl = make_controller(
        {"stats": router_stats(0.0, [replica("r0")])},
        {"stats": master_stats(1)},
    )
    with faults.inject("controller_kill:step=0"):
        with pytest.raises(faults.InjectedFault):
            ctl.tick(now=1.0)
    # through the loop thread the same fault marks the controller dead
    # (fleet degrades to static) instead of propagating
    ctl2 = make_controller(
        {"stats": router_stats(0.0, [replica("r0")])},
        {"stats": master_stats(1)},
    )
    ctl2.tick_s = 0.01
    with faults.inject("controller_kill:step=0"):
        ctl2.start()
        deadline = time.time() + 5.0
        while not ctl2.dead and time.time() < deadline:
            time.sleep(0.01)
    assert ctl2.dead and not ctl2.alive
    ctl2.stop()


def test_decider_emits_at_most_one_action_per_tick():
    d = ScaleDecider(cfg(high_ticks=1, low_ticks=1))
    for t in range(1, 50):
        s = sig(live_replicas=(t % 3) + 1, train_world=1,
                queue_wait_s=(5.0 if t % 2 else 0.01))
        assert len(d.decide(s, float(t))) <= 1


# ---------------------------------------------------------------------------
# the controller as a process (CLI)
# ---------------------------------------------------------------------------

@pytest.mark.timeout(120)
def test_cli_serve_starts_and_stops_clean():
    from paddle_tpu.serving.router import RouterServer

    router = RouterServer(lease_s=1.0, poll_interval_s=0.05).start()
    try:
        host, port = router.address
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.runtime.autoscaler",
             "serve", "--router", f"{host}:{port}", "--tick_s", "0.05"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
        )
        try:
            role = json.loads(proc.stdout.readline())
            assert role["role"] == "autoscaler"
            time.sleep(1.0)
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=30)
            final = json.loads(out.strip().splitlines()[-1])
            assert final["final"]["ticks"] >= 1
            assert final["final"]["dead"] is False
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# the full fleet drill (nightly)
# ---------------------------------------------------------------------------

@pytest.mark.nightly
@pytest.mark.timeout(600)
def test_chaos_autoscale_drill_gates():
    """chaos_bench --mode autoscale end-to-end: goodput retention across
    the burst, chips handed back when idle, zero lost requests, and
    exactly-once task accounting across resize epochs with the controller
    killed + restarted mid-epoch."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "chaos_bench.py"),
         "--mode", "autoscale"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["all_gates_pass"], json.dumps(rep["gates"], indent=2)
