"""Device-prefetching input pipeline + recompile telemetry tests.

Covers DevicePrefetcher (ordering, device residency, sharding, worker-error
propagation, clean shutdown), the trainer's device-batch fast path, the
RecompileStats shape-signature counter, and the persistent compilation cache
wiring."""

import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import stats
from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
from paddle_tpu.data.pipeline import DevicePrefetcher, is_device_batch


def _raw_batches(n=6, bs=8, dim=4, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    return [
        [(rs.randn(dim).astype(np.float32), int(i % classes)) for i in range(bs)]
        for _ in range(n)
    ]


def _feeder(dim=4, classes=3):
    return DataFeeder({"x": dense_vector(dim), "label": integer_value(classes)})


# ---------------------------------------------------------------------------
# DevicePrefetcher
# ---------------------------------------------------------------------------


def test_prefetcher_preserves_order_and_lands_on_device():
    import jax

    raws = _raw_batches()
    feeder = _feeder()
    sync = [feeder(r) for r in raws]
    got = list(DevicePrefetcher(lambda: iter(raws), feeder, prefetch_depth=2))
    assert len(got) == len(sync)
    for s, b in zip(sync, got):
        assert is_device_batch(b)
        assert all(isinstance(v, jax.Array) for v in b.values())
        np.testing.assert_array_equal(np.asarray(b["x"]), s["x"])
        np.testing.assert_array_equal(np.asarray(b["label"]), s["label"])


def test_prefetcher_accepts_dict_batches():
    """A reader already yielding feed-ready dicts (e.g. a DoubleBuffer)
    composes: the prefetcher only adds the device leg."""
    feeder = _feeder()
    dicts = [feeder(r) for r in _raw_batches(n=3)]
    got = list(DevicePrefetcher(lambda: iter(dicts), prefetch_depth=1))
    assert len(got) == 3 and all(is_device_batch(b) for b in got)


def test_prefetcher_propagates_worker_errors():
    def reader():
        yield _raw_batches(n=1)[0]
        raise RuntimeError("boom in feeder thread")

    with pytest.raises(RuntimeError, match="boom in feeder thread"):
        list(DevicePrefetcher(reader, _feeder(), prefetch_depth=1))


def test_prefetcher_clean_shutdown_on_early_exit():
    produced = []

    def reader():
        for i, r in enumerate(_raw_batches(n=100)):
            produced.append(i)
            yield r

    before = threading.active_count()
    it = iter(DevicePrefetcher(lambda: reader(), _feeder(), prefetch_depth=2))
    next(it)
    it.close()  # abandon mid-pass: the worker must retire, not spin
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before
    assert len(produced) < 100  # bounded queue stopped the producer early


def test_prefetcher_rejects_bad_depth_and_ragged_batches():
    with pytest.raises(ValueError, match="prefetch_depth"):
        DevicePrefetcher(lambda: iter(()), prefetch_depth=0)
    ragged = {"x": [np.zeros(2), np.zeros(3)]}
    # numpy >= 1.24 raises "inhomogeneous" itself; older paths hit _coerce's
    # object-dtype guard — either way the worker error reaches the consumer
    with pytest.raises(ValueError, match="ragged|inhomogeneous"):
        list(DevicePrefetcher(lambda: iter([ragged]), prefetch_depth=1))


def test_prefetcher_applies_parallel_sharding_and_pads_indivisible():
    import numpy as np

    from paddle_tpu.nn.graph import SAMPLE_MASK_KEY
    from paddle_tpu.parallel import DataParallel, make_mesh

    dp = DataParallel(make_mesh({"data": 8}))
    feeder = _feeder()
    good = feeder(_raw_batches(n=1, bs=16)[0])
    odd = feeder(_raw_batches(n=1, bs=9)[0])  # 9 % 8 != 0 → padded to 16
    got = list(
        DevicePrefetcher(lambda: iter([good, odd, good]), parallel=dp,
                         prefetch_depth=2)
    )
    assert len(got) == 3, "indivisible batch must pad+mask, not drop (ISSUE 5)"
    for b in got:
        assert is_device_batch(b)
        assert b["x"].sharding.is_equivalent_to(
            dp._batch_sharding, b["x"].ndim
        )
    padded = got[1]
    assert padded["x"].shape[0] == 16
    mask = np.asarray(padded[SAMPLE_MASK_KEY])
    assert mask.sum() == 9 and (mask[9:] == 0).all()


def test_trainer_reshards_device_batch_without_mesh_sharding():
    """A dict of device-resident arrays that never went through shard_batch
    must NOT take the fast path under DataParallel — the trainer reshards it
    onto the mesh instead of feeding default-device arrays to the step."""
    import jax

    from paddle_tpu.parallel import DataParallel, make_mesh

    dp = DataParallel(make_mesh({"data": 8}))
    feeder = _feeder()
    plain = {k: jax.device_put(v) for k, v in feeder(_raw_batches(n=1, bs=16)[0]).items()}
    assert is_device_batch(plain) and not dp.is_sharded_batch(plain)
    assert dp.is_sharded_batch(dp.shard_batch(plain))

    from paddle_tpu.trainer import EndPass

    trainer = _tiny_trainer()
    trainer.parallel = dp
    costs = []
    trainer.train(
        lambda: iter([plain, plain]), num_passes=1,
        event_handler=lambda e: costs.append(e.metrics["avg_cost"])
        if isinstance(e, EndPass)
        else None,
    )
    assert len(costs) == 1 and np.isfinite(costs[0])


def test_is_device_batch():
    import jax.numpy as jnp

    assert not is_device_batch({"x": np.zeros(3)})
    assert not is_device_batch({})
    assert not is_device_batch([np.zeros(3)])
    assert is_device_batch({"x": jnp.zeros(3)})


# ---------------------------------------------------------------------------
# trainer integration: device batches skip coerce/shard, telemetry flows
# ---------------------------------------------------------------------------


def _tiny_trainer():
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(4,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, 16, act="relu"), 3, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(cost, Adam(learning_rate=0.02), seed=1)


def test_trainer_trains_through_prefetcher():
    from paddle_tpu.trainer import EndPass

    raws = _raw_batches(n=8, bs=16)
    reader = DevicePrefetcher(lambda: iter(raws), _feeder(), prefetch_depth=3)
    trainer = _tiny_trainer()
    passes = []
    trainer.train(
        reader,
        num_passes=6,
        event_handler=lambda e: passes.append(e.metrics)
        if isinstance(e, EndPass)
        else None,
    )
    assert len(passes) == 6
    assert passes[-1]["avg_cost"] < passes[0]["avg_cost"]
    # one batch shape → one signature per pass, reported in EndPass metrics
    assert passes[-1]["shape_signatures"] == 1
    # test() takes the device-batch fast path too
    res = trainer.test(DevicePrefetcher(lambda: iter(raws), _feeder()))
    assert np.isfinite(res["cost"]) and res["samples"] == 8 * 16


def test_trainer_timer_split(monkeypatch):
    """PADDLE_TPU_TIMER surfaces the hostFeed / h2d / forwardBackward split."""
    from paddle_tpu.core.stats import GLOBAL_STATS, enable_timers

    GLOBAL_STATS.reset()
    enable_timers(True)
    try:
        trainer = _tiny_trainer()
        trainer.train(
            lambda: iter(_raw_batches(n=3, bs=16)), num_passes=1,
            feeder=_feeder(),
        )
        report = GLOBAL_STATS.as_dict()
        assert report["hostFeed"]["count"] == 3
        assert report["forwardBackward"]["count"] == 3
    finally:
        enable_timers(False)
        GLOBAL_STATS.reset()


# ---------------------------------------------------------------------------
# RecompileStats
# ---------------------------------------------------------------------------


def test_batch_signature_keys_on_shape_dtype_not_values():
    a = stats.batch_signature({"x": np.zeros((4, 2), np.float32)})
    b = stats.batch_signature({"x": np.ones((4, 2), np.float32)})
    c = stats.batch_signature({"x": np.zeros((4, 3), np.float32)})
    d = stats.batch_signature({"x": np.zeros((4, 2), np.int32)})
    assert a == b and a != c and a != d


def test_recompile_stats_pass_reset_and_warning(caplog):
    rc = stats.RecompileStats(warn_threshold=3)
    sig = lambda n: stats.batch_signature({"x": np.zeros((n, 2))})  # noqa: E731
    rc.start_pass()
    assert rc.record(sig(1)) is True
    assert rc.record(sig(1)) is False  # seen this pass
    rc.record(sig(2))
    assert rc.pass_signatures() == 2
    with caplog.at_level("WARNING", logger="paddle_tpu.stats"):
        rc.record(sig(3))  # hits warn_threshold=3
    assert any("distinct batch shapes" in r.message for r in caplog.records)
    rc.start_pass()
    assert rc.pass_signatures() == 0
    assert rc.total_signatures() == 3
    assert "shape signatures" in rc.report()


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------


def test_compilation_cache_round_trip(tmp_path):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.init_ctx import enable_compilation_cache

    old_dir = jax.config.jax_compilation_cache_dir
    try:
        cache_dir = enable_compilation_cache(str(tmp_path / "xla_cache"))
        assert cache_dir is not None
        misses0 = stats.RECOMPILES.cache_misses
        # a program shape unique to this test → must MISS then persist
        f = jax.jit(lambda x: x * 3.5 + x[::-1])
        f(jnp.arange(193, dtype=jnp.float32)).block_until_ready()
        assert stats.RECOMPILES.cache_misses > misses0
        assert os.listdir(cache_dir)  # entries persisted
        # identical program from a fresh jit wrapper → served from the cache
        hits0 = stats.RECOMPILES.cache_hits
        g = jax.jit(lambda x: x * 3.5 + x[::-1])
        g(jnp.arange(193, dtype=jnp.float32)).block_until_ready()
        assert stats.RECOMPILES.cache_hits > hits0
    finally:
        if old_dir:  # re-point the session cache (conftest) where it was
            enable_compilation_cache(old_dir)
        else:
            jax.config.update("jax_compilation_cache_dir", old_dir)


def test_compilation_cache_disabled_without_dir(monkeypatch):
    from paddle_tpu.core.init_ctx import enable_compilation_cache

    monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE", raising=False)
    assert enable_compilation_cache(None) is None
