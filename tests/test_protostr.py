"""Golden-protostr interchange (VERDICT r3 missing #2 / r2 task #10).

The reference proves its config DSL against golden protostr files
(python/paddle/trainer_config_helpers/tests/configs/protostr/, one per config
script). Here: execute the reference's own unmodified config scripts through
paddle_tpu.config.config_parser, emit ModelConfig text via dump_config, and
structurally diff (names / types / sizes / topology / parameter dims / typed
sub-confs) against the goldens with config.protostr.

`GOLDEN_MATCH` lists every config that must diff clean — all 51 of the
reference's goldens; regressions fail the test with the first discrepancy
lines, and test_match_count_floor keeps the count from silently shrinking.
"""

import os

import pytest

CFG_DIR = "/root/reference/python/paddle/trainer_config_helpers/tests/configs"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(CFG_DIR), reason="reference tree not available"
)

# configs whose emitted ModelConfig must structurally match the golden
GOLDEN_MATCH = [
    "img_layers",
    "img_trans_layers",
    "last_first_seq",
    "layer_activations",
    "math_ops",
    "projections",
    "shared_fc",
    "shared_gru",
    "shared_lstm",
    "simple_rnn_layers",
    "test_BatchNorm3D",
    "test_bi_grumemory",
    "test_bilinear_interp",
    "test_clip_layer",
    "test_conv3d_layer",
    "test_cost_layers",
    "test_cost_layers_with_weight",
    "test_cross_entropy_over_beam",
    "test_deconv3d_layer",
    "test_detection_output_layer",
    "test_expand_layer",
    "test_fc",
    "test_gated_unit_layer",
    "test_grumemory_layer",
    "test_hsigmoid",
    "test_kmax_seq_socre_layer",
    "test_lstmemory_layer",
    "test_maxout",
    "test_multibox_loss_layer",
    "test_multiplex_layer",
    "test_ntm_layers",
    "test_pad",
    "test_pooling3D_layer",
    "test_prelu_layer",
    "test_print_layer",
    "test_recursive_topology",
    "test_repeat_layer",
    "test_resize_layer",
    "test_rnn_group",
    "test_row_conv",
    "test_row_l2_norm_layer",
    "test_scale_shift_layer",
    "test_seq_concat_reshape",
    "test_seq_slice_layer",
    "test_sequence_pooling",
    "test_smooth_l1",
    "test_split_datasource",
    "test_spp_layer",
    "test_sub_nested_seq_select_layer",
    "unused_layers",
    "util_layers",
]


def _diff(name):
    from paddle_tpu import proto
    from paddle_tpu.config import protostr
    from paddle_tpu.config.config_parser import parse_config

    pc = parse_config(os.path.join(CFG_DIR, name + ".py"))
    golden = os.path.join(CFG_DIR, "protostr", name + ".protostr")
    # the full parsed ModelConfig (build_model_config output + declared
    # evaluators), the same artifact dump_config serializes
    return protostr.diff_files(golden, proto.to_text(pc.model_config))


@pytest.mark.parametrize("name", GOLDEN_MATCH)
def test_golden_config_structurally_matches(name):
    errs = _diff(name)
    assert not errs, f"{name} diverged from its golden:\n" + "\n".join(errs[:10])


def test_match_count_floor():
    """Sweep every golden; the structural-match count may only grow."""
    matched = []
    for fn in sorted(os.listdir(CFG_DIR)):
        if not fn.endswith(".py"):
            continue
        n = fn[:-3]
        if not os.path.exists(os.path.join(CFG_DIR, "protostr", n + ".protostr")):
            continue
        try:
            if not _diff(n):
                matched.append(n)
        except Exception:
            pass
    assert len(matched) >= len(GOLDEN_MATCH), (
        f"golden matches regressed: {len(matched)} < {len(GOLDEN_MATCH)} "
        f"({sorted(set(GOLDEN_MATCH) - set(matched))})"
    )


def test_text_proto_parser_roundtrip():
    from paddle_tpu.config.protostr import parse_text_proto

    d = parse_text_proto(
        'type: "nn"\nlayers {\n  name: "a"\n  size: 3\n  dims: 1\n  dims: 2\n'
        '  sub {\n    f: true\n    g: -1.5\n  }\n}\n'
    )
    assert d["type"] == ["nn"]
    (l,) = d["layers"]
    assert l["name"] == ["a"] and l["dims"] == [1, 2]
    assert l["sub"][0]["f"] == [True] and l["sub"][0]["g"] == [-1.5]


def test_param_name_normalization():
    from paddle_tpu.config.protostr import normalize_our_param, normalize_ref_param

    assert normalize_ref_param("___fc_layer_0__.w0") == "__fc_layer_0__.w.0"
    assert normalize_ref_param("___fc_layer_0__.wbias") == "__fc_layer_0__.b"
    assert normalize_ref_param("_a.w1") == "a.w.1"
    assert normalize_ref_param("shared_param") == "shared_param"
    assert normalize_our_param("__fc_layer_0__.w") == "__fc_layer_0__.w.0"
    assert normalize_our_param("__batch_norm_0__.scale") == "__batch_norm_0__.w.0"
    assert normalize_our_param("x.w.1") == "x.w.1"
