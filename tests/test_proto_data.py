"""DataFormat binary shards end-to-end: reader/writer round-trip, the
reference's in-tree shards feeding real training through unmodified configs
(ProtoDataProvider.cpp / test_TrainerOnePass.cpp idioms), the raw Layer()
config surface, and the chunking pipeline on generated CoNLL shards."""

import itertools
import os

import numpy as np
import pytest

REF_TESTS = "/root/reference/paddle/trainer/tests"

# wire-format and provider-semantics tests run everywhere; only the tests
# feeding the reference's in-tree shards need the reference checkout
needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF_TESTS), reason="reference tree not available"
)


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_shard_write_read_roundtrip(tmp_path):
    from paddle_tpu.data.proto_data import (
        INDEX, VECTOR_DENSE, VECTOR_SPARSE_NON_VALUE,
        DataSample, SlotDef, SubseqSlot, VectorSlot, read_shard, write_shard,
    )

    slot_defs = [
        SlotDef(VECTOR_DENSE, 3),
        SlotDef(VECTOR_SPARSE_NON_VALUE, 100),
        SlotDef(INDEX, 7),
    ]
    samples = [
        DataSample(
            is_beginning=(i % 2 == 0),
            vector_slots=[
                VectorSlot(values=np.arange(3, dtype=np.float32) + i),
                VectorSlot(ids=[i, i + 1, 99]),
            ],
            id_slots=[i % 7],
            subseq_slots=[SubseqSlot(slot_id=1, lens=[2, 1])] if i == 0 else [],
        )
        for i in range(5)
    ]
    path = str(tmp_path / "shard.bin")
    write_shard(path, slot_defs, samples)
    header, got = read_shard(path)
    assert [(sd.type, sd.dim) for sd in header] == [
        (sd.type, sd.dim) for sd in slot_defs
    ]
    assert len(got) == 5
    for a, b in zip(samples, got):
        assert a.is_beginning == b.is_beginning
        np.testing.assert_allclose(a.vector_slots[0].values, b.vector_slots[0].values)
        assert a.vector_slots[1].ids == b.vector_slots[1].ids
        assert a.id_slots == b.id_slots
    assert got[0].subseq_slots[0].slot_id == 1
    assert got[0].subseq_slots[0].lens == [2, 1]


def test_read_shard_rejects_truncated_file(tmp_path):
    """A shard cut mid-sample must raise ValueError naming the file, not
    silently parse partial samples (ProtoReader ParseFromZeroCopyStream
    parity)."""
    from paddle_tpu.data.proto_data import (
        VECTOR_DENSE, DataSample, SlotDef, VectorSlot, read_shard, write_shard,
    )

    path = str(tmp_path / "shard.bin")
    samples = [
        DataSample(vector_slots=[VectorSlot(values=np.arange(8, dtype=np.float32))])
        for _ in range(4)
    ]
    write_shard(path, [SlotDef(VECTOR_DENSE, 8)], samples)
    whole = open(path, "rb").read()
    cut = str(tmp_path / "cut.bin")
    with open(cut, "wb") as f:
        f.write(whole[: len(whole) - 9])  # clip into the last sample
    with pytest.raises(ValueError, match="cut.bin"):
        read_shard(cut)
    header, got = read_shard(path)  # the intact shard still parses
    assert len(got) == 4


def test_resolve_data_path_none_and_missing(tmp_path):
    from paddle_tpu.data.proto_data import resolve_data_path

    assert resolve_data_path(None, str(tmp_path)) is None
    assert resolve_data_path("", str(tmp_path)) is None
    assert resolve_data_path("nope.bin", str(tmp_path)) is None
    hit = tmp_path / "data.bin"
    hit.write_bytes(b"")
    assert resolve_data_path("data.bin", str(tmp_path)) == str(hit)


def test_proto_provider_shuffles_train_passes_only(tmp_path):
    """ProtoDataProvider::reset() parity: sequence order reshuffles per
    training pass (seeded), while test readers keep file order."""
    from paddle_tpu.data.proto_data import (
        INDEX, VECTOR_DENSE, DataSample, ProtoProvider, SlotDef, VectorSlot,
        write_shard,
    )

    path = str(tmp_path / "shard.bin")
    samples = [
        DataSample(
            vector_slots=[VectorSlot(values=np.full(2, i, np.float32))],
            id_slots=[i % 5],
        )
        for i in range(64)
    ]
    write_shard(path, [SlotDef(VECTOR_DENSE, 2), SlotDef(INDEX, 5)], samples)

    def order(provider, is_train):
        return [
            int(s[0][0]) for s in provider(file_list=[path], is_train=is_train)
        ]

    prov = ProtoProvider(seq_mode=False)
    file_order = list(range(64))
    p1, p2 = order(prov, True), order(prov, True)
    assert sorted(p1) == file_order and sorted(p2) == file_order
    assert p1 != file_order and p1 != p2  # reshuffled each pass
    assert order(prov, False) == file_order  # test reader: stable
    # seeded: a fresh provider replays the same per-pass permutations
    prov2 = ProtoProvider(seq_mode=False)
    assert order(prov2, True) == p1


@needs_ref
def test_read_reference_shards():
    """The reference's in-tree binaries parse with the expected schemas
    (mnist: dense 784 + 10-way label; qb data: 8 word-id slots + binary
    label, matching the configs' word_dim 1451594)."""
    from paddle_tpu.data.proto_data import read_shard

    header, samples = read_shard(os.path.join(REF_TESTS, "mnist_bin_part"))
    assert [(sd.type, sd.dim) for sd in header] == [(0, 784), (3, 10)]
    assert len(samples) == 1227
    assert all(len(s.vector_slots[0].values) == 784 for s in samples[:10])
    assert all(0 <= s.id_slots[0] < 10 for s in samples)

    header, samples = read_shard(os.path.join(REF_TESTS, "data_bin_part"))
    assert [(sd.type, sd.dim) for sd in header] == [(1, 1451594)] * 8 + [(3, 2)]
    assert len(samples) == 1000


# ---------------------------------------------------------------------------
# training helpers
# ---------------------------------------------------------------------------


def _train_config(conf_path, max_batches=None, config_args="", num_passes=1):
    """cmd_train's wiring, programmatic (the test_TrainerOnePass idiom):
    parse → optimizer → feeder/reader from the config's own DataConfig →
    train; returns per-pass avg costs."""
    from paddle_tpu.cli import _make_reader, bind_provider_types
    from paddle_tpu.config import build_optimizer
    from paddle_tpu.config.config_parser import parse_config
    from paddle_tpu.trainer.events import EndPass
    from paddle_tpu.trainer.trainer import SGDTrainer

    pc = parse_config(conf_path, config_args, emit_proto=False)
    bundle = build_optimizer(pc.trainer_config.opt_config)
    costs_out = [l for l in pc.outputs if getattr(l, "is_cost", False)] or pc.outputs
    extras = [l for l in pc.outputs if l not in costs_out]
    trainer = SGDTrainer(costs_out, bundle.optimizer, extra_outputs=extras,
                         schedule=bundle.schedule, seed=7)
    dc = pc.trainer_config.data_config
    feeding = bind_provider_types(pc.topology, dc)
    feeder = pc.topology.make_feeder(feeding)
    base_reader = _make_reader(dc, pc.trainer_config.opt_config.batch_size or 32)
    reader = (
        (lambda: itertools.islice(base_reader(), max_batches))
        if max_batches
        else base_reader
    )
    costs = []
    trainer.train(
        reader,
        num_passes=num_passes,
        feeder=feeder,
        event_handler=lambda e: costs.append(e.metrics["avg_cost"])
        if isinstance(e, EndPass)
        else None,
    )
    return pc, trainer, costs


# ---------------------------------------------------------------------------
# the trainer corpus trains (not just parses)
# ---------------------------------------------------------------------------


@needs_ref
@pytest.mark.slow
def test_mnist_proto_trains_opt_a():
    """sample_trainer_config_opt_a.conf: unmodified config + the in-tree
    mnist_bin_part shard train with momentum; cost must drop across passes
    (test_TrainerOnePass.cpp checkWork idiom)."""
    pc, _, costs = _train_config(
        os.path.join(REF_TESTS, "sample_trainer_config_opt_a.conf"),
        num_passes=3,
    )
    assert len(costs) == 3 and all(np.isfinite(costs))
    assert costs[-1] < costs[0], costs
    assert costs[0] < 10.0  # ~log(10) + init noise, not garbage


@needs_ref
@pytest.mark.slow
def test_mnist_proto_trains_opt_b():
    pc, _, costs = _train_config(
        os.path.join(REF_TESTS, "sample_trainer_config_opt_b.conf"),
        num_passes=2,
    )
    assert all(np.isfinite(costs)) and costs[-1] < costs[0]


@needs_ref
@pytest.mark.slow
def test_qb_rnn_trains_on_proto_sequence_data():
    """sample_trainer_config_qb_rnn.conf (raw Layer() API, 1.45M-word
    embedding, rank cost over left/right towers) trains on the in-tree
    data_bin_part proto_sequence shard; the rank cost must DROP over passes
    (test_TrainerOnePass checkWork bar), not just stay finite."""
    pc, _, costs = _train_config(
        os.path.join(REF_TESTS, "sample_trainer_config_qb_rnn.conf"),
        max_batches=8,
        num_passes=3,
    )
    assert all(np.isfinite(c) for c in costs)
    assert 0.0 < costs[0] < 5.0
    assert costs[-1] < costs[0], costs


@needs_ref
@pytest.mark.slow
def test_rnn_group_config_matches_flat_recurrent():
    """test_CompareTwoNets.cpp idiom on the reference's own config pair:
    sample_trainer_config_rnn.conf builds the recurrence with the raw
    RecurrentLayerGroupBegin/Memory API, qb_rnn with the flat `recurrent`
    layer — same parameter names, so with shared weights the costs must
    match on the same batch."""
    import itertools as it

    import jax

    from paddle_tpu.cli import _make_reader, bind_provider_types
    from paddle_tpu.config.config_parser import parse_config
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    pa = parse_config(
        os.path.join(REF_TESTS, "sample_trainer_config_qb_rnn.conf"),
        emit_proto=False,
    )
    reset_name_scope()
    pb = parse_config(
        os.path.join(REF_TESTS, "sample_trainer_config_rnn.conf"),
        emit_proto=False,
    )

    batches = {}
    for tag, pc in (("a", pa), ("b", pb)):
        dc = pc.trainer_config.data_config
        feeding = bind_provider_types(pc.topology, dc)
        feeder = pc.topology.make_feeder(feeding)
        raw = next(it.islice(_make_reader(dc, 10)(), 1))
        batches[tag] = feeder(raw)

    net_a = Network(pa.outputs)
    net_b = Network(pb.outputs)
    params_a, st_a = net_a.init(jax.random.PRNGKey(0), batches["a"])
    params_b, st_b = net_b.init(jax.random.PRNGKey(1), batches["b"])
    # identical parameter names by construction (embedding.w0, rnn1.*, ...)
    shared = {k: params_a[k] if k in params_a else v for k, v in params_b.items()}
    missing = [k for k in params_b if k not in params_a]
    assert not missing, f"parameter names diverge: {missing}"
    out_a, _ = net_a.apply(params_a, st_a, batches["a"])
    out_b, _ = net_b.apply(shared, st_b, batches["b"])
    cost_a = float(np.asarray(out_a[pa.outputs[0].name].value))
    cost_b = float(np.asarray(out_b[pb.outputs[0].name].value))
    assert cost_a == pytest.approx(cost_b, rel=2e-4), (cost_a, cost_b)


@needs_ref
@pytest.mark.slow
def test_compare_sparse_config_trains():
    """sample_trainer_config_compare_sparse.conf on its own shard
    (test_CompareSparse.cpp's config; the cross-process half lives in
    tests/test_distributed.py). Cost must drop over passes, matching the
    opt_a/chunking bar."""
    pc, _, costs = _train_config(
        os.path.join(REF_TESTS, "sample_trainer_config_compare_sparse.conf"),
        max_batches=8,
        num_passes=3,
    )
    assert all(np.isfinite(c) for c in costs)
    assert costs[-1] < costs[0], costs


# ---------------------------------------------------------------------------
# chunking end-to-end on generated CoNLL shards
# ---------------------------------------------------------------------------


@needs_ref
@pytest.mark.slow
def test_chunking_conf_e2e(tmp_path):
    """chunking.conf (raw Layer() API + CRF + ProtoData): generate the
    train/test shards from the in-tree CoNLL text exactly like
    gen_proto_data.py, check the feature dim lands on the config's declared
    4339, then train and eval with the ChunkEvaluator attached."""
    from paddle_tpu.cli import _make_reader, bind_provider_types
    from paddle_tpu.config import build_optimizer
    from paddle_tpu.config.config_parser import parse_config
    from paddle_tpu.data.datasets.conll_chunking import build_chunking_shards
    from paddle_tpu.metrics.evaluators import ChunkEvaluator
    from paddle_tpu.trainer.events import EndIteration
    from paddle_tpu.trainer.trainer import SGDTrainer

    info = build_chunking_shards(
        os.path.join(REF_TESTS, "train.txt"),
        os.path.join(REF_TESTS, "test.txt"),
        str(tmp_path),
    )
    assert info["feature_dim"] == 4339  # chunking.conf's features size
    assert info["index_dims"][2] == 23  # chunk labels

    pc = parse_config(os.path.join(REF_TESTS, "chunking.conf"), emit_proto=False)
    # point the unmodified config's relative data paths at the generated dir
    # (the reference's CMake generates the shards into its run dir too)
    for dc in (pc.trainer_config.data_config, pc.trainer_config.test_data_config):
        dc.config_dir = str(tmp_path)

    decoding = pc.topology.network.layers_by_name["crf_decoding"]
    bundle = build_optimizer(pc.trainer_config.opt_config)
    trainer = SGDTrainer(
        pc.outputs, bundle.optimizer, extra_outputs=[decoding],
        schedule=bundle.schedule, seed=3,
    )
    # the conf's own Evaluator("error", type="sum", inputs="crf_decoding")
    # parsed into the evaluator list
    assert any(e.type == "sum" for e in pc.context.evaluators)
    dc = pc.trainer_config.data_config
    feeding = bind_provider_types(pc.topology, dc)
    base_feeder = pc.topology.make_feeder(feeding)
    fed = []

    def feeder(samples):
        batch = base_feeder(samples)
        fed.append(batch)
        return batch

    reader = lambda: itertools.islice(_make_reader(dc, 100)(), 4)  # noqa: E731

    chunk_eval = ChunkEvaluator(scheme="IOB", num_chunk_types=11)
    chunk_eval.start()
    costs = []

    def handler(event):
        if isinstance(event, EndIteration):
            costs.append(float(event.cost))
            batch = fed[-1]
            chunk_eval.update(
                output=event.metrics["crf_decoding"],
                label=batch["chunk"],
                lengths=batch.get("chunk.lengths"),
            )

    trainer.train(reader, num_passes=2, feeder=feeder, event_handler=handler)
    f1 = chunk_eval.finish()
    assert 0.0 <= f1 <= 1.0
    assert all(np.isfinite(c) for c in costs)
    # CRF NLL per sequence starts near T*log(23); training must reduce it
    assert np.mean(costs[-4:]) < np.mean(costs[:4]), costs
