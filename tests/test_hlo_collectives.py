"""HLO collective-count lint for the data-parallel train step (ISSUE 5).

Compiles the trainer's step on a 4-device slice of the CPU host mesh and
counts the collective ops XLA emitted — the same way test_lint_hotloop.py
pins host syncs. A silent regression to chattier collectives (e.g. an
updater change that makes XLA emit per-parameter gathers where it combined
them, or an extra all-reduce from a stray unsharded reduction) changes these
counts and fails the build.

The counts are pinned for THIS model (3 Fc layers → 6 parameters) on the
CPU partitioner of the jax build in the container. On CPU the partitioner
realizes the sharded update's scatter leg as all-reduce + dynamic-slice
(the TPU weight-update-sharding pass forms a true reduce-scatter — PAPERS.md
"Automatic Cross-Replica Sharding of Weight Update..."), so the invariants
checked here are: the replicated path has NO gathers, the sharded path adds
a bounded number of all-gathers, and neither path's collective count scales
with batch or silently doubles."""

import re

import jax
import numpy as np
import pytest

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.parallel import DataParallel, make_mesh
from paddle_tpu.trainer import SGDTrainer

COLLECTIVES = (
    "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    "all-to-all",
)


def _counts(txt):
    return {
        op: len(re.findall(rf"= \S+ {op}\(", txt))
        + len(re.findall(rf"= \S+ {op}-start\(", txt))
        for op in COLLECTIVES
    }


def _built_trainer(shard, compression=None, extra_layer=False):
    reset_name_scope()
    x = L.Data("x", shape=(16,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 64, act="relu", name="h")
    h2 = L.Fc(h, 32, act="relu", name="h2")
    if extra_layer:
        h2 = L.Fc(h2, 32, act="relu", name="h3")
    logits = L.Fc(h2, 4, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": 4}))
    tr = SGDTrainer(
        cost, SGD(learning_rate=0.125), parallel=dp, seed=0,
        shard_update=shard, grad_compression=compression,
    )
    rs = np.random.RandomState(0)
    batch = dp.shard_batch({
        "x": rs.randn(32, 16).astype(np.float32),
        "label": rs.randint(0, 4, 32),
    })
    tr.init_state(batch)
    return tr, dp, batch


def _compiled_step_hlo(shard, compression=None, extra_layer=False):
    tr, _dp, batch = _built_trainer(shard, compression, extra_layer)
    # compile WITHOUT donation so the aliasing config cannot change op
    # counts between jax point releases; the collectives are identical
    return jax.jit(tr._build_step()).lower(tr.state, batch).compile().as_text()


def _compiled_multi_hlo(shard, k=4):
    """The K-step fused dispatch program (make_multi_step) for op pins."""
    tr, dp, batch = _built_trainer(shard)
    batches = dp.shard_batches(
        {key: np.stack([np.asarray(v)] * k) for key, v in batch.items()}
    )
    return tr.make_multi_step().lower(tr.state, batches).compile().as_text()


# measured on the container's jax 0.4.37 CPU partitioner; a changed count
# means the step's collective structure changed — review and re-pin
PINNED = {
    "replicated": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 0,
                   "collective-permute": 0, "all-to-all": 0},
    "sharded": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 6,
                "collective-permute": 0, "all-to-all": 0},
    "sharded_bf16": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 6,
                     "collective-permute": 0, "all-to-all": 0},
}


@pytest.mark.parametrize(
    "tag,shard,compression",
    [("replicated", False, None), ("sharded", True, None),
     ("sharded_bf16", True, "bf16")],
)
def test_collective_counts_pinned(tag, shard, compression):
    got = _counts(_compiled_step_hlo(shard, compression))
    assert got == PINNED[tag], (
        f"{tag} step now emits {got} (pinned {PINNED[tag]}) — the compiled "
        "train step's collective structure changed. If intentional (updater "
        "rework, XLA upgrade), re-pin after checking nothing regressed to "
        "per-parameter collectives; see tests/test_hlo_collectives.py"
    )


def test_replicated_path_has_no_gathers():
    """The replicated update must never gather/scatter params — its only
    collectives are gradient all-reduces (+ the cost mean)."""
    got = _counts(_compiled_step_hlo(False))
    assert got["all-gather"] == 0 and got["reduce-scatter"] == 0, got


def test_sharded_gathers_stay_bounded():
    """The sharded update concatenates per-param payloads, so its gather
    count must stay well under 2 collectives per parameter (6 params here;
    a per-param-per-leg regression would be >= 12)."""
    got = _counts(_compiled_step_hlo(True))
    n_params = 6
    assert 0 < got["all-gather"] <= n_params, got


# -- ZeRO-2/3 (ISSUE 14) -------------------------------------------------------
#
# zero2's contract is STRUCTURAL, not just a count: the K-dispatch program
# merges the window into one shard-local batch, so it compiles to a single
# fused forward/backward/update — NO while loop at all, and exactly the
# single-step collective budget regardless of K. zero1's K-dispatch keeps
# the scan: one while loop whose body repeats the per-step collectives K
# times (the op COUNT in the text stays small, but every op in the body
# executes per step — which is why the byte claim needs the loop gone, not
# just a low count).

WHILE_OP = re.compile(r" while\(")


def test_zero2_k_dispatch_one_scatter_per_dispatch():
    """Acceptance: zero2 at K emits exactly one grad reduce-scatter per
    DISPATCH (on the CPU partitioner the scatter realizes as the same
    all-reduce set as a single zero1 step — see module docstring), with no
    while loop to repeat it per step."""
    single = _counts(_compiled_step_hlo("zero1"))
    fused = _compiled_multi_hlo("zero2", k=4)
    assert not WHILE_OP.search(fused), (
        "the zero2 K-dispatch program contains a while loop — the window "
        "is being scanned per step instead of fused into one update"
    )
    assert _counts(fused) == single, (
        "zero2's fused dispatch must carry exactly the single-step "
        "collective budget (one scatter + one gather phase per DISPATCH)"
    )


def test_zero2_collectives_invariant_in_k():
    """The acceptance configuration (--steps_per_dispatch 16) compiles the
    same collective set as any other K — the scatter count is per-dispatch
    by construction, not per-step."""
    base = _counts(_compiled_multi_hlo("zero2", k=4))
    assert _counts(_compiled_multi_hlo("zero2", k=16)) == base
    assert _counts(_compiled_multi_hlo("zero2", k=8)) == base


def test_zero1_k_dispatch_keeps_per_step_collectives():
    """The contrast pin: zero1's K-dispatch is a scan — its collectives sit
    inside a while body and execute once per STEP."""
    assert WHILE_OP.search(_compiled_multi_hlo("zero1", k=4))


# zero3 step: 6 forward on-demand param all-gathers (one per flat param; the
# remat'd backward re-gathers CSE away on the CPU partitioner) and the same
# 7 all-reduces as the replicated/zero1 step — the grad scatter rides the
# baseline grad reductions (all-reduce + shard slice on CPU; a true
# reduce-scatter under the TPU weight-update-sharding pass), so sharding
# the PARAMS adds zero reduce ops. Measured on the container's jax 0.4.37
# CPU partitioner.
ZERO3_PINNED = {
    "all-reduce": 7, "reduce-scatter": 0, "all-gather": 6,
    "collective-permute": 0, "all-to-all": 0,
}


def test_zero3_collective_counts_pinned():
    got = _counts(_compiled_step_hlo("zero3"))
    assert got == ZERO3_PINNED, (
        f"zero3 step now emits {got} (pinned {ZERO3_PINNED}) — the on-demand "
        "gather structure changed. If intentional, re-pin after checking the "
        "gathers stayed per-param (not per-use) and no trailing param "
        "all-gather appeared; see Zero3Updater in parallel/updaters.py"
    )


def test_zero3_gathers_scale_per_layer_scatters_do_not():
    """+1 Fc layer = +2 on-demand gathers (its w and b) and +2 grad
    all-reduces — exactly what the REPLICATED step also adds for that layer
    (its grad reductions). The zero3 scatter therefore adds NOTHING on top
    of the baseline: layer-count-invariant scatter cost, per-layer gather
    count."""
    base = _counts(_compiled_step_hlo("zero3"))
    plus = _counts(_compiled_step_hlo("zero3", extra_layer=True))
    assert plus["all-gather"] == base["all-gather"] + 2
    rep_base = _counts(_compiled_step_hlo(False))
    rep_plus = _counts(_compiled_step_hlo(False, extra_layer=True))
    assert (plus["all-reduce"] - base["all-reduce"]
            == rep_plus["all-reduce"] - rep_base["all-reduce"]), (
        "zero3's reduce count must track the replicated baseline's exactly "
        "— extra reduces mean the update grew its own per-layer scatters"
    )


def test_zero3_int8_gather_crosses_payload_and_scales():
    """int8 zero3: each flat param's gather crosses as (int8 payload, f32
    block scales) — two collectives per param instead of one, visible as
    roughly doubled all-gather ops (the narrow payload is what crosses on
    TPU; the CPU partitioner may fold the dequantize first — the module
    docstring's realization caveat)."""
    got = _counts(_compiled_step_hlo("zero3", compression="int8"))
    base = _counts(_compiled_step_hlo("zero3"))
    assert got["all-gather"] >= 2 * base["all-gather"], (got, base)


# -- tensor-parallel serving decode (ISSUE 12) --------------------------------
#
# The TP decode step's collective budget is FIXED by construction: one
# all-reduce for the vocab-sharded embed gather, one all-reduce per
# row-parallel projection (wo and w2 — two per layer), and one all-gather
# replicating the logits at the unembed output so sampling (greedy argmax
# AND the gumbel branch) runs with ZERO collectives. A stray resharding
# boundary — an activation left sharded, a constraint dropped, a sampling
# op crossing the vocab shards — changes these counts and fails loudly.
# Compile-only (.lower().compile(), never executed), so the persistent-cache
# multi-device execution gotcha does not apply.

N_LAYERS_TP = 2


def _compiled_tp_decode_hlo(tp: int, max_slots: int = 4,
                            n_layers: int = N_LAYERS_TP) -> str:
    import jax.numpy as jnp
    import numpy as np_

    from paddle_tpu.parallel.rules import make_tp_mesh
    from paddle_tpu.serving.model import LMConfig, ServableLM

    mesh = make_tp_mesh(tp) if tp > 1 else None
    model = ServableLM(
        LMConfig(vocab=64, n_layers=n_layers, d_model=32, n_heads=4,
                 max_len=64),
        mesh=mesh,
    )
    params = model.shard_params(model.init_params(jax.random.PRNGKey(0)))
    shape = (n_layers, 9, 8, 32)
    if mesh is not None:
        k_pages = jax.jit(
            lambda: jnp.zeros(shape), out_shardings=model.pool_sharding()
        )()
    else:
        k_pages = jnp.zeros(shape)
    s = max_slots
    args = (
        params, k_pages, k_pages,
        np_.zeros(s, np_.int32), np_.zeros(s, np_.int32), np_.ones(s, bool),
        np_.zeros((s, 8), np_.int32), np_.zeros(s, np_.uint32),
        np_.zeros(s, np_.int32), np_.zeros(s, np_.float32),
        np_.zeros(s, np_.int32),
    )
    return jax.jit(model.decode_step).lower(*args).compile().as_text()


# 1 embed all-reduce + 2 row-parallel all-reduces per layer; 1 logits
# all-gather. Measured on the container's jax 0.4.37 CPU partitioner.
TP_DECODE_PINNED = {
    "all-reduce": 1 + 2 * N_LAYERS_TP,
    "reduce-scatter": 0,
    "all-gather": 1,
    "collective-permute": 0,
    "all-to-all": 0,
}


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_decode_collective_counts_pinned(tp):
    got = _counts(_compiled_tp_decode_hlo(tp))
    assert got == TP_DECODE_PINNED, (
        f"TP={tp} decode step now emits {got} (pinned {TP_DECODE_PINNED}) — "
        "a resharding boundary moved. Expected: one embed all-reduce, one "
        "all-reduce per row-parallel projection (wo, w2), one logits "
        "all-gather, nothing in sampling; see serving/model.py _constrain "
        "sites before re-pinning"
    )


def test_tp_decode_collectives_do_not_scale_with_slots():
    """Slots are data, not shape — and not collectives either: doubling
    max_slots must not add a single collective op."""
    assert (_counts(_compiled_tp_decode_hlo(2, max_slots=8))
            == _counts(_compiled_tp_decode_hlo(2, max_slots=4)))


def test_tp_decode_collectives_scale_only_with_layers():
    """+1 layer = +2 all-reduces (its wo and w2), nothing else — the
    per-layer budget the ISSUE names, directly."""
    base = _counts(_compiled_tp_decode_hlo(2))
    plus = _counts(_compiled_tp_decode_hlo(2, n_layers=N_LAYERS_TP + 1))
    assert plus["all-reduce"] == base["all-reduce"] + 2
    assert plus["all-gather"] == base["all-gather"]


def test_tp_single_chip_decode_has_no_collectives():
    """tp=1 must compile the PR-11 single-chip program: zero collectives,
    zero partitioning artifacts — TP support is free when unused."""
    got = _counts(_compiled_tp_decode_hlo(1))
    assert all(v == 0 for v in got.values()), got


def test_tp_sampling_branch_is_collective_free():
    """The sampling math ALONE (greedy argmax + the gumbel/top-k branch) on
    replicated logits under the TP mesh: zero collectives — the all-gather
    pinned above belongs to the unembed output, not to sampling."""
    import numpy as np_

    from paddle_tpu.parallel.rules import make_tp_mesh
    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=64, n_layers=1, d_model=32, n_heads=4, max_len=64),
        mesh=make_tp_mesh(2),
    )
    s = 4
    txt = jax.jit(model._sample).lower(
        np_.zeros((s, 64), np_.float32), np_.zeros(s, np_.uint32),
        np_.zeros(s, np_.int32), np_.ones(s, np_.float32),
        np_.full(s, 8, np_.int32),
    ).compile().as_text()
    got = _counts(txt)
    assert all(v == 0 for v in got.values()), got
