"""HLO collective-count lint for the data-parallel train step (ISSUE 5).

Compiles the trainer's step on a 4-device slice of the CPU host mesh and
counts the collective ops XLA emitted — the same way test_lint_hotloop.py
pins host syncs. A silent regression to chattier collectives (e.g. an
updater change that makes XLA emit per-parameter gathers where it combined
them, or an extra all-reduce from a stray unsharded reduction) changes these
counts and fails the build.

The counts are pinned for THIS model (3 Fc layers → 6 parameters) on the
CPU partitioner of the jax build in the container. On CPU the partitioner
realizes the sharded update's scatter leg as all-reduce + dynamic-slice
(the TPU weight-update-sharding pass forms a true reduce-scatter — PAPERS.md
"Automatic Cross-Replica Sharding of Weight Update..."), so the invariants
checked here are: the replicated path has NO gathers, the sharded path adds
a bounded number of all-gathers, and neither path's collective count scales
with batch or silently doubles."""

import re

import jax
import numpy as np
import pytest

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.parallel import DataParallel, make_mesh
from paddle_tpu.trainer import SGDTrainer

COLLECTIVES = (
    "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    "all-to-all",
)


def _counts(txt):
    return {
        op: len(re.findall(rf"= \S+ {op}\(", txt))
        + len(re.findall(rf"= \S+ {op}-start\(", txt))
        for op in COLLECTIVES
    }


def _compiled_step_hlo(shard, compression=None):
    reset_name_scope()
    x = L.Data("x", shape=(16,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 64, act="relu", name="h")
    h2 = L.Fc(h, 32, act="relu", name="h2")
    logits = L.Fc(h2, 4, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": 4}))
    tr = SGDTrainer(
        cost, SGD(learning_rate=0.125), parallel=dp, seed=0,
        shard_update=shard, grad_compression=compression,
    )
    rs = np.random.RandomState(0)
    batch = dp.shard_batch({
        "x": rs.randn(32, 16).astype(np.float32),
        "label": rs.randint(0, 4, 32),
    })
    tr.init_state(batch)
    # compile WITHOUT donation so the aliasing config cannot change op
    # counts between jax point releases; the collectives are identical
    return jax.jit(tr._build_step()).lower(tr.state, batch).compile().as_text()


# measured on the container's jax 0.4.37 CPU partitioner; a changed count
# means the step's collective structure changed — review and re-pin
PINNED = {
    "replicated": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 0,
                   "collective-permute": 0, "all-to-all": 0},
    "sharded": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 6,
                "collective-permute": 0, "all-to-all": 0},
    "sharded_bf16": {"all-reduce": 7, "reduce-scatter": 0, "all-gather": 6,
                     "collective-permute": 0, "all-to-all": 0},
}


@pytest.mark.parametrize(
    "tag,shard,compression",
    [("replicated", False, None), ("sharded", True, None),
     ("sharded_bf16", True, "bf16")],
)
def test_collective_counts_pinned(tag, shard, compression):
    got = _counts(_compiled_step_hlo(shard, compression))
    assert got == PINNED[tag], (
        f"{tag} step now emits {got} (pinned {PINNED[tag]}) — the compiled "
        "train step's collective structure changed. If intentional (updater "
        "rework, XLA upgrade), re-pin after checking nothing regressed to "
        "per-parameter collectives; see tests/test_hlo_collectives.py"
    )


def test_replicated_path_has_no_gathers():
    """The replicated update must never gather/scatter params — its only
    collectives are gradient all-reduces (+ the cost mean)."""
    got = _counts(_compiled_step_hlo(False))
    assert got["all-gather"] == 0 and got["reduce-scatter"] == 0, got


def test_sharded_gathers_stay_bounded():
    """The sharded update concatenates per-param payloads, so its gather
    count must stay well under 2 collectives per parameter (6 params here;
    a per-param-per-leg regression would be >= 12)."""
    got = _counts(_compiled_step_hlo(True))
    n_params = 6
    assert 0 < got["all-gather"] <= n_params, got
