"""Live elastic resize (ISSUE 8): grow/shrink the mesh mid-pass without
losing a step.

Equivalence contract (pinned here):
  * A pass that re-shards its data axis mid-pass lands allclose to the
    fixed-size run, with the SAME pass average (cross-device reduction
    order differs between world sizes, so bitwise across sizes is not a
    meaningful target — fixed 2-dev vs fixed 4-dev already differ at 1-2
    ULP).
  * The re-shard seam itself is value-preserving: a same-size "resize"
    (full canonical round trip + re-placement + recompiled step) is
    BITWISE identical to never resizing, and a run killed mid-re-shard
    (`reshard_kill`) that auto-resumes on the NEW world is BITWISE
    identical to the uninterrupted resized run.
  * Resize composes with --shard_update and steps_per_dispatch K>1.

Fleet half: the master's `_ResizeEpoch` state machine (announce → drain
barrier piggybacked on heartbeats → go → idle), barrier recomputation when a
member dies (lease eviction) or wedges (drain timeout — a wedged member's
daemon heartbeat thread keeps its lease alive, so the timeout is the
liveness guard), `ResizeClient` driving a real trainer end-to-end, and the
between-task drain of a registered `cluster_reader`.

The heavy multi-leg chaos_bench drill runs under the `nightly` marker
(nightly ⊆ slow, so tier-1 wall-clock stays within budget)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import faults, preempt, stats
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.parallel import DataParallel, make_mesh, resize_mesh
from paddle_tpu.trainer import SGDTrainer
from paddle_tpu.trainer import checkpoint as ckpt_mod
from paddle_tpu.trainer.events import EndIteration, EndPass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DIM, CLASSES, BATCH, N = 12, 3, 24, 144


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    preempt.reset()
    stats.FT_EVENTS.reset()
    yield
    preempt.reset()


def _reader():
    rs = np.random.RandomState(0)
    xs = rs.randn(N, DIM).astype(np.float32)
    ys = (xs.sum(-1) > 0).astype(np.int32)

    def reader():
        for i in range(0, N, BATCH):
            yield {"x": xs[i:i + BATCH], "label": ys[i:i + BATCH]}

    return reader


def _build(world, shard=False):
    reset_name_scope()
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 24, act="relu", name="h")
    logits = L.Fc(h, CLASSES, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    dp = DataParallel(make_mesh({"data": world}))
    # power-of-two lr/momentum: scale products are FMA-proof, so bitwise
    # gates test the resize seam, not XLA contraction luck (PR 5 idiom)
    return SGDTrainer(
        cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp, seed=5,
        shard_update=shard,
    )


def _run(world, target=None, at_batch=1, shard=False, passes=1, **train_kw):
    preempt.reset()
    tr = _build(world, shard=shard)
    metrics = []

    def handler(ev):
        if (
            target is not None
            and isinstance(ev, EndIteration)
            and (ev.pass_id, ev.batch_id) == (0, at_batch)
        ):
            preempt.get().request_resize(target, reason="test resize")
        if isinstance(ev, EndPass):
            metrics.append(ev.metrics)

    tr.train(
        _reader(), num_passes=passes, event_handler=handler,
        log_period=10_000, **train_kw,
    )
    return tr, metrics


def _params(tr):
    return {k: np.asarray(v) for k, v in tr.state["params"].items()}


def _assert_bitwise(a, b, what=""):
    for k in a:
        assert np.array_equal(
            a[k].view(np.uint32), b[k].view(np.uint32)
        ), f"{what}: param {k} differs (max abs {np.abs(a[k] - b[k]).max()})"


def _assert_close(a, b, what=""):
    for k in a:
        np.testing.assert_allclose(
            a[k], b[k], rtol=1e-5, atol=1e-7, err_msg=f"{what}: param {k}"
        )


# -- mesh helper --------------------------------------------------------------


def test_resize_mesh_reshapes_data_axis():
    m = make_mesh({"data": 2})
    m4 = resize_mesh(m, "data", 4)
    assert int(m4.shape["data"]) == 4


def test_resize_mesh_accepts_non_dividing_world():
    """A world size that does not divide the host device count (3 trainers
    on an 8-chip host) must truncate the pool, not trip make_mesh's
    divisibility check — otherwise join-triggered epochs can announce a
    world the trainers can never build and the fleet wedges at the old
    size."""
    m = make_mesh({"data": 2})
    m3 = resize_mesh(m, "data", 3)
    assert int(m3.shape["data"]) == 3
    assert m3.devices.size == 3


def test_resize_mesh_rejects_unknown_axis_and_overflow():
    m = make_mesh({"data": 2})
    with pytest.raises(ValueError, match="no axis"):
        resize_mesh(m, "pipeline", 2)
    with pytest.raises(ValueError, match="device"):
        resize_mesh(m, "data", 4096)
    with pytest.raises(ValueError, match=">= 1"):
        resize_mesh(m, "data", 0)


# -- trainer-side equivalence -------------------------------------------------


def test_grow_mid_pass_matches_fixed_size_run():
    tr_fixed, m_fixed = _run(2)
    tr_rz, m_rz = _run(2, target=4)
    assert tr_rz.parallel.data_axis_size == 4
    _assert_close(_params(tr_fixed), _params(tr_rz), "grow 2->4")
    assert m_rz[0]["avg_cost"] == pytest.approx(
        m_fixed[0]["avg_cost"], rel=1e-6
    )
    assert m_rz[0]["batches"] == m_fixed[0]["batches"]
    # the latency split is part of the pass metrics contract
    assert m_rz[0]["resize_epochs"] == 1
    (split,) = m_rz[0]["resizes"]
    assert split["world"] == 4
    for leg in ("drain_s", "reshard_s", "resume_s"):
        assert split[leg] >= 0.0
    assert stats.FT_EVENTS.get("resize_epoch") == 1


def test_shrink_mid_pass_matches_fixed_size_run():
    tr_fixed, m_fixed = _run(4)
    tr_rz, m_rz = _run(4, target=2)
    assert tr_rz.parallel.data_axis_size == 2
    _assert_close(_params(tr_fixed), _params(tr_rz), "shrink 4->2")
    assert m_rz[0]["avg_cost"] == pytest.approx(
        m_fixed[0]["avg_cost"], rel=1e-6
    )


def test_same_size_resize_roundtrip_is_bitwise():
    """The seam itself is value-preserving: an explicit resize_to at the
    SAME world size (full canonical round trip + re-placement + recompile)
    changes nothing bitwise — and a drained epoch targeting the size the
    trainer already runs is a cheap drain-only epoch (no re-shard, no
    compile-cache detach) that leaves training bitwise-identical too."""
    tr_fixed, _ = _run(2)
    before = _params(tr_fixed)
    tr_fixed.resize_to(2)  # the full seam, exercised directly
    _assert_bitwise(before, _params(tr_fixed), "2->2 resize_to roundtrip")
    tr_rz, m_rz = _run(2, target=2)  # drain-only epoch inside train()
    assert m_rz[0]["resize_epochs"] == 1
    _assert_bitwise(before, _params(tr_rz), "2->2 drain-only epoch")


def test_resize_composes_with_shard_update():
    """ZeRO-1 flat slots re-flatten for the new shard count through the
    canonical seams; the grown run still matches the fixed-size one."""
    tr_fixed, m_fixed = _run(2, shard=True)
    tr_rz, m_rz = _run(2, target=4, shard=True)
    assert tr_rz.parallel.data_axis_size == 4
    assert tr_rz.updater.n == 4  # rebind really rebuilt the flat geometry
    _assert_close(_params(tr_fixed), _params(tr_rz), "shard_update grow")
    assert m_rz[0]["avg_cost"] == pytest.approx(
        m_fixed[0]["avg_cost"], rel=1e-6
    )
    # the same-size seam stays bitwise under shard_update too (explicit
    # resize_to: the drained path would early-out as a drain-only epoch)
    before = _params(tr_fixed)
    tr_fixed.resize_to(2)
    _assert_bitwise(before, _params(tr_fixed), "sharded 2->2 roundtrip")


def test_resize_with_prefetcher_stacked_straggler():
    """A DevicePrefetcher's in-flight stacked [K, B, ...] groups were
    prepared under the PRE-resize plan: committed to old-mesh devices and
    padded to the old shard multiple. The trainer must rebuild those
    stragglers for the current plan instead of feeding the new compiled
    program incompatible arrays — and then rebind the prefetcher so the
    rest of the run lands directly on the new mesh; the result still
    matches the fixed-size run."""
    from paddle_tpu.data.pipeline import DevicePrefetcher

    def pf(dp):
        return DevicePrefetcher(
            _reader(), feeder=None, parallel=dp, prefetch_depth=2, stack_k=2
        )

    preempt.reset()
    tr_fixed = _build(2)
    m_fixed = []
    tr_fixed.train(
        pf(tr_fixed.parallel), num_passes=1, steps_per_dispatch=2,
        log_period=10_000,
        event_handler=lambda e: m_fixed.append(e.metrics)
        if isinstance(e, EndPass) else None,
    )

    preempt.reset()
    tr = _build(2)
    metrics = []

    def handler(ev):
        if isinstance(ev, EndIteration) and (ev.pass_id, ev.batch_id) == (0, 1):
            preempt.get().request_resize(4, reason="test resize")
        if isinstance(ev, EndPass):
            metrics.append(ev.metrics)

    prefetcher = pf(tr.parallel)
    tr.train(
        prefetcher, num_passes=1, steps_per_dispatch=2,
        event_handler=handler, log_period=10_000,
    )
    assert tr.parallel.data_axis_size == 4
    # the drain rebound the prefetcher onto the post-resize plan, so only
    # the <= depth in-flight groups took the straggler rebuild path
    assert prefetcher.parallel is tr.parallel
    assert metrics[0]["batches"] == m_fixed[0]["batches"]
    _assert_close(_params(tr_fixed), _params(tr), "prefetched grow")
    assert metrics[0]["avg_cost"] == pytest.approx(
        m_fixed[0]["avg_cost"], rel=1e-6
    )


def test_prefetcher_rebind_parallel_switches_plan_mid_stream():
    """rebind_parallel points FUTURE batches at the new plan: batches the
    worker prepared before the swap stay consistent under the old plan
    (pad and shard together — never mixed), later ones arrive sharded for
    the new mesh with its shard multiple."""
    from paddle_tpu.data.pipeline import DevicePrefetcher

    dp2 = DataParallel(make_mesh({"data": 2}))
    dp4 = DataParallel(make_mesh({"data": 4}))
    pf = DevicePrefetcher(_reader(), parallel=dp2, prefetch_depth=1)
    it = iter(pf)
    first = next(it)
    assert dp2.is_sharded_batch(first)
    pf.rebind_parallel(dp4)
    rest = list(it)
    assert rest, "reader should have more batches after the first"
    # in-flight batches (<= depth + 1) may still carry the old plan; the
    # tail of the stream must be on the new one
    last = rest[-1]
    assert dp4.is_sharded_batch(last)
    for b in rest:
        # every batch is internally consistent: sharded for exactly one
        # of the two plans, never padded for one and placed for the other
        assert dp2.is_sharded_batch(b) or dp4.is_sharded_batch(b)


def test_oversize_resize_rejected_and_training_continues():
    """A bad announce (world larger than the host's devices) must reject the
    resize after the drain — not kill a checkpointed trainer mid-pass — and
    the pass finishes on the current mesh with untouched results."""
    tr_fixed, m_fixed = _run(2)
    tr, m = _run(2, target=4096)
    assert tr.parallel.data_axis_size == 2  # resize rejected, mesh unchanged
    assert m[0].get("resize_epochs", 0) == 0  # no completed epoch recorded
    assert stats.FT_EVENTS.get("resize_rejected") == 1
    _assert_bitwise(_params(tr_fixed), _params(tr), "rejected resize")
    assert m[0]["avg_cost"] == m_fixed[0]["avg_cost"]


def test_resize_composes_with_k_step_dispatch():
    tr_fixed, m_fixed = _run(2, steps_per_dispatch=2)
    tr_rz, m_rz = _run(2, target=4, steps_per_dispatch=2)
    assert tr_rz.parallel.data_axis_size == 4
    _assert_close(_params(tr_fixed), _params(tr_rz), "K=2 grow")
    assert m_rz[0]["batches"] == m_fixed[0]["batches"]
    assert m_rz[0]["avg_cost"] == pytest.approx(
        m_fixed[0]["avg_cost"], rel=1e-6
    )


@pytest.mark.chaos
def test_reshard_kill_auto_resume_bitwise(tmp_path):
    """Acceptance gate: bitwise resume across a resize boundary for SGD.
    The seeded `reshard_kill` dies AFTER the drain checkpoint, mid-re-shard;
    a fresh trainer at the TARGET world auto-resumes from the drained
    boundary and must land exactly on the uninterrupted resized run."""
    oracle, m_o = _run(2, target=4)
    with faults.inject("reshard_kill:step=0") as inj:
        with pytest.raises(faults.InjectedKill):
            _run(2, target=4, save_dir=str(tmp_path))
        assert inj.fired["reshard_kill"] == 1
    # the drain checkpoint is durable and marked mid-pass
    pid = ckpt_mod.find_latest_valid_pass(str(tmp_path))
    assert pid == 0
    extra = ckpt_mod.pass_manifest(str(tmp_path), 0)["extra"]
    assert extra["mid_pass"] and extra["batches_done"] == 2
    assert extra["world_size"] == 2  # saved on the OLD mesh
    resumed, m_r = _run(4, save_dir=str(tmp_path), auto_resume=True)
    # the bitwise params gate is the contract; the replayed pass's avg_cost
    # covers only the replayed batches (existing auto_resume semantics), so
    # it is deliberately not compared against the full-pass oracle
    _assert_bitwise(_params(oracle), _params(resumed), "reshard_kill resume")
    assert m_r[0]["batches"] == m_o[0]["batches"] - 2  # replayed from batch 2


@pytest.mark.chaos
def test_resize_drain_stall_site_fires_locally(monkeypatch):
    """The stall site wedges the trainer inside its own drain (deterministic,
    seeded); with a short stall the run still completes and resizes."""
    monkeypatch.setenv("PADDLE_TPU_RESIZE_STALL_S", "0.05")
    with faults.inject("resize_drain_stall:step=0") as inj:
        tr, m = _run(2, target=4)
        assert inj.fired["resize_drain_stall"] == 1
    assert tr.parallel.data_axis_size == 4
    assert m[0]["resize_epochs"] == 1


def test_checkpoint_records_world_size(tmp_path):
    tr, _ = _run(2, save_dir=str(tmp_path))
    extra = ckpt_mod.pass_manifest(str(tmp_path), 0)["extra"]
    assert extra["world_size"] == 2


def test_resize_without_mesh_is_ignored():
    """A resize order reaching a mesh-less trainer must be dropped with a
    warning, not crash or spin."""
    reset_name_scope()
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(x, CLASSES, act=None)
    tr = SGDTrainer(C.ClassificationCost(logits, lbl), SGD(learning_rate=0.125))

    def handler(ev):
        if isinstance(ev, EndIteration) and ev.batch_id == 1:
            preempt.get().request_resize(4)

    tr.train(_reader(), num_passes=1, event_handler=handler, log_period=10_000)
    assert tr.parallel is None
    assert not preempt.resize_requested()  # claimed (and dropped), not stuck


# -- master resize-epoch state machine ---------------------------------------


def _native_available():
    from paddle_tpu.runtime import available

    return available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="native runtime unavailable"
)


@needs_native
@pytest.mark.timeout(60)
def test_epoch_barrier_all_members_ack():
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(TaskMaster(), lease_s=5.0).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        t2 = c.call("register")["trainer_id"]
        # malformed orders get an err REPLY on a surviving connection, not
        # a severed handler
        assert "err" in c.call("resize")
        assert "err" in c.call("resize", world="many")
        assert "err" in c.call("resize", world=0)
        ann = c.call("resize", world=4)
        assert ann["state"] == "draining" and ann["barrier"] == 2
        # a second announce while one is active is rejected with a reason
        assert "err" in c.call("resize", world=8)
        # a garbled epoch in the barrier RPCs replies status-only
        assert c.call("resize_drained", trainer_id=t1, epoch="x")["drained"] == 0
        # heartbeat piggybacks the drain signal, stamped with the resize
        # plane's instance token (epoch identity = instance + number)
        hb = c.call("heartbeat", trainer_id=t1)
        assert hb["resize"]["instance"]
        assert {
            k: hb["resize"][k] for k in ("state", "epoch", "world")
        } == {"state": "draining", "epoch": 1, "world": 4}
        mid = c.call("resize_drained", trainer_id=t1, epoch=1)
        assert mid["state"] == "draining" and mid["drained"] == 1
        go = c.call("resize_drained", trainer_id=t2, epoch=1)
        assert go["state"] == "go"
        # status polls double as resumed acks; epoch closes after both
        c.call("resize_status", trainer_id=t1, epoch=1)
        end = c.call("resize_status", trainer_id=t2, epoch=1)
        assert end["state"] == "idle" and end["completed"] == 1
        assert end["last"]["world"] == 4 and end["last"]["drain_s"] >= 0
        # idle → no piggyback
        assert "resize" not in c.call("heartbeat", trainer_id=t1)
        st = c.call("stats")
        assert st["resize"]["completed"] == 1
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(60)
def test_epoch_completes_when_member_dies_in_barrier():
    """Lease eviction recomputes the drain barrier: a member killed mid-drain
    (no heartbeats) cannot wedge the epoch."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(TaskMaster(), lease_s=0.6).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        c.call("register")  # t2 registers then dies silently
        c.call("resize", world=2)
        info = c.call("resize_drained", trainer_id=t1, epoch=1)
        assert info["state"] == "draining"  # waiting on the dead member
        deadline = time.time() + 20
        while time.time() < deadline and info["state"] == "draining":
            time.sleep(0.1)
            info = c.call("resize_status", trainer_id=t1, epoch=1)
        assert info["state"] == "idle", info
        assert info["last"]["evicted_during"] >= 1
        assert stats.FT_EVENTS.get("resize_barrier_evicted") >= 1
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(60)
def test_epoch_times_out_wedged_but_heartbeating_member():
    """A wedged member whose heartbeat thread is still alive holds its lease
    forever — the drain-barrier TIMEOUT is the liveness guard that drops it
    from the barrier so survivors proceed."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(
        TaskMaster(), lease_s=5.0, resize_drain_timeout_s=0.8
    ).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        t2 = c.call("register")["trainer_id"]
        c.call("resize", world=2)
        info = c.call("resize_drained", trainer_id=t1, epoch=1)
        assert info["state"] == "draining"
        deadline = time.time() + 20
        while time.time() < deadline and info["state"] == "draining":
            # t2 keeps heart-beating (wedged, not dead) yet never acks
            c.call("heartbeat", trainer_id=t2)
            time.sleep(0.1)
            info = c.call("resize_status", trainer_id=t1, epoch=1)
        assert info["state"] == "idle", info
        assert info["last"]["timed_out"] == 1
        # the woken straggler adopts the decided world from the idle epoch
        late = c.call("resize_drained", trainer_id=t2, epoch=1)
        assert late["state"] == "idle" and late["world"] == 2
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(90)
def test_resize_client_drives_trainer_end_to_end():
    """The full tentpole path with a REAL master: announce over RPC →
    heartbeat watcher parks the request → trainer drains at a batch
    boundary, acks the barrier, re-shards, resumes — and the result matches
    the fixed-size run."""
    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, ResizeClient, TaskMaster,
    )

    srv = MasterServer(TaskMaster(), lease_s=0.45).start()
    rc = None
    try:
        rc = ResizeClient(srv.address, poll_s=0.05)
        boot = MasterClient(srv.address)
        tr_fixed, m_fixed = _run(2, passes=2)

        preempt.reset()
        tr = _build(2)
        metrics = []
        announced = []

        def handler(ev):
            if isinstance(ev, EndIteration):
                if ev.pass_id == 0 and ev.batch_id == 1 and not announced:
                    announced.append(boot.call("resize", world=4))
                time.sleep(0.05)  # stretch the pass past a heartbeat period
            if isinstance(ev, EndPass):
                metrics.append(ev.metrics)

        tr.train(
            _reader(), num_passes=2, event_handler=handler,
            resize_barrier=rc.barrier, log_period=10_000,
        )
        assert announced and announced[0]["state"] == "draining"
        assert tr.parallel.data_axis_size == 4
        _assert_close(_params(tr_fixed), _params(tr), "fleet grow")
        assert sum(m.get("resize_epochs", 0) for m in metrics) == 1
        st = boot.call("stats")["resize"]
        assert st["state"] == "idle" and st["completed"] == 1
        boot.close()
    finally:
        if rc is not None:
            rc.close()
        srv.stop()


@needs_native
@pytest.mark.timeout(90)
def test_cluster_reader_drains_between_tasks(tmp_path):
    """A registered cluster_reader is a drain-barrier member: it acks between
    task acks (holding no lease on any task) and resumes pulling afterwards —
    task accounting stays exactly-once across the epoch."""
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, TaskMaster, cluster_reader,
    )

    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: ({"sid": i} for i in range(24)),
        records_per_file=2,
    )
    srv = MasterServer(TaskMaster(timeout_s=30.0), lease_s=0.45).start()
    try:
        boot = MasterClient(srv.address)
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        got = []

        def consume():
            for s in cluster_reader(srv.address, poll_interval=0.05)():
                got.append(s["sid"])
                time.sleep(0.05)

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        deadline = time.time() + 20
        while time.time() < deadline:
            if boot.call("stats").get("live_leases", 0) >= 1:
                break
            time.sleep(0.05)
        ann = boot.call("resize", world=2)
        assert ann["state"] == "draining"
        th.join(timeout=60)
        assert not th.is_alive()
        st = boot.call("stats")
        assert st["done"] == 12 and st["discarded"] == 0  # exactly-once
        assert sorted(got) == list(range(24))
        assert st["resize"]["completed"] == 1
        assert stats.FT_EVENTS.get("reader_resize_drain") == 1
        boot.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(90)
def test_two_lease_trainer_with_cluster_reader_no_deadlock(tmp_path):
    """The documented two-lease setup on ONE thread: a trainer whose data
    source is a registered cluster_reader. Whatever the ordering — the
    reader acks its drain without blocking for go, and when the resize
    lands mid-task the trainer's barrier acks the reader lease on its
    behalf — the epoch must complete with NO member timed out or evicted;
    the old circular wait could only be broken by the master timing out
    the healthy reader lease."""
    from paddle_tpu.runtime import recordio
    from paddle_tpu.runtime.master import (
        MasterClient, MasterServer, ResizeClient, TaskMaster, cluster_reader,
    )

    rs = np.random.RandomState(1)

    def batches():
        for _ in range(8):
            x = rs.randn(BATCH, DIM).astype(np.float32)
            yield {"x": x, "label": (x.sum(-1) > 0).astype(np.int32)}

    # ONE task holding every batch: the resize signal lands mid-task, so
    # the trainer reaches its dispatch-boundary drain while the reader can
    # never reach a between-task boundary — the barrier-services ordering
    shards = recordio.convert(
        str(tmp_path / "ds"), batches, records_per_file=8
    )
    srv = MasterServer(
        TaskMaster(timeout_s=60.0), lease_s=0.45, resize_drain_timeout_s=30.0,
    ).start()
    rc = None
    try:
        boot = MasterClient(srv.address)
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        rc = ResizeClient(srv.address, poll_s=0.05)
        tr = _build(2)
        announced = []

        def handler(ev):
            if isinstance(ev, EndIteration):
                time.sleep(0.2)  # let a heartbeat land inside the pass
                if ev.batch_id == 1 and not announced:
                    announced.append(boot.call("resize", world=4))

        t0 = time.time()
        tr.train(
            cluster_reader(srv.address, poll_interval=0.05), num_passes=1,
            event_handler=handler, resize_barrier=rc.barrier,
            log_period=10_000,
        )
        elapsed = time.time() - t0
        assert announced and announced[0]["state"] == "draining"
        assert tr.parallel.data_axis_size == 4
        st = boot.call("stats")["resize"]
        assert st["state"] == "idle" and st["completed"] == 1, st
        # the deadlock symptom: a healthy lease dropped by the drain timeout
        assert st["last"]["timed_out"] == 0, st
        assert st["last"]["evicted_during"] == 0, st
        assert elapsed < 25, f"epoch stalled ({elapsed:.1f}s): circular wait"
        assert boot.call("stats")["done"] == 1  # the single task, exactly once
        boot.close()
    finally:
        if rc is not None:
            rc.close()
        srv.stop()


@needs_native
@pytest.mark.timeout(60)
def test_resize_with_no_trainers_completes_immediately():
    """An announce with an empty live set must complete instantly, not wedge
    `draining` (and reject later resizes) until the drain timeout."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(TaskMaster(), lease_s=5.0).start()
    try:
        c = MasterClient(srv.address)
        info = c.call("resize", world=4)
        assert info["state"] == "idle" and info["completed"] == 1, info
        # the control plane is immediately free for the next epoch
        assert c.call("resize", world=2)["state"] == "idle"
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(60)
def test_resize_on_membership_announces_on_join():
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(
        TaskMaster(), lease_s=5.0, resize_on_membership=True
    ).start()
    try:
        c = MasterClient(srv.address)
        c.call("register")  # first join: nothing to re-shape yet
        assert c.call("stats")["resize"]["state"] == "idle"
        c.call("register")  # second join announces world=2
        info = c.call("stats")["resize"]
        assert info["state"] == "draining" and info["world"] == 2
        c.close()
    finally:
        srv.stop()


# -- fleet metrics ------------------------------------------------------------


def test_observe_resize_lands_in_snapshot():
    from paddle_tpu.obs import metrics as obs_metrics

    before = obs_metrics.snapshot().get("paddle_tpu_resize_epochs_total", 0.0)
    obs_metrics.observe_resize(
        {"drain": 0.25, "reshard": 0.5, "resume": 0.125}
    )
    snap = obs_metrics.snapshot()
    assert snap["paddle_tpu_resize_epochs_total"] == before + 1
    assert (
        snap["paddle_tpu_resize_latency_seconds_total{phase=drain}"] >= 0.25
    )
    # counters sum exactly across fleet heartbeat snapshots
    agg = obs_metrics.aggregate_snapshots([snap, snap])
    assert agg["paddle_tpu_resize_epochs_total"] == 2 * (before + 1)


@needs_native
@pytest.mark.timeout(60)
def test_epoch_go_phase_times_out_wedged_resharder():
    """A member that acks the drain and then wedges INSIDE its re-shard —
    heartbeat thread still renewing the lease, never polling resize_status —
    must not pin the epoch in `go` forever (which would reject every future
    announce). The go phase carries the same timeout guard as the drain."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(
        TaskMaster(), lease_s=5.0, resize_drain_timeout_s=0.8
    ).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        t2 = c.call("register")["trainer_id"]
        c.call("resize", world=2)
        c.call("resize_drained", trainer_id=t1, epoch=1)
        go = c.call("resize_drained", trainer_id=t2, epoch=1)
        assert go["state"] == "go"
        # t1 resumes; t2 wedges mid-re-shard but keeps heart-beating
        info = c.call("resize_status", trainer_id=t1, epoch=1)
        deadline = time.time() + 20
        while time.time() < deadline and info["state"] == "go":
            c.call("heartbeat", trainer_id=t2)
            time.sleep(0.1)
            info = c.call("resize_status", trainer_id=t1, epoch=1)
        assert info["state"] == "idle", info
        assert info["completed"] == 1
        assert info["last"]["timed_out"] == 1
        # the epoch is not pinned: a new announce is accepted
        assert c.call("resize", world=2)["state"] == "draining"
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(90)
def test_membership_churn_during_epoch_reannounces():
    """Churn that lands while an epoch is in flight must not be dropped:
    the rejected evict-triggered announce parks, and the reaper re-announces
    against the CURRENT membership once the epoch completes — the fleet
    never settles at a stale world size."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(
        TaskMaster(), lease_s=0.6, resize_on_membership=True,
        resize_drain_timeout_s=30.0,
    ).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        t2 = c.call("register")["trainer_id"]  # join-epoch 1: world=2
        c.call("resize_drained", trainer_id=t1, epoch=1)
        c.call("resize_drained", trainer_id=t2, epoch=1)
        c.call("resize_status", trainer_id=t1, epoch=1)
        info = c.call("resize_status", trainer_id=t2, epoch=1)
        assert info["state"] == "idle" and info["completed"] == 1

        t3 = c.call("register")["trainer_id"]  # join-epoch 2: world=3
        # t2 dies silently while epoch 2 drains; t1/t3 heartbeat but hold
        # their acks so the eviction lands mid-epoch
        info = c.call("resize_status", epoch=2)
        deadline = time.time() + 20
        while time.time() < deadline and info["barrier"] > 2:
            c.call("heartbeat", trainer_id=t1)
            c.call("heartbeat", trainer_id=t3)
            time.sleep(0.1)
            info = c.call("resize_status", epoch=2)
        assert info["barrier"] == 2, info  # t2 evicted from the barrier
        # epoch 2 completes at its (now stale) world=3
        c.call("resize_drained", trainer_id=t1, epoch=2)
        c.call("resize_drained", trainer_id=t3, epoch=2)
        c.call("resize_status", trainer_id=t1, epoch=2)
        c.call("resize_status", trainer_id=t3, epoch=2)
        # the parked churn re-announces epoch 3 with the live count (2)
        st = c.call("stats")["resize"]
        deadline = time.time() + 20
        while time.time() < deadline and st["epoch"] < 3:
            c.call("heartbeat", trainer_id=t1)
            c.call("heartbeat", trainer_id=t3)
            time.sleep(0.1)
            st = c.call("stats")["resize"]
        assert st["epoch"] == 3 and st["state"] == "draining", st
        assert st["world"] == 2, st
        c.call("resize_drained", trainer_id=t1, epoch=3)
        c.call("resize_drained", trainer_id=t3, epoch=3)
        c.call("resize_status", trainer_id=t1, epoch=3)
        end = c.call("resize_status", trainer_id=t3, epoch=3)
        assert end["state"] == "idle" and end["last"]["world"] == 2
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(90)
def test_reader_leases_join_barrier_but_not_world():
    """A process may hold a reader lease besides its trainer lease. The
    announced WORLD counts trainer-role leases only (double-counting would
    shard the data axis to a size no real trainer backs) while the drain
    BARRIER spans every lease — and a reader joining/leaving triggers no
    membership epoch at all."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    srv = MasterServer(
        TaskMaster(), lease_s=5.0, resize_on_membership=True,
    ).start()
    try:
        c = MasterClient(srv.address)
        t1 = c.call("register")["trainer_id"]
        r1 = c.call("register", role="reader")["trainer_id"]
        # a reader lease joining changes no world size: still idle
        assert c.call("stats")["resize"]["state"] == "idle"
        t2 = c.call("register")["trainer_id"]  # join-epoch: world=2, not 3
        st = c.call("stats")["resize"]
        assert st["state"] == "draining" and st["world"] == 2, st
        assert st["barrier"] == 3, st  # ...but ALL three leases must drain
        for tid in (t1, r1, t2):
            c.call("resize_drained", trainer_id=tid, epoch=st["epoch"])
        for tid in (t1, r1, t2):
            end = c.call("resize_status", trainer_id=tid, epoch=st["epoch"])
        assert end["state"] == "idle" and end["last"]["world"] == 2
        c.close()
    finally:
        srv.stop()


@needs_native
@pytest.mark.timeout(60)
def test_watcher_claims_colliding_epoch_from_restarted_master():
    """Epoch numbers are per-master-instance counters: a promoted standby
    counts from 1 again, so its first epoch can COLLIDE with (or sit below)
    a number this trainer already claimed from the dead primary. The
    watcher's replay guard keys on (instance, epoch), so the new master's
    epoch still drains this trainer — a bare-number guard would silently
    exempt it from every resize the new master runs."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, ResizeClient, TaskMaster

    srv = MasterServer(TaskMaster(), lease_s=0.6).start()
    rc = None
    try:
        rc = ResizeClient(srv.address)
        # as if epoch 1 (and a later epoch 7) were claimed pre-failover
        # from a master instance that no longer exists
        rc._seen = ("dead-primary", 1)
        # ...with the primary's epoch-7 order still parked, unclaimed
        assert preempt.get().request_resize(
            8, epoch=7, instance="dead-primary", reason="stale primary"
        )
        c = MasterClient(srv.address)
        ann = c.call("resize", world=2)
        assert ann["epoch"] == 1  # fresh master numbering restarts
        deadline = time.time() + 15
        req = None
        while time.time() < deadline:
            req = preempt.get().resize_request()
            if req is not None and req.epoch == 1:
                break
            time.sleep(0.05)
        req = preempt.get().take_resize()
        assert req is not None, "watcher never parked the epoch-1 order"
        # the live master's epoch 1 SUPERSEDED the dead primary's parked 7:
        # different instance outranks a higher stale number
        assert req.world == 2 and req.epoch == 1
        assert req.instance == ann["instance"] != "dead-primary"
        c.close()
    finally:
        if rc is not None:
            rc.close()
        srv.stop()


@needs_native
def test_resurrected_reader_lease_keeps_its_role():
    """An evicted reader whose next get_task/task_done resurrects the lease
    (note_seen carries no role) must keep its reader role — defaulting back
    to "trainer" would inflate the next membership-triggered world size."""
    from paddle_tpu.runtime.master import _Membership

    m = _Membership(lease_s=0.01)
    m.register("trainer")
    rid = m.register("reader")
    assert m.live_trainers == 1
    m.drop(rid)  # eviction path
    assert m.live_trainers == 1
    m.note_seen(rid)  # role-less RPC resurrects the lease
    assert m.live == 2
    assert m.live_trainers == 1  # still a reader, not a default trainer
    assert m.role(rid) == "reader"


def test_request_resize_instance_supersede_rules():
    """The parked-order channel: local epoch-0 never clobbers anything
    parked, same-instance duplicates/stale epochs are ignored, a later
    same-instance epoch and ANY different-instance epoch supersede."""
    g = preempt.get()
    assert g.request_resize(2)  # local order parks
    assert not g.request_resize(4)  # second local order ignored
    assert g.request_resize(4, epoch=3, instance="m1")  # master beats local
    assert not g.request_resize(8, epoch=3, instance="m1")  # duplicate
    assert not g.request_resize(8, epoch=2, instance="m1")  # stale
    assert not g.request_resize(8)  # local never clobbers a parked master's
    assert g.request_resize(8, epoch=4, instance="m1")  # later epoch wins
    assert g.request_resize(2, epoch=1, instance="m2")  # failover wins
    req = g.take_resize()
    assert (req.world, req.epoch, req.instance) == (2, 1, "m2")


@needs_native
@pytest.mark.timeout(60)
def test_drain_barrier_proceeds_alone_when_master_dies():
    """A dead master mid-epoch must trigger the documented proceed-alone
    fallback (announced world), not crash the training pass with an
    unhandled ConnectionError from the barrier polls."""
    import socket as socket_mod

    from paddle_tpu.runtime.master import MasterClient, _drain_barrier

    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here: every call exhausts retries
    c = MasterClient(
        ("127.0.0.1", port), timeout=2.0, retries=2, backoff_base=0.01
    )
    world = _drain_barrier(
        c, "t-gone", epoch=3, fallback_world=4, poll_s=0.01, max_wait_s=10.0
    )
    assert world == 4
    assert stats.FT_EVENTS.get("resize_barrier_master_lost") >= 1
    c.close()


# -- nightly: the full chaos_bench drill --------------------------------------


@pytest.mark.nightly
@pytest.mark.chaos
@pytest.mark.timeout(560)
def test_chaos_bench_resize_all_gates():
    """Heavy real-subprocess drill: every --mode resize gate (grow, shrink,
    reshard_kill resume, drain-barrier kill with exactly-once accounting)
    must pass in a fresh interpreter."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)  # the bench forces its own device count
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "chaos_bench.py"),
         "--mode", "resize", "--batches", "8"],
        env=env, capture_output=True, text=True, timeout=540,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout)
    assert result["all_gates_pass"], json.dumps(result, indent=1)
    assert result["grow"]["pass_avg_match"]
    assert result["shrink"]["pass_avg_match"]
    assert result["reshard_kill"]["resume_bitwise_vs_uninterrupted"]
    fleet = result["drain_barrier_kill"]
    assert fleet["exactly_once_tasks"] and fleet["coverage_complete"]
    assert fleet["barrier_exercised"]
