"""Op-level tests: conv/pool vs numpy reference, sequence ops vs per-example loops.

This is the analog of the reference's CPU-vs-GPU compare idiom
(paddle/math/tests/test_matrixCompare.cpp; function/*OpTest.cpp) — here numpy
loops are the oracle for the XLA lowering."""

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import conv as conv_ops
from paddle_tpu.ops import sequence as seq_ops


def _np_conv2d(x, w, stride, pad):
    b, h, wid, cin = x.shape
    kh, kw, _, cout = w.shape
    xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wid + 2 * pad - kw) // stride + 1
    out = np.zeros((b, oh, ow, cout), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, i * stride : i * stride + kh, j * stride : j * stride + kw, :]
            out[:, i, j, :] = np.tensordot(patch, w, axes=([1, 2, 3], [0, 1, 2]))
    return out


def test_conv2d_matches_numpy(np_rng):
    x = np_rng.randn(2, 8, 8, 3).astype(np.float32)
    w = np_rng.randn(3, 3, 3, 5).astype(np.float32)
    got = np.asarray(conv_ops.conv2d(x, w, stride=2, padding=1))
    want = _np_conv2d(x, w, 2, 1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_max_pool(np_rng):
    x = np_rng.randn(2, 6, 6, 4).astype(np.float32)
    got = np.asarray(conv_ops.max_pool2d(x, 2, 2))
    want = x.reshape(2, 3, 2, 3, 2, 4).max(axis=(2, 4))
    np.testing.assert_allclose(got, want)


def test_avg_pool_exclusive_padding(np_rng):
    x = np.ones((1, 4, 4, 1), np.float32)
    got = np.asarray(conv_ops.avg_pool2d(x, 3, 2, padding=1, exclusive=True))
    # with exclusive counting every window averages ones → 1.0 everywhere
    np.testing.assert_allclose(got, np.ones_like(got))


def test_conv_transpose_shape(np_rng):
    x = np_rng.randn(2, 4, 4, 8).astype(np.float32)
    w = np_rng.randn(4, 4, 16, 8).astype(np.float32)
    out = conv_ops.conv2d_transpose(x, w, stride=2, padding=1)
    assert out.shape == (2, 8, 8, 16)


def test_seq_pooling_vs_loop(np_rng):
    x = np_rng.randn(3, 7, 4).astype(np.float32)
    lengths = np.array([3, 7, 1], np.int32)
    for fn, red in [
        (seq_ops.seq_sum, lambda v: v.sum(0)),
        (seq_ops.seq_mean, lambda v: v.mean(0)),
        (seq_ops.seq_max, lambda v: v.max(0)),
    ]:
        got = np.asarray(fn(jnp.asarray(x), jnp.asarray(lengths)))
        want = np.stack([red(x[i, : lengths[i]]) for i in range(3)])
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_seq_last_first(np_rng):
    x = np_rng.randn(3, 5, 2).astype(np.float32)
    lengths = np.array([2, 5, 1], np.int32)
    got = np.asarray(seq_ops.seq_last(jnp.asarray(x), jnp.asarray(lengths)))
    want = np.stack([x[i, lengths[i] - 1] for i in range(3)])
    np.testing.assert_allclose(got, want)
    np.testing.assert_allclose(
        np.asarray(seq_ops.seq_first(jnp.asarray(x))), x[:, 0]
    )


def test_seq_softmax(np_rng):
    x = np_rng.randn(2, 6).astype(np.float32)
    lengths = np.array([4, 6], np.int32)
    got = np.asarray(seq_ops.seq_softmax(jnp.asarray(x), jnp.asarray(lengths)))
    assert got[0, 4:].sum() == 0
    np.testing.assert_allclose(got.sum(-1), [1.0, 1.0], rtol=1e-5)


def test_context_projection(np_rng):
    x = np_rng.randn(2, 5, 3).astype(np.float32)
    lengths = np.array([3, 5], np.int32)
    got = np.asarray(
        seq_ops.context_projection(jnp.asarray(x), jnp.asarray(lengths), -1, 3)
    )
    assert got.shape == (2, 5, 9)
    # middle block is x itself (masked beyond length)
    np.testing.assert_allclose(got[1, :, 3:6], x[1])
    # first block at t=0 is zeros (no left context)
    np.testing.assert_allclose(got[:, 0, 0:3], 0)
    # right context beyond sequence end is zero for the short sequence
    np.testing.assert_allclose(got[0, 2, 6:9], 0)


def test_bilinear_resize():
    x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
    out = conv_ops.bilinear_resize(x, 8, 8)
    assert out.shape == (1, 8, 8, 1)


def test_fused_batch_norm_matches_autodiff_oracle():
    """ops/normalization.py custom VJP vs plain-jnp autodiff in f32."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import normalization as N

    rs = np.random.RandomState(7)
    x = rs.randn(8, 5, 5, 6).astype(np.float32) * 2 + 1.5
    gamma = rs.randn(6).astype(np.float32) * 0.5 + 1.0
    beta = rs.randn(6).astype(np.float32)
    eps = 1e-5

    def oracle(x, g, b):
        axes = (0, 1, 2)
        m = jnp.mean(x, axis=axes)
        v = jnp.var(x, axis=axes)
        y = (x - m) * jax.lax.rsqrt(v + eps) * g + b
        return y

    def loss_fused(args):
        y, _, _ = N.batch_norm_train(*args, eps)
        return jnp.sum(jnp.sin(y))

    def loss_oracle(args):
        return jnp.sum(jnp.sin(oracle(*args)))

    y_f, m_f, v_f = N.batch_norm_train(x, gamma, beta, eps)
    np.testing.assert_allclose(np.asarray(y_f), np.asarray(oracle(x, gamma, beta)),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(m_f), x.mean((0, 1, 2)), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_f), x.var((0, 1, 2)), rtol=1e-3, atol=1e-4)

    g1 = jax.grad(loss_fused)((x, gamma, beta))
    g2 = jax.grad(loss_oracle)((x, gamma, beta))
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-3, atol=2e-3)

    # inference path
    y_i = N.batch_norm_inference(x, gamma, beta, m_f, v_f, eps)
    np.testing.assert_allclose(np.asarray(y_i), np.asarray(y_f), rtol=2e-3, atol=2e-3)


def test_softmax_xent_matches_log_softmax_oracle():
    """Fused big-vocab CE (ops/xent.py) vs the naive f32 log_softmax path:
    value and gradient, in f32 exactly and in bf16 at bf16 tolerance."""
    import jax
    from paddle_tpu.ops import xent as xent_ops

    rng = np.random.RandomState(7)
    n, v = 32, 97
    logits = rng.randn(n, v).astype(np.float32) * 3.0
    labels = rng.randint(0, v, n).astype(np.int32)

    def oracle(x, y):
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]

    got = xent_ops.softmax_xent_with_logits(jnp.asarray(logits), jnp.asarray(labels))
    want = oracle(jnp.asarray(logits), jnp.asarray(labels))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)

    g_got = jax.grad(lambda x: xent_ops.softmax_xent_with_logits(x, jnp.asarray(labels)).sum())(
        jnp.asarray(logits)
    )
    g_want = jax.grad(lambda x: oracle(x, jnp.asarray(labels)).sum())(jnp.asarray(logits))
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want), rtol=1e-5, atol=1e-6)

    # bf16 logits: big tensors stay bf16 end-to-end, loss still finite/close
    lb = jnp.asarray(logits, jnp.bfloat16)
    got16 = xent_ops.softmax_xent_with_logits(lb, jnp.asarray(labels))
    assert got16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got16), np.asarray(want), rtol=5e-2, atol=5e-2)
    g16 = jax.grad(lambda x: xent_ops.softmax_xent_with_logits(x, jnp.asarray(labels)).sum())(lb)
    assert g16.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(g16, np.float32), np.asarray(g_want), rtol=5e-2, atol=5e-2
    )
