"""Structured losses vs brute-force oracles.

The reference validates CTC/CRF with dedicated grad tests
(gserver/tests/test_CRFLayerGrad.cpp, test_LinearChainCRF.cpp,
test_WarpCTCLayer.cpp comparing warp-ctc vs LinearChainCTC). Here the oracle
is exhaustive path enumeration on tiny instances, and jax.grad replaces the
hand-written backward."""

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops


# --------------------------------------------------------------------------
# CTC
# --------------------------------------------------------------------------


def _brute_ctc_nll(logits, labels, blank=0):
    """-log p(labels) by enumerating all C^T alignment paths."""
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    t, c = logp.shape

    def collapse(path):
        out = []
        prev = None
        for p in path:
            if p != prev:
                prev = p
                if p != blank:
                    out.append(p)
            # repeated symbol collapses; blank resets prev? No: standard CTC
            # collapse removes repeats THEN blanks; track prev including blank.
        return tuple(out)

    total = -np.inf
    for path in itertools.product(range(c), repeat=t):
        if collapse(path) == tuple(labels):
            lp = sum(logp[i, p] for i, p in enumerate(path))
            total = np.logaddexp(total, lp)
    return -total


@pytest.mark.parametrize("labels", [[1], [1, 2], [1, 1], [2, 1, 2]])
def test_ctc_matches_brute_force(np_rng, labels):
    t, c = 4, 3
    logits = np_rng.randn(1, t, c).astype(np.float32)
    want = _brute_ctc_nll(logits[0], labels)
    lab = np.full((1, 3), 0, np.int32)
    lab[0, : len(labels)] = labels
    got = float(
        ctc_ops.ctc_loss(
            jnp.asarray(logits),
            jnp.array([t]),
            jnp.asarray(lab),
            jnp.array([len(labels)]),
        )[0]
    )
    assert math.isfinite(got)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ctc_batch_and_length_masking(np_rng):
    """Padded batch entries must match their standalone computation."""
    logits = np_rng.randn(2, 6, 4).astype(np.float32)
    labels = np.array([[1, 2, 0], [3, 0, 0]], np.int32)
    llens = np.array([3, 1])
    flens = np.array([6, 4])
    batch = np.asarray(
        ctc_ops.ctc_loss(
            jnp.asarray(logits), jnp.asarray(flens), jnp.asarray(labels), jnp.asarray(llens)
        )
    )
    solo1 = _brute_ctc_nll(logits[1, :4], [3])
    np.testing.assert_allclose(batch[1], solo1, rtol=1e-4, atol=1e-4)


def test_ctc_grad_finite(np_rng):
    logits = jnp.asarray(np_rng.randn(2, 5, 4).astype(np.float32))

    def f(lg):
        return jnp.sum(
            ctc_ops.ctc_loss(
                lg,
                jnp.array([5, 4]),
                jnp.array([[1, 2], [3, 0]]),
                jnp.array([2, 1]),
            )
        )

    g = jax.grad(f)(logits)
    assert np.isfinite(np.asarray(g)).all()


def test_ctc_greedy_decode():
    # frames argmax to: [1, 1, 0, 2, 2] → collapse → [1, 2]
    t, c = 5, 3
    logits = np.zeros((1, t, c), np.float32)
    for i, sym in enumerate([1, 1, 0, 2, 2]):
        logits[0, i, sym] = 5.0
    out = np.asarray(
        ctc_ops.ctc_greedy_decode(jnp.asarray(logits), jnp.array([t]))
    )[0]
    assert list(out[out >= 0]) == [1, 2]


# --------------------------------------------------------------------------
# CRF
# --------------------------------------------------------------------------


def _brute_crf_nll(emissions, labels, w):
    a, b, trans = w[0], w[1], w[2:]
    t, c = emissions.shape

    def score(tags):
        s = a[tags[0]] + b[tags[-1]] + sum(emissions[i, tg] for i, tg in enumerate(tags))
        s += sum(trans[tags[i], tags[i + 1]] for i in range(t - 1))
        return s

    logz = -np.inf
    for tags in itertools.product(range(c), repeat=t):
        logz = np.logaddexp(logz, score(tags))
    return logz - score(labels)


def test_crf_nll_matches_brute_force(np_rng):
    t, c = 4, 3
    emissions = np_rng.randn(1, t, c).astype(np.float32)
    w = np_rng.randn(c + 2, c).astype(np.float32)
    labels = np.array([[0, 2, 1, 1]], np.int32)
    got = float(
        crf_ops.crf_nll(
            jnp.asarray(emissions), jnp.array([t]), jnp.asarray(labels), jnp.asarray(w)
        )[0]
    )
    want = _brute_crf_nll(emissions[0], labels[0], w)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_crf_nll_respects_lengths(np_rng):
    t, c = 5, 3
    emissions = np_rng.randn(1, t, c).astype(np.float32)
    w = np_rng.randn(c + 2, c).astype(np.float32)
    labels = np.array([[1, 0, 2, 0, 0]], np.int32)
    got = float(
        crf_ops.crf_nll(
            jnp.asarray(emissions), jnp.array([3]), jnp.asarray(labels), jnp.asarray(w)
        )[0]
    )
    want = _brute_crf_nll(emissions[0, :3], labels[0, :3], w)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_crf_decode_matches_brute_force(np_rng):
    t, c = 4, 3
    emissions = np_rng.randn(1, t, c).astype(np.float32)
    w = np_rng.randn(c + 2, c).astype(np.float32)
    a, b, trans = w[0], w[1], w[2:]

    best, best_s = None, -np.inf
    for tags in itertools.product(range(c), repeat=t):
        s = a[tags[0]] + b[tags[-1]]
        s += sum(emissions[0, i, tg] for i, tg in enumerate(tags))
        s += sum(trans[tags[i], tags[i + 1]] for i in range(t - 1))
        if s > best_s:
            best, best_s = tags, s
    got = np.asarray(
        crf_ops.crf_decode(jnp.asarray(emissions), jnp.array([t]), jnp.asarray(w))
    )[0]
    assert tuple(got) == best


def test_crf_grad_finite(np_rng):
    emissions = jnp.asarray(np_rng.randn(2, 4, 3).astype(np.float32))
    w = jnp.asarray(np_rng.randn(5, 3).astype(np.float32))
    labels = jnp.array([[0, 1, 2, 1], [2, 2, 0, 0]])
    lens = jnp.array([4, 2])

    def f(e, ww):
        return jnp.sum(crf_ops.crf_nll(e, lens, labels, ww))

    ge, gw = jax.grad(f, argnums=(0, 1))(emissions, w)
    assert np.isfinite(np.asarray(ge)).all() and np.isfinite(np.asarray(gw)).all()


# --------------------------------------------------------------------------
# Layer wrappers: NCE, hsigmoid, lambda, CTC/CRF-in-graph
# --------------------------------------------------------------------------


def _one_layer_net(cost_layer):
    from paddle_tpu.nn.graph import Network

    return Network([cost_layer])


def test_nce_and_hsigmoid_train_decrease_loss(np_rng):
    import jax

    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import struct_costs as S
    from paddle_tpu.nn.graph import Network, reset_name_scope

    for make in (
        lambda x, y: S.NCECost(x, y, num_classes=11, num_neg_samples=5),
        lambda x, y: S.HierarchicalSigmoid(x, y, num_classes=11),
    ):
        reset_name_scope()
        x = L.Data("x", shape=(8,))
        y = L.Data("y", shape=())
        cost = make(L.Fc(x, 16, act="relu", name="h"), y)
        net = Network([cost])
        batch = {
            "x": np_rng.randn(16, 8).astype(np.float32),
            "y": np_rng.randint(0, 11, 16),
        }
        params, states = net.init(jax.random.PRNGKey(0), batch)

        def loss_fn(p, rng):
            outs, _ = net.apply(p, states, batch, train=True, rng=rng)
            return outs[cost.name].value

        g = jax.grad(loss_fn)(params, jax.random.PRNGKey(1))
        l0 = float(loss_fn(params, jax.random.PRNGKey(2)))
        stepped = jax.tree.map(lambda p_, g_: p_ - 0.5 * g_, params, g)
        l1 = float(loss_fn(stepped, jax.random.PRNGKey(2)))
        assert math.isfinite(l0) and l1 < l0


def test_hsigmoid_eval_consistency(np_rng):
    """hsigmoid loss must be a valid NLL: sum over classes of p(class) == 1."""
    import jax

    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import struct_costs as S
    from paddle_tpu.nn.graph import Network, reset_name_scope

    n_cls = 8
    reset_name_scope()
    x = L.Data("x", shape=(4,))
    y = L.Data("y", shape=())
    cost = S.HierarchicalSigmoid(x, y, num_classes=n_cls, name="hs")
    net = Network([cost])
    xv = np_rng.randn(1, 4).astype(np.float32)
    params, states = net.init(
        jax.random.PRNGKey(0), {"x": xv, "y": np.array([0])}
    )
    total = 0.0
    for cls in range(n_cls):
        outs, _ = net.apply(params, states, {"x": xv, "y": np.array([cls])})
        total += math.exp(-float(outs[cost.name].value))
    np.testing.assert_allclose(total, 1.0, rtol=1e-4)


def test_crf_layer_in_graph(np_rng):
    import jax

    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import struct_costs as S
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    x = L.Data("x", shape=(None, 6))
    y = L.Data("y", shape=(None,))
    emit = L.Fc(x, 4, act=None, name="emit")
    cost = S.CRFCost(emit, y, size=4, name="crf")
    net = Network([cost])
    batch = {
        "x": np_rng.randn(3, 5, 6).astype(np.float32),
        "x.lengths": np.array([5, 3, 4]),
        "y": np_rng.randint(0, 4, (3, 5)),
        "y.lengths": np.array([5, 3, 4]),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch, train=True)
    assert math.isfinite(float(outs["crf"].value))


def test_edit_distance_evaluator():
    from paddle_tpu.metrics.evaluators import CTCErrorEvaluator, _edit_distance

    assert _edit_distance([1, 2, 3], [1, 3]) == 1
    assert _edit_distance([], [1, 2]) == 2
    assert _edit_distance([1, 2], [1, 2]) == 0

    ev = CTCErrorEvaluator()
    ev.start()
    ev.update(
        decoded=np.array([[1, 2, -1], [3, -1, -1]]),
        label=np.array([[1, 2, 3], [3, 0, 0]]),
        label_lengths=np.array([3, 1]),
    )
    np.testing.assert_allclose(ev.finish(), 1 / 4)
