"""North-star compatibility: UNMODIFIED reference config scripts parse and
train against the `paddle` compat namespace (VERDICT round-1 item #2).

Configs under test are the reference's own files (read-only mount):
- benchmark/paddle/image/{smallnet_mnist_cifar,alexnet,vgg,googlenet}.py —
  parse AND train (their provider.py generates synthetic data; smallnet runs
  a full pass, the ImageNet-sized ones a few batches on CPU).
- v1_api_demo/quick_start/trainer_config.{lr,cnn,lstm}.py — parse, with the
  dictionary stubbed (their providers need downloaded data).
"""

import os

import numpy as np
import pytest

REF = "/root/reference"
IMG = f"{REF}/benchmark/paddle/image"
QS = f"{REF}/v1_api_demo/quick_start"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference tree not mounted"
)


def _parse(path, args=""):
    from paddle_tpu.config import parse_config

    return parse_config(path, args)


def _train_batches(pc, n_batches, batch_size):
    """Build the real provider-fed pipeline the CLI uses and run n batches."""
    from paddle_tpu.cli import _make_reader, bind_provider_types
    from paddle_tpu.config import build_optimizer
    from paddle_tpu.trainer import SGDTrainer

    dc = pc.trainer_config.data_config
    feeding = bind_provider_types(pc.topology, dc)
    feeder = pc.topology.make_feeder(feeding)
    reader = _make_reader(dc, batch_size)
    bundle = build_optimizer(pc.trainer_config.opt_config)
    trainer = SGDTrainer(pc.outputs, bundle.optimizer, schedule=bundle.schedule)

    costs = []
    it = iter(reader())
    for _ in range(n_batches):
        batch = feeder(next(it))
        if trainer.state is None:
            trainer.init_state(batch)
            step = trainer._make_step()
        trainer.state, cost, _ = step(trainer.state, batch)
        costs.append(float(cost))
    return costs


@pytest.fixture()
def bench_cwd(tmp_path, monkeypatch):
    # the benchmark providers iterate files named in train.list
    (tmp_path / "train.list").write_text("dummy\n")
    monkeypatch.chdir(tmp_path)
    return tmp_path


def test_smallnet_parses_and_trains_full_pass(bench_cwd):
    pc = _parse(f"{IMG}/smallnet_mnist_cifar.py", "batch_size=64")
    oc = pc.trainer_config.opt_config
    assert oc.batch_size == 64
    assert oc.learning_method == "momentum" and oc.momentum == 0.9
    assert oc.l2_weight_decay == pytest.approx(0.0005 * 64)
    # full pass: the provider yields 1024 synthetic samples
    costs = _train_batches(pc, 1024 // 64, 64)
    assert all(np.isfinite(c) for c in costs)
    assert costs[-1] < costs[0] + 0.5  # random data: just require stability


def test_alexnet_parses_and_trains(bench_cwd):
    pc = _parse(f"{IMG}/alexnet.py", "batch_size=4")
    costs = _train_batches(pc, 2, 4)
    assert all(np.isfinite(c) for c in costs)


def test_vgg16_parses_and_trains(bench_cwd):
    pc = _parse(f"{IMG}/vgg.py", "batch_size=2,layer_num=16")
    costs = _train_batches(pc, 2, 2)
    assert all(np.isfinite(c) for c in costs)


def test_googlenet_parses_and_trains(bench_cwd):
    pc = _parse(f"{IMG}/googlenet.py", "batch_size=2")
    # declaration order is (label, input) while the provider yields
    # (image, label) — binding must reconcile by declared size
    costs = _train_batches(pc, 2, 2)
    assert all(np.isfinite(c) for c in costs)


@pytest.fixture()
def qs_cwd(tmp_path, monkeypatch):
    (tmp_path / "data").mkdir()
    (tmp_path / "data" / "dict.txt").write_text(
        "".join(f"word{i}\t{i}\n" for i in range(30))
    )
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.mark.parametrize("cfg", ["lr", "cnn", "lstm"])
def test_quick_start_configs_parse(qs_cwd, cfg):
    pc = _parse(f"{QS}/trainer_config.{cfg}.py")
    assert pc.outputs, "no outputs declared"
    oc = pc.trainer_config.opt_config
    assert oc.batch_size == 128
    assert oc.learning_method == "adam"
    assert oc.gradient_clipping_threshold == 25
    assert oc.l2_weight_decay == pytest.approx(8e-4)
    # model config emitted (the serialized contract)
    assert pc.trainer_config.model_config.layers


def test_quick_start_lr_trains_with_synthetic_provider(qs_cwd, tmp_path):
    """The lr config trains once its provider is stubbed: feed ids + labels
    through the bound feeder directly."""
    pc = _parse(f"{QS}/trainer_config.lr.py")
    from paddle_tpu.config import build_optimizer
    from paddle_tpu.trainer import SGDTrainer

    feeder = pc.topology.make_feeder()
    rs = np.random.RandomState(0)
    samples = [
        {"word": rs.rand(30).astype(np.float32), "label": int(rs.randint(2))}
        for _ in range(64)
    ]
    bundle = build_optimizer(pc.trainer_config.opt_config)
    trainer = SGDTrainer(pc.outputs, bundle.optimizer, schedule=bundle.schedule)
    batch = feeder(samples[:32])
    trainer.init_state(batch)
    step = trainer._make_step()
    state = trainer.state
    for _ in range(5):
        state, cost, _ = step(state, batch)
    assert np.isfinite(float(cost))
