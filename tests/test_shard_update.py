"""ISSUE 5 + 14: ZeRO-1/2/3 sharded weight update + compressed collectives.

Equivalence contract (the paper's point — sharding the update is free):
  * SGD (plain + momentum) under shard_update=True applies BITWISE the same
    updates as the replicated updater on the CPU mesh. The tests pin
    power-of-two lr/momentum so the scale products are IEEE-exact — XLA
    freely FMA-contracts `p - lr*g` and two structurally different programs
    may contract differently, which for exact products cannot change a bit.
  * Adam matches to tight tolerance (sqrt/div chains contract).

Plus: per-chip opt-state bytes shrink ~N x, trailing batches pad+mask
instead of dropping, checkpoints round-trip across shard_update on/off
(canonical layout on disk), int8 error-feedback keeps LeNet converging, and
the sharded update composes with K-step fused dispatch, the device-resident
divergence guard, and async checkpoint auto-resume."""

import os

import jax
import numpy as np
import pytest

from paddle_tpu.core import stats
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import SAMPLE_MASK_KEY, reset_name_scope
from paddle_tpu.optim import SGD, Adam
from paddle_tpu.parallel import DataParallel, ShardedUpdater, make_mesh
from paddle_tpu.parallel import compression as compression_mod
from paddle_tpu.trainer import SGDTrainer
from paddle_tpu.trainer.events import EndPass


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


DIM, CLASSES = 16, 4


def _build():
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 48, act="relu", name="h")
    logits = L.Fc(h, CLASSES, act=None, name="out")
    return C.ClassificationCost(logits, lbl, name="cost")


def _data(n=96, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, DIM).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int32) + 2 * (x[:, 0] > 0).astype(np.int32)
    return x, y


def _reader(x, y, bs=32):
    def reader():
        for i in range(0, len(x), bs):
            yield {"x": x[i:i + bs], "label": y[i:i + bs]}

    return reader


def _train(n_dev, shard, optimizer=None, compression=None, passes=2,
           batch_size=32, n_samples=96, **train_kw):
    reset_name_scope()
    cost = _build()
    dp = DataParallel(make_mesh({"data": n_dev}))
    tr = SGDTrainer(
        cost,
        optimizer or SGD(learning_rate=0.125, momentum=0.5),
        parallel=dp, seed=5, shard_update=shard, grad_compression=compression,
    )
    x, y = _data(n_samples)
    tr.train(_reader(x, y, batch_size), num_passes=passes, **train_kw)
    return tr


def _params(tr):
    # canonical view so zero3's flat-sharded params compare like any other
    canonical = tr.updater.params_to_canonical(tr.state["params"])
    return {k: np.asarray(v) for k, v in canonical.items()}


def _assert_bitwise(a, b, what=""):
    for k in a:
        assert np.array_equal(
            a[k].view(np.uint32), b[k].view(np.uint32)
        ), f"{what}: param {k} differs (max abs {np.abs(a[k] - b[k]).max()})"


# -- equivalence vs the replicated updater -----------------------------------


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_sgd_bitwise_equal_replicated(n_dev):
    p_rep = _params(_train(n_dev, shard=False))
    p_sh = _params(_train(n_dev, shard=True))
    _assert_bitwise(p_rep, p_sh, f"SGD n_dev={n_dev}")


def test_sgd_plain_bitwise_equal():
    opt = SGD(learning_rate=0.0625)  # no momentum: empty slots path
    p_rep = _params(_train(4, shard=False, optimizer=opt))
    reset_name_scope()
    p_sh = _params(_train(4, shard=True, optimizer=SGD(learning_rate=0.0625)))
    _assert_bitwise(p_rep, p_sh, "plain SGD")


def test_adam_allclose_replicated():
    tr_rep = _train(4, shard=False, optimizer=Adam(learning_rate=1e-3))
    tr_sh = _train(4, shard=True, optimizer=Adam(learning_rate=1e-3))
    p_rep, p_sh = _params(tr_rep), _params(tr_sh)
    for k in p_rep:
        np.testing.assert_allclose(p_rep[k], p_sh[k], rtol=1e-5, atol=1e-7)
    # Adam moments too: compare in the canonical layout
    opt_rep = tr_rep.updater.to_canonical(tr_rep.state["opt"])
    opt_sh = tr_sh.updater.to_canonical(tr_sh.state["opt"])
    for k, slots in opt_rep["slots"].items():
        for s_rep, s_sh in zip(slots, opt_sh["slots"][k]):
            np.testing.assert_allclose(
                np.asarray(s_rep), np.asarray(s_sh), rtol=1e-4, atol=1e-7
            )


def test_opt_state_bytes_shrink_n_times():
    tr_rep = _train(4, shard=False, passes=1)
    tr_sh = _train(4, shard=True, passes=1)
    rep = stats.per_chip_tree_bytes(tr_rep.state["opt"])
    sh = stats.per_chip_tree_bytes(tr_sh.state["opt"])
    # ~N x up to flat-chunk padding of small leaves
    assert rep >= 3.2 * sh, (rep, sh)
    # and the collective-bytes model: sharded none == replicated all-reduce,
    # bf16 halves it
    assert (
        tr_sh.updater.collective_bytes_per_step()
        == tr_rep.updater.collective_bytes_per_step()
    )
    tr_bf = _train(4, shard=True, compression="bf16", passes=1)
    assert (
        2 * tr_bf.updater.collective_bytes_per_step()
        <= tr_rep.updater.collective_bytes_per_step()
    )


# -- compression --------------------------------------------------------------


def test_bf16_compression_close_and_converges():
    tr = _train(4, shard=True, compression="bf16")
    p_bf = _params(tr)
    p_rep = _params(_train(4, shard=False))
    for k in p_rep:
        np.testing.assert_allclose(p_bf[k], p_rep[k], rtol=0.05, atol=5e-3)


def test_int8_block_quantize_roundtrip():
    import jax.numpy as jnp

    from paddle_tpu.parallel.compression import (
        _block_dequantize, _block_quantize, BLOCK,
    )

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 2 * BLOCK).astype(np.float32))
    q, scale = _block_quantize(x)
    assert q.dtype == jnp.int8 and scale.shape == (4, 2)
    err = np.abs(np.asarray(_block_dequantize(q, scale)) - np.asarray(x))
    # block-scaled int8: error bounded by scale/2 per element
    assert err.max() <= float(np.asarray(scale).max()) * 0.51


def test_int8_error_feedback_residual_carried():
    tr = _train(2, shard=True, compression="int8", passes=1)
    assert "ef" in tr.state["opt"], "error-feedback residual missing"
    ef = tr.state["opt"]["ef"]
    assert any(np.abs(np.asarray(e)).max() > 0 for e in ef.values()), (
        "EF residual never updated — quantization error is being dropped"
    )


@pytest.mark.slow
def test_int8_lenet_convergence_smoke():
    """Error-feedback int8 on the LeNet config: cost must still drop."""
    from paddle_tpu.models import lenet

    reset_name_scope()
    _img, _lbl, _logits, cost = lenet(num_classes=4)
    dp = DataParallel(make_mesh({"data": 2}))
    tr = SGDTrainer(
        cost, SGD(learning_rate=0.03125, momentum=0.5), parallel=dp, seed=0,
        shard_update=True, grad_compression="int8",
    )
    rs = np.random.RandomState(1)
    n = 64
    x = rs.rand(n, 28, 28, 1).astype(np.float32)
    y = rs.randint(0, 4, n)
    # learnable rule: brightness quadrant
    y = (x.mean(axis=(1, 2, 3)) * 4).astype(np.int32).clip(0, 3)
    costs = []

    def handler(e):
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])

    def reader():
        for i in range(0, n, 16):
            yield {"pixel": x[i:i + 16], "label": y[i:i + 16]}

    tr.train(reader, num_passes=6, event_handler=handler)
    assert costs[-1] < costs[0] * 0.9, costs


# -- trailing-batch padding ----------------------------------------------------


def test_trailing_batch_padded_not_dropped():
    """88 samples / batch 32 → trailing 24 on a 16-wide mesh... use 4-dev
    mesh with trailing 24 % 4 == 0? pick sizes so the trailer is indivisible:
    90 samples → batches 32,32,26; 26 % 4 != 0 → padded to 28."""
    before = stats.DATA_EVENTS.get("padded_batches")
    metrics = {}

    def handler(e):
        if isinstance(e, EndPass):
            metrics.update(e.metrics)

    tr = _train(4, shard=False, passes=1, n_samples=90,
                event_handler=handler)
    assert stats.DATA_EVENTS.get("padded_batches") == before + 1
    assert metrics["padded_batches"] == 1
    assert metrics["batches"] == 3, "trailing batch must train, not drop"
    # samples counter counts REAL rows only (mask-sum, not padded size)
    assert int(tr.state["samples"]) == 90


def test_padded_cost_matches_unsharded():
    """The padded trailing batch's masked cost equals the unpadded cost the
    single-device run computes — pass averages match the unsharded run."""
    x, y = _data(90)
    costs = {}
    for tag, n_dev in [("single", 1), ("mesh", 4)]:
        reset_name_scope()
        cost = _build()
        dp = DataParallel(make_mesh({"data": n_dev}))
        tr = SGDTrainer(cost, SGD(learning_rate=0.125), parallel=dp, seed=5)
        got = []

        def handler(e):
            if isinstance(e, EndPass):
                got.append(e.metrics)

        tr.train(_reader(x, y), num_passes=1, event_handler=handler)
        costs[tag] = got[0]
    assert costs["mesh"]["batches"] == costs["single"]["batches"] == 3
    np.testing.assert_allclose(
        costs["mesh"]["avg_cost"], costs["single"]["avg_cost"],
        rtol=2e-5, atol=1e-7,
    )


def test_prefetcher_pads_trailing_batch():
    """DevicePrefetcher pads the indivisible trailer instead of dropping it
    — the device-resident sample stream matches the unsharded reader."""
    x, y = _data(90)  # trailing 26 % 4 != 0 → padded to 28
    from paddle_tpu.data.pipeline import DevicePrefetcher

    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 4}))
    before = stats.DATA_EVENTS.get("padded_batches")
    pf = DevicePrefetcher(_reader(x, y), parallel=dp, prefetch_depth=2)
    batches = list(pf())
    assert stats.DATA_EVENTS.get("padded_batches") == before + 1
    assert len(batches) == 3
    assert batches[-1]["x"].shape[0] == 28
    assert SAMPLE_MASK_KEY in batches[-1]
    mask = np.asarray(batches[-1][SAMPLE_MASK_KEY])
    assert mask.sum() == 26
    # and the trainer consumes the padded device batch end-to-end
    reset_name_scope()
    cost = _build()
    tr = SGDTrainer(cost, SGD(learning_rate=0.125), parallel=dp, seed=5)
    tr.train(DevicePrefetcher(_reader(x, y), parallel=dp), num_passes=1)
    assert int(tr.state["samples"]) == 90


def test_struct_cost_masked_mean():
    """Struct costs (CTC/CRF/NCE/...) reduce through _mean_over_examples —
    padded rows must drop out of the mean exactly like dense costs."""
    import jax.numpy as jnp

    from paddle_tpu.nn.graph import Context
    from paddle_tpu.nn.struct_costs import _mean_over_examples

    ctx = Context("apply", {}, {}, None, train=True)
    per = jnp.asarray([1.0, 2.0, 3.0, 99.0])  # row 3 is padding
    assert float(_mean_over_examples(ctx, per)) == pytest.approx(105.0 / 4)
    ctx.sample_mask = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    assert float(_mean_over_examples(ctx, per)) == pytest.approx(2.0)
    # per-timestep flattening: mask repeats per step
    per_t = jnp.asarray([1.0, 1.0, 2.0, 2.0, 3.0, 3.0, 99.0, 99.0])
    assert float(_mean_over_examples(ctx, per_t)) == pytest.approx(2.0)
    # unmaskable layout (rows don't divide the mask): falls back to plain mean
    per_odd = jnp.asarray([1.0, 2.0, 3.0])
    assert float(_mean_over_examples(ctx, per_odd)) == pytest.approx(2.0)


def test_pad_batch_helper():
    dp = DataParallel(make_mesh({"data": 4}))
    batch = {"x": np.arange(12, dtype=np.float32).reshape(6, 2),
             "label": np.arange(6, dtype=np.int32)}
    padded, n_pad = dp.pad_batch(batch)
    assert n_pad == 2
    assert padded["x"].shape == (8, 2) and padded["label"].shape == (8,)
    np.testing.assert_array_equal(padded["x"][6:], [[10, 11], [10, 11]])
    np.testing.assert_array_equal(
        padded[SAMPLE_MASK_KEY], [1, 1, 1, 1, 1, 1, 0, 0]
    )
    already, n = dp.pad_batch({"x": np.zeros((8, 2), np.float32)})
    assert n == 0 and SAMPLE_MASK_KEY not in already


# -- checkpoint round-trip across updater layouts ------------------------------


def _ckpt_roundtrip(tmp_path, save_shard, load_shard, optimizer_fn,
                    async_=False):
    x, y = _data(96)
    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 4}))
    tr1 = SGDTrainer(_build(), optimizer_fn(), parallel=dp, seed=5,
                     shard_update=save_shard)
    tr1.train(_reader(x, y), num_passes=1, save_dir=str(tmp_path),
              async_checkpoint=async_)
    tr1.checkpoint_wait()

    # fresh trainer in the OTHER layout resumes from the same checkpoint
    reset_name_scope()
    dp2 = DataParallel(make_mesh({"data": 4}))
    tr2 = SGDTrainer(_build(), optimizer_fn(), parallel=dp2, seed=5,
                     shard_update=load_shard)
    tr2.train(_reader(x, y), num_passes=2, save_dir=str(tmp_path),
              auto_resume=True, async_checkpoint=async_)
    tr2.checkpoint_wait()

    # reference: the same two passes straight through in the LOAD layout
    reset_name_scope()
    dp3 = DataParallel(make_mesh({"data": 4}))
    tr3 = SGDTrainer(_build(), optimizer_fn(), parallel=dp3, seed=5,
                     shard_update=load_shard)
    tr3.train(_reader(x, y), num_passes=2)
    return tr2, tr3


@pytest.mark.parametrize("save_shard,load_shard", [(True, False), (False, True)])
def test_checkpoint_roundtrip_across_layouts_sgd(tmp_path, save_shard, load_shard):
    tr2, tr3 = _ckpt_roundtrip(
        tmp_path, save_shard, load_shard,
        lambda: SGD(learning_rate=0.125, momentum=0.5),
    )
    _assert_bitwise(_params(tr3), _params(tr2),
                    f"resume {save_shard}->{load_shard}")
    # momentum slots too (canonical view)
    c2 = tr2.updater.to_canonical(tr2.state["opt"])
    c3 = tr3.updater.to_canonical(tr3.state["opt"])
    for k, slots in c3["slots"].items():
        for a, b in zip(slots, c2["slots"][k]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), k


def test_checkpoint_roundtrip_adam_moments(tmp_path):
    tr2, tr3 = _ckpt_roundtrip(
        tmp_path, True, False, lambda: Adam(learning_rate=1e-3),
    )
    p2, p3 = _params(tr2), _params(tr3)
    for k in p3:
        np.testing.assert_allclose(p3[k], p2[k], rtol=1e-5, atol=1e-7)
    c2 = tr2.updater.to_canonical(tr2.state["opt"])
    c3 = tr3.updater.to_canonical(tr3.state["opt"])
    for k, slots in c3["slots"].items():
        for a, b in zip(slots, c2["slots"][k]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
            )


def test_checkpoint_roundtrip_async_sharded(tmp_path):
    """The async-checkpointer path: sharded opt state is gathered to the
    canonical layout BEFORE the non-blocking host fetch, and a sharded
    trainer auto-resumes from it bitwise."""
    tr2, tr3 = _ckpt_roundtrip(
        tmp_path, True, True,
        lambda: SGD(learning_rate=0.125, momentum=0.5), async_=True,
    )
    _assert_bitwise(_params(tr3), _params(tr2), "async sharded resume")


def test_cross_world_size_load_is_exact_and_records_world(tmp_path):
    """The POSITIVE half of the world-size contract: canonical checkpoints
    are world-size-portable — a 2-chip sharded save resumes on a 4-chip
    sharded trainer with identical values — and the manifest records the
    writer's world size."""
    from paddle_tpu.trainer import checkpoint as ckpt_mod

    reset_name_scope()
    x, y = _data(64)
    dp = DataParallel(make_mesh({"data": 2}))
    tr1 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp, seed=5, shard_update=True)
    tr1.train(_reader(x, y), num_passes=1, save_dir=str(tmp_path))
    tr1.checkpoint_wait()
    assert ckpt_mod.pass_manifest(str(tmp_path), 0)["extra"]["world_size"] == 2

    reset_name_scope()
    dp4 = DataParallel(make_mesh({"data": 4}))
    tr2 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp4, seed=5, shard_update=True)
    tr2.init_state(dp4.shard_batch({"x": x[:32], "label": y[:32]}))
    tr2.load(str(tmp_path), 0)
    _assert_bitwise(_params(tr1), _params(tr2), "2->4 canonical load")
    c1 = tr1.updater.to_canonical(tr1.state["opt"])
    c2 = tr2.updater.to_canonical(tr2.state["opt"])
    for k, slots in c1["slots"].items():
        for a, b in zip(slots, c2["slots"][k]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), k


def test_mismatched_world_size_opt_state_fails_loudly(tmp_path):
    """The NEGATIVE half (ISSUE 8 satellite): an opt tree written as RAW
    per-shard state (bypassing the to_canonical seam — the pre-canonical /
    foreign-writer failure mode) must fail the resume with an error naming
    the expected vs found shapes and both world sizes. Before this contract,
    restore_tree silently kept freshly-initialized slots — a wrong resume
    that trained on, or crashed deep in jax."""
    from paddle_tpu.trainer import checkpoint as ckpt_mod

    reset_name_scope()
    x, y = _data(64)
    dp = DataParallel(make_mesh({"data": 4}))
    tr1 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp, seed=5, shard_update=True)
    tr1.train(_reader(x, y), num_passes=1)
    # write the RAW flat [4, chunk] slots, NOT the canonical layout
    ckpt_mod.save_pass(
        str(tmp_path), 0, tr1.state["params"], tr1.state["states"],
        {"opt": tr1.state["opt"]},
        extra_meta={"samples": 64, "world_size": 4},
    )

    reset_name_scope()
    dp2 = DataParallel(make_mesh({"data": 2}))
    tr2 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp2, seed=5, shard_update=True)
    tr2.init_state(dp2.shard_batch({"x": x[:32], "label": y[:32]}))
    with pytest.raises(ValueError) as ei:
        tr2.load(str(tmp_path), 0)
    msg = str(ei.value)
    assert "expected" in msg and "found" in msg
    assert "world_size=4" in msg and "world_size=2" in msg
    assert "to_canonical" in msg


def test_disjoint_key_opt_state_fails_loudly(tmp_path):
    """The shape guard's blind spot: a raw opt tree whose key PATHS don't
    overlap the canonical template at all (e.g. a foreign writer's naming)
    produces zero shape mismatches — every template leaf is simply missing
    from the checkpoint, and restore_tree silently keeps freshly-initialized
    slots. The missing-keys guard must turn that into the same loud error."""
    from paddle_tpu.trainer import checkpoint as ckpt_mod

    reset_name_scope()
    x, y = _data(64)
    dp = DataParallel(make_mesh({"data": 2}))
    tr1 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp, seed=5, shard_update=True)
    tr1.train(_reader(x, y), num_passes=1)
    # alien key layout: truthy opt tree, zero keys in common with canonical
    ckpt_mod.save_pass(
        str(tmp_path), 0, tr1.state["params"], tr1.state["states"],
        {"opt": {"alien_slot": np.zeros(3, np.float32)}},
        extra_meta={"samples": 64, "world_size": 4},
    )

    reset_name_scope()
    dp2 = DataParallel(make_mesh({"data": 2}))
    tr2 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp2, seed=5, shard_update=True)
    tr2.init_state(dp2.shard_batch({"x": x[:32], "label": y[:32]}))
    with pytest.raises(ValueError) as ei:
        tr2.load(str(tmp_path), 0)
    msg = str(ei.value)
    assert "no entry for" in msg
    assert "world_size=4" in msg and "world_size=2" in msg
    assert "to_canonical" in msg


def test_optimizer_structure_growth_still_resumes(tmp_path):
    """The POSITIVE half of the missing-keys guard: partial key overlap is
    the documented structure-change contract (docstring of load: 'optimizer
    slots (when the structure matches)'). A checkpoint saved before momentum
    was turned on must still resume — new slots start fresh with a warning,
    everything else (params, step counter) restores — instead of tripping
    the raw-per-shard error meant for zero-overlap foreign trees."""
    reset_name_scope()
    x, y = _data(64)
    tr1 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.0), seed=5)
    tr1.train(_reader(x, y), num_passes=1)
    tr1.save(str(tmp_path), 0)
    p1 = {k: np.array(v) for k, v in tr1.state["params"].items()}

    reset_name_scope()
    tr2 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5), seed=6)
    tr2.init_state({"x": x[:32], "label": y[:32]})
    tr2.load(str(tmp_path), 0)  # must not raise
    for k, v in tr2.state["params"].items():
        assert np.array_equal(np.asarray(v), p1[k]), k


# -- composition with the async execution runtime ------------------------------


def test_k_step_dispatch_composes():
    """shard_update under steps_per_dispatch=K applies the same updates."""
    p1 = _params(_train(4, shard=True, passes=1, steps_per_dispatch=1))
    p4 = _params(_train(4, shard=True, passes=1, steps_per_dispatch=3))
    _assert_bitwise(p1, p4, "K-fused sharded dispatch")


def test_divergence_guard_reverts_on_every_shard():
    """A poisoned batch under shard_update: the device-resident guard must
    revert params AND the sharded flat slots to pre-step values on every
    shard — the clean batches alone determine the result."""

    def run(poison):
        reset_name_scope()
        cost = _build()
        dp = DataParallel(make_mesh({"data": 4}))
        tr = SGDTrainer(
            cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp,
            seed=5, shard_update=True, divergence_policy="skip_batch",
            guard_check_every=1,
        )
        x, y = _data(96)
        batches = [
            {"x": x[i:i + 32].copy(), "label": y[i:i + 32].copy()}
            for i in range(0, 96, 32)
        ]
        if poison:
            batches.insert(1, {
                "x": batches[0]["x"] * np.float32("nan"),
                "label": batches[0]["label"],
            })
        tr.train(lambda: iter(batches), num_passes=1)
        return tr

    tr_clean = run(poison=False)
    tr_poison = run(poison=True)
    assert stats.FT_EVENTS.get("divergence") >= 1
    _assert_bitwise(_params(tr_clean), _params(tr_poison), "guarded shard")
    # slots reverted too: canonical views must match bitwise
    c1 = tr_clean.updater.to_canonical(tr_clean.state["opt"])
    c2 = tr_poison.updater.to_canonical(tr_poison.state["opt"])
    for k, slots in c1["slots"].items():
        for a, b in zip(slots, c2["slots"][k]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), k


# -- API validation ------------------------------------------------------------


def test_shard_update_requires_parallel():
    with pytest.raises(ValueError, match="DataParallel"):
        SGDTrainer(_build(), SGD(), shard_update=True)


def test_compression_requires_shard_update():
    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 2}))
    with pytest.raises(ValueError, match="shard_update"):
        SGDTrainer(_build(), SGD(), parallel=dp, grad_compression="bf16")


def test_shard_update_rejects_explicit_updater():
    """shard_update selects the built-in ShardedUpdater; combining it with
    an explicit updater= must fail loudly, not silently run replicated."""
    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 2}))
    opt = SGD()
    from paddle_tpu.parallel import IciAllReduceUpdater

    with pytest.raises(ValueError, match="updater"):
        SGDTrainer(_build(), opt, parallel=dp,
                   updater=IciAllReduceUpdater(opt, dp), shard_update=True)


def test_flat_slots_never_placed_replicated():
    """init_state must place ZeRO flat slots DIRECTLY on their data-axis
    sharding (opt_leaf_sharding) — a replicated intermediate would cost the
    full optimizer state per chip at init/resume."""
    tr = _train(4, shard=True, passes=1)
    sharding = tr.updater.opt_leaf_sharding
    for k, geom in tr.updater._geom.items():
        for s in tr.state["opt"]["slots"][k]:
            want = sharding(k, s)
            if geom.flat:
                assert want is not None
                assert s.sharding.is_equivalent_to(want, s.ndim), (k, s.sharding)
            else:
                assert want is None


def test_unknown_compression_rejected():
    with pytest.raises(ValueError, match="grad_compression"):
        compression_mod.make("fp4")


def test_sharded_updater_flat_geometry():
    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 4}))
    tr = _train(4, shard=True, passes=1)
    assert isinstance(tr.updater, ShardedUpdater)
    for k, geom in tr.updater._geom.items():
        if geom.flat:
            for s in tr.state["opt"]["slots"][k]:
                assert s.shape == (4, geom.chunk)
                spec = s.sharding.spec
                assert tuple(spec)[:1] == ("data",), (k, spec)


# -- ZeRO-2/3 modes (ISSUE 14) -------------------------------------------------


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_zero3_sgd_bitwise_equal_replicated(n_dev):
    """Acceptance: zero3 SGD training is bitwise-equal to the replicated
    updater on CPU — the on-demand gather is exact (none compression) and
    the shard-local update applies the same math per element."""
    p_rep = _params(_train(n_dev, shard=False))
    p_sh = _params(_train(n_dev, shard="zero3"))
    _assert_bitwise(p_rep, p_sh, f"zero3 n_dev={n_dev}")


@pytest.mark.parametrize("n_dev", [1, 2, 4])
def test_zero2_k1_bitwise_equal_replicated(n_dev):
    """At steps_per_dispatch=1 (and for remainder singles) zero2 applies
    exactly zero1's per-batch updates."""
    p_rep = _params(_train(n_dev, shard=False))
    p_sh = _params(_train(n_dev, shard="zero2"))
    _assert_bitwise(p_rep, p_sh, f"zero2 K=1 n_dev={n_dev}")


def test_zero2_fused_window_is_gradient_accumulation():
    """zero2 at K: the window's single update consumes the mean gradient
    over the merged K*B rows — reference: the same rows as ONE big batch
    under zero1 (row order inside a window differs only by the shard-local
    merge, which a mean cannot see beyond reduction-order ULPs)."""
    tr_z2 = _train(4, "zero2", passes=1, steps_per_dispatch=3)
    tr_big = _train(4, "zero1", passes=1, batch_size=96)
    p2, pb = _params(tr_z2), _params(tr_big)
    for k in pb:
        np.testing.assert_allclose(p2[k], pb[k], rtol=1e-5, atol=1e-7)
    # samples advanced by the window's real row count
    assert int(tr_z2.state["samples"]) == 96


def test_zero2_remainder_runs_single_updates():
    """A pass shorter than K never forms a window: every batch runs a
    single-step dispatch — bitwise zero1."""
    p_rem = _params(_train(4, "zero2", passes=1, steps_per_dispatch=4))
    p_z1 = _params(_train(4, "zero1", passes=1))
    _assert_bitwise(p_z1, p_rem, "zero2 remainder")


def test_zero2_collective_bytes_drop_k_times():
    tr1 = _train(4, "zero1", passes=1)
    tr2 = _train(4, "zero2", passes=1, steps_per_dispatch=3)
    d1 = tr1.updater.collective_bytes_detail(1)
    d2 = tr2.updater.collective_bytes_detail(16)
    for leg in ("scatter", "gather"):
        assert (
            d2["per_leg"][leg]["bytes_per_step"] * 16
            <= d1["per_leg"][leg]["bytes_per_step"] * 1.05
        ), (leg, d1, d2)
    assert d2["mode"] == "zero2"


def test_zero3_param_and_opt_bytes_shrink_n_times():
    """Acceptance: zero3 per-chip PARAM bytes and opt-state bytes are both
    ~N x below replicated at N=4, asserted from sharding metadata."""
    tr_rep = _train(4, shard=False, passes=1)
    tr3 = _train(4, "zero3", passes=1)
    rep_p = stats.per_chip_tree_bytes(tr_rep.state["params"])
    z3_p = stats.per_chip_tree_bytes(tr3.state["params"])
    assert rep_p >= 3.2 * z3_p, (rep_p, z3_p)
    rep_o = stats.per_chip_tree_bytes(tr_rep.state["opt"])
    z3_o = stats.per_chip_tree_bytes(tr3.state["opt"])
    assert rep_o >= 3.2 * z3_o, (rep_o, z3_o)
    # the flat param leaves really carry the data-axis sharding (residency,
    # not an estimate)
    for k, geom in tr3.updater._geom.items():
        p = tr3.state["params"][k]
        if geom.flat:
            assert p.shape == (4, geom.chunk)
            assert tuple(p.sharding.spec)[:1] == ("data",), (k, p.sharding)


def test_zero3_adam_allclose_replicated():
    tr_rep = _train(4, shard=False, optimizer=Adam(learning_rate=1e-3))
    tr3 = _train(4, "zero3", optimizer=Adam(learning_rate=1e-3))
    p_rep, p3 = _params(tr_rep), _params(tr3)
    for k in p_rep:
        np.testing.assert_allclose(p_rep[k], p3[k], rtol=1e-5, atol=1e-7)
    c_rep = tr_rep.updater.to_canonical(tr_rep.state["opt"])
    c3 = tr3.updater.to_canonical(tr3.state["opt"])
    for k, slots in c_rep["slots"].items():
        for a, b in zip(slots, c3["slots"][k]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-7
            )


def test_zero3_bf16_param_gather_close():
    """bf16 zero3: the forward sees bf16-rounded params (masters stay exact
    f32 on the owning shard) — training stays close to replicated."""
    p_bf = _params(_train(4, shard="zero3", compression="bf16"))
    p_rep = _params(_train(4, shard=False))
    for k in p_rep:
        np.testing.assert_allclose(p_bf[k], p_rep[k], rtol=0.05, atol=5e-3)


def test_zero3_int8_gather_error_feedback_carried():
    """int8 zero3 quantizes the PARAM gather with a master-tracking EF
    residual in opt_state['ef'] — it must exist, update, and training must
    stay in the replicated run's neighborhood."""
    tr = _train(4, shard="zero3", compression="int8")
    assert "ef" in tr.state["opt"]
    ef = tr.state["opt"]["ef"]
    assert any(np.abs(np.asarray(e)).max() > 0 for e in ef.values()), (
        "param-gather EF residual never updated"
    )
    p8 = _params(tr)
    p_rep = _params(_train(4, shard=False))
    for k in p_rep:
        np.testing.assert_allclose(p8[k], p_rep[k], rtol=0.2, atol=5e-2)


@pytest.mark.slow
def test_zero3_int8_lenet_convergence_smoke():
    """Acceptance: int8-in-collective param gather passes the LeNet
    convergence smoke with error feedback on."""
    from paddle_tpu.models import lenet

    reset_name_scope()
    _img, _lbl, _logits, cost = lenet(num_classes=4)
    dp = DataParallel(make_mesh({"data": 2}))
    tr = SGDTrainer(
        cost, SGD(learning_rate=0.03125, momentum=0.5), parallel=dp, seed=0,
        shard_update="zero3", grad_compression="int8",
    )
    rs = np.random.RandomState(1)
    n = 64
    x = rs.rand(n, 28, 28, 1).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) * 4).astype(np.int32).clip(0, 3)
    costs = []

    def handler(e):
        if isinstance(e, EndPass):
            costs.append(e.metrics["avg_cost"])

    def reader():
        for i in range(0, n, 16):
            yield {"pixel": x[i:i + 16], "label": y[i:i + 16]}

    tr.train(reader, num_passes=6, event_handler=handler)
    assert costs[-1] < costs[0] * 0.9, costs


@pytest.mark.parametrize(
    "save_mode,load_mode",
    [("zero3", False), (False, "zero3"), ("zero1", "zero3"),
     ("zero3", "zero2"), ("zero2", "zero3")],
)
def test_checkpoint_roundtrip_across_zero_modes(tmp_path, save_mode, load_mode):
    """Cross-MODE resumes are bitwise: checkpoints always hold the canonical
    per-param layout (zero3's flat params included), so any mode loads any
    mode's pass dir."""
    tr2, tr3 = _ckpt_roundtrip(
        tmp_path, save_mode, load_mode,
        lambda: SGD(learning_rate=0.125, momentum=0.5),
    )
    _assert_bitwise(_params(tr3), _params(tr2),
                    f"resume {save_mode}->{load_mode}")
    c2 = tr2.updater.to_canonical(tr2.state["opt"])
    c3 = tr3.updater.to_canonical(tr3.state["opt"])
    for k, slots in c3["slots"].items():
        for a, b in zip(slots, c2["slots"][k]):
            assert np.array_equal(np.asarray(a), np.asarray(b)), k


def test_zero3_cross_world_size_load_is_exact(tmp_path):
    """zero3 checkpoints are world-size-portable like the opt-state seam: a
    2-chip zero3 save resumes on a 4-chip zero3 trainer bitwise."""
    reset_name_scope()
    x, y = _data(64)
    dp = DataParallel(make_mesh({"data": 2}))
    tr1 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp, seed=5, shard_update="zero3")
    tr1.train(_reader(x, y), num_passes=1, save_dir=str(tmp_path))
    tr1.checkpoint_wait()

    reset_name_scope()
    dp4 = DataParallel(make_mesh({"data": 4}))
    tr2 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=dp4, seed=5, shard_update="zero3")
    tr2.init_state(dp4.shard_batch({"x": x[:32], "label": y[:32]}))
    tr2.load(str(tmp_path), 0)
    _assert_bitwise(_params(tr1), _params(tr2), "zero3 2->4 canonical load")


def test_zero3_divergence_guard_reverts_flat_params():
    """A poisoned batch under zero3: the device-resident guard reverts the
    FLAT SHARDED params (and slots) to pre-step values on every shard."""

    def run(poison):
        reset_name_scope()
        cost = _build()
        dp = DataParallel(make_mesh({"data": 4}))
        tr = SGDTrainer(
            cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp,
            seed=5, shard_update="zero3", divergence_policy="skip_batch",
            guard_check_every=1,
        )
        x, y = _data(96)
        batches = [
            {"x": x[i:i + 32].copy(), "label": y[i:i + 32].copy()}
            for i in range(0, 96, 32)
        ]
        if poison:
            batches.insert(1, {
                "x": batches[0]["x"] * np.float32("nan"),
                "label": batches[0]["label"],
            })
        tr.train(lambda: iter(batches), num_passes=1)
        return tr

    tr_clean = run(poison=False)
    tr_poison = run(poison=True)
    _assert_bitwise(_params(tr_clean), _params(tr_poison), "guarded zero3")


def test_zero2_poisoned_window_reverts_and_counts_k():
    """A NaN inside a zero2 fused window poisons the WHOLE window's merged
    batch: the guard reverts the single fused update and the dispatch counts
    as K diverged steps, so pass-average accounting stays exact."""
    reset_name_scope()
    cost = _build()
    dp = DataParallel(make_mesh({"data": 4}))
    tr = SGDTrainer(
        cost, SGD(learning_rate=0.125, momentum=0.5), parallel=dp, seed=5,
        shard_update="zero2", divergence_policy="skip_batch",
    )
    x, y = _data(96)
    x[40] = np.float32("nan")  # lands inside the one K=3 window
    metrics = {}

    def handler(e):
        if isinstance(e, EndPass):
            metrics.update(e.metrics)

    tr.train(_reader(x, y), num_passes=1, steps_per_dispatch=3,
             event_handler=handler)
    assert metrics["divergence_events"] == 3
    assert metrics["batches"] == 0
    # the whole window reverted: params still at their init values
    reset_name_scope()
    tr0 = SGDTrainer(_build(), SGD(learning_rate=0.125, momentum=0.5),
                     parallel=DataParallel(make_mesh({"data": 4})), seed=5,
                     shard_update="zero2")
    tr0.init_state(tr0.parallel.shard_batch(
        {"x": _data(96)[0][:32], "label": _data(96)[1][:32]}
    ))
    _assert_bitwise(_params(tr0), _params(tr), "reverted window")


def test_shard_update_mode_validation():
    reset_name_scope()
    dp = DataParallel(make_mesh({"data": 2}))
    with pytest.raises(ValueError, match="zero1"):
        SGDTrainer(_build(), SGD(), parallel=dp, shard_update="zero9")


def test_zero3_k_step_dispatch_composes():
    """zero3 under the K-step scan: per-step gathers/updates inside the
    scan body apply the same updates as unfused dispatches."""
    p1 = _params(_train(4, "zero3", passes=1, steps_per_dispatch=1))
    p3 = _params(_train(4, "zero3", passes=1, steps_per_dispatch=3))
    _assert_bitwise(p1, p3, "K-fused zero3 dispatch")


def test_zero3_composes_with_bf16_precision():
    """--precision bf16 under zero3: the gathered views feed Policy.cast at
    the dots, while the flat masters stay f32 on their owning shard."""
    reset_name_scope()
    cost = _build()
    dp = DataParallel(make_mesh({"data": 4}))
    tr = SGDTrainer(cost, SGD(learning_rate=0.125, momentum=0.5),
                    parallel=dp, seed=5, shard_update="zero3",
                    precision="bf16")
    x, y = _data(96)
    tr.train(_reader(x, y), num_passes=1)
    import jax.numpy as jnp

    for k, p in tr.state["params"].items():
        assert p.dtype == jnp.float32, (k, p.dtype)  # masters stay f32
    assert np.isfinite(tr.test(_reader(x, y))["cost"])


def test_zero3_resize_preserves_values_exactly():
    """Elastic resize under zero3: the flat params cross the re-shard
    through params_to/from_canonical bitwise, and the new geometry spans
    the new world."""
    tr = _train(2, "zero3", passes=1)
    p_before = _params(tr)
    tr.resize_to(4)
    p_after = _params(tr)
    _assert_bitwise(p_before, p_after, "zero3 resize 2->4")
    assert tr.updater.n == 4
    for k, geom in tr.updater._geom.items():
        if geom.flat:
            assert tr.state["params"][k].shape[0] == 4
    # and the resized trainer keeps training
    x, y = _data(96)
    tr.train(_reader(x, y), num_passes=1)


@pytest.mark.nightly
@pytest.mark.timeout(900)
def test_shard_update_bench_grid_nightly():
    """The heavy mode x compression x device-count grid with its acceptance
    gates (zero3 bytes ~1/N, zero2 grad leg ~1/K at K=16, int8 gather
    <= ~1/4 of f32), run as the real multi-process bench."""
    import json
    import subprocess
    import sys

    bench = os.path.join(
        os.path.dirname(__file__), "..", "benchmarks", "shard_update_bench.py"
    )
    out = subprocess.run(
        [sys.executable, bench, "--devices", "1,4", "--batches", "16"],
        capture_output=True, text=True, timeout=850,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert lines, (out.stdout[-500:], out.stderr[-500:])
    data = json.loads(lines[-1])
    assert data["all_gates_pass"], json.dumps(data)[:2000]


def test_flat_geometry_resolves_through_rules():
    """Flatness is decided by RESOLVED sharding, not tuple presence: a param
    declaring TP logical axes gets the flat ZeRO treatment on a data-only
    mesh (where "mlp" does not bite) and keeps its canonical TP layout on a
    dp x model mesh (where it does)."""
    from paddle_tpu.nn.graph import ParamAttr

    def geom_on(mesh_sizes):
        reset_name_scope()
        x = L.Data("x", shape=(DIM,))
        lbl = L.Data("label", shape=())
        h = L.Fc(x, 48, act="relu", name="h",
                 param_attr=ParamAttr(logical_axes=("embed", "mlp")))
        logits = L.Fc(h, CLASSES, act=None, name="out")
        cost = C.ClassificationCost(logits, lbl, name="cost")
        dp = DataParallel(make_mesh(mesh_sizes))
        tr = SGDTrainer(cost, SGD(learning_rate=0.125), parallel=dp, seed=5,
                        shard_update="zero3")
        x_, y_ = _data(32)
        tr.init_state(dp.shard_batch({"x": x_, "label": y_}))
        return tr.updater._geom["h.w"]

    assert geom_on({"data": 4}).flat, "TP axes must not bite on a data mesh"
    assert not geom_on({"data": 2, "model": 2}).flat, (
        "a param sharded over the model axis must keep its canonical layout"
    )
