"""Ring attention / Ulysses sequence parallelism vs the single-device oracle
on the 8-device virtual CPU mesh (SURVEY §4: in-process multi-host simulation
for collectives)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.sequence_parallel import (
    reference_attention,
    ring_attention,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh({"seq": 4})


def _qkv(seed=0, b=2, t=32, h=4, d=8, dtype=jnp.float32):
    rs = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rs.randn(b, t, h, d) * 0.5, dtype)
    return mk(), mk(), mk()


def test_ring_matches_reference_full(seq_mesh):
    q, k, v = _qkv()
    want = reference_attention(q, k, v)
    got = ring_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_causal(seq_mesh):
    q, k, v = _qkv(seed=1)
    want = reference_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_with_lengths(seq_mesh):
    q, k, v = _qkv(seed=2)
    lengths = jnp.asarray([20, 9], jnp.int32)
    want = reference_attention(q, k, v, lengths=lengths)
    got = ring_attention(q, k, v, seq_mesh, lengths=lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_grads_flow(seq_mesh):
    q, k, v = _qkv(seed=3, t=16)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring, (0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_ulysses_matches_reference(seq_mesh):
    q, k, v = _qkv(seed=4)  # h=4 divisible by seq axis 4
    for kwargs in ({}, {"causal": True}, {"lengths": jnp.asarray([25, 7], jnp.int32)}):
        want = reference_attention(q, k, v, **kwargs)
        got = ulysses_attention(q, k, v, seq_mesh, **kwargs)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-5, err_msg=str(kwargs)
        )


def test_ulysses_rejects_indivisible_heads(seq_mesh):
    q, k, v = _qkv(seed=5, h=3)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, seq_mesh)


def test_ring_composes_with_data_axis():
    """seq=4 × data=2 mesh: batch sharded on data, sequence on seq."""
    mesh = make_mesh({"data": 2, "seq": 4})
    q, k, v = _qkv(seed=6, b=4)
    want = reference_attention(q, k, v, causal=True)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P("data", "seq", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    got = ring_attention(qs, ks, vs, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
