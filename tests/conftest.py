"""Test harness config: force CPU backend with 8 virtual devices so multi-chip
sharding tests run without TPU hardware (the reference's analogous trick is the
GPU-less stub build, paddle/cuda/include/stub/ — CPU is the oracle everywhere,
SURVEY §4). Must run before jax is imported anywhere."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may inject a TPU-tunnel PJRT plugin via a sitecustomize that
# programmatically sets jax_platforms='axon,cpu' at interpreter startup —
# trumping the env var above; its client init can then block every test run
# when the tunnel is down. Force the config back to CPU before any backend
# initializes (tests must be hermetic on the CPU backend; SURVEY §4 CPU-oracle
# idiom).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the suite: point PADDLE_TPU_COMPILE_CACHE
# at a durable dir to make repeat runs skip compilation entirely (the suite is
# compile-dominated); unset, a per-run temp dir still dedups identical programs
# within the run. Hit/miss counts print at session end (see
# pytest_terminal_summary) so shape-churn suite-time regressions are visible.
import atexit  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

from paddle_tpu.core import stats as _stats  # noqa: E402
from paddle_tpu.core.init_ctx import enable_compilation_cache  # noqa: E402

_cache_dir = os.environ.get("PADDLE_TPU_COMPILE_CACHE")
if not _cache_dir:  # per-run temp dir: in-run dedup only, removed on exit
    _cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_xla_cache_")
    atexit.register(shutil.rmtree, _cache_dir, ignore_errors=True)
_cache_dir = enable_compilation_cache(_cache_dir)
# subprocess-spawning tests (test_cluster, test_distributed) inherit the
# cache dir via env, so child jax processes reuse this run's compilations
os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE", _cache_dir)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_terminal_summary(terminalreporter):
    terminalreporter.write_line(
        f"paddle_tpu compile cache [{_cache_dir}]: "
        f"hits={_stats.RECOMPILES.cache_hits} "
        f"misses={_stats.RECOMPILES.cache_misses} "
        f"distinct step shapes={_stats.RECOMPILES.total_signatures()}"
    )


@pytest.fixture
def rng():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)


# -- two-tier suite (VERDICT r3 weak #6) -------------------------------------
# The full suite is ~8-9 min serial, dominated by a handful of compile-heavy
# compat/model/e2e modules. Those are auto-marked `slow` here so the default
# developer/CI tier (`pytest -m "not slow"`) stays under ~3 min; the full run
# is `pytest tests/` (or `-m slow` for just the heavy tier).
_SLOW_MODULES = {
    "test_v1_compat",
    "test_models",
    "test_network_compare",
    "test_multi_network",
    "test_seq2seq",
    "test_distributed",
    "test_protostr",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__.rsplit(".", 1)[-1] in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
        # nightly ⊆ slow: the heavy real-subprocess chaos/resize drills ride
        # the nightly tier (`-m nightly`) and must never inflate tier-1
        # (`-m "not slow"`) wall-clock
        if item.get_closest_marker("nightly") is not None:
            item.add_marker(pytest.mark.slow)


# -- per-test wall-clock timeout (@pytest.mark.timeout(seconds)) --------------
# The multi-process cluster-chaos tests wait on subprocesses and sockets; a
# wedged child must fail ITS test, not stall the whole tier-1 run until the
# outer CI timeout. SIGALRM interrupts even a blocking wait; no plugin needed.


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    import signal as _signal
    import threading as _threading

    marker = item.get_closest_marker("timeout")
    usable = (
        marker is not None
        and hasattr(_signal, "SIGALRM")
        and _threading.current_thread() is _threading.main_thread()
    )
    if not usable:
        yield
        return
    limit = float(marker.args[0]) if marker.args else 120.0

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"{item.nodeid} exceeded its {limit:.0f}s per-test timeout"
        )

    old = _signal.signal(_signal.SIGALRM, _on_alarm)
    _signal.setitimer(_signal.ITIMER_REAL, limit)
    try:
        yield
    finally:
        _signal.setitimer(_signal.ITIMER_REAL, 0)
        _signal.signal(_signal.SIGALRM, old)
