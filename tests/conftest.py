"""Test harness config: force CPU backend with 8 virtual devices so multi-chip
sharding tests run without TPU hardware (the reference's analogous trick is the
GPU-less stub build, paddle/cuda/include/stub/ — CPU is the oracle everywhere,
SURVEY §4). Must run before jax is imported anywhere."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may inject a TPU-tunnel PJRT plugin via a sitecustomize that
# programmatically sets jax_platforms='axon,cpu' at interpreter startup —
# trumping the env var above; its client init can then block every test run
# when the tunnel is down. Force the config back to CPU before any backend
# initializes (tests must be hermetic on the CPU backend; SURVEY §4 CPU-oracle
# idiom).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)
