"""Test harness config: force CPU backend with 8 virtual devices so multi-chip
sharding tests run without TPU hardware (the reference's analogous trick is the
GPU-less stub build, paddle/cuda/include/stub/ — CPU is the oracle everywhere,
SURVEY §4). Must run before jax is imported anywhere."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment may inject a TPU-tunnel PJRT plugin via a sitecustomize that
# programmatically sets jax_platforms='axon,cpu' at interpreter startup —
# trumping the env var above; its client init can then block every test run
# when the tunnel is down. Force the config back to CPU before any backend
# initializes (tests must be hermetic on the CPU backend; SURVEY §4 CPU-oracle
# idiom).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    import jax

    return jax.random.PRNGKey(0)


@pytest.fixture
def np_rng():
    return np.random.RandomState(0)


# -- two-tier suite (VERDICT r3 weak #6) -------------------------------------
# The full suite is ~8-9 min serial, dominated by a handful of compile-heavy
# compat/model/e2e modules. Those are auto-marked `slow` here so the default
# developer/CI tier (`pytest -m "not slow"`) stays under ~3 min; the full run
# is `pytest tests/` (or `-m slow` for just the heavy tier).
_SLOW_MODULES = {
    "test_v1_compat",
    "test_models",
    "test_network_compare",
    "test_multi_network",
    "test_seq2seq",
    "test_distributed",
    "test_protostr",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__.rsplit(".", 1)[-1] in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)
