"""Cross-cutting utils: Stat timers (utils/Stat.h parity), layer-name crash
context (CustomStackTrace parity), flags."""

import numpy as np
import pytest

import jax

from paddle_tpu.core import stats
from paddle_tpu.core.stack_trace import LayerError


def test_stat_set_accumulates_and_reports():
    stats.GLOBAL_STATS.reset()
    stats.enable_timers(True)
    try:
        for _ in range(3):
            with stats.timer("unit_test_timer"):
                pass
        s = stats.GLOBAL_STATS.get("unit_test_timer")
        assert s.count == 3 and s.total >= 0
        rep = stats.GLOBAL_STATS.report()
        assert "unit_test_timer" in rep and "count=3" in rep
        d = stats.GLOBAL_STATS.as_dict()
        assert d["unit_test_timer"]["count"] == 3
    finally:
        stats.enable_timers(False)
        stats.GLOBAL_STATS.reset()


def test_timers_disabled_record_nothing():
    stats.GLOBAL_STATS.reset()
    stats.enable_timers(False)
    with stats.timer("should_not_exist"):
        pass
    assert "should_not_exist" not in stats.GLOBAL_STATS.as_dict()


def test_layer_error_names_failing_layer():
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    a = L.Data("a", shape=(4,))
    b = L.Data("b", shape=(5,))
    bad = L.Addto([a, b], name="mismatched_add")  # 4 vs 5: shape error inside
    net = Network([bad])
    with pytest.raises(LayerError) as ei:
        net.init(
            jax.random.PRNGKey(0),
            {"a": np.zeros((2, 4), np.float32), "b": np.zeros((2, 5), np.float32)},
        )
    assert "mismatched_add" in str(ei.value)
    assert ei.value.layer_name == "mismatched_add"


def test_trainer_hot_loop_stamps_timer():
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn.graph import Network, reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer.trainer import SGDTrainer

    stats.GLOBAL_STATS.reset()
    stats.enable_timers(True)
    try:
        reset_name_scope()
        x = L.Data("x", shape=(4,))
        y = L.Data("y", shape=())
        cost = C.ClassificationCost(L.Fc(x, 3, act=None), y)
        trainer = SGDTrainer(cost, SGD(learning_rate=0.1))
        rs = np.random.RandomState(0)

        def reader():
            yield [
                (rs.randn(4).astype(np.float32), rs.randint(3)) for _ in range(8)
            ]

        from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value

        feeder = DataFeeder({"x": dense_vector(4), "y": integer_value(3)})
        trainer.train(reader, num_passes=1, feeder=feeder)
        assert stats.GLOBAL_STATS.get("forwardBackward").count >= 1
    finally:
        stats.enable_timers(False)
        stats.GLOBAL_STATS.reset()


def test_chunk_evaluator_config_plumbing():
    """chunk_scheme/num_chunk_types/excluded flow config -> EvaluatorConfig ->
    constructed evaluator (VERDICT r2 missing #6)."""
    from paddle_tpu.config import parse_config
    from paddle_tpu.metrics.evaluators import ChunkEvaluator

    def cfg():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        seq = H.data_layer(name="toks", size=9)
        lab = H.data_layer(name="tags", size=9)
        out = H.fc_layer(input=seq, size=9, act=H.SoftmaxActivation(), name="out")
        H.chunk_evaluator(input=out, label=lab, chunk_scheme="IOBES",
                          num_chunk_types=2, excluded_chunk_types=[1])
        outputs(H.classification_cost(input=out, label=lab, name="cost"))

    pc = parse_config(cfg, emit_proto=False)
    ecs = [e for e in pc.context.evaluators if e.type == "chunk"]
    assert ecs and ecs[0].chunk_scheme == "IOBES"
    assert ecs[0].num_chunk_types == 2
    assert ecs[0].excluded_chunk_types == [1]

    ev = ChunkEvaluator(scheme="IOBES", num_chunk_types=2,
                        excluded_chunk_types=[1])
    ev.start()
    # IOBES with 2 types: tags = type*4 + pos, O = 8.
    # seq: S(type0)=3, B-I-E(type1)=4,5,6 — type1 chunks are excluded.
    tags = np.array([[3, 4, 5, 6, 8]])
    ev.update(output=None if False else np.eye(9)[tags], label=tags,
              lengths=np.array([5]))
    assert ev.n_label == 1 and ev.n_pred == 1 and ev.correct == 1
    assert ev.finish() == 1.0


def test_value_printer_evaluator():
    from paddle_tpu.metrics.evaluators import ValuePrinter

    lines = []
    ev = ValuePrinter(writer=lines.append)
    ev.start()
    ev.update(output=np.ones((2, 3)))
    assert ev.finish() == 1.0
    assert lines and "value_printer" in lines[0] and "(2, 3)" in lines[0]


def test_seq_text_printer_rejects_missing_payload(tmp_path):
    """update() with neither output ids nor a usable beam payload must raise
    a clear ValueError, not TypeError on len(None)."""
    from paddle_tpu.metrics.evaluators import SequenceTextPrinter

    printer = SequenceTextPrinter(result_file=str(tmp_path / "out.txt"))
    printer.start()
    try:
        with pytest.raises(ValueError, match="neither"):
            printer.update()
        with pytest.raises(ValueError, match="neither"):
            printer.update(beam=None, output=None)
    finally:
        printer.finish()
