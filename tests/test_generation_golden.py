"""Golden generation interchange against the reference's own trained model.

The strongest end-to-end proof the reference tree offers
(trainer/tests/test_recurrent_machine_generation.cpp:26-33,59-88): the
UNMODIFIED sample_trainer_rnn_gen.conf / sample_trainer_nest_rnn_gen.conf,
the reference's binary parameter files (rnn_gen_test_model_dir/t1), and
beam-search generation must reproduce the shipped golden outputs
r1.test.{nobeam,beam,nest} — config parsing, Parameter::Header interchange,
recurrent-group generation numerics and the SequenceTextPrinter format all
at once."""

import os

import numpy as np
import pytest

REF_ROOT = "/root/reference/paddle"
CONF_DIR = os.path.join(REF_ROOT, "trainer/tests")
MODEL_DIR = os.path.join(CONF_DIR, "rnn_gen_test_model_dir/t1")
GOLDEN = os.path.join(CONF_DIR, "rnn_gen_test_model_dir")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(MODEL_DIR), reason="reference tree not available"
)


def _read_floats(path):
    """readRetFile (test_recurrent_machine_generation.cpp:35): every
    whitespace-separated token parsed as a float."""
    with open(path) as f:
        return [float(t) for t in f.read().split()]


def _generate(conf, config_args, batch, dest):
    from paddle_tpu.config.config_parser import parse_config
    from paddle_tpu.trainer.generation import run_generation

    pc = parse_config(os.path.join(CONF_DIR, conf), config_args)
    written = run_generation(
        pc, batch, model_dir=MODEL_DIR, base_dir=REF_ROOT, result_file=dest
    )
    assert written, "config declared no seq_text_printer evaluator"
    return dest


def test_generation_session_reuse_matches_golden(tmp_path):
    """The serving-runtime contract on the golden model: ONE GenerationSession
    (params built + checkpoint loaded once) generates repeatedly, and every
    repeat reproduces the golden output — the compiled path run_generation
    wraps is the same one a long-lived server reuses."""
    from paddle_tpu.config.config_parser import parse_config
    from paddle_tpu.trainer.generation import GenerationSession

    pc = parse_config(
        os.path.join(CONF_DIR, "sample_trainer_rnn_gen.conf"), "beam_search=0"
    )
    sess = GenerationSession(pc, model_dir=MODEL_DIR, base_dir=REF_ROOT)
    want = _read_floats(os.path.join(GOLDEN, "r1.test.nobeam"))
    for i in range(2):  # the second call must NOT rebuild/reload
        dest = str(tmp_path / f"dump_text.{i}.test")
        written = sess.generate(_flat_batch(), result_file=dest)
        assert written
        assert _read_floats(dest) == want


def _flat_batch():
    rs = np.random.RandomState(0)
    return {
        "sent_id": np.arange(15, dtype=np.int32),
        "dummy_data_input": rs.rand(15, 2).astype(np.float32),
    }


def _nest_batch():
    # one sequence of 15 single-step subsequences (prepareInArgs hasSubseq
    # path, test_recurrent_machine_generation.cpp:76-88); one sample id
    rs = np.random.RandomState(0)
    return {
        "sent_id": np.zeros(1, np.int32),
        "dummy_data_input": rs.rand(1, 15, 1, 2).astype(np.float32),
        "dummy_data_input.lengths": np.array([15], np.int32),
        "dummy_data_input.sub_lengths": np.ones((1, 15), np.int32),
    }


def test_generation_matches_golden_nobeam(tmp_path):
    dest = str(tmp_path / "dump_text.test")
    _generate("sample_trainer_rnn_gen.conf", "beam_search=0", _flat_batch(), dest)
    assert _read_floats(dest) == _read_floats(
        os.path.join(GOLDEN, "r1.test.nobeam")
    )
    # goldens are checked-in files with an editor trailing newline; the
    # reference's own checker (readRetFile) is float-stream based
    assert open(dest).read().rstrip("\n") == open(
        os.path.join(GOLDEN, "r1.test.nobeam")
    ).read().rstrip("\n")


def test_generation_matches_golden_beam(tmp_path):
    dest = str(tmp_path / "dump_text.test")
    _generate("sample_trainer_rnn_gen.conf", "beam_search=1", _flat_batch(), dest)
    got, want = _read_floats(dest), _read_floats(
        os.path.join(GOLDEN, "r1.test.beam")
    )
    assert len(got) == len(want)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert open(dest).read().rstrip("\n") == open(
        os.path.join(GOLDEN, "r1.test.beam")
    ).read().rstrip("\n")


@pytest.mark.parametrize("beam_arg", ["beam_search=0", "beam_search=1"])
def test_nested_generation_matches_golden(tmp_path, beam_arg):
    """Hierarchical generation: beam and one-way search agree with the same
    golden (the inner beam concat contract, cpp:134-141)."""
    dest = str(tmp_path / "dump_text.test")
    _generate("sample_trainer_nest_rnn_gen.conf", beam_arg, _nest_batch(), dest)
    assert _read_floats(dest) == _read_floats(
        os.path.join(GOLDEN, "r1.test.nest")
    )
    assert open(dest).read().rstrip("\n") == open(
        os.path.join(GOLDEN, "r1.test.nest")
    ).read().rstrip("\n")
