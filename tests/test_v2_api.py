"""Tests for the paddle.v2-style user API (python/paddle/v2 parity surface)."""

import io
import itertools

import numpy as np
import pytest

import paddle_tpu.v2 as paddle
from paddle_tpu.nn.graph import reset_name_scope


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()
    yield


def _mlp():
    images = paddle.layer.data(name="pixel", type=paddle.data_type.dense_vector(784))
    label = paddle.layer.data(name="label", type=paddle.data_type.integer_value(10))
    h = paddle.layer.fc(input=images, size=32, act=paddle.activation.Tanh())
    out = paddle.layer.fc(input=h, size=10, act=None, name="output")
    cost = paddle.layer.classification_cost(input=out, label=label)
    return images, label, out, cost


def test_train_test_infer_roundtrip():
    paddle.init(use_gpu=False, trainer_count=1)
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    assert "output.w" in params.names()

    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9),
    )
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    reader = paddle.batch(
        paddle.reader.shuffle(paddle.dataset.mnist.train(), buf_size=500),
        batch_size=64,
    )
    trainer.train(reader=lambda: itertools.islice(reader(), 12), num_passes=2,
                  event_handler=handler)
    assert costs[-1] < costs[0], f"no learning: {costs[0]} -> {costs[-1]}"

    res = trainer.test(
        reader=lambda: itertools.islice(paddle.batch(paddle.dataset.mnist.test(), 64)(), 3)
    )
    assert np.isfinite(res.cost)

    samples = [(s,) for s, _ in itertools.islice(paddle.dataset.mnist.test()(), 8)]
    probs = paddle.infer(output_layer=out, parameters=trainer.parameters,
                         input=samples, feeding={"pixel": 0})
    assert probs.shape == (8, 10)


def test_parameters_tar_roundtrip():
    _, _, out, cost = _mlp()
    params = paddle.parameters.create(cost)
    buf = io.BytesIO()
    params.to_tar(buf)
    buf.seek(0)
    params2 = paddle.parameters.Parameters.from_tar(buf)
    assert set(params2.names()) == set(params.names())
    for k in params.names():
        np.testing.assert_array_equal(params.get(k), params2.get(k))


def test_topology_feeding_order():
    images, label, out, cost = _mlp()
    topo = paddle.topology.Topology(cost)
    assert set(topo.data_layers()) == {"pixel", "label"}
    feeder = topo.make_feeder({"label": 1, "pixel": 0})
    batch = feeder([(np.zeros(784, np.float32), 3), (np.ones(784, np.float32), 5)])
    assert batch["pixel"].shape == (2, 784)
    np.testing.assert_array_equal(batch["label"], [3, 5])


def test_sequence_layers_api():
    paddle.init(use_gpu=False)
    words = paddle.layer.data(
        name="words", type=paddle.data_type.integer_value_sequence(1000)
    )
    label = paddle.layer.data(name="label", type=paddle.data_type.integer_value(2))
    emb = paddle.layer.embedding(input=words, size=16)
    lstm = paddle.layer.lstmemory(input=paddle.layer.fc(input=emb, size=64))
    pooled = paddle.layer.pool(input=lstm, pooling_type=paddle.pooling.Max())
    out = paddle.layer.fc(input=pooled, size=2, act=None)
    cost = paddle.layer.classification_cost(input=out, label=label)

    trainer = paddle.trainer.SGD(
        cost=cost, update_equation=paddle.optimizer.Adam(learning_rate=1e-2)
    )
    reader = paddle.batch(paddle.dataset.imdb.train({f"w{i}": i for i in range(1000)}), 16)
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(reader=lambda: itertools.islice(reader(), 6), num_passes=1,
                  event_handler=handler)
    assert all(np.isfinite(c) for c in costs)


def test_mixed_and_projections():
    a = paddle.layer.data(name="a", type=paddle.data_type.dense_vector(8))
    m = paddle.layer.mixed(
        size=4,
        input=[paddle.layer.full_matrix_projection(input=a)],
        act=paddle.activation.Tanh(),
    )
    params = paddle.parameters.create(paddle.layer.sum_cost(input=m))
    assert any("proj" in n for n in params.names())


def test_optimizer_variants_build():
    for cls in (paddle.optimizer.Momentum, paddle.optimizer.Adam,
                paddle.optimizer.AdaGrad, paddle.optimizer.AdaDelta,
                paddle.optimizer.RMSProp, paddle.optimizer.DecayedAdaGrad,
                paddle.optimizer.AdaMax):
        opt = cls(learning_rate=0.01,
                  regularization=paddle.optimizer.L2Regularization(1e-4))
        assert opt.optimizer is not None


def test_datasets_schemas():
    x, y = next(paddle.dataset.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    img, lbl = next(paddle.dataset.cifar.train10()())
    assert img.shape == (3072,) and 0 <= lbl < 10
    ng = next(paddle.dataset.imikolov.train({f"w{i}": i for i in range(100)} | {"<unk>": 100}, 5)())
    assert len(ng) == 5
    rec = next(paddle.dataset.movielens.train()())
    assert len(rec) == 8
    srl = next(paddle.dataset.conll05.test()())
    assert len(srl) == 9 and len(srl[0]) == len(srl[8])
    s, t_in, t_out = next(paddle.dataset.wmt14.train(1000)())
    assert t_in[0] == 0 and t_out[-1] == 1 and len(t_in) == len(t_out)
    fa, fb = next(paddle.dataset.mq2007.train("pairwise")())
    assert fa.shape == (46,) and fb.shape == (46,)


def test_swig_api_shapes():
    """paddle/api SWIG-surface parity: GradientMachine forward/backward,
    Arguments seq start positions, SequenceGenerator over a beam layer."""
    import jax
    import numpy as np

    from paddle_tpu.api import Arguments, GradientMachine, SequenceGenerator
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector, dense_vector_sequence, integer_value

    reset_name_scope()
    x = vl.data(name="x", type=dense_vector(8))
    y = vl.data(name="y", type=integer_value(3))
    out = vl.fc(input=x, size=3, act="softmax", name="out")
    cost = vl.classification_cost(input=out, label=y)
    gm = GradientMachine([cost, out])

    args = Arguments()
    rs = np.random.RandomState(0)
    args.setSlotValue("x", rs.randn(4, 8).astype(np.float32))
    args.setSlotIds("y", rs.randint(0, 3, 4))
    outs = gm.forward(args)
    assert outs["out"].shape == (4, 3)
    c, grads = gm.forwardBackward(args)
    assert np.isfinite(c) and "out.w" in grads
    assert gm.getLayerOutput("out", args).shape == (4, 3)

    # ragged start positions → padded + lengths
    a2 = Arguments()
    flat = rs.randn(5, 2).astype(np.float32)
    a2.setSlotValue("s", flat)
    a2.setSlotSequenceStartPositions("s", [0, 2, 5])
    b = a2.as_batch()
    assert b["s"].shape == (2, 3, 2)
    np.testing.assert_array_equal(b["s.lengths"], [2, 3])

    # sequence generation
    reset_name_scope()
    enc = vl.data(name="enc", type=dense_vector_sequence(4))
    boot = vl.last_seq(input=enc)

    def step(enc_s, cur):
        mem = vl.memory(name="m", size=4, boot_layer=boot)
        h = vl.fc(input=[cur, mem], size=4, act="tanh", name="m")
        return vl.fc(input=h, size=6, act="softmax", name="probs")

    gen = vl.beam_search(
        step,
        input=[vl.StaticInput(enc, is_seq=True),
               vl.GeneratedInput(size=6, embedding_name="emb", embedding_size=4)],
        bos_id=0, eos_id=1, beam_size=2, max_length=5,
    )
    gm2 = GradientMachine([gen])
    sg = SequenceGenerator(gm2, gen, dict_file=[f"w{i}" for i in range(6)])
    batch = {"enc": rs.randn(2, 3, 4).astype(np.float32),
             "enc.lengths": np.asarray([3, 2], np.int32)}
    seqs = sg.generate(batch)
    assert len(seqs) == 2 and all(len(s) <= 5 for s in seqs)
    texts = sg.generateText(batch)
    assert all(isinstance(t, str) for t in texts)
