"""RNN op tests: scan implementations vs per-example numpy step loops — the
analog of gserver/tests/test_RecurrentLayer.cpp and test_LayerGrad LSTM/GRU
cases (CPU oracle idiom, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import rnn as rnn_ops
from paddle_tpu.ops import sequence as seq_ops


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_lstm(proj, lengths, w_hh, bias, peep=None):
    b, t, h4 = proj.shape
    h = h4 // 4
    hs = np.zeros((b, t, h), np.float32)
    for i in range(b):
        hv = np.zeros(h, np.float32)
        cv = np.zeros(h, np.float32)
        for s in range(lengths[i]):
            g = proj[i, s] + hv @ w_hh + bias
            gi, gf, gc, go = np.split(g, 4)
            if peep is not None:
                gi = gi + cv * peep[0]
                gf = gf + cv * peep[1]
            i_g = _sigmoid(gi)
            f_g = _sigmoid(gf)
            cand = np.tanh(gc)
            cv = f_g * cv + i_g * cand
            if peep is not None:
                go = go + cv * peep[2]
            o_g = _sigmoid(go)
            hv = o_g * np.tanh(cv)
            hs[i, s] = hv
    return hs


def _np_gru(proj, lengths, w_hzr, w_hc, bias):
    b, t, h3 = proj.shape
    h = h3 // 3
    hs = np.zeros((b, t, h), np.float32)
    for i in range(b):
        hv = np.zeros(h, np.float32)
        for s in range(lengths[i]):
            pz, pr, pc = np.split(proj[i, s] + bias, 3)
            rz = hv @ w_hzr
            z = _sigmoid(pz + rz[:h])
            r = _sigmoid(pr + rz[h:])
            c = np.tanh(pc + (r * hv) @ w_hc)
            hv = (1 - z) * hv + z * c
            hs[i, s] = hv
    return hs


@pytest.mark.parametrize("peephole", [False, True])
def test_lstm_scan_vs_numpy(np_rng, peephole):
    b, t, h = 3, 6, 5
    proj = np_rng.randn(b, t, 4 * h).astype(np.float32)
    lengths = np.array([4, 6, 1], np.int32)
    w_hh = (np_rng.randn(h, 4 * h) * 0.3).astype(np.float32)
    bias = np_rng.randn(4 * h).astype(np.float32) * 0.1
    peep = None
    checks = (None, None, None)
    if peephole:
        peep = [np_rng.randn(h).astype(np.float32) * 0.2 for _ in range(3)]
        checks = tuple(jnp.asarray(p) for p in peep)
    p = rnn_ops.LstmParams(jnp.asarray(w_hh), jnp.asarray(bias), *checks)
    mask = seq_ops.mask_from_lengths(jnp.asarray(lengths), t)
    hs, h_last, c_last = rnn_ops.lstm_scan(jnp.asarray(proj), mask, p)
    want = _np_lstm(proj, lengths, w_hh, bias, peep)
    np.testing.assert_allclose(np.asarray(hs) * np.asarray(mask)[:, :, None], want, rtol=2e-5, atol=2e-5)
    # final state equals state at each row's last valid step
    for i in range(b):
        np.testing.assert_allclose(np.asarray(h_last)[i], want[i, lengths[i] - 1], rtol=2e-5, atol=2e-5)


def test_gru_scan_vs_numpy(np_rng):
    b, t, h = 2, 5, 4
    proj = np_rng.randn(b, t, 3 * h).astype(np.float32)
    lengths = np.array([5, 3], np.int32)
    w_hzr = (np_rng.randn(h, 2 * h) * 0.3).astype(np.float32)
    w_hc = (np_rng.randn(h, h) * 0.3).astype(np.float32)
    bias = np_rng.randn(3 * h).astype(np.float32) * 0.1
    p = rnn_ops.GruParams(jnp.asarray(w_hzr), jnp.asarray(w_hc), jnp.asarray(bias))
    mask = seq_ops.mask_from_lengths(jnp.asarray(lengths), t)
    hs, h_last = rnn_ops.gru_scan(jnp.asarray(proj), mask, p)
    want = _np_gru(proj, lengths, w_hzr, w_hc, bias)
    np.testing.assert_allclose(np.asarray(hs) * np.asarray(mask)[:, :, None], want, rtol=2e-5, atol=2e-5)


def test_lstm_reverse_matches_flipped(np_rng):
    b, t, h = 2, 4, 3
    proj = np_rng.randn(b, t, 4 * h).astype(np.float32)
    lengths = np.full((b,), t, np.int32)  # full-length → reverse == flip
    w_hh = (np_rng.randn(h, 4 * h) * 0.3).astype(np.float32)
    bias = np.zeros(4 * h, np.float32)
    p = rnn_ops.LstmParams(jnp.asarray(w_hh), jnp.asarray(bias))
    mask = seq_ops.mask_from_lengths(jnp.asarray(lengths), t)
    hs_rev, _, _ = rnn_ops.lstm_scan(jnp.asarray(proj), mask, p, reverse=True)
    hs_flip, _, _ = rnn_ops.lstm_scan(jnp.asarray(proj[:, ::-1]), mask, p)
    np.testing.assert_allclose(np.asarray(hs_rev), np.asarray(hs_flip)[:, ::-1], rtol=1e-5, atol=1e-5)


def test_rnn_grad_flows(np_rng):
    # numeric vs analytic gradient through the scan (LayerGradUtil analog)
    b, t, h = 2, 3, 3
    proj = jnp.asarray(np_rng.randn(b, t, 4 * h).astype(np.float32) * 0.5)
    lengths = jnp.asarray([3, 2], dtype=jnp.int32)
    mask = seq_ops.mask_from_lengths(lengths, t)
    w0 = np_rng.randn(h, 4 * h).astype(np.float32) * 0.3

    def loss(w_hh):
        p = rnn_ops.LstmParams(w_hh, jnp.zeros(4 * h))
        hs, h_last, _ = rnn_ops.lstm_scan(proj, mask, p)
        return jnp.sum(h_last**2)

    g = jax.grad(loss)(jnp.asarray(w0))
    eps = 1e-3
    for idx in [(0, 0), (2, 5), (1, 11)]:
        wp = w0.copy()
        wp[idx] += eps
        wm = w0.copy()
        wm[idx] -= eps
        num = (float(loss(jnp.asarray(wp))) - float(loss(jnp.asarray(wm)))) / (2 * eps)
        assert abs(num - float(g[idx])) < 5e-3 * max(1.0, abs(num))
