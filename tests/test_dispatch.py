"""Async execution runtime tests: K-step fused dispatch equivalence, the
device-resident divergence guard's bounded-window reaction, and zero-stall
async checkpointing semantics.

The load-bearing contract: `train(steps_per_dispatch=K)` — whether the
stacking happens host-side in the trainer or on a DevicePrefetcher(stack_k=K)
worker — applies EXACTLY the updates of K single-step dispatches, bitwise on
the CPU oracle, including a trailing remainder that does not divide by K."""

import os

import numpy as np
import pytest

from paddle_tpu.core import faults, stats
from paddle_tpu.data.feeder import DataFeeder, dense_vector, integer_value
from paddle_tpu.data.pipeline import DevicePrefetcher, StackedBatch
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.trainer import DivergenceError, EndIteration, EndPass, SGDTrainer
from paddle_tpu.trainer import checkpoint as ckpt

DIM, CLASSES = 6, 3


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    stats.FT_EVENTS.reset()
    yield


def _trainer(policy=None, guard_every=16, lr=0.2, seed=11):
    reset_name_scope()
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, 16, act="relu"), CLASSES, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(
        cost, SGD(learning_rate=lr), seed=seed,
        divergence_policy=policy, guard_check_every=guard_every,
    )


def _dict_batches(n, bs=8, seed=0):
    rs = np.random.RandomState(seed)
    return [
        {
            "x": rs.randn(bs, DIM).astype(np.float32),
            "label": (rs.randint(0, CLASSES, bs)).astype(np.int64),
        }
        for _ in range(n)
    ]


def _params(t):
    return {k: np.asarray(v) for k, v in t.state["params"].items()}


def _assert_bitwise(a, b, what=""):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=f"{what}:{k}")


# ---------------------------------------------------------------------------
# K-step fused dispatch
# ---------------------------------------------------------------------------


def test_steps_per_dispatch_bitwise_with_remainder():
    """7 batches, K=3: two fused scans + one single-step remainder must land
    bitwise on the K=1 run — params, sample counter AND the on-device pass
    cost sum (avg_cost syncs the same accumulated scalar)."""
    batches = _dict_batches(7)
    passes1, passes3 = [], []

    t1 = _trainer()
    t1.train(
        lambda: iter(batches), num_passes=2,
        event_handler=lambda e: passes1.append(e.metrics)
        if isinstance(e, EndPass) else None,
    )
    t3 = _trainer()
    t3.train(
        lambda: iter(batches), num_passes=2, steps_per_dispatch=3,
        event_handler=lambda e: passes3.append(e.metrics)
        if isinstance(e, EndPass) else None,
    )
    _assert_bitwise(_params(t1), _params(t3), "K=3 vs K=1")
    assert int(t1.state["samples"]) == int(t3.state["samples"])
    assert [m["batches"] for m in passes1] == [m["batches"] for m in passes3]
    for m1, m3 in zip(passes1, passes3):
        assert m1["avg_cost"] == pytest.approx(m3["avg_cost"], rel=1e-6)


def test_steps_per_dispatch_through_prefetcher_stacking():
    """The production path: DevicePrefetcher(stack_k=K) stacks on its worker
    thread and the trainer dispatches the StackedBatch directly — still
    bitwise against the unfused run, remainder included."""
    raws = _dict_batches(7, seed=3)
    t1 = _trainer()
    t1.train(lambda: iter(raws), num_passes=2)

    seen = []

    def spy_reader():
        for b in DevicePrefetcher(
            lambda: iter(raws), prefetch_depth=2, stack_k=3
        ):
            seen.append(b)
            yield b

    tk = _trainer()
    tk.train(spy_reader, num_passes=2, steps_per_dispatch=3)
    _assert_bitwise(_params(t1), _params(tk), "prefetcher stack_k")
    assert int(t1.state["samples"]) == int(tk.state["samples"])
    # the prefetcher really did the stacking: 2 stacked groups + 1 single
    stacked = [b for b in seen if isinstance(b, StackedBatch)]
    singles = [b for b in seen if not isinstance(b, StackedBatch)]
    assert len(stacked) == 4 and all(b.k == 3 for b in stacked)  # 2 passes
    assert len(singles) == 2
    assert all(v.shape[0] == 3 for b in stacked for v in b.values())


def test_fused_dispatch_events_fire_per_dispatch():
    """Documented per-dispatch granularity: BeginIteration carries the first
    batch id of the window, EndIteration the last, one pair per dispatch."""
    batches = _dict_batches(7, seed=5)
    ends = []
    t = _trainer()
    t.train(
        lambda: iter(batches), num_passes=1, steps_per_dispatch=3,
        event_handler=lambda e: ends.append(e.batch_id)
        if isinstance(e, EndIteration) else None,
    )
    assert ends == [2, 5, 6]  # two fused windows + the remainder single


def test_shape_churn_flushes_group_to_singles():
    """A batch-size change mid-group must not break stacking — the buffered
    run flushes through single steps and the result still matches K=1."""
    batches = _dict_batches(3, bs=8) + _dict_batches(2, bs=4, seed=9)
    t1 = _trainer()
    t1.train(lambda: iter(batches), num_passes=1)
    t2 = _trainer()
    t2.train(lambda: iter(batches), num_passes=1, steps_per_dispatch=2)
    _assert_bitwise(_params(t1), _params(t2), "shape churn")


def test_steps_per_dispatch_rejects_bad_value():
    t = _trainer()
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        t.train(lambda: iter(_dict_batches(2)), steps_per_dispatch=0)
    with pytest.raises(ValueError, match="guard_check_every"):
        _trainer(policy="skip_batch", guard_every=0)


# ---------------------------------------------------------------------------
# device-resident divergence guard: bounded-window reaction
# ---------------------------------------------------------------------------


def test_guard_window_skip_reacts_within_bound(caplog):
    """NaN at batch 1, guard_check_every=4: the host learns about it at the
    poll after batch 3 (bounded window), the poisoned update never landed,
    and the pass metrics carry the event."""
    passes = []
    with faults.inject("nan_loss:step=1") as inj:
        t = _trainer(policy="skip_batch", guard_every=4)
        with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
            t.train(
                lambda: iter(_dict_batches(8)), num_passes=1,
                event_handler=lambda e: passes.append(e.metrics)
                if isinstance(e, EndPass) else None,
            )
        assert inj.fired["nan_loss"] == 1
    assert all(np.isfinite(v).all() for v in _params(t).values())
    assert passes[0]["divergence_events"] == 1
    assert passes[0]["batches"] == 7  # 8 stepped - 1 diverged
    assert np.isfinite(passes[0]["avg_cost"])
    assert stats.FT_EVENTS.get("divergence") == 1
    # the reaction happened at the window poll (batch 3), not at batch 1
    msgs = [r.message for r in caplog.records if "divergence guard" in r.message]
    assert any("batch 3" in m for m in msgs), msgs


def test_guard_check_every_one_restores_exact_batch_reaction():
    """guard_check_every=1 = the old latency: raise names the offending
    batch itself."""
    with faults.inject("nan_loss:step=2"):
        t = _trainer(policy="raise", guard_every=1)
        with pytest.raises(DivergenceError, match="pass 0 batch 2"):
            t.train(lambda: iter(_dict_batches(6)), num_passes=1)
    assert all(np.isfinite(v).all() for v in _params(t).values())


def test_guard_window_covers_fused_dispatch():
    """The guard composes with K-step fusion: a NaN inside a fused scan is
    reverted on device and shows up in the window poll's delta."""
    passes = []
    with faults.inject("nan_loss:step=1"):  # poisons the SECOND dispatch
        t = _trainer(policy="skip_batch", guard_every=16)
        t.train(
            lambda: iter(_dict_batches(8)), num_passes=1,
            steps_per_dispatch=4,
            event_handler=lambda e: passes.append(e.metrics)
            if isinstance(e, EndPass) else None,
        )
    assert all(np.isfinite(v).all() for v in _params(t).values())
    # _poison_batch NaNs the whole stacked slot → all 4 scanned steps diverge
    assert passes[0]["divergence_events"] == 4
    assert passes[0]["batches"] == 4
    assert np.isfinite(passes[0]["avg_cost"])


def test_guard_every_one_suppresses_poisoned_event():
    """guard_check_every=1 restores the full old contract: the poisoned
    batch joins neither cost nor the event stream; wider windows deliver the
    event (with a non-finite lazy cost) because the host learns too late."""
    ends1, ends4 = [], []
    with faults.inject("nan_loss:step=1"):
        t = _trainer(policy="skip_batch", guard_every=1)
        t.train(
            lambda: iter(_dict_batches(4)), num_passes=1,
            event_handler=lambda e: ends1.append(e.batch_id)
            if isinstance(e, EndIteration) else None,
        )
    assert ends1 == [0, 2, 3]  # batch 1 suppressed, like the old guard
    with faults.inject("nan_loss:step=1"):
        t = _trainer(policy="skip_batch", guard_every=4)
        t.train(
            lambda: iter(_dict_batches(4)), num_passes=1,
            event_handler=lambda e: ends4.append(e)
            if isinstance(e, EndIteration) else None,
        )
    assert [e.batch_id for e in ends4] == [0, 1, 2, 3]  # windowed: delivered
    assert not np.isfinite(ends4[1].cost)  # ...with the truthful NaN cost


def test_guard_poll_counter_is_device_resident():
    """The carry holds the cumulative diverged count; the host mirror only
    advances at polls."""
    with faults.inject("nan_loss:step=0"):
        t = _trainer(policy="skip_batch", guard_every=16)
        t.train(lambda: iter(_dict_batches(3)), num_passes=1)
    assert int(t.state["diverged"]) == 1
    assert t._diverged_seen == 1  # pass-end poll caught up


# ---------------------------------------------------------------------------
# zero-stall async checkpointing
# ---------------------------------------------------------------------------


def test_async_checkpoint_files_valid_and_resumable(tmp_path):
    """Async saves land CRC-valid with the same contents a sync save would
    persist, and a fresh trainer resumes from them bitwise."""
    batches = _dict_batches(4)
    d_async = str(tmp_path / "a")
    d_sync = str(tmp_path / "s")
    ta = _trainer()
    ta.train(lambda: iter(batches), num_passes=2, save_dir=d_async,
             async_checkpoint=True)
    ts = _trainer()
    ts.train(lambda: iter(batches), num_passes=2, save_dir=d_sync,
             async_checkpoint=False)
    for d in (d_async, d_sync):
        for p in (0, 1):
            assert ckpt.verify_pass(os.path.join(d, f"pass-{p:05d}"))
    pa, _, _, ma = ckpt.load_pass(d_async, 1)
    ps, _, _, ms = ckpt.load_pass(d_sync, 1)
    _assert_bitwise(pa, ps, "async vs sync checkpoint")
    assert ma["extra"] == ms["extra"]

    t2 = _trainer()
    t2.train(lambda: iter(batches), num_passes=2, save_dir=d_async,
             auto_resume=True)
    _assert_bitwise(_params(ta), _params(t2), "resume from async ckpt")


def test_async_checkpoint_wait_surfaces_writer_error(tmp_path):
    """A writer failure (save_dir ripped out mid-run) must re-raise on the
    training thread at the durability barrier, not die silently."""
    import shutil

    t = _trainer()
    batches = _dict_batches(2)
    t.train(lambda: iter(batches), num_passes=1)  # init state
    doomed = tmp_path / "doomed"
    doomed.mkdir()
    # make the writer fail deterministically: directory becomes a file
    shutil.rmtree(doomed)
    doomed.write_text("not a directory")
    t.save(str(doomed / "ckpts"), 0, async_=True)
    with pytest.raises((OSError, NotADirectoryError, FileExistsError)):
        t.checkpoint_wait()
    # the error is raised ONCE, then the writer is usable again
    t.checkpoint_wait()
    ok_dir = str(tmp_path / "ok")
    t.save(ok_dir, 0, async_=True)
    t.checkpoint_wait()
    assert ckpt.verify_pass(os.path.join(ok_dir, "pass-00000"))
