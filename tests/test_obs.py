"""Observability-plane tests (ISSUE 7): span tracing + Chrome export,
RPC trace-context propagation through a REAL MasterServer process,
heartbeat-aggregated fleet metrics, Prometheus export, serving request
correlation, HLO cost reporting, and the profiler-idempotence satellite."""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import stats
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import trace

pytestmark = [pytest.mark.timeout(150)]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _native_available() -> bool:
    from paddle_tpu.runtime import available

    return available()


needs_native = pytest.mark.skipif(
    not _native_available(), reason="native runtime unavailable"
)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    was = trace.TRACER.enabled
    trace.reset()
    trace.enable_tracing(True)
    yield
    trace.enable_tracing(was)
    trace.reset()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, deadline_s: float = 60.0) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.5).close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"port {port} never came up")


def _child_env() -> dict:
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return env


# -- span API + Chrome export -------------------------------------------------


def test_chrome_export_golden_format():
    """The export is loadable trace-event JSON: every event carries
    ph/ts/pid/tid/name (the Perfetto-required keys), complete-event phase,
    and parent/trace ids that reflect span nesting."""
    with trace.span("outer", role="test"):
        with trace.span("inner"):
            time.sleep(0.001)
    trace.record_span("external", 1_000, 2_000)
    out = trace.export_chrome()
    assert trace.validate_chrome(out) == []
    events = out["traceEvents"]
    assert {e["name"] for e in events} == {"outer", "inner", "external"}
    for ev in events:
        for key in ("ph", "ts", "pid", "tid", "name"):
            assert key in ev, f"event missing {key}: {ev}"
        assert ev["ph"] == "X" and ev["dur"] >= 0
    # survives a JSON round-trip byte-for-byte (what a file load sees)
    assert json.loads(json.dumps(out)) == out
    inner = next(e for e in events if e["name"] == "inner")
    outer = next(e for e in events if e["name"] == "outer")
    assert inner["args"]["trace_id"] == outer["args"]["trace_id"]
    assert inner["args"]["parent_id"] == outer["args"]["span_id"]
    assert outer["args"]["role"] == "test"
    assert inner["ts"] >= outer["ts"]


def test_ring_buffer_bounded_and_counts_drops():
    t = trace.Tracer(capacity=4)
    t.enabled = True
    for i in range(10):
        t.record("s", i, 1, "tid", f"sp{i}", None, None)
    rows = t.snapshot()
    assert len(rows) == 4
    assert [r[1] for r in rows] == [6, 7, 8, 9]  # oldest dropped, order kept
    assert t.dropped == 6 and t.recorded == 10


def test_disabled_tracing_records_nothing():
    trace.enable_tracing(False)
    before = trace.TRACER.recorded
    with trace.span("nope", x=1):
        trace.record_span("also_nope", 0, 1)
    assert trace.TRACER.recorded == before
    assert trace.wire_context() is None


def test_activate_foreign_context_stitches_trace():
    wire = {"t": "cafe" * 4, "s": "dead.1"}
    with trace.activate(wire):
        with trace.span("child"):
            pass
    ev = trace.export_chrome()["traceEvents"][0]
    assert ev["args"]["trace_id"] == wire["t"]
    assert ev["args"]["parent_id"] == wire["s"]


def test_span_stack_survives_exceptions():
    with pytest.raises(RuntimeError):
        with trace.span("outer"):
            raise RuntimeError("boom")
    assert trace.TRACER.current() is None  # stack fully unwound
    with trace.span("after"):
        assert trace.TRACER.current() is not None


# -- metrics registry + Prometheus -------------------------------------------


def test_metrics_registry_absorbs_event_counters():
    stats.FT_EVENTS.incr("obs_test_marker", 3)
    snap = obs_metrics.snapshot()
    key = "paddle_tpu_events_total{event=obs_test_marker,group=ft}"
    assert snap[key] == 3.0
    text = obs_metrics.to_prometheus_text()
    assert "# TYPE paddle_tpu_events_total counter" in text
    assert 'paddle_tpu_events_total{event="obs_test_marker",group="ft"} 3' in text


def test_histogram_and_prometheus_shape():
    reg = obs_metrics.MetricsRegistry()
    h = reg.histogram("t_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = obs_metrics.to_prometheus_text(reg)
    assert 't_seconds_bucket{le="0.1"} 1' in text
    assert 't_seconds_bucket{le="1.0"} 2' in text
    assert 't_seconds_bucket{le="+Inf"} 3' in text
    assert "t_seconds_count 3" in text
    c = reg.counter("reqs_total")
    c.inc(2, tenant="a")
    assert 'reqs_total{tenant="a"} 2' in obs_metrics.to_prometheus_text(reg)


def test_aggregate_snapshots_sums_and_skips_garbage():
    agg = obs_metrics.aggregate_snapshots(
        [{"a": 1, "b": 2}, {"a": 4, "c": "garbage"}]
    )
    assert agg == {"a": 5.0, "b": 2.0}


def test_fleet_metrics_ttl_and_drop():
    fm = obs_metrics.FleetMetrics(ttl_s=60)
    fm.update("tr-1", {"a": 1.0})
    fm.update("tr-2", {"a": 2.0, "b": 1.0})
    agg = fm.aggregate()
    assert agg["reporting_trainers"] == 2
    assert agg["counters"] == {"a": 3.0, "b": 1.0}
    fm.drop("tr-1")
    assert fm.aggregate()["reporting_trainers"] == 1


def test_obs_export_cli_local(tmp_path):
    """`python -m paddle_tpu.obs export` without an endpoint prints this
    process's registry as Prometheus text; `... trace` emits loadable JSON."""
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.obs", "export"],
        env=_child_env(), capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "# TYPE paddle_tpu_shape_signatures gauge" in r.stdout
    out = tmp_path / "t.json"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.obs", "trace", "--out", str(out)],
        env=_child_env(), capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    loaded = json.loads(out.read_text())
    assert "traceEvents" in loaded


# -- RPC propagation + fleet aggregation (master plane) -----------------------


@needs_native
def test_rpc_trace_roundtrips_through_real_master_process(tmp_path):
    """Acceptance: the trace context piggybacked on the line-JSON frames
    round-trips through a REAL `python -m paddle_tpu.runtime.master serve`
    process — the server's handler spans (fetched over the `trace_export`
    RPC) stitch into the client span's trace id, and the merged trace is
    Perfetto-loadable."""
    from paddle_tpu.runtime.master import MasterClient

    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.runtime.master", "serve",
         "--port", str(port), "--trace", "1"],
        env=_child_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        _wait_port(port)
        client = MasterClient(("127.0.0.1", port))
        client.call("set_dataset", shards=["a", "b"])
        got = client.call("get_task")
        assert "task_id" in got
        remote = client.call("trace_export")["chrome_trace"]
        client.close()
        local = trace.export_chrome()

        def events(tr, name, side):
            return [
                e for e in tr["traceEvents"]
                if e["name"] == name and e["args"].get("side") == side
            ]

        cl = events(local, "rpc.get_task", "client")
        sv = events(remote, "rpc.get_task", "server")
        assert len(cl) == 1 and len(sv) == 1
        # one trace id across the process boundary; the server span is the
        # client span's child; distinct processes (pid rows) in the merge
        assert sv[0]["args"]["trace_id"] == cl[0]["args"]["trace_id"]
        assert sv[0]["args"]["parent_id"] == cl[0]["args"]["span_id"]
        assert sv[0]["pid"] != cl[0]["pid"]
        merged = trace.merge_chrome([local, remote])
        assert trace.validate_chrome(merged) == []
    finally:
        proc.terminate()
        proc.wait(timeout=15)


@needs_native
def test_master_stats_aggregates_heartbeat_metrics():
    """Heartbeats carrying metric snapshots land in stats()["fleet"]:
    counters sum across trainers, deregister drops the contribution."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster

    server = MasterServer(TaskMaster(), lease_s=30.0).start()
    try:
        c = MasterClient(server.address)
        t1 = c.call("register")["trainer_id"]
        t2 = c.call("register")["trainer_id"]
        c.call("heartbeat", trainer_id=t1, metrics={"steps": 5, "x": 1})
        c.call("heartbeat", trainer_id=t2, metrics={"steps": 7})
        fleet = c.call("stats")["fleet"]
        assert fleet["reporting_trainers"] == 2
        assert fleet["counters"]["steps"] == 12.0
        assert fleet["counters"]["x"] == 1.0
        # a RE-heartbeat replaces (not doubles) that trainer's snapshot
        c.call("heartbeat", trainer_id=t2, metrics={"steps": 8})
        assert c.call("stats")["fleet"]["counters"]["steps"] == 13.0
        c.call("deregister", trainer_id=t2)
        fleet = c.call("stats")["fleet"]
        assert fleet["reporting_trainers"] == 1
        assert fleet["counters"]["steps"] == 5.0
        # the metrics RPC serves Prometheus text incl. the fleet aggregate
        text = c.call("metrics")["text"]
        assert "paddle_tpu_fleet_reporting_trainers 1" in text
        assert 'paddle_tpu_fleet{key="steps"} 5' in text
        c.close()
    finally:
        server.stop()


# -- serving correlation (client → server → session) --------------------------


@pytest.fixture(scope="module")
def tiny_session():
    from paddle_tpu.serving.session import make_demo_session

    return make_demo_session(
        vocab=64, n_layers=1, d_model=16, n_heads=2, seed=0,
        max_slots=2, page_size=8, prefill_buckets=(8,), max_new_limit=4,
    )


@pytest.mark.serving
@needs_native
def test_serving_request_spans_share_one_trace_id(tiny_session):
    """Acceptance: one serving request's spans — client RPC, server handler,
    and the engine's queue-wait/prefill/ttft — correlate under ONE trace id,
    and the server's buffer exports as loadable Chrome trace JSON."""
    from paddle_tpu.serving.server import ServingClient, ServingServer

    srv = ServingServer(session=tiny_session).start()
    try:
        c = ServingClient(srv.address)
        res = c.generate([1, 2, 3], max_new_tokens=3, timeout_s=60)
        assert res["done"]
        exported = c.trace_export()
        assert trace.validate_chrome(exported) == []
        c.close()
    finally:
        srv.stop()
    events = exported["traceEvents"]
    submit_client = [
        e for e in events
        if e["name"] == "rpc.submit" and e["args"].get("side") == "client"
    ]
    assert submit_client, [e["name"] for e in events]
    tid = submit_client[0]["args"]["trace_id"]
    by_trace = {
        e["name"] for e in events if e["args"].get("trace_id") == tid
    }
    assert {
        "rpc.submit", "serving.queue_wait", "serving.prefill", "serving.ttft",
    } <= by_trace, by_trace
    # batch-level decode steps ran too (their own trace — they serve many
    # requests at once) and TTFT landed in the histogram
    assert any(e["name"] == "serving.decode_step" for e in events)
    from paddle_tpu.serving.session import TTFT_HISTOGRAM

    assert TTFT_HISTOGRAM._n > 0


@pytest.mark.serving
@needs_native
def test_serving_stats_forwards_master_health(tiny_session):
    """Satellite: stats() on a serving server wired to a routing master
    surfaces the control plane's snapshot_failures / lease evictions /
    live+evicted trainer counts — and reports unreachability as data."""
    from paddle_tpu.runtime.master import MasterClient, MasterServer, TaskMaster
    from paddle_tpu.serving.server import ServingClient, ServingServer

    master = MasterServer(TaskMaster(), lease_s=30.0).start()
    mc = MasterClient(master.address)
    tid = mc.call("register")["trainer_id"]
    srv = ServingServer(
        session=tiny_session, master_endpoints=master.address
    ).start()
    srv._master_health_ttl_s = 0.0  # probe every stats() — the test flips
    # the master down and must see the change immediately, not the cache
    try:
        c = ServingClient(srv.address)
        st = c.stats()
        assert st["master"]["reachable"] is True
        assert st["master"]["snapshot_failures"] == 0
        assert st["master"]["live_trainers"] == 1
        assert st["master"]["evicted_trainers"] == 0
        mc.close()
        master.stop()  # control plane dies; serving stats must say so
        st = c.stats()
        assert st["master"]["reachable"] is False and st["master"]["error"]
        c.close()
    finally:
        srv.stop()
        master.stop()


# -- profiling hooks ----------------------------------------------------------


def test_profiler_start_stop_idempotent(tmp_path):
    """Satellite: double start warns + no-ops (no jax RuntimeError), stop
    without start no-ops."""
    stats.profiler_stop()  # no active trace: must be a silent no-op
    stats.profiler_start(str(tmp_path / "p"))
    stats.profiler_start(str(tmp_path / "p"))  # second start: warn + no-op
    stats.profiler_stop()
    stats.profiler_stop()  # double stop: no-op


def _toy_trainer_and_batch():
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(8,))
    lbl = L.Data("label", shape=())
    cost = C.ClassificationCost(L.Fc(L.Fc(x, 16, act="relu"), 3, act=None), lbl)
    trainer = SGDTrainer(cost, SGD(learning_rate=0.1), seed=0)
    rs = np.random.RandomState(0)
    batch = {
        "x": rs.randn(8, 8).astype(np.float32),
        "label": (np.arange(8) % 3).astype(np.int32),
    }
    return trainer, batch


def test_trainer_cost_report_top_k_buckets():
    from paddle_tpu.obs import profile as obs_profile

    trainer, batch = _toy_trainer_and_batch()
    trainer.init_state(batch)
    report = obs_profile.trainer_cost_report(trainer, batch, top_k=3)
    step = report["executables"]["train_step"]
    assert step["flops"] > 0
    assert step["bytes_accessed"] > 0
    assert 0 < len(step["top_buckets"]) <= 3
    # ranked descending, deterministically
    vals = [b["value"] for b in step["top_buckets"]]
    assert vals == sorted(vals, reverse=True)


def test_pass_profiler_captures_one_pass(tmp_path):
    from paddle_tpu.obs import profile as obs_profile

    trainer, batch = _toy_trainer_and_batch()
    profiler = obs_profile.PassProfiler.from_spec(
        "pass:1", logdir=str(tmp_path / "trace")
    )
    seen = []
    handler = profiler.wrap(lambda e: seen.append(type(e).__name__))
    trainer.train(
        lambda: iter([batch] * 4), num_passes=2, event_handler=handler,
        log_period=100,
    )
    assert profiler.captured
    assert not profiler._active
    assert (tmp_path / "trace").is_dir()
    assert "EndPass" in seen  # the wrapped handler still ran


def test_parse_profile_spec_rejects_bad_forms():
    from paddle_tpu.obs.profile import parse_profile_spec

    assert parse_profile_spec("pass:0") == ("pass", 0)
    for bad in ("", "pass", "pass:x", "pass:-1", "step:3"):
        with pytest.raises(ValueError):
            parse_profile_spec(bad)


def test_statset_report_percent_and_deterministic_ties():
    """Satellite: report() shows percent-of-total and breaks total ties by
    name so timer splits diff cleanly across runs."""
    ss = stats.StatSet()
    ss.get("zeta").add(0.010)
    ss.get("alpha").add(0.010)
    ss.get("big").add(0.080)
    rep = ss.report()
    lines = rep.splitlines()[1:]
    names = [ln.strip().split(":")[0] for ln in lines]
    assert names == ["big", "alpha", "zeta"]  # total desc, then name
    assert "80.0%" in lines[0]
    assert "10.0%" in lines[1]
    assert ss.report() == rep  # stable across calls


# -- trainer spans ------------------------------------------------------------


def test_trainer_emits_pass_dispatch_checkpoint_spans(tmp_path):
    trainer, batch = _toy_trainer_and_batch()
    trainer.train(
        lambda: iter([batch] * 3), num_passes=1, log_period=100,
        save_dir=str(tmp_path / "ckpt"),
    )
    names = [r[0] for r in trace.TRACER.snapshot()]
    assert names.count("train.dispatch") == 3
    assert "train.pass" in names
    assert "train.checkpoint" in names
