"""Cluster-level chaos tests: master failover, trainer membership leases,
preemption-safe shutdown, client partitions — every one a deterministic,
seeded code path (ISSUE 3 tentpole; the Go reference's lease/re-queue
discipline, go/master/service.go:166, exercised end-to-end with REAL process
death where it matters).

Multi-process scenarios spawn the master via `python -m
paddle_tpu.runtime.master` and the trainer via tests/distributed_worker.py
roles; each test carries a per-test wall-clock timeout (conftest SIGALRM
marker) so a hung subprocess cannot stall tier-1."""

import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import faults, preempt, stats
from paddle_tpu.runtime import available, recordio
from paddle_tpu.runtime.master import (
    KILLED_EXIT,
    MasterClient,
    MasterServer,
    TaskMaster,
    cluster_reader,
    parse_endpoints,
    standby_master,
)

pytestmark = [
    pytest.mark.chaos,
    pytest.mark.timeout(150),
    pytest.mark.skipif(not available(), reason="native runtime unavailable"),
]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "distributed_worker.py")


@pytest.fixture(autouse=True)
def _fresh():
    stats.FT_EVENTS.reset()
    preempt.reset()
    yield
    preempt.reset()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, deadline_s: float = 60.0) -> None:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.5).close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"port {port} never came up")


def _child_env() -> dict:
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return env


# -- endpoint parsing ---------------------------------------------------------


def test_parse_endpoints_forms():
    assert parse_endpoints(("h", 1)) == [("h", 1)]
    assert parse_endpoints("h:1") == [("h", 1)]
    assert parse_endpoints("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_endpoints([("a", 1), "b:2"]) == [("a", 1), ("b", 2)]
    with pytest.raises(ValueError):
        parse_endpoints("noport")
    with pytest.raises(ValueError):
        parse_endpoints("")


# -- master failover ----------------------------------------------------------


def test_master_kill_standby_failover_exactly_once(tmp_path):
    """THE acceptance scenario: a real master process dies to the seeded
    `master_kill` fault mid-pass; a warm standby on the same snapshot takes
    over; trainers fail over via their endpoint list — and every task is
    still delivered exactly once (done == ntasks, discarded == 0)."""
    nrec, per_task = 48, 4
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: ({"sid": i} for i in range(nrec)),
        records_per_file=per_task,
    )
    ntasks = len(shards)
    p1, p2 = _free_port(), _free_port()
    snap = str(tmp_path / "m.snap")
    primary = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.runtime.master", "serve",
         "--port", str(p1), "--snapshot", snap, "--lease_s", "2",
         "--timeout_s", "30", "--failure_max", "10",
         "--faults", "master_kill:step=9", "--faults_seed", "0"],
        env=_child_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    standby_holder = {}
    try:
        _wait_port(p1)
        boot = MasterClient(("127.0.0.1", p1))
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        boot.close()

        def run_standby():
            standby_holder["srv"] = standby_master(
                ("127.0.0.1", p1), port=p2, snapshot_path=snap,
                poll_s=0.1, max_wait_s=90, lease_s=2.0,
            )

        threading.Thread(target=run_standby, daemon=True).start()

        endpoints = [("127.0.0.1", p1), ("127.0.0.1", p2)]
        consumed = [[], []]
        errs = []

        def consume(i):
            try:
                reader = cluster_reader(
                    endpoints, client_kw={"retries": 40, "timeout": 5}
                )
                for s in reader():
                    consumed[i].append(s["sid"])
                    time.sleep(0.01)  # keep both trainers in the pass
            except Exception as e:  # surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=consume, args=(i,)) for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "consumers hung"
        assert not errs, errs

        primary.wait(timeout=10)
        assert primary.returncode == KILLED_EXIT  # chaos crash, not clean stop
        srv = standby_holder.get("srv")
        assert srv is not None, "standby never took over"

        # exactly-once task delivery across the failover
        post = MasterClient(("127.0.0.1", p2))
        st = post.call("stats")
        post.close()
        assert st["done"] == ntasks, st
        assert st["discarded"] == 0, st
        # full record coverage (a task in flight at the kill may legitimately
        # replay — re-delivered records, never lost ones)
        seen = set(consumed[0] + consumed[1])
        assert seen == set(range(nrec))
        assert consumed[0] and consumed[1]  # both trainers pulled work
        ft = stats.FT_EVENTS.as_dict()
        assert ft.get("master_failover", 0) > 0
        assert ft.get("master_takeover", 0) == 1
    finally:
        if primary.poll() is None:
            primary.kill()
        srv = standby_holder.get("srv")
        if srv is not None:
            srv.stop()


# -- trainer membership leases ------------------------------------------------


def test_trainer_lease_eviction_eagerly_requeues(tmp_path):
    """A trainer that stops heartbeating is evicted after lease_s and its
    pending task comes back to the queue IMMEDIATELY — not after the 120 s
    per-task timeout — and the eviction shows up in stats()/FT_EVENTS."""
    server = MasterServer(
        TaskMaster(timeout_s=120.0, failure_max=5), lease_s=0.3
    ).start()
    try:
        ca = MasterClient(server.address)
        ca.call("set_dataset", shards=["a", "b", "c", "d"])
        tid_a = ca.call("register")["trainer_id"]
        lost = ca.call("get_task", trainer_id=tid_a)
        assert "task_id" in lost
        ca.close()  # trainer A dies silently, task in hand

        cb = MasterClient(server.address)
        tid_b = cb.call("register")["trainer_id"]
        got, deadline = [], time.time() + 10
        while time.time() < deadline:
            resp = cb.call("get_task", trainer_id=tid_b)
            if "task_id" in resp:
                got.append(resp["task_id"])
                if lost["task_id"] in got:
                    break
            else:
                time.sleep(0.05)
        elapsed = time.time() - (deadline - 10)
        assert lost["task_id"] in got, "evicted trainer's task never requeued"
        assert elapsed < 10  # way below the 120 s per-task timeout
        st = cb.call("stats")
        assert st["evicted_trainers"] == 1
        assert st["live_trainers"] == 1  # B holds a live lease, A is gone
        assert stats.FT_EVENTS.get("trainer_evicted") == 1
        cb.close()
    finally:
        server.stop()
    # satellite: stop() must close the native handle, idempotently
    assert server.master.closed
    server.stop()


def test_fleet_metrics_aggregated_from_real_process_heartbeats(tmp_path):
    """ISSUE 7 acceptance: a REAL master process aggregates the metric
    snapshots riding on cluster_reader's heartbeats, and stats() answers
    with the fleet-wide view while the trainer is mid-pass."""
    nrec = 48
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: ({"sid": i} for i in range(nrec)),
        records_per_file=4,
    )
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.runtime.master", "serve",
         "--port", str(port), "--lease_s", "1"],
        env=_child_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        _wait_port(port)
        boot = MasterClient(("127.0.0.1", port))
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        # guarantee a recognizable counter in this process's snapshot
        stats.FT_EVENTS.incr("fleet_probe", 3)
        consumed, errs = [], []

        def consume():
            try:
                for s in cluster_reader(
                    ("127.0.0.1", port), client_kw={"retries": 20}
                )():
                    consumed.append(s["sid"])
                    time.sleep(0.05)  # stretch the pass past heartbeats
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=consume)
        t.start()
        fleet = None
        deadline = time.time() + 30
        while time.time() < deadline:
            st = boot.call("stats")
            fleet = st.get("fleet")
            if fleet and fleet.get("reporting_trainers", 0) >= 1 and any(
                "fleet_probe" in k for k in fleet.get("counters", {})
            ):
                break
            time.sleep(0.1)
        t.join(timeout=60)
        assert not t.is_alive() and not errs, errs
        assert fleet is not None and fleet["reporting_trainers"] >= 1, fleet
        key = next(k for k in fleet["counters"] if "fleet_probe" in k)
        assert fleet["counters"][key] >= 3.0
        assert sorted(consumed) == list(range(nrec))
        boot.close()
    finally:
        if proc.poll() is None:
            proc.terminate()
        proc.wait(timeout=15)


def test_deregister_releases_lease_without_eviction():
    server = MasterServer(TaskMaster(), lease_s=30.0).start()
    try:
        c = MasterClient(server.address)
        tid = c.call("register")["trainer_id"]
        assert c.call("stats")["live_trainers"] == 1
        assert c.call("deregister", trainer_id=tid)["ok"]
        st = c.call("stats")
        assert st["live_trainers"] == 0
        assert st["evicted_trainers"] == 0  # graceful exit, not an eviction
        c.close()
    finally:
        server.stop()


# -- client partition (conn_reset) -------------------------------------------


def test_conn_reset_partition_absorbed(tmp_path):
    """A flaky trainer↔master link (seeded RSTs on the client socket) costs
    reconnects, never records: the pass still delivers every record exactly
    once because the reset fires before the request is ever sent."""
    nrec = 24
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: ({"sid": i} for i in range(nrec)),
        records_per_file=4,
    )
    server = MasterServer(TaskMaster(timeout_s=30, failure_max=5)).start()
    try:
        boot = MasterClient(server.address)
        boot.call("set_dataset", shards=shards, chunks_per_task=1)
        boot.close()
        with faults.inject("conn_reset:0.2", seed=2) as inj:
            got = sorted(
                s["sid"]
                for s in cluster_reader(
                    server.address, client_kw={"retries": 40}
                )()
            )
            assert inj.fired.get("conn_reset", 0) > 0  # chaos actually bit
        assert got == list(range(nrec))  # exactly once, in spite of the RSTs
        st = MasterClient(server.address).call("stats")
        assert st["done"] == len(shards) and st["discarded"] == 0
        assert stats.FT_EVENTS.get("master_reconnect") > 0
    finally:
        server.stop()


# -- preemption-safe shutdown -------------------------------------------------


def _toy_trainer():
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    x = L.Data("x", shape=(8,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, 16, act="relu"), 3, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(cost, SGD(learning_rate=0.1), seed=3)


def _toy_reader():
    rs = np.random.RandomState(7)
    xs = rs.randn(64, 8).astype(np.float32)
    ys = (np.arange(64) % 3).astype(np.int32)

    def reader():
        for i in range(0, 64, 8):
            yield {"x": xs[i:i + 8], "label": ys[i:i + 8]}

    return reader


def test_preempt_fault_drains_midpass_and_resumes_bitwise(tmp_path):
    """Seeded `preempt` chaos site: the flagged batch still steps ("finish
    the step"), the NEXT boundary writes a CRC-valid mid-pass checkpoint and
    raises Preempted; a fresh trainer with auto_resume=True replays the rest
    of the pass and lands bitwise-identical to a never-preempted run."""
    from paddle_tpu.trainer import Preempted, checkpoint as ckpt
    from paddle_tpu.trainer.trainer import Preempted as P2  # same symbol

    assert Preempted is P2
    reader = _toy_reader()
    clean = _toy_trainer()
    clean.train(reader, num_passes=3, log_period=1000)

    d = str(tmp_path / "ckpt")
    victim = _toy_trainer()
    with faults.inject("preempt:step=4"):
        with pytest.raises(Preempted) as ei:
            victim.train(reader, num_passes=3, save_dir=d, log_period=1000)
    assert ei.value.pass_id == 0
    assert ei.value.batches_done == 5  # fault at batch 4 → drain at boundary 5
    assert ei.value.checkpoint_dir is not None
    man = ckpt.pass_manifest(d, 0)
    assert man["extra"]["mid_pass"] is True
    assert man["extra"]["batches_done"] == 5
    assert ckpt.find_latest_valid_pass(d) == 0  # CRC-valid, latest-pointed
    assert stats.FT_EVENTS.get("preempt_drain") == 1

    preempt.reset()  # the next run is a fresh process in spirit
    resumed = _toy_trainer()
    resumed.train(
        reader, num_passes=3, save_dir=d, auto_resume=True, log_period=1000
    )
    for k, v in clean.state["params"].items():
        np.testing.assert_array_equal(
            np.asarray(v), np.asarray(resumed.state["params"][k]),
            err_msg=f"param {k} diverged across preempt+resume",
        )


def test_preempt_sigterm_subprocess_resume_bitwise(tmp_path):
    """The real thing: a trainer process receives an actual SIGTERM mid-pass
    (sent to itself right after a step, so the timing is deterministic),
    exits with the distinct EXIT_PREEMPTED code, and a restarted process with
    auto_resume=True finishes the run bitwise-identical to a clean one."""
    out = str(tmp_path)

    def run(mode, *extra):
        return subprocess.run(
            [sys.executable, WORKER, "preempt_trainer", out, mode, *extra],
            env=_child_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, timeout=120,
        )

    r = run("run", "1", "2")  # SIGTERM itself after pass 1, batch 2
    assert r.returncode == preempt.EXIT_PREEMPTED, r.stdout[-2000:]
    assert os.path.isdir(os.path.join(out, "ckpt", "pass-00001"))

    r = run("resume")
    assert r.returncode == 0, r.stdout[-2000:]
    r = run("clean")
    assert r.returncode == 0, r.stdout[-2000:]

    got = dict(np.load(os.path.join(out, "params_resume.npz")))
    want = dict(np.load(os.path.join(out, "params_clean.npz")))
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(want[k], got[k], err_msg=k)


def test_second_signal_escalates():
    """Double-SIGTERM semantics: the first notice only sets the drain flag;
    a second one while draining restores the PREVIOUS handler and
    re-delivers — no graceful hang when the operator really means it."""
    import signal as _signal

    hits = []
    prev = _signal.signal(_signal.SIGTERM, lambda *a: hits.append(1))
    try:
        guard = preempt.install(grace_s=30.0)  # records our recorder as prior
        os.kill(os.getpid(), _signal.SIGTERM)
        assert guard.requested
        assert hits == []  # first notice handled by the guard alone
        os.kill(os.getpid(), _signal.SIGTERM)
        assert hits == [1]  # escalated to the prior handler
    finally:
        preempt.reset()
        _signal.signal(_signal.SIGTERM, prev)


# -- barrier timeout diagnostic ----------------------------------------------


def test_barrier_timeout_names_missing_processes(monkeypatch):
    """parallel.distributed.barrier with a coordinator: on timeout it must
    say WHICH process ids never arrived instead of hanging forever."""
    import jax

    from paddle_tpu.parallel.distributed import BarrierTimeout, barrier

    class StubClient:
        def __init__(self):
            self.kv = {}

        def key_value_set(self, k, v):
            self.kv[k] = v

        def key_value_dir_get(self, prefix):
            return [(k, v) for k, v in self.kv.items() if k.startswith(prefix)]

        def wait_at_barrier(self, bid, timeout_ms):
            # process 2 also made it; 1 and 3 never arrived
            self.kv[f"{bid}/arrived/2"] = "x"
            raise RuntimeError("DEADLINE_EXCEEDED: Barrier timed out")

    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with pytest.raises(BarrierTimeout, match=r"\[1, 3\]"):
        barrier("unit", timeout_s=0.01, _client=StubClient())


def test_barrier_single_process_fast_path():
    from paddle_tpu.parallel.distributed import barrier

    barrier("solo", timeout_s=5.0)  # psum path; must simply not hang
