"""Round-3 layer-breadth additions: 3-D conv/pool (Conv3DLayer.cpp,
Pool3DLayer.cpp), MDLSTM (MDLstmLayer.cpp), linear_comb/cos_vm, and the beam
machinery (SubNestedSequenceLayer.cpp, CrossEntropyOverBeam.cpp) — each
checked against an independent numpy/oracle formulation plus gradient
finiteness."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn import layers as L
from paddle_tpu.nn import layers3d as L3
from paddle_tpu.nn import recurrent as R
from paddle_tpu.nn import seq_layers as S
from paddle_tpu.nn import struct_costs as SC
from paddle_tpu.nn.graph import Argument, Network, reset_name_scope


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()


def test_conv3d_matches_manual_window_sum():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 4, 5, 6, 3).astype(np.float32)
    d = L.Data("x", shape=(4, 5, 6, 3))
    conv = L3.Conv3D(d, num_filters=2, filter_size=2, stride=1, padding=0,
                     act=None, bias=False, name="c3")
    net = Network(conv)
    params, states = net.init(jax.random.PRNGKey(0), {"x": x})
    outs, _ = net.apply(params, states, {"x": x})
    got = np.asarray(outs["c3"].value)
    w = np.asarray(params["c3.w"])  # [2,2,2,3,2]
    # manual direct convolution at a few positions
    for (b, dd, hh, ww) in [(0, 0, 0, 0), (1, 2, 3, 4), (0, 1, 2, 2)]:
        patch = x[b, dd:dd + 2, hh:hh + 2, ww:ww + 2, :]
        want = np.tensordot(patch, w, axes=([0, 1, 2, 3], [0, 1, 2, 3]))
        np.testing.assert_allclose(got[b, dd, hh, ww], want, rtol=1e-4, atol=1e-4)
    assert got.shape == (2, 3, 4, 5, 2)


def test_conv3d_transpose_is_adjoint_of_conv3d():
    """<conv(x), y> == <x, conv_T(y)> — the defining adjoint property."""
    from paddle_tpu.ops import conv as conv_ops

    rs = np.random.RandomState(1)
    x = rs.randn(1, 5, 5, 5, 2).astype(np.float32)
    w = rs.randn(3, 3, 3, 2, 5).astype(np.float32) * 0.1
    y = conv_ops.conv3d(x, w, stride=2, padding=1)  # [1, 3, 3, 3, 5]
    u = rs.randn(*y.shape).astype(np.float32)
    # transpose takes the fwd conv's weight as-is ([k,k,k, Cout_of_T, Cin_of_T])
    xt = conv_ops.conv3d_transpose(u, w, stride=2, padding=1)
    assert xt.shape == x.shape
    lhs = float(jnp.sum(y * u))
    rhs = float(jnp.sum(jnp.asarray(x) * xt))
    np.testing.assert_allclose(lhs, rhs, rtol=1e-3)


def test_pool3d_max_and_avg():
    rs = np.random.RandomState(2)
    x = rs.randn(2, 4, 4, 4, 3).astype(np.float32)
    d = L.Data("x", shape=(4, 4, 4, 3))
    mp = L3.Pool3D(d, 2, "max", name="mp")
    ap = L3.Pool3D(d, 2, "avg", name="ap")
    net = Network([mp, ap])
    params, states = net.init(jax.random.PRNGKey(0), {"x": x})
    outs, _ = net.apply(params, states, {"x": x})
    want_max = x.reshape(2, 2, 2, 2, 2, 2, 2, 3).max((2, 4, 6))
    want_avg = x.reshape(2, 2, 2, 2, 2, 2, 2, 3).mean((2, 4, 6))
    np.testing.assert_allclose(np.asarray(outs["mp"].value), want_max, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs["ap"].value), want_avg, rtol=1e-5)


@pytest.mark.parametrize("directions", [(True, True), (False, True),
                                        (True, False), (False, False)])
def test_mdlstm_matches_percell_oracle(directions):
    from paddle_tpu.ops import mdlstm as M

    rs = np.random.RandomState(3)
    hid = 4
    proj = rs.randn(2, 3, 5, 5 * hid).astype(np.float32) * 0.5
    p = M.MDLstmParams(
        w_h=rs.randn(hid, 5 * hid).astype(np.float32) * 0.3,
        bias=rs.randn(5 * hid).astype(np.float32) * 0.1,
        check_i=rs.randn(hid).astype(np.float32) * 0.1,
        check_f=rs.randn(2, hid).astype(np.float32) * 0.1,
        check_o=rs.randn(hid).astype(np.float32) * 0.1,
    )
    got = np.asarray(M.mdlstm_2d(jnp.asarray(proj), p, directions))
    want = M.mdlstm_2d_reference(proj, p, directions)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_mdlstm_layer_gradients_finite():
    rs = np.random.RandomState(4)
    hid = 3
    x = rs.randn(2, 3, 4, 5 * hid).astype(np.float32) * 0.3
    d = L.Data("x", shape=(3, 4, 5 * hid))
    md = R.MDLstm(d, size=hid, name="md")
    net = Network(md)
    params, states = net.init(jax.random.PRNGKey(0), {"x": x})

    def loss(p):
        outs, _ = net.apply(p, states, {"x": x})
        return jnp.sum(outs["md"].value ** 2)

    g = jax.grad(loss)(params)
    for k, v in g.items():
        assert np.all(np.isfinite(np.asarray(v))), k
    assert float(jnp.abs(g["md.w_h"]).sum()) > 0


def test_linear_comb_and_cos_vm():
    rs = np.random.RandomState(5)
    m, n, b = 3, 4, 2
    wts = rs.randn(b, m).astype(np.float32)
    vecs = rs.randn(b, m * n).astype(np.float32)
    dw = L.Data("w", shape=(m,))
    dv = L.Data("v", shape=(m * n,))
    lc = L.LinearComb(dw, dv, name="lc")
    cv = L.CosSimVecMat(dw, dv, scale=2.0, name="cv")
    net = Network([lc, cv])
    params, states = net.init(jax.random.PRNGKey(0), {"w": wts, "v": vecs})
    outs, _ = net.apply(params, states, {"w": wts, "v": vecs})
    # linear_comb: z = x^T Y with Y = vectors.reshape(M, N) (layers.py:4984)
    want_lc = np.einsum("bm,bmn->bn", wts, vecs.reshape(b, m, n))
    np.testing.assert_allclose(np.asarray(outs["lc"].value), want_lc, rtol=1e-5)
    # cos_vm: rows laid out by step M (CosSimVecMatLayer.cpp)
    mat = vecs.reshape(b, n, m)
    want_cv = 2.0 * np.einsum("bm,bnm->bn", wts, mat) / (
        np.linalg.norm(wts, axis=1, keepdims=True) * np.linalg.norm(mat, axis=2)
    )
    np.testing.assert_allclose(np.asarray(outs["cv"].value), want_cv,
                               rtol=1e-4, atol=1e-5)


def test_sub_nested_seq_selects_subsequences():
    rs = np.random.RandomState(6)
    val = rs.randn(2, 4, 3, 5).astype(np.float32)  # [B, S, T, D]
    sub_l = np.array([[3, 2, 1, 3], [2, 2, 2, 0]], np.int32)
    sel = np.array([[2, 0], [1, -1]], np.int32)
    nested = Argument(jnp.asarray(val), lengths=jnp.asarray([4, 3]),
                      sub_lengths=jnp.asarray(sub_l))
    layer = S.SubNestedSeq.__new__(S.SubNestedSeq)
    layer.name = "sns"
    out = layer.forward(None, [nested, Argument(jnp.asarray(sel))])
    got = np.asarray(out.value)
    np.testing.assert_allclose(got[0, 0], val[0, 2])
    np.testing.assert_allclose(got[0, 1], val[0, 0])
    np.testing.assert_allclose(got[1, 0], val[1, 1])
    np.testing.assert_allclose(got[1, 1], 0.0)  # -1 pad → zeroed
    np.testing.assert_array_equal(np.asarray(out.sub_lengths),
                                  [[1, 3], [2, 0]])
    np.testing.assert_array_equal(np.asarray(out.lengths), [2, 1])


def _beam_cost_oracle(scores, selected, gold):
    """Slow per-sample reimplementation of CostForOneSequence::forward for
    the dense encoding."""
    bsz = scores[0].shape[0]
    out = np.zeros(bsz)
    for b in range(bsz):
        prefix_sel = None
        gold_prefix = 0.0
        costs_t, hits_t = [], []
        for sc, sel, g in zip(scores, selected, gold):
            n = sc.shape[1]
            k_prev = 1 if prefix_sel is None else len(prefix_sel)
            seg = n // k_prev
            base = np.zeros(n) if prefix_sel is None else np.repeat(prefix_sel, seg)
            path = base + sc[b]
            sel_b = sel[b]
            valid = sel_b >= 0
            sel_scores = np.where(valid, path[np.maximum(sel_b, 0)], -1e30)
            gold_score = gold_prefix + sc[b, g[b]]
            hit = bool(np.any(valid & (sel_b == g[b])))
            logits = list(sel_scores) + ([] if hit else [gold_score])
            mx = max(logits)
            lse = mx + np.log(sum(np.exp(l - mx) for l in logits))
            costs_t.append(lse - gold_score)
            hits_t.append(hit)
            gold_prefix = gold_score
            prefix_sel = sel_scores
        # cost at the first expansion where gold fell off, else the last
        cut = next((t for t, h in enumerate(hits_t) if not h), len(costs_t) - 1)
        out[b] = costs_t[cut]
    return out


def test_cross_entropy_over_beam_matches_oracle():
    rs = np.random.RandomState(7)
    bsz, k = 3, 2
    scores = [rs.randn(bsz, 4).astype(np.float32),
              rs.randn(bsz, 2 * 3).astype(np.float32)]
    selected = [np.array([[1, 3], [0, 2], [2, -1]], np.int32),
                np.array([[0, 4], [1, 5], [3, 2]], np.int32)]
    # sample 0: gold in both beams; sample 1: falls off at t=1;
    # sample 2: falls off at t=0
    gold = [np.array([3, 0, 1], np.int32), np.array([4, 2, 0], np.int32)]

    layer = SC.CrossEntropyOverBeam.__new__(SC.CrossEntropyOverBeam)
    layer.name = "beam_ce"
    layer.beams = [None, None]
    ins = []
    for t in range(2):
        ins += [Argument(jnp.asarray(scores[t])),
                Argument(jnp.asarray(selected[t])),
                Argument(jnp.asarray(gold[t]))]
    got = float(layer.forward(None, ins).value)
    want = float(np.mean(_beam_cost_oracle(scores, selected, gold)))
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_v1_and_v2_wrappers_resolve():
    from paddle_tpu.config import helpers as H
    from paddle_tpu.v2 import layer as vl

    for name in ("img_conv3d_layer", "img_pool3d_layer", "linear_comb_layer",
                 "convex_comb_layer", "sub_nested_seq_layer",
                 "cross_entropy_over_beam", "BeamInput"):
        assert hasattr(H, name), name
    for name in ("img_conv3d", "img_pool3d", "linear_comb", "convex_comb",
                 "mdlstm", "sub_nested_seq", "cross_entropy_over_beam"):
        assert hasattr(vl, name), name
    # registry parity for the new type names
    from paddle_tpu.core.registry import LAYERS
    for t in ("conv3d", "deconv3d", "pool3d", "mdlstmemory", "convex_comb",
              "cos_vm", "sub_nested_seq", "cross_entropy_over_beam"):
        assert LAYERS.get(t) is not None, t
