"""Model zoo smoke + LeNet convergence (test_TrainerOnePass analog for the
BASELINE configs) on tiny shapes."""

import jax
import numpy as np
import pytest

from paddle_tpu.nn.graph import Network, reset_name_scope
from paddle_tpu import models


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def _smoke(builder, image_size, classes=10, batch=2, **kw):
    img, label, logits, cost = builder(num_classes=classes, image_size=image_size, **kw)
    net = Network([cost, logits])
    rs = np.random.RandomState(0)
    batch_data = {
        img.name: rs.randn(batch, image_size, image_size, 3).astype(np.float32),
        label.name: rs.randint(0, classes, batch),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch_data)
    outs, _ = net.apply(params, states, batch_data, train=False)
    assert outs[logits.name].value.shape == (batch, classes)
    assert np.isfinite(float(outs[cost.name].value))
    return params


def test_resnet50_tiny():
    # image 32 keeps CPU time sane; stage/block structure identical to 224
    params = _smoke(models.resnet50, 32)
    # 53 convs + bn scales etc.
    n_convs = sum(1 for k in params if k.endswith(".conv.w"))
    assert n_convs == 53


def test_vgg16_tiny():
    _smoke(models.vgg16, 32)


def test_alexnet():
    _smoke(models.alexnet, 224)


def test_googlenet_tiny():
    _smoke(models.googlenet, 64)


def test_lenet_converges():
    from paddle_tpu.data import DataFeeder, dense_array, integer_value, reader as rd
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGDTrainer

    img, label, logits, cost = models.lenet()
    rs = np.random.RandomState(0)
    # synthetic "digits": class k = blob at position k
    xs, ys = [], []
    for i in range(128):
        y = i % 10
        im = np.zeros((28, 28, 1), np.float32)
        im[2 * y : 2 * y + 6, 2 * y : 2 * y + 6] = 1.0
        im += rs.randn(28, 28, 1).astype(np.float32) * 0.1
        xs.append(im)
        ys.append(y)

    def reader():
        for x, y in zip(xs, ys):
            yield {"pixel": x, "label": y}

    trainer = SGDTrainer(cost, Adam(learning_rate=0.003))
    feeder = DataFeeder({"pixel": dense_array((28, 28, 1)), "label": integer_value(10)})
    state = trainer.train(rd.batch(reader, 32, drop_last=True), num_passes=6, feeder=feeder)
    res = trainer.test(rd.batch(reader, 32, drop_last=True), feeder)
    assert res["cost"] < 0.5, f"LeNet failed to learn: {res}"


def test_ctr_wide_deep_trains():
    """BASELINE config #4: wide&deep overfits a separable click pattern."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import models
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    ins, label, prob, cost = models.ctr_wide_deep(
        wide_dim=32, slot_vocab_sizes=(16, 16), embed_dim=8, hidden_dims=(16,)
    )
    net = Network([cost, prob])
    rs = np.random.RandomState(0)
    slot0 = rs.randint(0, 16, 32)
    click = (slot0 % 2).astype(np.float32)[:, None]  # click ⇔ even slot0 id
    batch = {
        "wide_features": rs.rand(32, 32).astype(np.float32) * 0.1,
        "slot0_id": slot0,
        "slot1_id": rs.randint(0, 16, 32),
        "click": click,
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(
            lambda p: net.apply(p, states, batch)[0][cost.name].value
        )(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.5 * b, p, g), l

    l0 = None
    for _ in range(60):
        params, l = step(params)
        l0 = l0 if l0 is not None else float(l)
    assert l0 / float(l) > 2.0, (l0, float(l))
    outs, _ = net.apply(params, states, batch)
    pred = (np.asarray(outs[prob.name].value) > 0.5).astype(np.float32)
    assert (pred == click).mean() > 0.9


def test_ocr_crnn_ctc_trains():
    """BASELINE config #5: CRNN+CTC loss drops on a fixed batch."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import models
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    img, lbl, logits, cost = models.ocr_crnn(
        image_height=32, image_width=64, num_classes=10, rnn_hidden=16
    )
    net = Network([cost])
    rs = np.random.RandomState(0)
    batch = {
        "image": rs.randn(2, 32, 64, 1).astype(np.float32),
        "label": rs.randint(1, 11, (2, 6)).astype(np.int32),
        "label.lengths": np.asarray([6, 4], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(
            lambda p: net.apply(p, states, batch, train=False)[0][cost.name].value
        )(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.01 * b, p, g), l

    l0 = None
    for _ in range(25):
        params, l = step(params)
        l0 = l0 if l0 is not None else float(l)
    assert float(l) < l0 * 0.8, (l0, float(l))
