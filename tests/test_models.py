"""Model zoo smoke + LeNet convergence (test_TrainerOnePass analog for the
BASELINE configs) on tiny shapes."""

import jax
import numpy as np
import pytest

from paddle_tpu.nn.graph import Network, reset_name_scope
from paddle_tpu import models


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def _smoke(builder, image_size, classes=10, batch=2, **kw):
    img, label, logits, cost = builder(num_classes=classes, image_size=image_size, **kw)
    net = Network([cost, logits])
    rs = np.random.RandomState(0)
    batch_data = {
        img.name: rs.randn(batch, image_size, image_size, 3).astype(np.float32),
        label.name: rs.randint(0, classes, batch),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch_data)
    outs, _ = net.apply(params, states, batch_data, train=False)
    assert outs[logits.name].value.shape == (batch, classes)
    assert np.isfinite(float(outs[cost.name].value))
    return params


def test_resnet50_tiny():
    # image 32 keeps CPU time sane; stage/block structure identical to 224
    params = _smoke(models.resnet50, 32)
    # 53 convs + bn scales etc.
    n_convs = sum(1 for k in params if k.endswith(".conv.w"))
    assert n_convs == 53


def test_vgg16_tiny():
    _smoke(models.vgg16, 32)


def test_alexnet():
    _smoke(models.alexnet, 224)


def test_googlenet_tiny():
    _smoke(models.googlenet, 64)


def test_lenet_converges():
    from paddle_tpu.data import DataFeeder, dense_array, integer_value, reader as rd
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGDTrainer

    img, label, logits, cost = models.lenet()
    rs = np.random.RandomState(0)
    # synthetic "digits": class k = blob at position k
    xs, ys = [], []
    for i in range(128):
        y = i % 10
        im = np.zeros((28, 28, 1), np.float32)
        im[2 * y : 2 * y + 6, 2 * y : 2 * y + 6] = 1.0
        im += rs.randn(28, 28, 1).astype(np.float32) * 0.1
        xs.append(im)
        ys.append(y)

    def reader():
        for x, y in zip(xs, ys):
            yield {"pixel": x, "label": y}

    trainer = SGDTrainer(cost, Adam(learning_rate=0.003))
    feeder = DataFeeder({"pixel": dense_array((28, 28, 1)), "label": integer_value(10)})
    state = trainer.train(rd.batch(reader, 32, drop_last=True), num_passes=6, feeder=feeder)
    res = trainer.test(rd.batch(reader, 32, drop_last=True), feeder)
    assert res["cost"] < 0.5, f"LeNet failed to learn: {res}"
