"""Continuous-batching serving runtime (ISSUE 6).

The load-bearing claims, each tested directly:

  * batching transparency — a request's generated tokens are IDENTICAL
    whether it ran alone, in a full batch, or joined/retired mid-stream
    (per-slot computation never crosses the slot dimension), and they match
    a naive full-context greedy reference;
  * one decode program — a mixed-length request stream records exactly one
    decode-step shape signature (the PR-1 RecompileStats zero-recompile
    assertion);
  * KV paging — pages are reserved at admission, recycled at retirement,
    and reused by later requests;
  * admission control — queue bounds, per-tenant token quotas and
    concurrency caps reject at the front door;
  * the front-end — register/heartbeat tenant leases over the master's
    line-JSON plane, blocking generate, submit/poll, eviction cancelling
    queued work;
  * GenerationSession — build/load once, generate many (run_generation's
    rebuild-per-call hoisted out)."""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serving


VOCAB = 96


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    return ServingSession(model, params, **kw)


def greedy_reference(model, params, prompt, max_new):
    """Naive sequential decode: full-context forward per token — the
    semantics `run_generation`-style serving gives one request at a time."""
    import jax.numpy as jnp

    toks, out = list(prompt), []
    for _ in range(max_new):
        logits = model.forward_logits(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if nxt == model.cfg.eos_id:
            break
    return out


PROMPTS = [
    [1, 5, 9, 11],
    [1, 7],
    [1, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18],
    [1, 40, 41, 42, 43, 44, 45, 46],
    [1, 90, 2, 90],  # early EOS-ish content; exercises retire-before-others
    [1] + list(range(3, 30)),
]


def test_batched_equals_sequential_and_reference(model_and_params):
    """The acceptance bit: dynamic batching changes THROUGHPUT, never
    tokens. All-at-once == one-at-a-time == full-context reference."""
    model, params = model_and_params

    batched = make_session(model_and_params)
    handles = [batched.submit(p, 10) for p in PROMPTS]
    batched.run_until_idle()
    got_batched = [h.tokens for h in handles]

    sequential = make_session(model_and_params)
    got_sequential = []
    for p in PROMPTS:
        h = sequential.submit(p, 10)
        sequential.run_until_idle()
        got_sequential.append(h.tokens)

    assert got_batched == got_sequential
    ref = [greedy_reference(model, params, p, 10) for p in PROMPTS]
    assert got_batched == ref


def test_midstream_join_and_retire(model_and_params):
    """A request joining at a step boundary neither perturbs the running
    request (bitwise) nor waits for it (retires first when shorter)."""
    s = make_session(model_and_params)
    long = s.submit(PROMPTS[2], 16)
    # advance a few decode steps before the join
    for _ in range(4):
        s.step()
    assert not long.done
    short = s.submit(PROMPTS[1], 3)
    order = []

    while s.scheduler.has_work():
        s.step()
        for name, h in (("short", short), ("long", long)):
            if h.done and name not in order:
                order.append(name)
    assert order == ["short", "long"], "shorter joiner must retire first"

    # bitwise unperturbed vs running each alone
    alone = make_session(model_and_params)
    h_long = alone.submit(PROMPTS[2], 16)
    alone.run_until_idle()
    h_short = alone.submit(PROMPTS[1], 3)
    alone.run_until_idle()
    assert long.tokens == h_long.tokens
    assert short.tokens == h_short.tokens


def test_kv_page_recycling(model_and_params):
    s = make_session(model_and_params)
    total_free = s.cache.free_pages
    h = s.submit(PROMPTS[0], 8)
    s._admit()
    used_first = s.cache.slot_pages(0)
    assert used_first and s.cache.free_pages == total_free - len(used_first)
    s.run_until_idle()
    assert h.done
    assert s.cache.free_pages == total_free, "retirement must return pages"

    # a later request must REUSE the recycled physical pages
    s.submit(PROMPTS[1], 8)
    s._admit()
    reused = s.cache.slot_pages(0)
    assert set(reused) <= set(used_first)
    s.run_until_idle()
    assert s.cache.free_pages == total_free


def test_zero_decode_recompiles_on_mixed_stream(model_and_params):
    """Variable lengths, variable ages, joins and retires — ONE decode
    signature for the whole lifetime (the compiled-program-sharing claim)."""
    s = make_session(model_and_params)
    # warmup: one request per bucket
    for ln in s.buckets:
        s.submit([1] + [3] * (ln - 1), 4)
    s.run_until_idle()
    assert s.decode_shape_signatures() == 1
    sigs0 = s.decode_shape_signatures()

    handles = [s.submit(p, 12) for p in PROMPTS * 2]
    s.run_until_idle()
    assert all(h.done for h in handles)
    assert s.decode_shape_signatures() - sigs0 == 0
    assert s.decode_shape_signatures() == 1


def test_prefill_compiles_bounded_by_buckets(model_and_params):
    """Prompt lengths 2..18 land in 3 buckets -> at most 3 prefill shapes
    (the 'few padded lengths' contract; jit's cache is keyed on shape)."""
    s = make_session(model_and_params)
    for ln in (2, 3, 5, 8, 9, 12, 16, 17, 18):
        s.submit([1] + [3] * (ln - 1), 2)
    s.run_until_idle()
    try:
        n = s._prefill._cache_size()
    except AttributeError:
        pytest.skip("jit cache introspection unavailable on this jax")
    assert n <= len(s.buckets)


def test_quota_and_queue_rejection(model_and_params):
    from paddle_tpu.serving.quota import QuotaExceeded, TenantQuotas

    quotas = TenantQuotas(token_capacity=40, tokens_per_s=0.0, max_concurrent=2)
    s = make_session(model_and_params, quotas=quotas, max_queue=3)

    # token quota: prompt 4 + max_new 16 = 20 per request; third exceeds 40
    a = s.submit(PROMPTS[0], 16, tenant="t1")
    b = s.submit(PROMPTS[0], 16, tenant="t1")  # noqa: F841 — holds quota
    with pytest.raises(QuotaExceeded) as ei:
        s.submit(PROMPTS[0], 16, tenant="t1")
    assert ei.value.reason in ("tokens", "concurrency")
    # another tenant is unaffected (per-tenant bucket)
    c = s.submit(PROMPTS[1], 4, tenant="t2")
    s.run_until_idle()
    assert a.done and c.done
    assert s.scheduler.rejected == 1

    # refund accounting: releasing returns UNUSED tokens (early EOS) and
    # frees the concurrency hold — after a manual refund t1 can submit again
    quotas.release("t1", unused_tokens=20)
    quotas.admit("t1", 20)
    quotas.release("t1", 20)

    # queue bound: an unserved flood rejects at max_queue
    s2 = make_session(model_and_params, max_queue=2)
    s2.scheduler.submit([1, 2], 2, "x")
    s2.scheduler.submit([1, 2], 2, "x")
    with pytest.raises(QuotaExceeded) as ei:
        s2.scheduler.submit([1, 2], 2, "x")
    assert ei.value.reason == "queue"


def test_oversize_requests_rejected_up_front(model_and_params):
    s = make_session(model_and_params)
    with pytest.raises(ValueError):
        s.submit([1] * 33, 4)  # beyond the largest bucket
    with pytest.raises(ValueError):
        s.submit([], 4)


@pytest.mark.timeout(120)
def test_server_roundtrip_and_eviction(model_and_params):
    """The line-JSON front-end: register/lease, blocking generate,
    submit/poll, stats, and lease-expiry cancelling queued requests."""
    from paddle_tpu.serving.quota import TenantQuotas
    from paddle_tpu.serving.server import ServingClient, ServingServer

    s = make_session(
        model_and_params,
        quotas=TenantQuotas(max_concurrent=8),
    )
    srv = ServingServer(session=s, lease_s=1.0, require_register=True).start()
    try:
        c = ServingClient(srv.address)
        with pytest.raises(RuntimeError):
            c.generate(PROMPTS[0], 4)  # unregistered
        # a fabricated tenant_id must NOT pass for registered (it would mint
        # itself a fresh quota bucket per request)
        c.tenant_id = "tr-forged-999"
        with pytest.raises(RuntimeError):
            c.generate(PROMPTS[0], 4)
        c.tenant_id = None
        tid = c.register()
        assert tid
        r = c.generate(PROMPTS[0], 6)
        assert r["done"] and len(r["tokens"]) <= 6
        # async submit/poll
        rid = c.submit(PROMPTS[1], 4)
        for _ in range(200):
            p = c.poll(rid)
            if p.get("done"):
                break
            time.sleep(0.02)
        assert p["done"] and p["finish_reason"] in ("length", "eos")
        st = c.stats()
        assert st["live_tenants"] >= 1 and st["completed"] >= 2
        # retry-exactness: a resent submit with the same idempotency key
        # reattaches to the SAME request (no duplicate queueing/charging)
        r1 = srv.dispatch(
            "submit",
            {"prompt": PROMPTS[1], "max_new_tokens": 2, "client_req_id": "k1"},
            tid,
        )
        r2 = srv.dispatch(
            "submit",
            {"prompt": PROMPTS[1], "max_new_tokens": 2, "client_req_id": "k1"},
            tid,
        )
        assert r1["request_id"] == r2["request_id"]
        # identical tokens through the wire as in-process
        direct = make_session(model_and_params)
        h = direct.submit(PROMPTS[0], 6)
        direct.run_until_idle()
        assert r["tokens"] == h.tokens
        c.close()

        # eviction: stop the ENGINE so a queued request cannot start, let the
        # lease lapse, and verify the reaper cancels the tenant's queued work
        s.stop()
        c2 = ServingClient(srv.address)
        t2 = c2.register()
        rid2 = c2.submit(PROMPTS[0], 4)
        c2.close()  # silent from here on — the lease must lapse
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with srv._handles_lock:
                h2 = srv._handles.get(rid2)
            if h2 is not None and h2.done:
                break
            time.sleep(0.05)
        assert h2 is not None and h2.status == h2.CANCELLED
        assert srv.membership.evicted >= 1
        assert t2 != tid
    finally:
        srv.stop()


@pytest.mark.timeout(180)
def test_cli_serve_subprocess(tmp_path):
    """`python -m paddle_tpu serve --demo` as a real OS process: prints its
    address, serves a generate RPC, drains cleanly on SIGTERM."""
    import json
    import os
    import signal
    import subprocess
    import sys

    from paddle_tpu.serving.server import ServingClient

    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve", "--demo",
         "--max_slots=2", "--page_size=8", "--prefill_buckets=8,16",
         "--max_new_limit=8"],
        stdout=subprocess.PIPE, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    try:
        line = proc.stdout.readline()
        addr = json.loads(line)["address"]
        c = ServingClient((addr[0], int(addr[1])))
        r = c.generate([1, 5, 9], max_new_tokens=6, timeout_s=60.0)
        assert r["done"] and 0 < len(r["tokens"]) <= 6
        c.close()
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert proc.returncode == 0


def test_generation_session_builds_once(monkeypatch):
    """GenerationSession: the Network is initialized and the checkpoint
    loaded ONCE; repeat generates reuse the same parameter buffers and
    reproduce run_generation exactly."""
    import jax.numpy as jnp

    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.trainer import generation as G

    reset_name_scope()
    x = L.Data("x", shape=(4,))
    out = L.Fc(x, 3, act=None, name="gen_out")

    class _Ctx:
        evaluators = []

    class _PC:
        outputs = [out]
        context = _Ctx()

    sess = G.GenerationSession(_PC())
    batch = {"x": np.ones((2, 4), np.float32)}
    assert not sess.built
    assert sess.generate(batch) == {}  # no printers declared -> nothing written
    assert sess.built
    params_first = sess._params
    sess.generate(batch)
    assert sess._params is params_first, "repeat generate must not re-init"

    # the wrapper path is the same code
    assert G.run_generation(_PC(), batch) == {}

    # init counted: a second generate must not call Network.init again
    calls = {"n": 0}
    real_init = sess.net.init

    def counting_init(*a, **k):
        calls["n"] += 1
        return real_init(*a, **k)

    sess2 = G.GenerationSession(_PC())
    monkeypatch.setattr(sess2.net, "init", counting_init)
    sess2.generate(batch)
    sess2.generate(batch)
    assert calls["n"] <= 1
