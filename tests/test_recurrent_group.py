"""recurrent_group / memory / beam_search tests — the RecurrentGradientMachine
API surface (RecurrentGradientMachine.h:32; trainer_config_helpers
recurrent_group/memory/StaticInput/GeneratedInput/beam_search). Gradient checks
follow the LayerGradUtil idiom (gserver/tests/LayerGradUtil.h:298): analytic
jax.grad vs numeric perturbation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn.graph import Network, reset_name_scope
from paddle_tpu.v2 import layer as vl
from paddle_tpu.v2.activation import Softmax, Tanh
from paddle_tpu.data.feeder import dense_vector_sequence


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    yield


def _seq_batch(b=4, t=6, d=8, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "x": rs.randn(b, t, d).astype(np.float32),
        "x.lengths": np.asarray([t, 3, t, 2][:b], np.int32),
    }


def _build_rnn(reverse=False):
    seq = vl.data(name="x", type=dense_vector_sequence(8))

    def step(x_t):
        mem = vl.memory(name="rnn_out", size=16)
        return vl.fc(input=[x_t, mem], size=16, act=Tanh(), name="rnn_out")

    return seq, vl.recurrent_group(step, seq, reverse=reverse)


def test_recurrent_group_matches_manual_unroll():
    _, g = _build_rnn()
    net = Network([g])
    batch = _seq_batch()
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    got = np.asarray(outs[g.name].value)

    # manual unroll with the same weights (Fc keeps one W per input):
    # h_t = tanh(x_t W0 + h_{t-1} W1 + b)
    w0 = np.asarray(params["rnn_out.w.0"])
    w1 = np.asarray(params["rnn_out.w.1"])
    b = np.asarray(params["rnn_out.b"])
    x = batch["x"]
    lens = batch["x.lengths"]
    h = np.zeros((x.shape[0], 16), np.float32)
    want = np.zeros((x.shape[0], x.shape[1], 16), np.float32)
    for t in range(x.shape[1]):
        new = np.tanh(x[:, t] @ w0 + h @ w1 + b)
        valid = (t < lens)[:, None]
        h = np.where(valid, new, h)
        want[:, t] = new
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_recurrent_group_grad_flows_through_time():
    _, g = _build_rnn()
    pooled = vl.last_seq(input=g)
    net = Network([pooled])
    batch = _seq_batch()
    params, states = net.init(jax.random.PRNGKey(0), batch)

    def loss(p):
        o, _ = net.apply(p, states, batch)
        return jnp.sum(o[pooled.name].value ** 2)

    g_analytic = jax.grad(loss)(params)
    # numeric check on a few weight entries (LayerGradUtil idiom)
    key = "rnn_out.w.1"  # the recurrent weight: grads must flow through time
    eps = 1e-3
    for idx in [(0, 0), (8, 3), (15, 15)]:
        p_plus = dict(params)
        p_plus[key] = params[key].at[idx].add(eps)
        p_minus = dict(params)
        p_minus[key] = params[key].at[idx].add(-eps)
        num = (loss(p_plus) - loss(p_minus)) / (2 * eps)
        # f32 central differences carry ~1e-3 absolute noise at this loss scale
        np.testing.assert_allclose(
            float(g_analytic[key][idx]), float(num), rtol=8e-2, atol=3e-3
        )


def test_recurrent_group_reverse():
    _, g = _build_rnn(reverse=True)
    net = Network([g])
    batch = _seq_batch()
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    got = np.asarray(outs[g.name].value)
    # reversed processing: last valid step has zero-memory input at t = T-1
    w0 = np.asarray(params["rnn_out.w.0"])
    w1 = np.asarray(params["rnn_out.w.1"])
    b = np.asarray(params["rnn_out.b"])
    x = batch["x"][0]
    h = np.zeros(16, np.float32)
    want_last = None
    for t in range(x.shape[0] - 1, -1, -1):
        h = np.tanh(x[t] @ w0 + h @ w1 + b)
        want_last = h
    np.testing.assert_allclose(got[0, 0], want_last, rtol=1e-5, atol=1e-5)


def test_get_output_layer_second_output():
    seq = vl.data(name="x", type=dense_vector_sequence(8))

    def step(x_t):
        mem = vl.memory(name="h", size=8)
        h = vl.fc(input=[x_t, mem], size=8, act=Tanh(), name="h")
        o = vl.fc(input=h, size=4, act=Softmax(), name="o")
        return [o, h]

    # multi-output steps return a tuple (the reference's contract); the
    # second output is also reachable via get_output_layer on the first
    g, h_tuple = vl.recurrent_group(step, seq)
    h_out = vl.get_output_layer(g, "h")
    net = Network([g, h_out])
    assert h_tuple.core is h_out.core
    batch = _seq_batch()
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    assert outs[g.name].value.shape == (4, 6, 4)
    assert outs[h_out.name].value.shape == (4, 6, 8)
    # probabilities sum to 1 over the softmax axis
    np.testing.assert_allclose(
        np.asarray(outs[g.name].value).sum(-1), np.ones((4, 6)), rtol=1e-5
    )


def test_beam_search_generates_and_respects_eos():
    enc = vl.data(name="enc", type=dense_vector_sequence(8))
    boot = vl.last_seq(input=enc)

    def gen_step(enc_static, cur):
        mem = vl.memory(name="dec", size=8, boot_layer=boot)
        ctx_vec = vl.last_seq(input=enc_static, name="ctxv")
        h = vl.fc(input=[cur, mem, ctx_vec], size=8, act=Tanh(), name="dec")
        return vl.fc(input=h, size=12, act=Softmax(), name="probs")

    gen = vl.beam_search(
        gen_step,
        input=[
            vl.StaticInput(enc, is_seq=True),
            vl.GeneratedInput(size=12, embedding_name="tok_emb", embedding_size=6),
        ],
        bos_id=0, eos_id=1, beam_size=3, max_length=7,
    )
    net = Network([gen])
    rs = np.random.RandomState(0)
    batch = {
        "enc": rs.randn(2, 5, 8).astype(np.float32),
        "enc.lengths": np.asarray([5, 3], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    assert "tok_emb" in params  # embedding param shared under embedding_name
    outs, _ = net.apply(params, states, batch)
    ids = np.asarray(outs[gen.name].value)
    lens = np.asarray(outs[gen.name].lengths)
    assert ids.shape == (2, 7)
    assert ((ids >= 0) & (ids < 12)).all()
    for i in range(2):
        if lens[i] < 7:  # ended on EOS
            assert ids[i, lens[i] - 1] == 1


def test_beam_engine_hand_checkable():
    """nn/beam_core.py beam_search_scan on a fixed-logits toy: beams and
    scores must match hand-computed expansion (the single engine both
    generation entry points wrap)."""
    import jax.numpy as jnp
    from paddle_tpu.nn.beam_core import beam_search_scan

    # vocab 4, eos=3. Step logp depends only on the current token.
    table = np.log(np.asarray([
        [0.1, 0.6, 0.2, 0.1],   # after token 0
        [0.05, 0.05, 0.5, 0.4], # after token 1
        [0.3, 0.3, 0.1, 0.3],   # after token 2
        [0.25, 0.25, 0.25, 0.25],
    ], np.float32))

    def step_fn(tokens, carry, t):
        return jnp.asarray(table)[tokens], carry

    res = beam_search_scan(
        step_fn, carry0=jnp.zeros((2 * 2, 1)), batch=2, vocab=4, bos_id=0,
        eos_id=3, beam_size=2, max_len=2,
    )
    # t=0 from bos(0): top2 = tok1 (0.6), tok2 (0.2)
    # t=1: from tok1: tok2 (0.6*0.5=0.30), tok3 (0.6*0.4=0.24);
    #      from tok2: tok0/1/3 (0.2*0.3=0.06) → top2 = [1,2](0.30), [1,3](0.24)
    hist = np.asarray(res.history)
    scores = np.exp(np.asarray(res.scores))
    np.testing.assert_array_equal(hist[0, 0], [1, 2])
    np.testing.assert_array_equal(hist[0, 1], [1, 3])
    np.testing.assert_allclose(scores[0], [0.30, 0.24], rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(res.lengths)[0], [2, 2])
    # batch row 1 identical (same dynamics)
    np.testing.assert_array_equal(hist[1], hist[0])


def test_nested_recurrent_group_matches_flat_chain():
    """sequence_nest_rnn.conf vs sequence_rnn.conf equivalence
    (gserver/tests/test_RecurrentGradientMachine.cpp idiom): the hierarchical
    group — outer scan over SubsequenceInput, inner rnn booted from an outer
    memory of the last inner state — must equal one flat RNN over the
    concatenated valid tokens."""
    b, s_max, t_sub, d, h = 3, 3, 4, 8, 16
    rs = np.random.RandomState(0)
    x = rs.randn(b, s_max, t_sub, d).astype(np.float32)
    outer_len = np.array([3, 2, 1], np.int32)
    sub_len = np.array([[4, 2, 3], [3, 4, 1], [2, 1, 1]], np.int32)

    seq = vl.data(name="x", type=dense_vector_sequence(d))

    def outer_step(xs):
        outer_mem = vl.memory(name="outer_state", size=h)

        def inner_step(y):
            inner_mem = vl.memory(name="inner_state", size=h, boot_layer=outer_mem)
            return vl.fc(input=[y, inner_mem], size=h, act=Tanh(), name="inner_state")

        inner_out = vl.recurrent_group(inner_step, xs, name="inner_rnn")
        # memory link target only — not a step output (the reference conf's
        # last_seq(name="outer_rnn_state") pattern)
        vl.last_seq(input=inner_out, name="outer_state")
        return inner_out

    out = vl.recurrent_group(outer_step, vl.SubsequenceInput(seq), name="outer_rnn")
    rep = vl.last_seq(input=out)
    net = Network([rep, out])
    batch = {"x": x, "x.lengths": outer_len, "x.sub_lengths": sub_len}
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    got = np.asarray(outs[rep.name].value)          # [B, H]
    nested = outs[out.name]
    assert nested.value.shape == (b, s_max, t_sub, h)
    assert nested.sub_lengths is not None

    # flat chain with the same weights: h_t = tanh(x W0 + h W1 + b) over the
    # concatenated valid tokens of each example (= sequence_rnn.conf)
    w0 = np.asarray(params["inner_state.w.0"])
    w1 = np.asarray(params["inner_state.w.1"])
    bb = np.asarray(params["inner_state.b"])
    want = np.zeros((b, h), np.float32)
    for i in range(b):
        hh = np.zeros(h, np.float32)
        for s in range(outer_len[i]):
            for t in range(sub_len[i, s]):
                hh = np.tanh(x[i, s, t] @ w0 + hh @ w1 + bb)
        want[i] = hh
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    # gradients flow end-to-end through both scans
    def loss(p):
        o, _ = net.apply(p, states, batch)
        return jnp.sum(o[rep.name].value ** 2)

    grads = jax.grad(loss)(params)
    for k in ("inner_state.w.0", "inner_state.w.1", "inner_state.b"):
        assert float(jnp.abs(grads[k]).sum()) > 0.0, k


def test_nested_group_flat_step_output_is_level1_seq():
    """A non-sequence step output of a nested group becomes a level-1 sequence
    over the subsequence index (the reference's seqlastins-in-group shape)."""
    b, s_max, t_sub, d, h = 2, 2, 3, 4, 8
    rs = np.random.RandomState(1)
    x = rs.randn(b, s_max, t_sub, d).astype(np.float32)
    outer_len = np.array([2, 1], np.int32)
    sub_len = np.array([[3, 2], [1, 1]], np.int32)

    seq = vl.data(name="x", type=dense_vector_sequence(d))

    def outer_step(xs):
        def inner_step(y):
            mem = vl.memory(name="m", size=h)
            return vl.fc(input=[y, mem], size=h, act=Tanh(), name="m")

        inner_out = vl.recurrent_group(inner_step, xs, name="in2")
        return vl.last_seq(input=inner_out)

    out = vl.recurrent_group(outer_step, vl.SubsequenceInput(seq), name="outer2")
    net = Network([out])
    batch = {"x": x, "x.lengths": outer_len, "x.sub_lengths": sub_len}
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    arg = outs[out.name]
    assert arg.value.shape == (b, s_max, h)
    assert arg.sub_lengths is None and arg.lengths is not None

    # row 0, subseq 1 should equal running the inner rnn by hand (fresh boot
    # per subsequence — no outer memory in this net)
    w0 = np.asarray(params["m.w.0"]); w1 = np.asarray(params["m.w.1"])
    bb = np.asarray(params["m.b"])
    hh = np.zeros(h, np.float32)
    for t in range(sub_len[0, 1]):
        hh = np.tanh(x[0, 1, t] @ w0 + hh @ w1 + bb)
    np.testing.assert_allclose(np.asarray(arg.value)[0, 1], hh, rtol=2e-5, atol=2e-5)
