"""Detection stack vs small numpy oracles (analog of the reference's
detection tests: gserver/tests/test_PriorBox.cpp, test_DetectionOutput.cpp,
and DetectionMAPEvaluator's eval tests)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import detection as det


def test_prior_boxes_count_and_range():
    boxes, var = det.prior_boxes(
        (2, 2), (32, 32), min_sizes=[8], max_sizes=[16], aspect_ratios=[2.0]
    )
    # per cell: 1 (min) + 1 (sqrt(min*max)) + 2 (ar 2, 1/2) = 4
    assert boxes.shape == (2 * 2 * 4, 4)
    assert var.shape == boxes.shape
    assert (boxes >= 0).all() and (boxes <= 1).all()
    # center of cell (0,0) is (0.25, 0.25)
    np.testing.assert_allclose(
        boxes[0], [0.25 - 0.125, 0.25 - 0.125, 0.25 + 0.125, 0.25 + 0.125]
    )


def test_iou_matrix():
    a = jnp.array([[0.0, 0.0, 1.0, 1.0], [0.0, 0.0, 0.5, 0.5]])
    b = jnp.array([[0.5, 0.5, 1.0, 1.0]])
    got = np.asarray(det.iou_matrix(a, b))
    np.testing.assert_allclose(got[:, 0], [0.25, 0.0], atol=1e-6)


def test_encode_decode_roundtrip(np_rng):
    priors = jnp.asarray(
        np.stack(
            [
                np_rng.uniform(0, 0.4, 12),
                np_rng.uniform(0, 0.4, 12),
                np_rng.uniform(0.5, 1.0, 12),
                np_rng.uniform(0.5, 1.0, 12),
            ],
            1,
        ).astype(np.float32)
    )
    var = jnp.full((12, 4), 0.1)
    gt = priors + 0.05
    enc = det.encode_boxes(priors, var, gt)
    dec = det.decode_boxes(priors, var, enc)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(gt), atol=1e-5)


def test_match_priors_bipartite_overrides_threshold():
    priors = jnp.array(
        [
            [0.0, 0.0, 0.3, 0.3],
            [0.35, 0.35, 0.65, 0.65],
            [0.7, 0.7, 1.0, 1.0],
        ]
    )
    # gt overlaps prior 1 weakly but it's the best available → bipartite match
    gt = jnp.array([[0.4, 0.4, 0.9, 0.9]])
    match, iou = det.match_priors(priors, gt, jnp.array([True]), 0.5)
    match = np.asarray(match)
    assert (match >= 0).sum() >= 1
    best = np.asarray(
        det.iou_matrix(priors, gt)
    )[:, 0].argmax()
    assert match[best] == 0


def test_multibox_loss_learns(np_rng):
    """Loss must decrease when loc preds move toward encoded targets."""
    priors_np, var_np = det.prior_boxes(
        (4, 4), (64, 64), min_sizes=[24], max_sizes=[40], aspect_ratios=[2.0]
    )
    priors, var = jnp.asarray(priors_np), jnp.asarray(var_np)
    p = priors.shape[0]
    gt_boxes = jnp.array([[[0.1, 0.1, 0.45, 0.5]]])
    gt_labels = jnp.array([[3]])
    gt_valid = jnp.array([[True]])
    loc0 = jnp.asarray(np_rng.randn(1, p, 4).astype(np.float32))
    conf0 = jnp.asarray(np_rng.randn(1, p, 5).astype(np.float32))

    def loss(loc, conf):
        return jnp.sum(
            det.multibox_loss(
                loc, conf, priors, var, gt_boxes, gt_labels, gt_valid
            )
        )

    l0 = float(loss(loc0, conf0))
    gl, gc = jax.grad(loss, argnums=(0, 1))(loc0, conf0)
    l1 = float(loss(loc0 - 0.1 * gl, conf0 - 0.1 * gc))
    assert np.isfinite(l0) and l1 < l0


def test_nms_suppresses_overlaps():
    boxes = jnp.array(
        [
            [0.0, 0.0, 0.5, 0.5],
            [0.02, 0.02, 0.52, 0.52],  # heavy overlap with box 0
            [0.6, 0.6, 0.9, 0.9],
        ]
    )
    scores = jnp.array([0.9, 0.8, 0.7])
    keep, idx = det.nms(boxes, scores, iou_threshold=0.5, top_k=3)
    keep, idx = np.asarray(keep), np.asarray(idx)
    kept = set(idx[keep])
    assert kept == {0, 2}


def test_detection_output_shape_and_content(np_rng):
    priors_np, var_np = det.prior_boxes(
        (2, 2), (32, 32), min_sizes=[12], max_sizes=[], aspect_ratios=[]
    )
    p = priors_np.shape[0]
    loc = jnp.zeros((1, p, 4))
    conf = np.full((1, p, 3), -4.0, np.float32)
    conf[0, 0, 2] = 6.0  # prior 0 confidently class 2
    out = np.asarray(
        det.detection_output(
            loc,
            jnp.asarray(conf),
            jnp.asarray(priors_np),
            jnp.asarray(var_np),
            num_classes=3,
            keep_top_k=10,
        )
    )
    assert out.shape == (1, 10, 6)
    top = out[0, 0]
    assert top[0] == 2.0 and top[1] > 0.9
    np.testing.assert_allclose(top[2:], priors_np[0], atol=1e-5)


def test_ssd_layers_end_to_end(np_rng):
    import jax

    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import detection_layers as D
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    img = L.Data("image", shape=(16, 16, 3))
    feat = L.Conv2D(img, 8, 3, padding=1, act="relu", name="feat")
    down = L.Pool2D(feat, 2, "max", name="down")
    n_cls, k1, k2 = 4, 4, 4  # 4 priors/cell (1 min + 1 maxgeo + 2 ar)
    loc1 = L.Conv2D(feat, 4 * k1, 3, padding=1, act=None, name="loc1")
    conf1 = L.Conv2D(feat, n_cls * k1, 3, padding=1, act=None, name="conf1")
    loc2 = L.Conv2D(down, 4 * k2, 3, padding=1, act=None, name="loc2")
    conf2 = L.Conv2D(down, n_cls * k2, 3, padding=1, act=None, name="conf2")
    pb1 = D.PriorBox(feat, (16, 16), [4], [8], [2.0], name="pb1")
    pb2 = D.PriorBox(down, (16, 16), [8], [12], [2.0], name="pb2")
    gtb = L.Data("gt_boxes", shape=(None, 4))
    gtl = L.Data("gt_labels", shape=(None,))
    cost = D.MultiBoxLoss(
        [loc1, loc2], [conf1, conf2], [pb1, pb2], gtb, gtl, num_classes=n_cls,
        name="mbloss",
    )
    out = D.DetectionOutput(
        [loc1, loc2], [conf1, conf2], [pb1, pb2], num_classes=n_cls,
        keep_top_k=20, name="detout",
    )
    net = Network([cost, out])
    batch = {
        "image": np_rng.randn(2, 16, 16, 3).astype(np.float32),
        "gt_boxes": np.array(
            [[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.8, 0.9]],
             [[0.2, 0.3, 0.7, 0.8], [0.0, 0.0, 0.0, 0.0]]],
            np.float32,
        ),
        "gt_boxes.lengths": np.array([2, 1]),
        "gt_labels": np.array([[1, 2], [3, 0]]),
        "gt_labels.lengths": np.array([2, 1]),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)

    @jax.jit
    def step(p):
        def f(p):
            outs, _ = net.apply(p, states, batch, train=True)
            return outs["mbloss"].value

        l, g = jax.value_and_grad(f)(p)
        return jax.tree.map(lambda a, b: a - 0.05 * b, p, g), l

    p = params
    l0 = None
    for _ in range(12):
        p, l = step(p)
        if l0 is None:
            l0 = float(l)
    assert np.isfinite(l0) and float(l) < l0

    outs, _ = net.apply(p, states, batch)
    assert outs["detout"].value.shape == (2, 20, 6)


def test_detection_map_evaluator():
    from paddle_tpu.metrics.evaluators import DetectionMAPEvaluator

    ev = DetectionMAPEvaluator(ap_type="integral")
    ev.start()
    dets = np.zeros((1, 3, 6), np.float32)
    dets[0, 0] = [1, 0.9, 0.1, 0.1, 0.4, 0.4]   # TP for gt 0
    dets[0, 1] = [1, 0.8, 0.6, 0.6, 0.9, 0.9]   # FP (no overlap)
    dets[0, 2] = [2, 0.7, 0.5, 0.5, 0.8, 0.8]   # TP for gt 1
    gtb = np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.8, 0.8]]], np.float32)
    gtl = np.array([[1, 2]])
    ev.update(detections=dets, gt_boxes=gtb, gt_labels=gtl, gt_lengths=np.array([2]))
    # class 1: AP = 1.0 (first det TP, recall 1 at precision 1); class 2: AP = 1.0
    np.testing.assert_allclose(ev.finish(), 1.0)


def test_v1_packed_detection_layers():
    """MultiBoxLossV1 / DetectionOutputV1: the packed v1 slot encodings
    (priorbox rows of 8, label rows of 6) produce finite losses with flowing
    gradients and id-prefixed detection rows."""
    import jax

    from paddle_tpu.nn.detection_layers import DetectionOutputV1, MultiBoxLossV1
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    b, p = 2, 4
    rs = np.random.RandomState(0)
    loc = L.Data("loc", shape=(p * 4,))
    conf = L.Data("conf", shape=(p * 21,))
    prior = L.Data("prior", shape=(p * 8,))
    label = L.Data("label", shape=(2 * 6,))

    priors = np.zeros((p, 8), np.float32)
    priors[:, 0] = np.linspace(0.0, 0.6, p)
    priors[:, 1] = 0.1
    priors[:, 2] = priors[:, 0] + 0.3
    priors[:, 3] = 0.5
    priors[:, 4:] = 0.1
    gt = np.zeros((b, 2, 6), np.float32)
    gt[:, 0] = [3, 0.05, 0.1, 0.35, 0.5, 0]  # one real box, class 3

    batch = {
        "loc": rs.randn(b, p * 4).astype(np.float32) * 0.05,
        "conf": rs.randn(b, p * 21).astype(np.float32),
        "prior": np.tile(priors.reshape(1, -1), (b, 1)),
        "label": gt.reshape(b, -1),
    }

    mb = MultiBoxLossV1([loc], [conf], prior, label, num_classes=21,
                        name="mb")
    net = Network([mb])
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    cost = float(outs["mb"].value)
    assert np.isfinite(cost) and cost > 0

    def loss(x):
        o, _ = net.apply(params, states, {**batch, "loc": x})
        return o["mb"].value

    g = jax.grad(loss)(jnp.asarray(batch["loc"]))
    assert float(jnp.abs(g).sum()) > 0

    reset_name_scope()
    loc2 = L.Data("loc", shape=(p * 4,))
    conf2 = L.Data("conf", shape=(p * 21,))
    prior2 = L.Data("prior", shape=(p * 8,))
    det = DetectionOutputV1([loc2], [conf2], prior2, num_classes=21,
                            keep_top_k=5, name="det")
    net2 = Network([det])
    params2, states2 = net2.init(jax.random.PRNGKey(0), batch)
    outs2, _ = net2.apply(params2, states2, batch)
    rows = np.asarray(outs2["det"].value)
    assert rows.shape == (b, 5, 7)
    np.testing.assert_array_equal(rows[0, :, 0], 0)  # image-id column
    np.testing.assert_array_equal(rows[1, :, 0], 1)
