"""Parallelism tests on the 8-device CPU mesh — the analog of the reference's
in-process distributed tests (trainer/tests/test_CompareSparse.cpp: run real
pservers on localhost and compare against single-process training for equality).
Here: DataParallel training over the mesh must match single-device training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.data import DataFeeder, dense_vector, integer_value, reader as rd
from paddle_tpu.nn import layers as L
from paddle_tpu.nn import costs as C
from paddle_tpu.nn.graph import Network, ParamAttr, reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.parallel import DataParallel, make_mesh
from paddle_tpu.trainer import SGDTrainer


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_scope()


def _data(n=64, dim=16, classes=4):
    rs = np.random.RandomState(0)
    x = rs.randn(n, dim).astype(np.float32)
    y = (x.sum(-1) > 0).astype(np.int32) + 2 * (x[:, 0] > 0).astype(np.int32)
    return x, y


def _build(dim=16, classes=4, shard_fc=False):
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    attr = ParamAttr(sharding=(None, "model")) if shard_fc else None
    h = L.Fc(x, 64, act="relu", param_attr=attr, name="h")
    logits = L.Fc(h, classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")
    return cost


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh({"data": 4, "model": 2})
    assert mesh2.shape["data"] == 4 and mesh2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_mesh({"data": 3})


def _train(parallel, batch_size=32, steps=6, seed=5):
    cost = _build()
    x, y = _data()

    def reader():
        for i in range(0, len(x), batch_size):
            yield {"x": x[i : i + batch_size], "label": y[i : i + batch_size]}

    tr = SGDTrainer(cost, SGD(learning_rate=0.1), parallel=parallel, seed=seed)
    for raw in reader():
        batch = raw
        if parallel is not None:
            batch = parallel.shard_batch(batch)
        if tr.state is None:
            tr.init_state(batch)
        if tr._step_fn is None:
            tr._step_fn = tr._make_step()
        tr.state, c, _ = tr._step_fn(tr.state, batch)
    return {k: np.asarray(v) for k, v in tr.state["params"].items()}, float(c)


def test_dp_matches_single_device():
    p_single, c_single = _train(None)
    reset_name_scope()
    mesh = make_mesh({"data": 8})
    p_dp, c_dp = _train(DataParallel(mesh))
    assert c_dp == pytest.approx(c_single, rel=2e-4)
    for k in p_single:
        np.testing.assert_allclose(p_dp[k], p_single[k], rtol=2e-4, atol=2e-5)


def test_dp_plus_tp_matches_single_device():
    # data axis 4 × model axis 2: fc weight sharded over 'model'
    reset_name_scope()
    cost1 = _build(shard_fc=False)
    x, y = _data()

    def run(cost, parallel):
        tr = SGDTrainer(cost, SGD(learning_rate=0.1), parallel=parallel, seed=5)
        for i in range(0, len(x), 32):
            batch = {"x": x[i : i + 32], "label": y[i : i + 32]}
            if parallel is not None:
                batch = parallel.shard_batch(batch)
            if tr.state is None:
                tr.init_state(batch)
            if tr._step_fn is None:
                tr._step_fn = tr._make_step()
            tr.state, c, _ = tr._step_fn(tr.state, batch)
        return {k: np.asarray(v) for k, v in tr.state["params"].items()}, float(c)

    p1, c1 = run(cost1, None)
    reset_name_scope()
    cost2 = _build(shard_fc=True)
    mesh = make_mesh({"data": 4, "model": 2})
    dp = DataParallel(mesh)
    # param_attrs are discovered at init; wire them through after trainer init
    tr_params, c2 = run(cost2, dp)
    assert c2 == pytest.approx(c1, rel=2e-4)
    for k in p1:
        np.testing.assert_allclose(tr_params[k], p1[k], rtol=2e-4, atol=2e-5)


def test_sharded_param_layout():
    reset_name_scope()
    mesh = make_mesh({"data": 4, "model": 2})
    cost = _build(shard_fc=True)
    x, y = _data()
    dp = DataParallel(mesh)
    tr = SGDTrainer(cost, SGD(learning_rate=0.1), parallel=dp, seed=0)
    batch = dp.shard_batch({"x": x[:32], "label": y[:32]})
    tr.init_state(batch)
    # DataParallel needs the attrs before shard_state; trainer passes them
    sh = tr.state["params"]["h.w"].sharding
    spec = sh.spec
    assert tuple(spec) == (None, "model"), spec


def test_legacy_sharding_shim_warns_exactly_once_per_process():
    """ISSUE 14 satellite: the deprecated ParamAttr(sharding=...) mesh-axis
    shim emits ONE DeprecationWarning per process — not one per parameter,
    not one per step trace, and not zero."""
    import warnings

    from paddle_tpu.parallel import rules as rules_mod

    was = rules_mod._legacy_sharding_warned
    try:
        rules_mod._legacy_sharding_warned = False
        mesh = make_mesh({"data": 2, "model": 2})
        dp = DataParallel(mesh, param_attrs={
            "a.w": ParamAttr(sharding=("model", None)),
            "b.w": ParamAttr(sharding=(None, "model")),
        })
        with warnings.catch_warnings(record=True) as got:
            warnings.simplefilter("always")
            dp.param_sharding("a.w", 2)
            dp.param_sharding("b.w", 2)  # second legacy param: no new warning
        dep = [w for w in got if issubclass(w.category, DeprecationWarning)]
        assert len(dep) == 1, [str(w.message) for w in dep]
        assert "a.w" in str(dep[0].message)
        assert "logical_axes" in str(dep[0].message)
        # logical_axes declarations never trip the shim
        dp2 = DataParallel(mesh, param_attrs={
            "c.w": ParamAttr(logical_axes=("embed", "mlp")),
        })
        rules_mod._legacy_sharding_warned = False
        with warnings.catch_warnings(record=True) as got2:
            warnings.simplefilter("always")
            dp2.param_sharding("c.w", 2)
        assert not [
            w for w in got2 if issubclass(w.category, DeprecationWarning)
        ]
    finally:
        rules_mod._legacy_sharding_warned = was
