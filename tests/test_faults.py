"""Chaos-injection tests: fault tolerance as a first-class, tested code path.

Every test here drives a REAL failure path — injected kill + auto-resume,
NaN loss + divergence policies, torn checkpoint writes, dropped master RPCs,
flaky feeders — through the seeded harness in paddle_tpu/core/faults.py, so
each failure is deterministic and cheap enough for tier-1 (the reference's
failure machinery, go/master + go/pserver, was only ever exercised by
hand-run cluster jobs)."""

import os
import traceback

import numpy as np
import pytest

from paddle_tpu.core import faults, stats
from paddle_tpu.data import DataFeeder, dense_vector, integer_value, reader as rd
from paddle_tpu.data.pipeline import DevicePrefetcher
from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import reset_name_scope
from paddle_tpu.optim import SGD
from paddle_tpu.trainer import DivergenceError, EndPass, SGDTrainer
from paddle_tpu.trainer import checkpoint as ckpt

pytestmark = pytest.mark.chaos

DIM, CLASSES = 4, 3


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    stats.FT_EVENTS.reset()
    yield


def _reader(n=64, seed=0):
    rs = np.random.RandomState(seed)
    xs = rs.randn(n, DIM).astype(np.float32)
    ys = (np.arange(n) % CLASSES).astype(np.int64)

    def reader():
        for x, y in zip(xs, ys):
            yield {"x": x, "label": int(y)}

    return reader


def _feeder():
    return DataFeeder({"x": dense_vector(DIM), "label": integer_value(CLASSES)})


def _trainer(policy=None, seed=5, lr=0.1):
    reset_name_scope()
    x = L.Data("x", shape=(DIM,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, 16, act="relu"), CLASSES, act=None)
    cost = C.ClassificationCost(logits, lbl)
    return SGDTrainer(
        cost, SGD(learning_rate=lr), seed=seed, divergence_policy=policy
    )


def _params(t):
    return {k: np.asarray(v) for k, v in t.state["params"].items()}


# ---------------------------------------------------------------------------
# fault spec / injector
# ---------------------------------------------------------------------------


def test_fault_spec_parsing():
    spec = faults.parse_spec(
        "feeder_raise:0.01,h2d_delay:5ms,master_drop:0.05,nan_loss:step=37"
    )
    assert spec["feeder_raise"].prob == 0.01
    assert spec["h2d_delay"].delay_s == pytest.approx(0.005)
    assert spec["master_drop"].prob == 0.05
    assert spec["nan_loss"].step == 37
    assert faults.parse_spec("io_delay:1.5s")["io_delay"].delay_s == 1.5
    assert faults.parse_spec("") == {}
    # the elastic-resize sites (ISSUE 8) ride the same grammar/seeding
    rz = faults.parse_spec("resize_drain_stall:step=0,reshard_kill:0.5")
    assert rz["resize_drain_stall"].step == 0
    assert rz["reshard_kill"].prob == 0.5
    # durations are only meaningful on *_delay sites ("kill:5s" would
    # otherwise silently mean "kill every batch")
    for bad in ("nan_loss", "x:1.5", "x:-0.1", "x:abc", "x:step=q",
                "kill:5s", "nan_loss:5ms", "h2d_delay:0.5", "h2d_delay:step=3"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_injector_is_seeded_and_deterministic():
    a = faults.FaultInjector("f:0.3", seed=7)
    b = faults.FaultInjector("f:0.3", seed=7)
    c = faults.FaultInjector("f:0.3", seed=8)
    pat = lambda inj: [inj.fire("f") for _ in range(64)]  # noqa: E731
    pa, pb, pc = pat(a), pat(b), pat(c)
    assert pa == pb, "same seed must give the same fire pattern"
    assert pa != pc, "different seed must give a different pattern"
    assert a.fired["f"] == sum(pa) and a.hits["f"] == 64
    # step= fires exactly once, on the right hit
    s = faults.FaultInjector("f:step=2")
    assert [s.fire("f") for s_ in range(5)] == [False, False, True, False, False]
    # unknown sites never fire and are never counted
    assert not a.fire("unknown") and "unknown" not in a.hits


def test_inject_context_restores_previous_config():
    before = faults.get().spec_str
    with faults.inject("kill:step=0") as inj:
        assert inj.active and faults.get() is inj
    assert faults.get().spec_str == before


# ---------------------------------------------------------------------------
# tentpole: kill + auto-resume is bitwise-identical to an unfaulted run
# ---------------------------------------------------------------------------


def test_kill_and_auto_resume_bitwise_identical(tmp_path):
    """A run killed mid-pass and auto-resumed must land on EXACTLY the params
    of a never-killed run (allclose rtol=0 == array_equal) — the CPU-oracle
    determinism contract for the whole save/CRC/restore chain."""
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)  # 2 batches/pass

    t_ref = _trainer()
    t_ref.train(batches, num_passes=3, feeder=feeder,
                save_dir=str(tmp_path / "ref"))
    ref = _params(t_ref)

    # faulted run: SIGKILL analog at global step 3 = pass 1, batch 1
    d = str(tmp_path / "faulted")
    with faults.inject("kill:step=3") as inj:
        t1 = _trainer()
        with pytest.raises(faults.InjectedKill):
            t1.train(batches, num_passes=3, feeder=feeder, save_dir=d)
        assert inj.fired["kill"] == 1
    assert ckpt.find_latest_valid_pass(d) == 0  # only pass 0 completed

    # "restarted process": fresh trainer, same config, auto_resume
    t2 = _trainer()
    t2.train(batches, num_passes=3, feeder=feeder, save_dir=d,
             auto_resume=True)
    got = _params(t2)
    assert set(got) == set(ref)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=0, atol=0, err_msg=k)
    assert int(t2.state["samples"]) == int(t_ref.state["samples"])


def test_auto_resume_skips_corrupt_checkpoint(tmp_path, caplog):
    """Truncate the newest params.npz: auto-resume must fall back to the
    previous valid pass (with a warning) and end up exactly where a clean
    resume from that pass would."""
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    d = str(tmp_path / "ckpts")

    t1 = _trainer()
    t1.train(batches, num_passes=2, feeder=feeder, save_dir=d)
    ref = _params(t1)  # state after pass 1

    bad = os.path.join(d, "pass-00001", "params.npz")
    with open(bad, "r+b") as f:
        f.truncate(os.path.getsize(bad) // 2)
    with caplog.at_level("WARNING", logger="paddle_tpu.checkpoint"):
        assert ckpt.find_latest_valid_pass(d) == 0
    assert any("corrupt" in r.message for r in caplog.records)

    # resume re-runs pass 1 from the pass-0 checkpoint → same final params
    t2 = _trainer()
    t2.train(batches, num_passes=2, feeder=feeder, save_dir=d,
             auto_resume=True)
    got = _params(t2)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=0, atol=0, err_msg=k)


def test_auto_resume_with_all_passes_done_loads_state(tmp_path):
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    d = str(tmp_path / "done")
    t1 = _trainer()
    t1.train(batches, num_passes=2, feeder=feeder, save_dir=d)

    t2 = _trainer()
    state = t2.train(batches, num_passes=2, feeder=feeder, save_dir=d,
                     auto_resume=True)
    assert state is not None
    for k, v in _params(t1).items():
        np.testing.assert_array_equal(np.asarray(state["params"][k]), v)


def test_ckpt_truncate_fault_is_caught_by_crc(tmp_path):
    params = {"w": np.arange(8, dtype=np.float32)}
    with faults.inject("ckpt_truncate:1.0") as inj:
        pdir = ckpt.save_pass(str(tmp_path), 0, params, v1_binary=False)
        assert inj.fired["ckpt_truncate"] >= 1
    assert not ckpt.verify_pass(pdir)
    assert ckpt.find_latest_valid_pass(str(tmp_path)) is None
    with pytest.raises(IOError, match="CRC"):
        ckpt.load_pass(str(tmp_path), 0)


def test_keep_last_n_retention_and_latest_pointer(tmp_path):
    d = str(tmp_path)
    for p in range(5):
        ckpt.save_pass(d, p, {"w": np.full(4, p, np.float32)},
                       v1_binary=False, keep_last_n=2)
    dirs = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
    assert dirs == ["pass-00003", "pass-00004"]
    assert not [x for x in os.listdir(d) if x.startswith(".trash")]
    with open(os.path.join(d, ckpt.LATEST_FILE)) as f:
        assert f.read().strip() == "pass-00004"
    assert ckpt.find_latest_valid_pass(d) == 4
    # a stale/corrupt latest pointer degrades to the scan, not a crash
    with open(os.path.join(d, ckpt.LATEST_FILE), "w") as f:
        f.write("garbage")
    assert ckpt.find_latest_valid_pass(d) == 4


# ---------------------------------------------------------------------------
# async (zero-stall) checkpointing under chaos
# ---------------------------------------------------------------------------


def test_async_ckpt_truncate_plus_kill_leaves_valid_older(tmp_path, caplog):
    """Crash-safety of the background writer: pass-1's async save is torn
    (ckpt_truncate fires on the writer thread), then the process 'dies'
    (injected kill) early in pass 2 while writes may still be in flight.
    auto_resume must skip the corrupt pass-1 dir, land on the CRC-valid
    pass-0 checkpoint, and finish bitwise-identical to a clean run."""
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)  # 2 batches/pass

    t_ref = _trainer()
    t_ref.train(batches, num_passes=3, feeder=feeder)
    ref = _params(t_ref)

    d = str(tmp_path / "chaos")
    # each pass writes params.npz then opt.npz (states empty for this net):
    # truncate hit 2 = pass-1 params.npz; kill hit 4 = pass 2 batch 0
    with faults.inject("ckpt_truncate:step=2,kill:step=4") as inj:
        t1 = _trainer()
        with pytest.raises(faults.InjectedKill):
            t1.train(batches, num_passes=3, feeder=feeder, save_dir=d,
                     async_checkpoint=True)
        assert inj.fired["ckpt_truncate"] == 1 and inj.fired["kill"] == 1
    assert not ckpt.verify_pass(os.path.join(d, "pass-00001"))  # torn
    with caplog.at_level("WARNING", logger="paddle_tpu.checkpoint"):
        assert ckpt.find_latest_valid_pass(d) == 0  # older one still trusted
    assert any("corrupt" in r.message for r in caplog.records)

    t2 = _trainer()
    t2.train(batches, num_passes=3, feeder=feeder, save_dir=d,
             auto_resume=True, async_checkpoint=True)
    got = _params(t2)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=0, atol=0, err_msg=k)


def test_async_ckpt_keep_last_n_retention_out_of_band(tmp_path):
    """keep_last_n runs on the writer thread, after saves that complete out
    of band — retention and the latest pointer must still be exact once the
    durability barrier returns."""
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    d = str(tmp_path / "keep")
    t = _trainer()
    t.train(batches, num_passes=5, feeder=feeder, save_dir=d,
            keep_last_n=2, async_checkpoint=True)
    dirs = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
    assert dirs == ["pass-00003", "pass-00004"]
    assert not [x for x in os.listdir(d) if x.startswith(".trash")]
    with open(os.path.join(d, ckpt.LATEST_FILE)) as f:
        assert f.read().strip() == "pass-00004"
    assert ckpt.find_latest_valid_pass(d) == 4


def test_preempt_drain_checkpoint_durable_with_async_writer(tmp_path):
    """The exit-77 contract with async checkpointing on: by the time
    Preempted propagates, the mid-pass checkpoint named in it passes CRC —
    the drain's wait() barrier ran before the raise."""
    from paddle_tpu.core import preempt
    from paddle_tpu.trainer import Preempted

    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    d = str(tmp_path / "drain")
    try:
        with faults.inject("preempt:step=2"):
            t = _trainer()
            with pytest.raises(Preempted) as ei:
                t.train(batches, num_passes=3, feeder=feeder, save_dir=d,
                        async_checkpoint=True)
        assert ei.value.checkpoint_dir is not None
        assert ckpt.verify_pass(ei.value.checkpoint_dir)
        man = ckpt.pass_manifest(d, ei.value.pass_id)
        assert man["extra"]["mid_pass"] is True
        assert man["extra"]["batches_done"] == ei.value.batches_done
    finally:
        preempt.reset()


# ---------------------------------------------------------------------------
# divergence guard
# ---------------------------------------------------------------------------


def test_nan_without_guard_poisons_params(tmp_path):
    """The motivating failure: with no policy, one NaN batch silently poisons
    every parameter from then on."""
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    with faults.inject("nan_loss:step=1"):
        t = _trainer(policy=None)
        t.train(batches, num_passes=1, feeder=feeder)
    assert any(not np.isfinite(v).all() for v in _params(t).values())


def test_divergence_skip_batch_recovers():
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    passes = []
    with faults.inject("nan_loss:step=1") as inj:
        t = _trainer(policy="skip_batch")
        t.train(
            batches, num_passes=2, feeder=feeder,
            event_handler=lambda e: passes.append(e.metrics)
            if isinstance(e, EndPass) else None,
        )
        assert inj.fired["nan_loss"] == 1
    # the poisoned step landed in neither params nor the pass average
    assert all(np.isfinite(v).all() for v in _params(t).values())
    assert all(np.isfinite(m["avg_cost"]) for m in passes)
    assert passes[0]["divergence_events"] == 1 and passes[0]["batches"] == 1
    assert passes[1]["divergence_events"] == 0
    assert stats.FT_EVENTS.get("divergence") == 1


def test_divergence_rollback_restores_and_cuts_lr(tmp_path):
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)  # 2 batches/pass
    d = str(tmp_path / "roll")
    # NaN at global step 4 = pass 2 batch 0; passes 0/1 are checkpointed
    with faults.inject("nan_loss:step=4"):
        t = _trainer(policy="rollback")
        t.train(batches, num_passes=3, feeder=feeder, save_dir=d)
    assert float(t.state["lr_scale"]) == 0.5  # halved exactly once
    assert all(np.isfinite(v).all() for v in _params(t).values())
    assert stats.FT_EVENTS.get("divergence_rollback") == 1
    # the halved lr_scale is persisted for the NEXT resume
    _, _, _, manifest = ckpt.load_pass(d)
    assert manifest["extra"]["lr_scale"] == 0.5


def test_divergence_rollback_without_checkpoint_degrades_to_skip(caplog):
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    with faults.inject("nan_loss:step=0"):
        t = _trainer(policy="rollback")
        with caplog.at_level("WARNING", logger="paddle_tpu.trainer"):
            t.train(batches, num_passes=1, feeder=feeder)  # no save_dir
    assert any("falling back" in r.message for r in caplog.records)
    assert float(t.state["lr_scale"]) == 1.0
    assert all(np.isfinite(v).all() for v in _params(t).values())


def test_divergence_raise_is_loud_and_state_safe():
    feeder = _feeder()
    batches = rd.batch(_reader(), 32, drop_last=True)
    with faults.inject("nan_loss:step=1"):
        t = _trainer(policy="raise")
        with pytest.raises(DivergenceError, match="non-finite cost.*pass 0 batch 1"):
            t.train(batches, num_passes=1, feeder=feeder)
    # the guard still protected the state before the raise
    assert all(np.isfinite(v).all() for v in _params(t).values())


def test_bad_divergence_policy_rejected():
    with pytest.raises(ValueError, match="divergence_policy"):
        _trainer(policy="explode")


# ---------------------------------------------------------------------------
# pipeline: retry, traceback fidelity, stall watchdog
# ---------------------------------------------------------------------------


def _raw_batches(n=4, bs=8):
    rs = np.random.RandomState(0)
    return [
        [(rs.randn(DIM).astype(np.float32), int(i % CLASSES)) for i in range(bs)]
        for _ in range(n)
    ]


def test_feeder_retry_rescues_transient_fault():
    raws = _raw_batches(n=4)
    with faults.inject("feeder_raise:step=1") as inj:
        got = list(DevicePrefetcher(lambda: iter(raws), _feeder(),
                                    prefetch_depth=1, feed_retries=2))
        fired = inj.fired.get("feeder_raise", 0)
    assert len(got) == 4, "one transient fault must not lose a batch"
    assert fired == 1
    assert stats.FT_EVENTS.get("feeder_retry") == 1


def test_feeder_retries_exhausted_raises():
    raws = _raw_batches(n=2)
    with faults.inject("feeder_raise:1.0"):  # every attempt fails
        with pytest.raises(faults.InjectedFault, match="feeder_raise"):
            list(DevicePrefetcher(lambda: iter(raws), _feeder(),
                                 prefetch_depth=1, feed_retries=2))
    assert stats.FT_EVENTS.get("feeder_retry") == 2  # N retries, then raise


def test_worker_traceback_reaches_consumer():
    """The satellite fix: a feeder bug must surface with the WORKER's frames
    (the actual buggy function), not just the consumer re-raise site."""

    def bad_feeder(raw):
        raise ValueError("corrupt sample: negative length")

    with pytest.raises(ValueError, match="corrupt sample") as ei:
        list(DevicePrefetcher(lambda: iter(_raw_batches(n=1)), bad_feeder,
                             prefetch_depth=1, feed_retries=0))
    frames = [f.name for f in traceback.extract_tb(ei.value.__traceback__)]
    assert "bad_feeder" in frames, f"worker frames lost: {frames}"


def test_h2d_delay_fault_and_stall_watchdog(caplog):
    from paddle_tpu.data.pipeline import iter_async

    def slow_reader():
        import time as _t

        _t.sleep(0.25)  # producer wedged long past the watchdog period
        yield {"x": np.zeros((2, DIM), np.float32)}

    with caplog.at_level("WARNING", logger="paddle_tpu.pipeline"):
        got = list(iter_async(slow_reader, lambda r: r, capacity=1,
                              stall_warn_s=0.05))
    assert len(got) == 1  # starvation logs, it does not drop data
    assert any("starved" in r.message for r in caplog.records)
    assert stats.FT_EVENTS.get("pipeline_stall") >= 1

    # h2d_delay measurably slows the prefetcher's device leg
    with faults.inject("h2d_delay:30ms") as inj:
        import time as _t

        t0 = _t.perf_counter()
        list(DevicePrefetcher(lambda: iter(_raw_batches(n=3)), _feeder(),
                             prefetch_depth=1))
        assert _t.perf_counter() - t0 > 0.09  # 3 batches x 30ms
        assert inj.fired["h2d_delay"] == 3


# ---------------------------------------------------------------------------
# master: dropped RPCs, snapshot failures, kill-and-restart mid-pass
# ---------------------------------------------------------------------------

from paddle_tpu.runtime import (  # noqa: E402
    MasterClient,
    MasterServer,
    TaskMaster,
    available,
    cluster_reader,
    recordio,
)

needs_native = pytest.mark.skipif(
    not available(), reason="native runtime library unavailable"
)


@needs_native
def test_master_drop_fault_client_backoff_completes(tmp_path):
    """Randomly dropped RPCs (seeded) must be absorbed by the client's
    reconnect+backoff: one pass still yields every sample exactly once."""
    samples = [{"x": i} for i in range(48)]
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: iter(samples), records_per_file=12
    )
    server = MasterServer(TaskMaster(timeout_s=30, failure_max=2)).start()
    try:
        with faults.inject("master_drop:0.2", seed=3) as inj:
            client = MasterClient(server.address, retries=6, backoff_base=0.01)
            assert client.call("set_dataset", shards=shards,
                               chunks_per_task=1)["ok"]
            got = sorted(list(cluster_reader(server.address)()),
                         key=lambda s: s["x"])
            client.close()
            dropped = inj.fired.get("master_drop", 0)
        assert got == samples
        assert dropped >= 1, "chaos produced no drops — raise prob or hits"
        assert stats.FT_EVENTS.get("master_reconnect") >= dropped
    finally:
        server.stop()


def test_master_client_terminal_error_is_clear():
    # nothing listens on this port: the client must back off, then name the
    # method, address and attempt count in one terminal error
    dead = MasterClient(("127.0.0.1", 1), timeout=0.2, retries=2,
                        backoff_base=0.01)
    with pytest.raises(ConnectionError, match="'get_task'.*after 2 attempts"):
        dead.call("get_task")


@needs_native
def test_master_snapshot_failure_logged_and_counted(tmp_path, caplog):
    """The satellite fix: snapshot OSError is no longer swallowed — it warns
    and shows up in stats()['snapshot_failures']."""
    bad = str(tmp_path / "no_such_dir" / "m.snap")  # parent doesn't exist
    server = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=bad
    ).start()
    try:
        client = MasterClient(server.address)
        client.call("set_dataset", shards=["s0", "s1"], chunks_per_task=1)
        with caplog.at_level("WARNING", logger="paddle_tpu.master"):
            resp = client.call("get_task")
            client.call("task_finished", task_id=resp["task_id"])
        st = client.call("stats")
        assert st["snapshot_failures"] >= 1
        assert server.snapshot_failures >= 1
        assert any("snapshot" in r.message for r in caplog.records)
        client.close()
    finally:
        server.stop()


@needs_native
def test_master_kill_restart_midpass_no_loss_no_dup(tmp_path):
    """Kill the master with a task LEASED (pending) mid-pass: the restarted
    master restores from snapshot, re-dispatches the lost lease, and never
    re-issues finished work — no sample lost, none duplicated."""
    samples = list(range(40))
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: iter(samples), records_per_file=10
    )
    snap = str(tmp_path / "m.snap")
    server = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    client = MasterClient(server.address)
    client.call("set_dataset", shards=shards, chunks_per_task=1)
    done = client.call("get_task")          # will be finished + snapshotted
    leased = client.call("get_task")        # will be LOST with the server
    consumed = list(recordio.read_shards(done["shards"]))
    client.call("task_finished", task_id=done["task_id"])
    client.close()
    server.stop()                           # kill mid-pass, lease outstanding

    server2 = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    try:
        rest = list(cluster_reader(server2.address)())
        # exactly-once over the pass: finished work not re-issued, the lost
        # lease re-dispatched (lease-requeue semantics)
        assert sorted(consumed + rest) == samples
        leased_samples = list(recordio.read_shards(leased["shards"]))
        assert all(s in rest for s in leased_samples)
        st = MasterClient(server2.address).call("stats")
        assert st["todo"] == 0 and st["pending"] == 0
    finally:
        server2.stop()
