"""Worker process for the 2-process distributed test (the reference's
in-process-localhost cluster idiom, trainer/tests/test_CompareSparse.cpp:65-73:
spawn real pservers + trainers on localhost, then compare parameters).

Spawned by tests/test_distributed.py as `python distributed_worker.py
<pid> <nprocs> <coord_addr> <master_port> <outdir>` with
XLA_FLAGS=--xla_force_host_platform_device_count=2, so the 2 processes form a
4-device global CPU mesh wired by gloo collectives.

Each worker:
1. joins the cluster via paddle_tpu.parallel.distributed.initialize,
2. pulls recordio tasks from the shared MasterServer (hosted by process 0)
   through cluster_reader and records which sample ids it consumed,
3. trains a small classifier via SGDTrainer + DataParallel over the global
   mesh, feeding only its shard_reader half of the data (grads allreduced by
   the SPMD partitioner over the data axis),
4. dumps its final parameters + consumed ids for the parent to compare.

Additional role (tests/test_cluster.py cluster-chaos scenarios):

    python distributed_worker.py preempt_trainer <outdir> <mode> [pass batch]

trains a deterministic toy classifier with checkpointing; mode `run` installs
the core.preempt guard and SIGTERMs ITSELF right after the given (pass,
batch) step — the real preemption-notice path — exiting with
preempt.EXIT_PREEMPTED after the drain; `resume` continues the run with
auto_resume=True; `clean` is the never-preempted oracle. Final params land in
<outdir>/params_<mode>.npz for the parent's bitwise comparison.
"""

import json
import os
import pickle
import signal
import sys

import numpy as np


def main() -> None:
    pid, nprocs = int(sys.argv[1]), int(sys.argv[2])
    coord_addr, master_port, outdir = sys.argv[3], int(sys.argv[4]), sys.argv[5]

    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=coord_addr, num_processes=nprocs, process_id=pid
    )
    assert jax.process_count() == nprocs

    from paddle_tpu.data import reader as rd
    from paddle_tpu.data.sharded_reader import shard_reader
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import Network, reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.runtime.master import MasterServer, TaskMaster, cluster_reader
    from paddle_tpu.trainer import SGDTrainer

    # -- master-backed data dispatch across the process boundary -------------
    shards = sorted(
        os.path.join(outdir, f) for f in os.listdir(outdir) if f.endswith(".recordio")
    )
    server = None
    if pid == 0:
        master = TaskMaster(timeout_s=30.0, failure_max=3)
        master.set_dataset(shards, chunks_per_task=1)
        server = MasterServer(master, port=master_port).start()
    else:  # wait for process 0's server to come up
        import socket
        import time

        deadline = time.time() + 30.0
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", master_port), 1.0).close()
                break
            except OSError:
                time.sleep(0.2)
    import time

    distributed.barrier()  # don't let one host drain the queue before the
    consumed = []          # other has even connected
    for s in cluster_reader(("127.0.0.1", master_port), pickle.loads)():
        consumed.append(s["sid"])
        # simulate per-sample work on both hosts so the task stream
        # demonstrably interleaves across the process boundary (whichever
        # host connects first would otherwise drain the whole queue)
        time.sleep(0.05)
    with open(os.path.join(outdir, f"consumed_{pid}.json"), "w") as f:
        json.dump(sorted(consumed), f)

    # -- deterministic sharded allreduce training ----------------------------
    reset_name_scope()
    dim, classes, batch_local = 16, 4, 8
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 32, act="relu", name="h")
    logits = L.Fc(h, classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")

    rs = np.random.RandomState(0)
    xs = rs.randn(96, dim).astype(np.float32)
    ys = (rs.rand(96) * classes).astype(np.int32)

    def full_reader():
        for i in range(len(xs)):
            yield {"x": xs[i], "label": ys[i]}

    mine = shard_reader(full_reader)  # idx % nprocs == process_index
    mesh = make_mesh({"data": len(jax.devices())})
    dp = DataParallel(mesh)
    tr = SGDTrainer(cost, SGD(learning_rate=0.1), parallel=dp, seed=11)

    costs = []
    for raw in rd.batch(mine, batch_local, drop_last=True)():
        batch = {
            "x": np.stack([s["x"] for s in raw]),
            "label": np.asarray([s["label"] for s in raw], np.int32),
        }
        batch = dp.shard_batch(batch)
        if tr.state is None:
            tr.init_state(batch)
            tr._step_fn = tr._make_step()
        tr.state, c, _ = tr._step_fn(tr.state, batch)
        costs.append(float(c))

    distributed.barrier()
    np.savez(
        os.path.join(outdir, f"params_{pid}.npz"),
        **{k: np.asarray(v) for k, v in tr.state["params"].items()},
    )
    with open(os.path.join(outdir, f"costs_{pid}.json"), "w") as f:
        json.dump(costs, f)
    if server is not None:
        server.stop()
    print(f"worker {pid}: done, final cost {costs[-1]:.4f}", flush=True)


def preempt_trainer(argv) -> None:
    """See module docstring: <outdir> <run|resume|clean> [sig_pass sig_batch]."""
    outdir, mode = argv[0], argv[1]
    sig = (int(argv[2]), int(argv[3])) if len(argv) > 3 else (1, 2)

    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.core import preempt
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import Preempted, SGDTrainer
    from paddle_tpu.trainer.events import EndIteration

    dim, classes, batch = 8, 3, 8
    rs = np.random.RandomState(7)
    xs = rs.randn(64, dim).astype(np.float32)
    ys = (np.arange(64) % classes).astype(np.int32)

    def reader():
        for i in range(0, len(xs), batch):
            yield {"x": xs[i:i + batch], "label": ys[i:i + batch]}

    reset_name_scope()
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    logits = L.Fc(L.Fc(x, 16, act="relu"), classes, act=None)
    cost = C.ClassificationCost(logits, lbl)
    tr = SGDTrainer(cost, SGD(learning_rate=0.1), seed=3)
    save_dir = os.path.join(outdir, "ckpt")

    handler = None
    if mode == "run":
        preempt.install(grace_s=30.0)

        def handler(ev):
            if isinstance(ev, EndIteration) and (ev.pass_id, ev.batch_id) == sig:
                # the cloud's preemption notice, for real: SIGTERM to self —
                # the guard's handler sets the drain flag, the next batch
                # boundary checkpoints and raises Preempted
                os.kill(os.getpid(), signal.SIGTERM)

    try:
        tr.train(
            reader,
            num_passes=3,
            event_handler=handler,
            save_dir=None if mode == "clean" else save_dir,
            auto_resume=(mode == "resume"),
            log_period=1000,
        )
    except Preempted as p:
        print(f"worker preempted: {p}", flush=True)
        sys.exit(preempt.EXIT_PREEMPTED)
    np.savez(
        os.path.join(outdir, f"params_{mode}.npz"),
        **{k: np.asarray(v) for k, v in tr.state["params"].items()},
    )
    print(f"worker {mode}: done", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "preempt_trainer":
        preempt_trainer(sys.argv[2:])
    else:
        main()
