"""Fluid control flow, LoD sequence ops, RNN ops, IO ops, beam ops
(VERDICT r3 weak #1 / task #2: the round-3 fluid surface shipped untested).

Oracles follow the repo's CPU-oracle idiom (SURVEY §4): numpy loops for the
recurrences, the eager interpreter vs the jit path for executor parity —
the reference's analogous corpus is framework/tests/test_recurrent_op.py,
test_while_op / test_cond_op, and the operators' python unit tests."""

import numpy as np
import pytest

from paddle_tpu import fluid
from paddle_tpu.fluid import layers as L
from paddle_tpu.fluid.ops import OPS, OpContext


@pytest.fixture(autouse=True)
def _fresh_program():
    fluid.reset_default_program()
    yield


# ---------------------------------------------------------------------------
# recurrent op (recurrent_op.cc → lax.scan)
# ---------------------------------------------------------------------------


def _build_rnn_program(b, t, d, h, seed=0):
    """h_t = tanh(x_t @ W + h_{t-1} @ U): the test_recurrent_op.py cell."""
    rs = np.random.RandomState(seed)
    wv = (rs.randn(d, h) * 0.3).astype(np.float32)
    uv = (rs.randn(h, h) * 0.3).astype(np.float32)

    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("x_seq", shape=[t, d], is_data=True)
    block.create_var("h0", shape=[h], is_data=True)
    w = block.create_parameter("W", shape=[d, h], initializer=wv)
    u = block.create_parameter("U", shape=[h, h], initializer=uv)

    sub = prog.create_block()
    sub.append_op("mul", {"X": "x_t", "Y": w}, {"Out": "xw"}, {})
    sub.append_op("mul", {"X": "h_pre", "Y": u}, {"Out": "hu"}, {})
    sub.append_op("elementwise_add", {"X": "xw", "Y": "hu"}, {"Out": "s"}, {})
    sub.append_op("tanh", {"X": "s"}, {"Y": "h_new"}, {})
    prog.rollback()

    block.desc.ops.append(
        fluid.framework.OpDesc(
            type="recurrent",
            attrs={
                "sub_block": sub.idx,
                "seq_ins": {"x_t": "x_seq"},
                "states": {"h_pre": ("h0", "h_new")},
                "seq_outs": {"h_seq": "h_new"},
            },
        )
    )
    return prog, wv, uv


def _np_rnn(x, h0, w, u):
    hs = []
    h = h0
    for step in range(x.shape[1]):
        h = np.tanh(x[:, step] @ w + h @ u)
        hs.append(h)
    return np.stack(hs, 1)


def test_recurrent_op_matches_numpy_and_jit_matches_eager():
    b, t, d, h = 4, 6, 5, 3
    rs = np.random.RandomState(1)
    xv = rs.randn(b, t, d).astype(np.float32)
    h0 = rs.randn(b, h).astype(np.float32)
    prog, wv, uv = _build_rnn_program(b, t, d, h)

    exe = fluid.Executor()
    (jit_out,) = exe.run(prog, feed={"x_seq": xv, "h0": h0}, fetch_list=["h_seq"])
    (eager_out,) = exe.run(
        prog, feed={"x_seq": xv, "h0": h0}, fetch_list=["h_seq"], use_jit=False
    )
    want = _np_rnn(xv, h0, wv, uv)
    np.testing.assert_allclose(np.asarray(jit_out), want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(eager_out), want, rtol=1e-5, atol=1e-5)


def test_recurrent_op_reverse():
    b, t, d, h = 2, 5, 4, 3
    rs = np.random.RandomState(2)
    xv = rs.randn(b, t, d).astype(np.float32)
    h0 = np.zeros((b, h), np.float32)
    prog, wv, uv = _build_rnn_program(b, t, d, h, seed=3)
    prog.global_block().desc.ops[-1].attrs["reverse"] = True

    exe = fluid.Executor()
    (out,) = exe.run(prog, feed={"x_seq": xv, "h0": h0}, fetch_list=["h_seq"])
    want = _np_rnn(xv[:, ::-1], h0, wv, uv)[:, ::-1]
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# while op (→ lax.while_loop)
# ---------------------------------------------------------------------------


def test_while_op_jit_matches_eager_and_closed_form():
    """v doubles until counter hits 7: v_final = v0 * 2^7."""
    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("v", shape=[4], is_data=True)
    block.create_var("c", shape=[], is_data=True)
    block.create_var("n", shape=[], is_data=True)
    block.create_var("keep_going", shape=[])
    # cond must hold before entry
    block.append_op("less_than", {"X": "c", "Y": "n"}, {"Out": "keep_going"}, {})

    sub = prog.create_block()
    sub.append_op("scale", {"X": "v"}, {"Out": "v"}, {"scale": 2.0})
    sub.append_op("increment", {"X": "c"}, {"Out": "c"}, {"step": 1.0})
    sub.append_op("less_than", {"X": "c", "Y": "n"}, {"Out": "keep_going"}, {})
    prog.rollback()

    block.desc.ops.append(
        fluid.framework.OpDesc(
            type="while",
            attrs={"sub_block": sub.idx, "cond": "keep_going", "carry": ["v", "c"]},
        )
    )
    feed = {
        "v": np.ones(4, np.float32),
        "c": np.zeros((), np.float32),
        "n": np.full((), 7.0, np.float32),
    }
    exe = fluid.Executor()
    (v_jit,) = exe.run(prog, feed=dict(feed), fetch_list=["v"])
    (v_eager,) = exe.run(prog, feed=dict(feed), fetch_list=["v"], use_jit=False)
    np.testing.assert_allclose(np.asarray(v_jit), np.full(4, 128.0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v_jit), np.asarray(v_eager), rtol=1e-6)


# ---------------------------------------------------------------------------
# cond op (→ lax.cond / masked select)
# ---------------------------------------------------------------------------


def _cond_prog(with_false_block: bool):
    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("flag", shape=[], is_data=True)
    block.create_var("x", shape=[3], is_data=True)
    block.create_var("out", shape=[3], is_data=True)  # passthrough default

    true_b = prog.create_block()
    true_b.append_op("scale", {"X": "x"}, {"Out": "out"}, {"scale": 10.0})
    prog.rollback()
    attrs = {"cond": "flag", "true_block": true_b.idx, "outs": ["out"]}
    if with_false_block:
        false_b = prog.create_block()
        false_b.append_op("scale", {"X": "x"}, {"Out": "out"}, {"scale": -1.0})
        prog.rollback()
        attrs["false_block"] = false_b.idx
    block.desc.ops.append(fluid.framework.OpDesc(type="cond", attrs=attrs))
    return prog


@pytest.mark.parametrize("use_jit", [True, False])
def test_cond_scalar_both_branches(use_jit):
    prog = _cond_prog(with_false_block=True)
    exe = fluid.Executor()
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    base = {"x": xv, "out": np.zeros(3, np.float32)}
    (t_out,) = exe.run(
        prog, feed={**base, "flag": np.asarray(1.0)}, fetch_list=["out"], use_jit=use_jit
    )
    (f_out,) = exe.run(
        prog, feed={**base, "flag": np.asarray(0.0)}, fetch_list=["out"], use_jit=use_jit
    )
    np.testing.assert_allclose(np.asarray(t_out), xv * 10.0)
    np.testing.assert_allclose(np.asarray(f_out), -xv)


def test_cond_passthrough_without_false_block():
    prog = _cond_prog(with_false_block=False)
    exe = fluid.Executor()
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    prior = np.array([7.0, 8.0, 9.0], np.float32)
    (f_out,) = exe.run(
        prog, feed={"x": xv, "out": prior, "flag": np.asarray(0.0)}, fetch_list=["out"]
    )
    np.testing.assert_allclose(np.asarray(f_out), prior)  # false → passthrough
    (t_out,) = exe.run(
        prog, feed={"x": xv, "out": prior, "flag": np.asarray(1.0)}, fetch_list=["out"]
    )
    np.testing.assert_allclose(np.asarray(t_out), xv * 10.0)


def test_cond_vector_per_sample_select():
    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("flag", shape=[4], is_data=True)
    block.create_var("x", shape=[4, 2], is_data=True)
    true_b = prog.create_block()
    true_b.append_op("scale", {"X": "x"}, {"Out": "y"}, {"scale": 2.0})
    prog.rollback()
    false_b = prog.create_block()
    false_b.append_op("scale", {"X": "x"}, {"Out": "y"}, {"scale": 0.0})
    prog.rollback()
    block.desc.ops.append(
        fluid.framework.OpDesc(
            type="cond",
            attrs={"cond": "flag", "true_block": true_b.idx,
                   "false_block": false_b.idx, "outs": ["y"]},
        )
    )
    xv = np.ones((4, 2), np.float32)
    flag = np.array([1, 0, 1, 0], np.float32)
    exe = fluid.Executor()
    (y,) = exe.run(prog, feed={"x": xv, "flag": flag}, fetch_list=["y"])
    np.testing.assert_allclose(np.asarray(y)[:, 0], [2.0, 0.0, 2.0, 0.0])


def test_cond_missing_passthrough_raises():
    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("flag", shape=[], is_data=True)
    block.create_var("x", shape=[3], is_data=True)
    true_b = prog.create_block()
    true_b.append_op("scale", {"X": "x"}, {"Out": "only_inside"}, {"scale": 2.0})
    prog.rollback()
    block.desc.ops.append(
        fluid.framework.OpDesc(
            type="cond",
            attrs={"cond": "flag", "true_block": true_b.idx, "outs": ["only_inside"]},
        )
    )
    exe = fluid.Executor()
    with pytest.raises(KeyError, match="false_block"):
        exe.run(
            prog,
            feed={"x": np.ones(3, np.float32), "flag": np.asarray(1.0)},
            fetch_list=["only_inside"],
            use_jit=False,
        )


# ---------------------------------------------------------------------------
# LSTM / GRU ops vs numpy oracles
# ---------------------------------------------------------------------------


def _np_lstm(proj, w_hh, bias, mask):
    """Gate order [i, f, c, o] (ops/rnn.py convention)."""
    b, t, h4 = proj.shape
    h = h4 // 4
    hs, cs = [], []
    hv = np.zeros((b, h), np.float32)
    cv = np.zeros((b, h), np.float32)

    def sig(x):
        return 1.0 / (1.0 + np.exp(-x))

    for step in range(t):
        g = proj[:, step] + hv @ w_hh + bias
        gi, gf, gc, go = np.split(g, 4, -1)
        c_new = sig(gf) * cv + sig(gi) * np.tanh(gc)
        h_new = sig(go) * np.tanh(c_new)
        m = mask[:, step][:, None]
        hv = m * h_new + (1 - m) * hv
        cv = m * c_new + (1 - m) * cv
        hs.append(hv)
        cs.append(cv)
    return np.stack(hs, 1), np.stack(cs, 1), hv


def test_fluid_lstm_op_matches_numpy_full_cell_sequence():
    rs = np.random.RandomState(0)
    b, t, h = 3, 5, 4
    proj = rs.randn(b, t, 4 * h).astype(np.float32) * 0.5
    w = (rs.randn(h, 4 * h) * 0.3).astype(np.float32)
    bias = (rs.randn(4 * h) * 0.1).astype(np.float32)
    lengths = np.array([5, 3, 4], np.int32)
    mask = (np.arange(t)[None, :] < lengths[:, None]).astype(np.float32)

    out = OPS.get("lstm")(
        OpContext(),
        {"Input": [proj], "Weight": [w], "Bias": [bias], "SeqLengths": [lengths]},
        {},
    )
    hs_w, cs_w, h_last_w = _np_lstm(proj, w, bias, mask)
    np.testing.assert_allclose(np.asarray(out["Hidden"]), hs_w, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["Cell"]), cs_w, rtol=1e-5, atol=1e-5)
    assert out["Cell"].shape == (b, t, h)  # FULL cell sequence (lstm_op.cc)
    np.testing.assert_allclose(np.asarray(out["LastH"]), h_last_w, rtol=1e-5, atol=1e-5)


def test_fluid_gru_unit_matches_numpy():
    rs = np.random.RandomState(4)
    b, h = 3, 4
    x = rs.randn(b, 3 * h).astype(np.float32) * 0.5
    hp = rs.randn(b, h).astype(np.float32) * 0.5
    w = (rs.randn(h, 3 * h) * 0.3).astype(np.float32)

    out = OPS.get("gru_unit")(
        OpContext(), {"Input": [x], "HiddenPrev": [hp], "Weight": [w], "Bias": [None]}, {}
    )

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    pz, pr, pc = np.split(x, 3, -1)
    rz = hp @ w[:, : 2 * h]
    z = sig(pz + rz[:, :h])
    r = sig(pr + rz[:, h:])
    c = np.tanh(pc + (r * hp) @ w[:, 2 * h:])
    want = (1 - z) * hp + z * c
    np.testing.assert_allclose(np.asarray(out["Hidden"]), want, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# LoDTensor sequence ops
# ---------------------------------------------------------------------------


def _lod_fixture():
    from paddle_tpu.fluid.lod import LoDTensor, lod_from_lengths

    rs = np.random.RandomState(5)
    lengths = [3, 1, 4]
    data = rs.randn(sum(lengths), 2).astype(np.float32)
    return LoDTensor(np.asarray(data), (lod_from_lengths(lengths),)), data, lengths


@pytest.mark.parametrize(
    "pooltype,reducer",
    [
        ("SUM", lambda seg: seg.sum(0)),
        ("AVERAGE", lambda seg: seg.mean(0)),
        ("MAX", lambda seg: seg.max(0)),
        ("SQRT", lambda seg: seg.sum(0) / np.sqrt(len(seg))),
        ("LAST", lambda seg: seg[-1]),
        ("FIRST", lambda seg: seg[0]),
    ],
)
def test_sequence_pool_vs_numpy(pooltype, reducer):
    t, data, lengths = _lod_fixture()
    out = OPS.get("sequence_pool")(OpContext(), {"X": [t]}, {"pooltype": pooltype})["Out"]
    offs = np.concatenate([[0], np.cumsum(lengths)])
    want = np.stack([reducer(data[offs[i]: offs[i + 1]]) for i in range(len(lengths))])
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


def test_sequence_softmax_vs_numpy():
    t, data, lengths = _lod_fixture()
    from paddle_tpu.fluid.lod import LoDTensor, lod_from_lengths

    v = data[:, 0].copy()
    t1 = LoDTensor(np.asarray(v), (lod_from_lengths(lengths),))
    out = OPS.get("sequence_softmax")(OpContext(), {"X": [t1]}, {})["Out"]
    offs = np.concatenate([[0], np.cumsum(lengths)])
    want = np.zeros_like(v)
    for i in range(len(lengths)):
        seg = v[offs[i]: offs[i + 1]]
        e = np.exp(seg - seg.max())
        want[offs[i]: offs[i + 1]] = e / e.sum()
    got = np.asarray(out.data if hasattr(out, "data") else out).reshape(-1)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_lod_padded_round_trip():
    from paddle_tpu.fluid import lod as lod_mod

    t, data, lengths = _lod_fixture()
    padded, lens = lod_mod.to_padded(t, max_len=4)
    assert padded.shape == (3, 4, 2)
    back = lod_mod.from_padded(np.asarray(padded), np.asarray(lens))
    np.testing.assert_allclose(np.asarray(back.data), data, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(back.lod[-1]), np.asarray(t.lod[-1])
    )


def test_selected_rows_to_dense_accumulates_duplicates():
    from paddle_tpu.fluid.lod import SelectedRows

    sr = SelectedRows(
        rows=np.asarray([1, 3, 1], np.int32),
        value=np.asarray([[1.0], [2.0], [10.0]], np.float32),
        height=5,
    )
    dense = np.asarray(sr.to_dense())
    np.testing.assert_allclose(dense[:, 0], [0.0, 11.0, 0.0, 2.0, 0.0])


# ---------------------------------------------------------------------------
# IO ops: feed / fetch / save / load
# ---------------------------------------------------------------------------


def test_feed_fetch_ops():
    holder = [np.asarray([1.0, 2.0]), np.asarray([3.0])]
    out = OPS.get("feed")(OpContext(), {"X": [holder]}, {"col": 1})["Out"]
    np.testing.assert_allclose(out, [3.0])
    fetch_holder = []
    got = OPS.get("fetch")(
        OpContext(), {"X": [np.asarray([9.0])], "Holder": [fetch_holder]}, {"col": 0}
    )["Out"]
    np.testing.assert_allclose(got, [9.0])
    np.testing.assert_allclose(fetch_holder[0], [9.0])


def test_save_load_round_trip(tmp_path):
    import jax

    path = str(tmp_path / "var.npy")
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    OPS.get("save")(OpContext(), {"X": [x]}, {"file_path": path})
    out = OPS.get("load")(OpContext(), {}, {"file_path": path})["Out"]
    np.testing.assert_allclose(np.asarray(out), x)

    # traced save: io_callback path
    path2 = str(tmp_path / "traced.npy")

    @jax.jit
    def f(v):
        return OPS.get("save")(OpContext(), {"X": [v]}, {"file_path": path2})["Out"]

    f(x).block_until_ready()
    np.testing.assert_allclose(np.load(path2), x)


# ---------------------------------------------------------------------------
# beam_search / beam_search_decode ops
# ---------------------------------------------------------------------------


def test_beam_search_step_selects_topk_and_masks_finished():
    k, v, end_id = 2, 5, 0
    pre_ids = np.asarray([[3], [0]], np.int64)  # beam 1 already finished (EOS)
    pre_scores = np.asarray([[-1.0], [-0.5]], np.float32)
    probs = np.full((2, v), 1e-9, np.float32)
    probs[0, 2] = 0.6
    probs[0, 4] = 0.3
    probs[1, 3] = 0.9  # ignored: beam is finished
    out = OPS.get("beam_search")(
        OpContext(),
        {"pre_ids": [pre_ids], "pre_scores": [pre_scores], "scores": [probs]},
        # probabilities in: is_accumulated=False (the default, matching the
        # reference, is accumulated log-probs)
        {"beam_size": k, "end_id": end_id, "is_accumulated": False},
    )
    ids = np.asarray(out["selected_ids"]).reshape(-1)
    parents = np.asarray(out["parent_idx"]).reshape(-1)
    scores = np.asarray(out["selected_scores"]).reshape(-1)
    # best candidate: finished beam propagating EOS at score -0.5
    assert ids[0] == end_id and parents[0] == 1
    np.testing.assert_allclose(scores[0], -0.5, rtol=1e-5)
    # second: token 2 from live beam 0 at -1 + log(0.6)
    assert ids[1] == 2 and parents[1] == 0
    np.testing.assert_allclose(scores[1], -1.0 + np.log(0.6), rtol=1e-5)


def test_beam_search_decode_backtracks():
    # B=1, K=2, T=3; hand-built parent chain.
    ids = np.asarray([[5, 7], [2, 4], [9, 1]], np.int64)  # [T, K]
    parents = np.asarray([[0, 0], [1, 0], [0, 1]], np.int64)
    scores = np.asarray([-0.1, -0.2], np.float32)
    out = OPS.get("beam_search_decode")(
        OpContext(),
        {"Ids": [ids], "ParentIdx": [parents], "Scores": [scores]},
        {"beam_size": 2},
    )
    seqs = np.asarray(out["SentenceIds"])[0]  # [K, T]
    # beam 0 at t=2: token 9, parent 0 → t=1 token 2, parent 1 → t=0 token 7
    np.testing.assert_array_equal(seqs[0], [7, 2, 9])
    # beam 1 at t=2: token 1, parent 1 → t=1 token 4, parent 0 → t=0 token 5
    np.testing.assert_array_equal(seqs[1], [5, 4, 1])
    np.testing.assert_allclose(np.asarray(out["SentenceScores"])[0], scores)


# ---------------------------------------------------------------------------
# end-to-end: text-classification LSTM trained through the fluid API
# (r2 task #5's done-bar; reference idiom test_recurrent_op.py + book ch.6)
# ---------------------------------------------------------------------------


def test_fluid_lstm_text_classifier_converges():
    rs = np.random.RandomState(0)
    vocab, emb_d, hid, b, t, ncls = 30, 8, 16, 16, 6, 2
    # class-separable synthetic text: class c's tokens cluster in one range
    lbl = rs.randint(0, ncls, (b, 1))
    ids = np.where(
        lbl == 0,
        rs.randint(2, vocab // 2, (b, t)),
        rs.randint(vocab // 2, vocab, (b, t)),
    ).astype(np.int64)
    lengths = np.full((b,), t, np.int32)

    prog = fluid.default_main_program()
    block = prog.global_block()
    block.create_var("ids", shape=[t], dtype=np.int64, is_data=True)
    block.create_var("lengths", shape=[], dtype=np.int32, is_data=True)
    block.create_var("label", shape=[1], dtype=np.int64, is_data=True)

    emb_w = block.create_parameter(
        "emb.w", shape=[vocab, emb_d], initializer=("uniform", -0.1, 0.1)
    )
    block.append_op("lookup_table", {"W": emb_w, "Ids": "ids"}, {"Out": "emb"}, {})
    proj_w = block.create_parameter(
        "proj.w", shape=[emb_d, 4 * hid], initializer=("uniform", -0.3, 0.3)
    )
    block.append_op(
        "mul", {"X": "emb", "Y": proj_w}, {"Out": "proj"}, {"x_num_col_dims": 2}
    )
    lstm_w = block.create_parameter(
        "lstm.w", shape=[hid, 4 * hid], initializer=("uniform", -0.3, 0.3)
    )
    lstm_b = block.create_parameter(
        "lstm.b", shape=[4 * hid], initializer=("constant", 0.0)
    )
    block.append_op(
        "lstm",
        {"Input": "proj", "Weight": lstm_w, "Bias": lstm_b, "SeqLengths": "lengths"},
        {"Hidden": "hidden", "Cell": "cell", "LastH": "last_h"},
        {},
    )
    fc_w = block.create_parameter(
        "fc.w", shape=[hid, ncls], initializer=("uniform", -0.3, 0.3)
    )
    block.append_op("mul", {"X": "last_h", "Y": fc_w}, {"Out": "logits"}, {})
    block.append_op("softmax", {"X": "logits"}, {"Y": "probs"}, {})
    block.append_op(
        "cross_entropy", {"X": "probs", "Label": "label"}, {"Y": "xent"}, {}
    )
    loss = block.create_var("loss", shape=[])
    block.append_op("mean", {"X": "xent"}, {"Out": loss}, {})

    fluid.optimizer.AdamOptimizer(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor()
    feed = {"ids": ids, "lengths": lengths, "label": lbl}
    losses = []
    for _ in range(30):
        (lv,) = exe.run(prog, feed=feed, fetch_list=[loss])
        losses.append(float(lv))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] / 4, (losses[0], losses[-1])
