"""GAN-style alternating training (MultiNetwork.cpp / v1_api_demo/gan):
two networks share parameters by name, each phase freezes the other side via
ParamAttr(is_static=True), shared values sync between phase steps."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nn import costs as C
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.graph import Network, ParamAttr, reset_name_scope
from paddle_tpu.optim import Adam
from paddle_tpu.trainer import SGDTrainer
from paddle_tpu.trainer.multi_network import MultiNetworkTrainer


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()


def _discriminator(sample, static: bool):
    pa = lambda n: ParamAttr(name=n, is_static=static)
    h = L.Fc(sample, 16, act="relu", param_attr=pa("dis_w1"),
             bias_attr=pa("dis_b1"), name=f"dish_{static}")
    return L.Fc(h, 2, act="softmax", param_attr=pa("dis_w2"),
                bias_attr=pa("dis_b2"), name=f"diso_{static}")


def _generator(noise, static: bool):
    pa = lambda n: ParamAttr(name=n, is_static=static)
    h = L.Fc(noise, 16, act="relu", param_attr=pa("gen_w1"),
             bias_attr=pa("gen_b1"), name=f"genh_{static}")
    return L.Fc(h, 2, act=None, param_attr=pa("gen_w2"),
                bias_attr=pa("gen_b2"), name=f"geno_{static}")


def test_gan_alternating_training_converges():
    rs = np.random.RandomState(0)
    data_mean = np.asarray([2.0, -1.0], np.float32)
    bs = 64

    # discriminator phase: real+fake samples fed as data, gen frozen N/A
    d_sample = L.Data("sample", shape=(2,))
    d_label = L.Data("label", shape=())
    d_out = _discriminator(d_sample, static=False)
    d_cost = C.ClassificationCost(d_out, d_label, name="d_cost")
    dis_tr = SGDTrainer(d_cost, Adam(learning_rate=1e-2))

    # generator phase: noise -> G (trainable) -> D (static) scored as "real"
    g_noise = L.Data("noise", shape=(4,))
    g_label = L.Data("label", shape=())
    g_sample = _generator(g_noise, static=False)
    g_out = _discriminator(g_sample, static=True)
    g_cost = C.ClassificationCost(g_out, g_label, name="g_cost")
    gen_tr = SGDTrainer(g_cost, Adam(learning_rate=1e-2))

    gen_net = Network(g_sample)

    def real_batch():
        return data_mean + rs.randn(bs, 2).astype(np.float32) * 0.3

    def noise_batch():
        return rs.randn(bs, 4).astype(np.float32)

    mt = MultiNetworkTrainer({"dis": dis_tr, "gen": gen_tr})
    mt.init_state({
        "dis": {"sample": real_batch(), "label": np.ones(bs, np.int64)},
        "gen": {"noise": noise_batch(), "label": np.ones(bs, np.int64)},
    })

    def gen_samples(n=256):
        params = mt.state_of("gen")["params"]
        outs, _ = gen_net.apply(params, mt.state_of("gen")["states"],
                                {"noise": rs.randn(n, 4).astype(np.float32)})
        return np.asarray(outs[g_sample.name].value)

    before = np.linalg.norm(gen_samples().mean(0) - data_mean)

    for it in range(400):
        fake = gen_samples(bs)
        samples = np.concatenate([real_batch(), fake], 0)
        labels = np.concatenate([np.ones(bs), np.zeros(bs)]).astype(np.int64)
        mt.step("dis", {"sample": samples, "label": labels})
        mt.step("gen", {"noise": noise_batch(),
                        "label": np.ones(bs, np.int64)})

    after = np.linalg.norm(gen_samples().mean(0) - data_mean)
    assert after < before * 0.5, (before, after)

    # frozen copies really stayed in sync: dis params identical across phases
    for k in ("dis_w1", "dis_w2"):
        np.testing.assert_array_equal(
            np.asarray(mt.state_of("dis")["params"][k]),
            np.asarray(mt.state_of("gen")["params"][k]),
        )
    # and the generator's params never moved inside the dis phase state
    assert "gen_w1" not in mt.state_of("dis")["params"]
