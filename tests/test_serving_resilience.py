"""Serving resilience (ISSUE 10).

The load-bearing claims, each tested directly:

  * deadlines — a request past its total-latency deadline is cancelled with
    the NAMED reason 'deadline' whether it is still queued or mid-decode,
    and its KV pages return to the free list the same step; TTFT-deadline
    misses are counted (the client-hedging signal) but never fatal;
  * overload shedding — admission rejects a request whose estimated queue
    wait exceeds its deadline budget ('overload', with a `retry_after_ms`
    hint) and a full queue ('queue') instead of queueing doomed work;
  * client abandonment — `result(timeout=)` expiring CANCELS the request
    server-side (reason 'client_timeout'), closing the classic leak where
    the client raises but the request keeps decoding and holding pages;
  * engine crash recovery — for every seeded fault site (decode_raise,
    engine_stall, page_exhaust) the supervisor restarts the engine,
    re-initializes the page pool and replays in-flight prompts so the run is
    RESULT-TRANSPARENT (same tokens as unfaulted) with zero page leak; past
    the restart budget every outstanding request fails 'engine_error';
  * hedged retry — `ServingClient.generate(hedge_ttft_s=)` re-submits under
    the same idempotency key after a TTFT miss and the server dedup
    guarantees exactly ONE engine execution per request id;
  * incremental poll — tokens generated so far ride every poll reply (the
    first step toward streaming delivery).

Deadline/cancellation unit tests drive the engine inline and pass explicit
`now` timestamps to step() — no sleeps, fully deterministic; the supervisor
tests run the real engine thread under seeded faults."""

import threading
import time

import pytest

from paddle_tpu.core import faults

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 96

NAMED_REASONS = {
    "eos", "length", "deadline", "cancelled", "client_timeout",
    "engine_error",
}


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    return ServingSession(model, params, **kw)


PROMPTS = [
    [1, 5, 9, 11],
    [1, 7],
    [1, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14],
    [1, 40, 41, 42, 43, 44, 45, 46],
]


# -- deadlines ----------------------------------------------------------------


def test_deadline_expires_in_queue(model_and_params):
    """A queued request past its deadline is reaped at the next step
    boundary with the named reason — before it ever costs a prefill."""
    s = make_session(model_and_params)
    total_free = s.cache.free_pages
    h = s.submit(PROMPTS[0], 8, deadline_s=5.0)
    misses0 = s.scheduler.deadline_misses
    s.step(h.t_deadline + 0.001)  # simulated clock: past the deadline
    assert h.done and h.status == h.CANCELLED
    assert h.finish_reason == "deadline"
    assert s.scheduler.deadline_misses == misses0 + 1
    assert s.cache.free_pages == total_free, "nothing was ever reserved"
    with pytest.raises(RuntimeError, match="deadline"):
        h.result()


def test_deadline_expires_mid_decode_recycles_pages(model_and_params):
    """A RUNNING request whose deadline passes is retired at the step
    boundary and its reserved KV pages return to the free list THAT step."""
    s = make_session(model_and_params)
    total_free = s.cache.free_pages
    h = s.submit(PROMPTS[2], 16, deadline_s=30.0)
    s.step()  # admit + prefill: pages now reserved
    assert h.status == h.RUNNING and s.cache.free_pages < total_free
    recycled0 = s.scheduler.pages_recycled_on_cancel
    s.step(h.t_deadline + 0.001)
    assert h.done and h.finish_reason == "deadline"
    assert len(h.tokens) < 16, "cancelled mid-decode, not run to budget"
    assert s.cache.free_pages == total_free, "pages must recycle on expiry"
    assert s.scheduler.pages_recycled_on_cancel > recycled0


def test_ttft_deadline_miss_counted_not_fatal(model_and_params):
    """TTFT is a *hedging signal*: a late first token increments the miss
    counter but the request still runs to a normal completion."""
    from paddle_tpu.serving.session import SERVING_EVENTS

    s = make_session(model_and_params)
    before = SERVING_EVENTS.get("serving_ttft_deadline_missed")
    # a freshly-jitted prefill takes far longer than 1ms, so the first
    # token is guaranteed late
    h = s.submit(PROMPTS[0], 4, ttft_deadline_s=1e-3)
    s.run_until_idle()
    assert h.done and h.status == h.DONE
    assert h.finish_reason in ("length", "eos")
    assert SERVING_EVENTS.get("serving_ttft_deadline_missed") == before + 1


def test_deadline_defaults_resolve_tenant_then_session(model_and_params):
    """Resolution order: explicit per-request value > tenant quota default >
    session-wide default; None all the way down = no deadline."""
    from paddle_tpu.serving.quota import TenantQuotas

    quotas = TenantQuotas(max_concurrent=8, default_deadline_s=7.0)
    quotas.set_quota("gold", deadline_s=3.0, ttft_deadline_s=0.5)
    s = make_session(model_and_params, quotas=quotas)
    gold = s.submit(PROMPTS[0], 2, tenant="gold")
    assert abs((gold.t_deadline - gold.t_submit) - 3.0) < 0.25
    assert abs((gold.t_ttft_deadline - gold.t_submit) - 0.5) < 0.25
    other = s.submit(PROMPTS[1], 2, tenant="other")
    assert abs((other.t_deadline - other.t_submit) - 7.0) < 0.25
    explicit = s.submit(PROMPTS[1], 2, tenant="gold", deadline_s=1.0)
    assert abs((explicit.t_deadline - explicit.t_submit) - 1.0) < 0.25

    s2 = make_session(model_and_params, default_deadline_s=2.0)
    sess_default = s2.submit(PROMPTS[0], 2)
    assert abs((sess_default.t_deadline - sess_default.t_submit) - 2.0) < 0.25
    none = make_session(model_and_params).submit(PROMPTS[0], 2)
    assert none.t_deadline is None and none.t_ttft_deadline is None


# -- overload shedding --------------------------------------------------------


def test_admission_sheds_doomed_request_with_retry_hint(model_and_params):
    """Load-aware admission: when the wait estimate says the deadline budget
    cannot be met, the request is shed at the front door with the named
    reason 'overload' and a retry_after_ms hint — not queued to die."""
    from paddle_tpu.serving.quota import QuotaExceeded

    s = make_session(model_and_params)
    s.scheduler._ewma_service_s = 1.0  # observed: one request takes ~1s
    shed0 = s.scheduler.shed
    with pytest.raises(QuotaExceeded) as ei:
        s.submit(PROMPTS[0], 8, deadline_s=0.5)
    assert ei.value.reason == "overload"
    assert ei.value.retry_after_ms >= 500
    assert s.scheduler.shed == shed0 + 1
    # an already-expired deadline is its own named reason
    with pytest.raises(QuotaExceeded) as ei:
        s.submit(PROMPTS[0], 8, deadline_s=0.0)
    assert ei.value.reason == "deadline"
    # no deadline -> no load gate: the same request is admitted
    h = s.submit(PROMPTS[0], 8)
    assert h.status == h.QUEUED
    h.cancel()


def test_ttft_budget_compared_to_queue_wait_not_completion(model_and_params):
    """A TTFT deadline shorter than one service time must NOT shed on an
    idle server (TTFT ≈ queue wait, which is 0 there — the 'counted, never
    fatal' contract); it DOES shed once a queue actually stands between the
    request and its first token."""
    from paddle_tpu.serving.quota import QuotaExceeded

    s = make_session(model_and_params)
    s.scheduler._ewma_service_s = 1.0
    h = s.submit(PROMPTS[0], 8, ttft_deadline_s=0.5)  # idle: admitted
    assert h.status == h.QUEUED
    # an already-expired TTFT budget still admits (it only counts a miss)
    h2 = s.submit(PROMPTS[0], 8, ttft_deadline_s=0.0)
    assert h2.status == h2.QUEUED
    # ~3 waves of queue now stand ahead -> est queue wait > 0.5s -> shed
    for _ in range(3 * s.cache.max_slots):
        s.submit(PROMPTS[1], 2)
    with pytest.raises(QuotaExceeded) as ei:
        s.submit(PROMPTS[0], 8, ttft_deadline_s=0.5)
    assert ei.value.reason == "overload"


def test_queue_bound_shed_carries_retry_hint(model_and_params):
    from paddle_tpu.serving.quota import QuotaExceeded

    s = make_session(model_and_params, max_queue=2)
    s.scheduler.submit([1, 2], 2, "x")
    s.scheduler.submit([1, 2], 2, "x")
    with pytest.raises(QuotaExceeded) as ei:
        s.scheduler.submit([1, 2], 2, "x")
    assert ei.value.reason == "queue"
    assert ei.value.retry_after_ms is not None and ei.value.retry_after_ms >= 1


# -- client abandonment (the satellite fix) -----------------------------------


def test_result_timeout_cancels_server_side(model_and_params):
    """The pre-ISSUE-10 leak: result(timeout=) raised client-side while the
    request kept decoding and holding KV pages. Now the expiry cancels the
    request — queued ones immediately, running ones at the next step
    boundary with their pages recycled."""
    s = make_session(model_and_params)
    total_free = s.cache.free_pages

    # queued: cancelled inline, nothing was reserved
    q = s.submit(PROMPTS[0], 8)
    with pytest.raises(TimeoutError, match="cancelled server-side"):
        q.result(timeout=0.01)
    assert q.done and q.status == q.CANCELLED
    assert q.finish_reason == "client_timeout"

    # running: pages reserved at admission must come back at the boundary
    r = s.submit(PROMPTS[2], 16)
    s.step()
    assert r.status == r.RUNNING and s.cache.free_pages < total_free
    recycled0 = s.scheduler.pages_recycled_on_cancel
    with pytest.raises(TimeoutError):
        r.result(timeout=0.01)
    assert not r.done, "a running request retires at the boundary, not mid-step"
    s.step()
    assert r.done and r.finish_reason == "client_timeout"
    assert s.cache.free_pages == total_free
    assert s.scheduler.pages_recycled_on_cancel > recycled0

    # opt-out keeps the old semantics for callers that poll later
    keep = s.submit(PROMPTS[1], 8)
    with pytest.raises(TimeoutError):
        keep.result(timeout=0.01, cancel_on_timeout=False)
    assert not keep.done and keep.status == keep.QUEUED
    s.run_until_idle()
    assert keep.done and keep.status == keep.DONE


# -- incremental poll ---------------------------------------------------------


def test_poll_returns_tokens_so_far(model_and_params):
    """Every poll of an unfinished request delivers the tokens generated so
    far — prefix-consistent across polls (streaming's first step)."""
    from paddle_tpu.serving.server import ServingServer

    s = make_session(model_and_params)
    srv = ServingServer(session=s)
    try:
        rid = srv.dispatch(
            "submit", {"prompt": PROMPTS[0], "max_new_tokens": 6}, None
        )["request_id"]
        s.step()  # prefill -> first token
        p1 = srv.dispatch("poll", {"request_id": rid}, None)
        assert not p1["done"]
        assert p1["tokens"] and len(p1["tokens"]) == p1["tokens_so_far"]
        s.step()
        p2 = srv.dispatch("poll", {"request_id": rid}, None)
        assert len(p2["tokens"]) > len(p1["tokens"])
        assert p2["tokens"][: len(p1["tokens"])] == p1["tokens"]
        s.run_until_idle()
        done = srv.dispatch("poll", {"request_id": rid}, None)
        assert done["done"] and done["finish_reason"] in ("length", "eos")
        assert done["tokens"][: len(p2["tokens"])] == p2["tokens"]
    finally:
        srv.stop()


def test_cancel_rpc(model_and_params):
    from paddle_tpu.serving.server import ServingServer

    s = make_session(model_and_params)
    srv = ServingServer(session=s)
    try:
        rid = srv.dispatch(
            "submit", {"prompt": PROMPTS[0], "max_new_tokens": 6}, None
        )["request_id"]
        r = srv.dispatch("cancel", {"request_id": rid}, None)
        assert r["cancelled"] is True
        p = srv.dispatch("poll", {"request_id": rid}, None)
        assert p["done"] and p["cancelled"] and p["finish_reason"] == "cancelled"
        # idempotent once finished
        again = srv.dispatch("cancel", {"request_id": rid}, None)
        assert again["cancelled"] is False and again["done"] is True
    finally:
        srv.stop()


# -- engine crash recovery ----------------------------------------------------


@pytest.mark.timeout(120)
@pytest.mark.parametrize(
    "site,spec",
    [
        ("decode_raise", "decode_raise:step=3"),
        ("engine_stall", "engine_stall:step=2"),
        ("page_exhaust", "page_exhaust:step=0"),
    ],
)
def test_engine_recovery_result_transparent_zero_leak(
    model_and_params, site, spec, monkeypatch
):
    """The acceptance bits, per seeded fault site: the supervisor restarts
    the engine, every accepted request finishes with a NAMED reason and the
    SAME tokens as an unfaulted run (replay is result-transparent), and the
    page free list is whole afterwards."""
    monkeypatch.setenv("PADDLE_TPU_SERVING_STALL_S", "1")

    clean = make_session(model_and_params)
    ref_handles = [clean.submit(p, 8) for p in PROMPTS]
    clean.run_until_idle()
    ref = [h.tokens for h in ref_handles]

    s = make_session(
        model_and_params, engine_stall_timeout_s=0.3, engine_restart_max=5
    )
    total_free = s.cache.free_pages
    with faults.inject(spec, seed=0) as inj:
        s.serve_forever()
        handles = [s.submit(p, 8, deadline_s=60.0) for p in PROMPTS]
        deadline = time.monotonic() + 90
        for h in handles:
            assert h._event.wait(max(0.1, deadline - time.monotonic())), (
                f"request {h.request_id} never completed after {site}"
            )
        fired = dict(inj.fired)
    s.stop()
    assert fired.get(site, 0) >= 1, "the seeded fault must actually fire"
    assert s.engine_restarts >= 1, "the supervisor must have recovered"
    assert all(h.finish_reason in NAMED_REASONS for h in handles)
    assert [h.tokens for h in handles] == ref, (
        "replayed greedy decode must be result-transparent"
    )
    assert s.cache.free_pages == total_free, "zero page leak after recovery"


@pytest.mark.timeout(60)
def test_restart_budget_exhausted_fails_engine_error(model_and_params):
    """Past engine_restart_max the supervisor gives up LOUDLY: outstanding
    requests fail with the named reason 'engine_error' and new submits are
    refused — a dead engine must never look healthy-but-slow."""
    s = make_session(model_and_params, engine_restart_max=1)
    total_free = s.cache.free_pages
    with faults.inject("decode_raise:1.0", seed=0):  # every decode attempt
        s.serve_forever()
        h = s.submit(PROMPTS[0], 8)
        assert h._event.wait(30)
    assert h.status == h.CANCELLED and h.finish_reason == "engine_error"
    assert s.engine_restarts == 1
    assert s.cache.free_pages == total_free
    with pytest.raises(RuntimeError, match="died"):
        s.submit(PROMPTS[1], 4)
    s.stop()


# -- hedged retry / dedup -----------------------------------------------------


@pytest.mark.timeout(120)
def test_hedged_generate_exactly_one_execution(model_and_params):
    """The hedge re-submits under the SAME idempotency key after a TTFT
    miss; the server's (tenant, client_req_id) dedup reattaches it to the
    original request — exactly one engine execution, one set of tokens."""
    from paddle_tpu.serving.server import ServingClient, ServingServer

    ref_sess = make_session(model_and_params)
    ref_h = ref_sess.submit(PROMPTS[0], 6)
    ref_sess.run_until_idle()

    s = make_session(model_and_params)
    # hold the engine: a placeholder thread makes ServingServer.start (and
    # serve_forever's idempotence guard) treat it as already running, so
    # nothing decodes until it starts for real below — the hedge is then
    # guaranteed to fire on a genuinely token-less request, and the dedup
    # path (not timing luck) is what collapses the two submits
    s._thread = threading.Thread(target=lambda: None)
    srv = ServingServer(session=s).start()
    try:
        c = ServingClient(srv.address)
        out = {}

        def run():
            out["resp"] = c.generate(
                PROMPTS[0], 6, hedge_ttft_s=0.1, timeout_s=60.0
            )

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while c.hedges == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c.hedges == 1, "TTFT miss must have triggered the hedge"
        s._thread = None
        s.serve_forever()
        t.join(60)
        assert not t.is_alive() and out["resp"]["done"]
        assert out["resp"]["tokens"] == ref_h.tokens
        # exactly one engine execution for the hedged pair
        assert s.scheduler.completed == 1
        with srv._handles_lock:
            assert len(srv._handles) == 1
        c.close()
    finally:
        srv.stop()
