"""Config/topology equivalence — the reference's test_NetworkCompare.cpp +
trainer_config_helpers golden-proto idiom: two ways of expressing the same
network must produce identical parameter shapes AND identical outputs under
identical parameter values."""

import numpy as np
import pytest

import jax

from paddle_tpu.nn.graph import Network, reset_name_scope


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    yield


def _run(net, batch, params=None, states=None):
    if params is None:
        params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    return params, states, outs


def test_v1_dsl_equals_v2_api():
    """The same MLP via config-script DSL and via the v2 layer API."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector, integer_value

    def dsl_config():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        img = H.data_layer(name="pixel", size=16)
        lbl = H.data_layer(name="label", size=4)
        h = H.fc_layer(input=img, size=8, act=H.TanhActivation(), name="h")
        out = H.fc_layer(input=h, size=4, act=H.SoftmaxActivation(), name="out")
        outputs(H.classification_cost(input=out, label=lbl, name="cost"))

    def v2():
        img = vl.data(name="pixel", type=dense_vector(16))
        lbl = vl.data(name="label", type=integer_value(4))
        h = vl.fc(input=img, size=8, act="tanh", name="h")
        out = vl.fc(input=h, size=4, act="softmax", name="out")
        return vl.classification_cost(input=out, label=lbl, name="cost")

    rs = np.random.RandomState(0)
    batch = {
        "pixel": rs.randn(6, 16).astype(np.float32),
        "label": rs.randint(0, 4, 6),
    }
    _compare_dsl_v2(dsl_config, v2, batch)


def test_mixed_projection_equals_primitive_fc():
    """mixed(full_matrix_projection) == fc without bias/activation — the
    concat_dotmul_a/b.conf equivalence class of the reference."""
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import projections as P
    from paddle_tpu.nn.graph import ParamAttr

    data = L.Data("x", shape=(12,))
    shared = ParamAttr(name="w_shared")
    mixed = L.Mixed(
        [P.FullMatrix(data, param_attr=shared)], size=8, act=None, bias=False,
        name="mixed_out",
    )
    fc = L.Fc(data, 8, act=None, bias=False, param_attr=shared, name="fc_out")
    net = Network([mixed, fc])
    rs = np.random.RandomState(1)
    batch = {"x": rs.randn(5, 12).astype(np.float32)}
    params, states = net.init(jax.random.PRNGKey(0), batch)
    assert list(params) == ["w_shared"]  # one shared weight, no duplicates
    outs, _ = net.apply(params, states, batch)
    np.testing.assert_allclose(
        np.asarray(outs["mixed_out"].value),
        np.asarray(outs["fc_out"].value),
        rtol=1e-5, atol=1e-6,
    )


def _compare_dsl_v2(dsl_config, v2_build, batch_dsl, batch_v2=None, cost="cost"):
    """Parse a v1 config script and build the same net via the v2 API; assert
    identical parameter names/shapes and identical cost under shared weights
    (the test_NetworkCompare.cpp:222 contract)."""
    from paddle_tpu.config import parse_config

    pc = parse_config(dsl_config, emit_proto=False)
    net_dsl = pc.topology.network
    reset_name_scope()
    net_v2 = Network([v2_build()])

    p1, s1, o1 = _run(net_dsl, batch_dsl)
    p2, s2 = net_v2.init(jax.random.PRNGKey(0), batch_v2 or batch_dsl)
    assert set(p1) == set(p2)
    assert {k: v.shape for k, v in p1.items()} == {k: v.shape for k, v in p2.items()}
    _, _, o2 = _run(net_v2, batch_v2 or batch_dsl, p1, s1)
    np.testing.assert_allclose(
        np.asarray(o1[cost].value), np.asarray(o2[cost].value),
        rtol=1e-5, atol=1e-6,
    )


def test_conv_net_pair():
    """Conv/pool/fc image net: v1 DSL (flat data + geometry annotations) vs
    v2 API (NHWC data) — same params, same cost."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector, integer_value

    def dsl():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        img = H.data_layer(name="pixel", size=64, height=8, width=8)
        lbl = H.data_layer(name="label", size=5)
        c = H.img_conv_layer(input=img, filter_size=3, num_filters=4,
                             padding=1, act=H.ReluActivation(), name="conv1")
        p = H.img_pool_layer(input=c, pool_size=2, stride=2,
                             ceil_mode=False, name="pool1")
        out = H.fc_layer(input=p, size=5, act=H.SoftmaxActivation(), name="out")
        outputs(H.classification_cost(input=out, label=lbl, name="cost"))

    def v2():
        img = vl.data(name="pixel", type=dense_vector(64), height=8, width=8)
        lbl = vl.data(name="label", type=integer_value(5))
        c = vl.img_conv(input=img, filter_size=3, num_filters=4, padding=1,
                        act="relu", name="conv1")
        p = vl.img_pool(input=c, pool_size=2, stride=2, name="pool1")
        out = vl.fc(input=p, size=5, act="softmax", name="out")
        return vl.classification_cost(input=out, label=lbl, name="cost")

    rs = np.random.RandomState(3)
    flat = rs.randn(4, 64).astype(np.float32)
    lbl = rs.randint(0, 5, 4)
    _compare_dsl_v2(
        dsl, v2,
        batch_dsl={"pixel": flat, "label": lbl},
        batch_v2={"pixel": flat.reshape(4, 8, 8, 1), "label": lbl},
    )


def test_regression_cost_pair():
    """Linear fc + square_error: DSL regression_cost vs v2 square_error_cost."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector

    def dsl():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        x = H.data_layer(name="x", size=12)
        y = H.data_layer(name="y", size=3)
        out = H.fc_layer(input=x, size=3, act=H.LinearActivation(), name="out")
        outputs(H.regression_cost(input=out, label=y, name="cost"))

    def v2():
        x = vl.data(name="x", type=dense_vector(12))
        y = vl.data(name="y", type=dense_vector(3))
        out = vl.fc(input=x, size=3, act="linear", name="out")
        return vl.square_error_cost(input=out, label=y, name="cost")

    rs = np.random.RandomState(4)
    batch = {"x": rs.randn(6, 12).astype(np.float32),
             "y": rs.randn(6, 3).astype(np.float32)}
    _compare_dsl_v2(dsl, v2, batch)


def test_embedding_seqpool_pair():
    """Text classifier: DSL (seq-ness inferred via _mark_seq_root) vs v2
    (explicit integer_value_sequence)."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import integer_value, integer_value_sequence

    def dsl():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        w = H.data_layer(name="word", size=10)
        lbl = H.data_layer(name="label", size=3)
        emb = H.embedding_layer(input=w, size=6, name="emb")
        pooled = H.pooling_layer(input=emb, pooling_type=H.MaxPooling(),
                                 name="pooled")
        out = H.fc_layer(input=pooled, size=3, act=H.SoftmaxActivation(),
                         name="out")
        outputs(H.classification_cost(input=out, label=lbl, name="cost"))

    def v2():
        w = vl.data(name="word", type=integer_value_sequence(10))
        lbl = vl.data(name="label", type=integer_value(3))
        emb = vl.embedding(input=w, size=6, name="emb")
        pooled = vl.pool(input=emb, pooling_type="max", name="pooled")
        out = vl.fc(input=pooled, size=3, act="softmax", name="out")
        return vl.classification_cost(input=out, label=lbl, name="cost")

    rs = np.random.RandomState(5)
    batch = {
        "word": rs.randint(0, 10, (4, 7)),
        "word.lengths": np.asarray([7, 5, 3, 6], np.int32),
        "label": rs.randint(0, 3, 4),
    }
    _compare_dsl_v2(dsl, v2, batch)


def test_addto_equals_mixed_identity():
    """Parameterless equivalence: addto([x, y], act=tanh) == mixed layer over
    two identity projections with tanh — the util_layers equivalence class."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector

    x = vl.data(name="x", type=dense_vector(9))
    y = vl.data(name="y", type=dense_vector(9))
    a = vl.addto([x, y], act="tanh", name="a")
    m = vl.mixed(
        input=[vl.identity_projection(x), vl.identity_projection(y)],
        size=9, act="tanh", name="m",
    )
    net = Network([a, m])
    rs = np.random.RandomState(6)
    batch = {"x": rs.randn(5, 9).astype(np.float32),
             "y": rs.randn(5, 9).astype(np.float32)}
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    np.testing.assert_allclose(
        np.asarray(outs["a"].value), np.asarray(outs["m"].value),
        rtol=1e-6, atol=1e-7,
    )


def test_simple_lstm_network_equals_composed():
    """networks.simple_lstm == mixed-projection + lstmemory composition under
    shared weights (the prebuilt-net equivalence the reference proves with
    golden protostrs)."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector_sequence

    x = vl.data(name="x", type=dense_vector_sequence(8))
    lstm_a = vl.simple_lstm(x, 6, name="a")
    net = Network([lstm_a])
    rs = np.random.RandomState(2)
    batch = {
        "x": rs.randn(3, 5, 8).astype(np.float32),
        "x.lengths": np.asarray([5, 3, 2], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    assert outs[lstm_a.name].value.shape == (3, 5, 6)
    # masked positions beyond each length must not affect pooled last step
    last = np.asarray(outs[lstm_a.name].value)[1, 2]
    batch2 = dict(batch)
    b2 = batch["x"].copy()
    b2[1, 3:] = 99.0  # garbage in padding of sequence 1 (len 3)
    batch2["x"] = b2
    outs2, _ = net.apply(params, states, batch2)
    np.testing.assert_allclose(
        np.asarray(outs2[lstm_a.name].value)[1, 2], last, rtol=1e-5
    )
