"""Config/topology equivalence — the reference's test_NetworkCompare.cpp +
trainer_config_helpers golden-proto idiom: two ways of expressing the same
network must produce identical parameter shapes AND identical outputs under
identical parameter values."""

import numpy as np
import pytest

import jax

from paddle_tpu.nn.graph import Network, reset_name_scope


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    yield


def _run(net, batch, params=None, states=None):
    if params is None:
        params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    return params, states, outs


def test_v1_dsl_equals_v2_api():
    """The same MLP via config-script DSL and via the v2 layer API."""
    from paddle_tpu.config import parse_config
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector, integer_value

    def dsl_config():
        from paddle_tpu.config import helpers as H
        from paddle_tpu.config.config_parser import outputs

        img = H.data_layer(name="pixel", size=16)
        lbl = H.data_layer(name="label", size=4)
        h = H.fc_layer(input=img, size=8, act=H.TanhActivation(), name="h")
        out = H.fc_layer(input=h, size=4, act=H.SoftmaxActivation(), name="out")
        outputs(H.classification_cost(input=out, label=lbl, name="cost"))

    pc = parse_config(dsl_config, emit_proto=False)
    net_dsl = pc.topology.network

    reset_name_scope()
    img = vl.data(name="pixel", type=dense_vector(16))
    lbl = vl.data(name="label", type=integer_value(4))
    h = vl.fc(input=img, size=8, act="tanh", name="h")
    out = vl.fc(input=h, size=4, act="softmax", name="out")
    cost = vl.classification_cost(input=out, label=lbl, name="cost")
    net_v2 = Network([cost])

    rs = np.random.RandomState(0)
    batch = {
        "pixel": rs.randn(6, 16).astype(np.float32),
        "label": rs.randint(0, 4, 6),
    }
    p1, s1, o1 = _run(net_dsl, batch)
    # same param names and shapes
    p2, s2 = net_v2.init(jax.random.PRNGKey(0), batch)
    assert set(p1) == set(p2)
    assert {k: v.shape for k, v in p1.items()} == {k: v.shape for k, v in p2.items()}
    # identical outputs under identical weights
    _, _, o2 = _run(net_v2, batch, p1, s1)
    np.testing.assert_allclose(
        np.asarray(o1["cost"].value), np.asarray(o2["cost"].value), rtol=1e-6
    )


def test_mixed_projection_equals_primitive_fc():
    """mixed(full_matrix_projection) == fc without bias/activation — the
    concat_dotmul_a/b.conf equivalence class of the reference."""
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn import projections as P
    from paddle_tpu.nn.graph import ParamAttr

    data = L.Data("x", shape=(12,))
    shared = ParamAttr(name="w_shared")
    mixed = L.Mixed(
        [P.FullMatrix(data, param_attr=shared)], size=8, act=None, bias=False,
        name="mixed_out",
    )
    fc = L.Fc(data, 8, act=None, bias=False, param_attr=shared, name="fc_out")
    net = Network([mixed, fc])
    rs = np.random.RandomState(1)
    batch = {"x": rs.randn(5, 12).astype(np.float32)}
    params, states = net.init(jax.random.PRNGKey(0), batch)
    assert list(params) == ["w_shared"]  # one shared weight, no duplicates
    outs, _ = net.apply(params, states, batch)
    np.testing.assert_allclose(
        np.asarray(outs["mixed_out"].value),
        np.asarray(outs["fc_out"].value),
        rtol=1e-5, atol=1e-6,
    )


def test_simple_lstm_network_equals_composed():
    """networks.simple_lstm == mixed-projection + lstmemory composition under
    shared weights (the prebuilt-net equivalence the reference proves with
    golden protostrs)."""
    from paddle_tpu.v2 import layer as vl
    from paddle_tpu.data.feeder import dense_vector_sequence

    x = vl.data(name="x", type=dense_vector_sequence(8))
    lstm_a = vl.simple_lstm(x, 6, name="a")
    net = Network([lstm_a])
    rs = np.random.RandomState(2)
    batch = {
        "x": rs.randn(3, 5, 8).astype(np.float32),
        "x.lengths": np.asarray([5, 3, 2], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    assert outs[lstm_a.name].value.shape == (3, 5, 6)
    # masked positions beyond each length must not affect pooled last step
    last = np.asarray(outs[lstm_a.name].value)[1, 2]
    batch2 = dict(batch)
    b2 = batch["x"].copy()
    b2[1, 3:] = 99.0  # garbage in padding of sequence 1 (len 3)
    batch2["x"] = b2
    outs2, _ = net.apply(params, states, batch2)
    np.testing.assert_allclose(
        np.asarray(outs2[lstm_a.name].value)[1, 2], last, rtol=1e-5
    )
