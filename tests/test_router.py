"""Multi-replica serving router (ISSUE 15).

The load-bearing claims, each tested against REAL TCP replica servers:

  * dispatch — requests route to the least-loaded live replica off
    piggybacked heartbeat state, and tokens match the single-session oracle
    (the router tier is result-invisible);
  * fleet-wide shed — when every replica sheds, the router sheds with the
    tightest retry_after_ms, and a router with no replicas sheds instead of
    hanging;
  * in-flight failover — a replica killed mid-stream has its outstanding
    requests re-submitted to a survivor under the same idempotency key and
    the SAME pinned seed, so re-execution is token-identical for greedy AND
    sampled streams;
  * exactly-once — the satellite pin: a partitioned-then-healed replica
    answering a request the router already failed over is deduplicated (the
    late winner dropped and counted), proven with two real servers;
  * hedging — a token-less request past hedge_ttft_s is duplicated onto a
    second replica; the first token wins and the loser is cancelled
    server-side;
  * planned drain — no new assignments, in-flight finishes, lease drops;
  * client shed-retry — ServingClient.generate honors retry_after_ms with a
    capped sleep-and-retry loop instead of surfacing Rejected on the first
    shed (counted in client stats).

Timing-sensitive tests use short leases + the deterministic wedge (parking
the engine between steps on the session's generation lock) rather than
sleeps-and-hope; every socket test carries the SIGALRM timeout marker."""

import threading
import time

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

VOCAB = 96

PROMPT = [1, 5, 9, 11]


def _wait(cond, timeout_s=15.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    return ServingSession(model, params, **kw)


@pytest.fixture(scope="module")
def reference(model_and_params):
    """Oracle tokens from a direct single session: greedy and sampled."""
    s = make_session(model_and_params)
    greedy = s.submit(PROMPT, 8)
    sampled = s.submit(PROMPT, 8, seed=77, temperature=0.8, top_k=8)
    s.run_until_idle()
    return {"greedy": greedy.tokens, "sampled": sampled.tokens}


def warm_session(sess):
    """Compile a session's executables BEFORE its replica holds a lease.

    First-generate jit compile takes seconds; on a 1-core host two engines
    tracing concurrently time-slice the agent heartbeat threads, so a short
    lease can lapse mid-compile and the eviction reads as a spurious
    failover. Sampling params are data lanes (one decode executable covers
    greedy AND sampled), so one tiny greedy generate covers every path the
    tests drive.  The warm request's own service time spans the compiles,
    so the load-estimate EWMAs are explicitly forgotten afterwards: warming
    is sequential and later sessions hit the compile cache the first one
    filled, which would otherwise leave ASYMMETRIC queue-wait estimates and
    flip the least-loaded tie-break the dispatch tests pin.  A session
    configured to shed everything (max_queue=0) never compiles either —
    nothing to warm."""
    from paddle_tpu.serving.quota import QuotaExceeded

    try:
        sess.submit(PROMPT, 4)
    except QuotaExceeded:
        return sess
    sess.run_until_idle()
    sess.scheduler.reset_load_estimate()
    return sess


def make_fleet(model_and_params, n, lease_s=3.0, stall_fence_s=5.0,
               session_kw=None, **router_kw):
    """A RouterServer + n real TCP replica servers joined to it; sessions
    are pre-warmed (see warm_session) so no lease window spans a compile.
    Tests that pin EVICTION timing pass their own short lease explicitly."""
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingServer

    router_kw.setdefault("poll_interval_s", 0.02)
    router = RouterServer(lease_s=lease_s, **router_kw).start()
    servers = []
    for _ in range(n):
        sess = warm_session(make_session(model_and_params, **(session_kw or {})))
        srv = ServingServer(
            session=sess, router_endpoints=router.address,
            stall_fence_s=stall_fence_s,
        ).start()
        servers.append((srv, sess))
    assert _wait(lambda: len(router.fleet.live()) == n), "replicas must join"
    return router, servers


def stop_fleet(router, servers):
    for srv, _ in servers:
        srv.stop()
    router.stop()


# -- dispatch -----------------------------------------------------------------


@pytest.mark.timeout(120)
def test_router_end_to_end_result_invisible(model_and_params, reference):
    """Through the router (real TCP, ServingClient) tokens match the direct
    single-session oracle — the tier adds availability, not results."""
    from paddle_tpu.serving.server import ServingClient

    router, servers = make_fleet(model_and_params, 2)
    try:
        c = ServingClient(router.address)
        out = c.generate(PROMPT, 8, timeout_s=60.0)
        assert out["done"] and out["tokens"] == reference["greedy"]
        st = c.stats()
        assert st["live_replicas"] == 2 and st["completed"] >= 1
        assert st["failovers"] == 0
        c.close()
    finally:
        stop_fleet(router, servers)


def test_fleet_choose_least_loaded():
    """Assignment scoring is pure piggybacked state: occupancy normalized by
    slot width, then the replica's own queue-wait estimate; registration
    order breaks ties deterministically."""
    from paddle_tpu.serving.fleet import FleetView

    fleet = FleetView(lease_s=30.0)
    a = fleet.register(("127.0.0.1", 1))
    b = fleet.register(("127.0.0.1", 2))
    assert fleet.choose().replica_id == a.replica_id  # idle tie -> index
    a.load = {"queue_depth": 3, "active_slots": 4, "max_slots": 4,
              "estimated_queue_wait_s": 0.5}
    b.load = {"queue_depth": 0, "active_slots": 1, "max_slots": 4,
              "estimated_queue_wait_s": 0.0}
    assert fleet.choose().replica_id == b.replica_id
    # the router's own in-flight books count too
    b.outstanding.update(range(8))
    assert fleet.choose().replica_id == a.replica_id
    assert fleet.choose(exclude={a.replica_id}).replica_id == b.replica_id
    assert fleet.choose(exclude={a.replica_id, b.replica_id}) is None


@pytest.mark.timeout(120)
def test_fleet_wide_shed_tightest_hint_never_hangs(model_and_params):
    """Every replica saturated -> the router sheds with a retry_after_ms
    hint (the tightest any replica offered) instead of hanging; a router
    with NO replicas sheds immediately too."""
    from paddle_tpu.serving.quota import QuotaExceeded
    from paddle_tpu.serving.router import RouterServer

    # max_queue=0: every replica-side submit sheds at the queue bound
    router, servers = make_fleet(
        model_and_params, 2, session_kw={"max_queue": 0}
    )
    try:
        with pytest.raises(QuotaExceeded) as ei:
            router.router.submit(PROMPT, 8)
        assert ei.value.reason == "overload"
        assert ei.value.retry_after_ms is not None
        assert ei.value.retry_after_ms >= 1
        assert router.router.shed == 1
        # a shed leaves no fleet state behind
        assert router.router.stats()["outstanding"] == 0
    finally:
        stop_fleet(router, servers)

    empty = RouterServer(lease_s=1.0).start()
    try:
        with pytest.raises(QuotaExceeded) as ei:
            empty.router.submit(PROMPT, 8)
        assert ei.value.reason == "overload"
        assert ei.value.retry_after_ms is not None
    finally:
        empty.stop()


# -- failover -----------------------------------------------------------------


def _wedge(session):
    """Park the engine BETWEEN steps (it blocks acquiring the generation
    lock before its next step): the deterministic stand-in for a stall —
    requests stay in flight, nothing progresses, and releasing the lock
    heals the replica. The session's own stall supervisor is configured
    far above test timescales so only the ROUTER reacts."""
    session._gen_lock.acquire()
    return session._gen_lock


@pytest.mark.timeout(120)
@pytest.mark.parametrize("sampled", [False, True], ids=["greedy", "sampled"])
def test_failover_replica_killed_mid_stream_token_identical(
    model_and_params, reference, sampled
):
    """A replica killed with a request in flight: the router re-submits it
    to the survivor under the same key + pinned seed — token-identical to
    the oracle for greedy AND sampled streams."""
    router, servers = make_fleet(
        model_and_params, 2, lease_s=1.0,
        session_kw={"engine_stall_timeout_s": 120.0},
    )
    try:
        # wedge replica 0 (the idle tie-break target) so the request cannot
        # finish before the kill lands
        lock = _wedge(servers[0][1])
        kw = (
            dict(seed=77, temperature=0.8, top_k=8) if sampled else {}
        )
        h = router.router.submit(PROMPT, 8, **kw)
        assert _wait(lambda: bool(h.assignments)), "must be assigned"
        victim_id = next(iter(h.assignments))
        victim = router.fleet.get(victim_id)
        assert victim.index == 0
        servers[0][0].kill()
        toks = h.result(timeout=60.0)
        lock.release()
        assert toks == reference["sampled" if sampled else "greedy"]
        assert h.failovers == 1
        assert h.delivered_by != victim_id
        assert router.router.failovers >= 1
    finally:
        servers[0][0].kill()  # idempotent
        servers[1][0].stop()
        router.stop()


@pytest.mark.timeout(120)
def test_late_winner_from_partitioned_replica_deduplicated(model_and_params,
                                                          reference):
    """THE exactly-once pin (satellite): replica A wedges past its lease
    (its agent self-fences, the router evicts and fails the request over to
    B, which delivers), then A HEALS and answers the same request — the
    late winner must be dropped and counted, never double-delivered. Two
    real servers, real TCP, real lease expiry."""
    from paddle_tpu.serving.router import RouterServer
    from paddle_tpu.serving.server import ServingServer

    router = RouterServer(
        lease_s=0.8, poll_interval_s=0.02, late_grace_s=30.0
    ).start()
    # warm BOTH sessions before any replica holds a lease: B's compile must
    # not time-slice A's heartbeats inside the deliberately short lease
    sess_a = warm_session(
        make_session(model_and_params, engine_stall_timeout_s=120.0)
    )
    sess_b = warm_session(make_session(model_and_params))
    srv_a = ServingServer(
        session=sess_a, router_endpoints=router.address, stall_fence_s=0.2
    ).start()
    srv_b = None
    try:
        assert _wait(lambda: len(router.fleet.live()) == 1)
        # wedge A BEFORE the submit: the request queues there, parked
        lock = _wedge(sess_a)
        h = router.router.submit(PROMPT, 8)
        assert _wait(lambda: bool(h.assignments))
        a_id = next(iter(h.assignments))
        # the survivor joins; A's agent self-fences (no progress), its lease
        # lapses, and the router fails the request over to B
        srv_b = ServingServer(
            session=sess_b, router_endpoints=router.address,
            stall_fence_s=30.0,
        ).start()
        toks = h.result(timeout=60.0)
        assert toks == reference["greedy"]
        assert h.failovers == 1 and h.delivered_by != a_id
        assert router.fleet.get(a_id).state == "evicted"
        dropped0 = router.router.late_results_dropped
        assert dropped0 == 0
        # HEAL the partition: A's engine resumes and completes the very
        # request the router already delivered from B
        lock.release()
        assert _wait(
            lambda: router.router.late_results_dropped == 1, timeout_s=30.0
        ), "the late winner must be dropped and counted"
        assert h.late_drops == 1
        assert h.tokens == reference["greedy"], (
            "the delivered result must be untouched by the late answer"
        )
        # exactly-once is also visible on the obs plane
        from paddle_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.snapshot()
        assert any(
            k.startswith("paddle_tpu_router_late_results_dropped_total")
            and v >= 1
            for k, v in snap.items()
        )
        # the healed replica re-registers under a fresh lease and serves
        assert _wait(
            lambda: any(
                r.state == "live" and r.replica_id != a_id
                and r.endpoint == router.fleet.get(a_id).endpoint
                for r in router.fleet.replicas()
            ), timeout_s=15.0,
        ), "a healed replica must rejoin under a fresh lease"
    finally:
        srv_a.stop()
        if srv_b is not None:
            srv_b.stop()
        router.stop()


@pytest.mark.timeout(120)
def test_unplaceable_requests_fail_named_not_hang(model_and_params):
    """Killing the LAST replica with work in flight: the request fails with
    the named reason 'replica_lost' once the park window lapses — never a
    silent hang."""
    router, servers = make_fleet(
        model_and_params, 1, lease_s=0.6,
        session_kw={"engine_stall_timeout_s": 120.0},
        park_give_up_s=1.0,
    )
    try:
        _wedge(servers[0][1])
        h = router.router.submit(PROMPT, 8)
        assert _wait(lambda: bool(h.assignments))
        servers[0][0].kill()
        with pytest.raises(RuntimeError, match="replica_lost"):
            h.result(timeout=60.0)
        assert h.finish_reason == "replica_lost"
    finally:
        servers[0][0].kill()
        router.stop()


# -- hedging ------------------------------------------------------------------


@pytest.mark.timeout(120)
def test_hedge_first_token_wins_loser_cancelled(model_and_params, reference):
    """A token-less request past hedge_ttft_s is duplicated onto the second
    replica under the same key + seed; the first token wins, the loser is
    cancelled server-side on its replica, and exactly one result lands."""
    router, servers = make_fleet(
        model_and_params, 2, lease_s=30.0, stall_fence_s=60.0,
        session_kw={"engine_stall_timeout_s": 120.0},
    )
    try:
        # hold replica 0's engine: the request it gets will sit token-less
        # (lease stays alive — the fence window is far above test time, so
        # HEDGING, not eviction, is what must rescue the request)
        lock = _wedge(servers[0][1])
        h = router.router.submit(PROMPT, 8, hedge_ttft_s=0.2)
        assert _wait(lambda: bool(h.assignments))
        first = next(iter(h.assignments))
        assert router.fleet.get(first).index == 0
        toks = h.result(timeout=60.0)
        assert toks == reference["greedy"]
        assert h.hedged and router.router.hedges == 1
        assert h.delivered_by != first
        # the loser is cancelled server-side WHILE still wedged (the cancel
        # order rides the pump; the parked engine is not needed) — waiting
        # for it BEFORE healing the wedge keeps this deterministic: a warmed
        # engine released first could race the cancel and finish, turning
        # the loser into a late result instead of a cancellation
        assert _wait(
            lambda: servers[0][1].scheduler.cancelled >= 1, timeout_s=15.0
        ), "hedge loser must be cancelled on its replica"
        lock.release()
        assert router.router.late_results_dropped == 0
    finally:
        stop_fleet(router, servers)


# -- planned drain ------------------------------------------------------------


@pytest.mark.timeout(120)
def test_drain_stops_assignments_finishes_in_flight_deregisters(
    model_and_params, reference
):
    """`drain <replica>`: no new assignments land on it, in-flight streams
    finish, then the lease drops (state 'drained') and the fleet serves on
    without it — the autoscaling controller's shrink lever."""
    router, servers = make_fleet(model_and_params, 2, lease_s=5.0)
    try:
        a_id = next(
            r.replica_id for r in router.fleet.replicas() if r.index == 0
        )
        out = router.router.drain(a_id, deadline_s=30.0)
        assert out.get("ok")
        handles = [router.router.submit(PROMPT, 8) for _ in range(4)]
        for h in handles:
            assert h.result(timeout=60.0) == reference["greedy"]
            assert h.delivered_by != a_id, "draining replica must get nothing"
        # "drained" is transient: the idle pump closes right after, so the
        # terminal observable state is drained-or-closed
        assert _wait(
            lambda: router.fleet.get(a_id).state in ("drained", "closed"),
            timeout_s=15.0,
        )
        assert len(router.fleet.live()) == 1
        assert router.router.drains_completed == 1
        # new work still flows through the survivor
        assert router.router.submit(PROMPT, 8).result(timeout=60.0) \
            == reference["greedy"]
    finally:
        stop_fleet(router, servers)


# -- client shed-retry (satellite) --------------------------------------------


@pytest.mark.timeout(120)
def test_client_generate_honors_retry_after_ms(model_and_params):
    """ServingClient.generate(max_retries=) converts a shed-with-hint into a
    capped sleep-and-retry instead of surfacing Rejected on the first shed;
    retries are counted in client stats. max_retries=0 keeps the old
    fail-fast behavior."""
    from paddle_tpu.serving.server import (
        Rejected, ServingClient, ServingServer,
    )

    s = make_session(model_and_params, max_queue=1)
    # hold the engine (serve_forever idempotence guard) so the queue stays
    # full until the timer releases it — the first submit must shed
    s._thread = threading.Thread(target=lambda: None)
    srv = ServingServer(session=s).start()
    try:
        s.submit(PROMPT, 4)  # fills the queue (engine held)
        # seed the service-time EWMA so the shed hint is a real wait, not
        # the 10ms cold floor (the retry loop must actually sleep on it)
        s.scheduler._ewma_service_s = 0.15
        c = ServingClient(srv.address)
        with pytest.raises(Rejected) as ei:
            c.generate(PROMPT, 4, max_retries=0)
        assert ei.value.retry_after_ms is not None
        assert c.shed_retries == 0

        def release():
            time.sleep(0.3)
            s._thread = None
            s.serve_forever()

        threading.Thread(target=release, daemon=True).start()
        out = c.generate(PROMPT, 4, max_retries=10, timeout_s=60.0)
        assert out["done"]
        assert c.shed_retries >= 1, "the retry loop must have slept-and-retried"
        c.close()
    finally:
        srv.stop()


# -- poll_many (the pump's batch RPC) ----------------------------------------


def test_poll_many_batches_and_scopes_tenancy(model_and_params):
    """One poll_many round trip answers for N requests, each item checked
    against ITS tenant — the router proxies many tenants over one pump
    connection."""
    from paddle_tpu.serving.server import ServingServer

    s = make_session(model_and_params)
    srv = ServingServer(session=s)
    try:
        r1 = srv.dispatch(
            "submit", {"prompt": PROMPT, "max_new_tokens": 4}, "t1"
        )["request_id"]
        r2 = srv.dispatch(
            "submit", {"prompt": PROMPT, "max_new_tokens": 4}, "t2"
        )["request_id"]
        s.run_until_idle()
        out = srv.dispatch("poll_many", {"items": [
            {"request_id": r1, "tenant_id": "t1"},
            {"request_id": r2, "tenant_id": "t1"},   # wrong tenant
            {"request_id": 999, "tenant_id": "t1"},  # unknown
        ]}, None)["results"]
        assert out[0]["done"] and out[0]["tokens"]
        assert out[0]["request_id"] == r1
        assert out[1]["err"] == "tenant"
        assert out[2]["err"] == "unknown"
    finally:
        srv.stop()


# -- prefix affinity (ISSUE 20 / ROADMAP 2a) ----------------------------------


def _mk_fleet_view(n):
    from paddle_tpu.serving.fleet import FleetView

    fv = FleetView(lease_s=30.0)
    reps = [fv.register(("127.0.0.1", 9000 + i)) for i in range(n)]
    for r in reps:
        r.load = {"max_slots": 4}
    return fv, reps


def test_fleet_choose_affinity_hint_semantics():
    """The affine replica wins within AFFINITY_SLACK occupancy; past the
    slack, dead, or excluded, the preference degrades to least-loaded —
    a hint, never a constraint."""
    from paddle_tpu.serving.fleet import ReplicaState

    fv, (r0, r1) = _mk_fleet_view(2)
    # idle fleet: the index tie-break says r0, the preference says r1
    assert fv.choose().replica_id == r0.replica_id
    assert fv.choose(prefer=r1.replica_id).replica_id == r1.replica_id
    # one in-flight request (0.25 occupancy at 4 slots) is exactly within
    # the slack: same-prefix traffic stays on the warm replica
    r1.outstanding.add(1)
    assert fv.choose(prefer=r1.replica_id).replica_id == r1.replica_id
    # past the slack the preference loses to load balance
    r1.load = {"max_slots": 4, "queue_depth": 2}
    assert fv.choose(prefer=r1.replica_id).replica_id == r0.replica_id
    # a dead affine replica fails over to the survivor
    r1.load = {"max_slots": 4}
    r1.outstanding.clear()
    r1.state = ReplicaState.EVICTED
    assert fv.choose(prefer=r1.replica_id).replica_id == r0.replica_id
    # an excluded affine replica (already tried this request) is skipped
    r1.state = ReplicaState.LIVE
    assert fv.choose(
        exclude={r1.replica_id}, prefer=r1.replica_id
    ).replica_id == r0.replica_id


def test_affinity_key_hashes_prompt_head():
    from paddle_tpu.serving.router import AFFINITY_HEAD, affinity_key

    a = affinity_key([1, 2, 3, 4])
    assert a == affinity_key([1, 2, 3, 4])          # deterministic
    assert a != affinity_key([9, 2, 3, 4])          # head-sensitive
    long = list(range(AFFINITY_HEAD)) + [50]
    assert affinity_key(long) == affinity_key(long[:-1] + [77])  # tail-blind
    assert affinity_key([]) is None                  # empty prompt: no key


def test_affinity_warm_hit_rate_beats_pure_least_loaded():
    """Synthetic dispatch trace at EQUAL load: two prompt heads interleave
    with a bounded in-flight window. The affinity map keeps each head on
    the replica that served it last (warm prefix cache); pure least-loaded
    ping-pongs on occupancy ties. Warm-hit rate = fraction of repeat-head
    dispatches landing where that head last ran."""

    def run(affine):
        fv, reps = _mk_fleet_view(2)
        amap, last, inflight = {}, {}, []
        hits = total = 0
        used = set()
        for i in range(40):
            head = "A" if i % 2 == 0 else "B"
            rep = fv.choose(prefer=amap.get(head) if affine else None)
            if head in last:
                total += 1
                hits += rep.replica_id == last[head]
            last[head] = amap[head] = rep.replica_id
            used.add(rep.replica_id)
            rep.outstanding.add(i)
            inflight.append((rep, i))
            if len(inflight) > 2:  # steady state: 2 requests in flight
                old, rid = inflight.pop(0)
                old.outstanding.discard(rid)
        return hits / total, used

    warm_rate, warm_used = run(affine=True)
    cold_rate, _ = run(affine=False)
    assert warm_rate > cold_rate, (warm_rate, cold_rate)
    assert warm_rate >= 0.9                 # affinity keeps heads pinned
    assert len(warm_used) == 2              # ... without starving a replica


@pytest.mark.timeout(120)
def test_affinity_failover_when_affine_replica_dies(
    model_and_params, reference
):
    """The router remembers which replica served PROMPT's head; kill that
    replica and the same head must complete on the survivor (preference is
    a hint — eviction beats affinity), token-identical to the oracle."""
    from paddle_tpu.serving.server import ServingClient

    router, servers = make_fleet(model_and_params, 2, lease_s=1.5)
    try:
        client = ServingClient(router.address)
        r1 = client.generate(PROMPT, 8)
        assert r1["tokens"] == reference["greedy"]
        aff = dict(router.router._affinity)
        assert len(aff) == 1, "dispatch recorded the prompt-head affinity"
        affine_id = next(iter(aff.values()))
        rep = router.fleet.get(affine_id)
        assert rep is not None
        victim = next(
            (srv, sess) for srv, sess in servers
            if srv.address[1] == rep.endpoint[1]
        )
        victim[0].kill()
        assert _wait(lambda: len(router.fleet.live()) == 1), "eviction"
        r2 = client.generate(PROMPT, 8)
        assert r2["tokens"] == reference["greedy"]
        # the map re-pointed at the survivor for the next warm hit
        survivor = router.fleet.live()[0].replica_id
        assert router.router._affinity.get(next(iter(aff))) == survivor
        assert survivor != affine_id
    finally:
        stop_fleet(router, servers)
