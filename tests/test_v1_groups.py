"""Numeric oracles for the layer-composed recurrent groups and the windowed
sequence layers added for v1 config parity (networks.py lstmemory_group /
gru_group family; SequencePoolLayer stride mode; SequenceSliceLayer
starts/ends) — the runtime semantics behind the golden-protostr corpus."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.config.v1_layers as v1
from paddle_tpu.config.config_parser import fresh_context
from paddle_tpu.nn import seq_layers as S
from paddle_tpu.nn.graph import Argument, Network, reset_name_scope


@pytest.fixture(autouse=True)
def _fresh():
    reset_name_scope()
    with fresh_context():
        yield


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_gru_group_matches_numpy_oracle():
    """gru_group (mixed 3H projection outside + GruStep inside a
    recurrent_group) must compute the standard GRU recurrence."""
    b, t, h = 2, 5, 4
    rs = np.random.RandomState(0)
    proj_np = rs.randn(b, t, 3 * h).astype(np.float32)

    din = v1.data_layer("proj", size=3 * h)
    out = v1.gru_group(input=din, size=h, name="g")
    net = Network([out])
    batch = {
        "proj": proj_np,
        "proj.lengths": np.array([5, 3], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    got = np.asarray(outs[out.name].value)  # [B, T, H]

    w_hzr = np.asarray(params["g.w_hzr"])
    w_hc = np.asarray(params["g.w_hc"])
    bias = np.asarray(params["g.b"])
    for i in range(b):
        hprev = np.zeros(h, np.float32)
        for s in range(int(batch["proj.lengths"][i])):
            m = proj_np[i, s] + bias
            zr = m[: 2 * h] + hprev @ w_hzr
            z, r = _sigmoid(zr[:h]), _sigmoid(zr[h:])
            c = np.tanh(m[2 * h :] + (r * hprev) @ w_hc)
            hprev = (1 - z) * hprev + z * c
            np.testing.assert_allclose(got[i, s], hprev, rtol=2e-5, atol=2e-5)


def test_lstmemory_group_matches_numpy_oracle():
    """lstmemory_group: in-step mixed(identity + recurrent full-matrix) +
    LstmStep with the state published through StepArgOutput."""
    b, t, h = 2, 4, 3
    rs = np.random.RandomState(1)
    proj_np = rs.randn(b, t, 4 * h).astype(np.float32)

    din = v1.data_layer("proj", size=4 * h)
    out = v1.lstmemory_group(input=din, size=h, name="lg")
    net = Network([out])
    batch = {
        "proj": proj_np,
        "proj.lengths": np.array([4, 2], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(1), batch)
    outs, _ = net.apply(params, states, batch)
    got = np.asarray(outs[out.name].value)

    w_rec = np.asarray(params["lg_input_recurrent.proj1.w"])  # [H, 4H]
    peep = np.asarray(params["lg.b"])  # [3H] checkI/checkF/checkO
    for i in range(b):
        hprev = np.zeros(h, np.float32)
        cprev = np.zeros(h, np.float32)
        for s in range(int(batch["proj.lengths"][i])):
            m = proj_np[i, s] + hprev @ w_rec
            gi = _sigmoid(m[:h] + peep[:h] * cprev)
            gf = _sigmoid(m[h : 2 * h] + peep[h : 2 * h] * cprev)
            gc = np.tanh(m[2 * h : 3 * h])
            cprev = gf * cprev + gi * gc
            go = _sigmoid(m[3 * h :] + peep[2 * h :] * cprev)
            hprev = go * np.tanh(cprev)
            np.testing.assert_allclose(got[i, s], hprev, rtol=2e-5, atol=2e-5)

    # gradients flow through both the step weights and the recurrent mixed
    def loss(p):
        o, _ = net.apply(p, states, batch)
        return jnp.sum(o[out.name].value ** 2)

    grads = jax.grad(loss)(params)
    for k in ("lg_input_recurrent.proj1.w", "lg.b"):
        assert float(jnp.abs(grads[k]).sum()) > 0.0, k


def test_windowed_seq_pool_and_instances():
    """SequencePoolLayer / SequenceLastInstanceLayer stride mode: fixed
    windows of `stride` steps, ragged tails handled by lengths."""
    x = np.arange(14, dtype=np.float32).reshape(1, 7, 2)
    lengths = np.array([5], np.int32)
    arg = Argument(jnp.asarray(x), jnp.asarray(lengths))

    pool = S.SeqPool(v1.data_layer("d", 2), "max", agg_level=None, stride=3)
    res = pool.forward(None, [arg])
    # windows: [0..2], [3..4(valid)]: max over valid rows
    np.testing.assert_allclose(
        np.asarray(res.value)[0, 0], x[0, 2]
    )
    np.testing.assert_allclose(np.asarray(res.value)[0, 1], x[0, 4])
    np.testing.assert_array_equal(np.asarray(res.lengths), [2])

    last = S.LastSeq(v1.data_layer("d2", 2), stride=3)
    res = last.forward(None, [arg])
    np.testing.assert_allclose(np.asarray(res.value)[0, 0], x[0, 2])
    np.testing.assert_allclose(np.asarray(res.value)[0, 1], x[0, 4])

    first = S.FirstSeq(v1.data_layer("d3", 2), stride=3)
    res = first.forward(None, [arg])
    np.testing.assert_allclose(np.asarray(res.value)[0, 0], x[0, 0])
    np.testing.assert_allclose(np.asarray(res.value)[0, 1], x[0, 3])


def test_seq_slice_with_start_end_layers():
    """SequenceSliceLayer starts/ends companion inputs → K sub-slices per
    sequence (a nested output)."""
    x = np.arange(10, dtype=np.float32).reshape(1, 5, 2)
    starts = np.array([[0, 2]], np.int32)
    ends = np.array([[1, 3]], np.int32)
    node = S.SeqSlice(
        v1.data_layer("x", 2), starts=v1.data_layer("s", 2),
        ends=v1.data_layer("e", 2),
    )
    res = node.forward(None, [
        Argument(jnp.asarray(x), jnp.asarray([5], jnp.int32)),
        Argument(jnp.asarray(starts)),
        Argument(jnp.asarray(ends)),
    ])
    v = np.asarray(res.value)  # [1, K=2, T=5, 2]
    np.testing.assert_allclose(v[0, 0, :2], x[0, 0:2])  # slice [0,1]
    np.testing.assert_allclose(v[0, 1, :2], x[0, 2:4])  # slice [2,3]
    np.testing.assert_array_equal(np.asarray(res.sub_lengths)[0], [2, 2])


def test_mixed_operator_slot_layout():
    """Mixed input slots: declaration-order first sources, operator extras
    appended last (the reference's operator_confs.input_indices contract)."""
    import paddle_tpu.v2.layer as v2

    a = v1.data_layer("a", size=4)
    b = v1.data_layer("b", size=4)
    m = v2.mixed(size=4, input=None, name="mx")
    m += v2.dotmul_operator(a, b)
    m += v2.scaling_projection(a)
    assert [l.name for l in m.inputs] == ["a", "a", "b"]
    assert m._arg_slots == [[0, 2], [1]]

    batch = {"a": np.ones((2, 4), np.float32), "b": np.full((2, 4), 2.0, np.float32)}
    net = Network([m])
    params, states = net.init(jax.random.PRNGKey(0), batch)
    outs, _ = net.apply(params, states, batch)
    # dotmul(a,b) + scaling(a) with scale init 1 → 1*2 + 1 = 3
    np.testing.assert_allclose(np.asarray(outs["mx"].value), 3.0)


def test_reference_sequence_nest_rnn_conf_equivalence():
    """The reference's own gserver/tests/sequence_nest_rnn.conf vs
    sequence_rnn.conf pair (test_RecurrentGradientMachine.cpp idiom): both
    UNMODIFIED configs parse here, and with shared weights the hierarchical
    group equals the flat RNN over the concatenated tokens."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")

    from paddle_tpu.config.config_parser import parse_config

    nest = parse_config(os.path.join(conf_dir, "sequence_nest_rnn.conf"))
    reset_name_scope()
    flat = parse_config(os.path.join(conf_dir, "sequence_rnn.conf"))

    rs = np.random.RandomState(0)
    # nested: batch of 2, [S=2, T=3] subsequences; flat: same tokens joined
    ids = rs.randint(0, 10, (2, 2, 3)).astype(np.int32)
    nest_batch = {
        "word": ids,
        "word.lengths": np.array([2, 2], np.int32),
        "word.sub_lengths": np.full((2, 2), 3, np.int32),
        "label": np.array([1, 2], np.int32),
    }
    flat_batch = {
        "word": ids.reshape(2, 6),
        "word.lengths": np.array([6, 6], np.int32),
        "label": np.array([1, 2], np.int32),
    }

    net_n = Network(nest.outputs)
    net_f = Network(flat.outputs)
    pf, sf = net_f.init(jax.random.PRNGKey(7), flat_batch)
    pn, sn = net_n.init(jax.random.PRNGKey(9), nest_batch)
    # share weights: the nested conf names its cell 'inner_rnn_state', the
    # flat one 'rnn_state'; embedding/prob-fc auto-names coincide
    mapped = {}
    for k, v in pn.items():
        src = k.replace("inner_rnn_state", "rnn_state")
        mapped[k] = pf[src] if src in pf else v
    out_n, _ = net_n.apply(mapped, sn, nest_batch)
    out_f, _ = net_f.apply(pf, sf, flat_batch)
    cost_n = float(out_n[nest.outputs[0].name].value)
    cost_f = float(out_f[flat.outputs[0].name].value)
    assert cost_n == pytest.approx(cost_f, rel=2e-5)


@pytest.mark.parametrize("pair", [
    "concat_dotmul", "concat_fullmatrix", "concat_slice", "concat_table",
    "img_conv", "img_pool",
])
def test_reference_gserver_ab_pairs_equivalent(pair):
    """The reference's test_NetworkCompare corpus (gserver/tests/{pair}_a.conf
    vs _b.conf): the same network built via layers vs projections must produce
    identical outputs under shared weights — on the reference's own
    unmodified config files."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    a_path = os.path.join(conf_dir, f"{pair}_a.conf")
    b_path = os.path.join(conf_dir, f"{pair}_b.conf")
    if not (os.path.exists(a_path) and os.path.exists(b_path)):
        pytest.skip("reference tree not available")

    from paddle_tpu.config.config_parser import parse_config

    pa = parse_config(a_path)
    reset_name_scope()
    pb = parse_config(b_path)

    net_a = Network(pa.outputs)
    net_b = Network(pb.outputs)
    batch = pa.topology.sample_batch(4)
    rs = np.random.RandomState(0)
    for k, v in batch.items():
        if not k.endswith(".lengths") and np.issubdtype(v.dtype, np.floating):
            batch[k] = rs.randn(*v.shape).astype(v.dtype) * 0.1
        elif not k.endswith(".lengths"):
            batch[k] = rs.randint(0, 100, v.shape).astype(v.dtype)
    params_a, states_a = net_a.init(jax.random.PRNGKey(0), batch)
    params_b, states_b = net_b.init(jax.random.PRNGKey(1), batch)
    shared = {}
    for (kb, vb), (ka, va) in zip(params_b.items(), params_a.items()):
        if np.shape(va) == np.shape(vb):
            shared[kb] = va
        elif (
            np.ndim(va) == 1 and np.ndim(vb) == 1
            and np.size(vb) % np.size(va) == 0
        ):
            # per-channel conv bias vs the mixed layer's full-size bias:
            # NHWC flatten repeats channels fastest, so tiling matches
            shared[kb] = jnp.tile(va, np.size(vb) // np.size(va))
        else:
            raise AssertionError(
                f"parameter shapes diverge: {ka}{np.shape(va)} vs {kb}{np.shape(vb)}"
            )

    out_a, _ = net_a.apply(params_a, states_a, batch)
    out_b, _ = net_b.apply(shared, states_b, batch)
    for la, lb in zip(pa.outputs, pb.outputs):
        va = np.asarray(out_a[la.name].value)
        vb = np.asarray(out_b[lb.name].value)
        # layer-built outputs may keep image layout where the projection
        # path flattens; compare the flat values
        np.testing.assert_allclose(
            va.reshape(va.shape[0], -1), vb.reshape(vb.shape[0], -1),
            rtol=2e-5, atol=2e-5,
        )


def test_reference_sequence_layer_group_confs_parse_and_trace():
    """gserver/tests/sequence_layer_group.conf and its nested twin: the
    lstmemory_group-inside-recurrent_group stack (plus TO_SEQUENCE pooling,
    FROM_SEQUENCE expand onto a nested target, per-sequence labels) parses
    and traces on the reference's own unmodified files."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    cwd = os.getcwd()
    os.chdir("/root/reference/paddle")  # the confs open dict files by relpath
    try:
        for conf in ("sequence_layer_group.conf", "sequence_nest_layer_group.conf"):
            reset_name_scope()
            pc = parse_config(os.path.join(conf_dir, conf))
            assert len(pc.topology.network.layer_order) >= 8
    finally:
        os.chdir(cwd)


def test_reference_multi_input_group_conf_equivalence():
    """sequence_nest_rnn_multi_input.conf vs sequence_rnn_multi_input.conf:
    a group iterating BOTH an embedding sequence and the raw id sequence
    (in-step embedding), hierarchical vs flat, on the reference's own files."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    nest = parse_config(os.path.join(conf_dir, "sequence_nest_rnn_multi_input.conf"))
    reset_name_scope()
    flat = parse_config(os.path.join(conf_dir, "sequence_rnn_multi_input.conf"))

    rs = np.random.RandomState(0)
    ids = rs.randint(0, 10, (2, 2, 3)).astype(np.int32)
    nest_batch = {
        "word": ids,
        "word.lengths": np.array([2, 2], np.int32),
        "word.sub_lengths": np.full((2, 2), 3, np.int32),
        "label": np.array([1, 2], np.int32),
    }
    flat_batch = {
        "word": ids.reshape(2, 6),
        "word.lengths": np.array([6, 6], np.int32),
        "label": np.array([1, 2], np.int32),
    }
    net_n = Network(nest.outputs)
    net_f = Network(flat.outputs)
    pf, sf = net_f.init(jax.random.PRNGKey(3), flat_batch)
    pn, sn = net_n.init(jax.random.PRNGKey(4), nest_batch)
    mapped = {}
    for k, v in pn.items():
        src = k.replace("inner_rnn_state", "rnn_state")
        mapped[k] = pf[src] if src in pf else v
    out_n, _ = net_n.apply(mapped, sn, nest_batch)
    out_f, _ = net_f.apply(pf, sf, flat_batch)
    cost_n = float(out_n[nest.outputs[0].name].value)
    cost_f = float(out_f[flat.outputs[0].name].value)
    assert cost_n == pytest.approx(cost_f, rel=2e-5)


def test_reference_unequalength_multi_output_group_confs_parse():
    """sequence_(nest_)rnn_multi_unequalength_inputs.py: two iterated inputs
    with different lengths and a MULTI-OUTPUT step (`a, b =
    recurrent_group(...)`) — parse + trace on the reference's files."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    for conf in (
        "sequence_rnn_multi_unequalength_inputs.py",
        "sequence_nest_rnn_multi_unequalength_inputs.py",
    ):
        reset_name_scope()
        pc = parse_config(os.path.join(conf_dir, conf))
        assert len(pc.topology.network.layer_order) >= 10


def test_reference_provider_inferred_nesting_confs_parse():
    """sequence_rnn_mixed_inputs.py / sequence_rnn_matched_inputs.py: nesting
    comes from the PROVIDER's slot types (integer_value_sub_sequence), not a
    SubsequenceInput wrapper — parse_config binds the provider's input_types
    before tracing, and the group machinery mixes nested / flat-seq / non-seq
    iterated inputs at runtime."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    cwd = os.getcwd()
    os.chdir("/root/reference/paddle")
    try:
        for conf in ("sequence_rnn_mixed_inputs.py", "sequence_rnn_matched_inputs.py"):
            reset_name_scope()
            pc = parse_config(os.path.join(conf_dir, conf))
            assert len(pc.topology.network.layer_order) >= 8
    finally:
        os.chdir(cwd)


def test_reference_unequalength_pair_numeric_equivalence():
    """sequence_nest_rnn_multi_unequalength_inputs.py vs its flat twin: two
    iterated inputs of DIFFERENT lengths, two inner groups chained through
    outer memories, in-step expand, multi-output steps — with shared weights
    the costs must match exactly (per-input sequence matching: each memory
    and output follows its own inputs' lengths)."""
    import os

    conf_dir = "/root/reference/paddle/gserver/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    pn = parse_config(
        os.path.join(conf_dir, "sequence_nest_rnn_multi_unequalength_inputs.py")
    )
    reset_name_scope()
    pf = parse_config(
        os.path.join(conf_dir, "sequence_rnn_multi_unequalength_inputs.py")
    )

    rs = np.random.RandomState(0)
    ids1 = rs.randint(0, 10, (2, 2, 3)).astype(np.int32)
    ids2 = rs.randint(0, 10, (2, 2, 4)).astype(np.int32)
    nb = {
        "word1": ids1, "word1.lengths": np.array([2, 2], np.int32),
        "word1.sub_lengths": np.full((2, 2), 3, np.int32),
        "word2": ids2, "word2.lengths": np.array([2, 2], np.int32),
        "word2.sub_lengths": np.full((2, 2), 4, np.int32),
        "label": np.array([1, 0], np.int32),
    }
    fb = {
        "word1": ids1.reshape(2, 6), "word1.lengths": np.array([6, 6], np.int32),
        "word2": ids2.reshape(2, 8), "word2.lengths": np.array([8, 8], np.int32),
        "label": np.array([1, 0], np.int32),
    }
    net_n, net_f = Network(pn.outputs), Network(pf.outputs)
    par_n, st_n = net_n.init(jax.random.PRNGKey(0), nb)
    par_f, st_f = net_f.init(jax.random.PRNGKey(1), fb)
    assert [tuple(np.shape(v)) for v in par_n.values()] == [
        tuple(np.shape(v)) for v in par_f.values()
    ]
    shared = dict(zip(par_n.keys(), par_f.values()))
    on, _ = net_n.apply(shared, st_n, nb)
    of, _ = net_f.apply(par_f, st_f, fb)
    cn = float(on[pn.outputs[0].name].value)
    cf = float(of[pf.outputs[0].name].value)
    assert cn == pytest.approx(cf, rel=1e-6)


def test_reference_trainer_sample_configs_parse():
    """paddle/trainer/tests sample configs using the legacy raw-config
    primitives (Settings/TrainData/ProtoData/Inputs/Outputs/default_*,
    py2-era builtins) plus the beam-generation conf with GeneratedInput and
    Outputs('__beam_search_predict__')."""
    import os

    conf_dir = "/root/reference/paddle/trainer/tests"
    if not os.path.isdir(conf_dir):
        pytest.skip("reference tree not available")
    from paddle_tpu.config.config_parser import parse_config

    for conf in (
        "sample_trainer_config.conf",
        "sample_trainer_config_hsigmoid.conf",
        "sample_trainer_config_opt_a.conf",
        "sample_trainer_config_opt_b.conf",
        "sample_trainer_config_parallel.conf",
        "sample_trainer_rnn_gen.conf",
        "test_config.conf",
    ):
        reset_name_scope()
        pc = parse_config(os.path.join(conf_dir, conf))
        assert pc.outputs
