"""Distributed building blocks on the 8-device virtual CPU mesh: sharded
embedding lookup (+ row-sparse grads), updater protocol, deterministic
sharded readers. SURVEY §2.5 sparse/EP row and §5 data sharding."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.embedding import shard_table, sharded_lookup
from paddle_tpu.parallel.updaters import IciAllReduceUpdater, SgdLocalUpdater
from paddle_tpu.data.sharded_reader import shard_file_list, shard_reader


@pytest.fixture(scope="module")
def exp_mesh():
    return make_mesh({"expert": 4})


def test_sharded_lookup_matches_dense(exp_mesh):
    rs = np.random.RandomState(0)
    table = jnp.asarray(rs.randn(32, 8), jnp.float32)  # 32 rows / 4 shards
    ids = jnp.asarray(rs.randint(0, 32, (5, 7)), jnp.int32)
    sharded = shard_table(table, exp_mesh)
    got = sharded_lookup(sharded, ids, exp_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[ids]), atol=1e-6)


def test_sharded_lookup_grad_is_row_sparse_scatter(exp_mesh):
    """d/dtable of the sharded lookup must equal the dense embedding grad —
    the row-sparse scatter-add the pserver protocol implements by hand."""
    rs = np.random.RandomState(1)
    table = jnp.asarray(rs.randn(16, 4), jnp.float32)
    ids = jnp.asarray([0, 3, 3, 15, 7], jnp.int32)
    cot = jnp.asarray(rs.randn(5, 4), jnp.float32)

    def loss_sharded(tab):
        out = sharded_lookup(shard_table(tab, exp_mesh), ids, exp_mesh)
        return jnp.sum(out * cot)

    def loss_dense(tab):
        return jnp.sum(tab[ids] * cot)

    g_sharded = jax.grad(loss_sharded)(table)
    g_dense = jax.grad(loss_dense)(table)
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense), atol=1e-5)
    # duplicate id 3 accumulated both cotangents
    np.testing.assert_allclose(
        np.asarray(g_dense[3]), np.asarray(cot[1] + cot[2]), atol=1e-6
    )


def test_sharded_table_vocab_divisibility(exp_mesh):
    with pytest.raises(ValueError, match="divisible"):
        shard_table(jnp.zeros((30, 4)), exp_mesh)


def test_updater_protocol():
    from paddle_tpu.optim import SGD

    opt = SGD(learning_rate=0.5)
    upd = SgdLocalUpdater(opt)
    params = {"w": jnp.ones((4,))}
    state = opt.init_state(params)
    grads = {"w": jnp.full((4,), 2.0)}
    new_params, _ = upd.apply(grads, state, params, 0.5)
    np.testing.assert_allclose(np.asarray(new_params["w"]), np.zeros(4), atol=1e-6)

    # IciAllReduceUpdater: same math, plus pass-boundary hooks run clean
    ici = IciAllReduceUpdater(opt, parallel=None)
    ici.start_pass()
    new_params2, _ = ici.apply(grads, state, params, 0.5)
    ici.finish_pass()
    np.testing.assert_allclose(
        np.asarray(new_params2["w"]), np.asarray(new_params["w"])
    )


def test_shard_reader_partitions_and_covers():
    data = list(range(23))
    shards = [list(shard_reader(lambda: iter(data), 4, i)()) for i in range(4)]
    # disjoint and complete
    flat = sorted(x for s in shards for x in s)
    assert flat == data
    # deterministic
    again = list(shard_reader(lambda: iter(data), 4, 2)())
    assert again == shards[2]
    with pytest.raises(ValueError):
        shard_reader(lambda: iter(data), 4, 7)


def test_shard_file_list():
    files = [f"f{i}" for i in range(10)]
    parts = [shard_file_list(files, 3, i) for i in range(3)]
    assert sorted(sum(parts, [])) == files
    assert parts[0] == ["f0", "f3", "f6", "f9"]


# -- real 2-process cluster (VERDICT r3 missing #3) ---------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_cluster_trains_identically(tmp_path):
    """The reference's in-process-localhost cluster test
    (trainer/tests/test_CompareSparse.cpp:65-73: real pservers + trainers on
    localhost, compare parameters) — here with real OS processes: 2 workers
    join via jax.distributed (gloo CPU collectives), pull recordio tasks from
    one MasterServer across the process boundary, train data-parallel over the
    4-device global mesh with partitioner-inserted allreduce, and must end
    with (a) byte-identical params on both hosts and (b) params matching a
    single-process run over the same global batches."""
    import json
    import subprocess
    import sys

    from paddle_tpu.runtime import native, recordio

    if native.lib() is None:
        pytest.skip("native runtime unavailable")

    outdir = str(tmp_path)
    recordio.convert(
        outdir, lambda: ({"sid": i} for i in range(24)), records_per_file=3
    )

    coord_port, master_port = _free_port(), _free_port()
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)), "distributed_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(p), "2", f"127.0.0.1:{coord_port}",
             str(master_port), outdir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for p in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    # exactly-once task dispatch across the process boundary
    consumed = [
        json.load(open(os.path.join(outdir, f"consumed_{i}.json"))) for i in range(2)
    ]
    assert sorted(consumed[0] + consumed[1]) == list(range(24))
    assert consumed[0] and consumed[1]  # both hosts actually pulled tasks

    # identical replicated params on both hosts
    p0 = dict(np.load(os.path.join(outdir, "params_0.npz")))
    p1 = dict(np.load(os.path.join(outdir, "params_1.npz")))
    assert set(p0) == set(p1)
    for k in p0:
        np.testing.assert_array_equal(p0[k], p1[k])

    # ...and equal to a single-process run over the same global batches
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    reset_name_scope()
    dim, classes, batch_local = 16, 4, 8
    x = L.Data("x", shape=(dim,))
    lbl = L.Data("label", shape=())
    h = L.Fc(x, 32, act="relu", name="h")
    logits = L.Fc(h, classes, act=None, name="out")
    cost = C.ClassificationCost(logits, lbl, name="cost")

    rs = np.random.RandomState(0)
    xs = rs.randn(96, dim).astype(np.float32)
    ys = (rs.rand(96) * classes).astype(np.int32)
    tr = SGDTrainer(cost, SGD(learning_rate=0.1), seed=11)
    for j in range(96 // (2 * batch_local)):
        idx0 = [16 * j + 2 * t for t in range(batch_local)]      # host 0 shard
        idx1 = [16 * j + 2 * t + 1 for t in range(batch_local)]  # host 1 shard
        batch = {
            "x": np.concatenate([xs[idx0], xs[idx1]]),
            "label": np.concatenate([ys[idx0], ys[idx1]]),
        }
        if tr.state is None:
            tr.init_state(batch)
            tr._step_fn = tr._make_step()
        tr.state, c, _ = tr._step_fn(tr.state, batch)
    for k, v in tr.state["params"].items():
        np.testing.assert_allclose(p0[k], np.asarray(v), rtol=2e-4, atol=2e-5)
