"""Distributed building blocks on the 8-device virtual CPU mesh: sharded
embedding lookup (+ row-sparse grads), updater protocol, deterministic
sharded readers. SURVEY §2.5 sparse/EP row and §5 data sharding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.embedding import shard_table, sharded_lookup
from paddle_tpu.parallel.updaters import IciAllReduceUpdater, SgdLocalUpdater
from paddle_tpu.data.sharded_reader import shard_file_list, shard_reader


@pytest.fixture(scope="module")
def exp_mesh():
    return make_mesh({"expert": 4})


def test_sharded_lookup_matches_dense(exp_mesh):
    rs = np.random.RandomState(0)
    table = jnp.asarray(rs.randn(32, 8), jnp.float32)  # 32 rows / 4 shards
    ids = jnp.asarray(rs.randint(0, 32, (5, 7)), jnp.int32)
    sharded = shard_table(table, exp_mesh)
    got = sharded_lookup(sharded, ids, exp_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[ids]), atol=1e-6)


def test_sharded_lookup_grad_is_row_sparse_scatter(exp_mesh):
    """d/dtable of the sharded lookup must equal the dense embedding grad —
    the row-sparse scatter-add the pserver protocol implements by hand."""
    rs = np.random.RandomState(1)
    table = jnp.asarray(rs.randn(16, 4), jnp.float32)
    ids = jnp.asarray([0, 3, 3, 15, 7], jnp.int32)
    cot = jnp.asarray(rs.randn(5, 4), jnp.float32)

    def loss_sharded(tab):
        out = sharded_lookup(shard_table(tab, exp_mesh), ids, exp_mesh)
        return jnp.sum(out * cot)

    def loss_dense(tab):
        return jnp.sum(tab[ids] * cot)

    g_sharded = jax.grad(loss_sharded)(table)
    g_dense = jax.grad(loss_dense)(table)
    np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_dense), atol=1e-5)
    # duplicate id 3 accumulated both cotangents
    np.testing.assert_allclose(
        np.asarray(g_dense[3]), np.asarray(cot[1] + cot[2]), atol=1e-6
    )


def test_sharded_table_vocab_divisibility(exp_mesh):
    with pytest.raises(ValueError, match="divisible"):
        shard_table(jnp.zeros((30, 4)), exp_mesh)


def test_updater_protocol():
    from paddle_tpu.optim import SGD

    opt = SGD(learning_rate=0.5)
    upd = SgdLocalUpdater(opt)
    params = {"w": jnp.ones((4,))}
    state = opt.init_state(params)
    grads = {"w": jnp.full((4,), 2.0)}
    new_params, _ = upd.apply(grads, state, params, 0.5)
    np.testing.assert_allclose(np.asarray(new_params["w"]), np.zeros(4), atol=1e-6)

    # IciAllReduceUpdater: same math, plus pass-boundary hooks run clean
    ici = IciAllReduceUpdater(opt, parallel=None)
    ici.start_pass()
    new_params2, _ = ici.apply(grads, state, params, 0.5)
    ici.finish_pass()
    np.testing.assert_allclose(
        np.asarray(new_params2["w"]), np.asarray(new_params["w"])
    )


def test_shard_reader_partitions_and_covers():
    data = list(range(23))
    shards = [list(shard_reader(lambda: iter(data), 4, i)()) for i in range(4)]
    # disjoint and complete
    flat = sorted(x for s in shards for x in s)
    assert flat == data
    # deterministic
    again = list(shard_reader(lambda: iter(data), 4, 2)())
    assert again == shards[2]
    with pytest.raises(ValueError):
        shard_reader(lambda: iter(data), 4, 7)


def test_shard_file_list():
    files = [f"f{i}" for i in range(10)]
    parts = [shard_file_list(files, 3, i) for i in range(3)]
    assert sorted(sum(parts, [])) == files
    assert parts[0] == ["f0", "f3", "f6", "f9"]
