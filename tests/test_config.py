"""v1 config pipeline tests: config_parser DSL → TrainerConfig proto → CLI
training → merge_model → capi inference (SURVEY §2.4 python/paddle/trainer,
trainer_config_helpers; §3.1/§3.5 call stacks). Mirrors the reference's
config-equivalence test idiom (trainer_config_helpers/tests golden protostrs,
test_TrainerOnePass.cpp)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROVIDER_SRC = textwrap.dedent(
    """
    import numpy as np
    from paddle_tpu.data.provider import provider
    from paddle_tpu.data.feeder import dense_vector, integer_value

    @provider(input_types={'pixel': dense_vector(64), 'label': integer_value(10)},
              should_shuffle=False)
    def process(settings, filename):
        rs = np.random.RandomState(7)
        for _ in range(96):
            y = rs.randint(10)
            x = rs.randn(64).astype('float32') * 0.1
            x[y] += 2.0
            yield {'pixel': x, 'label': int(y)}
    """
)

CONF_SRC = textwrap.dedent(
    """
    hid = get_config_arg('hid', int, 32)
    settings(batch_size=32, learning_rate=0.3,
             learning_method=MomentumOptimizer(0.9))
    define_py_data_sources2(train_list='dummy', test_list='dummy',
                            module='conf_provider', obj='process')
    img = data_layer(name='pixel', size=64)
    lbl = data_layer(name='label', size=10)
    h = fc_layer(input=img, size=hid, act=TanhActivation())
    out = fc_layer(input=h, size=10, act=SoftmaxActivation(), name='output')
    cost = classification_cost(input=out, label=lbl)
    classification_error_evaluator(input=out, label=lbl, name='err')
    outputs(cost)
    """
)


@pytest.fixture(scope="module")
def conf_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("conf")
    (d / "conf_provider.py").write_text(PROVIDER_SRC)
    (d / "the_conf.py").write_text(CONF_SRC)
    return d


def test_context_projection_padding_attr_semantics():
    """wrap_bias_attr_default parity (VERDICT item 2): `padding_attr` makes
    trainable padding when unset / None / True / a ParamAttr, and
    non-trainable ONLY for an explicit False (reference
    trainer_config_helpers/layers.py:719-755 — `__bias_attr_not_set__`
    substitutes a ParamAttr for unset/None/True, then `trainable =
    isinstance(padding_attr, ParameterAttribute)`). The old code inverted
    both the None and the False case."""
    from paddle_tpu.config.helpers import ParamAttr, context_projection
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope

    reset_name_scope()
    din = L.Data("x", shape=(8,))
    cases = [
        ({}, True, None),                      # unset → trainable default
        ({"padding_attr": None}, True, None),  # None → substituted, trainable
        ({"padding_attr": True}, True, None),  # True → substituted, trainable
        ({"padding_attr": False}, False, None),  # explicit False → frozen
    ]
    for kw, want_trainable, want_attr in cases:
        proj = context_projection(din, context_len=3, **kw)
        assert proj.trainable_padding is want_trainable, kw
        assert proj.param_attr is want_attr, kw
    attr = ParamAttr(name="ctx_pad")
    proj = context_projection(din, context_len=3, padding_attr=attr)
    assert proj.trainable_padding is True
    assert proj.param_attr is attr


def test_parse_config_emits_proto(conf_dir):
    from paddle_tpu import proto
    from paddle_tpu.config import parse_config

    pc = parse_config(str(conf_dir / "the_conf.py"), "hid=24")
    mc = pc.model_config
    names = {l.name for l in mc.layers}
    assert {"pixel", "label", "output"} <= names
    out_lc = next(l for l in mc.layers if l.name == "output")
    assert out_lc.size == 10 and out_lc.type == "fc"
    hid_lc = next(l for l in mc.layers if l.type == "fc" and l.name != "output")
    assert hid_lc.size == 24  # get_config_arg applied
    assert pc.trainer_config.opt_config.momentum == 0.9
    assert pc.trainer_config.data_config.load_data_module == "conf_provider"
    assert pc.context.evaluators[0].type == "classification_error"
    # parameters recorded with dims
    pnames = {p.name for p in mc.parameters}
    assert "output.w" in pnames and "output.b" in pnames
    text = proto.to_text(pc.trainer_config)
    assert 'type: "fc"' in text and 'input_layer_name: "pixel"' in text


def _run_cli(conf_dir, *args):
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}:{conf_dir}"
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu", *args],
        cwd=conf_dir, env=env, capture_output=True, text=True, timeout=600,
    )


def test_cli_train_merge_infer(conf_dir, tmp_path):
    save_dir = tmp_path / "out"
    r = _run_cli(
        conf_dir, "train", "--config=the_conf.py", "--num_passes=2",
        f"--save_dir={save_dir}", "--log_period=2", "--use_tpu=0",
    )
    assert r.returncode == 0, r.stderr
    assert (save_dir / "pass-00001").is_dir()
    assert "ClassificationErrorEvaluator" in r.stdout

    merged = tmp_path / "merged.npz"
    r = _run_cli(
        conf_dir, "merge_model", "--config=the_conf.py",
        f"--model_dir={save_dir}", f"--output={merged}",
    )
    assert r.returncode == 0, r.stderr
    assert merged.exists()

    from paddle_tpu.capi import create_for_inference

    m = create_for_inference(str(merged))
    rs = np.random.RandomState(7)
    x = rs.randn(8, 64).astype(np.float32) * 0.1
    y = rs.randint(0, 10, 8)
    for i in range(8):
        x[i, y[i]] += 2.0
    probs = m.get_layer_output("output", {"pixel": x, "label": y.astype(np.int32)})
    assert probs.shape == (8, 10)
    # 2 passes of momentum-SGD on a separable toy problem should beat chance
    assert (probs.argmax(-1) == y).mean() > 0.2


def test_cli_job_time(conf_dir):
    r = _run_cli(
        conf_dir, "train", "--config=the_conf.py", "--job=time",
        "--num_batches=3", "--use_tpu=0",
    )
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ms_per_batch"] > 0


def test_dump_config_cli(conf_dir):
    r = _run_cli(conf_dir, "dump_config", "--config=the_conf.py")
    assert r.returncode == 0, r.stderr
    assert 'name: "output"' in r.stdout and "opt_config" in r.stdout
