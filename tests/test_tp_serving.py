"""Tensor-parallel serving on the named sharding-rules mesh (ISSUE 12).

Three contracts pinned here, all on the 8-device CPU host mesh:

  * RULES — parallel/rules.py is the ONE sharding vocabulary: logical axes
    resolve through the table for training (DataParallel.param_sharding)
    and serving (ServableLM) alike; legacy ParamAttr.sharding mesh-axis
    tuples translate through the same table (the deprecation shim); rank-
    mismatched specs are REJECTED naming the param (they used to be
    silently truncated — the data_parallel.py:54 bug).

  * TOKEN IDENTITY — TP=2 and TP=4 decode produce tokens bitwise identical
    to the single-chip oracle, greedy AND sampled (same per-request seeds),
    through whole-prompt and chunked prefill, with ONE decode signature
    (zero recompiles) for the whole lifetime. Attention is per-head
    independent, activations re-replicate at each row-parallel all-reduce,
    and sampling runs on replicated logits — so TP is result-invisible.

  * BYTES — per-chip param and KV-pool bytes shrink ~N× at TP=N, asserted
    from SHARDING METADATA (stats.per_chip_tree_bytes), not trust; and
    checkpoints are canonical full arrays, so one .npz loads bitwise onto
    any layout (single chip ↔ TP=2 ↔ TP=4, and a --shard_update training
    run's async-written checkpoint re-places onto a TP mesh bitwise
    through the updater's canonical seams)."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from paddle_tpu.nn.graph import ParamAttr
from paddle_tpu.parallel import DataParallel, make_mesh
from paddle_tpu.parallel.rules import (
    DEFAULT_RULES,
    ShardingRules,
    make_tp_mesh,
)
from paddle_tpu.serving.model import ServableLM
from paddle_tpu.serving.session import ServingSession, make_demo_session
from paddle_tpu.serving.workload import (
    make_mixed_prompts,
    make_prompts,
    run_closed_loop,
)


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_cache():
    """Detach the suite's persistent compile cache for this module: it
    EXECUTES multi-device (TP mesh) programs, and on jax 0.4.37 CPU running
    a persistent-cache-DESERIALIZED multi-device program corrupts memory or
    segfaults (the PR-5/PR-8 gotcha test_precision.py documents). Compiling
    fresh here costs a few seconds; the cache is restored afterwards."""
    from jax.experimental.compilation_cache import compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    compilation_cache.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    compilation_cache.reset_cache()


# ---------------------------------------------------------------------------
# rules table
# ---------------------------------------------------------------------------


def test_default_rules_resolution():
    rules = ShardingRules()
    mesh = make_tp_mesh(2)
    assert rules.spec_for(("embed", "mlp"), mesh) == P(None, "model")
    assert rules.spec_for(("vocab", "embed"), mesh) == P("model", None)
    # batch -> data; the tp mesh HAS a data axis (size 1)
    assert rules.spec_for(("batch", "embed"), mesh) == P("data", None)
    # shorter specs pad with None (trailing dims replicated)
    assert rules.spec_for(("heads",), mesh, ndim=3) == P("model", None, None)


def test_rules_axis_absent_from_mesh_replicates():
    """The rules name the FULL vocabulary; a mesh without the target axis
    simply doesn't shard that entry — the same model runs on the data-only
    training mesh and the TP serving mesh without edits."""
    rules = ShardingRules()
    data_mesh = make_mesh({"data": 4})
    assert rules.spec_for(("embed", "mlp"), data_mesh) == P(None, None)
    assert rules.spec_for(("batch", "heads"), data_mesh) == P("data", None)


def test_rules_unknown_axis_raises_naming_param():
    with pytest.raises(KeyError, match=r"heds.*h\.w"):
        ShardingRules().spec_for(("embed", "heds"), make_tp_mesh(2), param="h.w")


def test_rules_pipeline_axis_reserved():
    """PARITY §2.5's reserved pipeline axis is a rules-table ENTRY now:
    present, unmapped — the day the mesh grows a pipe axis it is one edit."""
    assert "pipeline" in DEFAULT_RULES and DEFAULT_RULES["pipeline"] is None
    rules = ShardingRules().with_overrides(pipeline="model")
    assert rules.spec_for(("pipeline",), make_tp_mesh(2)) == P("model")


def test_legacy_mesh_axis_tuples_translate_through_table():
    """The deprecation shim: raw mesh-axis names in ParamAttr.sharding are
    their own logical names, resolved through the SAME table — old call
    sites (test_parallel, models/ctr.py) keep working unmodified."""
    mesh = make_mesh({"data": 4, "model": 2})
    dp = DataParallel(mesh, param_attrs={
        "w": ParamAttr(sharding=(None, "model")),
        "e": ParamAttr(logical_axes=("embed", "mlp")),
    })
    assert dp.param_sharding("w", 2).spec == P(None, "model")
    assert dp.param_sharding("e", 2).spec == P(None, "model")
    assert dp.param_sharding("unlisted", 2).spec == P()


def test_rank_mismatched_spec_rejected_naming_param():
    """Regression (ISSUE 12 satellite): param_sharding used to silently
    TRUNCATE a spec longer than the array's rank — a ("mlp", "embed") spec
    on a 1-D bias sharded the wrong dim without a word. Now it raises,
    naming the param."""
    dp = DataParallel(make_mesh({"data": 4, "model": 2}), param_attrs={
        "b": ParamAttr(sharding=("model", None)),
        "lb": ParamAttr(logical_axes=("mlp", "embed")),
    })
    with pytest.raises(ValueError, match="'b'"):
        dp.param_sharding("b", 1)
    with pytest.raises(ValueError, match="'lb'"):
        dp.param_sharding("lb", 1)
    # shorter-than-rank still pads (the documented convenience)
    assert dp.param_sharding("b", 3).spec == P("model", None, None)


# ---------------------------------------------------------------------------
# token identity + byte accounting
# ---------------------------------------------------------------------------

_DEMO = dict(vocab=64, n_layers=2, d_model=32, n_heads=4, seed=0,
             max_slots=4, page_size=8, max_new_limit=8)


def _greedy_run(tp):
    session = make_demo_session(prefill_buckets=(16, 32), tp=tp, **_DEMO)
    prompts = make_prompts(6, lengths=(5, 11, 16, 23), vocab=64, bos_id=1,
                           seed=0)
    res = run_closed_loop(session, prompts, 8, concurrency=4)
    return res.pop("results"), session.stats()


def _sampled_chunked_run(tp):
    """Sampling (temperature+top_k, per-request seeds) AND chunked prefill
    (long prompts beyond the bucket) in one leg — the two decode-path
    features PR 11 added must BOTH be TP-invariant."""
    session = make_demo_session(
        prefill_buckets=(16,), max_len=64, prefill_chunk=8, tp=tp,
        default_temperature=0.8, default_top_k=12, **_DEMO,
    )
    prompts = make_mixed_prompts(6, short_lengths=(5, 11), long_len=40,
                                 long_every=3, burst=1, vocab=64, bos_id=1,
                                 seed=1)
    res = run_closed_loop(session, prompts, 8, concurrency=4)
    return res.pop("results"), session.stats()


@pytest.fixture(scope="module")
def greedy_runs():
    return {tp: _greedy_run(tp) for tp in (0, 2, 4)}


@pytest.fixture(scope="module")
def sampled_runs():
    return {tp: _sampled_chunked_run(tp) for tp in (0, 2, 4)}


def test_tp_greedy_tokens_bitwise_identical(greedy_runs):
    tok0 = greedy_runs[0][0]
    assert greedy_runs[2][0] == tok0, "TP=2 greedy tokens diverged"
    assert greedy_runs[4][0] == tok0, "TP=4 greedy tokens diverged"
    assert all(t for t in tok0)  # every request actually produced tokens


def test_tp_sampled_chunked_tokens_bitwise_identical(sampled_runs):
    tok0 = sampled_runs[0][0]
    assert sampled_runs[2][0] == tok0, "TP=2 sampled/chunked tokens diverged"
    assert sampled_runs[4][0] == tok0, "TP=4 sampled/chunked tokens diverged"
    # the chunked path really ran (long prompts committed chunk-by-chunk)
    assert all(st["prefill_chunks_committed"] > 0
               for _, st in sampled_runs.values())


def test_tp_one_decode_signature(greedy_runs, sampled_runs):
    """The whole TP serving lifetime shares ONE compiled decode program —
    mesh-aware block tables ride as data, never shape."""
    for runs in (greedy_runs, sampled_runs):
        for tp, (_, st) in runs.items():
            assert st["decode_shape_signatures"] == 1, (tp, st)


def test_tp_param_and_pool_bytes_shrink(greedy_runs):
    """~N× per-chip shrink from sharding METADATA: the pool is fully
    kv_heads-sharded (exactly N×); params keep small replicated leaves
    (norms, biases, positions), so ≥ 0.6·N like shard_update_bench."""
    base = greedy_runs[0][1]
    for tp in (2, 4):
        st = greedy_runs[tp][1]
        assert st["tp"] == tp
        assert st["pool_bytes_per_chip"] * tp == base["pool_bytes_per_chip"]
        ratio = base["param_bytes_per_chip"] / st["param_bytes_per_chip"]
        assert ratio >= 0.6 * tp, (tp, ratio)


def test_tp_pool_reinit_keeps_sharding(greedy_runs):
    """Crash recovery re-creates the pools through the SAME cache seam: the
    re-init must land on the TP layout, or the first post-restart decode
    would silently reshard the whole pool every step."""
    session = make_demo_session(prefill_buckets=(16,), tp=2, **_DEMO)
    assert session.cache.pool_sharding is not None
    session.cache.reset()
    k2, v2 = session.cache.make_pools()
    assert k2.sharding.spec == P(None, None, None, "model")
    assert v2.sharding.spec == P(None, None, None, "model")


def test_tp_rejects_indivisible_heads():
    with pytest.raises(ValueError, match="n_heads"):
        make_demo_session(vocab=64, n_layers=1, d_model=32, n_heads=2,
                          seed=0, tp=4)


def test_tp_unknown_param_raises_not_replicates():
    """A param absent from param_logical_axes must raise under TP, not
    silently replicate — omission would quietly erode the per-chip memory
    win while every token-equality gate still passed."""
    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=64, n_layers=1, d_model=32, n_heads=4, max_len=64),
        mesh=make_tp_mesh(2),
    )
    with pytest.raises(KeyError, match="mystery"):
        model.param_sharding("mystery", 2)
    # single-chip path stays permissive (no table lookup happens at all)
    single = ServableLM(
        LMConfig(vocab=64, n_layers=1, d_model=32, n_heads=4, max_len=64)
    )
    assert single.param_sharding("mystery", 2) is None


# ---------------------------------------------------------------------------
# cross-layout checkpoints
# ---------------------------------------------------------------------------


def test_servable_checkpoint_cross_layout_bitwise(tmp_path, greedy_runs):
    """One .npz, any layout: a checkpoint written FROM a TP=2 session
    (sharded params gather to canonical full arrays in save()) loads
    bitwise onto a single chip and onto TP=4, and the loaded TP=4 session
    decodes the oracle's exact tokens."""
    tp2 = make_demo_session(prefill_buckets=(16, 32), tp=2, **_DEMO)
    path = os.path.join(str(tmp_path), "tp2.npz")
    tp2.model.save(path, tp2.params)

    single_model, single_params = ServableLM.load(path)
    tp4_model, tp4_params = ServableLM.load(path, mesh=make_tp_mesh(4))
    for k in single_params:
        np.testing.assert_array_equal(
            np.asarray(single_params[k]).view(np.uint32),
            np.asarray(tp4_params[k]).view(np.uint32),
        )
    tp4 = ServingSession(
        tp4_model, tp4_params, max_slots=4, page_size=8,
        prefill_buckets=(16, 32), max_new_limit=8,
    )
    prompts = make_prompts(4, lengths=(5, 11, 16), vocab=64, bos_id=1, seed=0)
    got = run_closed_loop(tp4, prompts, 8, concurrency=4).pop("results")
    oracle = make_demo_session(prefill_buckets=(16, 32), tp=0, **_DEMO)
    want = run_closed_loop(oracle, prompts, 8, concurrency=4).pop("results")
    assert got == want


def test_shard_update_checkpoint_places_onto_tp_mesh_bitwise(tmp_path):
    """The training↔serving seam: a --shard_update run's ASYNC-written
    checkpoint (flat data-axis-sharded opt state gathered through
    to_canonical) holds canonical full params that re-place bitwise onto a
    dp×tp mesh through the rules table — one sharding vocabulary, both
    runtimes."""
    from paddle_tpu.nn import costs as C
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.trainer import SGDTrainer

    def build():
        reset_name_scope()
        x = L.Data("x", shape=(8,))
        lbl = L.Data("label", shape=())
        h = L.Fc(x, 16, act="relu", name="h")
        logits = L.Fc(h, 4, act=None, name="out")
        return C.ClassificationCost(logits, lbl, name="cost")

    rs = np.random.RandomState(0)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(0, 4, 32)

    def reader():
        for i in range(0, 32, 16):
            yield {"x": x[i:i + 16], "label": y[i:i + 16]}

    # power-of-two lr: exact scale products keep sharded == replicated
    # bitwise on this XLA build (see tests/test_shard_update.py)
    dp = DataParallel(make_mesh({"data": 4}))
    tr = SGDTrainer(build(), SGD(learning_rate=0.125), parallel=dp, seed=3,
                    shard_update=True)
    tr.train(reader, num_passes=1, save_dir=str(tmp_path),
             async_checkpoint=True)
    tr.checkpoint_wait()

    with np.load(os.path.join(str(tmp_path), "pass-00000",
                              "params.npz")) as z:
        saved = {k: np.array(z[k]) for k in z.files}

    # replicated twin: same seed/data/optimizer, no sharded update — the
    # canonical checkpoint must be bitwise the same params
    dp2 = DataParallel(make_mesh({"data": 4}))
    tr2 = SGDTrainer(build(), SGD(learning_rate=0.125), parallel=dp2, seed=3,
                     shard_update=False)
    tr2.train(reader, num_passes=1)
    for k, v in tr2.state["params"].items():
        np.testing.assert_array_equal(
            saved[k].view(np.uint32), np.asarray(v).view(np.uint32)
        )

    # re-place the canonical arrays onto a dp×tp mesh through the rules
    # table (logical axes this time, not mesh tuples) and round-trip
    tp_dp = DataParallel(make_mesh({"data": 2, "model": 2}), param_attrs={
        "h.w": ParamAttr(logical_axes=("embed", "mlp")),
        "out.w": ParamAttr(logical_axes=("mlp", "embed")),
    })
    for k, v in saved.items():
        placed = jax.device_put(v, tp_dp.param_sharding(k, v.ndim))
        if k == "h.w":
            assert placed.sharding.spec == P(None, "model")
        np.testing.assert_array_equal(
            np.asarray(placed).view(np.uint32), v.view(np.uint32)
        )


# ---------------------------------------------------------------------------
# shared-prefix cache × TP (ISSUE 19)
# ---------------------------------------------------------------------------


def _prefix_run(tp, prefix):
    """Sampled + chunked + prefix-cache run: each prompt drains before the
    next submits, so later prompts genuinely alias the cached prefix."""
    session = make_demo_session(
        prefill_buckets=(16,), max_len=96, prefill_chunk=8, tp=tp,
        prefix_cache=prefix, **_DEMO,
    )
    sys_prompt = list(range(2, 26))  # 24 shared tokens = 3 pages of 8
    handles = []
    for i in range(4):
        handles.append(session.submit(
            sys_prompt + [30 + i, 31 + i], 6,
            seed=50 + i, temperature=0.6, top_k=12,
        ))
        session.run_until_idle()
    return [h.tokens for h in handles], session.stats()


def test_tp_prefix_cache_tokens_identical():
    """The prefix cache is HOST-side block-table state, so it composes with
    TP for free: aliased pages are just page ids in the replicated table,
    and the per-shard paged attention reads them like any other page. TP=2
    cache-on tokens must be bitwise the single-chip cache-off oracle, with
    a real hit rate and still ONE decode signature."""
    ref, _ = _prefix_run(0, False)
    for tp in (0, 2):
        out, st = _prefix_run(tp, True)
        assert out == ref, f"tp={tp} cache-on tokens diverged"
        assert st["prefix_hit_rate"] > 0.3, (tp, st["prefix_hit_rate"])
        assert st["prefix_pages_shared"] >= 9, (tp, st["prefix_pages_shared"])
        assert st["decode_shape_signatures"] == 1
