"""Native runtime tests (csrc/ via ctypes): buddy allocator, recordio,
elastic task master + TCP service. Mirrors the reference's test idioms:
in-process services on localhost ports (test_CompareSparse.cpp:65,
test_ProtoServer.cpp) and Go master lifecycle tests
(go/master/service_internal_test.go). The pure-Python recordio implementation
doubles as the cross-check oracle (SURVEY §4 CPU-oracle idiom)."""

import os
import pickle
import time

import numpy as np
import pytest

from paddle_tpu.runtime import (
    MasterClient,
    MasterServer,
    TaskMaster,
    available,
    cluster_reader,
    recordio,
)

pytestmark = pytest.mark.skipif(
    not available(), reason="native runtime library unavailable"
)


# -- allocator --------------------------------------------------------------


def test_buddy_allocator_alloc_free_coalesce():
    from paddle_tpu.runtime.allocator import HostPool

    pool = HostPool(total_bytes=1 << 20, min_block=256)
    addrs = [pool.alloc(1000) for _ in range(64)]
    assert len(set(addrs)) == 64
    st = pool.stats()
    assert st["in_use"] == 64 * 1024  # 1000 rounds up to 1024
    for a in addrs:
        pool.free(a)
    st = pool.stats()
    assert st["in_use"] == 0 and st["n_frees"] == 64
    # full coalescing: the whole arena must be allocatable again
    big = pool.alloc((1 << 20) - 1)
    pool.free(big)
    # double free is rejected
    a = pool.alloc(128)
    pool.free(a)
    with pytest.raises(ValueError):
        pool.free(a)
    pool.close()


def test_pool_ndarray_roundtrip():
    from paddle_tpu.runtime.allocator import HostPool

    pool = HostPool(total_bytes=1 << 20)
    arr = pool.ndarray((16, 32), np.float32)
    arr[:] = np.arange(512, dtype=np.float32).reshape(16, 32)
    assert float(arr.sum()) == float(np.arange(512).sum())
    pool.release(arr)
    # the block is NOT reusable while the view is alive (no use-after-free):
    assert pool.stats()["in_use"] > 0
    with pytest.raises(RuntimeError, match="view"):
        pool.close()
    view = arr[2:4]  # derived views extend the block's lifetime
    del arr
    assert pool.stats()["in_use"] > 0
    del view
    assert pool.stats()["in_use"] == 0  # freed once the last view died
    pool.close()


def test_pool_exhaustion_raises():
    from paddle_tpu.runtime.allocator import HostPool

    pool = HostPool(total_bytes=1 << 16)
    a = pool.alloc(1 << 15)
    b = pool.alloc(1 << 15)
    with pytest.raises(MemoryError):
        pool.alloc(1024)
    pool.free(a)
    pool.free(b)
    pool.close()


# -- recordio ---------------------------------------------------------------


def test_recordio_roundtrip_and_cross_impl(tmp_path, monkeypatch):
    path = str(tmp_path / "data.recordio")
    records = [os.urandom(np.random.randint(1, 2000)) for _ in range(257)]
    with recordio.Writer(path, chunk_records=50) as w:
        for r in records:
            w.write(r)
    # native reader
    assert list(recordio.Reader(path)) == records
    # pure-Python reader parses the native-written file (same format)
    assert list(recordio._py_read(path)) == records
    # and the native reader parses a python-written file
    path2 = str(tmp_path / "py.recordio")
    pw = recordio._PyWriter(path2, 50, 8 << 20)
    for r in records:
        pw.write(r)
    pw.close()
    assert list(recordio.Reader(path2)) == records


def test_recordio_corrupt_chunk_skipped(tmp_path):
    path = str(tmp_path / "corrupt.recordio")
    with recordio.Writer(path, chunk_records=10) as w:
        for i in range(30):  # 3 chunks
            w.write(f"rec-{i:03d}".encode())
    raw = bytearray(open(path, "rb").read())
    # flip a byte inside the second chunk's data region
    chunk_size = 16 + 10 * (4 + 7)
    raw[chunk_size + 16 + 8] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    r = recordio.Reader(path)
    got = list(r)
    assert [g.decode() for g in got[:10]] == [f"rec-{i:03d}" for i in range(10)]
    assert len(got) == 20  # middle chunk dropped whole
    assert r.errors == 1


def test_convert_and_read_shards(tmp_path):
    samples = [(i, float(i) * 0.5, f"s{i}") for i in range(100)]
    paths = recordio.convert(
        str(tmp_path / "shards"), lambda: iter(samples), records_per_file=32
    )
    assert len(paths) == 4
    back = list(recordio.read_shards(paths))
    assert back == samples


# -- task master ------------------------------------------------------------


def test_master_lifecycle_timeout_failure():
    m = TaskMaster(timeout_s=0.15, failure_max=1)
    m.set_dataset(["a", "b", "c", "d"], chunks_per_task=2)
    t1 = m.get_task()
    t2 = m.get_task()
    assert t1[1] == ["a", "b"] and t2[1] == ["c", "d"]
    assert m.get_task() is None  # all leased
    assert m.task_finished(t1[0])
    # t2 lease expires → requeued with failures=1
    time.sleep(0.2)
    t2b = m.get_task()
    assert t2b[1] == ["c", "d"]
    # explicit failure pushes past failure_max=1 → discarded
    assert m.task_failed(t2b[0])
    assert m.get_task() == (TaskMaster.PASS_FINISHED, [])
    st = m.stats()
    assert st["done"] == 1 and st["discarded"] == 1
    # next pass refills everything
    assert m.pass_finished(start_next=True)
    st = m.stats()
    assert st["todo"] == 2 and st["pass"] == 1
    m.close()


def test_master_snapshot_restore(tmp_path):
    snap = str(tmp_path / "master.snap")
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset([f"s{i}" for i in range(6)], chunks_per_task=2)
    t = m.get_task()
    m.task_finished(m.get_task()[0])
    m.snapshot(snap)
    m.close()
    # "restarted" master recovers; the leased (pending) task is re-dispatchable
    m2 = TaskMaster(timeout_s=60, failure_max=3)
    m2.restore(snap)
    st = m2.stats()
    assert st["done"] == 1 and st["pending"] == 0 and st["todo"] == 2
    seen = set()
    while True:
        got = m2.get_task()
        if got is None or got[0] == TaskMaster.PASS_FINISHED:
            break
        seen.add(tuple(got[1]))
        m2.task_finished(got[0])
    assert tuple(t[1]) in seen  # the lost lease came back
    m2.close()


# -- master TCP service + cluster reader ------------------------------------


def test_master_server_and_cluster_reader(tmp_path):
    samples = [{"x": i, "y": i * i} for i in range(64)]
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: iter(samples), records_per_file=16
    )
    server = MasterServer(TaskMaster(timeout_s=30, failure_max=2)).start()
    try:
        client = MasterClient(server.address)
        assert client.call("set_dataset", shards=shards, chunks_per_task=1)["ok"]
        reader = cluster_reader(server.address)
        got = sorted(list(reader()), key=lambda s: s["x"])
        assert got == samples
        st = client.call("stats")
        assert st["done"] == 4 and st["todo"] == 0
        client.close()
    finally:
        server.stop()


def test_master_server_crash_recovery(tmp_path):
    """Kill the server mid-pass; a new server restores from snapshot and the
    remaining work completes (go/master etcd-snapshot semantics)."""
    samples = list(range(40))
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: iter(samples), records_per_file=10
    )
    snap = str(tmp_path / "m.snap")
    server = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    client = MasterClient(server.address)
    client.call("set_dataset", shards=shards, chunks_per_task=1)
    # consume one task fully
    resp = client.call("get_task")
    consumed = list(recordio.read_shards(resp["shards"]))
    client.call("task_finished", task_id=resp["task_id"])
    client.close()
    server.stop()

    server2 = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    try:
        rest = list(cluster_reader(server2.address)())
        assert sorted(consumed + rest) == samples
    finally:
        server2.stop()


# -- native optimizer lib (csrc/optimizer.cc; paddle/optimizer parity) -------


def test_native_optimizer_matches_python_oracle():
    """The jax optim package is the oracle (SURVEY §4 cross-impl idiom)."""
    import jax.numpy as jnp

    from paddle_tpu.optim import SGD, Adam
    from paddle_tpu.runtime.optimizer import NativeOptimizer

    rs = np.random.RandomState(0)
    p0 = rs.randn(64).astype(np.float32)
    grads = [rs.randn(64).astype(np.float32) for _ in range(5)]

    for kind, native_kw, py_opt in [
        ("sgd", {"learning_rate": 0.1, "momentum": 0.9},
         SGD(learning_rate=0.1, momentum=0.9)),
        ("adam", {"learning_rate": 0.05}, Adam(learning_rate=0.05)),
    ]:
        nat = NativeOptimizer(kind, **native_kw)
        p_nat = p0.copy()
        params = {"w": jnp.asarray(p0)}
        state = py_opt.init_state(params)
        for g in grads:
            nat.update(p_nat, g)
            params, state = py_opt.update({"w": jnp.asarray(g)}, state, params, native_kw["learning_rate"])
        np.testing.assert_allclose(
            p_nat, np.asarray(params["w"]), rtol=2e-4, atol=2e-5, err_msg=kind
        )
        nat.close()


def test_native_optimizer_serialize_roundtrip():
    from paddle_tpu.runtime.optimizer import NativeOptimizer

    rs = np.random.RandomState(1)
    p = rs.randn(32).astype(np.float32)
    a = NativeOptimizer("adam", learning_rate=0.01)
    for _ in range(3):
        a.update(p, rs.randn(32).astype(np.float32))
    blob = a.serialize()

    b = NativeOptimizer("adam", learning_rate=0.01)
    b.deserialize(blob)
    g = rs.randn(32).astype(np.float32)
    pa, pb = p.copy(), p.copy()
    a.update(pa, g)
    b.update(pb, g)
    np.testing.assert_allclose(pa, pb, atol=1e-7)  # identical resumed state
    # wrong-type blob rejected
    c = NativeOptimizer("sgd")
    with pytest.raises(ValueError):
        c.deserialize(blob)


def test_native_optimizer_linear_lr_policy():
    from paddle_tpu.runtime.optimizer import NativeOptimizer

    o = NativeOptimizer("sgd", learning_rate=1.0, lr_policy="linear",
                        lr_decay_a=0.25, lr_decay_b=0.1)
    p = np.zeros(4, np.float32)
    g = np.ones(4, np.float32)
    assert o.current_lr == 1.0
    o.update(p, g)          # applied lr 1.0
    assert abs(o.current_lr - 0.75) < 1e-9
    for _ in range(10):
        o.update(p, g)
    assert abs(o.current_lr - 0.1) < 1e-9  # floored


def test_master_restore_truncated_snapshot_preserves_state(tmp_path):
    """A corrupt/truncated snapshot must fail WITHOUT destroying the live
    queues (commit-after-parse in pt_master_restore)."""
    snap = str(tmp_path / "good.snap")
    m = TaskMaster(timeout_s=60, failure_max=3)
    m.set_dataset([f"s{i}" for i in range(6)], chunks_per_task=2)
    m.snapshot(snap)
    blob = open(snap, "rb").read()
    bad = str(tmp_path / "bad.snap")
    open(bad, "wb").write(blob[: len(blob) // 2])  # truncate mid-task

    before = m.stats()
    assert before["todo"] == 3
    with pytest.raises(OSError):
        m.restore(bad)
    after = m.stats()
    assert after == before, "failed restore must not clobber live state"
    # and the master still dispatches normally
    assert m.get_task() is not None
    m.close()


def test_recordio_oversized_chunk_header_is_corruption(tmp_path):
    """A corrupted data_len with intact magic must be treated as corruption,
    not drive a multi-GiB allocation."""
    import struct

    path = str(tmp_path / "x.recordio")
    with recordio.Writer(path) as w:
        for i in range(5):
            w.write(f"rec{i}".encode())

    blob = bytearray(open(path, "rb").read())
    # chunk header: magic, n_records, data_len, crc — patch data_len huge
    struct.pack_into("<I", blob, 8, 0xF0000000)
    open(path, "wb").write(bytes(blob))

    r = recordio.Reader(path)
    assert list(r) == []  # framing untrustworthy -> no records, no abort


# -- elastic-cluster satellites (ISSUE 3) ------------------------------------


def test_master_restart_exactly_once_delivery(tmp_path):
    """Exactly-once across a master restart: snapshot-on-ack, crash (kill(),
    NO final snapshot), restore on a NEW port — done == ntasks, discarded ==
    0, and every record is consumed exactly once."""
    samples = list(range(36))
    shards = recordio.convert(
        str(tmp_path / "ds"), lambda: iter(samples), records_per_file=6
    )
    ntasks = len(shards)
    snap = str(tmp_path / "m.snap")
    s1 = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    c = MasterClient(s1.address)
    c.call("set_dataset", shards=shards, chunks_per_task=1)
    consumed = []
    for _ in range(2):  # two tasks fully done + acked (each ack snapshots)
        r = c.call("get_task")
        consumed += list(recordio.read_shards(r["shards"]))
        assert c.call("task_finished", task_id=r["task_id"])["ok"]
    c.close()
    s1.kill()  # crash semantics: no final snapshot, leases die with it
    s1.join(timeout=10)
    assert not s1.alive

    s2 = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2), snapshot_path=snap
    ).start()
    try:
        rest = list(cluster_reader(s2.address)())
        assert sorted(consumed + rest) == samples  # exactly once, no dupes
        st = MasterClient(s2.address).call("stats")
        assert st["done"] == ntasks and st["discarded"] == 0
    finally:
        s2.stop()


def test_master_stop_closes_native_handle():
    m = TaskMaster()
    server = MasterServer(m).start()
    server.stop()
    assert m.closed  # the handle used to leak here
    server.stop()  # idempotent
    assert m.closed


def test_master_snapshot_debounce(tmp_path):
    """snapshot_every/interval rate-limit the per-ack write; stop() makes
    whatever is still pending durable."""
    snap = str(tmp_path / "m.snap")
    server = MasterServer(
        TaskMaster(timeout_s=30, failure_max=2),
        snapshot_path=snap,
        snapshot_every=3,
        snapshot_interval_s=60.0,
    ).start()
    try:
        c = MasterClient(server.address)
        c.call("set_dataset", shards=[f"s{i}" for i in range(8)])
        tasks = [c.call("get_task")["task_id"] for _ in range(8)]
        for t in tasks[:2]:
            c.call("task_finished", task_id=t)
        assert not os.path.exists(snap)  # 2 acks < every=3: debounced away
        c.call("task_finished", task_id=tasks[2])
        deadline = time.time() + 5
        while not os.path.exists(snap) and time.time() < deadline:
            time.sleep(0.02)
        assert os.path.exists(snap)  # 3rd ack crossed the threshold
        stamp = os.path.getmtime(snap), os.path.getsize(snap)
        for t in tasks[3:6]:
            c.call("task_finished", task_id=t)
        # 3 more acks but inside the 60s interval: still the old snapshot
        assert (os.path.getmtime(snap), os.path.getsize(snap)) == stamp
        c.close()
    finally:
        server.stop()
    # clean stop flushed the pending acks: a restore sees all 6 done
    m2 = TaskMaster(timeout_s=30, failure_max=2)
    m2.restore(snap)
    assert m2.stats()["done"] == 6
    m2.close()
