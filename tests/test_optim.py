"""Optimizer tests — analog of paddle/math/tests/test_TrainingAlgorithm.cpp
(kernel impl vs reference formulas in OriginalOptimizerApi.h): each optimizer is
checked against a straightforward numpy re-implementation on one step, and all
optimizers must descend a quadratic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.nn.graph import ParamAttr
from paddle_tpu.optim import (
    SGD,
    Adam,
    AdaMax,
    AdaGrad,
    AdaDelta,
    DecayedAdaGrad,
    ModelAverage,
    RMSProp,
    schedules,
)

ALL_OPTS = [
    SGD(learning_rate=0.1),
    SGD(learning_rate=0.1, momentum=0.9),
    SGD(learning_rate=0.1, momentum=0.9, nesterov=True),
    AdaGrad(learning_rate=0.5),
    # leaky-accumulator optimizers take ~constant-magnitude steps of size lr,
    # so the quadratic only converges below tol with a small lr
    DecayedAdaGrad(learning_rate=0.05),
    # AdaDelta cold-starts with ~sqrt(eps)-sized steps; a larger eps keeps the
    # 150-step budget sufficient
    AdaDelta(learning_rate=1.0, rho=0.9, epsilon=1e-2),
    RMSProp(learning_rate=0.05),
    Adam(learning_rate=0.2),
    AdaMax(learning_rate=0.2),
]


@pytest.mark.parametrize("opt", ALL_OPTS, ids=lambda o: type(o).__name__ + str(getattr(o, "momentum", "")))
def test_descends_quadratic(opt):
    params = {"w": jnp.asarray([3.0, -2.0, 1.5])}
    state = opt.init_state(params)
    lr = jnp.asarray(opt.learning_rate)
    for _ in range(150):
        grads = {"w": 2.0 * params["w"]}  # d/dw ||w||^2
        params, state = opt.update(grads, state, params, lr)
    assert float(jnp.sum(params["w"] ** 2)) < 1e-2


def test_sgd_momentum_matches_numpy():
    opt = SGD(learning_rate=0.1, momentum=0.9)
    p = np.array([1.0, 2.0], np.float32)
    g = np.array([0.5, -1.0], np.float32)
    params = {"w": jnp.asarray(p)}
    state = opt.init_state(params)
    v = np.zeros_like(p)
    want = p.copy()
    got = params
    for _ in range(3):
        v = 0.9 * v - 0.1 * g
        want = want + v
        got, state = opt.update({"w": jnp.asarray(g)}, state, got, jnp.asarray(0.1))
    np.testing.assert_allclose(np.asarray(got["w"]), want, rtol=1e-6)


def test_adam_bias_correction_first_step():
    opt = Adam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=0.0)
    params = {"w": jnp.asarray([1.0])}
    state = opt.init_state(params)
    g = jnp.asarray([0.3])
    new_params, _ = opt.update({"w": g}, state, params, jnp.asarray(0.1))
    # after bias correction step 1: mhat = g, vhat = g^2 → update = lr * sign(g)
    np.testing.assert_allclose(np.asarray(new_params["w"]), [1.0 - 0.1], rtol=1e-5)


def test_static_param_untouched():
    opt = SGD(learning_rate=0.1)
    opt.param_attrs = {"w": ParamAttr(is_static=True)}
    params = {"w": jnp.asarray([1.0])}
    state = opt.init_state(params)
    new_params, _ = opt.update({"w": jnp.asarray([5.0])}, state, params, jnp.asarray(0.1))
    np.testing.assert_array_equal(np.asarray(new_params["w"]), [1.0])


def test_per_param_lr_scale():
    opt = SGD(learning_rate=0.1)
    opt.param_attrs = {"a": ParamAttr(learning_rate=0.0), "b": ParamAttr(learning_rate=2.0)}
    params = {"a": jnp.asarray([1.0]), "b": jnp.asarray([1.0])}
    state = opt.init_state(params)
    new_params, _ = opt.update(
        {"a": jnp.asarray([1.0]), "b": jnp.asarray([1.0])}, state, params, jnp.asarray(0.1)
    )
    np.testing.assert_allclose(np.asarray(new_params["a"]), [1.0])
    np.testing.assert_allclose(np.asarray(new_params["b"]), [0.8], rtol=1e-6)


def test_l1_l2_decay():
    opt = SGD(learning_rate=0.1, l2_rate=0.5)
    params = {"w": jnp.asarray([1.0])}
    state = opt.init_state(params)
    new_params, _ = opt.update({"w": jnp.asarray([0.0])}, state, params, jnp.asarray(0.1))
    # g_eff = 0 + 0.5*1 → w = 1 - 0.1*0.5 = 0.95
    np.testing.assert_allclose(np.asarray(new_params["w"]), [0.95], rtol=1e-6)
    opt1 = SGD(learning_rate=0.1, l1_rate=0.5)
    state1 = opt1.init_state(params)
    new1, _ = opt1.update({"w": jnp.asarray([0.0])}, state1, params, jnp.asarray(0.1))
    # shrinkage by lr*l1 = 0.05
    np.testing.assert_allclose(np.asarray(new1["w"]), [0.95], rtol=1e-6)


def test_gradient_clipping():
    opt = SGD(learning_rate=1.0, gradient_clipping_threshold=0.1)
    params = {"w": jnp.asarray([0.0])}
    state = opt.init_state(params)
    new_params, _ = opt.update({"w": jnp.asarray([10.0])}, state, params, jnp.asarray(1.0))
    np.testing.assert_allclose(np.asarray(new_params["w"]), [-0.1], rtol=1e-6)


def test_model_average():
    avg = ModelAverage(average_window=0.5)
    params = {"w": jnp.asarray([0.0])}
    st = avg.init_state(params)
    for v in [1.0, 2.0, 3.0]:
        st = avg.update(st, {"w": jnp.asarray([v])})
    out = avg.averaged_params(st, {"w": jnp.asarray([3.0])})
    np.testing.assert_allclose(np.asarray(out["w"]), [2.0], rtol=1e-6)


def test_schedules():
    t = jnp.asarray(100.0)
    assert float(schedules.build(0.1)(t)) == pytest.approx(0.1)
    poly = schedules.build(0.1, "poly", decay_a=0.01, decay_b=0.5)
    assert float(poly(t)) == pytest.approx(0.1 * (1 + 1.0) ** -0.5)
    exp = schedules.build(0.1, "exp", decay_a=0.5, decay_b=100.0)
    assert float(exp(t)) == pytest.approx(0.05)
    disc = schedules.build(0.1, "discexp", decay_a=0.5, decay_b=30.0)
    assert float(disc(t)) == pytest.approx(0.1 * 0.5**3)
    lin = schedules.build(0.1, "linear", decay_a=0.0005, decay_b=0.02)
    assert float(lin(t)) == pytest.approx(0.05)
    man = schedules.manual(1.0, [(50, 1.0), (100, 0.1), (200, 0.01)])
    assert float(man(jnp.asarray(120.0))) == pytest.approx(0.01)
    warm = schedules.build(0.1, warmup_samples=200.0)
    assert float(warm(t)) == pytest.approx(0.05)
