"""Shared-prefix KV cache (ISSUE 19).

The load-bearing claims, each pinned directly:

  * ALIASING — a prompt whose leading pages are cached aliases them into
    its slot read-only (refcounted) and prefills only its own suffix; the
    match never covers the whole prompt (the final chunk must still emit
    the sampled first token), and only COMMITTED pages ever register.
  * TOKEN IDENTITY — cache-on tokens are bitwise cache-off tokens: greedy
    AND seeded-sampled, chunked AND whole-prompt-routed prompts, with ONE
    decode signature (the cache is host-side block-table state; no
    executable ever learns it exists).
  * ACCOUNTING — a page frees exactly once, at refcount zero: releasing a
    slot that shares pages decrefs without freeing (cancel-mid-decode
    regression), LRU eviction only ever takes unreferenced cached pages,
    and after churn + flush the free list is whole (zero leak).
  * TENANCY — chains are rooted per tenant: identical prompts from two
    tenants never alias each other's pages, and hit counters are
    per-tenant in stats().
  * COMPOSITION — crash recovery invalidates the index (no stale aliases
    into the dead pool) and replays token-bitwise while the cache
    re-populates; speculation's +K headroom and aliased pages coexist
    without leak or double-free; adaptive draft-K stays a pure rule.
"""

import time

import pytest

from paddle_tpu.core import faults
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.prefix_cache import PrefixIndex
from paddle_tpu.serving.speculation import next_draft_k

pytestmark = [pytest.mark.serving, pytest.mark.prefix]

VOCAB = 96


@pytest.fixture(scope="module")
def model_and_params():
    import jax

    from paddle_tpu.serving.model import LMConfig, ServableLM

    model = ServableLM(
        LMConfig(vocab=VOCAB, n_layers=2, d_model=32, n_heads=2, max_len=96)
    )
    return model, model.init_params(jax.random.PRNGKey(0))


def make_session(model_and_params, **kw):
    from paddle_tpu.serving.session import ServingSession

    model, params = model_and_params
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefill_buckets", (8, 16, 32))
    kw.setdefault("max_new_limit", 16)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_cache", True)
    return ServingSession(model, params, **kw)


def make_cache(**kw):
    kw.setdefault("n_layers", 2)
    kw.setdefault("kv_dim", 8)
    kw.setdefault("num_pages", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_pages_per_seq", 8)
    kw.setdefault("prefix_cache", True)
    return PagedKVCache(**kw)


# a 24-token shared "system prompt" plus per-user 3-token suffixes
SYS = list(range(3, 27))


def user_prompts(n, base=40):
    return [SYS + [base + i, base + i + 1, base + i + 2] for i in range(n)]


# -- index + allocator units (no jax) -----------------------------------------


def test_match_caps_below_whole_prompt():
    """A fully-cached prompt still recomputes its final token: the match
    limit is (len-1)//page_size pages, so >= 1 suffix token always remains
    for the chunk that samples the request's first output."""
    assert PrefixIndex.max_match_pages(12, 4) == 2
    assert PrefixIndex.max_match_pages(13, 4) == 3
    assert PrefixIndex.max_match_pages(4, 4) == 0
    assert PrefixIndex.max_match_pages(3, 4) == 0
    c = make_cache()
    prompt = list(range(1, 13))  # 12 tokens = 3 exact pages
    c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, len(prompt))  # registers all 3
    assert len(c.prefix) == 3
    # ...but an identical prompt may only alias 2 of them
    assert c.peek_hit_tokens("a", prompt) == 8


def test_alias_refcount_and_physical_free_exactly_once():
    """Reserve→commit→alias: shared pages carry one ref per slot plus the
    index's; release() reports only PHYSICAL frees, so a page never
    double-frees and never leaks."""
    c = make_cache()
    total = c.free_pages
    prompt = list(range(1, 13))
    p0 = c.reserve(0, 16, tenant="a", prompt=prompt)  # 4 fresh pages
    assert c.hit_tokens(0) == 0
    c.commit_prefix(0, "a", prompt, len(prompt))
    p1 = c.reserve(1, 16, tenant="a", prompt=prompt)
    assert c.hit_tokens(1) == 8
    assert p1[:2] == p0[:2] and p1[2] not in p0, "2 aliased + private CoW"
    assert c.page_refcount(p0[0]) == 3  # slot0 + slot1 + index
    # slot0 out: pages 0-2 still referenced -> only its private page 3 frees
    assert c.release(0) == 1
    # slot1 out: its 2 fresh pages free; aliased pages stay cached (rc 1)
    assert c.release(1) == 2
    assert c.prefix_stats()["prefix_pages_unreferenced"] == 3
    # flush drops the index's refs -> everything home, counted exactly once
    assert c.flush_prefix() == 3
    assert c.free_pages == total


def test_uncommitted_pages_never_register():
    """Registration follows COMMITTED tokens only: a slot mid-prefill
    exposes exactly its committed full pages, never pages whose KV is still
    being written."""
    c = make_cache()
    prompt = list(range(1, 13))
    c.reserve(0, 16, tenant="a", prompt=prompt)
    assert c.commit_prefix(0, "a", prompt, 3) == 0   # no full page yet
    assert c.commit_prefix(0, "a", prompt, 6) == 1   # page 0 committed
    assert c.peek_hit_tokens("a", prompt) == 4
    assert c.commit_prefix(0, "a", prompt, 6) == 0   # idempotent
    assert c.commit_prefix(0, "a", prompt, 12) == 2  # the rest
    assert c.peek_hit_tokens("a", prompt) == 8


def test_peek_is_pure():
    """The admission-pricing peek mutates nothing: no recency bump, no
    counters, no root creation — pricing must not perturb eviction order."""
    c = make_cache()
    prompt = list(range(1, 13))
    c.peek_hit_tokens("ghost", prompt)
    idx = c.prefix
    assert idx.lookups == 0 and idx._roots == {} and idx._tick == 0
    c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, 12)
    tick0 = idx._tick
    c.peek_hit_tokens("a", prompt)
    assert idx._tick == tick0 and idx.hits == 0


def test_lru_eviction_under_pool_pressure():
    """Unreferenced cached pages are capacity, not occupancy: can_reserve
    counts them, reserve LRU-evicts them when the free list runs short, and
    a just-matched prefix can never evict itself (its refs go up first)."""
    c = make_cache(num_pages=12)
    prompt = list(range(1, 13))
    c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, 12)
    c.release(0)
    assert c.free_pages == 8 and c.prefix_stats()["prefix_pages_cached"] == 3
    c.reserve(1, 24, tenant="b", prompt=list(range(50, 56)))  # 6 fresh
    assert c.can_reserve(20), "2 free + 3 evictable must admit 5 pages"
    c.reserve(2, 20, tenant="b", prompt=list(range(60, 66)))
    s = c.prefix_stats()
    assert s["prefix_evictions"] == 3 and s["prefix_pages_cached"] == 0
    c.release(1), c.release(2)
    assert c.free_pages == 11


def test_matched_prefix_survives_same_reserve_eviction():
    """The eviction loop inside reserve must not free the pages the SAME
    reservation just matched: they are increffed before eviction runs."""
    c = make_cache(num_pages=10, max_pages_per_seq=9)
    prompt = list(range(1, 13))
    c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, 12)
    c.release(0)  # 3 cached (1 unreachable for the next match), 5 free
    # 2 aliased + 7 fresh needed, 5 free -> evicts the non-matched cached
    # page(s); the 2 matched pages must survive
    pages = c.reserve(1, 36, tenant="a", prompt=prompt)
    assert c.hit_tokens(1) == 8
    assert c.page_refcount(pages[0]) >= 2
    c.release(1)
    c.flush_prefix()
    assert c.free_pages == 9


def test_cache_size_cap_evicts_lru():
    """--prefix_cache_pages bounds the index: registration past the cap
    LRU-evicts unreferenced entries (best-effort — live aliases pin)."""
    c = make_cache(num_pages=32, prefix_cache_pages=2)
    p1, p2 = list(range(1, 13)), list(range(20, 32))
    c.reserve(0, 16, tenant="a", prompt=p1)
    c.commit_prefix(0, "a", p1, 12)
    c.release(0)
    assert c.prefix_stats()["prefix_pages_cached"] == 2  # capped already
    c.reserve(1, 16, tenant="a", prompt=p2)
    c.commit_prefix(1, "a", p2, 12)
    c.release(1)
    s = c.prefix_stats()
    assert s["prefix_pages_cached"] == 2 and s["prefix_evictions"] >= 3
    c.flush_prefix()
    assert c.free_pages == 31


def test_reset_invalidates_index_no_stale_aliases():
    """Crash recovery: reset() rebuilds the allocator AND drops the index —
    every cached page id pointed into the dead pool, so a replayed request
    must miss, re-prefill, and re-populate."""
    c = make_cache()
    total = c.free_pages
    prompt = list(range(1, 13))
    c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, 12)
    hits0 = c.prefix.hits
    c.reset()
    assert c.free_pages == total and len(c.prefix) == 0
    c.reserve(0, 16, tenant="a", prompt=prompt)
    assert c.hit_tokens(0) == 0, "no stale aliases into the re-init pool"
    c.commit_prefix(0, "a", prompt, 12)
    c.reserve(1, 16, tenant="a", prompt=prompt)
    assert c.hit_tokens(1) == 8, "the cache re-populates after recovery"
    assert c.prefix.hits > hits0, "telemetry is cumulative across resets"


def test_tenant_isolation_unit():
    """Identical token streams under different tenants walk disjoint
    chains: tenant b's reserve matches nothing and registers its own
    pages."""
    c = make_cache()
    prompt = list(range(1, 13))
    pa = c.reserve(0, 16, tenant="a", prompt=prompt)
    c.commit_prefix(0, "a", prompt, 12)
    pb = c.reserve(1, 16, tenant="b", prompt=prompt)
    assert c.hit_tokens(1) == 0, "cross-tenant aliasing is forbidden"
    assert not set(pa) & set(pb)
    c.commit_prefix(1, "b", prompt, 12)
    # now each tenant hits its OWN chain
    c.reserve(2, 16, tenant="a", prompt=prompt)
    c.reserve(3, 16, tenant="b", prompt=prompt)
    assert c.slot_pages(2)[:2] == pa[:2]
    assert c.slot_pages(3)[:2] == pb[:2]
    by_tenant = c.prefix_stats()["prefix_hit_rate_by_tenant"]
    assert by_tenant["a"] > 0 and by_tenant["b"] > 0


def test_adaptive_k_rule_pure():
    """next_draft_k (ROADMAP 1a): additive-increase on full acceptance,
    fall-to-observed on divergence, clamped to [1, k_max] — and a pure
    function (same inputs, same K, forever: the bitwise-replay contract)."""
    assert next_draft_k(3, 8, drafted=3, accepted=3) == 4   # grow
    assert next_draft_k(8, 8, drafted=8, accepted=8) == 8   # capped
    assert next_draft_k(6, 8, drafted=6, accepted=2) == 3   # fall to obs+1
    assert next_draft_k(6, 8, drafted=6, accepted=0) == 1   # floor
    assert next_draft_k(4, 8, drafted=0, accepted=0) == 4   # no evidence
    assert next_draft_k(0, 8, drafted=2, accepted=2) == 2   # clamp then grow
    for args in [(3, 8, 3, 3), (6, 8, 6, 2)]:
        assert next_draft_k(*args) == next_draft_k(*args)


# -- end-to-end token identity ------------------------------------------------


def run_prompts(model_and_params, prompts, prefix, temp=0.0, max_new=6, **kw):
    s = make_session(model_and_params, prefix_cache=prefix, **kw)
    handles = []
    for i, p in enumerate(prompts):
        handles.append(
            s.submit(p, max_new_tokens=max_new, tenant="t0",
                     temperature=temp, seed=1000 + i)
        )
        # drain between submits so later prompts actually see a warm cache
        s.run_until_idle()
    toks = [h.result(timeout=30) for h in handles]
    return toks, s


@pytest.mark.parametrize("temp", [0.0, 0.8])
def test_tokens_bitwise_cache_on_off(model_and_params, temp):
    """The acceptance bit: greedy AND seeded-sampled tokens are bitwise
    identical cache-on vs cache-off, across chunk-routed (long) and
    whole-prompt-routed (short) prompts — with ONE decode signature and a
    real hit rate (the cache demonstrably engaged)."""
    prompts = user_prompts(4) + [[7, 8, 9], [7, 8, 9]]  # long×4 + short×2
    ref, _ = run_prompts(model_and_params, prompts, prefix=False, temp=temp)
    out, s = run_prompts(model_and_params, prompts, prefix=True, temp=temp)
    assert out == ref, "the cache must be result-invisible"
    st = s.stats()
    assert st["prefix_hit_rate"] > 0.3 and st["prefix_pages_shared"] >= 18
    assert st["decode_shape_signatures"] == 1
    assert st["prefix_cache_enabled"] is True


def test_short_prompt_whole_path_registers_then_hits(model_and_params):
    """A short prompt prefills whole (one padded forward) yet still
    registers its full pages; an identical later prompt hits and routes
    through the chunked path for its suffix only."""
    prompts = [[7, 8, 9, 10, 11], [7, 8, 9, 10, 11]]
    out, s = run_prompts(model_and_params, prompts, prefix=True)
    assert out[0] == out[1]
    st = s.stats()
    assert st["prefix_hits"] == 1 and st["prefix_hit_tokens"] == 4
    assert st["prefill_chunks_committed"] == 1, (
        "the second prompt prefills only its 1-token suffix"
    )


def test_zero_page_leak_after_churn(model_and_params):
    """Alias/evict/retire churn across tenants ends with every page home
    after a flush — the leak gate."""
    s = make_session(model_and_params)
    total = s.cache.free_pages
    for tenant in ("a", "b"):
        for p in user_prompts(3):
            s.submit(p, max_new_tokens=4, tenant=tenant)
        s.run_until_idle()
    for h_p in user_prompts(2, base=60):
        s.submit(h_p, max_new_tokens=4, tenant="a")
    s.run_until_idle()
    assert s.scheduler.completed == 8
    s.cache.flush_prefix()
    assert s.cache.free_pages == total, "zero page leak after churn"


# -- satellite 2: cancel-mid-decode with a shared prefix ----------------------


def test_cancel_mid_decode_shared_prefix_counts_physical_frees(
    model_and_params
):
    """Two slots share a prefix; one is cancelled mid-decode. The recycle
    counter must count the cancelled slot's PHYSICAL frees exactly once —
    shared pages only decref — and nothing the survivor or the cache still
    references may hit the free list."""
    s = make_session(model_and_params)
    total = s.cache.free_pages
    warm = s.submit(SYS + [40, 41, 42], max_new_tokens=2, tenant="t0")
    s.run_until_idle()
    assert warm.done
    a = s.submit(SYS + [50, 51, 52], max_new_tokens=12, tenant="t0")
    b = s.submit(SYS + [60, 61, 62], max_new_tokens=12, tenant="t0")
    # admit + prefill both, decode a few steps, then cancel `a` mid-decode
    for _ in range(8):
        s.step()
    assert a.status == a.RUNNING and b.status == b.RUNNING
    slot_a = next(
        slot for slot, act in s.scheduler.active_slots()
        if act.handle.request_id == a.request_id
    )
    pages_a = s.cache.slot_pages(slot_a)
    shared_a = [p for p in pages_a if s.cache.page_refcount(p) > 1]
    private_a = [p for p in pages_a if s.cache.page_refcount(p) == 1]
    assert shared_a and private_a, "the slot must genuinely share pages"
    recycled0 = s.scheduler.pages_recycled_on_cancel
    free0 = s.cache.free_pages
    assert a.cancel()
    s.step()
    assert a.done and a.finish_reason == "cancelled"
    freed = s.scheduler.pages_recycled_on_cancel - recycled0
    assert freed == len(private_a), (
        "recycle counter = physical frees only: shared pages just decref"
    )
    assert s.cache.free_pages == free0 + freed
    for p in shared_a:
        assert s.cache.page_refcount(p) >= 1, "no double-free of shared pages"
    s.run_until_idle()
    assert b.done and b.status == b.DONE, "the survivor decodes to the end"
    s.cache.flush_prefix()
    assert s.cache.free_pages == total


# -- satellite 3: crash recovery with a warm cache ----------------------------


@pytest.mark.chaos
@pytest.mark.timeout(120)
@pytest.mark.parametrize(
    "site,spec",
    [
        ("decode_raise", "decode_raise:step=3"),
        ("page_exhaust", "page_exhaust:step=0"),
    ],
)
def test_crash_recovery_with_warm_cache_bitwise(
    model_and_params, site, spec, monkeypatch
):
    """Seeded faults against a WARM cache: the supervisor restarts the
    engine, reset() invalidates the index (no stale aliases into the dead
    pool), replayed requests are token-bitwise vs unfaulted, the free list
    is whole, and the cache re-populates for post-restart traffic."""
    monkeypatch.setenv("PADDLE_TPU_SERVING_STALL_S", "1")
    prompts = user_prompts(4)

    clean = make_session(model_and_params, prefix_cache=True)
    ref_handles = [clean.submit(p, 8, tenant="t0") for p in prompts]
    clean.run_until_idle()
    ref = [h.tokens for h in ref_handles]

    s = make_session(
        model_and_params, prefix_cache=True,
        engine_stall_timeout_s=0.3, engine_restart_max=5,
    )
    total_free = s.cache.free_pages
    # warm the cache BEFORE the faults arm: the shared prefix is cached and
    # later admissions genuinely alias it when the fault fires
    w = s.submit(SYS + [80, 81, 82], 2, tenant="t0")
    s.run_until_idle()
    assert w.done and s.stats()["prefix_pages_cached"] > 0
    with faults.inject(spec, seed=0) as inj:
        s.serve_forever()
        handles = [s.submit(p, 8, tenant="t0", deadline_s=60.0)
                   for p in prompts]
        deadline = time.monotonic() + 90
        for h in handles:
            assert h._event.wait(max(0.1, deadline - time.monotonic())), (
                f"request {h.request_id} never completed after {site}"
            )
        fired = dict(inj.fired)
    s.stop()
    assert fired.get(site, 0) >= 1, "the seeded fault must actually fire"
    assert s.engine_restarts >= 1, "the supervisor must have recovered"
    assert [h.tokens for h in handles] == ref, (
        "warm-cache replay must be result-transparent"
    )
    st = s.stats()
    assert st["prefix_pages_cached"] > 0, "the cache re-populated"
    s.cache.flush_prefix()
    assert s.cache.free_pages == total_free, "zero page leak after recovery"


# -- satellite 4: tenant isolation end-to-end ---------------------------------


def test_tenant_isolation_end_to_end(model_and_params):
    """Identical prompts across tenants never alias: tenant b's first
    submission is a cold miss even though tenant a just cached the same
    bytes, and stats() reports per-tenant hit rates."""
    s = make_session(model_and_params)
    p = SYS + [40, 41, 42]
    ha1 = s.submit(p, 4, tenant="a")
    s.run_until_idle()
    hb1 = s.submit(p, 4, tenant="b")
    s.run_until_idle()
    ha2 = s.submit(p, 4, tenant="a")
    hb2 = s.submit(p, 4, tenant="b")
    s.run_until_idle()
    assert ha1.tokens == hb1.tokens == ha2.tokens == hb2.tokens
    st = s.stats()
    by_tenant = st["prefix_hit_rate_by_tenant"]
    tokens_by_tenant = st["prefix_hit_tokens_by_tenant"]
    # each tenant hit only its OWN earlier registration: one cold miss each,
    # one full hit each -> identical per-tenant counters, no cross-leak
    assert tokens_by_tenant["a"] == tokens_by_tenant["b"] == 24
    assert 0 < by_tenant["a"] == by_tenant["b"] < 1


# -- speculation composition --------------------------------------------------


def test_speculation_composes_with_prefix_cache(model_and_params):
    """Speculation's +K headroom and aliased prefix pages coexist: repeated
    repetitive prompts hit the cache AND speculate, tokens stay bitwise vs
    cache-off, trims only ever free private tail pages (no double-free),
    and the pool is whole after flush."""
    prompt = SYS + [5, 9, 11] * 5  # shared prefix + a draftable cyclic tail
    ref, rs = run_prompts(
        model_and_params, [prompt, prompt], prefix=False, speculate_k=4,
        max_new=12,
    )
    out, s = run_prompts(
        model_and_params, [prompt, prompt], prefix=True, speculate_k=4,
        max_new=12,
    )
    assert out == ref
    st = s.stats()
    assert st["spec_rounds"] > 0 and st["prefix_hits"] >= 1
    assert 1.0 <= st["spec_effective_k"] <= 4.0
    assert st["verify_shape_signatures"] <= 1
    total = s.cache.num_pages - 1
    s.cache.flush_prefix()
    assert s.cache.free_pages == total, "no leak from headroom + aliasing"


def test_adaptive_k_converges_on_acceptance(model_and_params):
    """On a perfectly cyclic stream (acceptance ~1) the effective K grows
    past its floor: spec_effective_k ends ABOVE the all-miss floor of 1 and
    the draft budget is actually being used."""
    prompt = [5, 9, 11, 17] * 4
    out, s = run_prompts(model_and_params, [prompt], prefix=False,
                         speculate_k=6, max_new_limit=24, max_new=20)
    st = s.stats()
    assert st["spec_rounds"] >= 2
    assert st["spec_effective_k"] > 1.5, (
        f"adaptive K never grew: {st['spec_effective_k']}"
    )
    assert st["spec_acceptance_rate"] > 0.3
