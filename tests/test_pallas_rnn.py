"""Fused pallas LSTM/GRU kernels vs the lax.scan oracle (the CPU-oracle
cross-check idiom of SURVEY §4: test_matrixCompare / Compare2Function run the
same op on both implementations and assert near-equality — here scan vs
pallas-interpret, values AND grads)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import rnn
from paddle_tpu.ops.pallas.rnn_kernels import gru_seq_fused, lstm_seq_fused


@pytest.fixture(autouse=True)
def _force_interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")


def _data(seed=0, b=4, t=6, h=8, gates=4):
    rs = np.random.RandomState(seed)
    proj = jnp.asarray(rs.randn(b, t, gates * h), jnp.float32)
    lens = np.array([t, 3, 5, 2][:b])
    mask = jnp.asarray(np.arange(t)[None, :] < lens[:, None], jnp.float32)
    return proj, mask


def _tm(x):
    return jnp.swapaxes(x, 0, 1)


class TestLstmFused:
    def setup_method(self, _):
        rs = np.random.RandomState(1)
        self.h = 8
        self.whh = jnp.asarray(rs.randn(self.h, 4 * self.h) * 0.1, jnp.float32)
        self.bias = jnp.asarray(rs.randn(4 * self.h) * 0.1, jnp.float32)
        self.p = rnn.LstmParams(w_hh=self.whh, bias=self.bias)

    def test_forward_matches_scan(self):
        proj, mask = _data()
        b = proj.shape[0]
        hs_ref, hl_ref, cl_ref = rnn.lstm_scan(proj, mask, self.p)
        z = jnp.zeros((b, self.h))
        hs, hl, cl = lstm_seq_fused(
            _tm(proj), _tm(mask)[:, :, None], self.whh, self.bias, z, z
        )
        np.testing.assert_allclose(_tm(hs), hs_ref, atol=5e-4)
        np.testing.assert_allclose(hl, hl_ref, atol=5e-4)
        np.testing.assert_allclose(cl, cl_ref, atol=5e-4)

    def test_grads_match_scan(self):
        proj, mask = _data()
        b = proj.shape[0]
        z = jnp.zeros((b, self.h))
        mtm = _tm(mask)[:, :, None]

        def loss_ref(whh, bias, proj, h0, c0):
            hs, hl, cl = rnn.lstm_scan(
                proj, mask, rnn.LstmParams(w_hh=whh, bias=bias), h0=h0, c0=c0
            )
            return jnp.sum(hs**2) + jnp.sum(hl * cl)

        def loss_fused(whh, bias, proj, h0, c0):
            hs, hl, cl = lstm_seq_fused(_tm(proj), mtm, whh, bias, h0, c0)
            return jnp.sum(hs**2) + jnp.sum(hl * cl)

        argnums = (0, 1, 2, 3, 4)
        g_ref = jax.grad(loss_ref, argnums)(self.whh, self.bias, proj, z, z)
        g_fus = jax.grad(loss_fused, argnums)(self.whh, self.bias, proj, z, z)
        for name, a, c in zip(["dW", "db", "dproj", "dh0", "dc0"], g_ref, g_fus):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=2e-3, atol=5e-3,
                err_msg=name,
            )

    def test_scan_dispatch_equivalence(self, monkeypatch):
        """lstm_scan with the fused path forced must equal the pure scan,
        including reverse mode."""
        proj, mask = _data(seed=3)
        for reverse in (False, True):
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
            ref = rnn.lstm_scan(proj, mask, self.p, reverse=reverse)
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
            fus = rnn.lstm_scan(proj, mask, self.p, reverse=reverse)
            for a, c in zip(ref, fus):
                np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=5e-4)


class TestGruFused:
    def setup_method(self, _):
        rs = np.random.RandomState(2)
        self.h = 8
        self.wzr = jnp.asarray(rs.randn(self.h, 2 * self.h) * 0.1, jnp.float32)
        self.wc = jnp.asarray(rs.randn(self.h, self.h) * 0.1, jnp.float32)
        self.bias = jnp.asarray(rs.randn(3 * self.h) * 0.1, jnp.float32)
        self.p = rnn.GruParams(w_hzr=self.wzr, w_hc=self.wc, bias=self.bias)

    def test_forward_matches_scan(self):
        proj, mask = _data(gates=3)
        b = proj.shape[0]
        hs_ref, hl_ref = rnn.gru_scan(proj, mask, self.p)
        hs, hl = gru_seq_fused(
            _tm(proj), _tm(mask)[:, :, None], self.wzr, self.wc, self.bias,
            jnp.zeros((b, self.h)),
        )
        np.testing.assert_allclose(_tm(hs), hs_ref, atol=5e-4)
        np.testing.assert_allclose(hl, hl_ref, atol=5e-4)

    def test_grads_match_scan(self):
        proj, mask = _data(gates=3)
        b = proj.shape[0]
        z = jnp.zeros((b, self.h))
        mtm = _tm(mask)[:, :, None]

        def loss_ref(wzr, wc, bias, proj, h0):
            hs, hl = rnn.gru_scan(
                proj, mask, rnn.GruParams(w_hzr=wzr, w_hc=wc, bias=bias), h0=h0
            )
            return jnp.sum(hs**2) + jnp.sum(hl)

        def loss_fused(wzr, wc, bias, proj, h0):
            hs, hl = gru_seq_fused(_tm(proj), mtm, wzr, wc, bias, h0)
            return jnp.sum(hs**2) + jnp.sum(hl)

        argnums = (0, 1, 2, 3, 4)
        g_ref = jax.grad(loss_ref, argnums)(self.wzr, self.wc, self.bias, proj, z)
        g_fus = jax.grad(loss_fused, argnums)(self.wzr, self.wc, self.bias, proj, z)
        for name, a, c in zip(["dWzr", "dWc", "db", "dproj", "dh0"], g_ref, g_fus):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=2e-3, atol=5e-3,
                err_msg=name,
            )

    def test_scan_dispatch_equivalence(self, monkeypatch):
        proj, mask = _data(seed=5, gates=3)
        for reverse in (False, True):
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
            ref = rnn.gru_scan(proj, mask, self.p, reverse=reverse)
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
            fus = rnn.gru_scan(proj, mask, self.p, reverse=reverse)
            for a, c in zip(ref, fus):
                np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=5e-4)


def test_lstm_layer_end_to_end_with_fused(monkeypatch):
    """The Lstm layer trains with the fused kernel active (grads flow)."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
    from paddle_tpu.nn import recurrent as R
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    x = L.Data("x", shape=(8,), is_seq=True)
    lstm = R.Lstm(x, 2)  # lstmemory: input width must be 4*size
    net = Network([lstm])
    rs = np.random.RandomState(0)
    batch = {
        "x": rs.randn(4, 6, 8).astype(np.float32),
        "x.lengths": np.array([6, 3, 5, 2], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)

    def loss(p):
        outs, _ = net.apply(p, states, batch)
        return jnp.sum(outs[lstm.name].value ** 2)

    g = jax.grad(loss)(params)
    total = sum(float(jnp.abs(v).sum()) for v in g.values())
    assert np.isfinite(total) and total > 0


class TestAttentionFused:
    """Fused scaled-dot attention forward (ISSUE 9) vs the jnp oracle in
    ops/attention.dot_product_attention — forward AND end-to-end grads (the
    fused op's backward is the oracle's exact vjp, but the composition must
    still be verified through the custom_vjp seam)."""

    def setup_method(self, _):
        rs = np.random.RandomState(7)
        b, tq, tk, d, dv = 3, 5, 7, 8, 6
        self.q = jnp.asarray(rs.randn(b, tq, d), jnp.float32)
        self.k = jnp.asarray(rs.randn(b, tk, d), jnp.float32)
        self.v = jnp.asarray(rs.randn(b, tk, dv), jnp.float32)
        self.mask_kv = jnp.asarray(rs.rand(b, 1, tk) > 0.3, jnp.float32)
        self.mask_full = jnp.asarray(rs.rand(b, tq, tk) > 0.3, jnp.float32)

    def _both(self, **kw):
        from paddle_tpu.ops.attention import dot_product_attention

        ref = dot_product_attention(self.q, self.k, self.v, fused=False, **kw)
        fus = dot_product_attention(self.q, self.k, self.v, fused=True, **kw)
        return ref, fus

    def test_forward_matches_oracle(self):
        for kw in ({}, {"mask": self.mask_kv}, {"mask": self.mask_full},
                   {"mask": self.mask_kv, "scale": 0.5}):
            ref, fus = self._both(**kw)
            np.testing.assert_allclose(
                np.asarray(ref), np.asarray(fus), atol=1e-5, err_msg=str(kw)
            )

    def test_fully_masked_row_degrades_like_oracle(self):
        mask = self.mask_full.at[1, 2, :].set(0.0)
        ref, fus = self._both(mask=mask)
        assert np.isfinite(np.asarray(fus)).all()
        np.testing.assert_allclose(np.asarray(ref), np.asarray(fus), atol=1e-5)

    def test_grads_match_oracle(self):
        from paddle_tpu.ops.attention import dot_product_attention

        def loss(fused):
            def f(q, k, v):
                out = dot_product_attention(
                    q, k, v, mask=self.mask_full, fused=fused
                )
                return jnp.sum(out ** 2)

            return jax.grad(f, argnums=(0, 1, 2))(self.q, self.k, self.v)

        for name, a, c in zip("qkv", loss(False), loss(True)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=1e-4, atol=1e-5,
                err_msg=f"d{name}",
            )

    def test_bf16_inputs_f32_softmax(self):
        """bf16 q/k/v: output keeps v's dtype and tracks the f32-softmax
        oracle to bf16 resolution (the reductions never run in bf16)."""
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (self.q, self.k, self.v))
        from paddle_tpu.ops.attention import dot_product_attention

        ref = dot_product_attention(qb, kb, vb, mask=self.mask_kv, fused=False)
        fus = dot_product_attention(qb, kb, vb, mask=self.mask_kv, fused=True)
        assert fus.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(ref, np.float32), np.asarray(fus, np.float32),
            atol=2e-2,
        )

    def test_auto_dispatch_honors_pallas_flag(self, monkeypatch):
        """fused=None routes via ops.pallas.enabled(): off on CPU default,
        on under interpret; a traced (non-static) scale falls back to jnp."""
        from paddle_tpu.ops import attention as A

        monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
        assert not A._attn_fuse_ok(self.q, self.k, self.v, None)
        monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
        assert A._attn_fuse_ok(self.q, self.k, self.v, None)
        assert not A._attn_fuse_ok(
            self.q, self.k, self.v, jnp.asarray(0.5)
        )
        monkeypatch.setenv("PADDLE_TPU_FUSED_ATTN_MAX", "10")
        assert not A._attn_fuse_ok(self.q, self.k, self.v, None)

    def test_neg_inf_constant_in_lockstep(self):
        from paddle_tpu.ops import sequence as seq_ops
        from paddle_tpu.ops.pallas import rnn_kernels

        assert rnn_kernels._ATTN_NEG_INF == seq_ops.NEG_INF
