"""Fused pallas LSTM/GRU kernels vs the lax.scan oracle (the CPU-oracle
cross-check idiom of SURVEY §4: test_matrixCompare / Compare2Function run the
same op on both implementations and assert near-equality — here scan vs
pallas-interpret, values AND grads)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import rnn
from paddle_tpu.ops.pallas.rnn_kernels import gru_seq_fused, lstm_seq_fused


@pytest.fixture(autouse=True)
def _force_interpret(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")


def _data(seed=0, b=4, t=6, h=8, gates=4):
    rs = np.random.RandomState(seed)
    proj = jnp.asarray(rs.randn(b, t, gates * h), jnp.float32)
    lens = np.array([t, 3, 5, 2][:b])
    mask = jnp.asarray(np.arange(t)[None, :] < lens[:, None], jnp.float32)
    return proj, mask


def _tm(x):
    return jnp.swapaxes(x, 0, 1)


class TestLstmFused:
    def setup_method(self, _):
        rs = np.random.RandomState(1)
        self.h = 8
        self.whh = jnp.asarray(rs.randn(self.h, 4 * self.h) * 0.1, jnp.float32)
        self.bias = jnp.asarray(rs.randn(4 * self.h) * 0.1, jnp.float32)
        self.p = rnn.LstmParams(w_hh=self.whh, bias=self.bias)

    def test_forward_matches_scan(self):
        proj, mask = _data()
        b = proj.shape[0]
        hs_ref, hl_ref, cl_ref = rnn.lstm_scan(proj, mask, self.p)
        z = jnp.zeros((b, self.h))
        hs, hl, cl = lstm_seq_fused(
            _tm(proj), _tm(mask)[:, :, None], self.whh, self.bias, z, z
        )
        np.testing.assert_allclose(_tm(hs), hs_ref, atol=5e-4)
        np.testing.assert_allclose(hl, hl_ref, atol=5e-4)
        np.testing.assert_allclose(cl, cl_ref, atol=5e-4)

    def test_grads_match_scan(self):
        proj, mask = _data()
        b = proj.shape[0]
        z = jnp.zeros((b, self.h))
        mtm = _tm(mask)[:, :, None]

        def loss_ref(whh, bias, proj, h0, c0):
            hs, hl, cl = rnn.lstm_scan(
                proj, mask, rnn.LstmParams(w_hh=whh, bias=bias), h0=h0, c0=c0
            )
            return jnp.sum(hs**2) + jnp.sum(hl * cl)

        def loss_fused(whh, bias, proj, h0, c0):
            hs, hl, cl = lstm_seq_fused(_tm(proj), mtm, whh, bias, h0, c0)
            return jnp.sum(hs**2) + jnp.sum(hl * cl)

        argnums = (0, 1, 2, 3, 4)
        g_ref = jax.grad(loss_ref, argnums)(self.whh, self.bias, proj, z, z)
        g_fus = jax.grad(loss_fused, argnums)(self.whh, self.bias, proj, z, z)
        for name, a, c in zip(["dW", "db", "dproj", "dh0", "dc0"], g_ref, g_fus):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=2e-3, atol=5e-3,
                err_msg=name,
            )

    def test_scan_dispatch_equivalence(self, monkeypatch):
        """lstm_scan with the fused path forced must equal the pure scan,
        including reverse mode."""
        proj, mask = _data(seed=3)
        for reverse in (False, True):
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
            ref = rnn.lstm_scan(proj, mask, self.p, reverse=reverse)
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
            fus = rnn.lstm_scan(proj, mask, self.p, reverse=reverse)
            for a, c in zip(ref, fus):
                np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=5e-4)


class TestGruFused:
    def setup_method(self, _):
        rs = np.random.RandomState(2)
        self.h = 8
        self.wzr = jnp.asarray(rs.randn(self.h, 2 * self.h) * 0.1, jnp.float32)
        self.wc = jnp.asarray(rs.randn(self.h, self.h) * 0.1, jnp.float32)
        self.bias = jnp.asarray(rs.randn(3 * self.h) * 0.1, jnp.float32)
        self.p = rnn.GruParams(w_hzr=self.wzr, w_hc=self.wc, bias=self.bias)

    def test_forward_matches_scan(self):
        proj, mask = _data(gates=3)
        b = proj.shape[0]
        hs_ref, hl_ref = rnn.gru_scan(proj, mask, self.p)
        hs, hl = gru_seq_fused(
            _tm(proj), _tm(mask)[:, :, None], self.wzr, self.wc, self.bias,
            jnp.zeros((b, self.h)),
        )
        np.testing.assert_allclose(_tm(hs), hs_ref, atol=5e-4)
        np.testing.assert_allclose(hl, hl_ref, atol=5e-4)

    def test_grads_match_scan(self):
        proj, mask = _data(gates=3)
        b = proj.shape[0]
        z = jnp.zeros((b, self.h))
        mtm = _tm(mask)[:, :, None]

        def loss_ref(wzr, wc, bias, proj, h0):
            hs, hl = rnn.gru_scan(
                proj, mask, rnn.GruParams(w_hzr=wzr, w_hc=wc, bias=bias), h0=h0
            )
            return jnp.sum(hs**2) + jnp.sum(hl)

        def loss_fused(wzr, wc, bias, proj, h0):
            hs, hl = gru_seq_fused(_tm(proj), mtm, wzr, wc, bias, h0)
            return jnp.sum(hs**2) + jnp.sum(hl)

        argnums = (0, 1, 2, 3, 4)
        g_ref = jax.grad(loss_ref, argnums)(self.wzr, self.wc, self.bias, proj, z)
        g_fus = jax.grad(loss_fused, argnums)(self.wzr, self.wc, self.bias, proj, z)
        for name, a, c in zip(["dWzr", "dWc", "db", "dproj", "dh0"], g_ref, g_fus):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(c), rtol=2e-3, atol=5e-3,
                err_msg=name,
            )

    def test_scan_dispatch_equivalence(self, monkeypatch):
        proj, mask = _data(seed=5, gates=3)
        for reverse in (False, True):
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
            ref = rnn.gru_scan(proj, mask, self.p, reverse=reverse)
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
            fus = rnn.gru_scan(proj, mask, self.p, reverse=reverse)
            for a, c in zip(ref, fus):
                np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=5e-4)


def test_lstm_layer_end_to_end_with_fused(monkeypatch):
    """The Lstm layer trains with the fused kernel active (grads flow)."""
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "interpret")
    from paddle_tpu.nn import recurrent as R
    from paddle_tpu.nn import layers as L
    from paddle_tpu.nn.graph import Network, reset_name_scope

    reset_name_scope()
    x = L.Data("x", shape=(8,), is_seq=True)
    lstm = R.Lstm(x, 2)  # lstmemory: input width must be 4*size
    net = Network([lstm])
    rs = np.random.RandomState(0)
    batch = {
        "x": rs.randn(4, 6, 8).astype(np.float32),
        "x.lengths": np.array([6, 3, 5, 2], np.int32),
    }
    params, states = net.init(jax.random.PRNGKey(0), batch)

    def loss(p):
        outs, _ = net.apply(p, states, batch)
        return jnp.sum(outs[lstm.name].value ** 2)

    g = jax.grad(loss)(params)
    total = sum(float(jnp.abs(v).sum()) for v in g.values())
    assert np.isfinite(total) and total > 0
