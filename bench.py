"""Benchmark driver: ResNet-50 ImageNet training throughput on the available
accelerator (the BASELINE.json north-star metric: images/sec/chip and MFU vs
the ≥50% target).

Prints exactly ONE JSON line no matter what happens:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = achieved_MFU / 0.50 (the north-star MFU target), so 1.0 means
"hit the 50%-MFU goal"; extra keys are informational. On any failure the line
still appears, with an "error" key describing what went wrong.

Resilience (round-1 postmortem: the TPU tunnel backend raised UNAVAILABLE and
the script died with rc=1 and no JSON): backend init is probed in a child
process with a hard timeout and retried with backoff; if the accelerator never
comes up we fall back to the CPU backend with small shapes so a measured
number is still emitted, flagged with "error".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import traceback

_PROBE_SNIPPET = (
    "import jax, json, sys;"
    "d = jax.devices();"
    "sys.stdout.write(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
)


def _emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _error_payload(msg: str) -> dict:
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": msg[-800:],
    }


_PROBE_MEMO: list = []  # in-process memo: [verdict] once probed/cached


def _probe_cache_path() -> str | None:
    """Cross-invocation cache location for the probe verdict. BENCH_r05:
    every metric re-probed a dead tunnel — 3 runs × 3 retries × 240 s = 12
    minutes of guaranteed timeouts. Set BENCH_PROBE_CACHE=off to disable."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    path = os.environ.get(
        "BENCH_PROBE_CACHE",
        # per-uid filename: on a shared host another user's verdict (or an
        # unwritable sticky-bit file) must not leak into this run
        os.path.join(
            tempfile.gettempdir(), f"paddle_tpu_bench_probe_{uid}.json"
        ),
    )
    return None if path.lower() in ("", "off", "none", "0") else path


def probe_backend() -> dict | None:
    """The cached TPU-backend probe verdict: {'platform', 'n'} when the
    backend came up, None when it is down. Probes at most ONCE per run —
    in-process calls reuse the memo, and sibling invocations within
    BENCH_PROBE_CACHE_TTL (default 3600 s) reuse the on-disk verdict file,
    so a dead tunnel costs its timeout budget a single time."""
    if _PROBE_MEMO:
        return _PROBE_MEMO[0]
    path = _probe_cache_path()
    try:
        ttl = float(os.environ.get("BENCH_PROBE_CACHE_TTL", "3600"))
    except ValueError:  # garbled env var must not kill the whole bench
        sys.stderr.write("[bench] bad BENCH_PROBE_CACHE_TTL, using 3600s\n")
        ttl = 3600.0
    if path:
        try:
            with open(path) as f:
                cached = json.load(f)
            # bounded on BOTH sides: a garbled/clock-skewed future timestamp
            # must expire like any stale entry, not pin the verdict forever
            if 0 <= time.time() - float(cached["time"]) <= ttl:
                verdict = cached["verdict"]
                sys.stderr.write(
                    f"[bench] probe verdict (cached, {path}): {verdict}\n"
                )
                _PROBE_MEMO.append(verdict)
                return verdict
        except (OSError, ValueError, KeyError, TypeError):
            pass  # missing/garbled/stale cache → probe for real
    verdict = _probe_backend_uncached()
    _PROBE_MEMO.append(verdict)
    if path:
        try:  # atomic write: a concurrent bench must never read a torn file
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"verdict": verdict, "time": time.time()}, f)
            os.replace(tmp, path)
        except OSError:
            pass  # cache is best-effort; the memo still covers this process
    return verdict


def _probe_backend_uncached() -> dict | None:
    """Try to bring up the default (TPU/axon) backend in a child process.

    The tunnel backend has two observed failure modes: a fast UNAVAILABLE
    raise, and an indefinite hang inside PJRT client init (C code, holds the
    GIL — unkillable from a thread, hence the child process). Returns
    {'platform', 'n'} on success, None when every attempt fails.
    """
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    retries = int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "20"))
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            if out.returncode == 0 and out.stdout.strip():
                return json.loads(out.stdout.strip().splitlines()[-1])
            sys.stderr.write(
                f"[bench] probe attempt {attempt + 1}/{retries} rc="
                f"{out.returncode}: {out.stderr[-400:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] probe attempt {attempt + 1}/{retries} timed out "
                f"after {timeout:.0f}s\n"
            )
        except Exception as exc:  # noqa: BLE001 — never die in the probe
            sys.stderr.write(f"[bench] probe error: {exc!r}\n")
        if attempt + 1 < retries:
            time.sleep(backoff * (attempt + 1))
    return None


def peak_tflops_info(device) -> tuple:
    """(per-chip bf16 peak TFLOPs, source) calibrated from device_kind
    (ADVICE r2: a hardcoded v5e denominator makes MFU untrustworthy on other
    generations). BENCH_PEAK_TFLOPS overrides.

    `source` is "measured" when the number comes from a known chip's
    datasheet/trace-plane calibration (or an operator override), "assumed"
    for the rough CPU-fallback figure and unknown TPU kinds — every
    per-metric entry carries it so a fallback round's MFU can never be
    mistaken for a measured number (the ROADMAP cross-round caveat, made
    machine-readable)."""
    override = os.environ.get("BENCH_PEAK_TFLOPS")
    if override:
        return float(override), "measured"
    kind = getattr(device, "device_kind", "").lower()
    if device.platform != "tpu":
        # rough host CPU figure so the fallback still reports MFU
        return 0.2, "assumed"
    table = [
        # v5e: the r3 xplane trace plane reports 202.7 peak TFLOP/s for this
        # chip; use the measured plane value as the MFU denominator rather
        # than the 197 datasheet figure (VERDICT r3 weak #4: pick one)
        ("v5 lite", 202.7),
        ("v5e", 202.7),
        ("v5p", 459.0),
        ("v6 lite", 918.0),  # v6e / Trillium
        ("v6e", 918.0),
        ("v4", 275.0),
        ("v3", 123.0),
        ("v2", 46.0),
    ]
    for frag, tf in table:
        if frag in kind:
            return tf, "measured"
    return 197.0, "assumed"  # unknown TPU: assume v5e-class


def run_seq2seq(
    cpu_fallback: bool, peak: float, n_dev: int, peak_source: str = "assumed"
) -> dict:
    """Seq2seq NMT with attention (BASELINE config #3): teacher-forced
    training tokens/sec/chip on the reference demo's model scale (wmt14
    vocab 30k, embed/hidden 512 — train.conf of demo/seqToseq).

    ISSUE 9 (the MFU push): the metric now times TWO legs at the SAME
    shapes — the bf16 mixed-precision step (the headline, MXU-native) and
    the f32 baseline — both platform-tagged, with each leg's top-3 HLO cost
    buckets. The >=2x gate (speedup_vs_f32) is structural to the MXU: f32
    dots at Precision.HIGHEST cost ~6 bf16 MXU passes, so bf16 wins big on
    TPU; on the CPU fallback bf16 dots are EMULATED (convert + f32 gemm)
    and the ratio inverts — the per-leg platform tag is what keeps that
    round excludable instead of misleading."""
    import jax
    import numpy as np

    from paddle_tpu.models import Seq2SeqModel
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import Adam
    from paddle_tpu.trainer import SGDTrainer
    from paddle_tpu.core.benchmark import time_train_steps

    if cpu_fallback:
        vocab, dim, bs_spec, src_len, trg_len = 1000, 64, "8", 12, 12
        steps, warmup = 2, 1
    else:
        vocab = int(os.environ.get("BENCH_S2S_VOCAB", "30000"))
        dim = int(os.environ.get("BENCH_S2S_DIM", "512"))
        # "auto": quick-sweep candidate batch sizes on the chip and keep the
        # best tokens/s (r3's optimum was 128; the r4 decoder hoist + fused
        # xent shift the balance toward larger batches — measure, don't guess)
        bs_spec = os.environ.get("BENCH_S2S_BATCH", "auto")
        src_len = trg_len = int(os.environ.get("BENCH_S2S_LEN", "50"))
        steps = max(1, int(os.environ.get("BENCH_S2S_STEPS", "16")))
        warmup = 2
    # Defaults ON everywhere: the per-leg top-3 hlo_cost buckets are the
    # profile-driven pass's artifact and matter MOST on the real-hardware
    # rounds. BENCH_PROFILE=0 opts out (saves one AOT compile per leg).
    profile_on = os.environ.get("BENCH_PROFILE", "1") == "1"

    def make_step_for(bs: int, precision: str):
        reset_name_scope()
        model = Seq2SeqModel(vocab, vocab, embed_dim=dim, hidden_dim=dim)
        trainer = SGDTrainer(
            model.cost, Adam(learning_rate=1e-3), precision=precision
        )
        rs = np.random.RandomState(0)
        batch = {
            "source_ids": rs.randint(2, vocab, (bs, src_len)).astype(np.int32),
            "source_ids.lengths": np.full(bs, src_len, np.int32),
            "target_ids": rs.randint(2, vocab, (bs, trg_len)).astype(np.int32),
            "target_ids.lengths": np.full(bs, trg_len, np.int32),
            "label_ids": rs.randint(2, vocab, (bs, trg_len)).astype(np.int32),
            "label_ids.lengths": np.full(bs, trg_len, np.int32),
        }
        batch = jax.device_put(batch)
        trainer.init_state(batch)
        return trainer, trainer._make_step(), batch

    sweep_info = {}
    if bs_spec == "auto":
        candidates = [128, 256, 512]
        rates = {}
        for cand in candidates:
            try:
                tr, stp, bt = make_step_for(cand, "bf16")
                sec, _ = time_train_steps(stp, tr.state, bt, steps=3, warmup=1)
                rates[cand] = cand * trg_len / sec
            except Exception as exc:  # noqa: BLE001 — OOM etc: skip candidate
                sys.stderr.write(f"[bench] s2s bs={cand} failed: {exc!r}\n")
        bs = max(rates, key=rates.get) if rates else 128
        sweep_info = {
            "batch_sweep_tokens_per_sec": {
                str(k): round(v, 0) for k, v in rates.items()
            }
        }
        sys.stderr.write(f"[bench] s2s batch sweep: {rates} -> {bs}\n")
    else:
        bs = int(bs_spec)

    # Matmul FLOPs per target token (MACs x2), training ~= 3x forward.
    # Encoder work is amortized per target token (src_len == trg_len here).
    E = H = dim
    enc = 2 * 3 * (E * H + H * H) * 2            # bi-GRU, both directions
    dec = 3 * ((E + 2 * H) * H + H * H) * 2      # attention-GRU (ctx is 2H)
    attn = src_len * (2 * H) * 2                 # scores + context per token
    out = H * vocab * 2                          # output projection (dominant)
    flops_per_token = 3 * (enc + dec + attn + out)

    def time_leg(precision: str) -> dict:
        trainer, step, batch = make_step_for(bs, precision)
        lowered = step.lower(trainer.state, batch) if profile_on else None
        sec_per_step, _ = time_train_steps(
            step, trainer.state, batch, steps=steps, warmup=warmup
        )
        # the seq2seq trainer runs unsharded on one device — per-chip is per
        # this one chip regardless of how many devices the host exposes
        tokens = bs * trg_len / sec_per_step
        leg = {
            "precision": precision,
            "tokens_per_sec_per_chip": round(tokens, 1),
            "mfu": round(tokens * flops_per_token / (peak * 1e12), 4),
            "ms_per_step": round(sec_per_step * 1000, 2),
            "platform": jax.devices()[0].platform,
        }
        if lowered is not None:
            # the profile-driven pass's target list: top-3 FLOP/byte buckets
            # of exactly the executable this leg timed
            try:
                from paddle_tpu.obs.profile import compiled_cost_report

                leg["hlo_cost"] = compiled_cost_report(
                    lowered.compile(), top_k=3
                )
            except Exception as exc:  # noqa: BLE001 — never kill the leg
                leg["hlo_cost_error"] = repr(exc)[-200:]
        return leg

    bf16 = time_leg("bf16")
    # The baseline leg is best-effort: the batch size was swept under bf16
    # activations, so the f32 leg can OOM where bf16 fit — that must not
    # discard the already-measured headline, only the comparison.
    try:
        f32 = time_leg("f32")
    except Exception as exc:  # noqa: BLE001 — keep the bf16 headline
        sys.stderr.write(f"[bench] s2s f32 baseline leg failed: {exc!r}\n")
        f32 = {"precision": "f32", "error": repr(exc)[-300:]}
    # null (not 0.0) when the baseline leg failed: an unmeasured ratio must
    # stay machine-distinguishable from a measured one, same rule as
    # peak_tflops_source
    speedup = (
        round(bf16["tokens_per_sec_per_chip"] / f32["tokens_per_sec_per_chip"], 3)
        if f32.get("tokens_per_sec_per_chip")
        else None
    )
    entry = {
        "metric": "seq2seq_nmt_tokens_per_sec_per_chip",
        # headline stays the bf16 leg — the policy BENCH_r03..r05 measured —
        # so the cross-round trajectory is apples-to-apples
        "value": bf16["tokens_per_sec_per_chip"],
        "unit": "tokens/sec/chip",
        "precision": "bf16",
        "mfu": bf16["mfu"],
        "vs_baseline": round(bf16["mfu"] / 0.50, 4),
        # per-metric platform tag: fallback rounds are excludable per metric
        "platform": bf16["platform"],
        "peak_tflops_bf16": peak,
        "peak_tflops_source": peak_source,
        "batch_size": bs,
        "seq_len": src_len,
        "vocab": vocab,
        "hidden": dim,
        "ms_per_step": bf16["ms_per_step"],
        # the fixed-shape f32 baseline leg (same batch/seq/model), and the
        # ISSUE 9 gate ratio: >=2x expected on the MXU path, <1 on the CPU
        # fallback where bf16 is emulated (see docstring)
        "f32_baseline": f32,
        "speedup_vs_f32": speedup,
        **sweep_info,
    }
    if "hlo_cost" in bf16:
        entry["hlo_cost"] = dict(bf16["hlo_cost"], executable="s2s_step_bf16")
    return entry


def run_serving(cpu_fallback: bool) -> dict:
    """Continuous-batching serving leg (ISSUE 6): tokens/sec at 16 concurrent
    streams + speedup over the sequential per-request baseline, p50/p99
    request latency, and the zero-recompile gate over a mixed-length stream.
    Small demo-LM shapes — the number tracked across rounds is the *batching*
    speedup and the latency distribution, not model FLOPs (see
    benchmarks/serving_bench.py for the full grid)."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "24"))
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "16"))

    def fresh_session():
        return make_demo_session(
            vocab=256, n_layers=2, d_model=64, n_heads=2, seed=0,
            max_slots=16, page_size=16, prefill_buckets=(16, 32),
            max_new_limit=max_new,
        )

    prompts = make_prompts(
        requests, lengths=(5, 11, 16, 23, 32), vocab=256, bos_id=1, seed=0
    )
    warm_prompts = make_prompts(2, lengths=(16, 32), vocab=256, bos_id=1, seed=7)

    def measure(concurrency):
        session = fresh_session()
        run_closed_loop(session, warm_prompts, max_new, concurrency=2)
        sigs0 = session.decode_shape_signatures()
        res = run_closed_loop(session, prompts, max_new, concurrency)
        res["decode_recompiles_after_warmup"] = (
            session.decode_shape_signatures() - sigs0
        )
        return res

    seq = measure(1)
    bat = measure(16)
    speedup = (
        round(bat["tokens_per_sec"] / seq["tokens_per_sec"], 2)
        if seq["tokens_per_sec"]
        else 0.0
    )

    # chunked-prefill ITL column (ISSUE 11): the same 16-stream run with
    # long prompts joining mid-stream, chunked — p99 inter-token latency is
    # the no-stall number the serving_bench mixed-length leg gates at 0.5x
    # of the whole-prompt baseline; here the chunked leg alone rides the
    # cross-round metric (cheap), the full A/B lives in serving_bench
    from paddle_tpu.serving.workload import make_mixed_prompts

    chunk_session = make_demo_session(
        vocab=256, n_layers=2, d_model=64, n_heads=2, seed=0,
        max_slots=16, page_size=16, prefill_buckets=(16, 32),
        max_new_limit=max_new, max_len=96 + max_new, prefill_chunk=16,
    )
    run_closed_loop(chunk_session, warm_prompts, max_new, concurrency=2)
    mixed = make_mixed_prompts(
        requests, short_lengths=(5, 11, 16), long_len=96, long_every=8,
        burst=2, vocab=256, bos_id=1, seed=1,
    )
    chunks_before = chunk_session.prefill_chunks_committed  # warmup's chunks
    chunk_res = run_closed_loop(chunk_session, mixed, max_new, concurrency=16)

    return {
        "metric": "serving_tokens_per_sec_16_streams",
        "value": bat["tokens_per_sec"],
        "unit": "tokens/sec",
        # the cross-round headline: batching win over per-request serving
        "vs_baseline": speedup,
        "speedup_vs_sequential": speedup,
        "platform": jax.devices()[0].platform,
        "p50_latency_ms": bat["p50_latency_ms"],
        "p99_latency_ms": bat["p99_latency_ms"],
        "p99_inter_token_ms": bat["p99_inter_token_ms"],
        "mixed_chunked_p99_inter_token_ms": chunk_res["p99_inter_token_ms"],
        "mixed_chunked_prefill_chunks":
            chunk_session.prefill_chunks_committed - chunks_before,
        "sequential_tokens_per_sec": seq["tokens_per_sec"],
        "sequential_p50_latency_ms": seq["p50_latency_ms"],
        "decode_recompiles_after_warmup": bat["decode_recompiles_after_warmup"],
        "requests": requests,
        "max_new_tokens": max_new,
    }


def run_serving_speculative() -> list:
    """Speculative-decoding leg (ISSUE 16): ONE stream — the case batching
    cannot speed up — over high-overlap repeated-motif prompts, speculate_k
    on vs off over identical geometry. Two cross-round metrics ride out:
    `serving_single_stream_tokens_per_sec` (with the speedup-vs-non-
    speculative column) and `serving_spec_acceptance_rate` (drafted tokens
    the verify pass accepted — the workload-dependent number the speedup is
    a function of). The full gated A/B lives in benchmarks/serving_bench.py;
    this is the cheap tracked slice."""
    import jax

    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import (
        make_prompts, make_repetitive_prompts, run_closed_loop,
    )

    vocab = int(os.environ.get("BENCH_SPEC_VOCAB", "32"))
    k = int(os.environ.get("BENCH_SPEC_K", "8"))
    max_new = int(os.environ.get("BENCH_SPEC_MAX_NEW", "64"))
    requests = int(os.environ.get("BENCH_SPEC_REQUESTS", "8"))
    prompts = make_repetitive_prompts(
        requests, motif_len=4, repeats=6, vocab=vocab, bos_id=1, seed=3,
    )
    warm = make_prompts(2, lengths=(16, 32), vocab=vocab, bos_id=1, seed=7)
    warm += make_repetitive_prompts(
        1, motif_len=4, repeats=6, vocab=vocab, bos_id=1, seed=11,
    )

    def measure(speculate_k):
        session = make_demo_session(
            vocab=vocab, n_layers=2, d_model=64, n_heads=2, seed=0,
            max_slots=4, page_size=16, prefill_buckets=(16, 32),
            max_new_limit=max_new, speculate_k=speculate_k,
        )
        run_closed_loop(session, warm, max_new, concurrency=len(warm))
        res = run_closed_loop(session, prompts, max_new, concurrency=1)
        return res, session.stats()

    base, _ = measure(0)
    spec, st = measure(k)
    speedup = (
        round(spec["tokens_per_sec"] / base["tokens_per_sec"], 2)
        if base["tokens_per_sec"] else 0.0
    )
    platform = jax.devices()[0].platform
    return [
        {
            "metric": "serving_single_stream_tokens_per_sec",
            "value": spec["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": speedup,
            "speedup_vs_non_speculative": speedup,
            "non_speculative_tokens_per_sec": base["tokens_per_sec"],
            "speculate_k": k,
            "platform": platform,
            "requests": requests,
            "max_new_tokens": max_new,
        },
        {
            "metric": "serving_spec_acceptance_rate",
            "value": st["spec_acceptance_rate"],
            "unit": "accepted/drafted",
            "spec_rounds": st["spec_rounds"],
            "spec_tokens_drafted": st["spec_tokens_drafted"],
            "verify_shape_signatures": st["verify_shape_signatures"],
            "platform": platform,
        },
    ]


def run_serving_tp() -> dict:
    """Tensor-parallel serving leg (ISSUE 12): the SAME demo-LM geometry
    served single-chip and at TP=N (N = 4 when the host exposes >= 4
    devices), with per-chip param/KV-pool bytes read from sharding
    metadata. The cross-round headline is `serving_tp4_pool_bytes_per_chip`
    — the number that must keep dropping as the pool shards wider. Tokens
    must be identical across the legs (TP is result-invisible); on CPU the
    collectives are emulated, so tokens/sec here is a smoke number, tagged
    with the platform like every entry.

    Runs LAST and detaches the persistent compile cache first: this leg
    EXECUTES multi-device programs, and running a cache-DESERIALIZED
    multi-device program segfaults on this jax build (the PR-5/PR-8
    gotcha); detaching is sticky, which is why this leg is last."""
    import jax

    from paddle_tpu.core.init_ctx import detach_compilation_cache
    from paddle_tpu.serving.session import make_demo_session
    from paddle_tpu.serving.workload import make_prompts, run_closed_loop

    n_dev = len(jax.devices())
    tp = 4
    if n_dev < 4:
        # never measure a DIFFERENT tp under the tp4-named headline: the
        # cross-round series would silently change scale with the host's
        # device count — raise instead (caller records serving_tp_error and
        # appends no misleading metric entry)
        raise RuntimeError(
            f"serving TP leg needs >= 4 devices for the tp4 headline; host "
            f"exposes {n_dev}"
        )
    detach_compilation_cache("bench TP serving leg executes multi-device programs")
    requests = int(os.environ.get("BENCH_SERVE_TP_REQUESTS", "16"))
    max_new = int(os.environ.get("BENCH_SERVE_MAX_NEW", "16"))
    prompts = make_prompts(
        requests, lengths=(5, 11, 16, 23, 32), vocab=256, bos_id=1, seed=0
    )
    warm = make_prompts(2, lengths=(16, 32), vocab=256, bos_id=1, seed=7)

    def leg(tp_n):
        session = make_demo_session(
            vocab=256, n_layers=2, d_model=64, n_heads=4, seed=0,
            max_slots=16, page_size=16, prefill_buckets=(16, 32),
            max_new_limit=max_new, tp=tp_n,
        )
        run_closed_loop(session, warm, max_new, concurrency=2)
        res = run_closed_loop(session, prompts, max_new, concurrency=16)
        return res, session.stats()

    base_res, base_st = leg(0)
    tp_res, tp_st = leg(tp)
    return {
        "metric": "serving_tp4_pool_bytes_per_chip",
        "value": tp_st["pool_bytes_per_chip"],
        "unit": "bytes",
        "platform": jax.devices()[0].platform,
        "tp": tp,
        "pool_bytes_per_chip_single": base_st["pool_bytes_per_chip"],
        "pool_bytes_ratio": round(
            base_st["pool_bytes_per_chip"]
            / max(tp_st["pool_bytes_per_chip"], 1), 2
        ),
        "param_bytes_per_chip": tp_st["param_bytes_per_chip"],
        "param_bytes_per_chip_single": base_st["param_bytes_per_chip"],
        "tokens_per_sec": tp_res["tokens_per_sec"],
        "tokens_per_sec_single": base_res["tokens_per_sec"],
        "p99_inter_token_ms": tp_res["p99_inter_token_ms"],
        "tp_tokens_identical": bool(
            tp_res["results"] == base_res["results"]
        ),
        "decode_shape_signatures": tp_st["decode_shape_signatures"],
    }


def run_control_plane() -> list:
    """Binary control-plane legs (ISSUE 20): the framed wire's two headline
    numbers as cross-round metrics. `control_plane_tasks_per_sec` drains a
    task ledger through a simulated trainer fleet over the framed wire
    (bulk leases + piggybacked acks; the line-JSON leg rides along as the
    round-trip denominator). `stream_bytes_per_token` is the binary push
    stream's bytes per delivered token at fan-out, with the JSON wire's
    number and the ratio alongside. Both run the REAL TCP protocol against
    in-process servers — host-side numbers, so the jax platform tag marks
    the round, not the transport. The full gated grids live in
    benchmarks/chaos_bench.py --mode fleet and benchmarks/serving_bench.py
    streaming."""
    import argparse
    import importlib.util

    import jax

    bench_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"
    )

    def load(name):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(bench_dir, name + ".py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    platform = jax.devices()[0].platform
    entries = []

    fleet = load("chaos_bench").run_fleet(argparse.Namespace(
        fleet_trainers=int(os.environ.get("BENCH_FLEET_TRAINERS", "24")),
        fleet_tasks=int(os.environ.get("BENCH_FLEET_TASKS", "240")),
        fleet_lease_batch=8,
        seed=0,
    ))
    entries.append({
        "metric": "control_plane_tasks_per_sec",
        "value": fleet["value"],
        "unit": fleet["unit"],
        "round_trip_reduction": fleet["round_trip_reduction"],
        "round_trips_per_task": fleet["framed"]["round_trips_per_task"],
        "round_trips_per_task_json": fleet["legacy"]["round_trips_per_task"],
        "bytes_per_task": fleet["framed"]["bytes_per_task"],
        "trainers": fleet["framed"]["trainers"],
        "lease_batch": fleet["lease_batch"],
        "exactly_once": fleet["gates"]["exactly_once_both_legs"],
        "platform": platform,
    })

    streaming = load("serving_bench").run_streaming(argparse.Namespace(
        vocab=96, n_layers=2, d_model=64, n_heads=2,
        max_slots=8, page_size=16,
        stream_counts=os.environ.get("BENCH_STREAM_COUNTS", "16"),
        stream_max_new=16, speculate_k=0,
    ))
    leg = streaming["legs"][-1]
    entries.append({
        "metric": "stream_bytes_per_token",
        "value": leg["push_bin"]["bytes_per_token"],
        "unit": "bytes/token",
        "bytes_per_token_json": leg["push"]["bytes_per_token"],
        "bin_bytes_ratio": leg["bin_bytes_ratio"],
        "streams": leg["streams"],
        "frames_coalesced": streaming["stream_frames_coalesced"],
        "platform": platform,
    })
    return entries


def run_bench(cpu_fallback: bool) -> dict:
    import jax

    if cpu_fallback:
        # the sitecustomize-installed tunnel plugin sets jax_platforms
        # programmatically, trumping the JAX_PLATFORMS env var — the config
        # update is the only override that sticks (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from paddle_tpu.core import dtypes, stats
    from paddle_tpu.core.init_ctx import enable_compilation_cache
    from paddle_tpu import models

    # persistent compile cache (PADDLE_TPU_COMPILE_CACHE): repeat bench runs
    # skip the XLA compile; the hit/miss counts land in the JSON line below
    cache_dir = enable_compilation_cache()
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    if cpu_fallback:
        # deliberately separate env names: a TPU-sized BENCH_BATCH must not
        # leak into the reduced-shape CPU fallback and wedge it
        batch_size = int(os.environ.get("BENCH_CPU_BATCH", "16"))
        image_size = int(os.environ.get("BENCH_CPU_IMAGE", "64"))
        steps = max(1, int(os.environ.get("BENCH_CPU_STEPS", "4")))
        warmup = max(1, int(os.environ.get("BENCH_CPU_WARMUP", "1")))
        scan_k = max(1, int(os.environ.get("BENCH_CPU_SCAN", "2")))
    else:
        batch_size = int(os.environ.get("BENCH_BATCH", "256"))
        image_size = int(os.environ.get("BENCH_IMAGE", "224"))
        steps = max(1, int(os.environ.get("BENCH_STEPS", "32")))
        warmup = max(1, int(os.environ.get("BENCH_WARMUP", "1")))
        # steps per compiled dispatch: amortizes tunnel/host dispatch latency
        scan_k = max(1, int(os.environ.get("BENCH_SCAN", "8")))

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform

    dtypes.set_policy(dtypes.bf16_policy())
    reset_name_scope()
    img, label, logits, cost = models.resnet50(image_size=image_size)

    mesh = make_mesh({"data": n_dev})
    dp = DataParallel(mesh)

    rs = np.random.RandomState(0)
    batch = {
        "image": rs.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "label": rs.randint(0, 1000, batch_size),
    }

    from paddle_tpu.core.benchmark import time_multi_steps, time_train_steps

    # Rematerialization lever (PROFILE_r03 "After" table: the residual/BN
    # epilogue bytes): conv_only keeps conv/matmul outputs and recomputes
    # elementwise epilogues in backward — a bytes lever on a bytes-bound
    # model. BENCH_REMAT=none|conv_only|full|auto; auto quick-times both on
    # the real chip and keeps the winner.
    remat_env = os.environ.get("BENCH_REMAT", "auto" if not cpu_fallback else "none")
    chosen_remat = None if remat_env in ("none", "") else remat_env
    tune_info = {}
    if remat_env == "auto":
        variants = [None, "conv_only"]
        timings = {}
        for variant in variants:
            t = SGDTrainer(
                cost, SGD(learning_rate=0.1, momentum=0.9), parallel=dp,
                remat=variant, precision="bf16",
            )
            t.init_state(dp.shard_batch(batch))
            stp = t._make_step()
            sec, _ = time_train_steps(
                stp, t.state, dp.shard_batch(batch), steps=3, warmup=1
            )
            timings[str(variant)] = round(sec * 1000, 2)
        chosen_remat = (
            "conv_only"
            if timings["conv_only"] < timings["None"]
            else None
        )
        tune_info = {"remat_tune_ms": timings}
        sys.stderr.write(f"[bench] remat auto-tune: {timings} -> {chosen_remat}\n")

    trainer = SGDTrainer(
        cost, SGD(learning_rate=0.1, momentum=0.9), parallel=dp,
        remat=chosen_remat, precision="bf16",
    )
    trainer.init_state(dp.shard_batch(batch))
    # memory/comms accounting for the data-parallel step (ISSUE 5): per-chip
    # resident opt-state bytes from sharding metadata and the updater's
    # modeled collective bytes/step — benchmarks/shard_update_bench.py sweeps
    # these across replicated/sharded x compression
    opt_state_bytes = stats.per_chip_tree_bytes(trainer.state["opt"])
    collective_bytes = trainer.updater.collective_bytes_per_step()

    # HLO cost buckets (obs pillar 3 / ROADMAP item 2's target list): lower
    # BEFORE the donated timing runs delete the state buffers; the AOT
    # compile for the report happens after timing so it never skews it.
    # Defaults ON everywhere (the report is the profile-driven pass's
    # artifact, most valuable on real hardware); BENCH_PROFILE=0 opts out
    # of the one extra XLA compile of the step program.
    profile_on = os.environ.get("BENCH_PROFILE", "1") == "1"
    lowered = None
    if scan_k > 1:
        # K distinct stacked batches per dispatch, scanned inside one
        # compiled program (SGDTrainer.make_multi_step)
        batches = dp.shard_batches(
            {
                "image": rs.randn(
                    scan_k, batch_size, image_size, image_size, 3
                ).astype(np.float32),
                "label": rs.randint(0, 1000, (scan_k, batch_size)),
            }
        )
        multi = trainer.make_multi_step()
        if profile_on:
            lowered = multi.lower(trainer.state, batches)
        dispatches = max(1, steps // scan_k)
        sec_per_step, _ = time_multi_steps(
            multi, trainer.state, batches, scan_k,
            dispatches=dispatches, warmup=warmup,
        )
        steps = dispatches * scan_k
    else:
        step = trainer._make_step()
        batch = dp.shard_batch(batch)
        if profile_on:
            lowered = step.lower(trainer.state, batch)
        sec_per_step, _ = time_train_steps(
            step, trainer.state, batch, steps=steps, warmup=warmup
        )
    dt = sec_per_step * steps

    images_per_sec = batch_size * steps / dt
    images_per_sec_chip = images_per_sec / n_dev

    # ResNet-50 @224 is 4.089 GMACs = 8.18 GFLOPs forward (MACs×2; XLA
    # cost_analysis on the compiled fwd graph reports 7.5e9, same convention
    # modulo elementwise ops — see PROFILE_r03.md). Training (fwd + input-grad
    # + weight-grad) ≈ 3× fwd. Rounds 1-2 used 4.09e9 as if it were FLOPs and
    # UNDERSTATED MFU by 2×.
    flops_per_image = 3 * 8.18e9 * (image_size / 224.0) ** 2
    peak, peak_source = peak_tflops_info(devices[0])
    mfu = images_per_sec_chip * flops_per_image / (peak * 1e12)

    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "peak_tflops_bf16": peak,
        "peak_tflops_source": peak_source,
        "precision": "bf16",
        "n_devices": n_dev,
        "batch_size": batch_size,
        "image_size": image_size,
        "ms_per_step": round(1000 * dt / steps, 2),
        "scan_k": scan_k,
        "remat": chosen_remat or "none",
        "opt_state_bytes": opt_state_bytes,
        "collective_bytes_per_step": collective_bytes,
        # BASELINE.json's north-star names v5p hardware; vs_baseline here is
        # MFU/0.50 against THIS chip's peak (device_kind above) — the target
        # is redefined to the available chip, not silently met on v5p
        "baseline_note": "vs_baseline = mfu/0.50 on the available chip, not v5p",
        **tune_info,
    }
    if lowered is not None:
        # top-k FLOP/byte buckets of the timed executable — the
        # profile-driven optimization target list (obs/profile.py; the same
        # report the CLI's --profile pass:N writes)
        try:
            from paddle_tpu.obs.profile import compiled_cost_report

            out["hlo_cost"] = dict(
                compiled_cost_report(lowered.compile(), top_k=3),
                executable="train_step_scan" if scan_k > 1 else "train_step",
            )
        except Exception as exc:  # noqa: BLE001 — report must not kill bench
            sys.stderr.write(f"[bench] hlo cost report failed: {exc!r}\n")
            out["hlo_cost_error"] = repr(exc)[-300:]
    if cache_dir:
        # second runs against a warm cache report misses → 0 (or near it)
        out["compile_cache"] = {
            "dir": cache_dir,
            "hits": stats.RECOMPILES.cache_hits,
            "misses": stats.RECOMPILES.cache_misses,
        }
    # "platform" rides inside EVERY per-metric entry (not just top-level):
    # trajectory tooling excludes CPU-fallback rounds per metric, and the
    # fallback-relay path (accelerator died mid-run, child re-ran on CPU)
    # only preserves per-entry fields (BENCH_r05 `error` postmortem). The
    # headline entry lands FIRST and unconditionally — a failing secondary
    # leg must not drop it from the per-metric stream
    out["metrics"] = [
        {k: out[k] for k in ("metric", "value", "unit", "mfu", "vs_baseline",
                             "batch_size", "ms_per_step", "platform",
                             "peak_tflops_source", "precision")},
    ]
    try:
        out["metrics"].append(
            run_seq2seq(cpu_fallback, peak, n_dev, peak_source)
        )
    except Exception as exc:  # noqa: BLE001 — seq2seq must not kill the headline
        sys.stderr.write(f"[bench] seq2seq leg failed: {exc!r}\n")
        out["seq2seq_error"] = repr(exc)[-400:]
    try:
        out["metrics"].append(run_serving(cpu_fallback))
    except Exception as exc:  # noqa: BLE001 — serving must not kill the headline
        sys.stderr.write(f"[bench] serving leg failed: {exc!r}\n")
        out["serving_error"] = repr(exc)[-400:]
    try:
        out["metrics"].extend(run_serving_speculative())
    except Exception as exc:  # noqa: BLE001 — spec leg must not kill the headline
        sys.stderr.write(f"[bench] serving speculative leg failed: {exc!r}\n")
        out["serving_spec_error"] = repr(exc)[-400:]
    try:
        out["metrics"].extend(run_control_plane())
    except Exception as exc:  # noqa: BLE001 — wire legs must not kill the headline
        sys.stderr.write(f"[bench] control-plane leg failed: {exc!r}\n")
        out["control_plane_error"] = repr(exc)[-400:]
    # LAST on purpose: this leg detaches the persistent compile cache (it
    # executes multi-device programs — see run_serving_tp docstring)
    try:
        out["metrics"].append(run_serving_tp())
    except Exception as exc:  # noqa: BLE001 — TP leg must not kill the headline
        sys.stderr.write(f"[bench] serving TP leg failed: {exc!r}\n")
        out["serving_tp_error"] = repr(exc)[-400:]
    if cpu_fallback:
        out["error"] = (
            "tpu backend unavailable after probe retries; numbers are from the "
            "CPU fallback at reduced shapes"
        )
    return out


def main() -> None:
    # last-resort watchdog: if the bench wedges after a successful probe
    # (e.g. the tunnel dies mid-run while the GIL is released on an RPC
    # wait), still emit the JSON error line instead of hanging the driver
    total_timeout = float(os.environ.get("BENCH_TOTAL_TIMEOUT", "2400"))
    emit_lock = threading.Lock()
    emitted = [False]

    def _emit_once(obj: dict) -> None:
        with emit_lock:
            if not emitted[0]:
                emitted[0] = True
                _emit(obj)

    def _watchdog() -> None:
        _emit_once(_error_payload(f"bench watchdog fired after {total_timeout:.0f}s"))
        os._exit(0)

    timer = threading.Timer(total_timeout, _watchdog)
    timer.daemon = True
    timer.start()

    cpu_fallback = os.environ.get("BENCH_FORCE_CPU") == "1"
    if not cpu_fallback:
        info = probe_backend()
        if info is None or info.get("platform") == "cpu":
            # None = tunnel down/hung; platform 'cpu' = JAX silently fell
            # back inside the probe child — either way run reduced shapes
            cpu_fallback = True
        else:
            sys.stderr.write(f"[bench] backend up: {info}\n")

    try:
        out = run_bench(cpu_fallback)
    except Exception:
        err = traceback.format_exc()
        sys.stderr.write(err)
        if not cpu_fallback:
            # accelerator run died (OOM, compile error, tunnel drop). The
            # axon backend is already initialized in this process, so the
            # jax_platforms config can no longer be switched — rerun the CPU
            # fallback in a fresh interpreter and relay its JSON line.
            out = _error_payload(err)
            try:
                env = dict(os.environ, BENCH_FORCE_CPU="1")
                sub = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True,
                    text=True,
                    timeout=900,
                    env=env,
                )
                if sub.returncode == 0 and sub.stdout.strip():
                    out = json.loads(sub.stdout.strip().splitlines()[-1])
                    out["error"] = (
                        "accelerator run failed: "
                        + err.strip().splitlines()[-1]
                    )
            except Exception:
                pass
        else:
            out = _error_payload(err)
    timer.cancel()
    _emit_once(out)


if __name__ == "__main__":
    main()
