"""Benchmark driver: ResNet-50 ImageNet training throughput on the available
accelerator (the BASELINE.json north-star metric: images/sec/chip and MFU vs
the ≥50% target).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = achieved_MFU / 0.50 (the north-star MFU target), so 1.0 means
"hit the 50%-MFU goal"; extra keys are informational.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core import dtypes
    from paddle_tpu import models
    from paddle_tpu.nn.graph import Network, reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    batch_size = int(os.environ.get("BENCH_BATCH", "256"))
    image_size = int(os.environ.get("BENCH_IMAGE", "224"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20")))
    warmup = max(1, int(os.environ.get("BENCH_WARMUP", "3")))

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform

    dtypes.set_policy(dtypes.bf16_policy())
    reset_name_scope()
    img, label, logits, cost = models.resnet50(image_size=image_size)

    mesh = make_mesh({"data": n_dev})
    dp = DataParallel(mesh)
    trainer = SGDTrainer(cost, SGD(learning_rate=0.1, momentum=0.9), parallel=dp)

    rs = np.random.RandomState(0)
    batch = {
        "image": rs.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "label": rs.randint(0, 1000, batch_size),
    }
    batch = dp.shard_batch(batch)
    trainer.init_state(batch)
    step = trainer._make_step()

    from paddle_tpu.core.benchmark import time_train_steps

    sec_per_step, _ = time_train_steps(
        step, trainer.state, batch, steps=steps, warmup=warmup
    )
    dt = sec_per_step * steps

    images_per_sec = batch_size * steps / dt
    images_per_sec_chip = images_per_sec / n_dev

    # ResNet-50 @224 fwd ≈ 4.09 GFLOPs/image (conv+fc MACs×2); training
    # (fwd + input-grad + weight-grad) ≈ 3× fwd.
    flops_per_image = 3 * 4.09e9 * (image_size / 224.0) ** 2
    peak = {
        # bf16 peak TFLOPs per chip
        "tpu": float(os.environ.get("BENCH_PEAK_TFLOPS", "197")),  # v5e ≈ 197
        "cpu": 0.2,
    }.get(platform, 197.0)
    mfu = images_per_sec_chip * flops_per_image / (peak * 1e12)

    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "n_devices": n_dev,
        "batch_size": batch_size,
        "image_size": image_size,
        "ms_per_step": round(1000 * dt / steps, 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
