"""Benchmark driver: ResNet-50 ImageNet training throughput on the available
accelerator (the BASELINE.json north-star metric: images/sec/chip and MFU vs
the ≥50% target).

Prints exactly ONE JSON line no matter what happens:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
vs_baseline = achieved_MFU / 0.50 (the north-star MFU target), so 1.0 means
"hit the 50%-MFU goal"; extra keys are informational. On any failure the line
still appears, with an "error" key describing what went wrong.

Resilience (round-1 postmortem: the TPU tunnel backend raised UNAVAILABLE and
the script died with rc=1 and no JSON): backend init is probed in a child
process with a hard timeout and retried with backoff; if the accelerator never
comes up we fall back to the CPU backend with small shapes so a measured
number is still emitted, flagged with "error".
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback

_PROBE_SNIPPET = (
    "import jax, json, sys;"
    "d = jax.devices();"
    "sys.stdout.write(json.dumps({'platform': d[0].platform, 'n': len(d)}))"
)


def _emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _error_payload(msg: str) -> dict:
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": msg[-800:],
    }


def probe_backend() -> dict | None:
    """Try to bring up the default (TPU/axon) backend in a child process.

    The tunnel backend has two observed failure modes: a fast UNAVAILABLE
    raise, and an indefinite hang inside PJRT client init (C code, holds the
    GIL — unkillable from a thread, hence the child process). Returns
    {'platform', 'n'} on success, None when every attempt fails.
    """
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
    retries = int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
    backoff = float(os.environ.get("BENCH_PROBE_BACKOFF", "20"))
    for attempt in range(retries):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            if out.returncode == 0 and out.stdout.strip():
                return json.loads(out.stdout.strip().splitlines()[-1])
            sys.stderr.write(
                f"[bench] probe attempt {attempt + 1}/{retries} rc="
                f"{out.returncode}: {out.stderr[-400:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[bench] probe attempt {attempt + 1}/{retries} timed out "
                f"after {timeout:.0f}s\n"
            )
        except Exception as exc:  # noqa: BLE001 — never die in the probe
            sys.stderr.write(f"[bench] probe error: {exc!r}\n")
        if attempt + 1 < retries:
            time.sleep(backoff * (attempt + 1))
    return None


def run_bench(cpu_fallback: bool) -> dict:
    import jax

    if cpu_fallback:
        # the sitecustomize-installed tunnel plugin sets jax_platforms
        # programmatically, trumping the JAX_PLATFORMS env var — the config
        # update is the only override that sticks (see tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from paddle_tpu.core import dtypes
    from paddle_tpu import models
    from paddle_tpu.nn.graph import reset_name_scope
    from paddle_tpu.optim import SGD
    from paddle_tpu.parallel import DataParallel, make_mesh
    from paddle_tpu.trainer import SGDTrainer

    if cpu_fallback:
        # deliberately separate env names: a TPU-sized BENCH_BATCH must not
        # leak into the reduced-shape CPU fallback and wedge it
        batch_size = int(os.environ.get("BENCH_CPU_BATCH", "16"))
        image_size = int(os.environ.get("BENCH_CPU_IMAGE", "64"))
        steps = max(1, int(os.environ.get("BENCH_CPU_STEPS", "4")))
        warmup = max(1, int(os.environ.get("BENCH_CPU_WARMUP", "1")))
        scan_k = max(1, int(os.environ.get("BENCH_CPU_SCAN", "2")))
    else:
        batch_size = int(os.environ.get("BENCH_BATCH", "256"))
        image_size = int(os.environ.get("BENCH_IMAGE", "224"))
        steps = max(1, int(os.environ.get("BENCH_STEPS", "32")))
        warmup = max(1, int(os.environ.get("BENCH_WARMUP", "1")))
        # steps per compiled dispatch: amortizes tunnel/host dispatch latency
        scan_k = max(1, int(os.environ.get("BENCH_SCAN", "8")))

    devices = jax.devices()
    n_dev = len(devices)
    platform = devices[0].platform

    dtypes.set_policy(dtypes.bf16_policy())
    reset_name_scope()
    img, label, logits, cost = models.resnet50(image_size=image_size)

    mesh = make_mesh({"data": n_dev})
    dp = DataParallel(mesh)
    trainer = SGDTrainer(cost, SGD(learning_rate=0.1, momentum=0.9), parallel=dp)

    rs = np.random.RandomState(0)
    batch = {
        "image": rs.randn(batch_size, image_size, image_size, 3).astype(np.float32),
        "label": rs.randint(0, 1000, batch_size),
    }
    trainer.init_state(dp.shard_batch(batch))

    from paddle_tpu.core.benchmark import time_multi_steps, time_train_steps

    if scan_k > 1:
        # K distinct stacked batches per dispatch, scanned inside one
        # compiled program (SGDTrainer.make_multi_step)
        batches = dp.shard_batches(
            {
                "image": rs.randn(
                    scan_k, batch_size, image_size, image_size, 3
                ).astype(np.float32),
                "label": rs.randint(0, 1000, (scan_k, batch_size)),
            }
        )
        multi = trainer.make_multi_step()
        dispatches = max(1, steps // scan_k)
        sec_per_step, _ = time_multi_steps(
            multi, trainer.state, batches, scan_k,
            dispatches=dispatches, warmup=warmup,
        )
        steps = dispatches * scan_k
    else:
        step = trainer._make_step()
        batch = dp.shard_batch(batch)
        sec_per_step, _ = time_train_steps(
            step, trainer.state, batch, steps=steps, warmup=warmup
        )
    dt = sec_per_step * steps

    images_per_sec = batch_size * steps / dt
    images_per_sec_chip = images_per_sec / n_dev

    # ResNet-50 @224 fwd ≈ 4.09 GFLOPs/image (conv+fc MACs×2); training
    # (fwd + input-grad + weight-grad) ≈ 3× fwd.
    flops_per_image = 3 * 4.09e9 * (image_size / 224.0) ** 2
    peak = {
        # bf16 peak TFLOPs per chip
        "tpu": float(os.environ.get("BENCH_PEAK_TFLOPS", "197")),  # v5e ≈ 197
        "cpu": 0.2,
    }.get(platform, 197.0)
    mfu = images_per_sec_chip * flops_per_image / (peak * 1e12)

    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.50, 4),
        "mfu": round(mfu, 4),
        "platform": platform,
        "n_devices": n_dev,
        "batch_size": batch_size,
        "image_size": image_size,
        "ms_per_step": round(1000 * dt / steps, 2),
        "scan_k": scan_k,
    }
    if cpu_fallback:
        out["error"] = (
            "tpu backend unavailable after probe retries; numbers are from the "
            "CPU fallback at reduced shapes"
        )
    return out


def main() -> None:
    # last-resort watchdog: if the bench wedges after a successful probe
    # (e.g. the tunnel dies mid-run while the GIL is released on an RPC
    # wait), still emit the JSON error line instead of hanging the driver
    total_timeout = float(os.environ.get("BENCH_TOTAL_TIMEOUT", "2400"))
    emit_lock = threading.Lock()
    emitted = [False]

    def _emit_once(obj: dict) -> None:
        with emit_lock:
            if not emitted[0]:
                emitted[0] = True
                _emit(obj)

    def _watchdog() -> None:
        _emit_once(_error_payload(f"bench watchdog fired after {total_timeout:.0f}s"))
        os._exit(0)

    timer = threading.Timer(total_timeout, _watchdog)
    timer.daemon = True
    timer.start()

    cpu_fallback = os.environ.get("BENCH_FORCE_CPU") == "1"
    if not cpu_fallback:
        info = probe_backend()
        if info is None or info.get("platform") == "cpu":
            # None = tunnel down/hung; platform 'cpu' = JAX silently fell
            # back inside the probe child — either way run reduced shapes
            cpu_fallback = True
        else:
            sys.stderr.write(f"[bench] backend up: {info}\n")

    try:
        out = run_bench(cpu_fallback)
    except Exception:
        err = traceback.format_exc()
        sys.stderr.write(err)
        if not cpu_fallback:
            # accelerator run died (OOM, compile error, tunnel drop). The
            # axon backend is already initialized in this process, so the
            # jax_platforms config can no longer be switched — rerun the CPU
            # fallback in a fresh interpreter and relay its JSON line.
            out = _error_payload(err)
            try:
                env = dict(os.environ, BENCH_FORCE_CPU="1")
                sub = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)],
                    capture_output=True,
                    text=True,
                    timeout=900,
                    env=env,
                )
                if sub.returncode == 0 and sub.stdout.strip():
                    out = json.loads(sub.stdout.strip().splitlines()[-1])
                    out["error"] = (
                        "accelerator run failed: "
                        + err.strip().splitlines()[-1]
                    )
            except Exception:
                pass
        else:
            out = _error_payload(err)
    timer.cancel()
    _emit_once(out)


if __name__ == "__main__":
    main()
