"""Streaming evaluators (metrics accumulated across batches).

Parity with paddle/gserver/evaluators/Evaluator.h:42 (start/eval/finish,
registry :32) and its registered set: classification_error, seq error, auc,
precision_recall, pnpair, rank auc, chunk F1 (ChunkEvaluator.cpp), sum /
column-sum. CTC edit-distance lives with the CTC ops. Evaluators run on host
numpy over batch outputs — the per-batch tensors come out of the compiled step;
the streaming state is tiny and stays on host (same split as the reference:
kernels produce per-batch stats, Evaluator accumulates)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.core.registry import EVALUATORS


class Evaluator:
    """start() → update(batch fields) per batch → finish() returns the metric."""

    def start(self) -> None:
        raise NotImplementedError

    def update(self, **kw) -> None:
        raise NotImplementedError

    def finish(self) -> float:
        raise NotImplementedError


def _mask_flat(values: np.ndarray, lengths: Optional[np.ndarray]):
    """Flatten [B,T,...] with lengths → (flat values, keep mask); or
    (values, None) for non-sequence [B, ...]."""
    if lengths is None:
        return values, None
    b, t = values.shape[:2]
    keep = np.arange(t)[None, :] < lengths[:, None]
    return values.reshape((b * t,) + values.shape[2:]), keep.reshape(-1)


@EVALUATORS.register("classification_error")
class ClassificationErrorEvaluator(Evaluator):
    """classification_error (Evaluator.cpp ClassificationErrorEvaluator)."""

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def update(self, output=None, label=None, weight=None, lengths=None, **kw):
        output = np.asarray(output)
        label = np.asarray(label)
        if output.ndim == 3:  # sequence output
            flat, keep = _mask_flat(
                output, np.asarray(lengths) if lengths is not None else None
            )
            pred = flat.reshape((-1,) + flat.shape[-1:]).argmax(-1)
            lab = label.reshape(-1)
            if keep is None:
                keep = np.ones(len(lab), bool)
        else:
            pred = output.argmax(-1)
            lab = label.reshape(-1)
            keep = np.ones(len(lab), bool)
        w = np.asarray(weight).reshape(-1) if weight is not None else np.ones(len(lab))
        self.wrong += float((w * keep * (pred != lab)).sum())
        self.total += float((w * keep).sum())

    def finish(self):
        return self.wrong / max(self.total, 1e-12)


@EVALUATORS.register("seq_error", "sequence_classification_error")
class SequenceErrorEvaluator(Evaluator):
    """Whole-sequence error: a sequence counts wrong if ANY step is wrong."""

    def start(self):
        self.wrong = 0
        self.total = 0

    def update(self, output=None, label=None, lengths=None, **kw):
        output = np.asarray(output)
        label = np.asarray(label)
        pred = output.argmax(-1)
        b, t = pred.shape
        keep = np.arange(t)[None, :] < np.asarray(lengths)[:, None]
        bad = ((pred != label) & keep).any(axis=1)
        self.wrong += int(bad.sum())
        self.total += b

    def finish(self):
        return self.wrong / max(self.total, 1)


@EVALUATORS.register("auc")
class AucEvaluator(Evaluator):
    """Binary AUC via fixed binning (AucEvaluator in Evaluator.cpp uses the
    same discretized approach)."""

    def __init__(self, num_bins: int = 4096):
        self.num_bins = num_bins

    def start(self):
        self.pos = np.zeros(self.num_bins)
        self.neg = np.zeros(self.num_bins)

    def update(self, output=None, label=None, weight=None, **kw):
        output = np.asarray(output)
        p = output[:, 1] if output.ndim == 2 and output.shape[1] == 2 else output.reshape(-1)
        y = np.asarray(label).reshape(-1)
        w = np.asarray(weight).reshape(-1) if weight is not None else np.ones(len(y))
        idx = np.clip((p * self.num_bins).astype(int), 0, self.num_bins - 1)
        np.add.at(self.pos, idx, w * (y == 1))
        np.add.at(self.neg, idx, w * (y != 1))

    def finish(self):
        # sweep thresholds high→low accumulating TP/FP; trapezoid area
        tp = np.cumsum(self.pos[::-1])
        fp = np.cumsum(self.neg[::-1])
        tot_p, tot_n = tp[-1], fp[-1]
        if tot_p == 0 or tot_n == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        return float(np.trapezoid(tpr, fpr))


@EVALUATORS.register("precision_recall")
class PrecisionRecallEvaluator(Evaluator):
    """precision_recall (PrecisionRecallEvaluator): per-class + macro stats."""

    def __init__(self, positive_label: Optional[int] = None):
        self.positive_label = positive_label

    def start(self):
        self.tp: Dict[int, float] = {}
        self.fp: Dict[int, float] = {}
        self.fn: Dict[int, float] = {}

    def update(self, output=None, label=None, weight=None, **kw):
        pred = np.asarray(output).argmax(-1).reshape(-1)
        lab = np.asarray(label).reshape(-1)
        w = np.asarray(weight).reshape(-1) if weight is not None else np.ones(len(lab))
        for c in np.unique(np.concatenate([pred, lab])):
            c = int(c)
            self.tp[c] = self.tp.get(c, 0.0) + float((w * ((pred == c) & (lab == c))).sum())
            self.fp[c] = self.fp.get(c, 0.0) + float((w * ((pred == c) & (lab != c))).sum())
            self.fn[c] = self.fn.get(c, 0.0) + float((w * ((pred != c) & (lab == c))).sum())

    def stats(self, c: int):
        tp, fp, fn = self.tp.get(c, 0.0), self.fp.get(c, 0.0), self.fn.get(c, 0.0)
        prec = tp / max(tp + fp, 1e-12)
        rec = tp / max(tp + fn, 1e-12)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return prec, rec, f1

    def finish(self):
        if self.positive_label is not None:
            return self.stats(self.positive_label)[2]
        f1s = [self.stats(c)[2] for c in self.tp]
        return float(np.mean(f1s)) if f1s else 0.0


@EVALUATORS.register("pnpair")
class PnpairEvaluator(Evaluator):
    """Positive-negative pair ratio grouped by query id (PnpairEvaluator)."""

    def start(self):
        self.records: List[np.ndarray] = []

    def update(self, output=None, label=None, query_id=None, weight=None, **kw):
        score = np.asarray(output).reshape(-1)
        lab = np.asarray(label).reshape(-1)
        qid = np.asarray(query_id).reshape(-1)
        w = np.asarray(weight).reshape(-1) if weight is not None else np.ones(len(lab))
        self.records.append(np.stack([score, lab, qid, w], 1))

    def finish(self):
        if not self.records:
            return 0.0
        rec = np.concatenate(self.records, 0)
        pos, neg, tie = 0.0, 0.0, 0.0
        for q in np.unique(rec[:, 2]):
            grp = rec[rec[:, 2] == q]
            n = len(grp)
            for i in range(n):
                for j in range(i + 1, n):
                    if grp[i, 1] == grp[j, 1]:
                        continue
                    w = grp[i, 3] + grp[j, 3]
                    hi, lo = (i, j) if grp[i, 1] > grp[j, 1] else (j, i)
                    if grp[hi, 0] > grp[lo, 0]:
                        pos += w
                    elif grp[hi, 0] < grp[lo, 0]:
                        neg += w
                    else:
                        tie += w
        return (pos + 0.5 * tie) / max(pos + neg + tie, 1e-12)


RankAucEvaluator = PnpairEvaluator


@EVALUATORS.register("sum")
class SumEvaluator(Evaluator):
    def start(self):
        self.total = 0.0

    def update(self, output=None, weight=None, **kw):
        v = np.asarray(output)
        if weight is not None:
            v = v * np.asarray(weight).reshape((-1,) + (1,) * (v.ndim - 1))
        self.total += float(v.sum())

    def finish(self):
        return self.total


@EVALUATORS.register("column_sum")
class ColumnSumEvaluator(Evaluator):
    def start(self):
        self.total = None
        self.n = 0.0

    def update(self, output=None, **kw):
        v = np.asarray(output).reshape(-1, np.asarray(output).shape[-1])
        s = v.sum(0)
        self.total = s if self.total is None else self.total + s
        self.n += v.shape[0]

    def finish(self):
        return self.total / max(self.n, 1.0)


@EVALUATORS.register("chunk")
class ChunkEvaluator(Evaluator):
    """Chunk-level F1 for sequence labeling (ChunkEvaluator.cpp). Supports the
    same schemes: IOB/IOE/IOBES/plain with num_chunk_types."""

    def __init__(self, scheme: str = "IOB", num_chunk_types: int = 1,
                 excluded_chunk_types=()):
        assert scheme in ("IOB", "IOE", "IOBES", "plain")
        self.scheme = scheme
        self.num_chunk_types = num_chunk_types
        self.excluded = set(excluded_chunk_types or ())

    def start(self):
        self.correct = 0
        self.n_pred = 0
        self.n_label = 0

    def _extract(self, tags: np.ndarray):
        """tag ids → set of (start, end, type) chunks."""
        chunks = []
        start = None
        cur_type = None
        scheme = self.scheme
        n_tag_types = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
        other = n_tag_types * self.num_chunk_types  # the "O" tag id
        for i, t in enumerate(list(tags) + [other]):
            t = int(t)
            if t == other:
                pos, typ = None, None
            else:
                pos, typ = t % n_tag_types, t // n_tag_types
            if scheme == "plain":
                is_start = typ is not None and typ != cur_type
                ends_prev = typ != cur_type
            elif scheme == "IOB":
                is_start = typ is not None and (pos == 0 or typ != cur_type)
                ends_prev = typ is None or pos == 0 or typ != cur_type
            elif scheme == "IOE":
                # pos 0 = I, 1 = E(end)
                is_start = typ is not None and cur_type is None
                ends_prev = typ is None or typ != cur_type
            else:  # IOBES: 0=B 1=I 2=E 3=S
                is_start = typ is not None and pos in (0, 3)
                ends_prev = typ is None or pos in (0, 3)
            if start is not None and ends_prev:
                chunks.append((start, i - 1, cur_type))
                start, cur_type = None, None
            if typ is not None and is_start:
                start, cur_type = i, typ
            elif typ is not None and start is None:
                start, cur_type = i, typ
            if scheme == "IOBES" and typ is not None and pos in (2, 3):
                chunks.append((start, i, cur_type))
                start, cur_type = None, None
            if scheme == "IOE" and typ is not None and pos == 1:
                chunks.append((start, i, cur_type))
                start, cur_type = None, None
        # chunk of these types are not counted (ModelConfig.proto:561)
        return {c for c in chunks if c[2] not in self.excluded}

    def update(self, output=None, label=None, lengths=None, **kw):
        pred = np.asarray(output)
        if pred.ndim == 3:
            pred = pred.argmax(-1)
        lab = np.asarray(label)
        lens = np.asarray(lengths) if lengths is not None else [pred.shape[1]] * pred.shape[0]
        for i in range(pred.shape[0]):
            p_chunks = self._extract(pred[i, : lens[i]])
            l_chunks = self._extract(lab[i, : lens[i]])
            self.correct += len(p_chunks & l_chunks)
            self.n_pred += len(p_chunks)
            self.n_label += len(l_chunks)

    def finish(self):
        prec = self.correct / max(self.n_pred, 1e-12)
        rec = self.correct / max(self.n_label, 1e-12)
        return 2 * prec * rec / max(prec + rec, 1e-12)


def _edit_distance(a, b) -> int:
    """Levenshtein distance between two id sequences (host-side numpy DP —
    same role as CTCErrorEvaluator.cpp's per-pair editDistance)."""
    la, lb = len(a), len(b)
    if la == 0:
        return lb
    if lb == 0:
        return la
    prev = np.arange(lb + 1)
    for i in range(1, la + 1):
        cur = np.empty(lb + 1, dtype=np.int64)
        cur[0] = i
        for j in range(1, lb + 1):
            cur[j] = min(
                prev[j] + 1,
                cur[j - 1] + 1,
                prev[j - 1] + (0 if a[i - 1] == b[j - 1] else 1),
            )
        prev = cur
    return int(prev[lb])


@EVALUATORS.register("ctc_edit_distance", "ctc_error")
class CTCErrorEvaluator(Evaluator):
    """Sequence error rate of the CTC best path vs the gold label sequence
    (CTCErrorEvaluator.cpp): sum(edit_distance) / sum(label_len).

    update() takes either pre-decoded ids (`decoded`, -1-padded, from
    ops.ctc.ctc_greedy_decode) or raw `output` logits [B, T, C] which are
    greedy-decoded on host."""

    def start(self):
        self.total_dist = 0
        self.total_len = 0

    def update(
        self,
        label=None,
        label_lengths=None,
        decoded=None,
        output=None,
        lengths=None,
        blank=0,
        **kw,
    ):
        lab = np.asarray(label)
        lab_lens = (
            np.asarray(label_lengths)
            if label_lengths is not None
            else np.full(lab.shape[0], lab.shape[1])
        )
        if decoded is None:
            logits = np.asarray(output)
            lens = (
                np.asarray(lengths)
                if lengths is not None
                else np.full(logits.shape[0], logits.shape[1])
            )
            rows = []
            for i in range(logits.shape[0]):
                ids = logits[i, : lens[i]].argmax(-1)
                if len(ids) == 0:
                    rows.append(ids)
                    continue
                keep = np.concatenate([[True], ids[1:] != ids[:-1]])
                ids = ids[keep]
                rows.append(ids[ids != blank])
            dec_rows = rows
        else:
            dec = np.asarray(decoded)
            dec_rows = [row[row >= 0] for row in dec]
        for i, d in enumerate(dec_rows):
            g = lab[i, : lab_lens[i]]
            self.total_dist += _edit_distance(list(d), list(g))
            self.total_len += len(g)

    def finish(self):
        return self.total_dist / max(self.total_len, 1e-12)


@EVALUATORS.register("detection_map")
class DetectionMAPEvaluator(Evaluator):
    """Mean average precision over detection outputs
    (DetectionMAPEvaluator.cpp): accumulates per-class scored TP/FP marks
    across batches, then AP per class by 11-point or integral rule.

    update(detections=[B, K, 6] rows (label, score, xmin, ymin, xmax, ymax;
    score==0 padding), gt_boxes=[B, G, 4], gt_labels=[B, G],
    gt_lengths=[B]) — the padded-tensor form of the reference's sequence
    label input."""

    def __init__(self, overlap_threshold=0.5, ap_type="11point", background_id=0):
        self.overlap_threshold = overlap_threshold
        self.ap_type = ap_type
        self.background_id = background_id

    def start(self):
        self.marks = {}  # class -> list of (score, is_tp)
        self.n_gt = {}  # class -> count

    @staticmethod
    def _iou(a, b):
        lt = np.maximum(a[:2], b[:2])
        rb = np.minimum(a[2:], b[2:])
        wh = np.maximum(rb - lt, 0.0)
        inter = wh[0] * wh[1]
        ua = max(a[2] - a[0], 0) * max(a[3] - a[1], 0)
        ub = max(b[2] - b[0], 0) * max(b[3] - b[1], 0)
        return inter / max(ua + ub - inter, 1e-12)

    def update(self, detections=None, gt_boxes=None, gt_labels=None, gt_lengths=None, **kw):
        det = np.asarray(detections)
        gtb = np.asarray(gt_boxes)
        gtl = np.asarray(gt_labels)
        lens = (
            np.asarray(gt_lengths)
            if gt_lengths is not None
            else np.full(gtb.shape[0], gtb.shape[1])
        )
        for i in range(det.shape[0]):
            gts = gtb[i, : lens[i]]
            gls = gtl[i, : lens[i]]
            for c in np.unique(gls):
                if c == self.background_id:
                    continue
                self.n_gt[int(c)] = self.n_gt.get(int(c), 0) + int((gls == c).sum())
            used = np.zeros(len(gts), bool)
            rows = det[i]
            rows = rows[rows[:, 1] > 0]
            rows = rows[np.argsort(-rows[:, 1])]
            for row in rows:
                c, score, box = int(row[0]), float(row[1]), row[2:6]
                cand = np.where((gls == c) & ~used)[0]
                best_j, best_iou = -1, self.overlap_threshold
                for j in cand:
                    v = self._iou(box, gts[j])
                    if v >= best_iou:
                        best_j, best_iou = j, v
                tp = best_j >= 0
                if tp:
                    used[best_j] = True
                self.marks.setdefault(c, []).append((score, tp))

    def finish(self):
        aps = []
        for c, n_pos in self.n_gt.items():
            marks = sorted(self.marks.get(c, []), key=lambda t: -t[0])
            if n_pos == 0:
                continue
            if not marks:
                aps.append(0.0)
                continue
            tps = np.cumsum([m[1] for m in marks])
            fps = np.cumsum([not m[1] for m in marks])
            recall = tps / n_pos
            precision = tps / np.maximum(tps + fps, 1e-12)
            if self.ap_type == "11point":
                ap = 0.0
                for r in np.linspace(0, 1, 11):
                    p = precision[recall >= r].max() if (recall >= r).any() else 0.0
                    ap += p / 11.0
            else:  # integral
                ap = 0.0
                prev_r = 0.0
                for k in range(len(marks)):
                    ap += precision[k] * (recall[k] - prev_r)
                    prev_r = recall[k]
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0


@EVALUATORS.register("value_printer")
class ValuePrinter(Evaluator):
    """Utility evaluator (Evaluator.cpp ValuePrinter): logs layer outputs
    each batch — the debugging role of the reference printer evaluators."""

    def __init__(self, writer=None, **_kw):
        import sys

        self._write = writer or (lambda s: sys.stderr.write(s + "\n"))

    def start(self):
        self.batches = 0

    def update(self, **kw):
        self.batches += 1
        for k, v in kw.items():
            if v is None:
                continue
            arr = np.asarray(v)
            with np.printoptions(threshold=64, precision=6):
                self._write(f"[value_printer] {k}: shape={arr.shape} {arr}")

    def finish(self):
        return float(self.batches)


@EVALUATORS.register("gradient_printer")
class GradientPrinter(ValuePrinter):
    """GradientPrinter declaration compatibility. Per-layer gradients never
    leave the compiled step here (autodiff inside jit), so this prints the
    forward value and says so — the config keeps parsing and running."""

    def update(self, **kw):
        self.batches += 1
        for k, v in kw.items():
            if v is None:
                continue
            arr = np.asarray(v)
            with np.printoptions(threshold=64, precision=6):
                self._write(
                    f"[gradient_printer] {k} (forward value; grads stay "
                    f"inside the compiled step): shape={arr.shape} {arr}"
                )


@EVALUATORS.register("max_id_printer")
class MaxIdPrinter(ValuePrinter):
    """utils max_id printer: top-k argmax ids of the output distribution."""

    def __init__(self, num_results: int = 1, writer=None, **_kw):
        super().__init__(writer)
        self.k = max(1, int(num_results))

    def update(self, output=None, **kw):
        if output is None:
            return
        self.batches += 1
        arr = np.asarray(output)
        flat = arr.reshape(-1, arr.shape[-1])
        top = np.argsort(-flat, axis=-1)[:, : self.k]
        self._write(f"[max_id_printer] top{self.k} ids: {top[:8].tolist()}")


@EVALUATORS.register("classification_error_printer")
class ClassificationErrorPrinter(ValuePrinter):
    """Prints the per-example 0/1 error vector (utils printer parity)."""

    def update(self, output=None, label=None, **kw):
        if output is None or label is None:
            return
        self.batches += 1
        pred = np.asarray(output).reshape(-1, np.asarray(output).shape[-1]).argmax(-1)
        lab = np.asarray(label).reshape(-1)
        err = (pred != lab[: len(pred)]).astype(np.int32)
        self._write(f"[classification_error_printer] err={err[:32].tolist()}")


@EVALUATORS.register("seq_text_printer")
class SequenceTextPrinter(Evaluator):
    """seqtext_printer_evaluator → SequenceTextPrinter (Evaluator.cpp:1192):
    dump generated sequences to `result_file`, byte-compatible with the
    reference's three output shapes — plain per-sample lines, beam blocks
    (`sample\\n rank\\tscore\\t toks...` per result, Evaluator.cpp:1303 beam
    print), and nested per-subsequence lines (Evaluator.cpp:1286)."""

    def __init__(self, result_file: str, dict_file: str = "",
                 delimited: bool = True, **_kw):
        self.result_file = result_file
        self.delimited = delimited
        self.dict: list = []
        if dict_file:
            with open(dict_file) as f:
                self.dict = [line.rstrip("\n") for line in f]
        self._fh = None

    def start(self):
        self._fh = open(self.result_file, "w")

    def _toks(self, ids) -> str:
        sep = " " if self.delimited else ""
        return "".join(
            sep + (self.dict[int(i)] if self.dict else str(int(i)))
            for i in ids
        )

    def _fmt_score(self, v: float) -> str:
        # C++ default ostream float formatting (6 significant digits)
        return f"{float(v):g}"

    def update(self, output=None, sample_ids=None, beam=None, lengths=None,
               sub_lengths=None, **_kw):
        """output: [B, L] best-beam ids (or [B, S, L] nested); lengths [B]
        (valid subsequence count when nested); sub_lengths [B, S] per-subseq
        token counts; beam: the generation payload cached by BeamSearchLayer
        {history [B,K,L], scores [B,K], lengths [B,K], num_results}."""
        out = self._fh
        values = None if output is None else np.asarray(output)
        beam_mode = beam is not None and int(beam.get("num_results", 1)) > 1
        if values is None and beam is not None and not beam_mode:
            # best-beam fallback when the caller hands only the payload
            values = np.asarray(beam["history"])[:, 0]
            lengths = np.asarray(beam["lengths"])[:, 0]
        nested = values is not None and values.ndim == 3
        if beam_mode:
            all_hist = np.asarray(beam["history"])
            all_scores = np.asarray(beam["scores"])
            all_lens = np.asarray(beam["lengths"])
            n = len(all_hist)
        elif values is None:
            raise ValueError(
                "SequenceTextPrinter.update needs `output` ids or a beam "
                "payload with history/lengths; got neither — is the "
                "evaluator's input layer among the network outputs?"
            )
        else:
            n = len(values)
        ids_flat = (
            None if sample_ids is None else np.asarray(sample_ids).reshape(-1)
        )
        lengths = None if lengths is None else np.asarray(lengths)
        sub_lengths = None if sub_lengths is None else np.asarray(sub_lengths)
        for i in range(n):
            sid = i if ids_flat is None else int(ids_flat[i])
            out.write(str(sid))
            # each sample ends with the evalImp loop's final endl; in plain
            # mode it terminates the line, in beam/nested modes (whose inner
            # lines carry their own endl) it yields the blank separator line
            if beam_mode:
                hist, scores, lens = all_hist[i], all_scores[i], all_lens[i]
                out.write("\n")
                for j in range(int(beam["num_results"])):
                    out.write(f"{j}\t{self._fmt_score(scores[j])}\t")
                    out.write(self._toks(hist[j, : lens[j]]) + "\n")
            elif nested:
                n_sub = int(lengths[i]) if lengths is not None else values.shape[1]
                sl = sub_lengths[i] if sub_lengths is not None else None
                for s in range(n_sub):
                    t = int(sl[s]) if sl is not None else values.shape[2]
                    out.write("\t" + self._toks(values[i, s, :t]) + "\n")
            else:
                t = int(lengths[i]) if lengths is not None else values.shape[1]
                out.write("\t" + self._toks(values[i, :t]))
            out.write("\n")

    def finish(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        return 0.0
