from paddle_tpu.metrics.evaluators import (  # noqa: F401
    AucEvaluator,
    ChunkEvaluator,
    ClassificationErrorEvaluator,
    ColumnSumEvaluator,
    Evaluator,
    PnpairEvaluator,
    PrecisionRecallEvaluator,
    RankAucEvaluator,
    SequenceErrorEvaluator,
    SumEvaluator,
)
