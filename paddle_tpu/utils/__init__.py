"""Tooling (python/paddle/utils parity, SURVEY §2.4 'tooling only'):
dump_config lives on the CLI; here: model diagrams, training-curve plotting,
merged-model inspection."""

from paddle_tpu.utils.make_model_diagram import make_diagram, to_dot  # noqa: F401
from paddle_tpu.utils.show_pb import show_merged_model  # noqa: F401
