"""Inspect a merged model file (python/paddle/utils/show_pb.py parity):
prints the stored TrainerConfig text + parameter table."""

from __future__ import annotations

from typing import TextIO

import numpy as np


def show_merged_model(path: str, out: TextIO = None) -> str:
    import io
    import sys

    buf = io.StringIO()
    with np.load(path, allow_pickle=False) as z:
        if "__trainer_config__" in z.files:
            buf.write(str(z["__trainer_config__"]))
            buf.write("\n")
        buf.write("parameters:\n")
        for k in sorted(z.files):
            if k.startswith("param/"):
                a = z[k]
                buf.write(f"  {k[6:]}: shape={tuple(a.shape)} dtype={a.dtype}\n")
    text = buf.getvalue()
    (out or sys.stdout).write(text)
    return text
