"""Graphviz diagram of a network (python/paddle/utils/make_model_diagram.py)."""

from __future__ import annotations

from typing import Sequence, Union

from paddle_tpu.nn.graph import Layer, Network


def to_dot(topology: Union[Layer, Sequence[Layer], Network], name: str = "model") -> str:
    if isinstance(topology, Network):
        net = topology
    else:
        net = Network(topology)
    lines = [f"digraph {name} {{", "  rankdir=BT;"]
    for layer in net.layer_order:
        shape = "box" if layer.type_name != "data" else "oval"
        lines.append(
            f'  "{layer.name}" [label="{layer.name}\\n({layer.type_name})" '
            f"shape={shape}];"
        )
        for inp in layer.inputs:
            lines.append(f'  "{inp.name}" -> "{layer.name}";')
    lines.append("}")
    return "\n".join(lines)


def make_diagram(topology, output_path: str, name: str = "model") -> str:
    """Write .dot; renders to .png when the graphviz binary exists."""
    import shutil
    import subprocess

    dot = to_dot(topology, name)
    dot_path = output_path if output_path.endswith(".dot") else output_path + ".dot"
    with open(dot_path, "w") as f:
        f.write(dot)
    if shutil.which("dot") and not output_path.endswith(".dot"):
        subprocess.run(
            ["dot", "-Tpng", dot_path, "-o", output_path], check=False, timeout=60
        )
    return dot_path
