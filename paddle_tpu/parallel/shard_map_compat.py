"""shard_map import + kwarg compatibility across jax versions.

The replication-check kwarg was renamed over jax's life: `check_rep`
(experimental shard_map, <= 0.4.x) became `check_vma` when shard_map moved to
the jax namespace. Code in this repo targets the newer spelling; this shim
feature-detects what the installed jax actually accepts and translates, so
the same call sites run on jax 0.4.37 (the container's pin) and on current
jax without a version switch at every call site.
"""

from __future__ import annotations

import functools
import inspect

try:  # moved out of experimental in newer jax
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)
# the two spellings of the replication/varying-manual-axes check kwarg
_CHECK_ALIASES = ("check_vma", "check_rep")


def shard_map(f=None, /, **kwargs):
    """`jax.shard_map` with `check_vma`/`check_rep` translated to whichever
    spelling the installed jax supports (dropped when it supports neither).
    Usable exactly like the real one, including partial application:
    `functools.partial(shard_map, mesh=..., in_specs=..., out_specs=...)`."""
    for given in _CHECK_ALIASES:
        if given in kwargs and given not in _PARAMS:
            value = kwargs.pop(given)
            other = next(a for a in _CHECK_ALIASES if a != given)
            if other in _PARAMS:
                kwargs[other] = value
    if f is None:
        return functools.partial(shard_map, **kwargs)
    return _shard_map(f, **kwargs)
