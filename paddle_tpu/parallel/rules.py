"""Named sharding rules: logical array axes -> mesh axes (ISSUE 12).

The problem with raw ``ParamAttr.sharding`` tuples is that every call site
hard-codes MESH axis names ("model", "expert") into model code, so the same
model cannot move between a data-only training mesh, a 2-D dp x tp mesh and
a serving TP mesh without editing each tuple.  The fix is the DEFAULT_RULES
pattern (SNIPPETS.md [2]/[3], the t5x/flax ``logical_axis_rules`` idiom):

  * arrays declare LOGICAL axis names once at creation
    (``ParamAttr(logical_axes=("embed", "mlp"))``, or
    ``ServableLM.param_logical_axes()`` for the serving LM), and
  * ONE rules table maps logical names to mesh axes for the deployment at
    hand — ``{"batch": "data", "heads": "model", "mlp": "model", ...}``.

Training (ShardedUpdater canonical seams, elastic resize, checkpoints) and
serving then share a single sharding vocabulary: re-deploying the same
model on a different mesh is a rules-table edit, not a model edit.

Resolution semantics:

  * a logical name maps through the table to a mesh axis (or None =
    replicated);
  * a resolved mesh axis NOT present in the target mesh resolves to
    replicated — that is what lets a model declaring ``heads: "model"``
    run unchanged on the single-axis data mesh the CPU tests use and on a
    real TP mesh (the rules name the full vocabulary, the mesh decides
    which entries bite);
  * a name in neither the table nor the mesh axes raises, naming the
    parameter — typos must not silently replicate;
  * legacy ``ParamAttr.sharding`` tuples (mesh-axis names used directly)
    keep working as a deprecation shim: every mesh-axis name is implicitly
    a logical name that resolves to itself, so old call sites translate
    INTO the table rather than bypassing it.

``pipeline`` is deliberately present but unmapped: PARITY §2.5 reserves a
pipeline-parallel axis, and reserving it as a rules-table entry means the
day the mesh grows a "pipe" axis the mapping is one line here."""

from __future__ import annotations

import warnings
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.mesh import AXES, make_mesh

# the legacy ParamAttr(sharding=...) shim warns EXACTLY once per process —
# per-call warnings would spam every step trace of a legacy model, and
# python's default "once" filter dedups per call SITE, not per process
_legacy_sharding_warned = False


def warn_legacy_sharding(param: str) -> None:
    """One DeprecationWarning per process for raw mesh-axis ParamAttr.sharding
    tuples (they still resolve through the rules table's identity shim)."""
    global _legacy_sharding_warned
    if _legacy_sharding_warned:
        return
    _legacy_sharding_warned = True
    warnings.warn(
        f"ParamAttr(sharding=...) mesh-axis tuples are deprecated (first "
        f"seen on {param!r}): declare ParamAttr(logical_axes=...) and let "
        f"the rules table (parallel/rules.py DEFAULT_RULES) map logical "
        f"axes to mesh axes",
        DeprecationWarning,
        stacklevel=3,
    )

# the one serving+training sharding vocabulary (SNIPPETS.md DEFAULT_RULES
# pattern). Values are mesh axis names or None (replicated).
DEFAULT_RULES: Dict[str, Optional[str]] = {
    "batch": "data",      # batch rows over the data axis
    "heads": "model",     # attention query heads (column-parallel qkv)
    "kv_heads": "model",  # KV heads — the paged KV pool shards this too
    "mlp": "model",       # MLP hidden (column-parallel w1 / row-parallel w2)
    "vocab": "model",     # embed rows / unembed columns
    "embed": None,        # d_model stays replicated (activations are small)
    "length": None,       # sequence positions (the seq axis exists for ring
                          # attention; decode activations never shard it)
    "expert": "expert",   # row-sharded embedding tables (parallel/embedding)
    "pipeline": None,     # RESERVED (PARITY §2.5): maps to a mesh axis the
                          # day pipeline parallelism lands — a table edit
}


class ShardingRules:
    """A logical-axis -> mesh-axis table with validated resolution.

    ``spec_for`` is the single resolution seam: DataParallel.param_sharding
    (training) and ServableLM.param_sharding (serving) both call it, so the
    two runtimes cannot drift on what a logical name means."""

    def __init__(self, rules: Optional[Dict[str, Optional[str]]] = None):
        self.table: Dict[str, Optional[str]] = dict(
            DEFAULT_RULES if rules is None else rules
        )

    def with_overrides(self, **overrides: Optional[str]) -> "ShardingRules":
        return ShardingRules({**self.table, **overrides})

    def mesh_axis(
        self,
        logical: Optional[str],
        mesh: Optional[Mesh] = None,
        param: str = "<array>",
    ) -> Optional[str]:
        """One logical name -> the mesh axis it shards over (None =
        replicated). Unknown names that are not mesh axes raise, naming the
        parameter; known names whose mesh axis is absent from `mesh` resolve
        to replicated (see module docstring)."""
        if logical is None:
            return None
        if logical in self.table:
            axis = self.table[logical]
        elif logical in AXES or (mesh is not None and logical in mesh.axis_names):
            # deprecation shim: a raw mesh-axis name (legacy
            # ParamAttr.sharding tuples) is its own logical name
            axis = logical
        else:
            raise KeyError(
                f"unknown logical sharding axis {logical!r} for {param!r}: "
                f"not in the rules table {sorted(self.table)} and not a mesh "
                "axis — add a rules entry or fix the axis name"
            )
        if axis is not None and mesh is not None and axis not in mesh.axis_names:
            return None  # the mesh has no such axis: this entry does not bite
        return axis

    def spec_for(
        self,
        logical_axes: Sequence[Optional[str]],
        mesh: Optional[Mesh] = None,
        ndim: Optional[int] = None,
        param: str = "<array>",
    ) -> P:
        """Resolve a logical-axes tuple to a PartitionSpec.

        A spec LONGER than the array's rank is rejected loudly (the silent
        truncation this replaces dropped trailing axes — a param declared
        ("mlp", "embed") on a 1-D bias would silently shard over "mlp");
        shorter specs pad with None (trailing dims replicated), the
        documented convenience."""
        axes = tuple(logical_axes)
        if ndim is not None:
            if len(axes) > ndim:
                raise ValueError(
                    f"sharding spec {axes} for {param!r} names {len(axes)} "
                    f"axes but the array has rank {ndim} — rank-mismatched "
                    "specs are rejected (they used to be silently truncated)"
                )
            axes = axes + (None,) * (ndim - len(axes))
        return P(*[self.mesh_axis(a, mesh, param) for a in axes])

    def sharding_for(
        self,
        mesh: Mesh,
        logical_axes: Sequence[Optional[str]],
        ndim: Optional[int] = None,
        param: str = "<array>",
    ) -> NamedSharding:
        return NamedSharding(
            mesh, self.spec_for(logical_axes, mesh, ndim, param)
        )


def make_tp_mesh(
    tp: int,
    data: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """The 2-D ("data", "model") mesh the rules table targets: `tp` chips on
    the model axis, `data` replicas on the data axis (serving uses data=1 —
    replica scale-out is the router's job, ROADMAP item 1). Axis order
    follows mesh.AXES so the data axis stays the outermost, the layout every
    trainer/updater assumes."""
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"tensor-parallel size must be >= 1, got {tp}")
    return make_mesh({"data": int(data), "model": tp}, devices=devices)
