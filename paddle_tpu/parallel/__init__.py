from paddle_tpu.parallel.mesh import make_mesh  # noqa: F401
from paddle_tpu.parallel.data_parallel import DataParallel  # noqa: F401
from paddle_tpu.parallel import distributed as distributed  # noqa: F401
