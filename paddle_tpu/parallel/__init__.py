from paddle_tpu.parallel.mesh import make_mesh, resize_mesh  # noqa: F401
from paddle_tpu.parallel.rules import (  # noqa: F401
    DEFAULT_RULES,
    ShardingRules,
    make_tp_mesh,
)
from paddle_tpu.parallel.data_parallel import DataParallel  # noqa: F401
from paddle_tpu.parallel import distributed as distributed  # noqa: F401
from paddle_tpu.parallel.sequence_parallel import (  # noqa: F401
    ring_attention,
    ulysses_attention,
)
from paddle_tpu.parallel.embedding import (  # noqa: F401
    ShardedEmbeddingState,
    shard_table,
    sharded_lookup,
)
from paddle_tpu.parallel.updaters import (  # noqa: F401
    IciAllReduceUpdater,
    ParameterUpdater,
    SgdLocalUpdater,
    ShardedUpdater,
    Zero2Updater,
    Zero3Updater,
)
from paddle_tpu.parallel import compression as compression  # noqa: F401
