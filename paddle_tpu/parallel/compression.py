"""Compressed gradient collectives for the sharded (ZeRO-1/2/3) update.

"EQuARX: Efficient Quantized AllReduce in XLA" (PAPERS.md) shows the
gradient all-reduce can run quantized at near-zero quality cost. Here the
all-reduce is already decomposed by the ShardedUpdater into its phases —
reduce-scatter of gradients, all-gather of updated parameters (ZeRO-1/2) or
on-demand all-gather of resident-sharded parameters (ZeRO-3) — and each
phase's payload is quantized just before it crosses the collective boundary
(the `with_sharding_constraint` resharding point) and dequantized just after:

  bf16:  gradients and the parameter-delta gather both cross in bfloat16
         (half the f32 bytes on each leg → 2x total).
  int8:  gradients cross as block-scaled int8 (one f32 scale per
         BLOCK-element block, ~3.8x on the scatter leg) with an
         error-feedback residual carried in the train state so the
         quantization error is re-injected next step (1-bit-Adam style EF —
         int8 SGD without it plateaus); the gather leg stays bf16.

The gather leg of a compressed mode transports the parameter DELTA
(new - old), not the parameter: every replica holds the f32 master and adds
the dequantized increment, so master weights never round-trip through the
narrow dtype. The `none` mode gathers the updated parameter itself, which is
what keeps that path bitwise-identical to the replicated updater.

ZeRO-3 (the Zero3Updater) swaps the legs' roles: parameters live sharded and
the hot leg is the on-demand PARAM all-gather inside the forward (plus its
remat re-gather in the backward). That leg quantizes symmetrically INSIDE
the collective (the EQuARX all-gather case): each shard encodes its OWN rows
before the gather and every chip decodes the identical payload after, so the
decode is deterministic and SPMD-consistent — under int8 with a per-master
error-feedback residual (`encode_param_gather`), carried in opt_state["ef"]
just like the scatter EF, so the forward's quantized view chases the exact
f32 master instead of drifting. The ZeRO-3 grad leg needs no explicit
encode: the gather's autodiff transpose delivers cotangents already in the
flat [n, chunk] layout and the updater crosses them via
encode_z3_scatter/decode (bf16 for the compressed modes — grad EF is a
ZeRO-1/2 feature; under ZeRO-3 the residual budget belongs to the params).

Realization note (honest accounting): the quantize runs inside the jit
global-view program, so what XLA materializes on the wire depends on its
collective-forming passes — on TPU the weight-update-sharding pass
(PAPERS.md "Automatic Cross-Replica Sharding of Weight Update...") forms a
reduce-scatter at the constraint point and the narrow payload crosses ICI;
the CPU oracle validates the math, not wire bytes. `scatter_bytes`/
`gather_bytes` report the payload size at the collective boundary under the
ring convention (bytes/chip = payload * (n-1)/n per phase).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp

# int8 block size: one f32 scale per 64 elements (6% overhead on the int8
# payload); chunk layouts are aligned to this so blocks never straddle shards
BLOCK = 64

MODES = ("none", "bf16", "int8")


class GradCompression:
    """No-op transport: f32 on both legs; gather carries the parameter
    itself (bitwise-exact vs the replicated updater)."""

    name = "none"
    uses_error_feedback = False
    chunk_align = 1
    scatter_itemsize = 4.0  # effective bytes/element at the scatter boundary
    gather_itemsize = 4.0
    # ZeRO-3 legs: the on-demand param all-gather (forward + remat re-gather)
    # and the cotangent crossing at the updater's scatter constraint
    param_gather_itemsize = 4.0
    z3_scatter_itemsize = 4.0
    # dtype labels for the per-leg collective-bytes detail (observability)
    scatter_dtype = "f32"
    gather_dtype = "f32"
    param_gather_dtype = "f32"
    z3_scatter_dtype = "f32"

    # -- scatter leg (gradients) ----------------------------------------
    # encode_scatter returns (payload, new_ef) where payload is a TUPLE of
    # [n, w] arrays: the ShardedUpdater concatenates position-wise across
    # parameters so each position crosses the collective as ONE array (the
    # ZeRO flat-buffer layout — collective count independent of param count).
    def encode_scatter(self, g2, ef) -> Tuple[Tuple[Any, ...], Any]:
        """[n, chunk] f32 grads (+ error-feedback residual or None) →
        ((payload arrays...), new_ef). Payload crosses the reduce-scatter."""
        return (g2,), None

    def decode_scatter(self, payload: Tuple[Any, ...]):
        return payload[0]

    # -- gather leg (updated parameters) --------------------------------
    def encode_gather(self, new_p2, p2):
        """Updated [n, chunk] param shards (+ pre-update shards) → payload
        for the all-gather."""
        return new_p2

    def decode_gather(self, payload, p_full2):
        """Gathered payload (+ full pre-update flat param) → new flat param."""
        return payload

    # -- ZeRO-3 param-gather leg (quantize-inside-all-gather) ------------
    # Each shard encodes its OWN [n, chunk] rows (only the owned row is
    # real data under the P(data) layout); the payload tuple crosses the
    # all-gather (the wsc-to-replicated point in Zero3Updater.materialize)
    # and every chip decodes identically — symmetric quantization inside
    # the collective, exact in the sense that all chips compute the same
    # dequantized view. `ef` is the per-master error-feedback residual
    # (int8 only): encode returns (payload, new_ef); the updater persists
    # new_ef in opt_state["ef"] at apply time by recomputing this encode
    # on the pre-update params (deterministic, collective-free).
    def encode_param_gather(self, p2, ef) -> Tuple[Tuple[Any, ...], Any]:
        return (p2,), None

    def decode_param_gather(self, payload: Tuple[Any, ...]):
        return payload[0]

    # -- ZeRO-3 grad leg -------------------------------------------------
    # The gather transpose hands the updater cotangents already in the
    # flat [n, chunk] layout; they cross the scatter constraint encoded
    # here (no error feedback — see module docstring).
    def encode_z3_scatter(self, g2):
        return g2

    def decode_z3_scatter(self, payload):
        return payload


class Bf16Compression(GradCompression):
    name = "bf16"
    scatter_itemsize = 2.0
    gather_itemsize = 2.0
    param_gather_itemsize = 2.0
    z3_scatter_itemsize = 2.0
    scatter_dtype = "bf16"
    gather_dtype = "bf16"
    param_gather_dtype = "bf16"
    z3_scatter_dtype = "bf16"

    def encode_scatter(self, g2, ef):
        return (g2.astype(jnp.bfloat16),), None

    def decode_scatter(self, payload):
        return payload[0].astype(jnp.float32)

    def encode_gather(self, new_p2, p2):
        return (new_p2 - p2).astype(jnp.bfloat16)

    def decode_gather(self, payload, p_full2):
        return p_full2 + payload.astype(jnp.float32)

    def encode_param_gather(self, p2, ef):
        # params cross the on-demand gather in bf16: the forward computes on
        # the rounded view, the f32 master stays exact on the owning shard
        return (p2.astype(jnp.bfloat16),), None

    def decode_param_gather(self, payload):
        return payload[0].astype(jnp.float32)

    def encode_z3_scatter(self, g2):
        return g2.astype(jnp.bfloat16)

    def decode_z3_scatter(self, payload):
        return payload.astype(jnp.float32)


def _block_quantize(x2):
    """[n, chunk] f32 → (int8 [n, chunk], f32 scales [n, chunk/BLOCK]).
    chunk must be BLOCK-aligned (chunk_align below guarantees it)."""
    n, chunk = x2.shape
    blocks = x2.reshape(n, chunk // BLOCK, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-30)  # all-zero block: avoid 0-div
    q = jnp.clip(jnp.round(blocks / scale[..., None]), -127, 127)
    return q.reshape(n, chunk).astype(jnp.int8), scale


def _block_dequantize(q, scale):
    n, chunk = q.shape
    blocks = q.astype(jnp.float32).reshape(n, chunk // BLOCK, BLOCK)
    return (blocks * scale[..., None]).reshape(n, chunk)


class Int8Compression(GradCompression):
    """Block-scaled int8 gradients with error feedback; bf16 delta gather.
    Under ZeRO-3 the int8 + EF budget moves to the param-gather leg (the hot
    one there) and the cotangent crossing runs bf16."""

    name = "int8"
    uses_error_feedback = True
    chunk_align = BLOCK
    scatter_itemsize = 1.0 + 4.0 / BLOCK  # int8 payload + f32 scale per block
    gather_itemsize = 2.0
    param_gather_itemsize = 1.0 + 4.0 / BLOCK
    z3_scatter_itemsize = 2.0
    scatter_dtype = "int8+f32scale"
    gather_dtype = "bf16"
    param_gather_dtype = "int8+f32scale"
    z3_scatter_dtype = "bf16"

    def encode_scatter(self, g2, ef):
        corrected = g2 if ef is None else g2 + ef
        q, scale = _block_quantize(corrected)
        # residual of THIS step's quantization, re-injected next step; the
        # dequantize here is replicated-local math, not a second collective
        new_ef = corrected - _block_dequantize(q, scale)
        return (q, scale), new_ef

    def decode_scatter(self, payload):
        q, scale = payload
        return _block_dequantize(q, scale)

    def encode_gather(self, new_p2, p2):
        return (new_p2 - p2).astype(jnp.bfloat16)

    def decode_gather(self, payload, p_full2):
        return p_full2 + payload.astype(jnp.float32)

    def encode_param_gather(self, p2, ef):
        # EQuARX-style quantize-inside-all-gather with error feedback on the
        # MASTER: the forward sees dequant(quant(p + ef)); the residual of
        # that quantization is re-injected next step, so the quantized view
        # tracks the exact f32 master instead of accumulating drift
        corrected = p2 if ef is None else p2 + ef
        q, scale = _block_quantize(corrected)
        new_ef = corrected - _block_dequantize(q, scale)
        return (q, scale), new_ef

    def decode_param_gather(self, payload):
        q, scale = payload
        return _block_dequantize(q, scale)

    def encode_z3_scatter(self, g2):
        return g2.astype(jnp.bfloat16)

    def decode_z3_scatter(self, payload):
        return payload.astype(jnp.float32)


def make(mode: Optional[str]) -> GradCompression:
    mode = mode or "none"
    if mode == "none":
        return GradCompression()
    if mode == "bf16":
        return Bf16Compression()
    if mode == "int8":
        return Int8Compression()
    raise ValueError(f"grad_compression must be one of {MODES}, got {mode!r}")
