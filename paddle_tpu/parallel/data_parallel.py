"""Data (+tensor) parallel execution of the compiled train step.

The TPU-native collapse of three reference mechanisms (SURVEY §2.5):
- MultiGradientMachine's intra-node ring (MultiGradientMachine.h:44-157:
  batch split across trainer threads, ring grad-gather + value-scatter),
- the sync pserver round-trip (RemoteParameterUpdater.h:55 →
  ParameterServer2::addGradient with ThreadBarrier),
- Fluid's NCCL allreduce ops (operators/nccl_op.cu:80).

Here: the batch is sharded over the mesh 'data' axis, parameters are
replicated (or sharded over 'model' per ParamAttr.sharding = tensor
parallelism, the free generalization of ParallelNeuralNetwork's per-layer
device placement), and jit's SPMD partitioner inserts the all-reduce over
ICI — the ring the reference hand-codes is what the hardware collective does."""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.nn.graph import SAMPLE_MASK_KEY, ParamAttr

log = logging.getLogger("paddle_tpu.parallel")


class DataParallel:
    """Plugs into SGDTrainer(parallel=...). `batch_axis` shards batches;
    param shardings come from ParamAttr.logical_axes resolved through the
    rules table (parallel/rules.py), with legacy ParamAttr.sharding
    mesh-axis tuples translated through the same table as a shim."""

    def __init__(
        self,
        mesh: Mesh,
        batch_axis: str = "data",
        param_attrs: Optional[Dict[str, ParamAttr]] = None,
        rules=None,
    ):
        from paddle_tpu.parallel.rules import ShardingRules

        self.mesh = mesh
        self.batch_axis = batch_axis
        self.param_attrs = param_attrs or {}
        self.rules = rules if rules is not None else ShardingRules()
        self._replicated = NamedSharding(mesh, P())
        self._batch_sharding = NamedSharding(mesh, P(batch_axis))
        # K-stacked ([K, B, ...]) placement: scan axis unsharded, batch axis
        # over the data axis — cached because is_sharded_batches runs per
        # dispatch in the train hot loop
        self._batches_sharding = NamedSharding(mesh, P(None, batch_axis))

    # -- sharding rules ------------------------------------------------------
    def param_sharding(self, name: str, ndim: int) -> NamedSharding:
        """Resolve one parameter's placement through the rules table:
        `logical_axes` wins, the deprecated mesh-axis `sharding` tuple rides
        the table's identity shim. Rank-mismatched specs (more axes than the
        array has dims) raise naming the param — they used to be silently
        truncated, which sharded the WRONG dims of any param whose spec
        outlived a shape change."""
        attr = self.param_attrs.get(name)
        if attr is None:
            return self._replicated
        axes = attr.logical_axes
        if axes is None:
            axes = attr.sharding
            if axes is not None:
                from paddle_tpu.parallel.rules import warn_legacy_sharding

                warn_legacy_sharding(name)  # once per process
        if axes is None:
            return self._replicated
        return self.rules.sharding_for(self.mesh, axes, ndim=ndim, param=name)

    @property
    def data_axis_size(self) -> int:
        return int(self.mesh.shape[self.batch_axis])

    def batch_divisible(self, batch: Dict[str, Any]) -> bool:
        n_shards = self.data_axis_size
        for v in batch.values():
            if np.shape(v)[0] % n_shards != 0:
                return False
        return True

    def pad_batch(self, batch: Dict[str, Any]):
        """Pad an indivisible host batch up to the next data-axis multiple by
        repeating each slot's last row, and attach a [B_padded] 0/1 validity
        mask under graph.SAMPLE_MASK_KEY. Cost layers weight rows by the mask
        and normalize by the real count (nn/costs._masked_mean), so the
        padded batch reproduces the unpadded batch's cost/gradients — the
        trailing batch trains instead of being dropped. Returns
        (padded_batch, n_pad); (batch, 0) when already divisible.

        Caveat: layers that COUPLE rows through batch statistics (batch
        norm) see the repeated pad rows in their mean/var and moving
        averages — the mask zeroes cost contributions, not statistic
        contributions. Repeating real rows (rather than zeros) bounds the
        distortion to a duplicated-sample bias on ONE trailing batch per
        pass; size batches divisibly when exact BN statistics matter."""
        return self._pad_batch(batch)

    def maybe_pad_batch(
        self, batch: Dict[str, Any], where: str = "batch"
    ) -> Optional[Dict[str, Any]]:
        """The single pad-or-drop gate every consumer (trainer train loop,
        trainer.test, DevicePrefetcher) goes through: divisible batches pass
        untouched, indivisible ones pad+mask (counted in
        stats.DATA_EVENTS['padded_batches']), unpaddable ragged ones drop
        with a warning and return None."""
        if self.batch_divisible(batch):
            return batch
        padded, n_pad = self._pad_batch(batch)
        if n_pad:
            from paddle_tpu.core import stats

            stats.DATA_EVENTS.incr("padded_batches")
            return padded
        log.warning(
            "%s: dropping batch — ragged slot sizes not divisible by the "
            "mesh data axis", where,
        )
        return None

    def _pad_batch(self, batch: Dict[str, Any]):
        n_shards = self.data_axis_size
        rows = {np.shape(v)[0] for v in batch.values()}
        if len(rows) != 1:
            # heterogeneous leading dims (exotic provider): cannot pad safely
            return batch, 0
        b = rows.pop()
        pad = (-b) % n_shards
        if pad == 0:
            return batch, 0
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            out[k] = np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
        mask = np.ones(b + pad, np.float32)
        mask[b:] = 0.0
        if SAMPLE_MASK_KEY in batch:
            # re-padding an already-masked batch (a batch padded for the
            # pre-resize mesh crossing a grown data axis): EXTEND the
            # existing mask with zero rows — overwriting it would un-mask
            # the original pad rows
            mask[:b] = np.asarray(batch[SAMPLE_MASK_KEY], np.float32)
        out[SAMPLE_MASK_KEY] = mask
        return out, pad

    def _put(self, batch: Dict[str, Any], sharding: NamedSharding) -> Dict[str, Any]:
        out = {}
        multiproc = jax.process_count() > 1
        for k, v in batch.items():
            v = np.asarray(v) if not isinstance(v, jax.Array) else v
            if multiproc:
                # each host holds only its shard of the global batch (the
                # pserver-era trainers never saw each other's data either);
                # assemble the global array from per-process locals
                out[k] = jax.make_array_from_process_local_data(
                    sharding, np.asarray(v)
                )
            else:
                out[k] = jax.device_put(v, sharding)
        return out

    def shard_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        return self._put(batch, self._batch_sharding)

    def replicate(self, value: Any) -> Any:
        """Place one array replicated on THIS plan's mesh — how host-side
        accumulators (e.g. the pass-cost sum) migrate across an elastic
        resize, where arrays committed to the old mesh cannot join new-mesh
        computations."""
        return jax.device_put(value, self._replicated)

    def is_sharded_batch(self, batch: Dict[str, Any]) -> bool:
        """True when every slot already carries this plan's batch sharding —
        the trainer's device-batch fast path must not skip shard_batch for
        arrays that merely live on the default device."""
        return all(
            isinstance(v, jax.Array)
            and v.sharding.is_equivalent_to(self._batch_sharding, v.ndim)
            for v in batch.values()
        )

    def shard_batches(self, batches: Dict[str, Any]) -> Dict[str, Any]:
        """Shard a K-stacked batch dict ([K, B, ...] per slot) for the
        multi-step scan driver: the scan axis stays unsharded, batch axis 1
        shards over the mesh data axis."""
        return self._put(batches, self._batches_sharding)

    def is_sharded_batches(self, batches: Dict[str, Any]) -> bool:
        """is_sharded_batch for a K-stacked group: true when every [K, B,
        ...] slot already carries THIS plan's scan-unsharded/batch-sharded
        placement — false for groups a prefetcher stacked for a different
        (pre-resize) mesh, which must be rebuilt rather than dispatched."""
        want = self._batches_sharding
        return all(
            isinstance(v, jax.Array)
            and v.sharding.is_equivalent_to(want, v.ndim)
            for v in batches.values()
        )

    def shard_state(
        self, state: Dict[str, Any], opt_sharding=None, param_sharding=None
    ) -> Dict[str, Any]:
        """Place a train state on the mesh. `opt_sharding(param_name, leaf)`
        (from ParameterUpdater.opt_leaf_sharding) overrides the placement of
        optimizer slot/EF leaves — the ZeRO ShardedUpdater returns its
        data-axis sharding for flat leaves so they go STRAIGHT to their 1/n
        resident placement (a replicated intermediate would momentarily cost
        the full optimizer state per chip at init/resume, exactly the peak
        shard_update exists to avoid). `param_sharding(param_name, leaf)`
        (from ParameterUpdater.param_leaf_sharding) does the same for
        PARAMETER and model-average leaves — the ZeRO-3 updater's flat
        params land 1/n-resident directly too."""
        params = {
            k: jax.device_put(
                v,
                (param_sharding and param_sharding(k, v))
                or self.param_sharding(k, v.ndim),
            )
            for k, v in state["params"].items()
        }
        # optimizer slots follow their parameter's sharding unless the
        # updater dictates its own layout for them
        slots = {
            k: tuple(
                jax.device_put(
                    s,
                    (opt_sharding and opt_sharding(k, s))
                    or self.param_sharding(k, s.ndim),
                )
                for s in ss
            )
            for k, ss in state["opt"]["slots"].items()
        }
        opt = dict(state["opt"])
        opt["slots"] = slots
        opt["t"] = jax.device_put(opt["t"], self._replicated)
        if "ef" in opt:
            # compression error-feedback residuals share the flat layout;
            # placed unconditionally like every other leaf (a caller without
            # the seam still gets a committed replicated placement, never an
            # unplaced array that reshards on every step)
            opt["ef"] = {
                k: jax.device_put(
                    e,
                    (opt_sharding and opt_sharding(k, e)) or self._replicated,
                )
                for k, e in opt["ef"].items()
            }
        rest = {}
        for k in state:
            if k in ("params", "opt"):
                continue
            if k == "avg" and state[k] and param_sharding is not None:
                # model-average leaves mirror the param layout: under ZeRO-3
                # the flat averages go straight to their sharded residency
                avg = dict(state[k])
                avg["avg"] = {
                    name: jax.device_put(
                        v, param_sharding(name, v) or self._replicated
                    )
                    for name, v in avg["avg"].items()
                }
                avg["n"] = jax.device_put(avg["n"], self._replicated)
                rest[k] = avg
                continue
            rest[k] = jax.tree.map(
                lambda v: jax.device_put(v, self._replicated), state[k]
            )
        return {"params": params, "opt": opt, **rest}

    # -- hooks used inside the traced step ----------------------------------
    def reduce_grads(self, grads, cost):
        # Under jit's global-view SPMD, gradients of replicated params w.r.t.
        # a data-sharded batch are already global sums — the partitioner
        # materializes the psum over ICI. Nothing to do by hand.
        return grads, cost

    # -- compilation ---------------------------------------------------------
    def compile_step(self, step):
        return jax.jit(step, donate_argnums=0)

    def compile_eval(self, evaluate):
        return jax.jit(evaluate)
