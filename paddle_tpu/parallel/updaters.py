"""ParameterUpdater hierarchy — interface parity with the reference's updater
stack (trainer/ParameterUpdater.h:38 SgdLocalUpdater, ThreadParameterUpdater.h:41
SgdThreadUpdater, RemoteParameterUpdater.h:55/180/265, NewRemoteParameterUpdater).

In the reference the updater is where parallelism plugs into the trainer: the
same `init/startPass/startBatch/update/finishBatch/finishPass` protocol hides
local SGD, the multi-thread ring, or the pserver RPC. Here the heavy lifting
(grad all-reduce, sharded placement) is compiled INTO the step by
DataParallel, so these classes keep the protocol for API parity and host-side
orchestration: pass/batch bookkeeping, barriers across hosts, and the hook
point for custom update policies."""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.nn.graph import ParamAttr
from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.parallel import compression as compression_mod
from paddle_tpu.parallel import distributed


class ParameterUpdater:
    """The reference protocol (ParameterUpdater.h:38)."""

    def init(self, params: Dict[str, Any]) -> None:  # noqa: A003
        pass

    def start_pass(self) -> None:
        pass

    def finish_pass(self) -> None:
        pass

    def start_batch(self, batch_size: int) -> None:
        pass

    def finish_batch(self, cost: float) -> None:
        pass

    def apply(self, grads, opt_state, params, lr):
        raise NotImplementedError

    # -- optimizer-state ownership seam --------------------------------------
    # The updater owns the LAYOUT of the optimizer state: the ZeRO-style
    # ShardedUpdater stores slots in a flat per-replica-sharded form, while
    # these defaults keep the optimizer's canonical per-param layout. The
    # trainer goes through this seam for init, checkpoint save/load and mesh
    # placement so both layouts round-trip through the same checkpoints.

    # ZeRO mode tag: None for the replicated updaters, "zero1"/"zero2"/
    # "zero3" on the ShardedUpdater family — the trainer dispatches its
    # multi-step fusion (zero2) and state layout (zero3) on this
    mode: Optional[str] = None

    def init_opt_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return self.optimizer.init_state(params)

    def to_canonical(self, opt_state: Dict[str, Any]) -> Dict[str, Any]:
        """Updater layout → the optimizer's canonical per-param layout (what
        checkpoints store, so resumes work across updater choices)."""
        return opt_state

    def from_canonical(self, opt_canonical: Dict[str, Any]) -> Dict[str, Any]:
        return opt_canonical

    # -- parameter-layout seam (ZeRO-3) --------------------------------------
    # Mirrors the opt-state seam above: the Zero3Updater stores PARAMETERS in
    # the flat data-axis-sharded layout too, so checkpoints/resizes cross
    # through the canonical per-param layout exactly like optimizer slots.
    # Identity for every other updater.

    def params_to_canonical(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return params

    def params_from_canonical(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return params

    def param_resolver(self, opt_state=None) -> Optional[Callable]:
        """Optional `(name, stored_leaf) -> full_view` resolver threaded
        through Network.apply (Context.param), built INSIDE the compiled
        step. None by default (params are stored full); the Zero3Updater
        returns the on-demand all-gather of its resident-sharded flat
        leaves, so each parameter is gathered layer-by-layer AT ITS POINT
        OF USE — and the gather's autodiff transpose delivers
        already-scattered gradients to apply."""
        return None

    def opt_leaf_sharding(self, name: str, leaf) -> Optional[Any]:
        """Placement override for one optimizer slot/EF leaf of param `name`,
        consulted by DataParallel.shard_state. None = default rule (follow
        the parameter's sharding). The ShardedUpdater returns its data-axis
        sharding for flat leaves so they are placed resident-sharded
        DIRECTLY — never through a full-size replicated intermediate."""
        return None

    def param_leaf_sharding(self, name: str, leaf) -> Optional[Any]:
        """Same override for PARAMETER (and model-average) leaves — non-None
        only on the Zero3Updater, whose params live flat-sharded."""
        return None

    def collective_bytes_per_step(self, steps_per_dispatch: int = 1) -> int:
        """Modeled bytes/chip crossing collectives per train step for the
        parameter update + gradient reduction (ring convention: an all-reduce
        of M bytes moves 2*M*(n-1)/n per chip; each decomposed phase moves
        M*(n-1)/n). `steps_per_dispatch` amortizes per-dispatch collectives
        (the zero2 fused update) back to per-step units. 0 for
        single-replica updaters."""
        return 0

    def collective_bytes_detail(
        self, steps_per_dispatch: int = 1
    ) -> Dict[str, Any]:
        """Per-leg breakdown of collective_bytes_per_step: {"mode": ...,
        "per_leg": {leg: {"dtype": ..., "bytes_per_step": ...}}} — the
        scatter/gather × zero-mode × dtype accounting surfaced in EndPass
        metrics and shard_update_bench. {} for single-replica updaters."""
        return {}

    def rebind(self, parallel, params: Dict[str, Any]) -> "ParameterUpdater":
        """Elastic-resize seam: a NEW updater of this kind bound to a
        different mesh/parallel plan, with its layout geometry derived from
        `params` — no optimizer slots are allocated (the live state crosses
        the resize through to_canonical on the OLD updater and
        from_canonical on the returned one). Single-replica updaters are
        mesh-free and rebind to themselves."""
        return self


class SgdLocalUpdater(ParameterUpdater):
    """Single-replica updater (ParameterUpdater.h:38 SgdLocalUpdater): the
    optimizer update runs inside the compiled step; no collectives."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer

    def apply(self, grads, opt_state, params, lr):
        return self.optimizer.update(grads, opt_state, params, lr)


class IciAllReduceUpdater(SgdLocalUpdater):
    """The pserver/ring replacement (SURVEY §2.5 rows 1-2): gradients are
    mean-reduced over the mesh data axis by pjit's SPMD partitioner (see
    DataParallel.reduce_grads), then updated locally-identically on every
    replica — semantically the synchronous pserver round-trip
    (ParameterServer2::addGradient + ThreadBarrier) with the barrier provided
    by the collective itself."""

    def __init__(self, optimizer: Optimizer, parallel):
        super().__init__(optimizer)
        self.parallel = parallel

    def start_pass(self) -> None:
        # host-level sync at pass boundaries, the synchronize() RPC parity
        if distributed.process_count() > 1:
            distributed.barrier("start_pass")

    def finish_pass(self) -> None:
        if distributed.process_count() > 1:
            distributed.barrier("finish_pass")

    def init_opt_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        self._record_grad_bytes(params)
        return super().init_opt_state(params)

    def _record_grad_bytes(self, params: Dict[str, Any]) -> None:
        # record sizes for the collective-bytes model (the replicated path's
        # gradient all-reduce is the baseline the sharded path halves)
        # the grad all-reduce carries the PARAM dtype (the f32 cast happens
        # after the reduction, inside update_one) — model its itemsize, not
        # a hardcoded f32, or bf16 models overstate the baseline 2x
        self._grad_bytes = sum(
            int(np.prod(p.shape)) * getattr(p.dtype, "itemsize", 4)
            for k, p in params.items()
            if not (self.optimizer.param_attrs.get(k) or ParamAttr()).is_static
        )

    def collective_bytes_per_step(self, steps_per_dispatch: int = 1) -> int:
        n = self.parallel.mesh.shape[self.parallel.batch_axis]
        if n <= 1:
            return 0
        # full-precision grad all-reduce: 2*M*(n-1)/n bytes per chip; one
        # per STEP regardless of dispatch fusion (the scan body reduces
        # every iteration)
        return int(2 * getattr(self, "_grad_bytes", 0) * (n - 1) / n)

    def collective_bytes_detail(
        self, steps_per_dispatch: int = 1
    ) -> Dict[str, Any]:
        total = self.collective_bytes_per_step(steps_per_dispatch)
        if not total:
            return {}
        return {
            "mode": "replicated",
            "per_leg": {
                "all_reduce": {"dtype": "grad", "bytes_per_step": total},
            },
        }

    def rebind(self, parallel, params: Dict[str, Any]) -> "IciAllReduceUpdater":
        new = type(self)(self.optimizer, parallel)
        new._record_grad_bytes(params)
        return new


@dataclasses.dataclass
class _FlatGeom:
    """Flat-shard geometry of one parameter: reshaped to [n, chunk] with
    `pad` trailing zeros (chunk aligned for block quantization)."""

    shape: Tuple[int, ...]
    size: int
    chunk: int
    flat: bool  # False: canonical treatment (static / tensor-parallel)


def _to_flat(x, n: int, chunk: int):
    """[*shape] → [n, chunk] zero-padded flat shard view."""
    xf = x.reshape(-1)
    pad = n * chunk - xf.shape[0]
    if pad:
        xf = jnp.pad(xf, (0, pad))
    return xf.reshape(n, chunk)


def _from_flat(x2, shape, size: int):
    return x2.reshape(-1)[:size].reshape(shape)


class ShardedUpdater(IciAllReduceUpdater):
    """ZeRO-1-style cross-replica sharded weight update (PAPERS.md
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training"): instead of every replica applying the identical optimizer
    update on the full parameter set — with optimizer state replicated
    n_data times — the update is decomposed inside the compiled step into

        reduce-scatter(grads) → shard-local optimizer step on 1/n of the
        state → all-gather(updated params)

    Each non-static parameter is viewed as a zero-padded flat [n, chunk]
    array; gradients are constrained to NamedSharding(P(data)) at the
    scatter point (XLA's weight-update-sharding pass forms the
    reduce-scatter from the pending grad reduction on TPU), optimizer slots
    LIVE in that flat sharded layout (1/n of the bytes per chip, resident),
    and the updated shards are constrained back to replicated — the
    all-gather. Per-param flats are concatenated position-wise so each
    collective phase presents ONE resharding boundary to XLA (the
    partitioner may re-split it per consumer; tests/test_hlo_collectives.py
    pins the realized collective counts so a regression to noisier
    per-parameter collectives fails the build).

    Tensor-parallel (`ParamAttr.sharding`) and static parameters keep the
    canonical per-param update — their layout is already sharded or frozen.

    `compression` (parallel/compression.py) quantizes each phase's payload:
    bf16 halves both legs; int8 block-scales the grad leg with an
    error-feedback residual carried in opt_state["ef"].

    On CPU the none-compression path applies bitwise-identical updates to
    the replicated updater for SGD (exactly equal when lr/momentum scale
    products are exact, e.g. power-of-two lr — tests/test_shard_update.py;
    XLA freely FMA-contracts the scale multiplies, so arbitrary lr agrees
    to 1-2 ULP) and matches Adam to tight tolerance."""

    mode = "zero1"

    def __init__(self, optimizer: Optimizer, parallel, compression: str = "none"):
        super().__init__(optimizer, parallel)
        self.compression = compression_mod.make(compression)
        self.axis = parallel.batch_axis
        self.n = int(parallel.mesh.shape[self.axis])
        self._shard = NamedSharding(parallel.mesh, P(self.axis))
        self._rep = NamedSharding(parallel.mesh, P())
        self._geom: Dict[str, _FlatGeom] = {}

    # -- layout ---------------------------------------------------------------
    def _param_geom(self, k: str, p) -> _FlatGeom:
        attr = self.optimizer.param_attrs.get(k) or ParamAttr()
        size = int(np.prod(p.shape)) if p.shape else 1
        flat = not attr.is_static and self._resolves_replicated(k, attr, p)
        align = self.compression.chunk_align
        chunk = -(-size // self.n)
        chunk = -(-chunk // align) * align
        return _FlatGeom(tuple(p.shape), size, chunk, flat)

    def _resolves_replicated(self, k: str, attr: ParamAttr, p) -> bool:
        """Whether this param's declared axes resolve to REPLICATED on this
        mesh — resolved through the rules table, not by tuple presence, so a
        model declaring TP logical axes ("heads": "model") still gets the
        flat ZeRO treatment on a data-only mesh (where those axes do not
        bite) and keeps its canonical TP layout on a dp x tp mesh."""
        axes = attr.logical_axes if attr.logical_axes is not None else attr.sharding
        if axes is None:
            return True
        spec = self.parallel.rules.spec_for(
            axes, self.parallel.mesh, ndim=len(p.shape), param=k
        )
        return all(a is None for a in spec)

    def bind_geometry(self, params: Dict[str, Any]) -> None:
        """Derive the flat-shard geometry for `params` without allocating any
        optimizer state — the elastic-resize rebind path, where the slot
        values arrive separately through from_canonical."""
        self._geom = {k: self._param_geom(k, p) for k, p in params.items()}

    def rebind(self, parallel, params: Dict[str, Any]) -> "ShardedUpdater":
        new = type(self)(
            self.optimizer, parallel, compression=self.compression.name
        )
        new._record_grad_bytes(params)
        new.bind_geometry(params)
        return new

    def init_opt_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        opt = super().init_opt_state(params)  # canonical slots (+ _grad_bytes)
        self.bind_geometry(params)
        slots = {}
        for k, ss in opt["slots"].items():
            geom = self._geom[k]
            if not geom.flat:
                slots[k] = ss
                continue
            slots[k] = tuple(_to_flat(s, self.n, geom.chunk) for s in ss)
        opt["slots"] = slots
        if self.compression.uses_error_feedback:
            opt["ef"] = {
                k: jnp.zeros((self.n, g.chunk), jnp.float32)
                for k, g in self._geom.items()
                if g.flat
            }
        return opt

    def to_canonical(self, opt_state: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(opt_state)
        out["slots"] = {
            k: ss
            if not self._geom[k].flat
            else tuple(
                _from_flat(s, self._geom[k].shape, self._geom[k].size) for s in ss
            )
            for k, ss in opt_state["slots"].items()
        }
        if "ef" in opt_state:
            out["ef"] = {
                k: _from_flat(e, self._geom[k].shape, self._geom[k].size)
                for k, e in opt_state["ef"].items()
            }
        return out

    def from_canonical(self, opt_canonical: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(opt_canonical)
        out["slots"] = {
            k: ss
            if not self._geom[k].flat
            else tuple(_to_flat(s, self.n, self._geom[k].chunk) for s in ss)
            for k, ss in opt_canonical["slots"].items()
        }
        if "ef" in opt_canonical:
            out["ef"] = {
                k: _to_flat(e, self.n, self._geom[k].chunk)
                for k, e in opt_canonical["ef"].items()
            }
        return out

    def opt_leaf_sharding(self, name: str, leaf):
        """Flat slot/EF leaves go straight to their resident data-axis shard
        placement — this is what makes the 1/n per-chip opt-state bytes REAL
        (XLA keeps donated sharded leaves sharded across steps), and placing
        them directly avoids a full-size replicated intermediate at
        init/resume."""
        geom = self._geom.get(name)
        if geom is not None and geom.flat:
            return self._shard
        return None

    # -- the sharded update (runs inside the compiled step) -------------------
    def apply(self, grads, opt_state, params, lr):
        wsc = jax.lax.with_sharding_constraint
        opt = self.optimizer
        comp = self.compression
        t = opt_state["t"] + 1
        opt._t = t
        ef = opt_state.get("ef")
        new_params: Dict[str, Any] = {}
        new_slots: Dict[str, Tuple] = {}
        new_ef: Dict[str, Any] = {}

        flat_keys = [k for k in params if self._geom[k].flat]
        # canonical path for static / tensor-parallel params
        for k in params:
            if not self._geom[k].flat:
                new_params[k], new_slots[k] = opt.update_one(
                    k, grads[k], opt_state["slots"][k], params[k], lr
                )

        if flat_keys:
            # 1) encode each grad's flat view, concat position-wise, and
            #    cross the reduce-scatter boundary as one array per position
            payloads = []
            for k in flat_keys:
                geom = self._geom[k]
                g2 = _to_flat(grads[k].astype(jnp.float32), self.n, geom.chunk)
                payload, nef = comp.encode_scatter(
                    g2, None if ef is None else ef[k]
                )
                payloads.append(payload)
                if nef is not None:
                    new_ef[k] = nef
            widths = [[arr.shape[1] for arr in p] for p in payloads]
            # reshard-ok: THE grad reduce-scatter boundary (one per step)
            cat = tuple(
                wsc(jnp.concatenate(arrs, axis=1), self._shard)
                for arrs in zip(*payloads)
            )
            # 2) shard-local optimizer step on the owned 1/n of each param
            gathers = []
            offs = [0] * len(cat)
            for i, k in enumerate(flat_keys):
                geom = self._geom[k]
                payload = tuple(
                    c[:, offs[j]:offs[j] + widths[i][j]]
                    for j, c in enumerate(cat)
                )
                for j in range(len(cat)):
                    offs[j] += widths[i][j]
                g2 = comp.decode_scatter(payload)
                # reshard-ok: placement pin of the local shard view
                p2 = wsc(_to_flat(params[k], self.n, geom.chunk), self._shard)
                np2, new_slots[k] = opt.update_one(
                    k, g2, opt_state["slots"][k], p2, lr
                )
                gathers.append(comp.encode_gather(np2, p2))
            # 3) one all-gather of the concatenated updated shards
            # reshard-ok: THE updated-param all-gather (one per step)
            gat = wsc(jnp.concatenate(gathers, axis=1), self._rep)
            off = 0
            for i, k in enumerate(flat_keys):
                geom = self._geom[k]
                piece = gat[:, off:off + geom.chunk]
                off += geom.chunk
                p_full2 = _to_flat(params[k], self.n, geom.chunk)
                new_params[k] = _from_flat(
                    comp.decode_gather(piece, p_full2), geom.shape, geom.size
                )

        new_opt = {"slots": new_slots, "t": t}
        if ef is not None:
            new_opt["ef"] = new_ef
        return new_params, new_opt

    # -- collective-bytes model (ring convention, per-leg) --------------------
    def _flat_payload_elems(self) -> int:
        return sum(self.n * g.chunk for g in self._geom.values() if g.flat)

    def _leg_bytes(self, itemsize: float, per_dispatch_of: int = 1) -> int:
        """One decomposed phase: payload * (n-1)/n bytes/chip, amortized to
        per-step units when the leg runs once per `per_dispatch_of` steps."""
        if self.n <= 1:
            return 0
        ring = (self.n - 1) / self.n
        return int(
            self._flat_payload_elems() * itemsize * ring
            / max(per_dispatch_of, 1)
        )

    def collective_bytes_detail(
        self, steps_per_dispatch: int = 1
    ) -> Dict[str, Any]:
        """zero1: one grad reduce-scatter + one updated-param all-gather per
        STEP, regardless of dispatch fusion (the scan body repeats both)."""
        comp = self.compression
        return {
            "mode": self.mode,
            "per_leg": {
                "scatter": {
                    "dtype": comp.scatter_dtype,
                    "bytes_per_step": self._leg_bytes(comp.scatter_itemsize),
                },
                "gather": {
                    "dtype": comp.gather_dtype,
                    "bytes_per_step": self._leg_bytes(comp.gather_itemsize),
                },
            },
        }

    def collective_bytes_per_step(self, steps_per_dispatch: int = 1) -> int:
        detail = self.collective_bytes_detail(steps_per_dispatch)
        if not detail:
            return 0
        return int(
            sum(l["bytes_per_step"] for l in detail["per_leg"].values())
        )


class Zero2Updater(ShardedUpdater):
    """ZeRO-2: gradients stay reduce-scattered across the K-step fused
    dispatch. The update math is zero1's (the class inherits `apply`
    unchanged); what changes is WHERE it runs — the trainer's multi-step
    program (SGDTrainer.make_multi_step) merges the K stacked batches into
    one shard-local [K*B] batch and applies ONE fused update per dispatch,
    so the gradient reduce-scatter and the param all-gather each cross the
    wire once per dispatch instead of once per step (~K x fewer collective
    bytes on the grad leg at --steps_per_dispatch K).

    Semantics: classic gradient accumulation — the dispatch's single update
    consumes the mean gradient over the window's K*B rows (sample masks
    included, so padded trailing rows still drop out exactly), parameters
    hold still within the window, and the optimizer steps once per dispatch.
    At K=1 (and for the trailing remainder batches the loop runs as
    singles) zero2 applies exactly zero1's per-batch updates."""

    mode = "zero2"

    def collective_bytes_detail(
        self, steps_per_dispatch: int = 1
    ) -> Dict[str, Any]:
        comp = self.compression
        k = max(int(steps_per_dispatch), 1)
        return {
            "mode": self.mode,
            "per_leg": {
                "scatter": {
                    "dtype": comp.scatter_dtype,
                    "bytes_per_step": self._leg_bytes(
                        comp.scatter_itemsize, per_dispatch_of=k
                    ),
                },
                "gather": {
                    "dtype": comp.gather_dtype,
                    "bytes_per_step": self._leg_bytes(
                        comp.gather_itemsize, per_dispatch_of=k
                    ),
                },
            },
        }


class Zero3Updater(ShardedUpdater):
    """ZeRO-3: parameters THEMSELVES live in the flat [n, chunk]
    data-axis-sharded layout in the train state (~n x less param HBM per
    chip, same as the optimizer slots), and the compiled step gathers each
    one on demand:

      * `network_params` (called inside the step's loss function) rebuilds
        every flat param's full view through a custom_vjp gather: the
        payload crosses the all-gather boundary encoded by the compression
        mode (f32 / bf16 / block-scaled int8 with a master-tracking
        error-feedback residual in opt_state["ef"] — quantization INSIDE
        the collective, EQuARX-style), and the trainer remats the gathered
        views (checkpoint_name "zero3_gathered") so the backward re-gathers
        instead of holding every full parameter across the forward.
      * The gather's transpose delivers gradients ALREADY in the flat
        sharded layout — `apply` concatenates them across one scatter
        constraint (the grad reduce-scatter), steps the optimizer
        shard-locally, and leaves the updated params sharded. There is no
        trailing param all-gather: the next step's forward re-gathers.

    Tensor-parallel / static params keep their canonical layout and
    placement (geometry resolves through the rules table), so zero3
    composes with TP logical axes the same way zero1 does. Checkpoints
    store the canonical layout via params_to/from_canonical — resumes
    cross zero modes and world sizes bitwise (SGD) exactly like the
    opt-state seam."""

    mode = "zero3"

    # -- parameter layout seams ----------------------------------------------
    def params_to_canonical(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: p
            if not self._geom[k].flat
            else _from_flat(p, self._geom[k].shape, self._geom[k].size)
            for k, p in params.items()
        }

    def params_from_canonical(self, params: Dict[str, Any]) -> Dict[str, Any]:
        return {
            k: p
            if not self._geom[k].flat
            else _to_flat(p, self.n, self._geom[k].chunk)
            for k, p in params.items()
        }

    def param_leaf_sharding(self, name: str, leaf):
        geom = self._geom.get(name)
        if geom is not None and geom.flat:
            return self._shard
        return None

    # -- the on-demand gather (runs inside the compiled step) -----------------
    def param_resolver(self, opt_state=None) -> Optional[Callable]:
        """The Context.param seam: each flat leaf's full view is rebuilt at
        the consuming layer's trace position (memoized per trace by the
        Context so shared params gather once). Canonical (TP/static) leaves
        pass through."""
        from jax.ad_checkpoint import checkpoint_name

        ef = (opt_state or {}).get("ef")

        def resolve(name: str, leaf):
            geom = self._geom.get(name)
            if geom is None or not geom.flat:
                return leaf
            e = None if ef is None else ef[name]
            full2 = _z3_gather(self, leaf, e)
            # named so the trainer's default zero3 remat policy
            # (save_anything_except_these_names) recomputes exactly these:
            # the gathered view is dropped after its layer consumes it and
            # re-gathered in the backward
            return checkpoint_name(
                _from_flat(full2, geom.shape, geom.size), "zero3_gathered"
            )

        return resolve

    # -- the sharded update (no trailing gather) ------------------------------
    def apply(self, grads, opt_state, params, lr):
        wsc = jax.lax.with_sharding_constraint
        opt = self.optimizer
        comp = self.compression
        t = opt_state["t"] + 1
        opt._t = t
        ef = opt_state.get("ef")
        new_params: Dict[str, Any] = {}
        new_slots: Dict[str, Tuple] = {}
        new_ef: Dict[str, Any] = {}

        flat_keys = [k for k in params if self._geom[k].flat]
        for k in params:
            if not self._geom[k].flat:
                new_params[k], new_slots[k] = opt.update_one(
                    k, grads[k], opt_state["slots"][k], params[k], lr
                )

        if flat_keys:
            # cotangents of the gather arrive already [n, chunk]-shaped;
            # concat → ONE resharding boundary = the grad reduce-scatter
            # (encode narrows the crossing for the compressed modes)
            # reshard-ok: THE zero3 grad reduce-scatter boundary
            cat = wsc(
                jnp.concatenate(
                    [comp.encode_z3_scatter(grads[k]) for k in flat_keys],
                    axis=1,
                ),
                self._shard,
            )
            off = 0
            for k in flat_keys:
                geom = self._geom[k]
                g2 = comp.decode_z3_scatter(cat[:, off:off + geom.chunk])
                off += geom.chunk
                # reshard-ok: placement pin of the resident shard
                p2 = wsc(params[k], self._shard)
                np2, new_slots[k] = opt.update_one(
                    k, g2, opt_state["slots"][k], p2, lr
                )
                # params STAY sharded — the next forward re-gathers
                # reshard-ok: placement pin, no collective
                new_params[k] = wsc(np2, self._shard)
                if ef is not None:
                    # persist the param-gather error feedback: re-run the
                    # forward's deterministic encode on the PRE-update
                    # master (local math, no second collective)
                    _, new_ef[k] = comp.encode_param_gather(p2, ef[k])

        new_opt = {"slots": new_slots, "t": t}
        if ef is not None:
            new_opt["ef"] = new_ef
        return new_params, new_opt

    def collective_bytes_detail(
        self, steps_per_dispatch: int = 1
    ) -> Dict[str, Any]:
        """zero3 legs: the on-demand param all-gather runs TWICE per step
        (forward + the remat'd backward re-gather) and the grad scatter
        once; both repeat every step of a fused dispatch."""
        comp = self.compression
        return {
            "mode": self.mode,
            "per_leg": {
                "scatter": {
                    "dtype": comp.z3_scatter_dtype,
                    "bytes_per_step": self._leg_bytes(comp.z3_scatter_itemsize),
                },
                "gather": {
                    "dtype": comp.param_gather_dtype,
                    "bytes_per_step": self._leg_bytes(
                        2 * comp.param_gather_itemsize
                    ),
                },
            },
        }


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _z3_gather(updater, p2, ef):
    """One flat param's on-demand all-gather: encode the owned rows, cross
    the replication constraint (the all-gather), decode identically on every
    chip. custom_vjp so (a) the quantized view's gradient flows straight
    through to the master (STE) and (b) autodiff never tries to transpose
    the non-differentiable quantize."""
    wsc = jax.lax.with_sharding_constraint
    comp = updater.compression
    # reshard-ok: placement pin of the owned rows before encoding
    payload, _ = comp.encode_param_gather(wsc(p2, updater._shard), ef)
    # reshard-ok: THE on-demand param all-gather (per flat param, fwd +
    # remat'd bwd re-gather)
    crossed = tuple(wsc(x, updater._rep) for x in payload)
    return comp.decode_param_gather(crossed)


def _z3_gather_fwd(updater, p2, ef):
    return _z3_gather(updater, p2, ef), ef


def _z3_gather_bwd(updater, ef_res, d_full2):
    # straight-through estimator for the quantized modes: the cotangent of
    # the gathered (possibly quantized) view passes to the master unchanged;
    # its narrow wire crossing happens at apply's scatter constraint. The
    # EF residual is state, not a differentiated input — zero cotangent.
    return d_full2, None if ef_res is None else jnp.zeros_like(ef_res)


_z3_gather.defvjp(_z3_gather_fwd, _z3_gather_bwd)


# SparseRemoteParameterUpdater (RemoteParameterUpdater.h:265) has no updater
# class here on purpose: embedding tables live row-sharded on the mesh
# (parallel/embedding.py), the sharded lookup's gather touches only owned
# rows, and its transpose is the row-sparse scatter-add the pserver applied
# by hand — so the "sparse updater" is the compiled step itself.
