"""ParameterUpdater hierarchy — interface parity with the reference's updater
stack (trainer/ParameterUpdater.h:38 SgdLocalUpdater, ThreadParameterUpdater.h:41
SgdThreadUpdater, RemoteParameterUpdater.h:55/180/265, NewRemoteParameterUpdater).

In the reference the updater is where parallelism plugs into the trainer: the
same `init/startPass/startBatch/update/finishBatch/finishPass` protocol hides
local SGD, the multi-thread ring, or the pserver RPC. Here the heavy lifting
(grad all-reduce, sharded placement) is compiled INTO the step by
DataParallel, so these classes keep the protocol for API parity and host-side
orchestration: pass/batch bookkeeping, barriers across hosts, and the hook
point for custom update policies."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.optim.optimizers import Optimizer
from paddle_tpu.parallel import distributed


class ParameterUpdater:
    """The reference protocol (ParameterUpdater.h:38)."""

    def init(self, params: Dict[str, Any]) -> None:  # noqa: A003
        pass

    def start_pass(self) -> None:
        pass

    def finish_pass(self) -> None:
        pass

    def start_batch(self, batch_size: int) -> None:
        pass

    def finish_batch(self, cost: float) -> None:
        pass

    def apply(self, grads, opt_state, params, lr):
        raise NotImplementedError


class SgdLocalUpdater(ParameterUpdater):
    """Single-replica updater (ParameterUpdater.h:38 SgdLocalUpdater): the
    optimizer update runs inside the compiled step; no collectives."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer

    def apply(self, grads, opt_state, params, lr):
        return self.optimizer.update(grads, opt_state, params, lr)


class IciAllReduceUpdater(SgdLocalUpdater):
    """The pserver/ring replacement (SURVEY §2.5 rows 1-2): gradients are
    mean-reduced over the mesh data axis by pjit's SPMD partitioner (see
    DataParallel.reduce_grads), then updated locally-identically on every
    replica — semantically the synchronous pserver round-trip
    (ParameterServer2::addGradient + ThreadBarrier) with the barrier provided
    by the collective itself."""

    def __init__(self, optimizer: Optimizer, parallel):
        super().__init__(optimizer)
        self.parallel = parallel

    def start_pass(self) -> None:
        # host-level sync at pass boundaries, the synchronize() RPC parity
        if distributed.process_count() > 1:
            distributed.barrier("start_pass")

    def finish_pass(self) -> None:
        if distributed.process_count() > 1:
            distributed.barrier("finish_pass")


# SparseRemoteParameterUpdater (RemoteParameterUpdater.h:265) has no updater
# class here on purpose: embedding tables live row-sharded on the mesh
# (parallel/embedding.py), the sharded lookup's gather touches only owned
# rows, and its transpose is the row-sparse scatter-add the pserver applied
# by hand — so the "sparse updater" is the compiled step itself.
