"""Sharded embedding tables — the EP ancestor in the reference (SURVEY §2.5):
row-sharded embeddings on pservers (SparseRemoteParameterUpdater,
RemoteParameterUpdater.h:265; SparsePrefetchRowCpuMatrix prefetch;
--ports_num_for_sparse).

TPU-native: the table's rows are sharded over a mesh axis ('expert'); lookup
runs under shard_map — each device gathers the ids that fall in its row range
and a psum combines the partial one-hot results. Autodiff of the masked
gather yields exactly the row-sparse gradient scatter the pserver protocol
implements by hand; XLA keeps it as a scatter-add on the owning shard."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# feature-detects the check_vma/check_rep kwarg rename across jax versions
from paddle_tpu.parallel.shard_map_compat import shard_map

Array = jax.Array


def shard_table(table: Array, mesh: Mesh, axis: str = "expert") -> Array:
    """Place a [V, D] table row-sharded over `axis` (V must divide evenly)."""
    n = mesh.shape[axis]
    if table.shape[0] % n != 0:
        raise ValueError(
            f"vocab {table.shape[0]} not divisible by mesh axis {axis!r} ({n})"
        )
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def sharded_lookup(
    table: Array,  # [V, D] sharded over rows on `axis`
    ids: Array,  # [...] int32 (replicated or batch-sharded on another axis)
    mesh: Mesh,
    axis: str = "expert",
) -> Array:
    """ids → [..., D]. Each shard serves its own row range; psum combines."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    def lookup(tab, idx):
        rows = tab.shape[0]
        my = lax.axis_index(axis)
        lo = my * rows
        local = idx - lo
        mine = (local >= 0) & (local < rows)
        safe = jnp.clip(local, 0, rows - 1)
        part = jnp.where(mine[..., None], tab[safe], 0.0)
        return lax.psum(part, axis)

    return lookup(table, ids)


class ShardedEmbeddingState:
    """Bundles the sharded table with its mesh/axis for the layer seam."""

    def __init__(self, table: Array, mesh: Mesh, axis: str = "expert"):
        self.mesh = mesh
        self.axis = axis
        self.table = shard_table(table, mesh, axis)

    def __call__(self, ids: Array) -> Array:
        return sharded_lookup(self.table, ids, self.mesh, self.axis)
