"""Device mesh construction.

The TPU replacement for the reference's process/device topology flags
(--trainer_count, --num_gradient_servers; utils/Flags.h:19-43): a named
`jax.sharding.Mesh` whose axes express every parallelism the framework offers —
data (the MultiGradientMachine ring / pserver sync), model (per-layer placement
of ParallelNeuralNetwork), seq (ring-attention sequence parallelism), expert
(sparse/embedding sharding à la SparseRemoteParameterUpdater)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("data", "model", "seq", "expert")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """axis_sizes e.g. {"data": 4, "model": 2}. Unmentioned axes get size 1.
    The product must divide the device count; when it is smaller, only the
    first `product` devices are used (axis_sizes=None uses all on 'data')."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"data": n}
    sizes = dict(axis_sizes)
    total = int(np.prod(list(sizes.values()))) if sizes else 1
    if n % total != 0:
        raise ValueError(f"{n} devices not divisible by mesh {sizes}")
    # explicit sizes are honored exactly: extra devices are left out rather
    # than silently inflating an axis
    devices = devices[:total]
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def resize_mesh(
    mesh: Mesh,
    axis: str,
    new_size: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Elastic resize: the same mesh with `axis` re-shaped to `new_size`
    chips (every other axis keeps its extent). Raises with a clear message
    when the host cannot supply enough devices — the caller (trainer drain /
    chaos bench) turns that into a rejected resize rather than a deep jax
    error."""
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh has no axis {axis!r} to resize (axes: {mesh.axis_names})"
        )
    new_size = int(new_size)
    if new_size < 1:
        raise ValueError(f"resize target for axis {axis!r} must be >= 1")
    sizes = {a: int(mesh.shape[a]) for a in mesh.axis_names}
    sizes[axis] = new_size
    total = int(np.prod(list(sizes.values())))
    pool = list(devices if devices is not None else jax.devices())
    if total > len(pool):
        raise ValueError(
            f"cannot resize mesh axis {axis!r} to {new_size}: the new mesh "
            f"needs {total} device(s) but only {len(pool)} are available"
        )
    # hand make_mesh exactly the devices the new shape consumes — the full
    # pool would trip its divisibility check for any world size that does
    # not divide the host device count (e.g. 3 trainers on an 8-chip host)
    return make_mesh(sizes, devices=pool[:total])
