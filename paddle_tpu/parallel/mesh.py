"""Device mesh construction.

The TPU replacement for the reference's process/device topology flags
(--trainer_count, --num_gradient_servers; utils/Flags.h:19-43): a named
`jax.sharding.Mesh` whose axes express every parallelism the framework offers —
data (the MultiGradientMachine ring / pserver sync), model (per-layer placement
of ParallelNeuralNetwork), seq (ring-attention sequence parallelism), expert
(sparse/embedding sharding à la SparseRemoteParameterUpdater)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("data", "model", "seq", "expert")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """axis_sizes e.g. {"data": 4, "model": 2}. Unmentioned axes get size 1.
    The product must divide the device count; when it is smaller, only the
    first `product` devices are used (axis_sizes=None uses all on 'data')."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"data": n}
    sizes = dict(axis_sizes)
    total = int(np.prod(list(sizes.values()))) if sizes else 1
    if n % total != 0:
        raise ValueError(f"{n} devices not divisible by mesh {sizes}")
    # explicit sizes are honored exactly: extra devices are left out rather
    # than silently inflating an axis
    devices = devices[:total]
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names))
