"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference (2017) scales sequences via ragged batching and dynamic RNN
unroll (SURVEY §2.5 row "Sequence parallelism": absent); a TPU-native
framework must treat long-context as first-class. Two schemes over the mesh
'seq' axis:

- `ring_attention`: Q stays put; K/V blocks rotate around the ring via
  `lax.ppermute` while a flash-style online softmax (running max / numerator /
  denominator) accumulates — memory O(T_local), compute overlapped with ICI
  transfers by XLA. (Liu et al., Ring Attention, 2023.)
- `ulysses_attention`: `lax.all_to_all` swaps the sharded axis from sequence
  to heads, runs full attention locally on H/n heads, swaps back. Cheaper at
  moderate T when heads divide the axis. (DeepSpeed-Ulysses, 2023.)

Both are exact (not approximations): tests compare against single-device
attention on the virtual CPU mesh."""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# feature-detects the check_vma/check_rep kwarg rename across jax versions
from paddle_tpu.parallel.shard_map_compat import shard_map

Array = jax.Array
NEG_INF = -1e30


def _mask_scores(
    scores: Array,  # [B, H, Tq, Tk]
    q_pos: Array,  # [Tq] global positions
    k_pos: Array,  # [Tk] global positions
    lengths: Optional[Array],  # [B]
    causal: bool,
) -> Array:
    if causal:
        scores = jnp.where(
            k_pos[None, None, None, :] > q_pos[None, None, :, None],
            NEG_INF,
            scores,
        )
    if lengths is not None:
        valid = k_pos[None, :] < lengths[:, None]  # [B, Tk]
        scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    return scores


def ring_attention(
    q: Array,  # [B, T, H, D] (T sharded over `axis`)
    k: Array,
    v: Array,
    mesh: Mesh,
    axis: str = "seq",
    lengths: Optional[Array] = None,  # [B] valid key lengths (replicated)
    causal: bool = False,
    scale: Optional[float] = None,
) -> Array:
    """Exact blockwise attention with K/V rotating over the ring."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qkv_spec = P(None, axis, None, None)
    len_spec = P(None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec)
        + ((len_spec,) if lengths is not None else ()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    def ring(qb, kb, vb, *rest):
        lens = rest[0] if rest else None
        n = lax.psum(1, axis)
        my = lax.axis_index(axis)
        b, tq, h, _ = qb.shape
        tk = kb.shape[1]
        q_pos = my * tq + jnp.arange(tq)
        # [B, H, Tq, D] layout for the matmuls
        qh = jnp.swapaxes(qb, 1, 2).astype(jnp.float32) * scale

        perm = [(j, (j - 1) % n) for j in range(n)]  # block i+1 arrives next

        def step(carry, i):
            kc, vc, m, num, den = carry
            src = (my + i) % n  # which global block kc/vc hold now
            k_pos = src * tk + jnp.arange(tk)
            kh = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
            vh = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
            s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)
            s = _mask_scores(s, q_pos, k_pos, lens, causal)
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            num = num * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vh)
            den = den * alpha + p.sum(axis=-1)
            kc = lax.ppermute(kc, axis, perm)
            vc = lax.ppermute(vc, axis, perm)
            return (kc, vc, m_new, num, den), None

        m0 = jnp.full((b, h, tq), NEG_INF, jnp.float32)
        num0 = jnp.zeros((b, h, tq, d), jnp.float32)
        den0 = jnp.zeros((b, h, tq), jnp.float32)
        (_, _, _, num, den), _ = lax.scan(
            step, (kb, vb, m0, num0, den0), jnp.arange(n)
        )
        out = num / jnp.maximum(den, 1e-20)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(qb.dtype)

    args = (q, k, v) + ((lengths,) if lengths is not None else ())
    return ring(*args)


def ulysses_attention(
    q: Array,  # [B, T, H, D] (T sharded over `axis`; H divisible by axis size)
    k: Array,
    v: Array,
    mesh: Mesh,
    axis: str = "seq",
    lengths: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> Array:
    """All-to-all head/sequence swap: full-T attention on H/n local heads."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    qkv_spec = P(None, axis, None, None)
    len_spec = P(None)
    n_seq = mesh.shape[axis]
    if q.shape[2] % n_seq != 0:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by mesh axis "
            f"{axis!r} ({n_seq}); use ring_attention otherwise"
        )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec)
        + ((len_spec,) if lengths is not None else ()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    def ulysses(qb, kb, vb, *rest):
        lens = rest[0] if rest else None
        # [B, T_loc, H, D] → all-to-all → [B, T_glob, H_loc, D]
        swap = lambda x: lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
        qg, kg, vg = swap(qb), swap(kb), swap(vb)
        t = qg.shape[1]
        pos = jnp.arange(t)
        qh = jnp.swapaxes(qg, 1, 2).astype(jnp.float32) * scale
        kh = jnp.swapaxes(kg, 1, 2).astype(jnp.float32)
        vh = jnp.swapaxes(vg, 1, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)
        s = _mask_scores(s, pos, pos, lens, causal)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
        out = jnp.swapaxes(out, 1, 2).astype(qb.dtype)  # [B, T_glob, H_loc, D]
        # reverse swap: sequence back to local, heads back to full
        return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)

    args = (q, k, v) + ((lengths,) if lengths is not None else ())
    return ulysses(*args)


def reference_attention(
    q: Array, k: Array, v: Array,
    lengths: Optional[Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
) -> Array:
    """Single-device oracle (same math, no sharding)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    t = q.shape[1]
    pos = jnp.arange(t)
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh)
    s = _mask_scores(s, pos, pos, lengths, causal)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)
