"""Multi-host initialization + host-level barriers.

Replaces the reference's cluster bring-up: ParameterServerController (N pserver
ports), ParameterClient2 connection setup, and the Go master/etcd discovery
(go/master/etcd_client.go). On TPU pods, `jax.distributed.initialize` does
discovery/rendezvous (GCS or coordinator address) and the resulting global
device set feeds one Mesh spanning all hosts; DCN handles cross-slice."""

from __future__ import annotations

import itertools
import logging
import os
import time
from typing import Optional

import jax

log = logging.getLogger("paddle_tpu.distributed")

_initialized = False


class BarrierTimeout(RuntimeError):
    """A host-level barrier expired; the message names which process ids
    never arrived (the hang diagnostic a stuck pod actually needs)."""


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the multi-host runtime. No-op on single host (mirrors the
    reference: local training skips pserver setup, TrainerMain.cpp:32)."""
    global _initialized
    if _initialized:
        return
    if num_processes is None or num_processes <= 1:
        _initialized = True
        return
    # CPU backends need an explicit cross-process collectives transport (the
    # TPU path rides ICI/DCN natively); gloo is jaxlib's built-in. Harmless
    # on TPU — the flag only affects the CPU client.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jaxlib without the option
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed init: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


# every process must call barrier() in the same order, so a shared call
# counter yields matching (unique) barrier ids without any negotiation
_barrier_seq = itertools.count()


def _coordinator_client():
    """The jax.distributed KV/barrier client, or None outside a multi-process
    run (the public alias for global_state moved around across jax versions —
    go through the _src module that owns it)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:  # pragma: no cover - depends on jax internals
        return None


def barrier(
    name: str = "barrier",
    timeout_s: Optional[float] = None,
    _client: Optional[object] = None,
) -> None:
    """Host-level sync point — parity with ParameterServer2::synchronize
    (ParameterServer2.h:423) and the ThreadBarrier across gradient servers.

    Multi-process runs go through the coordinator's barrier service with a
    timeout (default $PADDLE_TPU_BARRIER_TIMEOUT_S or 300 s): instead of
    hanging the pod forever on one dead host, the raised BarrierTimeout says
    WHICH process ids never arrived (each arrival is recorded in the
    coordinator KV store first). Single-process runs keep the tiny-psum
    barrier — there is no remote peer to wait on, so nothing can hang."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("PADDLE_TPU_BARRIER_TIMEOUT_S", "300"))
    client = _client if _client is not None else _coordinator_client()
    n = jax.process_count()
    if client is None or n <= 1:
        import jax.numpy as jnp

        x = jnp.ones((jax.local_device_count(),))
        jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x).block_until_ready()
        return
    seq = next(_barrier_seq)
    bid = f"paddle_tpu/{name}/{seq}"
    me = jax.process_index()
    try:
        # arrival marker for the who-is-missing diagnostic; best-effort
        client.key_value_set(f"{bid}/arrived/{me}", str(time.time()))
    except Exception:
        pass
    try:
        client.wait_at_barrier(bid, int(timeout_s * 1000))
    except Exception as e:
        arrived = set()
        try:
            for key, _val in client.key_value_dir_get(f"{bid}/arrived/"):
                arrived.add(int(key.rsplit("/", 1)[1]))
        except Exception:
            pass
        missing = sorted(set(range(n)) - arrived)
        raise BarrierTimeout(
            f"barrier {name!r} (#{seq}) timed out after {timeout_s:.0f}s on "
            f"process {me}: waiting for process(es) "
            f"{missing if missing else '<unknown>'}; arrived "
            f"{sorted(arrived)} of {n}"
        ) from e
