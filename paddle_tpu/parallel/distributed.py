"""Multi-host initialization + host-level barriers.

Replaces the reference's cluster bring-up: ParameterServerController (N pserver
ports), ParameterClient2 connection setup, and the Go master/etcd discovery
(go/master/etcd_client.go). On TPU pods, `jax.distributed.initialize` does
discovery/rendezvous (GCS or coordinator address) and the resulting global
device set feeds one Mesh spanning all hosts; DCN handles cross-slice."""

from __future__ import annotations

import logging
from typing import Optional

import jax

log = logging.getLogger("paddle_tpu.distributed")

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the multi-host runtime. No-op on single host (mirrors the
    reference: local training skips pserver setup, TrainerMain.cpp:32)."""
    global _initialized
    if _initialized:
        return
    if num_processes is None or num_processes <= 1:
        _initialized = True
        return
    # CPU backends need an explicit cross-process collectives transport (the
    # TPU path rides ICI/DCN natively); gloo is jaxlib's built-in. Harmless
    # on TPU — the flag only affects the CPU client.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jaxlib without the option
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed init: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def barrier(name: str = "barrier") -> None:
    """Host-level sync point — parity with ParameterServer2::synchronize
    (ParameterServer2.h:423) and the ThreadBarrier across gradient servers.
    Implemented as a tiny psum across all devices."""
    import jax.numpy as jnp

    x = jnp.ones((jax.local_device_count(),))
    jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x).block_until_ready()
