"""Convolution / pooling ops, NHWC (TPU-preferred layout).

Replaces the reference's conv stack: im2col+GEMM (paddle/function/GemmConvOp.cpp,
Im2ColOp.cpp), cuDNN conv/pool (paddle/cuda/src/hl_cuda_cudnn.cc), and the CNN
pooling kernels (paddle/cuda/src/hl_cuda_cnn.cu maxpool/avgpool fwd/bwd). On TPU
the conv *is* a first-class XLA HLO that tiles onto the MXU — no im2col needed;
backward comes from autodiff instead of the hand-written *BackwardData/Filter."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from paddle_tpu.core import dtypes

Array = jax.Array
IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        assert len(v) == 2
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


DIMNUMS = ("NHWC", "HWIO", "NHWC")


def conv2d(
    x: Array,
    w: Array,
    stride: IntOr2 = 1,
    padding: Union[str, IntOr2] = 0,
    dilation: IntOr2 = 1,
    groups: int = 1,
    policy: Optional[dtypes.Policy] = None,
) -> Array:
    """x: [B, H, W, Cin], w: [kh, kw, Cin/groups, Cout] → [B, H', W', Cout]."""
    p = policy or dtypes.current()
    x = p.cast(x)
    w = p.cast(w)
    if isinstance(padding, str):
        pad = padding  # "SAME" / "VALID"
    else:
        ph, pw = _pair(padding)
        pad = [(ph, ph), (pw, pw)]
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=_pair(stride),
        padding=pad,
        rhs_dilation=_pair(dilation),
        dimension_numbers=DIMNUMS,
        feature_group_count=groups,
        preferred_element_type=p.accum_dtype,
        precision=p.precision,
    )
    # residency tag for the conv-only rematerialization policy
    # (SGDTrainer(remat="conv_only")): under jax.checkpoint these outputs
    # are stored while everything else recomputes; a no-op otherwise
    return checkpoint_name(out, "conv_out")


def conv2d_transpose(
    x: Array,
    w: Array,
    stride: IntOr2 = 1,
    padding: IntOr2 = 0,
    policy: Optional[dtypes.Policy] = None,
) -> Array:
    """Transposed conv (ExpandConvLayer with trans=True / DeConv).

    w: [kh, kw, Cout, Cin] in HWIO w.r.t. the *forward* conv of the transpose."""
    p = policy or dtypes.current()
    x = p.cast(x)
    w = p.cast(w)
    ph, pw = _pair(padding)
    sh, sw = _pair(stride)
    kh, kw = w.shape[0], w.shape[1]
    # lhs_dilation implements the fractional stride; padding converts to the
    # equivalent full conv padding: k - 1 - p on each side.
    out = lax.conv_general_dilated(
        x,
        jnp.flip(w, (0, 1)).swapaxes(2, 3),
        window_strides=(1, 1),
        padding=[(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)],
        lhs_dilation=(sh, sw),
        dimension_numbers=DIMNUMS,
        preferred_element_type=p.accum_dtype,
        precision=p.precision,
    )
    return out


def _pool_pads(padding) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """padding: int | (ph, pw) | ((top, bottom), (left, right))."""
    if isinstance(padding, (tuple, list)) and padding and isinstance(
        padding[0], (tuple, list)
    ):
        (pt, pb), (pl, pr) = padding
        return (int(pt), int(pb)), (int(pl), int(pr))
    ph, pw = _pair(padding)
    return (ph, ph), (pw, pw)


def _pool_pads3d(padding):
    """3-D analog: int | (pd, ph, pw) | ((lo, hi) x 3)."""
    if isinstance(padding, (tuple, list)) and padding and isinstance(
        padding[0], (tuple, list)
    ):
        return tuple((int(lo), int(hi)) for lo, hi in padding)
    pd, ph, pw = _triple(padding)
    return ((pd, pd), (ph, ph), (pw, pw))


def max_pool2d(
    x: Array, window: IntOr2, stride: Optional[IntOr2] = None, padding: IntOr2 = 0
) -> Array:
    """[B, H, W, C] max pooling (hl_maxpool_forward, hl_cuda_cnn.cu).
    `padding` may be asymmetric ((top, bottom), (left, right)) — used by the
    v1 DSL's ceil_mode output-size emulation."""
    wh, ww = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    hpad, wpad = _pool_pads(padding)
    neg = (
        -jnp.inf
        if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    return lax.reduce_window(
        x,
        neg,
        lax.max,
        window_dimensions=(1, wh, ww, 1),
        window_strides=(1, sh, sw, 1),
        padding=((0, 0), hpad, wpad, (0, 0)),
    )


def avg_pool2d(
    x: Array,
    window: IntOr2,
    stride: Optional[IntOr2] = None,
    padding: IntOr2 = 0,
    exclusive: bool = True,
) -> Array:
    """[B, H, W, C] average pooling (hl_avgpool_forward). `exclusive` divides by
    the count of valid (non-pad) elements, matching the reference kernel which
    clips each window to the image region before dividing. `padding` may be
    asymmetric ((top, bottom), (left, right))."""
    wh, ww = _pair(window)
    sh, sw = _pair(stride if stride is not None else window)
    hpad, wpad = _pool_pads(padding)
    dims = (1, wh, ww, 1)
    strides = (1, sh, sw, 1)
    pads = ((0, 0), hpad, wpad, (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if exclusive and (sum(hpad) or sum(wpad)):
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return summed / counts
    return summed / float(wh * ww)


def global_avg_pool2d(x: Array) -> Array:
    return jnp.mean(x, axis=(1, 2))


def bilinear_resize(x: Array, out_h: int, out_w: int) -> Array:
    """Bilinear interpolation (hl_bilinear_forward / BilinearInterpLayer)."""
    return jax.image.resize(
        x, (x.shape[0], out_h, out_w, x.shape[3]), method="bilinear"
    )


def conv_out_size(in_size: int, k: int, stride: int, pad: int, dilation: int = 1) -> int:
    eff = (k - 1) * dilation + 1
    return (in_size + 2 * pad - eff) // stride + 1


# ---------------------------------------------------------------------------
# 3D convolution / pooling — Conv3DLayer.cpp / DeConv3DLayer.cpp /
# Pool3DLayer.cpp. NDHWC layout; XLA's conv HLO is rank-agnostic so these
# lower onto the MXU exactly like the 2D path.
# ---------------------------------------------------------------------------

IntOr3 = Union[int, Tuple[int, int, int]]
DIMNUMS3D = ("NDHWC", "DHWIO", "NDHWC")


def _triple(v: IntOr3) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        assert len(v) == 3
        return (int(v[0]), int(v[1]), int(v[2]))
    return (int(v), int(v), int(v))


def conv3d(
    x: Array,
    w: Array,
    stride: IntOr3 = 1,
    padding: IntOr3 = 0,
    dilation: IntOr3 = 1,
    groups: int = 1,
    policy: Optional[dtypes.Policy] = None,
) -> Array:
    """x: [B, D, H, W, Cin], w: [kd, kh, kw, Cin/groups, Cout]."""
    p = policy or dtypes.current()
    x = p.cast(x)
    w = p.cast(w)
    pd, ph, pw = _triple(padding)
    return lax.conv_general_dilated(
        x,
        w,
        window_strides=_triple(stride),
        padding=[(pd, pd), (ph, ph), (pw, pw)],
        rhs_dilation=_triple(dilation),
        dimension_numbers=DIMNUMS3D,
        feature_group_count=groups,
        preferred_element_type=p.accum_dtype,
        precision=p.precision,
    )


def conv3d_transpose(
    x: Array,
    w: Array,
    stride: IntOr3 = 1,
    padding: IntOr3 = 0,
    policy: Optional[dtypes.Policy] = None,
) -> Array:
    """Transposed 3D conv (DeConv3DLayer.cpp); w is DHWIO of the forward conv."""
    p = policy or dtypes.current()
    x = p.cast(x)
    w = p.cast(w)
    pd, ph, pw = _triple(padding)
    sd, sh, sw = _triple(stride)
    kd, kh, kw = w.shape[0], w.shape[1], w.shape[2]
    return lax.conv_general_dilated(
        x,
        jnp.flip(w, (0, 1, 2)).swapaxes(3, 4),
        window_strides=(1, 1, 1),
        padding=[
            (kd - 1 - pd, kd - 1 - pd),
            (kh - 1 - ph, kh - 1 - ph),
            (kw - 1 - pw, kw - 1 - pw),
        ],
        lhs_dilation=(sd, sh, sw),
        dimension_numbers=DIMNUMS3D,
        preferred_element_type=p.accum_dtype,
        precision=p.precision,
    )


def max_pool3d(
    x: Array, window: IntOr3, stride: Optional[IntOr3] = None, padding: IntOr3 = 0
) -> Array:
    wd, wh, ww = _triple(window)
    sd, sh, sw = _triple(stride if stride is not None else window)
    dpad, hpad, wpad = _pool_pads3d(padding)
    neg = (
        -jnp.inf
        if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    return lax.reduce_window(
        x,
        neg,
        lax.max,
        window_dimensions=(1, wd, wh, ww, 1),
        window_strides=(1, sd, sh, sw, 1),
        padding=((0, 0), dpad, hpad, wpad, (0, 0)),
    )


def avg_pool3d(
    x: Array,
    window: IntOr3,
    stride: Optional[IntOr3] = None,
    padding: IntOr3 = 0,
    exclusive: bool = True,
) -> Array:
    wd, wh, ww = _triple(window)
    sd, sh, sw = _triple(stride if stride is not None else window)
    dpad, hpad, wpad = _pool_pads3d(padding)
    dims = (1, wd, wh, ww, 1)
    strides = (1, sd, sh, sw, 1)
    pads = ((0, 0), dpad, hpad, wpad, (0, 0))
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
    if exclusive and any(lo or hi for lo, hi in (dpad, hpad, wpad)):
        ones = jnp.ones(x.shape[:4] + (1,), x.dtype)
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return summed / counts
    return summed / float(wd * wh * ww)
