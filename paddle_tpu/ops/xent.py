"""Fused softmax cross-entropy over large vocabularies.

The reference computes softmax as an activation then gathers -log(p) in the
cost layer (paddle/cuda/src/hl_cuda_cnn.cu softmax + CostLayer.cpp
MultiClassCrossEntropy). On TPU that shape of computation is
HBM-bandwidth-bound: with a 30k vocab the [B*T, V] probability tensor is the
largest array in the whole NMT step, and routing it through float32
(r3 profile: costs.py log_softmax at ~640 GB/s for 3 ms/step, plus a 2.8 ms
f32 relayout) doubles the bytes for no accuracy benefit in the loss.

This custom-VJP keeps every [N, V]-sized tensor in the logits' own dtype
(bf16 under the mixed policy) while doing all *reductions* in f32:

  fwd: m = max(x); lse = m + log(sum(exp(x - m)))   (f32 accumulation,
       bf16 reads — XLA fuses the cast into the reduce, nothing f32 of
       size [N, V] is ever materialized)
  bwd: dx = (exp(x - lse) - onehot(label)) * g      (single fused pass,
       written back in the logits dtype)

so the HBM traffic is one read of x per reduction pass and one bf16 write of
dx — about 3x less than the naive f32 log_softmax path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _reductions(logits: Array, labels: Array):
    x32 = logits.astype(jnp.float32)
    m = jnp.max(x32, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x32 - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse, picked.astype(jnp.float32)


@jax.custom_vjp
def softmax_xent_with_logits(logits: Array, labels: Array) -> Array:
    """Per-example -log softmax(logits)[label] → f32 [N] (labels int [N])."""
    lse, picked = _reductions(logits, labels)
    return lse - picked


def _fwd(logits, labels):
    lse, picked = _reductions(logits, labels)
    return lse - picked, (logits, labels, lse)


def _bwd(res, g):
    logits, labels, lse = res
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
        == labels[..., None]
    )
    dx = (p - onehot.astype(jnp.float32)) * g[..., None].astype(jnp.float32)
    return dx.astype(logits.dtype), None


softmax_xent_with_logits.defvjp(_fwd, _bwd)
