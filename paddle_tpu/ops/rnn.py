"""Recurrent cells + time scans.

The TPU replacement for the fused CUDA recurrences: hl_cuda_lstm.cu (872 LoC,
all four gates fused per step), hl_gpu_gru.cuh, and the batching transform
SequenceToBatch.h:41. Design shift: instead of reordering ragged sequences into
per-timestep dense batches, we keep padded [B, T, ...] arrays time-major inside
`lax.scan` and carry a mask — XLA fuses the per-step gate math into a single
kernel, and the big input projections are hoisted OUT of the scan as one large
[B*T, 4H] matmul on the MXU (the reference does the same hoist: the layer
projects via Mixed/fc before LstmLayer).

Gate conventions — NOTE the LSTM block order intentionally differs from the
reference: here the 4H weight/bias blocks are [input, forget, cell(candidate),
output], while the reference packs [candidate(In), input(Ig), forget(Fg),
output(Og)] (hl_cpu_lstm.cuh:42-45, hl_gpu_lstm.cuh). The math is identical;
only the block layout differs — any loader interchanging LSTM weights with
reference-trained models MUST permute the 4H blocks accordingly (no such
loader exists yet; reference-format weights cannot currently be loaded into
LSTM layers unpermuted). GRU gates [update(z), reset(r), candidate(c)] match
GruCompute.cu. Optional peephole ("check") weights as in the reference."""

from __future__ import annotations

import os
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.nn import activations as act_mod
from paddle_tpu.ops import linalg

Array = jax.Array


def _use_fused(standard_config: bool, bh: int = 0) -> bool:
    """Route to the pallas whole-sequence kernel when on TPU (or forced) and
    the layer uses the reference-default activations (no peepholes).

    `bh` = batch*hidden of the carry: the kernel keeps per-step blocks
    resident in VMEM, and past ~100k carry elements the *backward* kernel's
    scoped-VMEM stack exceeds the 16 MB limit (measured: 256×512 GRU bwd
    wants 16.21M) — fall back to the lax.scan path there."""
    if not standard_config:
        return False
    limit = int(os.environ.get("PADDLE_TPU_FUSED_RNN_MAX_BH", "100000"))
    if bh > limit:
        return False
    from paddle_tpu.ops import pallas as pal

    return pal.enabled()


def _run_fused(proj: Array, mask: Array, reverse: bool, fn: Callable) -> Tuple:
    """Shared fused-kernel dispatch: batch-major → time-major (+flip for
    reverse), call `fn(proj_tm, mask_tm) -> (hs_tm, *finals)`, restore layout
    and the caller's dtype."""
    ptm = jnp.swapaxes(proj, 0, 1)
    mtm = jnp.swapaxes(mask, 0, 1)[:, :, None]
    if reverse:
        ptm, mtm = jnp.flip(ptm, 0), jnp.flip(mtm, 0)
    hs, *finals = fn(ptm, mtm)
    if reverse:
        hs = jnp.flip(hs, 0)
    hs = jnp.swapaxes(hs, 0, 1).astype(proj.dtype)
    return (hs, *(f.astype(proj.dtype) for f in finals))


class LstmParams(NamedTuple):
    w_hh: Array  # [H, 4H] recurrent weights
    bias: Array  # [4H]
    check_i: Optional[Array] = None  # peephole [H] for input gate
    check_f: Optional[Array] = None
    check_o: Optional[Array] = None


def lstm_step(
    proj_t: Array,  # [B, 4H] (x_t already projected)
    h: Array,
    c: Array,
    p: LstmParams,
    gate_act: str = "sigmoid",
    cell_act: str = "tanh",
    state_act: str = "tanh",
) -> Tuple[Array, Array]:
    """One LSTM step (hl_lstm fused kernel semantics, incl. peepholes)."""
    hdim = h.shape[-1]
    # params are f32 masters; compute in the activations' dtype so bf16
    # carries stay bf16 through lax.scan (carry dtypes must be invariant)
    gates = proj_t + linalg.matmul(h, p.w_hh) + p.bias.astype(proj_t.dtype)
    gi, gf, gc, go = jnp.split(gates, 4, axis=-1)
    ga = act_mod.get(gate_act)
    if p.check_i is not None:
        gi = gi + c * p.check_i.astype(c.dtype)
        gf = gf + c * p.check_f.astype(c.dtype)
    i = ga(gi)
    f = ga(gf)
    cand = act_mod.get(cell_act)(gc)
    c_new = f * c + i * cand
    if p.check_o is not None:
        go = go + c_new * p.check_o.astype(c_new.dtype)
    o = ga(go)
    h_new = o * act_mod.get(state_act)(c_new)
    return h_new, c_new


def lstm_scan(
    proj: Array,  # [B, T, 4H]
    mask: Array,  # [B, T]
    p: LstmParams,
    h0: Optional[Array] = None,
    c0: Optional[Array] = None,
    reverse: bool = False,
    gate_act: str = "sigmoid",
    cell_act: str = "tanh",
    state_act: str = "tanh",
    return_cell_seq: bool = False,
) -> Tuple[Array, Array, Array]:
    """Full-sequence LSTM → (h_seq [B,T,H], h_last, c_last). Masked steps
    carry the previous state through (ragged batches stay correct).

    `return_cell_seq=True` returns (h_seq, c_seq [B,T,H], h_last) instead —
    the fluid lstm_op contract (full cell sequence in its 'Cell' slot). The
    fused pallas kernel only materializes final states, so that mode always
    takes the scan path."""
    b, t, h4 = proj.shape
    hdim = h4 // 4
    h0 = h0 if h0 is not None else jnp.zeros((b, hdim), proj.dtype)
    c0 = c0 if c0 is not None else jnp.zeros((b, hdim), proj.dtype)

    if not return_cell_seq and _use_fused(
        gate_act == "sigmoid" and cell_act == "tanh" and state_act == "tanh"
        and p.check_i is None and p.check_f is None and p.check_o is None,
        bh=b * hdim,
    ):
        from paddle_tpu.ops.pallas.rnn_kernels import lstm_seq_fused

        return _run_fused(
            proj, mask, reverse,
            lambda ptm, mtm: lstm_seq_fused(ptm, mtm, p.w_hh, p.bias, h0, c0),
        )

    def step(carry, xs):
        h, c = carry
        proj_t, m_t = xs
        h_new, c_new = lstm_step(proj_t, h, c, p, gate_act, cell_act, state_act)
        m = m_t[:, None].astype(h_new.dtype)
        h = m * h_new + (1 - m) * h
        c = m * c_new + (1 - m) * c
        return (h, c), ((h, c) if return_cell_seq else h)

    xs = (jnp.swapaxes(proj, 0, 1), jnp.swapaxes(mask, 0, 1))
    (h_last, c_last), out = lax.scan(step, (h0, c0), xs, reverse=reverse)
    if return_cell_seq:
        hs, cs = out
        return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1), h_last
    return jnp.swapaxes(out, 0, 1), h_last, c_last


class GruParams(NamedTuple):
    w_hzr: Array  # [H, 2H] recurrent weights for update+reset gates
    w_hc: Array  # [H, H] recurrent weight for candidate
    bias: Array  # [3H]


def gru_step(
    proj_t: Array,  # [B, 3H] in gate order [z, r, c]
    h: Array,
    p: GruParams,
    gate_act: str = "sigmoid",
    cand_act: str = "tanh",
) -> Array:
    """One GRU step (GruCompute / hl_gpu_gru.cuh semantics: reset gate applies
    to the *recurrent* candidate term)."""
    hdim = h.shape[-1]
    pz, pr, pc = jnp.split(proj_t + p.bias.astype(proj_t.dtype), 3, axis=-1)
    rz = linalg.matmul(h, p.w_hzr)
    ga = act_mod.get(gate_act)
    z = ga(pz + rz[:, :hdim])
    r = ga(pr + rz[:, hdim:])
    c = act_mod.get(cand_act)(pc + linalg.matmul(r * h, p.w_hc))
    return (1.0 - z) * h + z * c


def gru_scan(
    proj: Array,  # [B, T, 3H]
    mask: Array,  # [B, T]
    p: GruParams,
    h0: Optional[Array] = None,
    reverse: bool = False,
    gate_act: str = "sigmoid",
    cand_act: str = "tanh",
) -> Tuple[Array, Array]:
    """Full-sequence GRU → (h_seq [B,T,H], h_last)."""
    b, t, h3 = proj.shape
    hdim = h3 // 3
    h0 = h0 if h0 is not None else jnp.zeros((b, hdim), proj.dtype)

    if _use_fused(gate_act == "sigmoid" and cand_act == "tanh", bh=b * hdim):
        from paddle_tpu.ops.pallas.rnn_kernels import gru_seq_fused

        return _run_fused(
            proj, mask, reverse,
            lambda ptm, mtm: gru_seq_fused(ptm, mtm, p.w_hzr, p.w_hc, p.bias, h0),
        )

    def step(h, xs):
        proj_t, m_t = xs
        h_new = gru_step(proj_t, h, p, gate_act, cand_act)
        m = m_t[:, None].astype(h_new.dtype)
        h = m * h_new + (1 - m) * h
        return h, h

    xs = (jnp.swapaxes(proj, 0, 1), jnp.swapaxes(mask, 0, 1))
    h_last, hs = lax.scan(step, h0, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), h_last


def simple_rnn_scan(
    proj: Array,  # [B, T, H] (input already projected)
    mask: Array,
    w_hh: Array,  # [H, H]
    bias: Optional[Array],
    act: str = "tanh",
    h0: Optional[Array] = None,
    reverse: bool = False,
) -> Tuple[Array, Array]:
    """Vanilla RNN (RecurrentLayer.cpp): h_t = act(x_t + W h_{t-1} + b)."""
    b, t, hdim = proj.shape
    h0 = h0 if h0 is not None else jnp.zeros((b, hdim), proj.dtype)
    a = act_mod.get(act)

    def step(h, xs):
        proj_t, m_t = xs
        pre = proj_t + linalg.matmul(h, w_hh)
        if bias is not None:
            pre = pre + bias
        h_new = a(pre)
        m = m_t[:, None].astype(h_new.dtype)
        h = m * h_new + (1 - m) * h
        return h, h

    xs = (jnp.swapaxes(proj, 0, 1), jnp.swapaxes(mask, 0, 1))
    h_last, hs = lax.scan(step, h0, xs, reverse=reverse)
    return jnp.swapaxes(hs, 0, 1), h_last
