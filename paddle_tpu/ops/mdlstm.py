"""2-D multi-dimensional LSTM (MDLstmLayer.cpp:180, mdlstmemory).

The reference walks grid cells one CoordIterator step at a time; that serial
order is hostile to the MXU. TPU-native formulation: *skew* the [H, W] grid so
anti-diagonals become columns (cell (i, j) → column i + j), then one
`lax.scan` over the H+W-1 skewed columns updates every row in parallel — the
classic wavefront schedule. Per Graves' MD-LSTM and the reference's gate
layout: gates = x·Wx + (Σ_d h_neighbor_d)·Wh + b with blocks
[inode, input_gate, forget_gate_per_dim×2, output_gate], per-dim forget gates
on each neighbor state, and peephole weights checkIg/checkFg[2]/checkOg."""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.ops import linalg

Array = jax.Array


class MDLstmParams(NamedTuple):
    w_h: Array  # [H, 5H] recurrent weight (shared over dims, ref layout)
    bias: Array  # [5H] for [inode, ig, fg0, fg1, og]
    check_i: Array  # [H] peephole on input gate
    check_f: Array  # [2, H] peephole per dim on forget gates
    check_o: Array  # [H] peephole on output gate


def _skew(x: Array) -> Array:
    """[B, H, W, C] → [B, H, H+W-1, C]: row i shifted right by i."""
    b, h, w, c = x.shape
    out = jnp.zeros((b, h, h + w - 1, c), x.dtype)
    for i in range(h):  # static python loop: h is a compile-time constant
        out = out.at[:, i, i : i + w].set(x[:, i])
    return out


def _unskew(x: Array, w: int) -> Array:
    b, h, _, c = x.shape
    return jnp.stack([x[:, i, i : i + w] for i in range(h)], axis=1)


def mdlstm_2d(
    proj: Array,  # [B, H, W, 5*hid] = x @ w_x (input projection, done outside)
    p: MDLstmParams,
    directions: Tuple[bool, bool] = (True, True),
) -> Array:
    """Returns h: [B, H, W, hid]. directions[d]=False walks dim d backwards."""
    b, gh, gw, h5 = proj.shape
    hid = h5 // 5
    # walk direction: flip the grid, scan forward, flip back
    flip_axes = [ax + 1 for ax, fwd in enumerate(directions) if not fwd]
    if flip_axes:
        proj = jnp.flip(proj, flip_axes)

    sk = _skew(proj)  # [B, gh, T, 5*hid], T = gh + gw - 1
    t_len = gh + gw - 1
    valid = _skew(jnp.ones((1, gh, gw, 1), proj.dtype))  # [1, gh, T, 1]

    dt = proj.dtype
    w_h = p.w_h.astype(dt)
    bias = p.bias.astype(dt)
    ci = p.check_i.astype(dt)
    cf0 = p.check_f[0].astype(dt)
    cf1 = p.check_f[1].astype(dt)
    co = p.check_o.astype(dt)

    def shift_down(x):  # row i receives row i-1 (the up-neighbor)
        return jnp.pad(x, ((0, 0), (1, 0), (0, 0)))[:, :-1]

    def step(carry, xs):
        h_prev, c_prev = carry  # [B, gh, hid] — the previous skewed column
        col, m = xs  # [B, gh, 5*hid], [1, gh, 1]
        h_up, c_up = shift_down(h_prev), shift_down(c_prev)  # dim-0 neighbor
        h_left, c_left = h_prev, c_prev  # dim-1 neighbor (same row, prev col)
        gates = col + linalg.matmul(h_up + h_left, w_h) + bias
        g, ig, f0, f1, og = jnp.split(gates, 5, axis=-1)
        i_t = jax.nn.sigmoid(ig + ci * (c_up + c_left))
        f0_t = jax.nn.sigmoid(f0 + cf0 * c_up)
        f1_t = jax.nn.sigmoid(f1 + cf1 * c_left)
        c_t = i_t * jnp.tanh(g) + f0_t * c_up + f1_t * c_left
        o_t = jax.nn.sigmoid(og + co * c_t)
        h_t = o_t * jnp.tanh(c_t)
        # zero out the skew padding so neighbors outside the grid read 0
        h_t = h_t * m
        c_t = c_t * m
        return (h_t, c_t), h_t

    zeros = jnp.zeros((b, gh, hid), dt)
    xs = (jnp.moveaxis(sk, 2, 0), jnp.moveaxis(valid, 2, 0))
    _, hs = lax.scan(step, (zeros, zeros), xs)
    h_grid = _unskew(jnp.moveaxis(hs, 0, 2), gw)  # [B, gh, gw, hid]
    if flip_axes:
        h_grid = jnp.flip(h_grid, flip_axes)
    return h_grid


def mdlstm_2d_reference(proj, p, directions=(True, True)):
    """Slow per-cell oracle for tests (the reference's CoordIterator walk)."""
    import numpy as np

    proj = np.asarray(proj, np.float32)
    b, gh, gw, h5 = proj.shape
    hid = h5 // 5
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    h = np.zeros((b, gh, gw, hid), np.float32)
    c = np.zeros((b, gh, gw, hid), np.float32)
    zero = np.zeros((b, hid), np.float32)
    ii = range(gh) if directions[0] else range(gh - 1, -1, -1)
    for i in ii:
        jj = range(gw) if directions[1] else range(gw - 1, -1, -1)
        for j in jj:
            pi = i - 1 if directions[0] else i + 1
            pj = j - 1 if directions[1] else j + 1
            h_up = h[:, pi, j] if 0 <= pi < gh else zero
            c_up = c[:, pi, j] if 0 <= pi < gh else zero
            h_left = h[:, i, pj] if 0 <= pj < gw else zero
            c_left = c[:, i, pj] if 0 <= pj < gw else zero
            gates = proj[:, i, j] + (h_up + h_left) @ np.asarray(p.w_h) + np.asarray(p.bias)
            g, ig, f0, f1, og = np.split(gates, 5, axis=-1)
            i_t = sig(ig + np.asarray(p.check_i) * (c_up + c_left))
            f0_t = sig(f0 + np.asarray(p.check_f)[0] * c_up)
            f1_t = sig(f1 + np.asarray(p.check_f)[1] * c_left)
            c_t = i_t * np.tanh(g) + f0_t * c_up + f1_t * c_left
            o_t = sig(og + np.asarray(p.check_o) * c_t)
            h[:, i, j] = o_t * np.tanh(c_t)
            c[:, i, j] = c_t
    return h
