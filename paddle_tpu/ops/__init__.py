"""XLA/Pallas compute ops.

This package is the TPU-native replacement for the reference's three compute
tiers — paddle/cuda (hl_* kernels), paddle/math (Matrix/BaseMatrix), and
paddle/function (op functors); see SURVEY §2.1. Everything is a pure jnp/lax
function designed to be traced inside jit; hand-fused Pallas kernels live in
paddle_tpu/ops/pallas/ and are used only where XLA fusion is insufficient.
"""

from paddle_tpu.ops import linalg as linalg  # noqa: F401
from paddle_tpu.ops import conv as conv  # noqa: F401
from paddle_tpu.ops import sequence as sequence  # noqa: F401
