"""Pallas TPU kernels for the hot fused ops (SURVEY §7: "Pallas kernels only
where fusion matters — LSTM/GRU step"; ISSUE 9 fused attention; ISSUE 11
ragged paged-attention decode, `paged_attention.py`).

Dispatch policy: `enabled()` is on when running on TPU (or when
PADDLE_TPU_PALLAS=1/interpret is forced); the lax.scan implementations in
ops/rnn.py and the jnp gather path in serving/model.py remain the oracles
and the fallback for exotic activations / peepholes / non-TPU backends."""

from __future__ import annotations

import os

import jax


def _flag() -> str:
    return os.environ.get("PADDLE_TPU_PALLAS", "auto").lower()


def enabled() -> bool:
    f = _flag()
    if f in ("0", "off", "false"):
        return False
    if f in ("1", "on", "true", "interpret"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def interpret_mode() -> bool:
    """Interpret on non-TPU backends so the same kernels are testable on CPU."""
    if _flag() == "interpret":
        return True
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True
