"""Ragged paged-attention decode kernel ("Ragged Paged Attention", PAPERS.md).

The serving decode step's inner loop is attention over a paged KV cache:
every slot owns a row of the block table mapping logical page j -> physical
page id, and attends over its own committed tokens only (per-slot length
masking — sequence length is *data*, never *shape*). The jnp path in
`serving/model.ServableLM.decode_step` materializes that as a dense gather
`k_pages[block_table]` — [S, P, PS, KD] per layer per step round-tripping
HBM — before a masked softmax. This kernel is the TPU shape of the same
computation:

  * grid = (slots, pages_per_seq); the PAGE loop is the inner grid dim;
  * the block table rides in as a SCALAR-PREFETCH operand, so each grid
    step's k/v BlockSpec index map picks the slot's PHYSICAL page straight
    out of it — the gather happens in the DMA engine, one [PS, KD] page at
    a time, and the dense [S, P, PS, KD] intermediate never exists;
  * per-slot length masking against the slot's own position (logical token
    index <= position), so ragged mixed-age batches share the executable;
  * numerically-stable ONLINE softmax in f32: running max / denominator /
    weighted-value accumulator live in VMEM scratch across the page loop
    (the flash-attention recurrence), flushed to the output on the last
    page.

Unused block-table entries point at dump page 0 and their logical indices
exceed the slot's position, so they contribute exp(-1e9 - m) == 0 exactly —
bitwise the same masking contract as the oracle.

The jnp gather path remains the CPU oracle: `paged_attention_decode` must
match it to float tolerance (argmax-equal under greedy decode) for every
mixed length / block-table layout — asserted in interpret mode on CPU by
tests/test_decode_fastpath.py, the same discipline as PR 9's fused
attention kernel. Dispatch policy lives in `ops.pallas.enabled()`:
TPU on by default, CPU oracle otherwise, PADDLE_TPU_PALLAS=interpret forces
the kernel through the Pallas interpreter for the equality tests."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import interpret_mode

Array = jax.Array

# must equal serving/model.NEG_INF: fully-masked pages then degrade to a
# zero contribution exactly as the oracle's softmax does
NEG_INF = -1e9


def _paged_decode_kernel(
    bt_ref,    # scalar prefetch: [S, P] block table (SMEM)
    pos_ref,   # scalar prefetch: [S] positions (SMEM)
    q_ref,     # [1, H, hd] — this slot's query, pre-scaled
    k_ref,     # [1, PS, KD] — this grid step's physical page
    v_ref,     # [1, PS, KD]
    out_ref,   # [1, KD]
    m_scr,     # VMEM [H, 1] running max
    l_scr,     # VMEM [H, 1] running denominator
    acc_scr,   # VMEM [H, hd] running weighted values
    *,
    page_size: int,
    n_heads: int,
    head_dim: int,
):
    s = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)  # [H, hd]
    k = k_ref[0].reshape(page_size, n_heads, head_dim).astype(jnp.float32)
    v = v_ref[0].reshape(page_size, n_heads, head_dim).astype(jnp.float32)
    # scores for this page, per head: [H, PS] (q pre-scaled by the caller)
    sc = jax.lax.dot_general(
        q.reshape(n_heads, 1, head_dim), k.transpose(1, 2, 0),
        (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32,
    ).reshape(n_heads, page_size)
    # ragged masking: logical token index within THIS slot's sequence
    idx = p * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
    sc = jnp.where(idx <= pos_ref[s], sc, NEG_INF)
    # online-softmax recurrence (f32 throughout)
    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    probs = jnp.exp(sc - m_new)  # [H, PS]
    l_scr[:] = l_scr[:] * alpha + jnp.sum(probs, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        probs.reshape(n_heads, 1, page_size), v.transpose(1, 0, 2),
        (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32,
    ).reshape(n_heads, head_dim)
    acc_scr[:] = acc_scr[:] * alpha + pv
    m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _flush():
        # l >= exp(0 - m) > 0 always: logical index 0 is <= every position
        out_ref[0] = (acc_scr[:] / l_scr[:]).reshape(n_heads * head_dim)


def paged_attention_decode(
    q: Array,            # [S, KD] — one query token per slot
    k_pages: Array,      # [NP, PS, KD] — one layer's physical page pool
    v_pages: Array,      # [NP, PS, KD]
    block_table: Array,  # [S, P] int32 logical->physical page map
    positions: Array,    # [S] int32 — each slot's current token position
    *,
    scale: float,
    n_heads: int,
) -> Array:
    """One decode step of ragged paged attention for all slots: [S, KD] f32
    context, numerically equivalent to the jnp gather oracle in
    `ServableLM.decode_step` (same masking, f32 softmax; the online
    recurrence reassociates the sum so equality is to float tolerance,
    argmax/token-exact under greedy decode)."""
    s, kd = q.shape
    ps = k_pages.shape[1]
    pmax = block_table.shape[1]
    hd = kd // n_heads
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s, pmax),
        in_specs=[
            pl.BlockSpec((1, n_heads, hd), lambda i, j, bt, pos: (i, 0, 0)),
            # the ragged gather: the block table (prefetched to SMEM before
            # the body runs) drives which physical page the DMA fetches
            pl.BlockSpec((1, ps, kd), lambda i, j, bt, pos: (bt[i, j], 0, 0)),
            pl.BlockSpec((1, ps, kd), lambda i, j, bt, pos: (bt[i, j], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, kd), lambda i, j, bt, pos: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_heads, 1), jnp.float32),
            pltpu.VMEM((n_heads, 1), jnp.float32),
            pltpu.VMEM((n_heads, hd), jnp.float32),
        ],
    )
    qs = (q.astype(jnp.float32) * scale).reshape(s, n_heads, hd)
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel, page_size=ps, n_heads=n_heads, head_dim=hd
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, kd), jnp.float32),
        interpret=interpret_mode(),
    )(
        block_table.astype(jnp.int32), positions.astype(jnp.int32),
        qs, k_pages.astype(jnp.float32), v_pages.astype(jnp.float32),
    )
    return out
