"""Fused whole-sequence LSTM/GRU Pallas kernels.

Parity target: hl_cuda_lstm.cu (all four gates fused per step, 872 LoC) and
hl_gpu_gru.cuh. TPU design: ONE pallas_call runs the entire time loop as a
sequential grid over T; the recurrent state (h, c) lives in VMEM scratch for
the whole sequence — zero HBM round-trips for the carry, one [B,H]x[H,4H]
MXU matmul per step, VPU for the gate math. The backward pass is a second
kernel walking the grid in reverse, accumulating dW in VMEM scratch.

Time-major layout [T, B, ...] so each grid step's block is one timestep.
Activations are fixed sigmoid/tanh (the reference's defaults); layers with
exotic activations or peepholes use the lax.scan path (ops/rnn.py), which is
also the numerical oracle for these kernels' tests."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paddle_tpu.ops.pallas import interpret_mode

Array = jax.Array


def _sig(x):
    return jax.nn.sigmoid(x)


# ===========================================================================
# LSTM
# ===========================================================================


def _lstm_fwd_kernel(proj_ref, mask_ref, whh_ref, b_ref, h0_ref, c0_ref,
                     hs_ref, gates_ref, ct_ref, cs_ref, h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    h = h_scr[:]
    c = c_scr[:]
    gates = proj_ref[0] + jnp.dot(
        h, whh_ref[:], preferred_element_type=jnp.float32
    ) + b_ref[:]
    hdim = h.shape[-1]
    i = _sig(gates[:, :hdim])
    f = _sig(gates[:, hdim : 2 * hdim])
    g = jnp.tanh(gates[:, 2 * hdim : 3 * hdim])
    o = _sig(gates[:, 3 * hdim :])
    c_tilde = f * c + i * g
    h_tilde = o * jnp.tanh(c_tilde)
    m = mask_ref[0]
    h_new = m * h_tilde + (1.0 - m) * h
    c_new = m * c_tilde + (1.0 - m) * c
    # saved for backward: post-activation gates, pre-mask cell, masked cell
    gates_ref[0] = jnp.concatenate([i, f, g, o], axis=-1)
    ct_ref[0] = c_tilde
    cs_ref[0] = c_new
    hs_ref[0] = h_new
    h_scr[:] = h_new
    c_scr[:] = c_new


def _lstm_bwd_kernel(gates_ref, ct_ref, hprev_ref, cprev_ref, mask_ref,
                     whh_ref, dhs_ref, dhlast_ref, dclast_ref,
                     dproj_ref, dw_ref, db_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr, dw_scr, db_scr):
    ti = pl.program_id(0)  # 0 .. T-1, walking t = T-1-ti via index maps
    nt = pl.num_programs(0)

    @pl.when(ti == 0)
    def _init():
        dh_scr[:] = dhlast_ref[:]
        dc_scr[:] = dclast_ref[:]
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    hdim = ct_ref.shape[-1]
    gates = gates_ref[0]
    i = gates[:, :hdim]
    f = gates[:, hdim : 2 * hdim]
    g = gates[:, 2 * hdim : 3 * hdim]
    o = gates[:, 3 * hdim :]
    c_tilde = ct_ref[0]
    c_prev = cprev_ref[0]
    h_prev = hprev_ref[0]
    m = mask_ref[0]

    dh = dh_scr[:] + dhs_ref[0]
    dc = dc_scr[:]
    tanh_ct = jnp.tanh(c_tilde)
    dh_tilde = m * dh
    dc_tilde = m * dc + dh_tilde * o * (1.0 - tanh_ct * tanh_ct)
    do = dh_tilde * tanh_ct
    di = dc_tilde * g
    dg = dc_tilde * i
    df = dc_tilde * c_prev
    # pre-activation grads
    dgi = di * i * (1.0 - i)
    dgf = df * f * (1.0 - f)
    dgg = dg * (1.0 - g * g)
    dgo = do * o * (1.0 - o)
    dgates = jnp.concatenate([dgi, dgf, dgg, dgo], axis=-1)

    dproj_ref[0] = dgates
    dh_prev = jnp.dot(
        dgates, whh_ref[:].T, preferred_element_type=jnp.float32
    ) + (1.0 - m) * dh
    dc_prev = dc_tilde * f + (1.0 - m) * dc
    dw_scr[:] = dw_scr[:] + jnp.dot(
        h_prev.T, dgates, preferred_element_type=jnp.float32
    )
    db_scr[:] = db_scr[:] + jnp.sum(dgates, axis=0)
    dh_scr[:] = dh_prev
    dc_scr[:] = dc_prev

    @pl.when(ti == nt - 1)
    def _finish():
        dw_ref[:] = dw_scr[:]
        db_ref[:] = db_scr[:]
        dh0_ref[:] = dh_scr[:]
        dc0_ref[:] = dc_scr[:]


def _lstm_fwd(proj_tm: Array, mask_tm: Array, w_hh: Array, bias: Array,
              h0: Array, c0: Array):
    t, b, h4 = proj_tm.shape
    h = h4 // 4
    f32 = jnp.float32
    args = (proj_tm.astype(f32), mask_tm.astype(f32), w_hh.astype(f32),
            bias.astype(f32), h0.astype(f32), c0.astype(f32))
    out_shape = (
        jax.ShapeDtypeStruct((t, b, h), f32),   # hs
        jax.ShapeDtypeStruct((t, b, 4 * h), f32),  # post-act gates
        jax.ShapeDtypeStruct((t, b, h), f32),   # c_tilde (pre-mask)
        jax.ShapeDtypeStruct((t, b, h), f32),   # c sequence (masked)
    )
    step_specs = lambda width: pl.BlockSpec((1, b, width), lambda i: (i, 0, 0))
    hs, gates, ct, cs = pl.pallas_call(
        _lstm_fwd_kernel,
        grid=(t,),
        in_specs=[
            step_specs(4 * h),                      # proj
            pl.BlockSpec((1, b, 1), lambda i: (i, 0, 0)),  # mask
            pl.BlockSpec((h, 4 * h), lambda i: (0, 0)),    # w_hh
            pl.BlockSpec((4 * h,), lambda i: (0,)),        # bias
            pl.BlockSpec((b, h), lambda i: (0, 0)),        # h0
            pl.BlockSpec((b, h), lambda i: (0, 0)),        # c0
        ],
        out_specs=(
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, 4 * h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
        ),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((b, h), f32),
            pltpu.VMEM((b, h), f32),
        ],
        interpret=interpret_mode(),
    )(*args)
    return hs, gates, ct, cs


@functools.partial(jax.custom_vjp)
def lstm_seq_fused(proj_tm: Array, mask_tm: Array, w_hh: Array, bias: Array,
                   h0: Array, c0: Array) -> Tuple[Array, Array, Array]:
    """Time-major fused LSTM: proj_tm [T,B,4H], mask_tm [T,B,1] →
    (hs [T,B,H], h_last, c_last)."""
    hs, gates, ct, cs = _lstm_fwd(proj_tm, mask_tm, w_hh, bias, h0, c0)
    return hs, hs[-1], cs[-1]


def _lstm_vjp_fwd(proj_tm, mask_tm, w_hh, bias, h0, c0):
    hs, gates, ct, cs = _lstm_fwd(proj_tm, mask_tm, w_hh, bias, h0, c0)
    # zero-size carriers: dtype objects aren't valid pytree leaves
    dtypes = tuple(jnp.zeros((0,), a.dtype) for a in (proj_tm, bias, h0, c0))
    res = (proj_tm.shape, dtypes, mask_tm, w_hh, h0, c0, hs, gates, ct, cs)
    return (hs, hs[-1], cs[-1]), res


def _lstm_vjp_bwd(res, grads):

    proj_shape, dtypes, mask_tm, w_hh, h0, c0, hs, gates, ct, cs = res
    dhs, dh_last, dc_last = grads
    t, b, h4 = proj_shape
    h = h4 // 4
    f32 = jnp.float32
    # grads on the hs output plus the explicit last-state grads
    dhs = dhs.astype(f32).at[-1].add(dh_last.astype(f32))

    # previous-step states (shift by one; cs is the masked cell sequence
    # the forward kernel saved — no reconstruction scan needed)
    h_prev = jnp.concatenate([h0.astype(f32)[None], hs[:-1]], axis=0)
    c_prev = jnp.concatenate([c0.astype(f32)[None], cs[:-1]], axis=0)

    rev = lambda i: (t - 1 - i, 0, 0)
    dproj, dw, db, dh0, dc0 = pl.pallas_call(
        _lstm_bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, 4 * h), rev),   # gates
            pl.BlockSpec((1, b, h), rev),       # c_tilde
            pl.BlockSpec((1, b, h), rev),       # h_prev
            pl.BlockSpec((1, b, h), rev),       # c_prev
            pl.BlockSpec((1, b, 1), rev),       # mask
            pl.BlockSpec((h, 4 * h), lambda i: (0, 0)),  # w_hh
            pl.BlockSpec((1, b, h), rev),       # dhs
            pl.BlockSpec((b, h), lambda i: (0, 0)),  # dh_last → consumed via dhs[-1]; zeros
            pl.BlockSpec((b, h), lambda i: (0, 0)),  # dc_last
        ],
        out_specs=(
            pl.BlockSpec((1, b, 4 * h), rev),        # dproj
            pl.BlockSpec((h, 4 * h), lambda i: (0, 0)),
            pl.BlockSpec((4 * h,), lambda i: (0,)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((t, b, 4 * h), f32),
            jax.ShapeDtypeStruct((h, 4 * h), f32),
            jax.ShapeDtypeStruct((4 * h,), f32),
            jax.ShapeDtypeStruct((b, h), f32),
            jax.ShapeDtypeStruct((b, h), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((b, h), f32),
            pltpu.VMEM((b, h), f32),
            pltpu.VMEM((h, 4 * h), f32),
            pltpu.VMEM((4 * h,), f32),
        ],
        interpret=interpret_mode(),
    )(
        gates, ct, h_prev, c_prev, mask_tm.astype(f32), w_hh.astype(f32),
        dhs, jnp.zeros((b, h), f32), dc_last.astype(f32),
    )
    proj_dt, bias_dt, h0_dt, c0_dt = (a.dtype for a in dtypes)
    # cotangent dtypes must match the primals (bf16 policy runs)
    return (dproj.astype(proj_dt), jnp.zeros_like(mask_tm),
            dw.astype(w_hh.dtype), db.astype(bias_dt),
            dh0.astype(h0_dt), dc0.astype(c0_dt))


lstm_seq_fused.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


# ===========================================================================
# GRU
# ===========================================================================


def _gru_fwd_kernel(proj_ref, mask_ref, wzr_ref, wc_ref, b_ref, h0_ref,
                    hs_ref, zrc_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _init():
        h_scr[:] = h0_ref[:]

    h = h_scr[:]
    hdim = h.shape[-1]
    p = proj_ref[0] + b_ref[:]
    rz = jnp.dot(h, wzr_ref[:], preferred_element_type=jnp.float32)
    z = _sig(p[:, :hdim] + rz[:, :hdim])
    r = _sig(p[:, hdim : 2 * hdim] + rz[:, hdim:])
    c = jnp.tanh(p[:, 2 * hdim :] + jnp.dot(
        r * h, wc_ref[:], preferred_element_type=jnp.float32
    ))
    h_tilde = (1.0 - z) * h + z * c
    m = mask_ref[0]
    h_new = m * h_tilde + (1.0 - m) * h
    zrc_ref[0] = jnp.concatenate([z, r, c], axis=-1)
    hs_ref[0] = h_new
    h_scr[:] = h_new


def _gru_bwd_kernel(zrc_ref, hprev_ref, mask_ref, wzr_ref, wc_ref,
                    dhs_ref, dhlast_ref,
                    dproj_ref, dwzr_ref, dwc_ref, db_ref, dh0_ref,
                    dh_scr, dwzr_scr, dwc_scr, db_scr):
    ti = pl.program_id(0)
    nt = pl.num_programs(0)

    @pl.when(ti == 0)
    def _init():
        dh_scr[:] = dhlast_ref[:]
        dwzr_scr[:] = jnp.zeros_like(dwzr_scr)
        dwc_scr[:] = jnp.zeros_like(dwc_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    hdim = hprev_ref.shape[-1]
    zrc = zrc_ref[0]
    z = zrc[:, :hdim]
    r = zrc[:, hdim : 2 * hdim]
    c = zrc[:, 2 * hdim :]
    h_prev = hprev_ref[0]
    m = mask_ref[0]

    dh = dh_scr[:] + dhs_ref[0]
    dht = m * dh  # grad into h_tilde
    dz = dht * (c - h_prev)
    dc = dht * z
    dgc = dc * (1.0 - c * c)  # pre-tanh candidate grad
    # candidate path: c = tanh(pc + (r*h) Wc)
    d_rh = jnp.dot(dgc, wc_ref[:].T, preferred_element_type=jnp.float32)
    dr = d_rh * h_prev
    dgz = dz * z * (1.0 - z)
    dgr = dr * r * (1.0 - r)
    dgzr = jnp.concatenate([dgz, dgr], axis=-1)

    dproj_ref[0] = jnp.concatenate([dgz, dgr, dgc], axis=-1)
    dh_prev = (
        dht * (1.0 - z)
        + d_rh * r
        + jnp.dot(dgzr, wzr_ref[:].T, preferred_element_type=jnp.float32)
        + (1.0 - m) * dh
    )
    dwzr_scr[:] = dwzr_scr[:] + jnp.dot(
        h_prev.T, dgzr, preferred_element_type=jnp.float32
    )
    dwc_scr[:] = dwc_scr[:] + jnp.dot(
        (r * h_prev).T, dgc, preferred_element_type=jnp.float32
    )
    db_scr[:] = db_scr[:] + jnp.sum(
        jnp.concatenate([dgz, dgr, dgc], axis=-1), axis=0
    )
    dh_scr[:] = dh_prev

    @pl.when(ti == nt - 1)
    def _finish():
        dwzr_ref[:] = dwzr_scr[:]
        dwc_ref[:] = dwc_scr[:]
        db_ref[:] = db_scr[:]
        dh0_ref[:] = dh_scr[:]


def _gru_fwd(proj_tm, mask_tm, w_hzr, w_hc, bias, h0):

    t, b, h3 = proj_tm.shape
    h = h3 // 3
    f32 = jnp.float32
    hs, zrc = pl.pallas_call(
        _gru_fwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, 3 * h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((h, 2 * h), lambda i: (0, 0)),
            pl.BlockSpec((h, h), lambda i: (0, 0)),
            pl.BlockSpec((3 * h,), lambda i: (0,)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, b, h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, b, 3 * h), lambda i: (i, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((t, b, h), f32),
            jax.ShapeDtypeStruct((t, b, 3 * h), f32),
        ),
        scratch_shapes=[pltpu.VMEM((b, h), f32)],
        interpret=interpret_mode(),
    )(proj_tm.astype(f32), mask_tm.astype(f32), w_hzr.astype(f32),
      w_hc.astype(f32), bias.astype(f32), h0.astype(f32))
    return hs, zrc


@jax.custom_vjp
def gru_seq_fused(proj_tm, mask_tm, w_hzr, w_hc, bias, h0):
    """Time-major fused GRU: proj_tm [T,B,3H] (gate order z,r,c), mask
    [T,B,1] → (hs [T,B,H], h_last)."""
    hs, _ = _gru_fwd(proj_tm, mask_tm, w_hzr, w_hc, bias, h0)
    return hs, hs[-1]


def _gru_vjp_fwd(proj_tm, mask_tm, w_hzr, w_hc, bias, h0):
    hs, zrc = _gru_fwd(proj_tm, mask_tm, w_hzr, w_hc, bias, h0)
    dtypes = tuple(jnp.zeros((0,), a.dtype) for a in (proj_tm, bias, h0))
    return (hs, hs[-1]), (proj_tm.shape, dtypes, mask_tm, w_hzr, w_hc, h0, hs, zrc)


def _gru_vjp_bwd(res, grads):

    proj_shape, dtypes, mask_tm, w_hzr, w_hc, h0, hs, zrc = res
    dhs, dh_last = grads
    t, b, h3 = proj_shape
    h = h3 // 3
    f32 = jnp.float32
    dhs = dhs.astype(f32).at[-1].add(dh_last.astype(f32))
    h_prev = jnp.concatenate([h0.astype(f32)[None], hs[:-1]], axis=0)
    rev = lambda i: (t - 1 - i, 0, 0)
    dproj, dwzr, dwc, db, dh0 = pl.pallas_call(
        _gru_bwd_kernel,
        grid=(t,),
        in_specs=[
            pl.BlockSpec((1, b, 3 * h), rev),
            pl.BlockSpec((1, b, h), rev),
            pl.BlockSpec((1, b, 1), rev),
            pl.BlockSpec((h, 2 * h), lambda i: (0, 0)),
            pl.BlockSpec((h, h), lambda i: (0, 0)),
            pl.BlockSpec((1, b, h), rev),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, b, 3 * h), rev),
            pl.BlockSpec((h, 2 * h), lambda i: (0, 0)),
            pl.BlockSpec((h, h), lambda i: (0, 0)),
            pl.BlockSpec((3 * h,), lambda i: (0,)),
            pl.BlockSpec((b, h), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((t, b, 3 * h), f32),
            jax.ShapeDtypeStruct((h, 2 * h), f32),
            jax.ShapeDtypeStruct((h, h), f32),
            jax.ShapeDtypeStruct((3 * h,), f32),
            jax.ShapeDtypeStruct((b, h), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((b, h), f32),
            pltpu.VMEM((h, 2 * h), f32),
            pltpu.VMEM((h, h), f32),
            pltpu.VMEM((3 * h,), f32),
        ],
        interpret=interpret_mode(),
    )(zrc, h_prev, mask_tm.astype(f32), w_hzr.astype(f32), w_hc.astype(f32),
      dhs, jnp.zeros((b, h), f32))
    proj_dt, bias_dt, h0_dt = (a.dtype for a in dtypes)
    return (dproj.astype(proj_dt), jnp.zeros_like(mask_tm),
            dwzr.astype(w_hzr.dtype), dwc.astype(w_hc.dtype),
            db.astype(bias_dt), dh0.astype(h0_dt))


gru_seq_fused.defvjp(_gru_vjp_fwd, _gru_vjp_bwd)


# ===========================================================================
# Fused scaled-dot attention forward (ISSUE 9)
# ===========================================================================
#
# One pallas_call per batch row fuses the whole attention forward —
# scores = scale * q @ k^T, mask, numerically-stable softmax (f32), and the
# context matmul — so the [Tq, Tk] score/weight tensors live only in VMEM and
# never round-trip HBM between the four ops XLA would otherwise emit. The
# jnp path in ops/attention.dot_product_attention stays the CPU oracle (and
# the source of the backward below: the VJP recomputes the forward in jnp
# and differentiates it, so training through the fused op is exact-adjoint
# against the oracle while the kernel accelerates the forward).

# must equal ops/sequence.NEG_INF: a fully-masked row then degrades to the
# same uniform weights as the oracle instead of NaN
_ATTN_NEG_INF = -1e9


def _attn_fwd_kernel(q_ref, k_ref, v_ref, mask_ref, out_ref, *, scale):
    q = q_ref[0]  # [Tq, D] f32
    k = k_ref[0]  # [Tk, D]
    v = v_ref[0]  # [Tk, Dv]
    m = mask_ref[0]  # [Mq, Tk] 0/1, Mq in {1, Tq} (broadcast over rows)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    s = jnp.where(m > 0.0, s, _ATTN_NEG_INF)
    mx = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - mx)
    w = e / jnp.sum(e, axis=-1, keepdims=True)
    out_ref[0] = jnp.dot(w, v, preferred_element_type=jnp.float32)


def _attn_fwd(scale: float, q, k, v, mask):
    b, tq, d = q.shape
    tk = k.shape[1]
    dv = v.shape[2]
    mq = mask.shape[1]
    f32 = jnp.float32
    out = pl.pallas_call(
        functools.partial(_attn_fwd_kernel, scale=scale),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, tq, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, dv), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, mq, tk), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tq, dv), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, tq, dv), f32),
        interpret=interpret_mode(),
    )(q.astype(f32), k.astype(f32), v.astype(f32), mask.astype(f32))
    return out.astype(v.dtype)


def _attn_oracle(scale: float, q, k, v, mask):
    """The jnp reference this kernel must match — kept in lockstep with
    ops/attention.dot_product_attention (the public oracle); the fused op's
    backward is the exact vjp of THIS function."""
    logits = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    logits = jnp.where(mask > 0.0, logits, _ATTN_NEG_INF)
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bqk,bkv->bqv", w, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _attn_fused(scale: float, q, k, v, mask):
    return _attn_fwd(scale, q, k, v, mask)


def _attn_vjp_fwd(scale, q, k, v, mask):
    return _attn_fwd(scale, q, k, v, mask), (q, k, v, mask)


def _attn_vjp_bwd(scale, res, g):
    q, k, v, mask = res
    # recompute-in-backward: differentiate the jnp oracle (cheap VPU math
    # relative to storing [Tq, Tk] weights per row) — cotangents are the
    # oracle's exact adjoints, in the primals' dtypes
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attn_oracle(scale, q_, k_, v_, mask), q, k, v
    )
    dq, dk, dv = vjp(g.astype(v.dtype))
    return dq, dk, dv, jnp.zeros_like(mask)


_attn_fused.defvjp(_attn_vjp_fwd, _attn_vjp_bwd)


def attention_seq_fused(q: Array, k: Array, v: Array, mask: Array,
                        scale: float) -> Array:
    """Fused scaled-dot attention forward: q [B,Tq,D], k [B,Tk,D],
    v [B,Tk,Dv], mask [B,Mq,Tk] (0/1 float; Mq in {1,Tq}) → [B,Tq,Dv] in
    v's dtype. `scale` must be a static Python float (it is folded into the
    kernel). Kernel math runs f32; softmax reductions are f32 regardless of
    the input dtype (the mixed-precision contract of ops/xent.py applied to
    attention weights)."""
    return _attn_fused(float(scale), q, k, v, mask.astype(jnp.float32))
