"""SSD-style detection ops: prior boxes, matching, multibox loss, NMS, decode.

Parity targets: paddle/gserver/layers/PriorBox.cpp, MultiBoxLossLayer.cpp,
DetectionOutputLayer.cpp and DetectionUtil.cpp (jaccardOverlap,
encodeBBoxWithVar/decodeBBoxWithVar, matchBBox/generateMatchIndices, NMS).

TPU shift: the reference walks per-sequence std::vectors of NormalizedBBox on
the host. Here ground truth is a padded [B, G, 4] tensor + validity mask and
every stage (IoU matrix, bipartite+threshold matching, hard negative mining,
NMS) is a fixed-shape batched computation that compiles into the training or
inference step — matching is an argmax over an IoU matrix instead of loops,
NMS is a fori_loop over a top-k-sorted prefix.

Boxes are normalized corners (xmin, ymin, xmax, ymax) throughout, like
NormalizedBBox (DetectionUtil.h:54).
"""

from __future__ import annotations

import itertools
import math
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


# ---------------------------------------------------------------------------
# Prior (anchor) box generation — PriorBox.cpp
# ---------------------------------------------------------------------------


def prior_boxes(
    feature_hw: Tuple[int, int],
    image_hw: Tuple[int, int],
    min_sizes: Sequence[float],
    max_sizes: Sequence[float],
    aspect_ratios: Sequence[float],
    variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
    clip: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Anchor grid for one feature map → ([P, 4] boxes, [P, 4] variances).

    Per cell, in PriorBoxLayer::forward's order: for each min_size an
    aspect-1 box, then (if given) the sqrt(min*max) box, then one box per
    extra aspect ratio (and its reciprocal). Static python/numpy — priors are
    compile-time constants baked into the XLA program."""
    fh, fw = feature_hw
    ih, iw = image_hw
    ratios = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - r) > 1e-6 for r in ratios):
            ratios.append(ar)
        recip = 1.0 / ar
        if all(abs(recip - r) > 1e-6 for r in ratios):
            ratios.append(recip)

    boxes = []
    for y, x in itertools.product(range(fh), range(fw)):
        cx = (x + 0.5) / fw
        cy = (y + 0.5) / fh
        for k, msize in enumerate(min_sizes):
            # aspect 1, min size
            bw, bh = msize / iw, msize / ih
            boxes.append((cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2))
            if k < len(max_sizes):
                s = math.sqrt(msize * max_sizes[k])
                bw, bh = s / iw, s / ih
                boxes.append(
                    (cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2)
                )
            for ar in ratios:
                if abs(ar - 1.0) < 1e-6:
                    continue
                bw = msize * math.sqrt(ar) / iw
                bh = msize / math.sqrt(ar) / ih
                boxes.append(
                    (cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2)
                )
    out = np.asarray(boxes, np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.asarray(variances, np.float32)[None, :], (out.shape[0], 1))
    return out, var


# ---------------------------------------------------------------------------
# IoU + box coding — DetectionUtil.cpp jaccardOverlap / encode / decode
# ---------------------------------------------------------------------------


def iou_matrix(a: Array, b: Array) -> Array:
    """[N, 4] × [M, 4] → [N, M] Jaccard overlap."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0.0) * jnp.maximum(
        a[:, 3] - a[:, 1], 0.0
    )
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0.0) * jnp.maximum(
        b[:, 3] - b[:, 1], 0.0
    )
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def _to_center(boxes: Array) -> Tuple[Array, Array, Array, Array]:
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    cx = boxes[..., 0] + w / 2
    cy = boxes[..., 1] + h / 2
    return cx, cy, w, h


def encode_boxes(priors: Array, variances: Array, gt: Array) -> Array:
    """Center-form offset targets (encodeBBoxWithVar)."""
    pcx, pcy, pw, ph = _to_center(priors)
    gcx, gcy, gw, gh = _to_center(gt)
    pw = jnp.maximum(pw, 1e-12)
    ph = jnp.maximum(ph, 1e-12)
    tx = (gcx - pcx) / pw / variances[..., 0]
    ty = (gcy - pcy) / ph / variances[..., 1]
    tw = jnp.log(jnp.maximum(gw / pw, 1e-12)) / variances[..., 2]
    th = jnp.log(jnp.maximum(gh / ph, 1e-12)) / variances[..., 3]
    return jnp.stack([tx, ty, tw, th], axis=-1)


def decode_boxes(priors: Array, variances: Array, loc: Array) -> Array:
    """Inverse of encode_boxes (decodeBBoxWithVar)."""
    pcx, pcy, pw, ph = _to_center(priors)
    cx = loc[..., 0] * variances[..., 0] * pw + pcx
    cy = loc[..., 1] * variances[..., 1] * ph + pcy
    w = jnp.exp(loc[..., 2] * variances[..., 2]) * pw
    h = jnp.exp(loc[..., 3] * variances[..., 3]) * ph
    return jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1
    )


# ---------------------------------------------------------------------------
# Matching — DetectionUtil.cpp matchBBox / generateMatchIndices
# ---------------------------------------------------------------------------


def match_priors(
    priors: Array,
    gt_boxes: Array,
    gt_valid: Array,
    overlap_threshold: float = 0.5,
) -> Tuple[Array, Array]:
    """SSD matching for ONE example.

    priors [P, 4], gt_boxes [G, 4], gt_valid [G] bool.
    Returns (match_idx [P] int32 — index into gt, -1 unmatched;
             match_iou [P]).
    Bipartite stage: each valid gt claims its best prior. Threshold stage:
    remaining priors take their best gt if IoU > threshold."""
    p, g = priors.shape[0], gt_boxes.shape[0]
    iou = iou_matrix(priors, gt_boxes)  # [P, G]
    iou = jnp.where(gt_valid[None, :], iou, -1.0)

    # threshold stage
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)  # [P]
    best_gt_iou = jnp.max(iou, axis=1)
    match = jnp.where(best_gt_iou > overlap_threshold, best_gt, -1)
    match_iou = jnp.where(best_gt_iou > overlap_threshold, best_gt_iou, 0.0)

    # bipartite stage overrides (generateMatchIndices): G rounds, each round
    # the globally-best still-unassigned (prior, gt) pair is locked in, so
    # every valid gt ends up with a DISTINCT prior even when several gts
    # share the same favorite.
    def round_(state, _):
        match, match_iou, work = state  # work: [P, G] with used rows/cols -inf
        flat = jnp.argmax(work)
        p_star = (flat // g).astype(jnp.int32)
        g_star = (flat % g).astype(jnp.int32)
        ok = work[p_star, g_star] >= 0.0
        match = jnp.where(
            ok, match.at[p_star].set(g_star), match
        )
        match_iou = jnp.where(
            ok, match_iou.at[p_star].set(work[p_star, g_star]), match_iou
        )
        work = jnp.where(ok, work.at[p_star, :].set(-jnp.inf), work)
        work = jnp.where(ok, work.at[:, g_star].set(-jnp.inf), work)
        return (match, match_iou, work), None

    (match, match_iou, _), _ = jax.lax.scan(
        round_,
        (match, match_iou, jnp.where(gt_valid[None, :], iou, -jnp.inf)),
        None,
        length=g,
    )
    return match, match_iou


# ---------------------------------------------------------------------------
# MultiBox loss — MultiBoxLossLayer.cpp
# ---------------------------------------------------------------------------


def multibox_loss(
    loc_preds: Array,
    conf_preds: Array,
    priors: Array,
    variances: Array,
    gt_boxes: Array,
    gt_labels: Array,
    gt_valid: Array,
    overlap_threshold: float = 0.5,
    neg_pos_ratio: float = 3.0,
    background_id: int = 0,
) -> Array:
    """Batched SSD loss → per-example cost [B].

    loc_preds  [B, P, 4], conf_preds [B, P, C] logits,
    priors [P, 4], variances [P, 4],
    gt_boxes [B, G, 4], gt_labels [B, G] (real class ids; background_id
    reserved), gt_valid [B, G] bool.

    Positives get smooth-L1 on encoded offsets + softmax CE on their class;
    negatives are hard-mined by conf loss at `neg_pos_ratio`× the positive
    count (MultiBoxLossLayer's mining, as one sort per example)."""

    def one(loc_p, conf_p, gtb, gtl, gtv):
        p = priors.shape[0]
        match, _ = match_priors(priors, gtb, gtv, overlap_threshold)
        pos = match >= 0
        n_pos = jnp.sum(pos.astype(jnp.int32))

        safe_match = jnp.maximum(match, 0)
        matched_gt = gtb[safe_match]  # [P, 4]
        loc_target = encode_boxes(priors, variances, matched_gt)
        diff = loc_p - loc_target
        adiff = jnp.abs(diff)
        smooth_l1 = jnp.where(adiff < 1.0, 0.5 * diff * diff, adiff - 0.5)
        loc_loss = jnp.sum(
            jnp.where(pos[:, None], smooth_l1, 0.0)
        )

        cls_target = jnp.where(pos, gtl[safe_match], background_id)
        logp = jax.nn.log_softmax(conf_p, axis=-1)
        ce = -jnp.take_along_axis(
            logp, cls_target[:, None].astype(jnp.int32), axis=1
        )[:, 0]  # [P]

        # hard negative mining: top (ratio * n_pos) background-CE among negs
        neg_score = -logp[:, background_id]
        neg_score = jnp.where(pos, -jnp.inf, neg_score)
        order = jnp.argsort(-neg_score)
        rank = jnp.zeros((p,), jnp.int32).at[order].set(jnp.arange(p, dtype=jnp.int32))
        n_neg = jnp.minimum(
            (neg_pos_ratio * n_pos).astype(jnp.int32), p - n_pos
        )
        neg = (~pos) & (rank < n_neg)

        conf_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0))
        denom = jnp.maximum(n_pos, 1).astype(loc_loss.dtype)
        return (loc_loss + conf_loss) / denom

    return jax.vmap(one)(loc_preds, conf_preds, gt_boxes, gt_labels, gt_valid)


# ---------------------------------------------------------------------------
# NMS + detection output — DetectionOutputLayer.cpp
# ---------------------------------------------------------------------------


def nms(
    boxes: Array,
    scores: Array,
    iou_threshold: float = 0.45,
    top_k: int = 100,
    score_threshold: float = 0.01,
) -> Tuple[Array, Array]:
    """Greedy NMS over one class → (keep mask [K] over the top-k prefix,
    indices [K] into the input). Fixed shapes: sorts once, then a fori_loop
    marks suppressions in the score-ordered prefix."""
    k = min(top_k, scores.shape[0])
    top_scores, idx = jax.lax.top_k(scores, k)
    top_boxes = boxes[idx]
    iou = iou_matrix(top_boxes, top_boxes)

    valid0 = top_scores > score_threshold

    def body(i, keep):
        alive = keep[i]
        suppress = (iou[i] > iou_threshold) & (jnp.arange(k) > i)
        return jnp.where(alive, keep & ~suppress, keep)

    keep = jax.lax.fori_loop(0, k, body, valid0)
    return keep, idx


def detection_output(
    loc_preds: Array,
    conf_preds: Array,
    priors: Array,
    variances: Array,
    num_classes: int,
    background_id: int = 0,
    nms_threshold: float = 0.45,
    nms_top_k: int = 400,
    keep_top_k: int = 200,
    confidence_threshold: float = 0.01,
) -> Array:
    """[B, P, 4] locs + [B, P, C] logits → [B, keep_top_k, 6] detections
    (label, score, xmin, ymin, xmax, ymax), score 0 rows are padding.
    Per-class NMS then global keep_top_k, as in DetectionOutputLayer."""
    probs = jax.nn.softmax(conf_preds, axis=-1)

    def one(loc_p, prob):
        decoded = decode_boxes(priors, variances, loc_p)  # [P, 4]
        all_scores = []
        all_boxes = []
        all_labels = []
        for c in range(num_classes):
            if c == background_id:
                continue
            keep, idx = nms(
                decoded,
                prob[:, c],
                iou_threshold=nms_threshold,
                top_k=min(nms_top_k, priors.shape[0]),
                score_threshold=confidence_threshold,
            )
            sc = jnp.where(keep, prob[idx, c], 0.0)
            all_scores.append(sc)
            all_boxes.append(decoded[idx])
            all_labels.append(jnp.full(sc.shape, c, jnp.float32))
        scores = jnp.concatenate(all_scores)
        boxes_c = jnp.concatenate(all_boxes, axis=0)
        labels = jnp.concatenate(all_labels)
        kk = min(keep_top_k, scores.shape[0])
        top_s, ti = jax.lax.top_k(scores, kk)
        out = jnp.concatenate(
            [labels[ti][:, None], top_s[:, None], boxes_c[ti]], axis=1
        )
        if kk < keep_top_k:
            out = jnp.pad(out, ((0, keep_top_k - kk), (0, 0)))
        return out

    return jax.vmap(one)(loc_preds, probs)
