"""Sequence ops over padded [B, T, ...] batches with per-example lengths.

The TPU-native encoding of the reference's ragged sequences: where the reference
carries exact start offsets (paddle/parameter/Argument.h:84 sequenceStartPositions)
and reorders into per-timestep dense batches (gserver/layers/SequenceToBatch.h:41),
we keep static padded shapes + masks so XLA sees fixed shapes, and express per-step
recurrences as lax.scan over the time axis (SURVEY §5 "Long-context / sequence
scaling"). Replaces the hl_sequence.h kernel family (seq2batch, sequence softmax,
context projection) from paddle/cuda/src/hl_cuda_sequence.cu."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

NEG_INF = -1e9


def mask_from_lengths(lengths: Array, max_len: int, dtype=jnp.float32) -> Array:
    """[B] lengths → [B, T] validity mask."""
    return (jnp.arange(max_len)[None, :] < lengths[:, None]).astype(dtype)


def seq_softmax(x: Array, lengths: Array) -> Array:
    """Softmax over the valid time steps of [B, T] scores
    (hl_sequence_softmax_forward, paddle/cuda/include/hl_matrix.h:67).
    The reduction is pinned f32 regardless of the score dtype (the
    mixed-precision contract: bf16 attention scores, f32 softmax) and the
    weights return f32 — callers cast back at their next dot boundary."""
    m = mask_from_lengths(lengths, x.shape[1], jnp.bool_)
    x = jnp.where(m, x, NEG_INF).astype(jnp.float32)
    return jax.nn.softmax(x, axis=1) * m.astype(x.dtype)


def seq_sum(x: Array, lengths: Array) -> Array:
    """Sum-pool [B, T, D] → [B, D] over valid steps (SequencePoolLayer sum)."""
    m = mask_from_lengths(lengths, x.shape[1], x.dtype)
    return jnp.einsum("btd,bt->bd", x, m)


def seq_mean(x: Array, lengths: Array) -> Array:
    """(AverageLayer)"""
    denom = jnp.maximum(lengths, 1).astype(x.dtype)[:, None]
    return seq_sum(x, lengths) / denom


def seq_max(x: Array, lengths: Array) -> Array:
    """(MaxLayer)"""
    m = mask_from_lengths(lengths, x.shape[1], jnp.bool_)[:, :, None]
    return jnp.max(jnp.where(m, x, NEG_INF), axis=1)


def seq_sqrt_pool(x: Array, lengths: Array) -> Array:
    """sum / sqrt(len) (SequencePoolLayer 'sqrt' mode)."""
    denom = jnp.sqrt(jnp.maximum(lengths, 1).astype(x.dtype))[:, None]
    return seq_sum(x, lengths) / denom


def seq_first(x: Array, lengths: Optional[Array] = None) -> Array:
    """(SequenceLastInstanceLayer with select_first / FirstSeqLayer)"""
    return x[:, 0]


def seq_last(x: Array, lengths: Array) -> Array:
    """(SequenceLastInstanceLayer)"""
    idx = jnp.maximum(lengths - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


def context_projection(
    x: Array, lengths: Array, context_start: int, context_len: int
) -> Array:
    """Sliding window concat of neighbouring steps (ContextProjection,
    paddle/function/ContextProjectionOp.cpp; hl_context_projection_forward).

    [B, T, D] → [B, T, context_len * D]; out-of-range steps are zero (the
    trainable-padding variant is handled at the layer level)."""
    b, t, d = x.shape
    cols = []
    valid = mask_from_lengths(lengths, t, x.dtype)[:, :, None]
    xm = x * valid
    for offset in range(context_start, context_start + context_len):
        if offset == 0:
            cols.append(xm)
        elif offset < 0:
            shifted = jnp.pad(xm, ((0, 0), (-offset, 0), (0, 0)))[:, :t]
            cols.append(shifted)
        else:
            shifted = jnp.pad(xm, ((0, 0), (0, offset), (0, 0)))[:, offset:]
            # steps beyond each sequence's own end are invalid → zero them
            idx = jnp.arange(t)[None, :] + offset
            ok = (idx < lengths[:, None]).astype(x.dtype)[:, :, None]
            cols.append(shifted * ok)
    return jnp.concatenate(cols, axis=-1)


def expand_to_seq(x: Array, like_lengths: Array, max_len: int) -> Array:
    """[B, D] → [B, T, D] broadcast across time (ExpandLayer)."""
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], max_len, x.shape[1]))
