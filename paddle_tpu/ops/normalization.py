"""Fused batch normalization for TPU.

Why hand-write this (profiled on the real chip, see PROFILE_r03.md): the
naive formulation (`xf = x.astype(f32); mean(xf); var(xf); normalize(xf)`)
lets XLA materialize/share a float32 copy of every conv activation between the
statistics pass and the apply pass, and jax autodiff of that formulation emits
more full passes over the activation than the textbook backward needs. On a
bandwidth-bound model (ResNet-50 conv stack streams HBM at ~87% of peak) every
extra pass over a [B,H,W,C] tensor is pure step time.

Design (reference behavioral contract: BatchNormLayer.cpp / CudnnBatchNorm,
per-channel statistics over batch+spatial):
- statistics in ONE fused pass: sum and sum-of-squares reductions over bf16
  input with the f32 convert fused INTO the reduction (no f32 activation
  tensor exists in HBM). This is the "batch-norm statistics stay f32" leg of
  the mixed-precision contract (SGDTrainer(precision="bf16"), ISSUE 9): the
  reductions here are f32 REGARDLESS of the policy's compute dtype, by
  construction, not by Policy.cast;
- normalize in one elementwise pass (f32 math in registers, bf16 in/out);
- custom VJP with the minimal pass structure: one fused dual-reduction pass
  (sum(dy), sum(dy*xhat)) + one elementwise pass for dx.

Total traffic: fwd reads x twice + writes y once; bwd reads (x, dy) twice +
writes dx once — 9 activation-sized streams vs 13+ from autodiff.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_train(x, gamma, beta, eps: float):
    """Training-mode BN over all axes but the last. Returns (y, mean, var)
    with mean/var float32 [C] (biased variance, like the reference)."""
    y, mean, var = _bn_fwd_impl(x, gamma, beta, eps)
    return y, mean, var


def _bn_fwd_impl(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    xf = x.astype(jnp.float32)  # fused into the reductions below, never stored
    s1 = jnp.sum(xf, axis=axes)
    s2 = jnp.sum(jnp.square(xf), axis=axes)
    mean = s1 / n
    var = jnp.maximum(s2 / n - jnp.square(mean), 0.0)
    inv = jax.lax.rsqrt(var + eps)
    # scale/shift folded to per-channel a,b so the apply pass is one fma.
    # a/b stay f32 (they are [C]-sized — free) and the normalize arithmetic
    # runs f32 with ONE cast on the output: with bf16 activations and large
    # beta/mean magnitudes, doing the fma in bf16 loses mantissa (ADVICE r3);
    # XLA fuses the converts into the elementwise pass either way.
    a = gamma.astype(jnp.float32) * inv
    b = beta.astype(jnp.float32) - gamma.astype(jnp.float32) * inv * mean
    y = (xf * a + b).astype(x.dtype)
    return y, mean, var


def _bn_fwd(x, gamma, beta, eps):
    y, mean, var = _bn_fwd_impl(x, gamma, beta, eps)
    inv = jax.lax.rsqrt(var + eps)
    return (y, mean, var), (x, gamma, mean, inv)


def _bn_bwd(eps, res, cts):
    x, gamma, mean, inv = res
    dy, _dmean, _dvar = cts  # stats outputs feed moving averages: no grad path
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    # one fused pass: both reductions read (x, dy) together
    dbeta = jnp.sum(dyf, axis=axes)
    dgx = jnp.sum(dyf * xf, axis=axes)
    # sum(dy * xhat) = inv * (sum(dy*x) - mean*sum(dy))
    dgamma = inv * (dgx - mean * dbeta)
    # dx = gamma*inv/n * (n*dy - dbeta - xhat*dgamma). Per-channel constants
    # stay f32 like the forward's a/b (same mantissa-loss argument): the fma
    # runs f32 with one cast on the output, XLA fuses the converts.
    gi = gamma.astype(jnp.float32) * inv
    c2 = gi * (dbeta + mean * inv * -dgamma) / -n  # constant term
    # xhat*dgamma = (x-mean)*inv*dgamma -> express dx as a*dy + b*x + c per channel
    bx = gi * inv * dgamma / -n
    dx = (dyf * gi + xf * bx + c2).astype(x.dtype)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)


def batch_norm_inference(x, gamma, beta, mean, var, eps: float):
    """Inference-mode BN with running statistics (per-channel affine only)."""
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    a = (gamma.astype(jnp.float32) * inv).astype(x.dtype)
    b = (
        beta.astype(jnp.float32)
        - gamma.astype(jnp.float32) * inv * mean.astype(jnp.float32)
    ).astype(x.dtype)
    return x * a + b
