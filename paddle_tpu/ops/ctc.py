"""Connectionist Temporal Classification, scan-based and jit-friendly.

TPU-native replacement for the reference's CTC pair:
  - paddle/gserver/layers/LinearChainCTC.cpp (exact alpha/beta DP on CPU)
  - paddle/cuda/src/hl_warpctc_wrap.cc (warp-ctc dlopen shim)

Design: one `lax.scan` over time carrying log-alpha over the blank-extended
label sequence [2L+1]. Static shapes (padded labels + length masks) so the
whole loss compiles into the training step; the backward pass is jax.grad of
this forward — no hand-written beta recursion needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array

_NEG_INF = -1e30


def _logadd(a: Array, b: Array) -> Array:
    # NaN-safe under jax.grad: when both inputs are ~-inf the sum of exps is 0
    # and log() would emit -inf with a NaN cotangent that jnp.where cannot
    # stop; clamping the sum keeps the dead branch finite (exact otherwise,
    # since the finite branch's sum is >= 1).
    mx = jnp.maximum(a, b)
    mx_safe = jnp.where(mx <= _NEG_INF, 0.0, mx)
    ssum = jnp.exp(a - mx_safe) + jnp.exp(b - mx_safe)
    out = mx_safe + jnp.log(jnp.maximum(ssum, 1e-30))
    return jnp.where(mx <= _NEG_INF, _NEG_INF, out)


def ctc_loss(
    logits: Array,
    logit_lengths: Array,
    labels: Array,
    label_lengths: Array,
    blank: int = 0,
    norm_by_times: bool = False,
) -> Array:
    """Per-example negative log-likelihood of the label sequences.

    logits:         [B, T, C] unnormalized scores.
    logit_lengths:  [B] valid frames per example.
    labels:         [B, L] int labels padded with anything (masked by lengths).
    label_lengths:  [B] valid labels per example.
    blank:          blank id (the reference fixes blank=0 in CTCLayer.cpp).
    norm_by_times:  divide each example's NLL by its frame count
                    (WarpCTCLayer `norm_by_times` config).
    """
    b, t, c = logits.shape
    l = labels.shape[1]
    s = 2 * l + 1

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # Blank-extended label row per example: [blank, y1, blank, y2, ..., blank]
    ext = jnp.full((b, s), blank, dtype=labels.dtype)
    ext = ext.at[:, 1::2].set(labels)

    idx = jnp.arange(s)[None, :]
    valid_s = idx < (2 * label_lengths[:, None] + 1)

    # skip transition s-2 -> s allowed when ext[s] is a label differing from ext[s-2]
    ext_shift2 = jnp.concatenate(
        [jnp.full((b, 2), -1, dtype=ext.dtype), ext[:, :-2]], axis=1
    )
    can_skip = (idx % 2 == 1) & (ext != ext_shift2)

    # emission log-probs gathered per extended symbol: [B, T, S]
    emit = jnp.take_along_axis(
        logp, ext[:, None, :].astype(jnp.int32), axis=2
    )

    alpha0 = jnp.full((b, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit[:, 0, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_lengths > 0, emit[:, 0, 1], _NEG_INF)
    )
    alpha0 = jnp.where(valid_s, alpha0, _NEG_INF)

    def step(alpha, inputs):
        emit_t, t_i = inputs
        prev1 = jnp.concatenate(
            [jnp.full((b, 1), _NEG_INF), alpha[:, :-1]], axis=1
        )
        prev2 = jnp.concatenate(
            [jnp.full((b, 2), _NEG_INF), alpha[:, :-2]], axis=1
        )
        acc = _logadd(alpha, prev1)
        acc = _logadd(acc, jnp.where(can_skip, prev2, _NEG_INF))
        new = jnp.where(valid_s, acc + emit_t, _NEG_INF)
        # frozen past each example's final frame so the end-read is stable
        active = (t_i < logit_lengths)[:, None]
        new = jnp.where(active, new, alpha)
        return new, None

    alpha, _ = jax.lax.scan(
        step,
        alpha0,
        (jnp.swapaxes(emit, 0, 1)[1:], jnp.arange(1, t)),
    )

    end = 2 * label_lengths  # final blank position
    a_end = jnp.take_along_axis(alpha, end[:, None], axis=1)[:, 0]
    a_last_label = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(
            alpha, jnp.maximum(end - 1, 0)[:, None], axis=1
        )[:, 0],
        _NEG_INF,
    )
    nll = -_logadd(a_end, a_last_label)
    if norm_by_times:
        nll = nll / jnp.maximum(logit_lengths.astype(nll.dtype), 1.0)
    return nll


def ctc_greedy_decode(
    logits: Array, logit_lengths: Array, blank: int = 0
) -> Array:
    """Best-path decode: argmax per frame, collapse repeats, drop blanks.

    Returns [B, T] decoded ids padded with -1 (left-packed), for the
    ctc_error evaluator (CTCErrorEvaluator.cpp computes edit distance on the
    best path)."""
    ids = jnp.argmax(logits, axis=-1)  # [B, T]
    t = ids.shape[1]
    valid = jnp.arange(t)[None, :] < logit_lengths[:, None]
    prev = jnp.concatenate(
        [jnp.full_like(ids[:, :1], -1), ids[:, :-1]], axis=1
    )
    keep = valid & (ids != blank) & (ids != prev)

    # left-pack kept ids with a cumsum-scatter (static-shape friendly);
    # dropped slots route to an out-of-range index
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full_like(ids, -1)
    safe_pos = jnp.where(keep, pos, t)
    out = jax.vmap(
        lambda o, i, p, k: o.at[p].set(jnp.where(k, i, -1), mode="drop")
    )(out, ids, safe_pos, keep)
    return out
