"""Dense linear algebra with the TPU dtype policy applied.

Replaces the cuBLAS seam (paddle/cuda/src/hl_cuda_cublas.cc hl_matrix_mul and
paddle/math/Matrix.cpp GpuMatrix::mul). Matmuls cast inputs to the compute dtype
(bf16 for the MXU) and accumulate in f32 via preferred_element_type."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes

Array = jax.Array


def matmul(a: Array, b: Array, policy: Optional[dtypes.Policy] = None) -> Array:
    """a @ b over the last axis of a / first axis of b, MXU-friendly."""
    p = policy or dtypes.current()
    a = p.cast(a)
    b = p.cast(b)
    out = jnp.matmul(
        a, b, preferred_element_type=p.accum_dtype, precision=p.precision
    )
    from jax.ad_checkpoint import checkpoint_name

    # see ops/conv.py: stored under SGDTrainer(remat="conv_only")
    return checkpoint_name(out, "conv_out")


def linear(x: Array, w: Array, b: Optional[Array] = None, policy=None) -> Array:
    """x @ w + b, where x may have arbitrary leading batch/time dims."""
    out = matmul(x, w, policy)
    if b is not None:
        out = out + b
    return out
