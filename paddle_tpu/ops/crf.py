"""Linear-chain CRF: log-likelihood + Viterbi decode, scan-based.

Parity with paddle/gserver/layers/LinearChainCRF.cpp (forward/backward over
per-sequence emissions with start/end/transition weights packed into one
(C+2, C) parameter — row 0 = start weights a, row 1 = end weights b, rows
2.. = transition matrix w[from, to]) and CRFDecodingLayer.cpp (Viterbi).

TPU shift: the reference runs per-sequence variable-length DPs on CPU; here
both the partition function and Viterbi are single `lax.scan`s over the padded
time axis with length masks, batched over [B], so they compile into the
training step and vectorize on the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

_NEG_INF = -1e30


def _unpack(w: Array) -> Tuple[Array, Array, Array]:
    """(C+2, C) packed weights → (start[C], end[C], trans[C, C])."""
    return w[0], w[1], w[2:]


def crf_nll(
    emissions: Array, lengths: Array, labels: Array, w: Array
) -> Array:
    """Per-example negative log-likelihood.

    emissions: [B, T, C] unnormalized scores (the CRF input layer's output).
    lengths:   [B] valid timesteps.
    labels:    [B, T] gold tag ids (padding ignored).
    w:         [C+2, C] packed start/end/transition weights.
    """
    a, b_w, trans = _unpack(w)
    bsz, t, c = emissions.shape
    emissions = emissions.astype(jnp.float32)
    steps = jnp.arange(t)

    # --- gold path score ---------------------------------------------------
    lab_emit = jnp.take_along_axis(emissions, labels[:, :, None], axis=2)[..., 0]
    valid = steps[None, :] < lengths[:, None]
    emit_score = jnp.sum(jnp.where(valid, lab_emit, 0.0), axis=1)

    prev_lab = labels[:, :-1]
    next_lab = labels[:, 1:]
    trans_steps = trans[prev_lab, next_lab]  # [B, T-1]
    tvalid = steps[None, 1:] < lengths[:, None]
    trans_score = jnp.sum(jnp.where(tvalid, trans_steps, 0.0), axis=1)

    first_lab = labels[:, 0]
    last_idx = jnp.maximum(lengths - 1, 0)
    last_lab = jnp.take_along_axis(labels, last_idx[:, None], axis=1)[:, 0]
    path = emit_score + trans_score + a[first_lab] + b_w[last_lab]

    # --- partition function (forward algorithm) ----------------------------
    alpha0 = a[None, :] + emissions[:, 0]  # [B, C]

    def step(alpha, inputs):
        emit_t, t_i = inputs
        # alpha[:, i] + trans[i, j] → logsumexp over i
        scores = alpha[:, :, None] + trans[None, :, :]
        new = jax.scipy.special.logsumexp(scores, axis=1) + emit_t
        active = (t_i < lengths)[:, None]
        return jnp.where(active, new, alpha), None

    alpha, _ = jax.lax.scan(
        step, alpha0, (jnp.swapaxes(emissions, 0, 1)[1:], jnp.arange(1, t))
    )
    log_z = jax.scipy.special.logsumexp(alpha + b_w[None, :], axis=1)
    return log_z - path


def crf_decode(emissions: Array, lengths: Array, w: Array) -> Array:
    """Viterbi decode → [B, T] best tag ids (entries past `lengths` are the
    frozen last tag; mask with lengths downstream). CRFDecodingLayer parity."""
    a, b_w, trans = _unpack(w)
    bsz, t, c = emissions.shape
    emissions = emissions.astype(jnp.float32)

    delta0 = a[None, :] + emissions[:, 0]

    def fwd(delta, inputs):
        emit_t, t_i = inputs
        scores = delta[:, :, None] + trans[None, :, :]  # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1)  # [B, C]
        new = jnp.max(scores, axis=1) + emit_t
        active = (t_i < lengths)[:, None]
        new = jnp.where(active, new, delta)
        # frozen frames point back at themselves so backtrace passes through
        best_prev = jnp.where(
            active, best_prev, jnp.arange(c)[None, :].astype(best_prev.dtype)
        )
        return new, best_prev

    delta, backptrs = jax.lax.scan(
        fwd, delta0, (jnp.swapaxes(emissions, 0, 1)[1:], jnp.arange(1, t))
    )  # backptrs: [T-1, B, C]

    last = jnp.argmax(delta + b_w[None, :], axis=1)  # [B]

    def back(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, tags_rev = jax.lax.scan(back, last, backptrs, reverse=True)
    tags = jnp.concatenate(
        [first[None, :], tags_rev], axis=0
    )  # [T, B]
    return jnp.swapaxes(tags, 0, 1)
